#!/usr/bin/env python
"""One rank of the multi-process elastic-gang integration tests
(tests/test_elastic.py).

Runs a deterministic lockstep "training" loop over the gang KV plane:
every step each member publishes ``(rank+1) * w.sum()`` to
``red/<epoch>/<step>/<rank>``, meets a gang barrier, and applies the
same update from the mean contribution — so ``w`` stays bitwise
replicated across ranks and the printed loss trajectory can be checked
against a serial numpy simulation of the SAME membership history.

Fault sites (MXTPU_FAULT_INJECT): the worker runs them via
``ElasticGang.step_tick`` (kill_rank / slow_rank / heartbeat_loss).  A
respawned rank disarms its own ``kill_rank`` through a marker file in
the work dir so the second life survives.

Protocol lines on stdout (flushed, parsed by the test):

    PID <rank> <pid>
    LOSS <rank> <epoch> <step> <loss-as-float-hex>
    FENCED <rank> <epoch>
    EVICTED <rank>
    RESULT <json>   (rank, pid, final_step, w0 hex, epoch, members,
                     source, disk_restores, reshapes, fenced,
                     rejoined, evictions)

Usage:  elastic_gang_worker.py <work_dir> <num_steps> [snap_every]
                               [step_ms]
Env:    MXTPU_WORKER_RANK, MXTPU_NUM_WORKERS, and a control plane —
        MXTPU_GANG_DIR (FileKV) or MXTPU_GANG_KV=tcp + MXTPU_GANG_ADDR
        (TcpKV, no shared filesystem) — plus the resilience knobs the
        test sets: heartbeat interval/timeout, MXTPU_KILL_AT_STEP, ...

Split-brain extras: MXTPU_FAULT_AT_STEP defers MXTPU_FAULT_INJECT's
arming until this rank reaches that step (partition_split/pause_rank
must not fire while the gang is still forming).  On a KV cut the
worker parks fenced and rejoins after the heal; with
MXTPU_REJOIN_ON_EVICT=1 an evicted rank (the resumed-zombie case)
re-enters via gang.join() instead of exiting.
"""

import importlib
import json
import os
import sys
import time
import types


def _emit(line):
    """One atomic write per protocol line: ranks share the launcher's
    stdout pipe, and under PYTHONUNBUFFERED a print()'s text and
    newline are separate syscalls that interleave across processes."""
    sys.stdout.write(line + "\n")
    sys.stdout.flush()


def _import_elastic():
    """Load the resilience/distributed submodules without executing the
    package __init__ (keeps the gang jax-free and spawn cheap)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "mxnet_tpu" not in sys.modules:
        pkg = types.ModuleType("mxnet_tpu")
        pkg.__path__ = [os.path.join(root, "mxnet_tpu")]
        sys.modules["mxnet_tpu"] = pkg
    res = importlib.import_module("mxnet_tpu.resilience")
    dist = importlib.import_module("mxnet_tpu.distributed")
    return res, dist


def _allreduce(gang, kv, step, contribution):
    """Lockstep mean over the gang KV: publish, barrier, read all."""
    epoch = gang.epoch
    kv.put_json(f"red/{epoch}/{step}/{gang.rank}",
                {"v": float(contribution)})
    gang.barrier(f"red{step}")
    total = 0.0
    for r in sorted(gang.members):
        rec = kv.get_json(f"red/{epoch}/{step}/{r}")
        total += float(rec["v"])
    return total / len(gang.members)


def _adopt(np, info, rank):
    """Rebuild local state from a RecoveryInfo: own shard when we have
    one, any peer's ``w`` (replicated) with a zeroed ``opt`` when we are
    a fresh joiner, or the full disk state."""
    if info.shards is not None:
        st = info.shards.get(rank)
        if st is None:                  # joiner: no shard of its own
            st = dict(next(iter(info.shards.values())))
            st["opt"] = 0.0
    else:
        st = info.full_state
    return {"w": np.array(st["w"], dtype=np.float64),
            "opt": float(st["opt"])}


def main():
    import numpy as np
    res, dist = _import_elastic()

    work_dir = sys.argv[1]
    num_steps = int(sys.argv[2])
    snap_every = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    step_s = (float(sys.argv[4]) / 1e3) if len(sys.argv) > 4 else 0.0
    rank = int(os.environ["MXTPU_WORKER_RANK"])
    world = int(os.environ["MXTPU_NUM_WORKERS"])

    # second-life disarm: the first life of a kill_rank target leaves a
    # marker; the respawn sees it and drops the fault so it can rejoin
    marker = os.path.join(work_dir, f"killed_rank{rank}.marker")
    if rank in res.fault_args("kill_rank"):
        if os.path.exists(marker):
            os.environ.pop("MXTPU_FAULT_INJECT", None)
            os.environ.pop("MXTPU_KILL_AT_STEP", None)
            res.reset_faults()
        else:
            with open(marker, "w") as f:
                f.write("armed")

    # deferred arming: partition_split / pause_rank fired at spawn time
    # would cut the rank off before the gang even forms — hold the plan
    # until this rank's own step counter reaches MXTPU_FAULT_AT_STEP
    fault_at = os.environ.get("MXTPU_FAULT_AT_STEP")
    deferred_fault = None
    if fault_at is not None:
        fault_at = int(fault_at)
        deferred_fault = os.environ.pop("MXTPU_FAULT_INJECT", None)
        res.reset_faults()

    _emit(f"PID {rank} {os.getpid()}")

    kv = dist.gang_kv()     # FileKV (MXTPU_GANG_DIR) or TcpKV
    assert kv is not None, "worker needs MXTPU_GANG_DIR or MXTPU_GANG_ADDR"
    ck = res.LocalCheckpointer(os.path.join(work_dir, f"rank{rank}"))
    gang = res.ElasticGang(rank, world, kv=kv, checkpointer=ck,
                           peer_snap_every=snap_every)
    state = {"w": np.full(8, 1.0, dtype=np.float64), "opt": 0.0}
    step = 0
    stats = {"reshapes": 0, "disk_restores": 0, "source": None,
             "fenced": 0, "rejoined": 0, "evictions": 0}
    rejoin_on_evict = os.environ.get(
        "MXTPU_REJOIN_ON_EVICT", "") not in ("", "0")

    def adopt_info(info):
        nonlocal state, step
        state = _adopt(np, info, rank)
        step = info.snap_step
        stats["reshapes"] += 1
        stats["source"] = info.source
        if info.source == "disk":
            stats["disk_restores"] += 1

    try:
        info = gang.join()
        if info is not None:
            adopt_info(info)
        while step < num_steps:
            if deferred_fault is not None and step >= fault_at:
                os.environ["MXTPU_FAULT_INJECT"] = deferred_fault
                res.reset_faults()
                deferred_fault = None
            try:
                try:
                    gang.step_tick(step, state=state)
                    if step % snap_every == 0:
                        ck.save(step, state)
                    w = state["w"]
                    loss = _allreduce(gang, kv, step,
                                      (rank + 1) * float(w.sum()))
                except res.RankFailure as rf:
                    info = gang.recover(rf)
                    adopt_info(info)
                    continue
                except (res.GangFenced, dist.GangKVError):
                    # the losing side of a partition: no stepping, no
                    # durable writes — park until the heal, then rejoin
                    stats["fenced"] += 1
                    _emit(f"FENCED {rank} {gang.epoch}")
                    info = gang.park_fenced(timeout=60.0)
                    stats["rejoined"] += 1
                    if info is not None:
                        adopt_info(info)
                    continue
            except res.GangEvicted:
                # declared dead while out to lunch (resumed zombie):
                # containment already blocked the durable writes; ask
                # the majority for a planned re-admission
                if not rejoin_on_evict:
                    raise
                stats["evictions"] += 1
                _emit(f"EVICTED {rank}")
                info = gang.join()
                if info is not None:
                    adopt_info(info)
                continue
            _emit(f"LOSS {rank} {gang.epoch} {step} {loss.hex()}")
            state["w"] = state["w"] * 0.99 - 0.01 * (loss / w.size)
            state["opt"] += loss
            if step_s:
                time.sleep(step_s)
            step += 1
        gang.stop()
    except res.GangEvicted:
        _emit(f"EVICTED {rank}")
        return 0
    _emit("RESULT " + json.dumps(
        {"rank": rank, "pid": os.getpid(), "final_step": step,
         "w0": float(state["w"][0]).hex(), "epoch": gang.epoch,
         "members": gang.members, "source": stats["source"],
         "disk_restores": stats["disk_restores"],
         "reshapes": stats["reshapes"], "fenced": stats["fenced"],
         "rejoined": stats["rejoined"],
         "evictions": stats["evictions"],
         "kv_failovers": getattr(kv, "failovers", 0)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
