"""Pipeline parallelism through the captured step (PR 17).

The `pp` mesh axis partitions the scanned trunk's leading layer-stack
dim into contiguous stages (parallel/sharding.py PPRules), and
gluon/captured.py restructures the grad-accum scan into a 1F1B-style
shifted-carry microbatch schedule — still ONE donated jit program, one
dispatch + one readback per step.  Everything runs on the forced-host
8-device CPU mesh (conftest).  Load-bearing claims:

- a transformer trains on the 3-axis tp×pp×dp mesh with the PR 6
  regression discipline intact (1 dispatch, 1 readback, 0 retraces,
  cache hits post-warmup);
- captured(grad_accum=k, pp_microbatches=m) is BITWISE equal to the
  eager oracle at grad_accum=k*m, for k∈{1,2}×m∈{1,4};
- MXTPU_PP=0 degenerates bitwise to the flat (PR 9) captured scan;
- an indivisible k×m split raises up front, naming both knobs;
- `bubble_fraction` lands in StepStats (telemetry schema v5) and
  matches the analytic (S−1)/(n+S−1), cross-checked against the
  measured 1F1B schedule table.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, numerics, parallel, telemetry
from mxnet_tpu.gluon import captured, nn
from mxnet_tpu.gluon.model_zoo.bert import ScanTransformerEncoder
from mxnet_tpu.optimizer import grouped

UNITS = 16


def _scan_net(layers=2, units=UNITS, hidden=32, seed=7):
    mx.random.seed(seed)
    net = ScanTransformerEncoder(num_layers=layers, units=units,
                                 num_heads=2, hidden_size=hidden,
                                 dropout=0.0)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    return net


def _batch(rng, n=8, t=4):
    x = mx.nd.array(rng.normal(size=(n, t, UNITS)).astype(np.float32))
    y = mx.nd.array(rng.randint(0, UNITS, size=(n, t))
                    .astype(np.float32))
    return x, y


def _run(monkeypatch, mesh_axes, mode, captured_on=True, grad_accum=1,
         pp="1", pp_m=None, steps=3, seed=3):
    """One fresh train run; returns (losses, weights) as numpy."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1" if captured_on
                       else "0")
    monkeypatch.setenv("MXTPU_PP", pp)
    if pp_m is None:
        monkeypatch.delenv("MXTPU_PP_MICROBATCHES", raising=False)
    else:
        monkeypatch.setenv("MXTPU_PP_MICROBATCHES", str(pp_m))
    mesh = parallel.make_mesh(axes=mesh_axes)
    net = _scan_net()
    parallel.shard_model(net, mesh, mode=mode)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    rng = np.random.RandomState(seed)
    mx.random.seed(123)  # identical RNG-key stream across runs
    losses = []
    for _ in range(steps):
        x, y = _batch(rng)
        losses.append(np.asarray(
            tr.train_step(net, loss_fn, x, y,
                          grad_accum=grad_accum).asnumpy()).ravel())
    weights = [p.data().asnumpy() for p in tr._params]
    parallel.set_default_mesh(None)
    return losses, weights, tr


def _assert_bitwise(a, b):
    for s, (x, y) in enumerate(zip(a[0], b[0])):
        np.testing.assert_array_equal(x, y, err_msg=f"loss step {s}")
    for i, (x, y) in enumerate(zip(a[1], b[1])):
        np.testing.assert_array_equal(x, y, err_msg=f"weight {i}")


# -- acceptance: 3-axis mesh, one donated program, zero retraces ---------------

def test_tp_pp_dp_one_dispatch_one_readback_zero_retrace(
        mesh222, monkeypatch):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    monkeypatch.setenv("MXTPU_PP", "1")
    net = _scan_net()
    specs = parallel.shard_model(net, mesh222, mode="tp_pp")
    assert any("pp" in tuple(s) and "tp" in tuple(s)
               for s in specs.values())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    rng = np.random.RandomState(5)
    for _ in range(2):  # warmup: trace + compile
        x, y = _batch(rng)
        tr.train_step(net, loss_fn, x, y)
    captured.reset_counters()
    grouped.reset_dispatch_count()
    numerics.reset_readback_count()
    for _ in range(4):
        x, y = _batch(rng)
        tr.train_step(net, loss_fn, x, y)
    assert captured.dispatch_count() == 4
    assert grouped.dispatch_count() == 0
    assert numerics.readback_count() == 4
    assert captured.trace_count() == 0
    assert captured.cache_stats() == {"hits": 4, "misses": 0}
    # the donated program IS pipelined: schedule accounting exists
    step = next(iter(tr._captured_cache.values()))
    stats = step.pipeline_stats()
    assert stats["stages"] == 2
    assert stats["microbatches"] == 2  # auto: pp size
    assert 0.0 < stats["bubble_fraction"] < 1.0


def test_pp_microbatches_knob_misses_capture_cache(mesh222, monkeypatch):
    """pp_microbatches is a program-affecting knob: flipping it must
    re-capture (new slice count = new program), not reuse."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    net = _scan_net()
    parallel.shard_model(net, mesh222, mode="tp_pp")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    rng = np.random.RandomState(5)
    monkeypatch.setenv("MXTPU_PP_MICROBATCHES", "2")
    x, y = _batch(rng)
    tr.train_step(net, loss_fn, x, y)
    captured.reset_counters()
    monkeypatch.setenv("MXTPU_PP_MICROBATCHES", "4")
    x, y = _batch(rng)
    tr.train_step(net, loss_fn, x, y)
    assert captured.cache_stats()["misses"] == 1
    parallel.set_default_mesh(None)


# -- bitwise parity: grad-accum × microbatch grid (satellite) ------------------

@pytest.mark.parametrize("k,m", [(1, 1), (1, 4), (2, 1), (2, 4)])
def test_pp_schedule_bitwise_vs_eager_oracle(mesh8, monkeypatch, k, m):
    """captured(grad_accum=k, pp_microbatches=m) == the eager oracle at
    grad_accum=k*m, bitwise — the pipeline schedule re-orders WORK, not
    arithmetic.  Pure-pp mesh: the (pre-existing, pp-independent)
    captured-vs-eager divergence of dp-sharded microbatches at
    grad_accum>1 is out of scope here."""
    cap = _run(monkeypatch, {"pp": 2}, "pp", captured_on=True,
               grad_accum=k, pp_m=m)
    ora = _run(monkeypatch, {"pp": 2}, "pp", captured_on=False,
               grad_accum=k * m)
    _assert_bitwise(cap, ora)


def test_pp_disabled_degenerates_to_flat_scan_bitwise(mesh8,
                                                      monkeypatch):
    """MXTPU_PP=0 on a pp mesh == the PR 9 flat grad-accum scan; and
    the ACTIVE schedule at m=1 matches it bitwise too (the shifted
    carry adds an exact +0, nothing else)."""
    flat = _run(monkeypatch, {"pp": 2, "dp": 2}, "pp",
                grad_accum=2, pp="0")
    shifted = _run(monkeypatch, {"pp": 2, "dp": 2}, "pp",
                   grad_accum=2, pp="1", pp_m=1)
    _assert_bitwise(flat, shifted)


# -- divisibility: hard error naming both knobs (satellite) --------------------

def test_pp_indivisible_microbatch_split_raises(mesh8, monkeypatch):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    monkeypatch.setenv("MXTPU_PP", "1")
    monkeypatch.setenv("MXTPU_PP_MICROBATCHES", "4")
    mesh = parallel.make_mesh(pp=2)
    net = _scan_net()
    parallel.shard_model(net, mesh, mode="pp")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    rng = np.random.RandomState(5)
    x, y = _batch(rng, n=6)  # 6 % (2*4) != 0
    with pytest.raises(ValueError) as ei:
        tr.train_step(net, loss_fn, x, y, grad_accum=2)
    msg = str(ei.value)
    assert "grad_accum" in msg and "pp_microbatches" in msg
    assert "6" in msg and "8" in msg
    parallel.set_default_mesh(None)


def test_resolve_pp_schedule_off_paths():
    """No mesh / pp=1 / MXTPU_PP=0 all resolve to the flat scan."""
    assert captured.resolve_pp_schedule(None, 2, 8) == (1, 1, 2)
    mesh = parallel.make_mesh(dp=4)
    assert captured.resolve_pp_schedule(mesh, 3, 9) == (1, 1, 3)
    pmesh = parallel.make_mesh(pp=2)
    import os
    os.environ["MXTPU_PP"] = "0"
    try:
        assert captured.resolve_pp_schedule(pmesh, 2, 8) == (1, 1, 2)
    finally:
        del os.environ["MXTPU_PP"]
    assert captured.resolve_pp_schedule(pmesh, 2, 8) == (2, 2, 4)


# -- bubble_fraction: StepStats + schedule cross-check -------------------------

def test_bubble_fraction_in_stepstats_and_crosscheck(mesh222,
                                                     monkeypatch):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    losses, _w, tr = _run(monkeypatch, {"tp": 2, "pp": 2, "dp": 2},
                          "tp_pp")
    assert all(np.isfinite(l).all() for l in losses)
    recs = [r for r in telemetry.recent_steps()
            if r.get("path") == "captured"
            and r.get("bubble_fraction") is not None]
    assert recs
    rec = recs[-1]
    telemetry.validate_record(rec)
    assert 0.0 < rec["bubble_fraction"] < 1.0

    from mxnet_tpu.parallel.pipeline import (_schedule_1f1b,
                                             gpipe_bubble_fraction)

    step = next(iter(tr._captured_cache.values()))
    stats = step.pipeline_stats()
    s, n = stats["stages"], stats["microbatches"]
    assert rec["bubble_fraction"] == pytest.approx(
        stats["bubble_fraction"])
    # analytic warmup/cooldown accounting ...
    assert stats["warmup"] == stats["cooldown"] == s - 1
    assert stats["ticks"] == n + s - 1
    assert stats["bubble_fraction"] == pytest.approx(
        gpipe_bubble_fraction(s, n))
    # ... cross-checked against the measured 1F1B schedule table
    *_tables, bub = _schedule_1f1b(s, n)
    assert abs(stats["bubble_fraction"] - bub) < 0.12


def test_pp_collective_bytes_row(mesh222, monkeypatch):
    """Per-axis collective accounting grows a ``pp`` row: the layer
    scan over pp-sharded stacks moves bytes over the pp axis inside
    the one captured program."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    _l, _w, tr = _run(monkeypatch, {"tp": 2, "pp": 2, "dp": 2},
                      "tp_pp")
    step = next(iter(tr._captured_cache.values()))
    coll = step.collective_bytes_by_axis()
    assert isinstance(coll, dict)
    assert coll.get("pp", 0) > 0
    assert coll.get("tp", 0) > 0


def test_bubble_fraction_schema_validation():
    """Schema v5: bubble_fraction must be a number in [0, 1) or
    absent; v1–v4 records (no field) stay valid."""
    base = None
    for r in telemetry.recent_steps():
        if r.get("type", "step") != "step":
            continue
        base = dict(r)
        break
    if base is None:
        pytest.skip("no step record in the ring to mutate")
    base.pop("bubble_fraction", None)
    telemetry.validate_record(base)          # absent: valid (v1–v4)
    base["bubble_fraction"] = 0.25
    telemetry.validate_record(base)
    for bad in (-0.1, 1.0, "big"):
        base["bubble_fraction"] = bad
        with pytest.raises(ValueError):
            telemetry.validate_record(base)


# -- trace_report pipeline section (CLI smoke) ---------------------------------

def test_trace_report_pipeline_section(tmp_path, monkeypatch):
    """A pipelined run's event log flows through the trace_report CLI:
    the pipeline section aggregates bubble_fraction and the pp
    hand-off bytes; --validate accepts the v5 records."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = os.path.join(repo, "tools", "trace_report.py")
    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    for step_id in range(2):
        acc = telemetry.step_begin(path="captured")
        telemetry.note(bubble_fraction=0.25,
                       collective_bytes_by_axis={"pp": 4096,
                                                 "tp": 1024,
                                                 "all": 5120})
        telemetry.step_end(acc, step=step_id)
    telemetry.reset()                            # close the sink

    env = dict(os.environ)
    env.pop("MXTPU_TELEMETRY_PATH", None)
    proc = subprocess.run(
        [sys.executable, report, path, "--validate"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    out = proc.stdout
    assert "records validate against schema" in out
    assert "pipeline:" in out
    assert "bubble_fraction: mean 0.2500" in out
    assert "min 0.2500" in out and "max 0.2500" in out
    assert "pp hand-off: mean 4096 bytes/step/device" in out
