"""Self-tuning performance layer (mxnet_tpu/autotune/).

The acceptance loop, end to end: ``MXTPU_AUTOTUNE=search`` finds a
config no slower than the defaults within ``MXTPU_TUNE_BUDGET`` trials
(OOM candidates score infeasible, never crash) and persists it to the
CRC'd tuning DB; a second run in ``replay`` mode starts at the tuned
point with ZERO trials (``tune_db_hit`` event) and a loss trajectory
bitwise-identical to defaults — every searchable knob is
numerics-preserving, including all MXTPU_REMAT policies over the
captured step.  Plus: corrupt-DB fallback (``corrupt_tune_db`` fault),
telemetry schema v2, the trace_report autotune section, and
MXTPU_GROUP_MAX_ITEMS bitwise group splitting.
"""

import math
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, telemetry
from mxnet_tpu.autotune import db, runner, search, space
from mxnet_tpu.gluon import nn
from mxnet_tpu.optimizer import grouped

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")

#: every env var the tuner may set (apply_config writes os.environ
#: directly, outside monkeypatch's view) plus the driver's own knobs.
_TUNE_ENVS = [k.env for k in space.KNOBS.values()] + [
    "MXTPU_AUTOTUNE", "MXTPU_TUNE_DB", "MXTPU_TUNE_BUDGET",
    "MXTPU_TUNE_STEPS", "MXTPU_TUNE_SEMANTICS", "MXTPU_FAULT_INJECT",
    "MXTPU_COMPILE_CACHE_DIR",
]


@pytest.fixture(autouse=True)
def _tune_clean():
    """apply_config / the search mutate os.environ directly; scrub the
    whole tuner env and the telemetry trial state around every test."""
    saved = {e: os.environ.pop(e, None) for e in _TUNE_ENVS}
    telemetry.reset()
    yield
    for e in _TUNE_ENVS:
        os.environ.pop(e, None)
    for e, v in saved.items():
        if v is not None:
            os.environ[e] = v
    telemetry.reset()


def _make_net(seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(3))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    return net


def _train(steps=4, seed=7, opt="adam"):
    """Fresh net + trainer, `steps` train_step calls; returns (losses,
    weights) as numpy."""
    net = _make_net(seed=seed)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), opt,
                            {"learning_rate": 0.01})
    rs = np.random.RandomState(42)
    xs = [rs.normal(size=(8, 6)).astype(np.float32) for _ in range(steps)]
    ys = [rs.randint(0, 3, size=(8,)).astype(np.float32)
          for _ in range(steps)]
    losses = [trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                                 mx.nd.array(ys[s])).asnumpy()
              for s in range(steps)]
    weights = [p.data().asnumpy() for p in trainer._params]
    return losses, weights


# -- knob space ----------------------------------------------------------------

def test_knob_space_declaration():
    """Every knob: default in domain, env-backed `current`, adjacent
    `neighbors`, out-of-domain `validate` raises."""
    for knob in space.KNOBS.values():
        assert knob.default in knob.domain
        assert knob.current() == knob.default    # env scrubbed by fixture
        for v in knob.domain:
            nbrs = knob.neighbors(v)
            assert nbrs and all(n in knob.domain for n in nbrs)
            assert v not in nbrs
        with pytest.raises(mx.MXNetError):
            knob.validate("definitely-not-a-value")
    # fingerprint is order-independent and value-sensitive
    cfg = space.default_config()
    assert space.fingerprint(dict(reversed(list(cfg.items())))) \
        == space.fingerprint(cfg)
    cfg["remat"] = "dots"
    assert space.fingerprint(cfg) != space.fingerprint(
        space.default_config())


def test_semantics_changing_knobs_gated(monkeypatch):
    """grad_accum is searched and applied ONLY behind
    MXTPU_TUNE_SEMANTICS=1 — not even a stored DB entry applies it
    silently."""
    names = [k.name for k in space.searchable_knobs()]
    assert "grad_accum" not in names
    prev = space.apply_config({**space.default_config(),
                               "grad_accum": "4"})
    assert os.environ.get("MXTPU_GRAD_ACCUM") is None
    space.restore_env(prev)

    monkeypatch.setenv("MXTPU_TUNE_SEMANTICS", "1")
    assert "grad_accum" in [k.name for k in space.searchable_knobs()]
    prev = space.apply_config({**space.default_config(),
                               "grad_accum": "4"})
    assert os.environ.get("MXTPU_GRAD_ACCUM") == "4"
    space.restore_env(prev)
    assert os.environ.get("MXTPU_GRAD_ACCUM") is None


def test_mode_parsing(monkeypatch):
    assert search.mode() == "replay"            # the default
    for raw, want in (("off", "off"), ("0", "off"), ("false", "off"),
                      ("replay", "replay"), ("SEARCH", "search")):
        monkeypatch.setenv("MXTPU_AUTOTUNE", raw)
        assert search.mode() == want
    monkeypatch.setenv("MXTPU_AUTOTUNE", "bogus")
    with pytest.raises(mx.MXNetError):
        search.mode()


# -- trial runner --------------------------------------------------------------

def test_oom_trial_is_infeasible_not_a_crash(fault_inject):
    """tune_oom fault (hermetic RESOURCE_EXHAUSTED): the trial returns
    an infeasible result, emits tune_infeasible, and restores the
    env."""
    fault_inject("tune_oom:1")
    cfg = dict(space.default_config(), remat="dots")
    res = runner.run_trial(lambda: None, cfg, steps=2)
    assert not res.feasible
    assert res.score_us == math.inf
    assert "RESOURCE_EXHAUSTED" in res.error
    assert telemetry.event_counts().get("tune_infeasible") == 1
    assert os.environ.get("MXTPU_REMAT") is None   # trial env undone


def test_search_survives_oom_candidate(fault_inject):
    """One OOM candidate mid-search: the winner is still a feasible
    config and the infeasible one is never kept in the pool."""
    fault_inject("tune_oom:1")                  # first trial (= base) OOMs
    winner, results = search.successive_halving(
        lambda: None, total_budget=4, rung_steps=1)
    assert winner.feasible
    assert sum(1 for r in results if not r.feasible) == 1
    assert telemetry.event_counts().get("tune_infeasible") == 1
    assert telemetry.event_counts().get("tune_search_start") == 1


def test_search_budget_respected(monkeypatch):
    monkeypatch.setenv("MXTPU_TUNE_BUDGET", "3")
    _, results = search.successive_halving(lambda: None, rung_steps=1)
    assert len(results) == 3


# -- tuning DB -----------------------------------------------------------------

def test_db_roundtrip_and_key(tmp_path, monkeypatch):
    path = str(tmp_path / "tune_db.jsonl")
    monkeypatch.setenv("MXTPU_TUNE_DB", path)
    key = db.entry_key("abcd1234", "cpu", (("data", 8),))
    assert key == "abcd1234|cpu|data=8"
    assert db.entry_key("abcd1234", "cpu", None).endswith("|single")
    cfg = space.default_config()
    entry = db.record(key, cfg, 123.4, mfu=0.1, trials=5,
                      default_score_us=150.0)
    got = db.lookup(key)
    assert got == entry
    assert got["config"] == cfg
    assert got["fingerprint"] == space.fingerprint(cfg)
    assert got["db_version"] == db.DB_VERSION
    # later write for the same key wins
    db.record(key, dict(cfg, remat="dots"), 99.0)
    assert db.lookup(key)["config"]["remat"] == "dots"
    assert telemetry.event_counts().get("tune_db_write") == 2


def test_db_lives_next_to_compile_cache(tmp_path, monkeypatch):
    assert db.tune_db_path() is None            # no persistence configured
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    assert db.tune_db_path() == str(tmp_path / "tune_db.jsonl")
    monkeypatch.setenv("MXTPU_TUNE_DB", str(tmp_path / "elsewhere.jsonl"))
    assert db.tune_db_path() == str(tmp_path / "elsewhere.jsonl")


@pytest.mark.faults
def test_corrupt_db_falls_back_and_gcs(fault_inject, tmp_path,
                                       monkeypatch):
    """A corrupt entry (injected bit-rot via corrupt_tune_db) reads as
    absent with a tune_db_fallback event — never a crash — and the next
    write GCs it along with stale-version entries."""
    path = str(tmp_path / "tune_db.jsonl")
    monkeypatch.setenv("MXTPU_TUNE_DB", path)
    cfg = space.default_config()
    fault_inject("corrupt_tune_db:1")
    db.record("k1|cpu|single", cfg, 123.0)      # line lands corrupted
    assert db.lookup("k1|cpu|single") is None   # CRC catches it
    counts = telemetry.event_counts()
    assert counts.get("tune_db_fallback", 0) >= 1
    # a stale-schema entry (valid CRC, old db_version) is also skipped
    stale = {"db_version": db.DB_VERSION - 1, "key": "old",
             "config": cfg, "fingerprint": "x", "score_us": 1.0, "t": 0}
    with open(path, "a", encoding="utf-8") as f:
        f.write(db._encode(stale))
    assert db.lookup("old") is None
    # the next clean write GCs both: only the new entry survives, and
    # loading the rewritten file emits no further fallback
    db.record("k2|cpu|single", cfg, 50.0)
    before = telemetry.event_counts().get("tune_db_fallback", 0)
    entries = db.load(path)
    assert set(entries) == {"k2|cpu|single"}
    assert telemetry.event_counts().get("tune_db_fallback", 0) == before


def test_torn_tail_is_skipped(tmp_path, monkeypatch):
    """A half-written last line (crash mid-append) must not poison the
    file."""
    path = str(tmp_path / "tune_db.jsonl")
    monkeypatch.setenv("MXTPU_TUNE_DB", path)
    db.record("good|cpu|single", space.default_config(), 10.0)
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"crc": 123, "payl')            # torn
    entries = db.load(path)
    assert set(entries) == {"good|cpu|single"}


# -- telemetry schema v2 -------------------------------------------------------

def test_telemetry_trial_marking_and_v2_schema():
    acc = telemetry.step_begin(path="manual")
    telemetry.step_end(acc, step=0)
    telemetry.trial_begin("abc123def456")
    acc = telemetry.step_begin(path="manual")
    telemetry.step_end(acc, step=1)
    telemetry.trial_end()
    telemetry.set_config_fingerprint("feedc0ffee12")
    acc = telemetry.step_begin(path="manual")
    telemetry.step_end(acc, step=2)

    every = telemetry.recent_steps(include_trials=True)
    steady = telemetry.recent_steps()
    assert len(every) == 3
    assert [r["step"] for r in steady] == [0, 2]   # trial excluded
    assert every[1]["tuning_trial"] is True
    assert every[1]["config_fingerprint"] == "abc123def456"
    assert "tuning_trial" not in every[0]
    assert every[2]["config_fingerprint"] == "feedc0ffee12"
    for rec in every:
        assert rec["v"] == telemetry.SCHEMA_VERSION == 8   # v8: fencing
        telemetry.validate_record(rec)
    v1 = dict(every[0])
    v1["v"] = 1                                  # v1 records stay valid
    telemetry.validate_record(v1)
    bad = dict(every[1])
    bad["tuning_trial"] = "yes"
    with pytest.raises(ValueError):
        telemetry.validate_record(bad)
    bad = dict(every[1])
    bad["config_fingerprint"] = ""
    with pytest.raises(ValueError):
        telemetry.validate_record(bad)


# -- the acceptance loop: search -> persist -> replay --------------------------

def test_search_persist_replay_end_to_end(tmp_path):
    """search mode finds a winner within budget and persists it; a
    fresh replay run applies it with ZERO trials (tune_db_hit) and a
    loss trajectory bitwise-identical to MXTPU_AUTOTUNE=off — every
    searchable knob is numerics-preserving."""
    path = str(tmp_path / "tune_db.jsonl")
    os.environ["MXTPU_TUNE_DB"] = path
    os.environ["MXTPU_TUNE_STEPS"] = "1"
    os.environ["MXTPU_TUNE_BUDGET"] = "5"

    # 1) search: trials run on the live trainer, winner persisted
    os.environ["MXTPU_AUTOTUNE"] = "search"
    telemetry.reset()
    _train()
    counts = telemetry.event_counts()
    assert counts.get("tune_search_start") == 1
    assert counts.get("tune_trial") == 5         # the whole budget
    assert counts.get("tune_winner") == 1
    assert counts.get("tune_db_write") == 1
    (entry,) = db.load(path).values()
    assert entry["score_us"] < math.inf
    assert entry["trials"] == 5
    # the measured winner is never slower than the measured defaults
    if entry.get("default_score_us") is not None:
        assert entry["score_us"] <= entry["default_score_us"]
    # trial steps are marked: steady-state view saw only the 4 real steps
    assert len(telemetry.recent_steps()) == 4
    for k in space.KNOBS.values():               # winner's env, scrubbed
        os.environ.pop(k.env, None)

    # 2) baseline at defaults (fresh net, same seed), tuner off
    os.environ["MXTPU_AUTOTUNE"] = "off"
    telemetry.reset()
    losses_off, weights_off = _train()
    assert not telemetry.event_counts()

    # 3) replay: fresh net, same seed — DB hit, zero trials, bitwise
    os.environ["MXTPU_AUTOTUNE"] = "replay"
    telemetry.reset()
    losses_rep, weights_rep = _train()
    counts = telemetry.event_counts()
    assert counts.get("tune_db_hit") == 1
    assert "tune_trial" not in counts            # ZERO trials on restart
    assert "tune_search_start" not in counts
    assert not [r for r in telemetry.recent_steps(include_trials=True)
                if r.get("tuning_trial")]
    for s, (a, b) in enumerate(zip(losses_rep, losses_off)):
        np.testing.assert_array_equal(a, b, err_msg=f"loss step {s}")
    for i, (a, b) in enumerate(zip(weights_rep, weights_off)):
        np.testing.assert_array_equal(a, b, err_msg=f"weight {i}")
    # steady-state records carry the tuned config's fingerprint
    fps = {r.get("config_fingerprint")
           for r in telemetry.recent_steps()}
    assert fps == {entry["fingerprint"]}


def test_replay_is_noop_without_db():
    """Default mode (replay) with no DB configured: no events, no
    trials, just training."""
    telemetry.reset()
    losses_a, _ = _train()
    assert not telemetry.event_counts()
    os.environ["MXTPU_AUTOTUNE"] = "off"
    telemetry.reset()
    losses_b, _ = _train()
    for a, b in zip(losses_a, losses_b):
        np.testing.assert_array_equal(a, b)


# -- remat policy registry (bitwise over the captured step) --------------------

def _train_scan_encoder(policy, steps=3):
    """Captured ScanTransformerEncoder training under one MXTPU_REMAT
    policy; returns (losses, weights, peak_bytes, captured?)."""
    from mxnet_tpu.gluon.model_zoo import bert as bz

    if policy:
        os.environ["MXTPU_REMAT"] = policy
    else:
        os.environ.pop("MXTPU_REMAT", None)
    os.environ["MXTPU_AUTOTUNE"] = "off"
    mx.random.seed(11)
    net = bz.ScanTransformerEncoder(4, 32, 4, dropout=0.0)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(4, 8, 32).astype("float32"))
    y = mx.nd.array(rs.randn(4, 8, 32).astype("float32"))
    losses = [trainer.train_step(net, loss_fn, x, y).asnumpy()
              for _ in range(steps)]
    cache = getattr(trainer, "_captured_cache", {})
    step = next(iter(cache.values())) if cache else None
    peak = step.memory_high_water() if step is not None else None
    # key by counter-stripped name: each net instantiation bumps the
    # gluon auto-name counter ("scantransformerencoder9_..." vs "...10_")
    import re

    weights = {re.sub(r"\d+", "", n): p.data().asnumpy()
               for n, p in net.collect_params().items()}
    return losses, weights, peak, step is not None


def test_remat_registry_parsing():
    from mxnet_tpu import remat

    assert set(remat.names()) >= {"full", "dots", "dots_no_batch"}
    assert remat.canonical(True) == "full"
    assert remat.canonical("all") == "full"
    assert remat.canonical("none") is None
    assert remat.canonical(None) is None
    assert remat.parse_save_every("save_every_k:2") == 2
    assert remat.parse_save_every("dots") is None
    with pytest.raises(mx.MXNetError):
        remat.canonical("bogus_policy")
    with pytest.raises(mx.MXNetError):
        remat.parse_save_every("save_every_k:0")


def test_remat_env_precedence(monkeypatch):
    from mxnet_tpu import remat

    assert remat.env_default(None) is None
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    assert remat.env_default(None) == "full"     # reference-compat alias
    monkeypatch.setenv("MXTPU_REMAT", "dots")
    assert remat.env_default(None) == "dots"     # MXTPU_REMAT wins
    assert remat.env_default("save_every_k:2") == "save_every_k:2"


@pytest.mark.parametrize("policy", ["none", "full", "dots",
                                    "dots_no_batch", "save_every_k:2"])
def test_remat_policy_bitwise_parity(policy):
    """Every named policy over the captured ScanTransformerEncoder step
    is a pure recompute schedule: losses AND weights bitwise-identical
    to the unremat'd capture."""
    base_l, base_w, _, base_cap = _train_scan_encoder(None)
    assert base_cap, "baseline must take the captured path"
    l, w, _, cap = _train_scan_encoder(policy)
    assert cap, f"policy {policy} must stay capture-eligible"
    for s, (a, b) in enumerate(zip(l, base_l)):
        np.testing.assert_array_equal(a, b,
                                      err_msg=f"{policy} loss step {s}")
    assert set(w) == set(base_w)
    for n in w:
        np.testing.assert_array_equal(w[n], base_w[n],
                                      err_msg=f"{policy} weight {n}")


def test_remat_save_every_k_lowers_high_water():
    """The measured activation-memory delta: chunked trunk remat
    (save_every_k:2 over the L=4 scanned stack) must lower the step
    program's high-water mark below the unremat'd capture."""
    _, _, peak_none, _ = _train_scan_encoder(None)
    _, _, peak_k2, _ = _train_scan_encoder("save_every_k:2")
    assert peak_none is not None and peak_k2 is not None, \
        "memory_analysis unavailable on this jax build"
    assert peak_k2 < peak_none, (peak_k2, peak_none)


# -- optimizer group splitting (MXTPU_GROUP_MAX_ITEMS) -------------------------

def test_group_max_items_split_is_bitwise(monkeypatch):
    """Capping fused-group size re-plans into more groups (one eager
    dispatch per chunk) without changing a single bit of the update
    math, on both the eager and captured paths."""
    os.environ["MXTPU_AUTOTUNE"] = "off"
    # eager: 4 adam params = 1 fused dispatch/step; cap 1 -> 4
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "0")
    grouped.reset_dispatch_count()
    base_l, base_w = _train(steps=2)
    assert grouped.dispatch_count() == 2         # one group, two steps
    monkeypatch.setenv("MXTPU_GROUP_MAX_ITEMS", "1")
    grouped.reset_dispatch_count()
    split_l, split_w = _train(steps=2)
    assert grouped.dispatch_count() == 8         # four chunks, two steps
    for a, b in zip(split_l, base_l):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(split_w, base_w):
        np.testing.assert_array_equal(a, b)
    # captured path under the same cap stays bitwise too
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    cap_l, cap_w = _train(steps=2)
    for a, b in zip(cap_l, base_l):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(cap_w, base_w):
        np.testing.assert_array_equal(a, b)


# -- trace_report autotune section ---------------------------------------------

def test_trace_report_autotune_section(tmp_path, monkeypatch):
    """A tuning run's event log flows through the trace_report CLI: the
    autotune section shows trials, the winner + improvement, and DB
    activity; trial steps are split out of the steady-state header."""
    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    monkeypatch.setenv("MXTPU_TUNE_DB", str(tmp_path / "db.jsonl"))
    telemetry.reset()
    telemetry.event("tune_search_start", candidates=3, budget=4)
    telemetry.trial_begin("aaaabbbbcccc")
    acc = telemetry.step_begin(path="manual")
    telemetry.step_end(acc, step=0)
    telemetry.trial_end()
    telemetry.event("tune_trial", fingerprint="aaaabbbbcccc", steps=1,
                    score_us=120.0)
    telemetry.event("tune_infeasible", fingerprint="ddddeeeeffff",
                    error="RESOURCE_EXHAUSTED: injected")
    db.record("sig|cpu|single", space.default_config(), 100.0,
              default_score_us=120.0)
    telemetry.event("tune_winner", key="sig|cpu|single",
                    fingerprint="aaaabbbbcccc", score_us=100.0,
                    default_score_us=120.0, improvement=1.2, trials=4)
    telemetry.event("tune_db_hit", key="sig|cpu|single",
                    fingerprint="aaaabbbbcccc", score_us=100.0)
    acc = telemetry.step_begin(path="manual")
    telemetry.step_end(acc, step=1)
    telemetry.reset()                            # close the sink

    env = dict(os.environ)
    env.pop("MXTPU_TELEMETRY_PATH", None)
    proc = subprocess.run(
        [sys.executable, _TRACE_REPORT, path, "--validate"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    out = proc.stdout
    assert "1 step records (+1 tuning trials)" in out
    assert "autotune:" in out
    assert "trials: 1 scored, 1 infeasible (OOM)" in out
    assert "winner: aaaabbbbcccc at 100.0 us/step" in out
    assert "1.200x vs default 120.0 us" in out
    assert "db hits (replayed with zero trials): 1" in out
    assert "db writes: 1" in out
