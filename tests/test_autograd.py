"""Autograd tests (reference: tests/python/unittest/test_autograd.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain():
    x = nd.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * 2 + x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * np.exp(x.asnumpy()) + 1,
                       rtol=1e-5)


def test_multi_input():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    assert np.allclose(a.grad.asnumpy(), b.asnumpy() + 1)
    assert np.allclose(b.grad.asnumpy(), a.asnumpy())


def test_dot_grad():
    a = nd.array(np.random.rand(2, 3).astype(np.float32))
    w = nd.array(np.random.rand(3, 4).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        out = nd.dot(a, w)
        loss = nd.sum(out)
    loss.backward()
    expected = a.asnumpy().T @ np.ones((2, 4), np.float32)
    assert np.allclose(w.grad.asnumpy(), expected, rtol=1e-5)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30, 300])


def test_grad_req_add():
    x = nd.array([2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * x
        y.backward()
    assert np.allclose(x.grad.asnumpy(), 3 * 2 * 2.0)


def test_detach_blocks_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 4.0)  # only d(y_detached * x)/dx


def test_stop_gradient_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.stop_gradient(x * x) + x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 1.0)


def test_pause():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        with autograd.pause():
            y = x * 10
        z = x * 2
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2.0)


def test_recording_flags():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    assert not autograd.is_recording()


def test_getitem_grad():
    x = nd.array([1.0, 2.0, 3.0, 4.0])
    x.attach_grad()
    with autograd.record():
        y = x[1:3] * 2
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0, 2, 2, 0])


def test_autograd_grad_api():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad([y], [x])
    assert np.allclose(g.asnumpy(), 12.0)


def test_mark_variables():
    x = nd.array([1.0, 1.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = nd.sum(x * 5)
    y.backward()
    assert np.allclose(g.asnumpy(), 5.0)


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self._saved
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_multi_output_op_grad():
    x = nd.array(np.random.rand(4, 6).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        a, b = nd.split(x, num_outputs=2, axis=1)
        loss = nd.sum(a) + nd.sum(b * 2)
    loss.backward()
    g = x.grad.asnumpy()
    assert np.allclose(g[:, :3], 1) and np.allclose(g[:, 3:], 2)


def test_softmax_output_custom_grad():
    x = nd.array(np.random.rand(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3], dtype="int32")
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, label.astype("float32"))
    out.backward()
    p = np.exp(x.asnumpy()) / np.exp(x.asnumpy()).sum(1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    # reference default normalization='null': grad is p - onehot, unscaled
    assert np.allclose(x.grad.asnumpy(), p - onehot, rtol=1e-4, atol=1e-5)


# -- higher-order (create_graph=True) ------------------------------------------
# Reference: tests/python/unittest/test_higher_order_grad.py

def test_second_order_cube():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        dy = autograd.grad(y, x, create_graph=True)
    dy.backward()
    # d2(x^3)/dx2 = 6x
    assert np.allclose(x.grad.asnumpy(), 6 * x.asnumpy())


def test_second_order_sin():
    x = nd.array([0.3, -0.7, 1.2])
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x)
        dy = autograd.grad(y, x, create_graph=True)
        z = (dy * dy).sum()
    z.backward()
    # d/dx (cos^2 x) = -2 cos x sin x
    expect = -2 * np.cos(x.asnumpy()) * np.sin(x.asnumpy())
    assert np.allclose(x.grad.asnumpy(), expect, atol=1e-5)


def test_third_order():
    x = nd.array([0.5, 1.5])
    x.attach_grad()
    with autograd.record():
        y = x * x * x * x          # x^4
        d1 = autograd.grad(y, x, create_graph=True)   # 4x^3
        d2 = autograd.grad(d1, x, create_graph=True)  # 12x^2
    d2.backward()                                     # 24x
    assert np.allclose(x.grad.asnumpy(), 24 * x.asnumpy(), atol=1e-4)


def test_create_graph_multivar():
    x = nd.array([1.0, 2.0])
    w = nd.array([3.0, 4.0])
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = (x * x * w).sum()
        dx, dw = autograd.grad(y, [x, w], create_graph=True)
        z = (dx * dx).sum() + (dw * dw).sum()
    z.backward()
    # dx = 2xw, dw = x^2; z = sum 4x^2w^2 + x^4
    # dz/dx = 8xw^2 + 4x^3 ; dz/dw = 8x^2 w
    xn, wn = x.asnumpy(), w.asnumpy()
    assert np.allclose(x.grad.asnumpy(), 8 * xn * wn ** 2 + 4 * xn ** 3)
    assert np.allclose(w.grad.asnumpy(), 8 * xn ** 2 * wn)


def test_second_order_through_hybridized_block():
    from mxnet_tpu import gluon

    net = gluon.nn.Dense(1, use_bias=False, in_units=2)
    net.initialize()
    net.hybridize()
    x = nd.array([[0.5, -1.0]])
    x.attach_grad()
    net(x)  # build/compile
    with autograd.record():
        y = net(x)
        dx = autograd.grad(y, x, create_graph=True)
        z = (dx * dx).sum()
    z.backward()
    # y = xW^T, dx = W (const in x), z = |W|^2 -> d z/dx = 0
    assert np.allclose(x.grad.asnumpy(), 0.0)
    # and dx itself equals the weight row
    w = net.weight.data().asnumpy()
    assert np.allclose(dx.asnumpy(), w.reshape(1, -1), atol=1e-6)
