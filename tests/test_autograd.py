"""Autograd tests (reference: tests/python/unittest/test_autograd.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain():
    x = nd.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * 2 + x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * np.exp(x.asnumpy()) + 1,
                       rtol=1e-5)


def test_multi_input():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    assert np.allclose(a.grad.asnumpy(), b.asnumpy() + 1)
    assert np.allclose(b.grad.asnumpy(), a.asnumpy())


def test_dot_grad():
    a = nd.array(np.random.rand(2, 3).astype(np.float32))
    w = nd.array(np.random.rand(3, 4).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        out = nd.dot(a, w)
        loss = nd.sum(out)
    loss.backward()
    expected = a.asnumpy().T @ np.ones((2, 4), np.float32)
    assert np.allclose(w.grad.asnumpy(), expected, rtol=1e-5)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30, 300])


def test_grad_req_add():
    x = nd.array([2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * x
        y.backward()
    assert np.allclose(x.grad.asnumpy(), 3 * 2 * 2.0)


def test_detach_blocks_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 4.0)  # only d(y_detached * x)/dx


def test_stop_gradient_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.stop_gradient(x * x) + x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 1.0)


def test_pause():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        with autograd.pause():
            y = x * 10
        z = x * 2
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2.0)


def test_recording_flags():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    assert not autograd.is_recording()


def test_getitem_grad():
    x = nd.array([1.0, 2.0, 3.0, 4.0])
    x.attach_grad()
    with autograd.record():
        y = x[1:3] * 2
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0, 2, 2, 0])


def test_autograd_grad_api():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad([y], [x])
    assert np.allclose(g.asnumpy(), 12.0)


def test_mark_variables():
    x = nd.array([1.0, 1.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = nd.sum(x * 5)
    y.backward()
    assert np.allclose(g.asnumpy(), 5.0)


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self._saved
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_multi_output_op_grad():
    x = nd.array(np.random.rand(4, 6).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        a, b = nd.split(x, num_outputs=2, axis=1)
        loss = nd.sum(a) + nd.sum(b * 2)
    loss.backward()
    g = x.grad.asnumpy()
    assert np.allclose(g[:, :3], 1) and np.allclose(g[:, 3:], 2)


def test_softmax_output_custom_grad():
    x = nd.array(np.random.rand(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3], dtype="int32")
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, label.astype("float32"))
    out.backward()
    p = np.exp(x.asnumpy()) / np.exp(x.asnumpy()).sum(1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    # reference default normalization='null': grad is p - onehot, unscaled
    assert np.allclose(x.grad.asnumpy(), p - onehot, rtol=1e-4, atol=1e-5)
