"""2-process crash-consistency worker for the AsyncCheckpointer
(tests/test_checkpoint.py, slow tier).

Each rank runs an identical deterministic decay update and saves through
one shared :class:`checkpoint.AsyncCheckpointer` every ``SAVE_EVERY``
steps — leaves partition round-robin across the two ranks, so both the
shard barrier and the rank-0 manifest commit are exercised for real.

``CKPT_CRASH_SITE`` + ``CKPT_CRASH_STEP`` arm an injected crash on rank
``CKPT_CRASH_RANK`` the FIRST time that step's save runs (a marker file
keeps the relaunched gang clean): the dying rank kills its commit
mid-flight, the survivor's barrier wedges until the collective watchdog
aborts it, ``launch.py --max-restarts`` relaunches the gang, and both
ranks resume from the last COMMITTED step.  The final state must match
an uninterrupted serial replay bit-for-bit.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from mxnet_tpu import checkpoint, distributed, resilience

SAVE_EVERY = 5


def apply_step(state):
    state["w"] = (state["w"] * 0.9).astype(np.float32)
    state["b"] = (state["b"] + state["w"].sum()).astype(np.float32)


def initial_state():
    return {"w": np.full((8, 8), 10.0, np.float32),
            "b": np.zeros(4, np.float32)}


def main():
    work, num_steps = sys.argv[1], int(sys.argv[2])
    distributed.init_from_env()
    rank = distributed.rank()
    ck = checkpoint.AsyncCheckpointer(os.path.join(work, "ckpt"),
                                      max_to_keep=3)
    assert ck.world_size == 2, ck.world_size

    crash_site = os.environ.get("CKPT_CRASH_SITE")
    crash_rank = int(os.environ.get("CKPT_CRASH_RANK", "0"))
    crash_step = int(os.environ.get("CKPT_CRASH_STEP", "10"))
    marker = os.path.join(work, "crashed_once")

    state = initial_state()

    def set_state(s):
        state["w"] = np.asarray(s["w"], np.float32).copy()
        state["b"] = np.asarray(s["b"], np.float32).copy()

    start = resilience.resume_latest(ck, set_state)
    if start:
        print(f"worker {rank}: resumed from step {start}", flush=True)
    for step in range(start + 1, num_steps + 1):
        apply_step(state)
        if step % SAVE_EVERY == 0:
            if (crash_site and rank == crash_rank
                    and step == crash_step
                    and not os.path.exists(marker)):
                # drain the PREVIOUS async commit before arming, so the
                # injected crash fires inside THIS step's commit (the
                # fault plan is process-global — an in-flight writer
                # would consume it mid-way through the prior step)
                ck.wait()
                open(marker, "w").close()
                os.environ["MXTPU_FAULT_INJECT"] = f"{crash_site}:1"
                resilience.reset_faults()
            ck.save(step, {"w": state["w"], "b": state["b"]})
    ck.wait()
    print(f"worker {rank}: ckpt run done at step {num_steps} "
          f"w00={state['w'][0, 0]:.9g}", flush=True)


if __name__ == "__main__":
    main()
