"""Multi-process dist_sync kvstore worker.

Reference parity: tests/nightly/dist_sync_kvstore.py, which the reference
runs as fake-multi-node via `tools/launch.py -n 2 --launcher local` (dmlc
tracker forks scheduler/server/workers on localhost).  Here the same
launcher spawns N processes that rendezvous through
``jax.distributed.initialize`` and all-reduce over the global device set
(no parameter server — SURVEY.md §2.6).

Run directly by tests/test_distributed.py; asserts the reference
invariants: pulled value == sum of all workers' pushes, list-key push/pull,
barrier, and data-parallel Trainer steps keeping weights bit-identical
across workers.
"""

import os
import sys

import numpy as np


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == int(os.environ["MXTPU_NUM_WORKERS"]), \
        (nw, os.environ["MXTPU_NUM_WORKERS"])

    # -- push/pull invariant: pulled == sum over workers of pushed -------------
    shape = (8, 8)
    kv.init("w0", mx.nd.zeros(shape))
    kv.push("w0", mx.nd.ones(shape) * (rank + 1))
    out = mx.nd.zeros(shape)
    kv.pull("w0", out=out)
    expect = sum(r + 1 for r in range(nw))
    np.testing.assert_allclose(out.asnumpy(), np.full(shape, float(expect)))

    # -- list keys -------------------------------------------------------------
    kv.init(["a", "b"], [mx.nd.zeros((4,)), mx.nd.zeros((2, 3))])
    kv.push(["a", "b"], [mx.nd.ones((4,)) * rank, mx.nd.ones((2, 3))])
    oa, ob = mx.nd.zeros((4,)), mx.nd.zeros((2, 3))
    kv.pull(["a", "b"], out=[oa, ob])
    np.testing.assert_allclose(oa.asnumpy(),
                               np.full((4,), float(sum(range(nw)))))
    np.testing.assert_allclose(ob.asnumpy(), np.full((2, 3), float(nw)))
    kv.barrier()

    # -- data-parallel training: different data per worker, identical ----------
    # weights after sync steps (the dist Trainer path)
    mx.random.seed(42)
    np.random.seed(42)
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    loss_fn = gluon.loss.L2Loss()
    x = mx.nd.array(np.random.RandomState(100 + rank).randn(4, 8)
                    .astype("float32"))
    for _ in range(2):
        with autograd.record():
            loss = loss_fn(net(x), mx.nd.zeros((4, 4)))
        loss.backward()
        trainer.step(4 * nw)
    w = net.weight.data().asnumpy()
    # all-reduce the weights on a FRESH store (the Trainer installed its
    # updater on `kv`, so pushes there apply sgd instead of summing);
    # mean must equal the local copy if every worker holds the same
    # weights
    kv2 = mx.kv.create("dist_sync")
    kv2.init("wcheck", mx.nd.zeros(w.shape))
    kv2.push("wcheck", mx.nd.array(w))
    avg = mx.nd.zeros(w.shape)
    kv2.pull("wcheck", out=avg)
    np.testing.assert_allclose(avg.asnumpy() / nw, w, rtol=1e-5,
                               atol=1e-6)

    # -- 2-bit gradient compression over the real wire -------------------------
    # (reference: tests/nightly/dist_sync_kvstore.py compressed section)
    kv3 = mx.kv.create("dist_sync")
    kv3.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv3.init("c0", mx.nd.zeros((5,)))
    kv3.set_updater(lambda k, g, s: s._set_data((s + g)._data))
    # worker r pushes [-0.8, 0.6, 0.2, 0.9, -0.1]; every worker
    # quantizes identically -> sum = nw * [-0.5, 0.5, 0, 0.5, 0]
    kv3.push("c0", mx.nd.array(np.array([-0.8, 0.6, 0.2, 0.9, -0.1],
                                        np.float32)))
    oc = mx.nd.zeros((5,))
    kv3.pull("c0", out=oc)
    np.testing.assert_allclose(
        oc.asnumpy(), nw * np.array([-0.5, 0.5, 0.0, 0.5, 0.0]),
        atol=1e-6)
    # error feedback: second identical push sees acc = g + r
    kv3.push("c0", mx.nd.array(np.array([-0.8, 0.6, 0.2, 0.9, -0.1],
                                        np.float32)))
    kv3.pull("c0", out=oc)
    # acc=[-1.1,0.7,0.4,1.3,-0.2] -> q=[-0.5(hit twice: -1.0),...]
    np.testing.assert_allclose(
        oc.asnumpy(), nw * np.array([-1.0, 1.0, 0.0, 1.0, 0.0]),
        atol=1e-6)
    # -- fp16 compression: the WIRE carries f16 (ADVICE r3) ---------------------
    kv4 = mx.kv.create("dist_sync")
    kv4.set_gradient_compression({"type": "fp16"})
    kv4.init("f0", mx.nd.zeros((4,)))
    kv4.set_updater(lambda k, g, s: s._set_data((s + g)._data))
    g16 = np.array([1.0009766, -2.0, 0.333333, 4096.5], np.float32)
    kv4.push("f0", mx.nd.array(g16))
    of = mx.nd.zeros((4,))
    kv4.pull("f0", out=of)
    expect = nw * np.float16(g16).astype(np.float32)
    np.testing.assert_allclose(of.asnumpy(), expect, rtol=1e-3)
    print(f"worker {rank}/{nw}: dist_sync_kvstore OK", flush=True)


if __name__ == "__main__":
    sys.exit(main())
