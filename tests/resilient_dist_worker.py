"""Worker for the 2-process crash-resume fault test (test_distributed.py).

Each rank trains a deterministic toy model (rank-independent SGD on a
quadratic, so a serial replay verifies the final state), checkpoints to a
per-rank LocalCheckpointer every 5 steps, and runs a guarded
``distributed.barrier`` every step so the gang fate-shares.

Fault script: rank 1 self-SIGTERMs at step CRASH_STEP on its FIRST life
(a marker file in the shared work dir prevents the relaunched rank from
re-crashing).  Rank 0's next barrier then wedges waiting on the dead
peer; MXTPU_COLLECTIVE_TIMEOUT + MXTPU_WATCHDOG_ACTION=abort must kill
it with a stack dump instead of letting it hang.  On relaunch both ranks
resume from their latest checkpoint and finish.
"""

import os
import signal
import sys

import numpy as np

CRASH_STEP = 17


def main():
    work_dir = sys.argv[1]
    num_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 40

    from mxnet_tpu import distributed, resilience

    distributed.init_from_env()
    rank = distributed.rank()
    marker = os.path.join(work_dir, "crashed_once")
    ck = resilience.LocalCheckpointer(
        os.path.join(work_dir, f"rank{rank}"), max_to_keep=3)

    state = {"w": np.full(4, 10.0)}

    def set_state(s):
        state["w"] = np.asarray(s["w"]).copy()

    start = resilience.resume_latest(ck, set_state)
    if start:
        print(f"worker {rank}: resumed from step {start}", flush=True)

    for step in range(start, num_steps):
        if rank == 1 and step == CRASH_STEP and not os.path.exists(marker):
            # first life only: die hard, mid-step, before the barrier —
            # the last checkpoint (step 15) is what the relaunch resumes
            with open(marker, "w"):
                pass
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
        distributed.barrier(f"step{step}")
        state["w"] = state["w"] - 0.05 * 2 * state["w"]
        if (step + 1) % 5 == 0:
            ck.save(step + 1, {"w": state["w"]})

    if ck.latest_step() != num_steps:
        ck.save(num_steps, {"w": state["w"]})
    print(f"worker {rank}: resilient run done at step {num_steps} "
          f"w0={state['w'][0]:.6f}", flush=True)


if __name__ == "__main__":
    main()
