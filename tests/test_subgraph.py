"""Subgraph partitioning (reference: src/operator/subgraph/ +
tests/python/unittest/test_subgraph_op.py)."""

import numpy as np

import mxnet_tpu as mx
import mxnet_tpu.subgraph as sg


def test_partition_whole_graph_single_region():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    y = mx.sym.broadcast_mul(mx.sym.relu(a + b), a)
    p = y.optimize_for("XLA")
    ops = [n.op for n in p._topo() if n.op]
    assert ops == ["_subgraph_exec"], ops
    av = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    bv = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(p.eval_raw(a=av, b=bv)),
                               np.asarray(y.eval_raw(a=av, b=bv)),
                               rtol=1e-6)


def test_partition_multi_output_region_member():
    """A multi-output op (BatchNorm stats) consumed outside via
    out_index must surface through the region's outputs."""
    data = mx.sym.Variable("data")
    g = mx.sym.Variable("g")
    be = mx.sym.Variable("be")
    mm = mx.sym.Variable("mm")
    mv = mx.sym.Variable("mv")
    bn = mx.sym.BatchNorm(data, g, be, mm, mv, output_mean_var=True,
                          fix_gamma=False, _is_training=True)
    y = mx.sym.broadcast_add(mx.sym.relu(bn[0]),
                             mx.sym.Reshape(bn[1], shape=(1, -1)))
    p = y.optimize_for("XLA")
    dv = np.random.RandomState(2).randn(4, 3).astype(np.float32)
    feed = dict(data=dv, g=np.ones(3, np.float32),
                be=np.zeros(3, np.float32), mm=np.zeros(3, np.float32),
                mv=np.ones(3, np.float32))
    np.testing.assert_allclose(np.asarray(p.eval_raw(**feed)),
                               np.asarray(y.eval_raw(**feed)),
                               rtol=1e-6)


def test_partition_splits_around_unsupported():
    class NoRelu(sg.SubgraphProperty):
        min_size = 1

        def op_filter(self, op, attrs):
            return op not in ("Activation", "relu") and \
                sg.XLASubgraphProperty().op_filter(op, attrs)

    sg.register_subgraph_property("_test_norelu", NoRelu())
    a = mx.sym.Variable("a")
    y = mx.sym.relu(mx.sym.broadcast_mul(a + a, a))
    y2 = mx.sym.broadcast_add(mx.sym.relu(y + a), a)
    p = y2.optimize_for("_test_norelu")
    ops = [n.op for n in p._topo() if n.op]
    assert ops.count("relu") == 2
    assert ops.count("_subgraph_exec") >= 2
    av = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(p.eval_raw(a=av)),
                               np.asarray(y2.eval_raw(a=av)), rtol=1e-6)


def test_partition_min_size_leaves_small_regions():
    class Tiny(sg.SubgraphProperty):
        min_size = 3

        def op_filter(self, op, attrs):
            return sg.XLASubgraphProperty().op_filter(op, attrs)

    sg.register_subgraph_property("_test_tiny", Tiny())
    a = mx.sym.Variable("a")
    y = mx.sym.relu(a)  # 1-op graph < min_size
    p = y.optimize_for("_test_tiny")
    ops = [n.op for n in p._topo() if n.op]
    assert ops == ["relu"], ops


def test_unknown_backend_raises():
    import pytest

    a = mx.sym.Variable("a")
    with pytest.raises(mx.base.MXNetError, match="backend"):
        mx.sym.relu(a).optimize_for("no_such_backend")


def test_partition_no_group_level_cycle():
    """Review repro: two groups must not become mutually dependent —
    X=mul(a,a) [g0], W=relu(X) unsupported, Q=mul(b,b), Y=add(W,Q),
    M=add(X,Q).  Joining M to g0 while Y's group depends on g0 would
    deadlock the rebuilt graph."""
    class NoRelu(sg.SubgraphProperty):
        min_size = 1

        def op_filter(self, op, attrs):
            return op not in ("Activation", "relu") and \
                sg.XLASubgraphProperty().op_filter(op, attrs)

    sg.register_subgraph_property("_test_norelu2", NoRelu())
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    X = mx.sym.broadcast_mul(a, a)
    W = mx.sym.relu(X)
    Q = mx.sym.broadcast_mul(b, b)
    Y = mx.sym.broadcast_add(W, Q)
    M = mx.sym.broadcast_add(X, Q)
    out = mx.sym.broadcast_add(Y, M)
    p = out.optimize_for("_test_norelu2")
    av = np.random.RandomState(0).randn(2, 2).astype(np.float32)
    bv = np.random.RandomState(1).randn(2, 2).astype(np.float32)
    np.testing.assert_allclose(np.asarray(p.eval_raw(a=av, b=bv)),
                               np.asarray(out.eval_raw(a=av, b=bv)),
                               rtol=1e-6)


def test_partition_random_and_mode_ops_in_region():
    """Review repro: random (Dropout) members need PRNG keys and mode
    injection inside the jitted region."""
    from mxnet_tpu import autograd

    a = mx.sym.Variable("a")
    y = mx.sym.broadcast_mul(mx.sym.Dropout(a + a, p=0.5), a)
    p = y.optimize_for("XLA")
    av = np.ones((4, 64), np.float32)
    with autograd.train_mode():
        out_t = np.asarray(p.eval_raw(a=av))
    # train mode: some elements dropped
    assert (out_t == 0).any()
    with autograd.predict_mode():
        out_p = np.asarray(p.eval_raw(a=av))
    # predict mode: dropout is identity -> (a+a)*a = 2
    np.testing.assert_allclose(out_p, 2.0 * np.ones((4, 64)), rtol=1e-6)


def test_partition_multioutput_member_not_duplicated():
    """Review repro: a multi-output node consumed both inside and
    outside its region must be computed ONCE (inside), its second
    output surfacing through the region outputs."""
    data = mx.sym.Variable("data")
    g = mx.sym.Variable("g")
    be = mx.sym.Variable("be")
    mm = mx.sym.Variable("mm")
    mv = mx.sym.Variable("mv")
    bn = mx.sym.BatchNorm(data, g, be, mm, mv, output_mean_var=True,
                          fix_gamma=False, _is_training=True)
    y = mx.sym.broadcast_add(mx.sym.relu(bn[0]),
                             mx.sym.Reshape(bn[1], shape=(1, -1)))
    p = y.optimize_for("XLA")
    names = [n.name for n in p._topo() if n.op]
    bn_nodes = [nm for nm in names if "batchnorm" in nm]
    assert not bn_nodes, f"BatchNorm duplicated outside region: {bn_nodes}"


def test_hybridblock_optimize_for():
    """gluon entry (reference: HybridBlock.optimize_for >=1.6): trace,
    partition, return a bound SymbolBlock with identical outputs."""
    from mxnet_tpu import autograd, gluon, nd

    rs = np.random.RandomState(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    x = nd.array(rs.randn(3, 8).astype("float32"))
    with autograd.predict_mode():
        opt = net.optimize_for(x)
        ref = net(x)
        out = opt(x)
    ops = [n.op for n in opt._outputs_sym._topo() if n.op]
    assert "_subgraph_exec" in ops, ops
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), atol=1e-6)


def test_optimize_for_multi_input_block():
    """optimize_for derives ordered input names from the trace, so a
    TWO-input HybridBlock partitions and rebinds correctly (the old
    hard-coded single var('data') mis-bound it)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import HybridBlock, nn

    class TwoIn(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc = nn.Dense(4, in_units=3)

        def hybrid_forward(self, F, a, b):
            return self.fc(a) + self.fc(b) * 2.0

    net = TwoIn()
    net.initialize(init=mx.init.Xavier())
    rs = np.random.RandomState(0)
    a = mx.nd.array(rs.randn(2, 3).astype("float32"))
    b = mx.nd.array(rs.randn(2, 3).astype("float32"))
    ref = net(a, b).asnumpy()
    sb = net.optimize_for(a, "XLA", b)
    np.testing.assert_allclose(sb(a, b).asnumpy(), ref, rtol=1e-5,
                               atol=1e-6)
