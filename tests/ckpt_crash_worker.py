"""Crash-consistency worker for tests/test_checkpoint.py.

Commits step 10 cleanly, then arms ONE MXTPU_FAULT_INJECT crash site
and saves step 20: the injected ``os._exit`` kills the process mid-save,
leaving the directory exactly as a power cut would.  The parent asserts
the process died with ``resilience.CRASH_EXIT_CODE`` and that restore
still yields the step-10 state — the previous checkpoint, never a torn
one.

Usage: ckpt_crash_worker.py <ckpt_dir> <fault_site> <sync|async>
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from mxnet_tpu import checkpoint, resilience


def state(tag):
    return {"w": np.full((64, 64), float(tag), np.float32),
            "b": np.arange(16, dtype=np.float32) + tag,
            "step": tag}


def main():
    ckdir, site, mode = sys.argv[1], sys.argv[2], sys.argv[3]
    ck = checkpoint.AsyncCheckpointer(
        ckdir, async_save=(mode == "async"), rank=0, world_size=1)
    ck.save(10, state(10))
    ck.wait()
    os.environ["MXTPU_FAULT_INJECT"] = f"{site}:1"
    resilience.reset_faults()
    ck.save(20, state(20))
    ck.wait()
    # only reachable if the injection never fired — the parent asserts
    # on CRASH_EXIT_CODE, so this is a loud failure
    print("survived: no crash was injected", flush=True)


if __name__ == "__main__":
    main()
