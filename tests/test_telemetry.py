"""Always-on telemetry (mxnet_tpu/telemetry.py): metrics registry,
per-step StepStats assembly, MFU accounting, crash-safe JSONL event log,
zero-extra-device-work regression, and the trace_report.py consumer."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, numerics, telemetry
from mxnet_tpu.gluon import captured, nn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CRASH_WORKER = os.path.join(_REPO, "tests", "telemetry_crash_worker.py")
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")


def _clean_env():
    """Subprocess workers must run on the CPU backend, never the TPU
    tunnel (same recipe as tests/test_checkpoint.py)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_FAULT_INJECT", None)
    env.pop("MXTPU_TELEMETRY_PATH", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(autouse=True)
def _telemetry_clean(monkeypatch):
    """Each test starts from empty ring/registry and no sink."""
    monkeypatch.delenv("MXTPU_TELEMETRY_PATH", raising=False)
    monkeypatch.delenv("MXTPU_TELEMETRY", raising=False)
    telemetry.reset()
    telemetry.REGISTRY.reset()
    yield
    telemetry.reset()
    telemetry.REGISTRY.reset()


def _tiny(seed=0):
    net = nn.Dense(4, in_units=8)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(seed)
    x = mx.nd.array(rng.rand(16, 8).astype("float32"))
    y = mx.nd.array(rng.rand(16, 4).astype("float32"))
    return net, loss_fn, trainer, x, y


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# -- metrics registry ----------------------------------------------------------

def test_metrics_registry():
    reg = telemetry.MetricsRegistry()
    reg.counter("a").inc()
    reg.counter("a").inc(2)
    reg.gauge("g").set(7)
    h = reg.histogram("h")
    for v in (1.0, 3.0, 2.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["a"] == 3
    assert snap["g"] == 7
    assert snap["h"] == {"count": 3, "total": 6.0, "min": 1.0, "max": 3.0}
    # a name is ONE metric type forever — silent aliasing would corrupt
    # whichever consumer registered first
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("a")


def test_disabled_is_a_noop(monkeypatch):
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    assert telemetry.step_begin() is None
    telemetry.count("x")
    telemetry.event("y", step=1)
    assert telemetry.recent_steps() == []
    assert telemetry.event_counts() == {}


# -- the acceptance pin: one captured step, one record, no extra work ----------

def test_captured_step_emits_one_complete_record():
    """ISSUE 7 acceptance: one healthy captured-path train step emits
    exactly one StepStats with non-null MFU, breakdown shares summing to
    ~1.0, and no device work beyond the step's own single dispatch +
    single guard readback."""
    net, loss_fn, trainer, x, y = _tiny()
    for _ in range(3):
        trainer.train_step(net, loss_fn, x, y)
    telemetry.reset()
    captured.reset_counters()
    numerics.reset_readback_count()

    trainer.train_step(net, loss_fn, x, y)

    recs = telemetry.recent_steps()
    assert len(recs) == 1
    rec = recs[0]
    telemetry.validate_record(rec)
    assert rec["path"] == "captured"
    assert rec["skipped"] is False
    assert rec["step"] == trainer._step_count
    assert rec["cache_hit"] is True
    assert rec["flops"] is not None and rec["flops"] > 0
    assert rec["mfu"] is not None and rec["mfu"] > 0
    assert abs(sum(rec["shares"].values()) - 1.0) < 0.02
    assert rec["breakdown_us"]["dispatch"] > 0
    assert rec["breakdown_us"]["readback"] > 0
    # the telemetry cost the step actually paid, in device terms: none
    assert captured.dispatch_count() == 1
    assert numerics.readback_count() == 1


def test_zero_extra_dispatch_readback_regression():
    """PR 6 pins: N captured steps = N dispatches, N guard readbacks,
    zero runtime retraces — telemetry (incl. the cost-analysis lowering
    behind MFU) must not move any of those counters."""
    net, loss_fn, trainer, x, y = _tiny()
    for _ in range(3):
        trainer.train_step(net, loss_fn, x, y)
    telemetry.reset()
    captured.reset_counters()
    numerics.reset_readback_count()
    n = 5
    for _ in range(n):
        trainer.train_step(net, loss_fn, x, y)
    assert captured.dispatch_count() == n
    assert captured.trace_count() == 0
    assert numerics.readback_count() == n
    assert len(telemetry.recent_steps(path="captured")) == n


def test_overhead_below_one_percent():
    """The <1% budget, pinned: the full per-record mechanism cost
    (step_begin + scope hooks + notes + step_end assembly into the
    ring) must stay under 1% of a representative captured step's wall
    time.  The model is deliberately NOT the 4-unit toy used elsewhere
    — the budget is relative to a step doing real work, and a
    microscopic step would pin Python dict overhead against XLA
    dispatch overhead, which bounds nothing."""
    net = nn.HybridSequential()
    net.add(nn.Dense(256, in_units=256, activation="relu"))
    net.add(nn.Dense(256, in_units=256))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(128, 256).astype("float32"))
    y = mx.nd.array(rng.rand(128, 256).astype("float32"))
    for _ in range(3):
        trainer.train_step(net, loss_fn, x, y)
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        trainer.train_step(net, loss_fn, x, y)
        times.append(time.perf_counter() - t0)
    step_s = sorted(times)[len(times) // 2]

    telemetry.reset()
    n = 300
    t0 = time.perf_counter()
    for i in range(n):
        acc = telemetry.step_begin(path="captured")
        telemetry.on_scope("captured_host_prep", 1e-4)
        telemetry.on_scope("captured_step", 2e-4)
        telemetry.on_scope("guard_readback", 1e-5)
        telemetry.note(flops=1e6, cache_hit=True, grad_norm=1.0)
        telemetry.step_end(acc, step=i)
    mech_s = (time.perf_counter() - t0) / n
    assert mech_s < 0.01 * step_s, \
        f"telemetry {mech_s * 1e6:.1f}us/record vs step " \
        f"{step_s * 1e6:.1f}us"


# -- JSONL sink: schema roundtrip and crash consistency ------------------------

def test_jsonl_schema_roundtrip(monkeypatch, tmp_path):
    path = str(tmp_path / "train_events.jsonl")
    net, loss_fn, trainer, x, y = _tiny()
    for _ in range(2):
        trainer.train_step(net, loss_fn, x, y)    # warm, unsunk
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    trainer.train_step(net, loss_fn, x, y)
    telemetry.event("marker", step=99, note="roundtrip")
    telemetry.reset()   # closes the sink handle

    recs = _read_jsonl(path)
    assert [r["type"] for r in recs] == ["step", "event"]
    for rec in recs:
        telemetry.validate_record(rec)
    step, ev = recs
    assert step["run"] == ev["run"] == telemetry.run_id()
    assert step["path"] == "captured"
    assert ev["event"] == "marker" and ev["step"] == 99


@pytest.mark.faults
def test_crash_mid_append_leaves_parseable_log(tmp_path):
    """telemetry_crash kills the process after HALF a line: every
    earlier line still parses and readers skip the truncated tail."""
    from mxnet_tpu import resilience

    path = str(tmp_path / "ev.jsonl")
    proc = subprocess.run(
        [sys.executable, _CRASH_WORKER, path],
        env=_clean_env(), capture_output=True, text=True, timeout=180)
    assert proc.returncode == resilience.CRASH_EXIT_CODE, \
        (proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:])

    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 4          # 3 whole lines + the torn tail
    good = [json.loads(ln) for ln in lines[:3]]
    assert [g["step"] for g in good] == [0, 1, 2]
    with pytest.raises(ValueError):
        json.loads(lines[3])

    r = subprocess.run(
        [sys.executable, _TRACE_REPORT, path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "skipping unparseable line" in r.stderr
    assert "3 records validate" in r.stdout


# -- resilience events carry correct step ids ----------------------------------

@pytest.mark.faults
def test_skip_step_event(fault_inject, monkeypatch, tmp_path):
    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    params = []
    import jax.numpy as jnp
    for k, shape in enumerate([(5, 7), (3,)]):
        p = gluon.Parameter(f"p{k}_weight", shape=shape, dtype="float32")
        p.initialize(init=mx.init.Zero())
        p.data()._set_data(jnp.asarray(
            np.random.RandomState(k).standard_normal(shape)
            .astype("float32")))
        params.append(p)
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                            kvstore=None)

    def set_grads():
        for p in params:
            p.list_grad()[0]._set_data(
                jnp.ones(p.shape, jnp.float32))

    set_grads()
    trainer.step(2, ignore_stale_grad=True)       # healthy
    fault_inject("nan_grad:1")
    set_grads()
    trainer.step(2, ignore_stale_grad=True)       # poisoned -> skipped
    telemetry.reset()   # close sink so the file is complete

    assert len(trainer.skipped_steps) == 1
    recs = _read_jsonl(path)
    for rec in recs:
        telemetry.validate_record(rec)
    evs = [r for r in recs if r.get("type") == "event"
           and r["event"] == "step_skipped"]
    assert len(evs) == 1
    assert evs[0]["step"] == trainer.skipped_steps[0].step == 2
    steps = [r for r in recs if r.get("type") == "step"]
    assert [s["path"] for s in steps] == ["manual", "manual"]
    assert [s["skipped"] for s in steps] == [False, True]
    assert steps[1]["step"] == 2


def test_divergence_rollback_event(monkeypatch, tmp_path):
    from mxnet_tpu.resilience import LocalCheckpointer

    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    ck = LocalCheckpointer(tmp_path / "ck")
    ck.save(7, {"w": np.arange(4.0)})
    restored = {}
    mon = numerics.DivergenceMonitor(
        checkpointer=ck, set_state=restored.update, max_bad_steps=3)
    for i in range(2):
        assert not mon.observe(step=i, loss=float("nan"),
                               batch_indices=[i])
    assert mon.observe(step=2, loss=float("nan"), batch_indices=[2])
    assert telemetry.event_counts() == {"divergence_rollback": 1}
    telemetry.reset()   # close the sink before reading the file

    (ev,) = _read_jsonl(path)
    telemetry.validate_record(ev)
    assert ev["event"] == "divergence_rollback"
    assert ev["step"] == 7            # the step rolled back TO
    assert ev["last_step"] == 2       # the last observed bad step
    assert ev["bad_steps"] == 3
    assert ev["quarantined"] == 3


def test_watchdog_expired_event():
    from mxnet_tpu import resilience

    wd = resilience.Watchdog(0.05, name="telemetry_test", action="none",
                             dump_stacks=False)
    wd.start()
    deadline = time.time() + 10
    while not wd.expired and time.time() < deadline:
        time.sleep(0.01)
    wd.cancel()
    assert wd.expired
    assert telemetry.event_counts().get("watchdog_expired") == 1


# -- component counters --------------------------------------------------------

def test_prefetcher_counters():
    from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher

    data = [np.ones((4, 3), np.float32) for _ in range(5)]
    assert len(list(DevicePrefetcher(data, depth=2))) == 5
    assert telemetry.REGISTRY.counter("input.batches").value == 5
    assert telemetry.REGISTRY.gauge("input.queue_depth").value is not None
    assert len(list(DevicePrefetcher(data, depth=0))) == 5
    assert telemetry.REGISTRY.counter("input.batches").value == 10
    assert telemetry.REGISTRY.counter("input.wait_us").value >= 0


def test_collective_counters():
    from mxnet_tpu import kvstore as kvs

    kv = kvs.create("device")
    kv.init(0, mx.nd.array(np.ones((8,), np.float32)))
    g = mx.nd.array(np.full((8,), 2.0, np.float32))
    kv.bucketed_pushpull([0], [g], outs=[g])
    assert telemetry.REGISTRY.counter("collective.buckets").value == 1
    assert telemetry.REGISTRY.counter("collective.bytes").value == 32


def test_ckpt_counters(monkeypatch, tmp_path):
    from mxnet_tpu import checkpoint

    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    ck = checkpoint.AsyncCheckpointer(
        str(tmp_path / "ck"), async_save=False, rank=0, world_size=1)
    ck.save(3, {"w": np.arange(8.0)})
    assert telemetry.REGISTRY.counter("ckpt.saves").value == 1
    assert telemetry.REGISTRY.counter("ckpt.stall_us").value > 0
    assert telemetry.REGISTRY.counter("ckpt.commits").value == 1
    assert telemetry.event_counts().get("ckpt_commit") == 1
    telemetry.reset()
    evs = [r for r in _read_jsonl(path)
           if r.get("event") == "ckpt_commit"]
    assert len(evs) == 1 and evs[0]["step"] == 3


# -- satellite: profiler.scope skips TraceAnnotation when idle -----------------

def test_scope_skips_trace_annotation_when_idle(monkeypatch):
    import jax

    from mxnet_tpu import profiler

    constructed = []

    class _Stub:
        def __init__(self, name):
            constructed.append(name)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", _Stub)
    with profiler.annotate("idle_scope"):
        pass
    assert constructed == []          # profiling off: no jax round-trip
    profiler.set_state("run")
    try:
        with profiler.annotate("hot_scope"):
            pass
    finally:
        profiler.set_state("stop")
    assert constructed == ["hot_scope"]


# -- satellite: CI smoke — one step, validate everything, run the CLI ----------

def test_smoke_one_step_validate_and_report(monkeypatch, tmp_path):
    path = str(tmp_path / "train_events.jsonl")
    net, loss_fn, trainer, x, y = _tiny()
    for _ in range(2):
        trainer.train_step(net, loss_fn, x, y)
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    trainer.train_step(net, loss_fn, x, y)
    telemetry.reset()

    recs = _read_jsonl(path)
    assert len([r for r in recs if r["type"] == "step"]) == 1
    for rec in recs:
        telemetry.validate_record(rec)

    r = subprocess.run(
        [sys.executable, _TRACE_REPORT, path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "validate against schema" in r.stdout
    assert "1 step records" in r.stdout
    assert "breakdown" in r.stdout
    assert "mfu" in r.stdout
