"""Fault-tolerance layer tests (mxnet_tpu/resilience.py).

All CPU-hermetic: every failure mode — flaky rendezvous, flaky IO,
stalled collectives, SIGTERM preemption, corrupt checkpoints — is
produced by the MXTPU_FAULT_INJECT harness or by hand-corrupting files,
never by real hardware.  No test may hang past its watchdog deadline.
"""

import io
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import resilience
from mxnet_tpu.resilience import (CheckpointCorrupt, InjectedFault,
                                  LocalCheckpointer, Watchdog,
                                  WatchdogExpired, retry_call,
                                  run_resilient)


# -- retry_call ----------------------------------------------------------------

def test_retry_call_succeeds_after_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, retries=4, backoff=0.001) == "ok"
    assert len(calls) == 3


def test_retry_call_exhausts_retries():
    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, retries=2, backoff=0.001)


def test_retry_call_deadline():
    def always():
        raise OSError("down")

    t0 = time.monotonic()
    with pytest.raises(mx.MXNetError, match="deadline"):
        retry_call(always, retries=100, backoff=0.05, jitter=0.0,
                   deadline=0.2)
    assert time.monotonic() - t0 < 2.0


def test_retry_call_non_retryable_immediate():
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        retry_call(missing, retries=5, backoff=0.001,
                   retryable=(OSError,),
                   non_retryable=(FileNotFoundError,))
    assert len(calls) == 1


def test_retry_call_backoff_grows():
    sleeps = []

    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, retries=3, backoff=0.001, jitter=0.0,
                   on_retry=lambda a, e, s: sleeps.append(s))
    assert sleeps == sorted(sleeps) and len(sleeps) == 3
    assert sleeps[1] == pytest.approx(2 * sleeps[0])


# -- fault-injection harness ---------------------------------------------------

@pytest.mark.faults
def test_fault_spec_parsing(fault_inject):
    fault_inject("rendezvous:2,corrupt_record:7,stall_collective:9.5")
    assert resilience.fault_arg("corrupt_record") == 7
    assert resilience.fault_arg("stall_collective") == 9.5
    with pytest.raises(InjectedFault):
        resilience.inject_failure("rendezvous")
    with pytest.raises(InjectedFault):
        resilience.inject_failure("rendezvous")
    resilience.inject_failure("rendezvous")  # count exhausted: no-op
    assert resilience.consume_fault("corrupt_record")
    assert not resilience.consume_fault("corrupt_record")


@pytest.mark.faults
def test_fault_spec_unknown_site(fault_inject):
    fault_inject("warp_core_breach:1")
    with pytest.raises(mx.MXNetError, match="unknown site"):
        resilience.inject_failure("rendezvous")


@pytest.mark.faults
def test_io_retry_recovers(fault_inject, monkeypatch):
    monkeypatch.setenv("MXTPU_IO_RETRIES", "3")
    monkeypatch.setenv("MXTPU_IO_BACKOFF", "0.001")
    fault_inject("io_open:2")
    calls = []

    def opener():
        calls.append(1)
        return "handle"

    assert resilience.io_retry(opener) == "handle"
    assert len(calls) == 1  # two injected failures happened pre-open


@pytest.mark.faults
def test_io_retry_exhausted(fault_inject, monkeypatch):
    monkeypatch.setenv("MXTPU_IO_RETRIES", "1")
    monkeypatch.setenv("MXTPU_IO_BACKOFF", "0.001")
    fault_inject("io_open:5")
    with pytest.raises(InjectedFault):
        resilience.io_retry(lambda: "never")


# -- watchdog ------------------------------------------------------------------

def test_watchdog_interrupts_stall():
    stream = io.StringIO()
    t0 = time.monotonic()
    with pytest.raises(WatchdogExpired, match="deadline"):
        with Watchdog(0.3, name="stall-test", action="interrupt",
                      stream=stream):
            time.sleep(30)
    assert time.monotonic() - t0 < 5.0
    out = stream.getvalue()
    assert "watchdog 'stall-test' expired" in out
    assert "thread stack dump" in out
    assert "time.sleep(30)" in out  # the dump shows WHERE it was stuck


def test_watchdog_feed_extends_deadline():
    with Watchdog(0.4, name="fed", action="interrupt") as wd:
        for _ in range(4):
            time.sleep(0.2)
            wd.feed()
    assert not wd.expired


def test_watchdog_cancel_no_fire():
    wd = Watchdog(0.2, name="cancelled", action="interrupt")
    wd.start()
    wd.cancel()
    time.sleep(0.4)
    assert not wd.expired


def test_watchdog_none_action_runs_on_expire():
    fired = []
    with Watchdog(0.15, name="observer", action="none",
                  on_expire=lambda: fired.append(1),
                  stream=io.StringIO()) as wd:
        time.sleep(0.5)
    assert wd.expired and fired == [1]


def test_watchdog_abort_exits_process():
    """action='abort' is the only escape from a wedged C call: the
    process must die with the configured exit code AFTER dumping
    stacks.  Exercised in a subprocess (os._exit kills pytest too)."""
    code = ("import importlib.util, time\n"
            "spec = importlib.util.spec_from_file_location(\n"
            "    'res', 'mxnet_tpu/resilience.py')\n"
            "res = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(res)\n"
            "wd = res.Watchdog(0.3, name='wedge', action='abort',"
            " exit_code=42)\n"
            "wd.start()\n"
            "time.sleep(60)\n")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 42, proc.stderr
    assert "thread stack dump" in proc.stderr
    assert "watchdog 'wedge' expired" in proc.stderr


def test_watchdog_rejects_unknown_action():
    with pytest.raises(mx.MXNetError, match="unknown action"):
        Watchdog(1.0, action="self-destruct")


# -- rendezvous retry ----------------------------------------------------------

@pytest.mark.faults
def test_rendezvous_retries_then_succeeds(fault_inject, monkeypatch):
    from mxnet_tpu import distributed

    attempts = []
    import jax

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: attempts.append(kw))
    monkeypatch.setenv("MXTPU_RENDEZVOUS_RETRIES", "3")
    monkeypatch.setenv("MXTPU_RENDEZVOUS_TIMEOUT", "30")
    monkeypatch.setattr(resilience.time, "sleep", lambda s: None)
    fault_inject("rendezvous:2")
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    distributed.initialize("127.0.0.1:1", 1, 0)
    # two injected failures burned two attempts; the third connected
    assert len(attempts) == 1
    assert attempts[0]["coordinator_address"] == "127.0.0.1:1"


@pytest.mark.faults
def test_rendezvous_retries_exhausted(fault_inject, monkeypatch):
    from mxnet_tpu import distributed

    import jax

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: None)
    monkeypatch.setenv("MXTPU_RENDEZVOUS_RETRIES", "1")
    monkeypatch.setattr(resilience.time, "sleep", lambda s: None)
    fault_inject("rendezvous:10")
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    with pytest.raises(InjectedFault):
        distributed.initialize("127.0.0.1:1", 1, 0)


# -- stalled collective --------------------------------------------------------

@pytest.mark.faults
def test_stalled_collective_hits_watchdog(fault_inject, monkeypatch):
    """The round-5 tunnel wedge, hermetic: a collective that stalls must
    be killed by MXTPU_COLLECTIVE_TIMEOUT, not hang the suite."""
    from mxnet_tpu import distributed

    monkeypatch.setenv("MXTPU_COLLECTIVE_TIMEOUT", "0.5")
    fault_inject("stall_collective:30")
    t0 = time.monotonic()
    with pytest.raises(WatchdogExpired):
        distributed.barrier("stall-test")
    assert time.monotonic() - t0 < 10.0


@pytest.mark.faults
def test_guarded_collective_passes_when_healthy(monkeypatch):
    from mxnet_tpu import distributed

    monkeypatch.setenv("MXTPU_COLLECTIVE_TIMEOUT", "30")
    distributed.barrier("healthy")  # single process: returns instantly


# -- local checkpointer --------------------------------------------------------

def test_local_checkpointer_roundtrip(tmp_path):
    ck = LocalCheckpointer(tmp_path)
    state = {"w": np.arange(6.0).reshape(2, 3), "step": 5,
             "nested": {"b": [1, 2, 3]}}
    ck.save(5, state)
    got = ck.restore(5)
    np.testing.assert_array_equal(got["w"], state["w"])
    assert got["nested"]["b"] == [1, 2, 3]
    assert ck.latest_step() == 5
    ck.verify(5)


def test_local_checkpointer_prunes(tmp_path):
    ck = LocalCheckpointer(tmp_path, max_to_keep=2)
    for s in (1, 2, 3, 4):
        ck.save(s, {"s": s})
    assert ck.all_steps() == [3, 4]


def test_local_checkpointer_detects_corruption(tmp_path):
    ck = LocalCheckpointer(tmp_path)
    ck.save(3, {"w": np.ones(8)})
    path = os.path.join(str(tmp_path), "ckpt_0000000003.mxtckpt")
    blob = open(path, "rb").read()
    with open(path, "wb") as f:        # flip payload bytes: crc mismatch
        f.write(blob[:-4] + b"\xde\xad\xbe\xef")
    with pytest.raises(CheckpointCorrupt, match="checksum"):
        ck.restore(3)
    with open(path, "wb") as f:        # truncate: length mismatch
        f.write(blob[:len(blob) // 2])
    with pytest.raises(CheckpointCorrupt, match="truncated"):
        ck.restore(3)
    with open(path, "wb") as f:        # stomp magic
        f.write(b"NOTCKPT!" + blob[8:])
    with pytest.raises(CheckpointCorrupt, match="magic"):
        ck.restore(3)


def test_resume_latest_falls_back_past_corrupt(tmp_path):
    ck = LocalCheckpointer(tmp_path)
    ck.save(10, {"v": 10})
    ck.save(20, {"v": 20})
    path = os.path.join(str(tmp_path), "ckpt_0000000020.mxtckpt")
    with open(path, "wb") as f:
        f.write(b"garbage")
    restored = []
    step = resilience.resume_latest(ck, restored.append)
    assert step == 10
    assert restored[0]["v"] == 10


def test_resume_latest_fresh_start(tmp_path):
    ck = LocalCheckpointer(tmp_path)
    assert resilience.resume_latest(ck, lambda s: None) == 0


# -- run_resilient: numpy model ------------------------------------------------

def _numpy_trainer():
    """Deterministic toy SGD on a quadratic — state is one weight
    vector, loss strictly decreases, trajectory is exactly replayable."""
    state = {"w": np.full(4, 10.0)}

    def step_fn(step):
        w = state["w"]
        loss = float((w ** 2).sum())
        state["w"] = w - 0.1 * 2 * w
        return loss

    return (step_fn, lambda: {"w": state["w"].copy()},
            lambda s: state.update(w=np.asarray(s["w"]).copy()))


def test_run_resilient_uninterrupted(tmp_path):
    step_fn, get_state, set_state = _numpy_trainer()
    report = run_resilient(step_fn, LocalCheckpointer(tmp_path), 20,
                           get_state=get_state, set_state=set_state,
                           checkpoint_every=5)
    assert report.final_step == 20
    assert report.restarts == 0 and not report.preempted
    assert sorted(report.losses) == list(range(20))
    losses = [report.losses[i] for i in range(20)]
    assert losses == sorted(losses, reverse=True)  # converging
    # final checkpoint written + valid
    ck = LocalCheckpointer(tmp_path)
    assert ck.latest_step() == 20
    ck.verify(20)


@pytest.mark.faults
def test_run_resilient_sigterm_preemption(tmp_path, fault_inject):
    """Injected SIGTERM mid-run: checkpoint at the preemption step,
    in-process restart, resume, identical final state."""
    fault_inject("sigterm_at_step:7")
    step_fn, get_state, set_state = _numpy_trainer()
    report = run_resilient(step_fn, LocalCheckpointer(tmp_path), 20,
                           get_state=get_state, set_state=set_state,
                           checkpoint_every=5, max_restarts=3)
    assert report.preempted
    assert report.restarts == 1
    assert report.final_step == 20
    assert report.resumed_from == [0, 7]  # preemption saved step 7
    # trajectory identical to an uninterrupted run
    base_step, base_get, base_set = _numpy_trainer()
    base = run_resilient(base_step, LocalCheckpointer(tmp_path / "b"),
                         20, get_state=base_get, set_state=base_set,
                         checkpoint_every=5)
    for s in range(20):
        assert report.losses[s] == pytest.approx(base.losses[s])
    np.testing.assert_allclose(get_state()["w"], base_get()["w"])


@pytest.mark.faults
def test_run_resilient_exit_on_preempt(tmp_path, fault_inject):
    fault_inject("sigterm_at_step:4")
    step_fn, get_state, set_state = _numpy_trainer()
    report = run_resilient(step_fn, LocalCheckpointer(tmp_path), 20,
                           get_state=get_state, set_state=set_state,
                           checkpoint_every=100, exit_on_preempt=True)
    assert report.preempted and report.final_step == 4
    # the grace-window checkpoint landed; a relaunch resumes from it
    step_fn2, get2, set2 = _numpy_trainer()
    report2 = run_resilient(step_fn2, LocalCheckpointer(tmp_path), 20,
                            get_state=get2, set_state=set2,
                            checkpoint_every=100)
    assert report2.resumed_from == [4]
    assert report2.final_step == 20


def test_run_resilient_step_failure_restart(tmp_path):
    step_fn, get_state, set_state = _numpy_trainer()
    boom = [True]

    def flaky_step(step):
        if step == 12 and boom[0]:
            boom[0] = False
            raise RuntimeError("device wedged")
        return step_fn(step)

    report = run_resilient(flaky_step, LocalCheckpointer(tmp_path), 20,
                           get_state=get_state, set_state=set_state,
                           checkpoint_every=5, max_restarts=2)
    assert report.final_step == 20
    assert report.restarts == 1
    assert report.resumed_from == [0, 10]  # replays from checkpoint 10


def test_run_resilient_max_restarts_exhausted(tmp_path):
    def always_fails(step):
        raise RuntimeError("permanently broken")

    with pytest.raises(RuntimeError, match="permanently broken"):
        run_resilient(always_fails, LocalCheckpointer(tmp_path), 20,
                      get_state=lambda: {}, set_state=lambda s: None,
                      checkpoint_every=5, max_restarts=2)


def test_run_resilient_corrupt_latest_falls_back(tmp_path):
    """Kill the latest checkpoint after a partial run: the next run must
    fall back to the previous checkpoint and still finish."""
    step_fn, get_state, set_state = _numpy_trainer()
    run_resilient(step_fn, LocalCheckpointer(tmp_path), 10,
                  get_state=get_state, set_state=set_state,
                  checkpoint_every=5)
    path = os.path.join(str(tmp_path), "ckpt_0000000010.mxtckpt")
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])
    step_fn2, get2, set2 = _numpy_trainer()
    report = run_resilient(step_fn2, LocalCheckpointer(tmp_path), 15,
                           get_state=get2, set_state=set2,
                           checkpoint_every=5)
    assert report.resumed_from == [5]   # 10 was corrupt, fell back
    assert report.final_step == 15
    # identical trajectory to a clean run over the same steps
    base_step, base_get, base_set = _numpy_trainer()
    base = run_resilient(base_step, LocalCheckpointer(tmp_path / "b"),
                         15, get_state=base_get, set_state=base_set,
                         checkpoint_every=5)
    np.testing.assert_allclose(get2()["w"], base_get()["w"])


# -- run_resilient: real gluon model (the acceptance e2e) ----------------------

def _gluon_trainer():
    """Tiny deterministic gluon MLP + plain SGD (stateless optimizer so
    params ARE the full state), fixed batches."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(11)
    np.random.seed(11)
    rng = np.random.RandomState(11)
    data = rng.normal(size=(64, 8)).astype(np.float32)
    labels = rng.randint(0, 3, size=64).astype(np.float32)
    batches = [(mx.nd.array(data[i:i + 16]),
                mx.nd.array(labels[i:i + 16]))
               for i in range(0, 64, 16)]

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    params = net.collect_params()

    def step_fn(step):
        x, y = batches[step % len(batches)]
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
        return float(loss.asnumpy().mean())

    def get_state():
        return {k: p.data().asnumpy() for k, p in params.items()}

    def set_state(state):
        for k, v in state.items():
            params[k].set_data(mx.nd.array(v))

    return step_fn, get_state, set_state


@pytest.mark.faults
def test_e2e_gluon_crash_resume_matches_uninterrupted(tmp_path,
                                                      fault_inject):
    """THE acceptance test: a gluon training run SIGTERMed mid-epoch by
    fault injection restarts in-process, resumes from the preemption
    checkpoint, and reproduces the uninterrupted run's loss trajectory
    and final parameters exactly."""
    num_steps = 24

    # uninterrupted reference trajectory
    step_fn, get_state, set_state = _gluon_trainer()
    base = run_resilient(step_fn, LocalCheckpointer(tmp_path / "base"),
                         num_steps, get_state=get_state,
                         set_state=set_state, checkpoint_every=8)
    base_params = get_state()
    assert base.final_step == num_steps and base.restarts == 0

    # crashed-and-resumed run
    fault_inject("sigterm_at_step:13")
    step_fn2, get2, set2 = _gluon_trainer()
    report = run_resilient(step_fn2, LocalCheckpointer(tmp_path / "c"),
                           num_steps, get_state=get2, set_state=set2,
                           checkpoint_every=8, max_restarts=3)
    assert report.preempted and report.restarts == 1
    assert report.final_step == num_steps
    assert report.resumed_from == [0, 13]

    # same steps, same losses, same final parameters
    assert sorted(report.losses) == sorted(base.losses)
    for s in sorted(base.losses):
        assert report.losses[s] == pytest.approx(base.losses[s],
                                                 rel=1e-5), f"step {s}"
    # param names carry a per-net auto prefix (hybridsequential0_ vs
    # hybridsequential1_); pair them positionally in sorted order
    crashed_params = get2()
    for bk, ck in zip(sorted(base_params), sorted(crashed_params)):
        np.testing.assert_allclose(crashed_params[ck], base_params[bk],
                                   rtol=1e-5, atol=1e-6)


# -- PreemptionHandler ---------------------------------------------------------

def test_preemption_handler_chains_previous(tmp_path):
    from mxnet_tpu.checkpoint import PreemptionHandler

    outer = []
    prev = signal.signal(signal.SIGTERM,
                         lambda s, f: outer.append("outer"))
    try:
        ck = LocalCheckpointer(tmp_path)
        with PreemptionHandler(ck, lambda: {"x": 1}, lambda: 3) as h:
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.05)
            assert h.preempted.is_set()
            assert outer == ["outer"]   # the previous handler STILL ran
            assert h.maybe_checkpoint()
        assert ck.restore(3) == {"x": 1}
        # context exit restored the outer handler
        assert signal.getsignal(signal.SIGTERM) is not h._on_sigterm
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_preemption_handler_restore_idempotent(tmp_path):
    from mxnet_tpu.checkpoint import PreemptionHandler

    prev = signal.getsignal(signal.SIGTERM)
    h = PreemptionHandler(LocalCheckpointer(tmp_path),
                          lambda: {}, lambda: 0)
    h.restore_handler()
    h.restore_handler()
    assert signal.getsignal(signal.SIGTERM) == prev


def test_preemption_handler_no_preempt_no_save(tmp_path):
    from mxnet_tpu.checkpoint import PreemptionHandler

    ck = LocalCheckpointer(tmp_path)
    with PreemptionHandler(ck, lambda: {}, lambda: 0) as h:
        assert not h.maybe_checkpoint()
    assert ck.latest_step() is None

# -- decorrelated jitter (PR 8) ------------------------------------------------

def test_retry_call_decorrelated_jitter_bounds():
    """jitter=True (default): every sleep lands in [backoff,
    max_backoff] and depends on the PREVIOUS sleep (uniform up to 3x
    it), so lockstep retry herds spread out."""
    sleeps = []

    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, retries=20, backoff=0.001, max_backoff=0.004,
                   on_retry=lambda a, e, s: sleeps.append(s))
    assert len(sleeps) == 20
    for s in sleeps:
        assert 0.001 <= s <= 0.004
    # with a cap 4x the floor and 20 draws, identical values would mean
    # the jitter is not actually sampling
    assert len(set(sleeps)) > 1


def test_retry_call_legacy_proportional_jitter():
    sleeps = []

    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, retries=3, backoff=0.001, jitter=0.5,
                   on_retry=lambda a, e, s: sleeps.append(s))
    # exponential base with at most +50% proportional noise
    for i, s in enumerate(sleeps):
        base = 0.001 * (2 ** i)
        assert base <= s <= base * 1.5 + 1e-9


# -- verify-after-write rewrite path (PR 8) ------------------------------------

@pytest.mark.faults
def test_save_verified_rewrites_once_on_bitrot(fault_inject, tmp_path):
    """corrupt_ckpt_write:1 bit-rots the first committed file AFTER the
    rename; _save_verified's readback must catch it and the single
    rewrite must produce a restorable checkpoint."""
    fault_inject("corrupt_ckpt_write:1")
    ck = LocalCheckpointer(tmp_path)
    resilience._save_verified(ck, 5, {"w": [1.0, 2.0]})
    assert ck.restore(5) == {"w": [1.0, 2.0]}


@pytest.mark.faults
def test_save_verified_raises_on_persistent_bitrot(fault_inject,
                                                   tmp_path):
    """When the rewrite is corrupted too (corrupt_ckpt_write:2), the
    failure must surface as CheckpointCorrupt — never a silent bad
    checkpoint."""
    fault_inject("corrupt_ckpt_write:2")
    ck = LocalCheckpointer(tmp_path)
    with pytest.raises(CheckpointCorrupt):
        resilience._save_verified(ck, 5, {"w": [1.0, 2.0]})


# -- recovery decisions as telemetry events (PR 8) -----------------------------

def _read_events(path):
    import json

    with open(path) as f:
        return [json.loads(ln) for ln in f.read().splitlines() if ln]


def test_resume_latest_emits_ckpt_fallback_event(tmp_path, monkeypatch):
    from mxnet_tpu import telemetry

    ck = LocalCheckpointer(tmp_path / "ck")
    ck.save(3, {"x": 1})
    ck.save(6, {"x": 2})
    with open(ck._path(6), "r+b") as f:    # bit-rot the newest
        f.seek(-1, os.SEEK_END)
        last = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last[0] ^ 0xFF]))
    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    got = {}
    try:
        assert resilience.resume_latest(ck, got.update) == 3
    finally:
        telemetry.reset()                   # close the sink
    assert got == {"x": 1}
    events = [r for r in _read_events(path) if r.get("type") == "event"]
    assert [e["event"] for e in events] == ["ckpt_fallback"]
    assert events[0]["step"] == 6
    assert events[0]["reason"] == "CheckpointCorrupt"


def test_flush_inflight_emits_dropped_event(tmp_path, monkeypatch):
    from mxnet_tpu import telemetry

    class FailingAsync:
        pending_step = 11

        def wait(self):
            raise OSError("backing store went away")

    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    try:
        resilience.flush_inflight(FailingAsync())   # must not raise
    finally:
        telemetry.reset()
    events = [r for r in _read_events(path) if r.get("type") == "event"]
    assert [e["event"] for e in events] == ["inflight_save_dropped"]
    assert events[0]["step"] == 11
    assert events[0]["reason"] == "OSError"


# -- retry_call total-elapsed budget (PR 20) ------------------------------------

def test_retry_call_max_elapsed_caps_total_time():
    """Unlike ``deadline`` (which only vetoes the next SLEEP), a slow
    fn() burning the whole budget inside one attempt still stops at the
    next failure — the partition-era property: KV retries hand over to
    the fencing checks instead of retrying unboundedly."""
    calls = []

    def slow_always():
        calls.append(1)
        time.sleep(0.03)
        raise OSError("partitioned")

    t0 = time.monotonic()
    with pytest.raises(mx.MXNetError, match="retry budget"):
        retry_call(slow_always, retries=1000, backoff=0.001, jitter=0.0,
                   max_elapsed=0.05)
    assert time.monotonic() - t0 < 2.0
    assert 1 < len(calls) < 10


def test_retry_call_max_elapsed_off_by_default():
    calls = []

    def flaky():
        calls.append(1)
        time.sleep(0.02)
        if len(calls) < 4:
            raise OSError("transient")
        return "ok"

    # four slow attempts, no budget: must still succeed
    assert retry_call(flaky, retries=10, backoff=0.001) == "ok"


# -- partition_split / pause_rank fault sites (PR 20) ---------------------------

@pytest.mark.faults
def test_fault_spec_partition_split(fault_inject, monkeypatch):
    fault_inject("partition_split:1,partition_split:2")
    monkeypatch.delenv("MXTPU_PARTITION_SECS", raising=False)
    assert resilience.partition_blocked(1)
    assert resilience.partition_blocked(2)
    assert not resilience.partition_blocked(0)
    # persistent (no heal configured): still blocked on re-check
    assert resilience.partition_blocked(1)


@pytest.mark.faults
def test_partition_split_heals_after_deadline(fault_inject, monkeypatch):
    fault_inject("partition_split:1")
    monkeypatch.setenv("MXTPU_PARTITION_SECS", "0.15")
    assert resilience.partition_blocked(1)   # starts the heal timer
    deadline = time.monotonic() + 5.0
    while resilience.partition_blocked(1):
        assert time.monotonic() < deadline, "partition never healed"
        time.sleep(0.02)
    assert not resilience.partition_blocked(1)   # healed stays healed


@pytest.mark.faults
def test_fault_spec_pause_rank_parses_one_shot(fault_inject):
    fault_inject("pause_rank:3")
    plan = resilience._plan()
    assert 3 in plan.list_args["pause_rank"]
    # one-shot per listed rank, like the other SDC sites
    assert resilience.consume_rank_fault("pause_rank", 3)
    assert not resilience.consume_rank_fault("pause_rank", 3)
    assert not resilience.consume_rank_fault("pause_rank", 0)


# -- wall-clock-jump immunity (PR 20: monotonic freshness arithmetic) -----------

def test_wall_clock_jump_does_not_kill_detector(tmp_path, monkeypatch):
    """An NTP step (hours, either direction) must not fake a partition:
    heartbeat freshness and phi inter-arrival math run on
    time.monotonic(), never time.time()."""
    from mxnet_tpu import distributed

    kv = distributed.FileKV(str(tmp_path))
    hb = resilience.HeartbeatPublisher(kv, 1, interval=0.05).start()
    det = resilience.FailureDetector(kv, 0, [0, 1], timeout=5.0,
                                     check_interval=0.0)
    try:
        deadline = time.monotonic() + 5.0
        while not det.peer_steps() and time.monotonic() < deadline:
            det.poll(force=True)
            time.sleep(0.02)
        assert det.poll(force=True) == set()
        real_time = time.time
        monkeypatch.setattr(time, "time",
                            lambda: real_time() + 86400.0)
        for _ in range(10):     # a day forward: nobody dies
            assert det.poll(force=True) == set()
            time.sleep(0.02)
        monkeypatch.setattr(time, "time",
                            lambda: real_time() - 86400.0)
        for _ in range(10):     # two days backward: nobody dies
            assert det.poll(force=True) == set()
            time.sleep(0.02)
    finally:
        hb.stop()


def test_wall_clock_jump_does_not_expire_leases(monkeypatch):
    """GangKVServer lease deadlines are monotonic: a wall-clock jump
    while a client is connected must not mass-expire its ephemeral
    keys (heartbeats) and fake a gang-wide death."""
    from mxnet_tpu import distributed

    server = distributed.GangKVServer(lease_ttl=30.0).start()
    kv = distributed.TcpKV(server.addr, rank=0, lease_ttl=30.0)
    try:
        kv.put("hb/0", b"alive")        # ephemeral -> leased
        real_time = time.time
        monkeypatch.setattr(time, "time",
                            lambda: real_time() + 86400.0)
        time.sleep(0.3)                 # a few sweeper passes
        assert kv.get("hb/0") == b"alive"
    finally:
        monkeypatch.undo()
        kv.close()
        server.stop()
