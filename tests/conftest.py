"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY.md §4): tests run on a virtual
8-device CPU mesh so multi-chip sharding paths execute without TPU hardware —
the analog of the reference's local dmlc tracker for fake multi-node
(tests/nightly run via `tools/launch.py --launcher local`).
"""

import os

# Must be set before jax is imported anywhere.  Append, don't setdefault:
# the container exports XLA_FLAGS="" which would defeat setdefault and
# leave the mesh at 1 device.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# force, not setdefault: the container env pins JAX_PLATFORMS=axon (the
# one-chip TPU tunnel) — tests always run on the virtual CPU mesh.  NOTE:
# the axon tunnel registers in sitecustomize at interpreter start; run
# pytest as `env -u PALLAS_AXON_POOL_IPS python -m pytest ...` to skip the
# tunnel claim entirely (a stale claim otherwise hangs jax init).
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seeded():
    """Reference: @with_seed() in tests/python/unittest/common.py —
    deterministic seeds per test, logged for replay on failure."""
    import mxnet_tpu as mx

    np.random.seed(0)
    mx.random.seed(0)
    yield
