"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY.md §4): tests run on a virtual
8-device CPU mesh so multi-chip sharding paths execute without TPU hardware —
the analog of the reference's local dmlc tracker for fake multi-node
(tests/nightly run via `tools/launch.py --launcher local`).
"""

import os

# Must be set before jax is imported anywhere.  Append, don't setdefault:
# the container exports XLA_FLAGS="" which would defeat setdefault and
# leave the mesh at 1 device.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# force, not setdefault: the container env pins JAX_PLATFORMS=axon (the
# one-chip TPU tunnel) — tests always run on the virtual CPU mesh.  NOTE:
# the axon tunnel registers in sitecustomize at interpreter start; run
# pytest as `env -u PALLAS_AXON_POOL_IPS python -m pytest ...` to skip the
# tunnel claim entirely (a stale claim otherwise hangs jax init).
os.environ["JAX_PLATFORMS"] = "cpu"

import shutil
import subprocess

import numpy as np
import pytest

# Build the native libs once per session if the toolchain exists — a
# fresh checkout carries no .so, and the native paths (recordio codec,
# jpeg decode, C API) should be exercised, not silently skipped.
_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")
if shutil.which("make") and shutil.which("g++"):
    _missing = [n for n in ("libmxtpu_io.so", "libmxtpu_img.so",
                            "libmxtpu.so")
                if not os.path.exists(os.path.join(_SRC, n))]
    if _missing:
        # -k: a failing target (e.g. libmxtpu_img.so on a host without
        # libjpeg headers) must not stop the OTHER native libs building
        subprocess.run(["make", "-k", "-C", _SRC], capture_output=True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 "
                   "(`-m 'not slow'`)")
    config.addinivalue_line(
        "markers", "faults: CPU-hermetic fault-injection tests driven "
                   "by MXTPU_FAULT_INJECT (run in tier-1 by default)")


@pytest.fixture
def fault_inject(monkeypatch):
    """Arm MXTPU_FAULT_INJECT for one test and reset injection counters
    on both arm and teardown (counters are cached per env value)."""
    from mxnet_tpu import resilience

    def arm(spec):
        monkeypatch.setenv("MXTPU_FAULT_INJECT", spec)
        resilience.reset_faults()

    yield arm
    monkeypatch.delenv("MXTPU_FAULT_INJECT", raising=False)
    resilience.reset_faults()


@pytest.fixture
def mesh8():
    """Factory for multi-device meshes on the virtual 8-device CPU
    platform (the XLA_FLAGS forcing at the top of this file): tier-1
    TP/FSDP sharding tests run on every CI pass, not only on real
    hardware.  Skips when the platform somehow exposes < 8 devices
    (e.g. XLA_FLAGS was pinned by the environment before pytest
    started).  Tears down the process default mesh so a test's
    `shard_model` can't leak placements into the next test."""
    import jax

    from mxnet_tpu import parallel

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (forced-host) devices")

    def make(**axes):
        return parallel.make_mesh(**axes)

    yield make
    parallel.set_default_mesh(None)


@pytest.fixture
def mesh222():
    """The canonical 3-axis tp=2×pp=2×dp=2 mesh over the forced-host
    8-device CPU platform — the PR 17 pipeline-parallel layout, built
    through `make_mesh`'s dict form.  Same skip/teardown discipline as
    `mesh8`."""
    import jax

    from mxnet_tpu import parallel

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (forced-host) devices")
    yield parallel.make_mesh(axes={"tp": 2, "pp": 2, "dp": 2})
    parallel.set_default_mesh(None)


@pytest.fixture(autouse=True)
def _seeded():
    """Reference: @with_seed() in tests/python/unittest/common.py —
    deterministic seeds per test, logged for replay on failure."""
    import mxnet_tpu as mx

    np.random.seed(0)
    mx.random.seed(0)
    yield
