"""ONNX export/import (reference: tests/python-pytest/onnx/ — the
mx2onnx/onnx2mx conversion suite, self-contained here because the wire
format is handled via the checked-in proto subset)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.contrib import onnx as onnx_mxnet


def _convnet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(5))
    net.initialize()
    net.hybridize()
    return net


def test_onnx_export_import_roundtrip_convnet(tmp_path):
    rs = np.random.RandomState(0)
    net = _convnet()
    x = nd.array(rs.randn(2, 3, 16, 16).astype("float32"))
    net(x)
    with mx.autograd.predict_mode():
        ref = net(x)
    sym = mx.sym.trace_block(net)
    params = {n: p.data() for n, p in net.collect_params().items()}
    path = str(tmp_path / "model.onnx")
    onnx_mxnet.export_model(sym, params, [(2, 3, 16, 16)],
                            onnx_file_path=path)
    sym2, arg2, aux2 = onnx_mxnet.import_model(path)
    feed = {"data": x}
    feed.update(arg2)
    feed.update(aux2)
    out = sym2.eval(**feed)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), atol=1e-6)


def test_onnx_import_to_gluon(tmp_path):
    rs = np.random.RandomState(1)
    net = _convnet()
    x = nd.array(rs.randn(2, 3, 16, 16).astype("float32"))
    net(x)
    with mx.autograd.predict_mode():
        ref = net(x)
    sym = mx.sym.trace_block(net)
    params = {n: p.data() for n, p in net.collect_params().items()}
    path = str(tmp_path / "model.onnx")
    onnx_mxnet.export_model(sym, params, [(2, 3, 16, 16)],
                            onnx_file_path=path)
    sb = onnx_mxnet.import_to_gluon(path)
    np.testing.assert_allclose(sb(x).asnumpy(), ref.asnumpy(), atol=1e-6)


def test_onnx_roundtrip_resnet18(tmp_path):
    from mxnet_tpu.gluon.model_zoo import vision

    rs = np.random.RandomState(2)
    net = vision.resnet18_v1(classes=10)
    net.initialize()
    net.hybridize()
    x = nd.array(rs.randn(2, 3, 32, 32).astype("float32"))
    net(x)
    with mx.autograd.predict_mode():
        ref = net(x)
    sym = mx.sym.trace_block(net)
    params = {n: p.data() for n, p in net.collect_params().items()}
    path = str(tmp_path / "r18.onnx")
    onnx_mxnet.export_model(sym, params, [(2, 3, 32, 32)],
                            onnx_file_path=path)
    sb = onnx_mxnet.import_to_gluon(path)
    np.testing.assert_allclose(sb(x).asnumpy(), ref.asnumpy(), atol=1e-5)


def test_onnx_proto_is_wire_compatible():
    """The checked-in proto must keep ONNX's field numbers: a model
    serialized here parses under the well-known field layout (spot-check
    via manual varint decode of the graph field tag)."""
    from mxnet_tpu.contrib.onnx import onnx_minimal_pb2 as pb

    m = pb.ModelProto()
    m.ir_version = 4
    m.graph.name = "g"
    data = m.SerializeToString()
    # field 1 (ir_version, varint): tag 0x08; field 7 (graph, message):
    # tag 0x3a — both must appear
    assert data[0] == 0x08
    assert 0x3A in data
