"""End-to-end convergence tests.

Reference parity: tests/python/train/test_mlp.py / test_conv.py — train a
tiny model for a few epochs on a small problem and assert an accuracy
threshold.  This is the go/no-go milestone of SURVEY.md §7.3.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def _synthetic_classification(n=512, d=16, classes=4, seed=3):
    """Linearly separable-ish blobs: learnable to >90% by a small MLP."""
    rng = np.random.RandomState(seed)
    centers = rng.uniform(-3, 3, size=(classes, d)).astype(np.float32)
    labels = rng.randint(0, classes, size=n)
    data = centers[labels] + rng.normal(0, 0.6, size=(n, d)) \
        .astype(np.float32)
    return data.astype(np.float32), labels.astype(np.float32)


def test_mlp_trains_to_accuracy():
    data, labels = _synthetic_classification()
    train_iter = mx.io.NDArrayIter(data, labels, batch_size=64,
                                   shuffle=True)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"),
                nn.Dense(32, activation="relu"),
                nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(8):
        train_iter.reset()
        metric.reset()
        for batch in train_iter:
            x = batch.data[0]
            y = batch.label[0]
            with mx.autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
    name, acc = metric.get()
    assert acc > 0.9, f"MLP failed to learn: {name}={acc}"


def test_cnn_trains_loss_decreases():
    rng = np.random.RandomState(0)
    n = 128
    labels = rng.randint(0, 2, size=n)
    # class 0: vertical stripe; class 1: horizontal stripe (+noise)
    data = rng.normal(0, 0.3, size=(n, 1, 8, 8)).astype(np.float32)
    data[labels == 0, :, :, 3:5] += 1.0
    data[labels == 1, :, 3:5, :] += 1.0

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
                nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(2))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    losses = []
    it = mx.io.NDArrayIter(data, labels.astype(np.float32), batch_size=32)
    for epoch in range(8):
        it.reset()
        epoch_loss = 0.0
        nb = 0
        for batch in it:
            with mx.autograd.record():
                loss = loss_fn(net(batch.data[0]), batch.label[0])
            loss.backward()
            trainer.step(32)
            epoch_loss += float(loss.mean().asscalar())
            nb += 1
        losses.append(epoch_loss / nb)
    assert losses[-1] < losses[0] * 0.7, f"loss not decreasing: {losses}"


def test_speedometer_reports():
    import logging

    from mxnet_tpu.callback import BatchEndParam, Speedometer

    speedometer = Speedometer(batch_size=32, frequent=2)
    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array([1])], [mx.nd.array([[0.1, 0.9]])])
    for nbatch in range(1, 5):
        speedometer(BatchEndParam(epoch=0, nbatch=nbatch,
                                  eval_metric=metric))
    assert speedometer.last_speed is not None and \
        speedometer.last_speed > 0


def test_real_data_convergence_digits():
    """REAL-data convergence artifact (VERDICT r3 task #7): the UCI
    handwritten-digits dataset (1797 genuine 8x8 scans, shipped inside
    scikit-learn — an offline-cached real dataset, not synthetic blobs)
    trained to a stated held-out accuracy.  Published baselines put
    simple classifiers at ~0.95-0.97 on this split; the CNN must reach
    0.95.  The ImageNet-scale recipe for chip runs is
    examples/train_imagenet_sharded.py (docs/perf.md)."""
    pytest.importorskip("sklearn")
    from sklearn.datasets import load_digits

    digits = load_digits()
    x = (digits.images.astype(np.float32) / 16.0)[:, None]  # (N,1,8,8)
    y = digits.target.astype(np.float32)
    rng = np.random.RandomState(0)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    n_train = 1500
    xtr, ytr = x[:n_train], y[:n_train]
    xte, yte = x[n_train:], y[n_train:]

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(16, 3, padding=1, activation="relu"),
                nn.MaxPool2D(2),
                nn.Conv2D(32, 3, padding=1, activation="relu"),
                nn.Flatten(),
                nn.Dense(64, activation="relu"),
                nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    train_iter = mx.io.NDArrayIter(xtr, ytr, batch_size=100,
                                   shuffle=True)
    for epoch in range(12):
        train_iter.reset()
        for batch in train_iter:
            xb, yb = batch.data[0], batch.label[0]
            with mx.autograd.record():
                out = net(xb)
                loss = loss_fn(out, yb)
            loss.backward()
            trainer.step(xb.shape[0])

    pred = net(mx.nd.array(xte)).asnumpy().argmax(axis=1)
    acc = float((pred == yte).mean())
    assert acc >= 0.95, f"held-out accuracy {acc:.3f} < 0.95"
