"""Engine policy surface (reference: tests/python/unittest/
test_engine.py + test_exc_handling.py — NaiveEngine mode, WaitForAll,
exception propagation)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_wait_all_and_bulk():
    a = mx.nd.ones((8, 8))
    b = a * 2 + 1
    mx.engine.wait_all()          # Engine::WaitForAll analog: no hang
    np.testing.assert_allclose(b.asnumpy(), 3.0)
    with mx.engine.bulk(16):      # bulking context is a no-op policy
        c = (a + b).sum()
    assert float(c.asnumpy()) == 8 * 8 * 4.0
    prev = mx.engine.set_bulk_size(5)
    assert mx.engine.set_bulk_size(prev) == 5


def test_exception_propagation_raises_mxnet_error():
    """Invalid op invocations surface as exceptions on the issuing call
    or at readback — never a silent wrong answer (reference
    test_exc_handling: async errors re-thrown at WaitToRead)."""
    a = mx.nd.ones((3, 4))
    b = mx.nd.ones((5, 6))
    with pytest.raises(Exception):
        mx.nd.dot(a, b).asnumpy()  # inner dims mismatch
    with pytest.raises(Exception):
        mx.nd.reshape(a, shape=(7, 7)).asnumpy()  # size mismatch


def test_naive_engine_env_mode():
    """MXNET_ENGINE_TYPE=NaiveEngine puts the engine in synchronous
    mode (reference naive_engine.cc); verified in a subprocess since
    the flag is read at import."""
    code = (
        "import mxnet_tpu as mx\n"
        "assert mx.engine.is_naive()\n"
        "x = mx.nd.ones((4,)) * 3\n"
        "mx.engine.maybe_sync(x)\n"
        "print('naive ok', float(x.sum().asnumpy()))\n")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_",
                                "LIBTPU"))}
    env.update({"MXNET_ENGINE_TYPE": "NaiveEngine",
                "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-400:]
    assert "naive ok 12.0" in r.stdout
    # and the default (this process) is NOT naive
    assert not mx.engine.is_naive()
