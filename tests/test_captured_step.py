"""Whole-step graph capture (gluon/captured.py + Trainer.train_step).

The captured path must be a pure performance transform: ONE donated jit
dispatch + one readback per step, bitwise-identical to the eager
multi-dispatch oracle (forward / backward / health / per-group update
programs) — including skipped (non-finite) steps, clipped steps, amp
loss-scale bookkeeping, gradient accumulation, and BatchNorm aux
threading.  Per-step scalars are traced inputs, so LR schedules and
loss-scale changes must never retrace.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, numerics
from mxnet_tpu.gluon import captured, nn
from mxnet_tpu.optimizer import grouped

STEPS = 10


def _make_net(with_bn=False, seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        if with_bn:
            net.add(nn.BatchNorm(axis=1), nn.Dropout(0.3))
        net.add(nn.Dense(3))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    return net


def _batches(steps=STEPS, n=8, d=6, nan_at=None, seed=42):
    rng = np.random.RandomState(seed)
    xs = [rng.normal(size=(n, d)).astype(np.float32) for _ in range(steps)]
    ys = [rng.randint(0, 3, size=(n,)).astype(np.float32)
          for _ in range(steps)]
    if nan_at is not None:
        xs[nan_at][0, 0] = np.nan
    return xs, ys


def _state_leaves(state):
    if state is None:
        return []
    if isinstance(state, (list, tuple)):
        return [a for s in state for a in _state_leaves(s)]
    return [state.asnumpy()] if hasattr(state, "asnumpy") else []


def _run(monkeypatch, captured_on, opt="sgd", opt_params=None, k=1,
         clip=None, nan_at=None, loss_scale=None, steps=STEPS,
         with_bn=False):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1" if captured_on else "0")
    net = _make_net(with_bn=with_bn)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(
        net.collect_params(), opt,
        dict(opt_params or {"learning_rate": 0.1}),
        clip_global_norm=clip)
    if loss_scale is not None:
        from mxnet_tpu import amp
        trainer._amp_loss_scaler = amp.DynamicLossScaler(
            init_scale=loss_scale)
    xs, ys = _batches(steps=steps, nan_at=nan_at)
    losses = []
    for s in range(steps):
        l = trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                               mx.nd.array(ys[s]), grad_accum=k)
        losses.append(l.asnumpy())
    weights = [p.data().asnumpy() for p in trainer._params]
    states = {i: _state_leaves(st)
              for i, st in trainer._updaters[0].states.items()}
    return losses, weights, states, trainer


def _assert_same(a, b):
    le, we, se, te = a
    lc, wc, sc, tc = b
    for s, (x, y) in enumerate(zip(le, lc)):
        np.testing.assert_array_equal(x, y, err_msg=f"loss step {s}")
    for i, (x, y) in enumerate(zip(we, wc)):
        np.testing.assert_array_equal(x, y, err_msg=f"weight {i}")
    assert set(se) == set(sc)
    for i in se:
        for x, y in zip(se[i], sc[i]):
            np.testing.assert_array_equal(x, y, err_msg=f"state {i}")


# -- bitwise parity vs the eager oracle ----------------------------------------

@pytest.mark.parametrize("opt,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("lamb", {"learning_rate": 0.01}),
])
@pytest.mark.parametrize("guard", ["1", "0"])
def test_bitwise_parity(monkeypatch, opt, opt_params, guard):
    """10 steps captured == 10 steps eager, to the last bit: losses,
    weights, and optimizer states.  Guard-on runs include a NaN batch
    (a skipped step on BOTH paths); guard-off runs include a tight
    global-norm clip (every step clipped)."""
    monkeypatch.setenv("MXTPU_GRAD_GUARD", guard)
    guard_on = guard == "1"
    kw = dict(opt=opt, opt_params=opt_params,
              nan_at=4 if guard_on else None,
              clip=None if guard_on else 0.5)
    eager = _run(monkeypatch, False, **kw)
    cap = _run(monkeypatch, True, **kw)
    _assert_same(eager, cap)
    if guard_on:
        assert len(eager[3].skipped_steps) == 1
        assert len(cap[3].skipped_steps) == 1
        assert eager[3].skipped_steps[0].step \
            == cap[3].skipped_steps[0].step


def test_bitwise_parity_grad_accum_bn_dropout(monkeypatch):
    """grad_accum=2 with BatchNorm (aux threading through the scan
    carry) and Dropout (per-microbatch PRNG keys): still bitwise."""
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    kw = dict(opt="adam", opt_params={"learning_rate": 0.01}, k=2,
              with_bn=True, steps=6)
    eager = _run(monkeypatch, False, **kw)
    cap = _run(monkeypatch, True, **kw)
    _assert_same(eager, cap)


def test_bitwise_parity_amp_loss_scale(monkeypatch):
    """Dynamic loss scaling: the scale is a traced input (seed =
    full(scale)), unscaling rides rescale_grad, and the skipped NaN
    step halves the scale identically on both paths."""
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    kw = dict(opt="sgd", opt_params={"learning_rate": 0.1},
              nan_at=3, loss_scale=1024.0)
    eager = _run(monkeypatch, False, **kw)
    cap = _run(monkeypatch, True, **kw)
    _assert_same(eager, cap)
    assert eager[3]._amp_loss_scaler.loss_scale \
        == cap[3]._amp_loss_scaler.loss_scale
    assert cap[3]._amp_loss_scaler.loss_scale < 1024.0  # the halving


# -- dispatch / readback / retrace accounting ----------------------------------

@pytest.mark.parametrize("k", [1, 4])
def test_one_dispatch_one_readback_per_step(monkeypatch, k):
    """The whole point: a healthy captured step is ONE compiled dispatch
    (no separate forward/backward/health/per-group programs) and ONE
    host readback (the guard decision, after the update)."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    net = _make_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    xs, ys = _batches(steps=5)
    # warm up (trace + cache miss), then measure steady state
    trainer.train_step(net, loss_fn, mx.nd.array(xs[0]),
                       mx.nd.array(ys[0]), grad_accum=k)
    captured.reset_counters()
    grouped.reset_dispatch_count()
    numerics.reset_readback_count()
    for s in range(1, 5):
        trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                           mx.nd.array(ys[s]), grad_accum=k)
    assert captured.dispatch_count() == 4
    assert grouped.dispatch_count() == 0
    assert numerics.readback_count() == 4
    assert captured.trace_count() == 0  # no retrace after warmup
    stats = captured.cache_stats()
    assert stats["hits"] == 4 and stats["misses"] == 0


@pytest.mark.parametrize("k", [1, 4])
def test_no_retrace_on_schedule_ticks(monkeypatch, k):
    """LR schedule ticks, loss-scale changes, and optimizer time steps
    are traced scalars: ONE trace per configuration, ever."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    from mxnet_tpu import amp
    net = _make_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    trainer._amp_loss_scaler = amp.DynamicLossScaler(init_scale=256.0)
    xs, ys = _batches(steps=8)
    captured.reset_counters()
    for s in range(8):
        trainer.set_learning_rate(0.01 * (0.9 ** s))   # schedule tick
        if s == 3:
            trainer._amp_loss_scaler.loss_scale *= 2   # scale change
        trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                           mx.nd.array(ys[s]), grad_accum=k)
    assert captured.trace_count() == 1
    assert captured.cache_stats() == {"hits": 7, "misses": 1}
    assert captured.dispatch_count() == 8


def test_nan_grad_fault_routes_to_eager_oracle(monkeypatch, fault_inject):
    """An armed nan_grad injection has no gradient buffer to poison
    inside the captured program — that step must run (and skip) on the
    eager path, then capture resumes."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    net = _make_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    xs, ys = _batches(steps=4)
    captured.reset_counters()
    for s in range(4):
        if s == 2:
            fault_inject("nan_grad:1")
        trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                           mx.nd.array(ys[s]))
    assert len(trainer.skipped_steps) == 1
    assert captured.dispatch_count() == 3  # step 2 went eager


# -- capture-cache invalidation ------------------------------------------------

def test_capture_invalidates_on_lora_attach_freeze_merge(monkeypatch):
    """apply_lora / freeze_for_lora / merge() all clear the CachedOp —
    the captured-step cache keys on the same structure version (plus
    the grad_req layout) and must rebuild, not replay a stale program."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    from mxnet_tpu.gluon.contrib import lora
    net = _make_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    xs, ys = _batches(steps=1, nan_at=None)
    x, y = mx.nd.array(xs[0]), mx.nd.array(ys[0])

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    captured.reset_counters()
    trainer.train_step(net, loss_fn, x, y)
    trainer.train_step(net, loss_fn, x, y)
    assert captured.cache_stats() == {"hits": 1, "misses": 1}

    v0 = net._cache_version
    wrapped = lora.apply_lora(net, rank=2, patterns=(".*",))
    assert net._cache_version > v0  # attach invalidates
    trainer2 = gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 0.1})
    captured.reset_counters()
    trainer2.train_step(net, loss_fn, x, y)
    assert captured.cache_stats()["misses"] == 1  # rebuilt, not replayed
    before = {name: p.data().asnumpy()
              for name, p in net.collect_params().items()
              if "lora" not in name}
    trainer2.train_step(net, loss_fn, x, y)
    for name, p in net.collect_params().items():
        if "lora" not in name:  # frozen base stayed frozen
            np.testing.assert_array_equal(before[name], p.data().asnumpy())

    v1 = net._cache_version
    lora.freeze_for_lora(net)  # re-freeze walk bumps the version too
    assert net._cache_version > v1

    v2 = net._cache_version
    wrapped[0].merge()  # detach event
    assert wrapped[0]._cache_version > 0
    assert net._cache_version == v2  # merge is local to the layer
    captured.reset_counters()
    trainer2.train_step(net, loss_fn, x, y)
    assert captured.cache_stats()["misses"] == 1


# -- fallback behavior ---------------------------------------------------------

def test_eager_fallback_unhybridized_and_env_off(monkeypatch):
    """Non-capturable configs and MXTPU_CAPTURED_STEP=0 run the eager
    oracle — and still train."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    net = _make_net()
    net._active = False  # un-hybridize
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    xs, ys = _batches(steps=2)
    captured.reset_counters()
    net(mx.nd.array(xs[0]))  # materialize deferred shapes
    w0 = trainer._params[0].data().asnumpy().copy()
    l = trainer.train_step(net, loss_fn, mx.nd.array(xs[0]),
                           mx.nd.array(ys[0]))
    assert np.isfinite(l.asnumpy()).all()
    assert captured.dispatch_count() == 0
    assert not (trainer._params[0].data().asnumpy() == w0).all()

    net.hybridize()
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "0")
    trainer.train_step(net, loss_fn, mx.nd.array(xs[1]),
                       mx.nd.array(ys[1]))
    assert captured.dispatch_count() == 0


def test_grad_accum_batch_not_divisible_falls_back(monkeypatch):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    net = _make_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.array(np.random.RandomState(0)
                    .normal(size=(6, 6)).astype(np.float32))
    y = mx.nd.array(np.zeros((6,), np.float32))
    # 6 % 4 != 0 → capture refuses; the eager path raises explicitly
    trainer._init_kvstore()
    assert "divisible" in captured.ineligible_reason(
        trainer, net, loss_fn, x, 4)
    with pytest.raises(ValueError, match="divisible"):
        trainer.train_step(net, loss_fn, x, y, grad_accum=4)
