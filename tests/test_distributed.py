"""Multi-process distributed tests.

Reference parity: tests/nightly/dist_sync_kvstore.py launched through
`tools/launch.py -n 2 --launcher local` (SURVEY.md §4 — multi-node
without a cluster).  Spawns real processes that rendezvous via
jax.distributed, so the cross-process all-reduce path
(kvstore._cross_process_allreduce) is exercised for real, not mocked.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env():
    """Child processes must run on the CPU backend, never the TPU tunnel."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # one device per process
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.parametrize("n_workers", [2])
def test_dist_sync_kvstore_multiprocess(n_workers):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
         "-n", str(n_workers), "--launcher", "local",
         "--port", str(_free_port()), "--",
         sys.executable, os.path.join(_REPO, "tests",
                                      "dist_sync_kvstore.py")],
        env=_clean_env(), capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    for rank in range(n_workers):
        assert f"worker {rank}/{n_workers}: dist_sync_kvstore OK" \
            in proc.stdout
