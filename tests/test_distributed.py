"""Multi-process distributed tests.

Reference parity: tests/nightly/dist_sync_kvstore.py launched through
`tools/launch.py -n 2 --launcher local` (SURVEY.md §4 — multi-node
without a cluster).  Spawns real processes that rendezvous via
jax.distributed, so the cross-process all-reduce path
(kvstore._cross_process_allreduce) is exercised for real, not mocked.
"""

import os
import signal
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env():
    """Child processes must run on the CPU backend, never the TPU tunnel."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # one device per process
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.parametrize("n_workers", [2])
def test_dist_sync_kvstore_multiprocess(n_workers):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
         "-n", str(n_workers), "--launcher", "local",
         "--port", str(_free_port()), "--",
         sys.executable, os.path.join(_REPO, "tests",
                                      "dist_sync_kvstore.py")],
        env=_clean_env(), capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    for rank in range(n_workers):
        assert f"worker {rank}/{n_workers}: dist_sync_kvstore OK" \
            in proc.stdout


# -- fault tolerance (mxnet_tpu/resilience.py) ---------------------------------

_WORKER = os.path.join(_REPO, "tests", "resilient_dist_worker.py")


@pytest.mark.slow
def test_dist_survivor_exits_via_watchdog(tmp_path):
    """SIGTERM one worker mid-run: the survivor's next collective wedges
    waiting on the dead peer, and the MXTPU_COLLECTIVE_TIMEOUT watchdog
    must abort it (stack dump + exit code 42), not let it hang."""
    port = _free_port()
    env = _clean_env()
    env.update({
        "MXTPU_COORDINATOR": f"127.0.0.1:{port}",
        "MXTPU_NUM_WORKERS": "2",
        "MXTPU_COLLECTIVE_TIMEOUT": "8",
        "MXTPU_WATCHDOG_ACTION": "abort",
        "MXTPU_WATCHDOG_EXIT_CODE": "42",
    })
    procs = []
    for rank in range(2):
        e = dict(env)
        e["MXTPU_WORKER_RANK"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER, str(tmp_path), "40"],
            env=e, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    outs = [p.communicate(timeout=120) for p in procs]
    # rank 1 died of its self-delivered SIGTERM
    assert procs[1].returncode == -signal.SIGTERM, outs[1]
    # rank 0 did NOT hang: the collective watchdog aborted it with the
    # configured exit code after dumping where it was stuck
    assert procs[0].returncode == 42, (procs[0].returncode, outs[0])
    assert "watchdog" in outs[0][1] and "expired" in outs[0][1]
    assert "thread stack dump" in outs[0][1]


@pytest.mark.slow
def test_dist_gang_restart_resumes_from_checkpoint(tmp_path):
    """launch.py --max-restarts 1: worker 1 crashes mid-run, the gang is
    torn down and relaunched, both ranks resume from their latest
    checkpoint and reach the final step with the exact state a serial
    replay produces."""
    num_steps = 40
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--max-restarts", "1",
         "--port", str(_free_port()), "--",
         sys.executable, _WORKER, str(tmp_path), str(num_steps)],
        env={**_clean_env(),
             "MXTPU_COLLECTIVE_TIMEOUT": "8",
             "MXTPU_WATCHDOG_ACTION": "abort"},
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    assert "restarting gang" in proc.stderr
    for rank in range(2):
        assert f"worker {rank}: resilient run done at step {num_steps}" \
            in proc.stdout
        assert f"worker {rank}: resumed from step" in proc.stdout

    # both ranks' final checkpoints match an uninterrupted serial replay
    sys.path.insert(0, _REPO)
    try:
        from mxnet_tpu import resilience
    finally:
        sys.path.pop(0)
    import numpy as np

    w = np.full(4, 10.0)
    for _ in range(num_steps):
        w = w - 0.05 * 2 * w
    for rank in range(2):
        ck = resilience.LocalCheckpointer(
            os.path.join(str(tmp_path), f"rank{rank}"))
        assert ck.latest_step() == num_steps
        np.testing.assert_allclose(ck.restore(num_steps)["w"], w,
                                   rtol=1e-12)
