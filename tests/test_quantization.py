"""int8 quantization flow (reference: tests/python/quantization/
test_quantization.py — quantize_model/quantize_net int8 conversion)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.contrib import quantization as qz


def _small_net(rs):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.Activation("relu"),
            gluon.nn.Flatten(),
            gluon.nn.Dense(6))
    net.initialize()
    net.hybridize()
    x = nd.array(rs.randn(4, 3, 8, 8).astype("float32"))
    net(x)
    return net, x


def test_quantize_net_naive_close_to_float():
    rs = np.random.RandomState(0)
    net, x = _small_net(rs)
    with mx.autograd.predict_mode():
        ref = net(x).asnumpy()
    calib = [nd.array(rs.randn(4, 3, 8, 8).astype("float32"))
             for _ in range(3)] + [x]
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode="naive")
    out = qnet(x).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel
    # weights really are int8 in the quantized block
    wq = [p for n, p in qnet.params.items()
          if "conv" in n and n.endswith("_weight")]
    assert wq and wq[0].data().dtype == np.int8


def test_quantize_net_entropy_mode_runs():
    rs = np.random.RandomState(1)
    net, x = _small_net(rs)
    with mx.autograd.predict_mode():
        ref = net(x).asnumpy()
    calib = [nd.array(rs.randn(8, 3, 8, 8).astype("float32"))
             for _ in range(4)]
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode="entropy")
    out = qnet(x).asnumpy()
    # entropy clips tails: bound MEAN error, not max
    mean_rel = np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-9)
    assert mean_rel < 0.25, mean_rel


def test_quantize_model_dynamic_symbol_path():
    rs = np.random.RandomState(2)
    net, x = _small_net(rs)
    with mx.autograd.predict_mode():
        ref = net(x).asnumpy()
    sym = mx.sym.trace_block(net)
    args = {n: p.data() for n, p in net.collect_params().items()
            if p.grad_req != "null"}
    qsym, qarg, qaux = qz.quantize_model(sym, args, {}, calib_mode="none")
    feed = {"data": x}
    feed.update(qarg)
    feed.update(qaux)
    out = qsym.eval(**feed).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_model_excluded_layers_stay_float():
    rs = np.random.RandomState(3)
    net, x = _small_net(rs)
    sym = mx.sym.trace_block(net)
    conv_names = [n.name for n in sym._topo() if n.op == "Convolution"]
    args = {n: p.data() for n, p in net.collect_params().items()
            if p.grad_req != "null"}
    qsym, qarg, _ = qz.quantize_model(sym, args, {}, calib_mode="none",
                                      excluded_sym_names=conv_names)
    ops = {n.op for n in qsym._topo()}
    assert "Convolution" in ops  # excluded conv kept float
    assert "_contrib_quantized_fully_connected" in ops  # fc quantized
    # excluded layer's weight is still float in qarg
    wname = [k for k in qarg if "conv" in k and k.endswith("_weight")][0]
    assert qarg[wname].dtype == np.float32


def test_kl_threshold_sane_on_gaussian():
    from mxnet_tpu.contrib.quantization import _get_optimal_threshold

    rs = np.random.RandomState(0)
    t = _get_optimal_threshold(rs.randn(50000))
    assert 2.0 < t < 5.0, t


def test_quantize_net_no_bias_convs():
    # review regression: use_bias=False layers must quantize (resnet-style)
    rs = np.random.RandomState(4)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, use_bias=False),
            gluon.nn.Activation("relu"),
            gluon.nn.Flatten(),
            gluon.nn.Dense(6, use_bias=False))
    net.initialize()
    net.hybridize()
    x = nd.array(rs.randn(4, 3, 8, 8).astype("float32"))
    net(x)
    with mx.autograd.predict_mode():
        ref = net(x).asnumpy()
    qnet = qz.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = qnet(x).asnumpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_net_nonzero_bias_preserved():
    # review regression: the bias contribution must survive quantization
    rs = np.random.RandomState(5)
    net = gluon.nn.Dense(4, in_units=3)
    net.initialize()
    net.bias.set_data(nd.array(np.array([1.0, -2.0, 0.5, 3.0],
                                        np.float32)))
    net.hybridize()
    x = nd.array(rs.randn(2, 3).astype("float32"))
    net(x)
    with mx.autograd.predict_mode():
        ref = net(x).asnumpy()
    qnet = qz.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = qnet(x).asnumpy()
    assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.05


def test_entropy_threshold_known_distribution():
    """Calibration fixture (ADVICE r3 Weak #9): on a distribution with
    a dense Gaussian core and rare far outliers, KL-optimal calibration
    must clip the outliers (threshold well below absmax, covering the
    core), while naive calibration returns absmax."""
    from mxnet_tpu.contrib.quantization import _get_optimal_threshold

    rs = np.random.RandomState(0)
    core = rs.normal(0.0, 1.0, 100_000)
    outliers = np.array([50.0, -50.0, 48.0])     # 3 of 100k at |x|~50
    arr = np.concatenate([core, outliers])
    t = _get_optimal_threshold(arr)
    absmax = float(np.abs(arr).max())
    # clips the outliers...
    assert t < 0.5 * absmax, (t, absmax)
    # ...but keeps the Gaussian core (≥ ~4 sigma: <0.01% clipped mass)
    assert t > 3.5, t
    # degenerate inputs stay sane
    assert abs(_get_optimal_threshold(np.zeros(16)) - 1e-8) < 1e-12
    # a uniform distribution has nothing to clip: threshold ~ absmax
    u = rs.uniform(-2, 2, 50_000)
    assert _get_optimal_threshold(u) > 1.8


def test_quantize_transformer_gpt():
    """Transformer int8 PTQ (unlocked by round-4 tracing): quantize_net
    rewrites the traced GPT's FullyConnected FFN/projection nodes to
    int8 MXU matmuls; outputs stay close and next-token argmax
    agreement holds on the calibration batch."""
    from mxnet_tpu import nd
    from mxnet_tpu.contrib.quantization import quantize_net
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.gpt_tiny(units=64, num_heads=4)
    net.initialize(init=mx.init.Xavier())
    ids = nd.array(np.random.RandomState(0)
                   .randint(0, 128, (4, 16)).astype(np.float32))
    ref = net(ids).asnumpy()
    net.hybridize()
    net(ids)
    qnet = quantize_net(net, calib_data=[ids], calib_mode="naive")
    qo = qnet(ids).asnumpy()
    rel = np.abs(qo - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.1, rel
    import json

    js = json.loads(qnet._outputs_sym.tojson())
    nq = sum(1 for n in js["nodes"] if "quantized" in n["op"])
    assert nq >= 8, nq  # the FFN + projection matmuls went int8
    agree = (qo[:, -1].argmax(-1) == ref[:, -1].argmax(-1)).mean()
    assert agree == 1.0, agree


def test_quantize_net_vit():
    """int8 PTQ generalizes to the ViT family (patchify conv + scanned
    trunk): traced matmuls rewrite, argmax agreement holds."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.contrib.quantization import quantize_net
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    np.random.seed(0)
    net = vision.vit_tiny()
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(4, 3, 32, 32)
                    .astype(np.float32))
    net(x)
    qnet = quantize_net(net, calib_data=[x], calib_mode="naive")
    agree = (qnet(x).asnumpy().argmax(1)
             == net(x).asnumpy().argmax(1)).mean()
    assert agree >= 0.75, agree
