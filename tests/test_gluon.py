"""Gluon core tests (reference: tests/python/unittest/test_gluon.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "test.params")
        params.save(fname)
        params.load(fname, mx.cpu())


def test_constant():
    class Test(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.value = np.asarray([[1, 2], [3, 4]], dtype="float32")
            self.const = self.params.get_constant("const", self.value)

        def hybrid_forward(self, F, x, const):
            return x + const

    test = Test()
    test.initialize()
    trainer = gluon.Trainer(test.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.5})
    with mx.autograd.record():
        x = mx.nd.ones((2, 2))
        x.attach_grad()
        y = test(x)
        y.backward()
    trainer.step(1)
    assert (test.const.data().asnumpy() == test.value).all()
    assert (x.grad.asnumpy() == 1).all()


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False,
                     prefix="test_")
    inputs = mx.nd.zeros((3, 4, 10))
    model.initialize()
    outputs = model(inputs)
    assert {p.name for p in model.collect_params().values()} == \
        {"test_weight", "test_bias"}
    assert outputs.shape == (3, 4, 128)

    model2 = nn.Dense(64, in_units=30, prefix="test2_")
    inputs2 = mx.nd.zeros((17, 2, 15))
    model2.initialize()
    assert model2(inputs2).shape == (17, 64)


def test_deferred_init_and_reinit():
    net = nn.Dense(5)
    net.initialize()
    x = mx.nd.ones((3, 7))
    net(x)
    assert net.weight.shape == (5, 7)


def test_sequential_and_getitem():
    net = nn.Sequential()
    net.add(nn.Dense(10), nn.Dense(5), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    sliced = net[0:2]
    assert len(sliced) == 2


def test_hybrid_matches_eager():
    np.random.seed(42)
    mx.random.seed(42)

    def build():
        net = nn.HybridSequential(prefix="m_")
        with net.name_scope():
            net.add(nn.Dense(12, activation="relu"),
                    nn.LayerNorm(),
                    nn.Dense(3))
        return net

    x = mx.nd.random_normal(shape=(4, 6))
    net = build()
    net.initialize(init="xavier")
    eager_out = net(x).asnumpy()
    net.hybridize()
    hybrid_out = net(x).asnumpy()
    np.testing.assert_allclose(eager_out, hybrid_out, rtol=1e-5, atol=1e-6)


def test_hybrid_backward_matches_eager():
    x = mx.nd.random_normal(shape=(4, 6))
    label = mx.nd.array([0, 1, 2, 0])
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    grads = []
    for hybridize in (False, True):
        mx.random.seed(7)
        np.random.seed(7)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(12, activation="relu"), nn.Dense(3))
        net.initialize(init="xavier")
        if hybridize:
            net.hybridize()
        with mx.autograd.record():
            loss = loss_fn(net(x), label)
        loss.backward()
        grads.append(net[0].weight.grad().asnumpy())
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-4, atol=1e-6)


def test_batchnorm_moving_stats_update():
    net = nn.BatchNorm(axis=1, momentum=0.5, in_channels=4)
    net.initialize()
    x = mx.nd.random_normal(shape=(8, 4), loc=3.0)
    with mx.autograd.record():
        net(x)
    rm = net.running_mean.data().asnumpy()
    # moving mean pulled toward the batch mean (≈3)
    assert np.abs(rm).sum() > 0
    # inference uses moving stats, differs from train-mode output
    out_inf = net(x).asnumpy()
    with mx.autograd.record():
        out_train = net(x).asnumpy()
    assert not np.allclose(out_inf, out_train)


def test_conv_layers_shapes():
    layers = [
        (nn.Conv1D(16, 3, in_channels=4), (2, 4, 10), (2, 16, 8)),
        (nn.Conv2D(16, (3, 4), in_channels=4), (2, 4, 20, 20),
         (2, 16, 18, 17)),
        (nn.Conv3D(16, (1, 8, 4), in_channels=4, activation="relu"),
         (2, 4, 10, 10, 10), (2, 16, 10, 3, 7)),
        (nn.Conv2DTranspose(16, (3, 4), in_channels=4), (2, 4, 20, 20),
         (2, 16, 22, 23)),
        (nn.MaxPool2D((3, 3), 2), (2, 2, 20, 20), (2, 2, 9, 9)),
        (nn.AvgPool1D(), (2, 2, 10), (2, 2, 5)),
        (nn.GlobalAvgPool2D(), (2, 2, 8, 8), (2, 2, 1, 1)),
    ]
    for layer, in_shape, out_shape in layers:
        layer.initialize()
        out = layer(mx.nd.random_normal(shape=in_shape))
        assert out.shape == out_shape, \
            f"{layer.__class__.__name__}: {out.shape} != {out_shape}"


def test_group_conv():
    net = nn.Conv2D(8, 3, groups=2, in_channels=4)
    net.initialize()
    assert net.weight.shape == (8, 2, 3, 3)
    out = net(mx.nd.random_normal(shape=(1, 4, 8, 8)))
    assert out.shape == (1, 8, 6, 6)


def test_pool_ceil_mode():
    # x=6,k=3,s=2: valid → floor(3/2)+1 = 2; full/ceil → ceil(3/2)+1 = 3
    net = nn.MaxPool2D(3, 2, ceil_mode=True)
    out = net(mx.nd.random_normal(shape=(1, 1, 6, 6)))
    assert out.shape == (1, 1, 3, 3)
    net_v = nn.MaxPool2D(3, 2, ceil_mode=False)
    assert net_v(mx.nd.random_normal(shape=(1, 1, 6, 6))).shape == \
        (1, 1, 2, 2)


def test_embedding_and_flatten():
    emb = nn.Embedding(input_dim=20, output_dim=5)
    emb.initialize()
    idx = mx.nd.array([[1, 2], [3, 4]])
    out = emb(idx)
    assert out.shape == (2, 2, 5)
    with mx.autograd.record():
        loss = (emb(idx) * emb(idx)).sum()
    loss.backward()
    assert emb.weight.grad().shape == (20, 5)

    f = nn.Flatten()
    assert f(mx.nd.zeros((2, 3, 4))).shape == (2, 12)


def test_lambda_blocks():
    add = nn.HybridLambda(lambda F, x: x + 2)
    assert float(add(mx.nd.zeros((1,))).asnumpy()[0]) == 2.0
    relu_l = nn.Lambda("relu")
    np.testing.assert_allclose(
        relu_l(mx.nd.array([-1.0, 1.0])).asnumpy(), [0.0, 1.0])


def test_block_attr_registration():
    class Model(gluon.Block):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.dense0 = nn.Dense(5)
                self.dense1 = nn.Dense(5)

        def forward(self, x):
            return self.dense1(self.dense0(x))

    model = Model()
    assert len(model._children) == 2
    model.initialize()
    assert model(mx.nd.zeros((2, 4))).shape == (2, 5)
    assert len(model.collect_params()) == 4


def test_collect_params_select():
    net = nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(nn.Dense(4), nn.BatchNorm())
    net.initialize()
    net(mx.nd.zeros((2, 3)))
    weights = net.collect_params(".*weight")
    assert all("weight" in k for k in weights.keys())
    assert len(weights) == 1


def test_save_load_parameters_roundtrip():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    x = mx.nd.random_normal(shape=(2, 3))
    before = net(x).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "net.params")
        net.save_parameters(fname)
        net2 = nn.HybridSequential()
        with net2.name_scope():
            net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
        net2.load_parameters(fname)
        np.testing.assert_allclose(net2(x).asnumpy(), before, rtol=1e-6)


def test_parameter_sharing():
    shared = nn.Dense(4, in_units=4, prefix="shared_")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(shared)
        net.add(nn.Dense(4, in_units=4, params=shared.params,
                         prefix="shared_"))
    net.initialize()
    w0 = net[0].weight.data().asnumpy()
    w1 = net[1].weight.data().asnumpy()
    np.testing.assert_allclose(w0, w1)


def test_losses_basic():
    pred = mx.nd.array([[1.0, 2.0], [0.5, 0.1]])
    label2 = mx.nd.array([[1.5, 1.5], [0.0, 0.0]])
    l2 = gluon.loss.L2Loss()(pred, label2).asnumpy()
    exp = 0.5 * ((pred.asnumpy() - label2.asnumpy()) ** 2).mean(axis=1)
    np.testing.assert_allclose(l2, exp, rtol=1e-6)

    l1 = gluon.loss.L1Loss()(pred, label2).asnumpy()
    np.testing.assert_allclose(
        l1, np.abs(pred.asnumpy() - label2.asnumpy()).mean(axis=1),
        rtol=1e-6)

    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    out = sce(pred, mx.nd.array([1, 0])).asnumpy()
    p = pred.asnumpy()
    logp = p - np.log(np.exp(p).sum(axis=1, keepdims=True))
    np.testing.assert_allclose(out, [-logp[0, 1], -logp[1, 0]], rtol=1e-5)

    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    out = bce(pred, mx.nd.array([[1.0, 0.0], [1.0, 1.0]])).asnumpy()
    assert np.all(out > 0)

    huber = gluon.loss.HuberLoss()(pred, label2).asnumpy()
    assert huber.shape == (2,)

    hinge = gluon.loss.HingeLoss()(pred, mx.nd.array([[1.0, -1.0],
                                                      [1.0, -1.0]]))
    assert hinge.shape == (2,)


def test_ctc_loss():
    # uniform logits over 3 classes: -log P(label) is analytic
    T, C = 4, 3
    pred = mx.nd.zeros((1, T, C))
    label = mx.nd.array([[1, 2]])
    loss = gluon.loss.CTCLoss()(pred, label).asnumpy()
    # all paths equally likely: P = (#valid paths) / C^T
    # valid CTC alignments of "12" into 4 frames over 3 symbols w/ blank=0
    assert loss.shape == (1,)
    assert loss[0] > 0


def test_trainer_updates_and_state_roundtrip():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.nd.random_normal(shape=(4, 3))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    w0 = net.weight.data().asnumpy().copy()
    trainer.step(4)
    assert not np.allclose(w0, net.weight.data().asnumpy())
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "trainer.states")
        trainer.save_states(fname)
        trainer.load_states(fname)


def test_trainer_lr_control():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    assert trainer.learning_rate == 0.5
    trainer.set_learning_rate(0.1)
    assert trainer.learning_rate == 0.1


def test_clip_global_norm():
    arrays = [mx.nd.ones((3,)) * 3, mx.nd.ones((4,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    norm = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert norm == pytest.approx(1.0, rel=1e-4)


def test_split_and_load():
    data = mx.nd.random_normal(shape=(8, 3))
    splits = gluon.utils.split_data(data, 4)
    assert len(splits) == 4
    assert splits[0].shape == (2, 3)
    loaded = gluon.utils.split_and_load(np.ones((4, 2)), [mx.cpu()])
    assert loaded[0].shape == (4, 2)


def test_lora_adapters_train_frozen_base():
    """gluon.contrib.lora: adapted net starts equal to base (B=0),
    only adapters train, merge() folds the update losslessly."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.contrib import apply_lora

    mx.random.seed(0)
    np.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(8))
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(16, 12)
                    .astype(np.float32))
    net(x)
    base_out = net(x).asnumpy()
    wrapped = apply_lora(net, rank=4, alpha=8, patterns=("dense",))
    assert len(wrapped) == 2
    np.testing.assert_allclose(net(x).asnumpy(), base_out, rtol=1e-6)

    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    frozen = {n: p.data().asnumpy().copy()
              for n, p in net.collect_params().items()
              if p.grad_req == "null"}
    assert frozen, "base params must be frozen"
    y = mx.nd.array(np.random.RandomState(1).randn(16, 8)
                    .astype(np.float32))
    l2 = gluon.loss.L2Loss()
    first = last = None
    for _ in range(20):
        with autograd.record():
            l = l2(net(x), y)
        l.backward()
        tr.step(16)
        v = float(l.mean().asnumpy())
        first = v if first is None else first
        last = v
    assert last < 0.7 * first, (first, last)
    for n, p in net.collect_params().items():
        if p.grad_req == "null":
            np.testing.assert_array_equal(p.data().asnumpy(), frozen[n])
    pred = net(x).asnumpy()
    for b in wrapped:
        b.merge()
    np.testing.assert_allclose(net(x).asnumpy(), pred, rtol=2e-5,
                               atol=1e-5)


def test_lora_on_hybridized_attribute_held_net():
    """Review regressions: (a) a net storing Dense as ATTRIBUTES
    (self.fc = ...) must rebind through __setattr__'s type gate
    (LoRADense IS-A Dense); (b) a net hybridized-AND-RUN before
    apply_lora must retrace with the adapters (stale jit caches
    cleared) so adapters actually train."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.contrib import apply_lora

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc1 = nn.Dense(16, activation="relu")
                self.fc2 = nn.Dense(4)

        def hybrid_forward(self, F, x):
            return self.fc2(self.fc1(x))

    mx.random.seed(0)
    np.random.seed(0)
    net = Net()
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).randn(8, 6)
                    .astype(np.float32))
    net(x)  # builds the jit cache WITHOUT adapters
    wrapped = apply_lora(net, rank=2, alpha=4, patterns=("dense",))
    assert len(wrapped) == 2
    assert net.fc1 is wrapped[0] and isinstance(net.fc1, nn.Dense)

    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    l2 = gluon.loss.L2Loss()
    y = mx.nd.array(np.random.RandomState(1).randn(8, 4)
                    .astype(np.float32))
    a0 = {i: b.lora_a.data().asnumpy().copy()
          for i, b in enumerate(wrapped)}
    b0 = {i: b.lora_b.data().asnumpy().copy()
          for i, b in enumerate(wrapped)}
    first = last = None
    for _ in range(10):
        with autograd.record():
            l = l2(net(x), y)
        l.backward()
        tr.step(8)
        v = float(l.mean().asnumpy())
        first = v if first is None else first
        last = v
    assert last < first, (first, last)
    # the adapters moved — the stale pre-wrap jit was NOT reused
    moved = any(not np.allclose(b.lora_b.data().asnumpy(), b0[i])
                for i, b in enumerate(wrapped))
    assert moved, "adapters never trained: stale jit cache reused"
    # idempotence: a second apply_lora must not re-wrap LoRADense
    import pytest as _pytest

    with _pytest.raises(ValueError):
        apply_lora(net, rank=2, patterns=("no_match_pattern",))

    # the adapted net exports and round-trips through SymbolBlock
    with autograd.predict_mode():
        ref_exp = net(x)
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "lora"))
        sb = gluon.SymbolBlock.imports(
            os.path.join(d, "lora-symbol.json"), ["data"],
            os.path.join(d, "lora-0000.params"))
        np.testing.assert_allclose(sb(x).asnumpy(), ref_exp.asnumpy(),
                                   atol=1e-5)
