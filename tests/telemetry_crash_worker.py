"""Crash-mid-append worker for tests/test_telemetry.py.

Emits three event records cleanly into the JSONL sink, then arms the
``telemetry_crash`` fault site and emits a fourth: the injected
``os._exit`` fires inside ``telemetry._emit`` after HALF the line is
written and flushed — the on-disk state a power cut mid-append leaves.
The parent asserts the process died with ``resilience.CRASH_EXIT_CODE``,
that the three earlier lines still parse, and that readers
(``tools/trace_report.py``) skip the truncated tail.

Usage: telemetry_crash_worker.py <jsonl_path>
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["MXTPU_TELEMETRY_PATH"] = sys.argv[1]

from mxnet_tpu import resilience, telemetry


def main():
    for i in range(3):
        telemetry.event("marker", step=i)
    os.environ["MXTPU_FAULT_INJECT"] = "telemetry_crash:1"
    resilience.reset_faults()
    telemetry.event("marker", step=3)
    # only reachable if the injection never fired — the parent asserts
    # on CRASH_EXIT_CODE, so this is a loud failure
    print("survived: no crash was injected", flush=True)


if __name__ == "__main__":
    main()
