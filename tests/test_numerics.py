"""Numerical-health guard (mxnet_tpu/numerics.py): fused finite-checks,
skip-step with state rollback, global-norm clipping, divergence
auto-recovery, loss-scaler fixes, and metric NaN robustness."""

import math
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, numerics
from mxnet_tpu.amp import DynamicLossScaler
from mxnet_tpu.numerics import (DivergenceError, DivergenceMonitor,
                                StepGuard, StepSkipped)
from mxnet_tpu.optimizer import grouped

SHAPES = [(5, 7), (3,), (2, 3, 4), (1,), (8, 2), (4, 4)]


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.get(k)
             for k in ("MXTPU_FUSED_STEP", "MXTPU_GRAD_GUARD",
                       "MXTPU_CLIP_GLOBAL_NORM", "MXTPU_MAX_BAD_STEPS")}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _make_params(dtype="float32", seed=0):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    params = []
    for k, shape in enumerate(SHAPES):
        p = gluon.Parameter(f"p{k}_weight", shape=shape, dtype=dtype)
        p.initialize(init=mx.init.Zero())
        p.data()._set_data(
            jnp.asarray(rng.standard_normal(shape).astype(dtype)))
        params.append(p)
    return params


def _set_grads(params, grads):
    import jax.numpy as jnp

    for p, g in zip(params, grads):
        p.list_grad()[0]._set_data(jnp.asarray(g))


def _grad_seq(steps, dtype="float32", seed=1):
    rng = np.random.RandomState(seed)
    return [[rng.standard_normal(s).astype(dtype) for s in SHAPES]
            for _ in range(steps)]


def _nan_grads(dtype="float32"):
    gs = [np.ones(s, dtype) for s in SHAPES]
    gs[2].flat[3] = np.nan
    return gs


def _flat_state(state):
    if state is None:
        return []
    if isinstance(state, (list, tuple)):
        return [a for s in state for a in _flat_state(s)]
    return [state]


def _snapshot(trainer, params):
    weights = [p.data().asnumpy().copy() for p in params]
    states = {k: [s.asnumpy().copy() for s in _flat_state(v)]
              for k, v in trainer._updaters[0].states.items()}
    return weights, states


# -- the tentpole: skip-step, one readback, bitwise rollback -------------------

def test_nan_grad_skips_step_bitwise_one_readback():
    params = _make_params()
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                            kvstore=None)
    seq = _grad_seq(2)
    _set_grads(params, seq[0])
    trainer.step(2, ignore_stale_grad=True)  # healthy: states exist now
    snap_w, snap_s = _snapshot(trainer, params)
    num_update = trainer._optimizer.num_update
    counts = dict(trainer._optimizer._index_update_count)

    _set_grads(params, _nan_grads())
    numerics.reset_readback_count()
    grouped.reset_dispatch_count()
    trainer.step(2, ignore_stale_grad=True)

    # exactly ONE scalar readback and the usual ONE group dispatch
    assert numerics.readback_count() == 1
    assert grouped.dispatch_count() == 1
    # weights and optimizer state bitwise-unchanged
    for p, w0 in zip(params, snap_w):
        np.testing.assert_array_equal(p.data().asnumpy(), w0)
    for k, v in trainer._updaters[0].states.items():
        for s, s0 in zip(_flat_state(v), snap_s[k]):
            np.testing.assert_array_equal(s.asnumpy(), s0)
    # host-side step counters rolled back (Adam bias-correction t)
    assert trainer._optimizer.num_update == num_update
    assert dict(trainer._optimizer._index_update_count) == counts
    # the skip was recorded
    assert len(trainer.skipped_steps) == 1
    rec = trainer.skipped_steps[0]
    assert isinstance(rec, StepSkipped)
    assert math.isnan(rec.grad_norm)
    assert "non-finite" in rec.reason


def test_healthy_steps_one_readback_each():
    params = _make_params()
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                            kvstore=None)
    seq = _grad_seq(3)
    numerics.reset_readback_count()
    for g in seq:
        _set_grads(params, g)
        trainer.step(2, ignore_stale_grad=True)
    assert numerics.readback_count() == len(seq)
    assert not trainer.skipped_steps


def test_skipped_step_trajectory_as_if_batch_dropped():
    """[g0, NaN, g1] must land bitwise where [g0, g1] lands — the skipped
    step leaves NO trace (weights, states, or step counts)."""
    seq = _grad_seq(2)

    def run(with_nan):
        params = _make_params()
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                                kvstore=None)
        gs = [seq[0]] + ([_nan_grads()] if with_nan else []) + [seq[1]]
        for g in gs:
            _set_grads(params, g)
            trainer.step(2, ignore_stale_grad=True)
        return _snapshot(trainer, params)

    w_clean, s_clean = run(False)
    w_nan, s_nan = run(True)
    for a, b in zip(w_clean, w_nan):
        np.testing.assert_array_equal(a, b)
    for k in s_clean:
        for a, b in zip(s_clean[k], s_nan[k]):
            np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("optname,kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-3, "wd": 1e-4}),
    ("lamb", {"learning_rate": 1e-3}),
    ("ftml", {"learning_rate": 1e-3}),
])
def test_guard_on_off_bitwise_identical(optname, kwargs):
    """Healthy steps with the guard ON are bitwise-identical to guard
    OFF (the lax.cond true branch compiles like the unguarded program)."""
    seq = _grad_seq(4)

    def run(guard):
        os.environ["MXTPU_GRAD_GUARD"] = "1" if guard else "0"
        params = _make_params()
        trainer = gluon.Trainer(params, optname, dict(kwargs),
                                kvstore=None)
        for g in seq:
            _set_grads(params, g)
            trainer.step(2, ignore_stale_grad=True)
        return [p.data().asnumpy() for p in params]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_guard_off_no_readbacks_no_skip():
    os.environ["MXTPU_GRAD_GUARD"] = "0"
    params = _make_params()
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore=None)
    numerics.reset_readback_count()
    _set_grads(params, _nan_grads())
    trainer.step(2, ignore_stale_grad=True)
    assert numerics.readback_count() == 0
    assert not trainer.skipped_steps
    # with the guard off the NaN really does poison the weights
    assert not np.isfinite(params[2].data().asnumpy()).all()


def test_fallback_items_host_skipped():
    """Non-groupable items (fp16 multi-precision master weights) take the
    legacy loop — a guarded unhealthy step must skip them too."""
    params = _make_params(dtype="float16")
    trainer = gluon.Trainer(
        params, "sgd",
        {"learning_rate": 0.1, "multi_precision": True}, kvstore=None)
    _set_grads(params, _grad_seq(1, dtype="float16")[0])
    trainer.step(2, ignore_stale_grad=True)
    snap_w, _ = _snapshot(trainer, params)
    _set_grads(params, _nan_grads(dtype="float16"))
    trainer.step(2, ignore_stale_grad=True)
    for p, w0 in zip(params, snap_w):
        np.testing.assert_array_equal(p.data().asnumpy(), w0)
    assert trainer.skipped_steps


# -- global-norm clipping ------------------------------------------------------

def _run_clipped(clip_arg=None, env=None, manual=False, steps=3):
    from mxnet_tpu.gluon.utils import clip_global_norm

    if env is not None:
        os.environ["MXTPU_CLIP_GLOBAL_NORM"] = str(env)
    seq = _grad_seq(steps, seed=3)
    params = _make_params()
    kw = {"clip_global_norm": clip_arg} if clip_arg is not None else {}
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-2},
                            kvstore=None, **kw)
    for g in seq:
        _set_grads(params, g)
        if manual:
            clip_global_norm([p.grad() for p in params], manual)
        trainer.step(2, ignore_stale_grad=True)
    return [p.data().asnumpy() for p in params]


def test_clip_global_norm_matches_reference():
    """The fused in-program clip reproduces gluon.utils.clip_global_norm
    applied eagerly before an unclipped step."""
    fused = _run_clipped(clip_arg=0.05)
    os.environ.pop("MXTPU_CLIP_GLOBAL_NORM", None)
    manual = _run_clipped(manual=0.05)
    for a, b in zip(fused, manual):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7)


def test_clip_global_norm_env_var():
    a = _run_clipped(clip_arg=0.05)
    b = _run_clipped(env=0.05)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_clip_works_with_guard_off():
    os.environ["MXTPU_GRAD_GUARD"] = "0"
    clipped = _run_clipped(clip_arg=0.05)
    os.environ["MXTPU_GRAD_GUARD"] = "1"
    ref = _run_clipped(clip_arg=0.05)
    for a, b in zip(clipped, ref):
        np.testing.assert_array_equal(a, b)


def test_clip_no_op_above_norm():
    """A huge threshold never rescales: bitwise-identical to no clip."""
    plain = _run_clipped()
    clipped = _run_clipped(clip_arg=1e9)
    for a, b in zip(plain, clipped):
        np.testing.assert_array_equal(a, b)


# -- bucketed_pushpull health + watchdog labels --------------------------------

def test_bucketed_pushpull_returns_health():
    import jax.numpy as jnp

    kv = mx.kvstore.create("device")
    vals = [mx.nd.array(np.ones((4, 4), np.float32)),
            mx.nd.array(np.full((8,), 2.0, np.float32))]
    for k, v in enumerate(vals):
        kv.init(k, v)
    outs = [mx.nd.zeros_like(v) for v in vals]
    health = kv.bucketed_pushpull([0, 1], vals, outs=outs, health=True)
    h = np.asarray(health)
    assert h[0] == 1.0
    np.testing.assert_allclose(h[1], 16.0 + 8 * 4.0)
    # poisoned value flips the finite flag
    bad = [mx.nd.array(np.full((4, 4), np.nan, np.float32)), vals[1]]
    health = kv.bucketed_pushpull([0, 1], bad, outs=None, health=True)
    assert np.asarray(health)[0] == 0.0
    # health=False keeps the legacy None contract
    assert kv.bucketed_pushpull([0, 1], vals, outs=outs) is None


def test_trainer_spy_kvstore_without_health_kwarg():
    """A monkeypatched/legacy bucketed_pushpull without the health kwarg
    must still work — the Trainer computes health itself."""
    params = _make_params()
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore="device")
    trainer._init_kvstore()

    class SpyKV:
        type = "device"
        num_workers = 1
        calls = []

        def bucketed_pushpull(self, keys, values, outs=None, priority=0):
            self.calls.append(list(keys))

    trainer._kvstore = SpyKV()
    trainer._update_on_kvstore = False
    _set_grads(params, _nan_grads())
    trainer.step(2, ignore_stale_grad=True)
    assert trainer._kvstore.calls  # the reduce ran without health=
    assert trainer.skipped_steps   # and the guard still caught the NaN


@pytest.mark.faults
def test_watchdog_message_names_bucket(fault_inject, monkeypatch):
    """A wedged bucketed all-reduce must say WHICH bucket: dtype and
    byte size in the WatchdogExpired message."""
    from mxnet_tpu import kvstore as kvmod
    from mxnet_tpu.resilience import WatchdogExpired

    monkeypatch.setenv("MXTPU_COLLECTIVE_TIMEOUT", "0.5")
    kv = mx.kvstore.create("device")
    v = mx.nd.array(np.ones((4, 4), np.float32))
    kv.init(0, v)
    # single-process stores never hit the collective; force the dist
    # branch so _cross_process_allreduce (and its watchdog) runs
    monkeypatch.setattr(kv, "_is_dist", True, raising=False)
    monkeypatch.setattr(type(kv), "num_workers",
                        property(lambda self: 2), raising=False)
    fault_inject("stall_collective:30")
    with pytest.raises(WatchdogExpired) as ei:
        kv.bucketed_pushpull([0], [v], outs=None)
    msg = str(ei.value)
    assert "float32" in msg
    assert "64 bytes" in msg


# -- fault-injection sites -----------------------------------------------------

@pytest.mark.faults
def test_nan_grad_fault_site_skips_and_recovers(fault_inject):
    """Inject a NaN batch mid-run: the step is skipped and the
    post-recovery loss/weight trajectory is IDENTICAL to a run that
    never saw the poisoned batch."""
    seq = _grad_seq(4, seed=9)

    def run(poison_at=None):
        params = _make_params()
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                                kvstore=None)
        traj = []
        for i, g in enumerate(seq):
            if i == poison_at:
                fault_inject("nan_grad:1")
            _set_grads(params, g)
            trainer.step(2, ignore_stale_grad=True)
            traj.append([p.data().asnumpy().copy() for p in params])
        return trainer, traj

    clean_tr, clean = run()
    assert not clean_tr.skipped_steps
    pois_tr, pois = run(poison_at=2)
    assert len(pois_tr.skipped_steps) == 1
    # the poisoned step left weights exactly at the previous step's
    np.testing.assert_array_equal(pois[2][0], pois[1][0])
    # post-recovery trajectory identical to the run that skipped batch 2
    # (same grads applied to the same weights — the NaN left no trace,
    # but step 3 consumed grad 3 in both runs, so compare weight deltas)
    for a, b in zip(clean[0], pois[0]):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(clean[1], pois[1]):
        np.testing.assert_array_equal(a, b)


@pytest.mark.faults
def test_inf_loss_fault_site(fault_inject):
    mon = DivergenceMonitor(max_bad_steps=3)
    fault_inject("inf_loss:1")
    assert not mon.observe(step=0, loss=1.0)
    assert mon.bad_streak == 1  # the injected inf made step 0 bad
    assert not mon.observe(step=1, loss=1.0)
    assert mon.bad_streak == 0


# -- DynamicLossScaler satellites ----------------------------------------------

def test_scaler_unscale_returns_new_arrays():
    s = DynamicLossScaler(init_scale=8.0)
    g = mx.nd.array(np.full((3,), 16.0, np.float32))
    out = s.unscale([g])
    np.testing.assert_allclose(out[0].asnumpy(), np.full((3,), 2.0))
    # the input is NOT mutated (JAX arrays are immutable)
    np.testing.assert_allclose(g.asnumpy(), np.full((3,), 16.0))


def test_scaler_growth_capped():
    s = DynamicLossScaler(init_scale=2.0 ** 16, scale_window=1)
    for _ in range(10):
        s.update_scale(False)
    assert s.loss_scale == 2.0 ** 16  # capped at the init/2^16 ceiling
    s2 = DynamicLossScaler(init_scale=2.0 ** 20, scale_window=1)
    for _ in range(10):
        s2.update_scale(False)
    assert s2.loss_scale == 2.0 ** 20  # a larger init raises the ceiling


def test_scaler_tolerance_honored():
    # tolerance=0.5: a lone overflow in a long clean stretch (rate
    # 1/N < 0.5) must NOT halve the scale
    s = DynamicLossScaler(init_scale=1024.0, scale_window=100,
                          tolerance=0.5)
    for _ in range(9):
        s.update_scale(False)
    s.update_scale(True)
    assert s.loss_scale == 1024.0
    # an overflow-dominated stretch crosses the tolerance -> halve
    s2 = DynamicLossScaler(init_scale=1024.0, scale_window=100,
                           tolerance=0.5)
    s2.update_scale(False)
    s2.update_scale(True)  # rate 1/2 >= 0.5
    assert s2.loss_scale == 512.0
    # default tolerance=0.0 preserves the classic always-halve
    s0 = DynamicLossScaler(init_scale=1024.0)
    assert s0.update_scale(True) == 512.0


def test_scaler_has_overflow_single_readback():
    s = DynamicLossScaler()
    good = [mx.nd.array(np.ones((4,), np.float32)) for _ in range(5)]
    bad = good + [mx.nd.array(np.array([np.inf], np.float32))]
    numerics.reset_readback_count()
    assert not s.has_overflow(good)
    assert numerics.readback_count() == 1
    assert s.has_overflow(bad)
    assert numerics.readback_count() == 2


def test_trainer_amp_scaler_integration():
    """A NaN step under an attached loss scaler halves the scale and the
    next step's rescale_grad reflects it (unscale folded into the fused
    step)."""
    params = _make_params()
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore=None)
    trainer._amp_loss_scaler = DynamicLossScaler(init_scale=1024.0)
    _set_grads(params, _nan_grads())
    trainer.step(2, ignore_stale_grad=True)
    assert trainer._amp_loss_scaler.loss_scale == 512.0
    assert trainer.skipped_steps[0].loss_scale == 1024.0
    _set_grads(params, _grad_seq(1)[0])
    trainer.step(2, ignore_stale_grad=True)
    assert trainer._optimizer.rescale_grad == (1.0 / 512.0) / 2


# -- DivergenceMonitor ---------------------------------------------------------

def test_divergence_monitor_rolls_back(tmp_path):
    from mxnet_tpu.resilience import LocalCheckpointer

    ck = LocalCheckpointer(tmp_path)
    ck.save(7, {"w": np.arange(4.0)})
    restored = {}
    scaler = DynamicLossScaler(init_scale=1024.0)
    mon = DivergenceMonitor(checkpointer=ck, set_state=restored.update,
                            scaler=scaler, max_bad_steps=3)
    for i in range(2):
        assert not mon.observe(step=i, loss=float("nan"),
                               batch_indices=[i])
    assert mon.observe(step=2, loss=float("nan"), batch_indices=[2])
    np.testing.assert_array_equal(restored["w"], np.arange(4.0))
    assert mon.recoveries == 1
    assert mon.quarantined == [0, 1, 2]
    assert scaler.loss_scale == 512.0  # re-seeded
    assert mon.bad_streak == 0


def test_divergence_monitor_explosion_detection():
    mon = DivergenceMonitor(max_bad_steps=100, explode_factor=8.0)
    for i in range(20):
        mon.observe(step=i, loss=1.0, grad_norm=1.0)
    assert mon.bad_streak == 0
    mon.observe(step=20, loss=1.0, grad_norm=100.0)  # 100x the EWMA
    assert mon.bad_streak == 1
    mon.observe(step=21, loss=50.0, grad_norm=1.0)  # loss explosion
    assert mon.bad_streak == 2


def test_divergence_error_without_checkpointer():
    mon = DivergenceMonitor(max_bad_steps=2)
    mon.observe(step=0, loss=float("inf"), batch_indices=[10])
    with pytest.raises(DivergenceError) as ei:
        mon.observe(step=1, loss=float("inf"), batch_indices=[11])
    assert ei.value.bad_steps == 2
    assert ei.value.batch_indices == [10, 11]
    assert "diverged" in str(ei.value)


def test_divergence_monitor_env_default(monkeypatch):
    monkeypatch.setenv("MXTPU_MAX_BAD_STEPS", "7")
    assert DivergenceMonitor().max_bad_steps == 7


def test_trainer_divergence_monitor_attached():
    params = _make_params()
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore=None)
    mon = DivergenceMonitor(max_bad_steps=50)
    trainer.divergence_monitor = mon
    _set_grads(params, _grad_seq(1)[0])
    trainer.step(2, ignore_stale_grad=True)
    assert mon.norm_ewma is not None and mon.norm_ewma > 0
    _set_grads(params, _nan_grads())
    trainer.step(2, ignore_stale_grad=True)
    assert mon.bad_streak == 1


# -- metric NaN robustness -----------------------------------------------------

def test_loss_metric_excludes_nonfinite():
    m = mx.metric.Loss()
    m.update(None, [mx.nd.array(np.array([1.0, 2.0], np.float32))])
    with pytest.warns(RuntimeWarning):
        m.update(None, [mx.nd.array(
            np.array([np.nan, 4.0, np.inf], np.float32))])
    name, val = m.get()
    assert math.isfinite(val)
    np.testing.assert_allclose(val, (1.0 + 2.0 + 4.0) / 3)
    assert m.num_nonfinite == 2
    m.reset()
    assert m.num_nonfinite == 0


@pytest.mark.parametrize("metric_fn", [
    lambda: mx.metric.Accuracy(),
    lambda: mx.metric.TopKAccuracy(top_k=2),
])
def test_accuracy_metrics_not_poisoned_by_nan(metric_fn):
    """NaN/Inf prediction rows contribute WRONG (finite) counts, never
    NaN sums — the running accuracy stays a real number."""
    m = metric_fn()
    labels = mx.nd.array(np.array([0, 1, 2, 1], np.float32))
    preds = np.random.RandomState(0).rand(4, 4).astype(np.float32)
    preds[1] = np.nan
    preds[3] = np.inf
    m.update([labels], [mx.nd.array(preds)])
    _, val = m.get()
    assert math.isfinite(val)
    assert 0.0 <= val <= 1.0


# -- guard internals -----------------------------------------------------------

def test_grad_health_values():
    import jax.numpy as jnp

    h = np.asarray(numerics.grad_health(
        [jnp.ones((2, 2), jnp.float32), jnp.full((3,), 2.0, jnp.float32)]))
    assert h[0] == 1.0
    np.testing.assert_allclose(h[1], 4.0 + 12.0)
    h = np.asarray(numerics.grad_health(
        [jnp.array([np.inf], jnp.float32)]))
    assert h[0] == 0.0


def test_grad_health_f16_overflow_detected():
    """An f16 inf survives the f32 accumulation upcast."""
    import jax.numpy as jnp

    g = StepGuard(numerics.grad_health(
        [jnp.array([np.inf, 1.0], jnp.float16)]))
    assert not g.healthy


def test_combine_health():
    import jax.numpy as jnp

    parts = [numerics.grad_health([jnp.ones((2,), jnp.float32)]),
             numerics.grad_health([jnp.full((3,), 2.0, jnp.float32)])]
    h = np.asarray(numerics.combine_health(parts))
    assert h[0] == 1.0
    np.testing.assert_allclose(h[1], 2.0 + 12.0)
    bad = [parts[0],
           numerics.grad_health([jnp.array([np.nan], jnp.float32)])]
    assert np.asarray(numerics.combine_health(bad))[0] == 0.0


def test_step_guard_caches_single_readback():
    import jax.numpy as jnp

    g = StepGuard(numerics.grad_health([jnp.ones((4,), jnp.float32)]))
    numerics.reset_readback_count()
    assert g.healthy
    assert g.grad_norm == 2.0
    assert numerics.readback_count() == 1  # both reads share one sync
