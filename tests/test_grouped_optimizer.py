"""Grouped (multi-tensor) optimizer step: bitwise parity with the legacy
per-parameter loop, dispatch-count regression, bucketed all-reduce, and
Trainer.load_states validation."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.optimizer import grouped

SHAPES = [(5, 7), (3,), (2, 3, 4), (1,), (8, 2), (4, 4)]


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.get(k)
             for k in ("MXTPU_FUSED_STEP", "MXTPU_ALLREDUCE_BUCKET_MB")}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _make_params(dtype="float32", seed=0, lr_mults=None, wd_mults=None):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    params = []
    for k, shape in enumerate(SHAPES):
        p = gluon.Parameter(f"p{k}_weight", shape=shape, dtype=dtype)
        p.initialize(init=mx.init.Zero())
        p.data()._set_data(
            jnp.asarray(rng.standard_normal(shape).astype(dtype)))
        if lr_mults:
            p.lr_mult = lr_mults[k % len(lr_mults)]
        if wd_mults:
            p.wd_mult = wd_mults[k % len(wd_mults)]
        params.append(p)
    return params


def _run(optname, opt_kwargs, fused, dtype="float32", steps=5, seed=0,
         lr_mults=None, wd_mults=None):
    """Run `steps` Trainer.step calls with deterministic grads; return
    final weights (and optimizer states) as numpy."""
    import jax.numpy as jnp

    os.environ["MXTPU_FUSED_STEP"] = "1" if fused else "0"
    params = _make_params(dtype=dtype, seed=seed, lr_mults=lr_mults,
                          wd_mults=wd_mults)
    trainer = gluon.Trainer(params, optname, dict(opt_kwargs),
                            kvstore=None)
    rng = np.random.RandomState(seed + 1)
    for _ in range(steps):
        for p in params:
            g = rng.standard_normal(p.shape).astype(dtype)
            p.list_grad()[0]._set_data(jnp.asarray(g))
        trainer.step(2, ignore_stale_grad=True)
    return [p.data().asnumpy() for p in params]


CONFIGS = [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9,
             "clip_gradient": 0.5}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-3, "wd": 1e-4}),
    ("adam", {"learning_rate": 1e-3, "clip_gradient": 0.3}),
    ("adamw", {"learning_rate": 1e-3, "wd": 0.01}),
    ("rmsprop", {"learning_rate": 1e-3}),
    ("rmsprop", {"learning_rate": 1e-3, "centered": True}),
    ("adagrad", {"learning_rate": 0.1}),
    ("adadelta", {"rho": 0.9, "epsilon": 1e-5}),
    ("ftrl", {"learning_rate": 0.1, "lamda1": 0.01}),
    ("signum", {"learning_rate": 0.01, "momentum": 0.9, "wd_lh": 1e-5}),
    ("lamb", {"learning_rate": 1e-3}),
    ("lamb", {"learning_rate": 1e-3, "bias_correction": False,
              "lower_bound": 0.1, "upper_bound": 10.0}),
    ("lars", {"learning_rate": 0.1, "momentum": 0.9}),
    ("lbsgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("ftml", {"learning_rate": 1e-3}),
]


@pytest.mark.parametrize(
    "optname,kwargs", CONFIGS,
    ids=[f"{n}-{i}" for i, (n, _) in enumerate(CONFIGS)])
def test_grouped_bitwise_parity(optname, kwargs):
    fused = _run(optname, kwargs, fused=True)
    legacy = _run(optname, kwargs, fused=False)
    for f, l in zip(fused, legacy):
        np.testing.assert_array_equal(f, l)


def test_grouped_parity_fp16():
    fused = _run("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                 fused=True, dtype="float16")
    legacy = _run("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                  fused=False, dtype="float16")
    for f, l in zip(fused, legacy):
        np.testing.assert_array_equal(f, l)


def test_grouped_parity_lr_scheduler():
    kw = {"learning_rate": 0.2,
          "lr_scheduler": mx.lr_scheduler.FactorScheduler(
              step=2, factor=0.5)}
    fused = _run("sgd", dict(kw), fused=True)
    kw = {"learning_rate": 0.2,
          "lr_scheduler": mx.lr_scheduler.FactorScheduler(
              step=2, factor=0.5)}
    legacy = _run("sgd", dict(kw), fused=False)
    for f, l in zip(fused, legacy):
        np.testing.assert_array_equal(f, l)


def test_grouped_parity_lr_wd_mult():
    mults = dict(lr_mults=[1.0, 0.5, 2.0], wd_mults=[1.0, 0.0])
    fused = _run("sgd", {"learning_rate": 0.1, "wd": 1e-3}, fused=True,
                 **mults)
    legacy = _run("sgd", {"learning_rate": 0.1, "wd": 1e-3}, fused=False,
                  **mults)
    for f, l in zip(fused, legacy):
        np.testing.assert_array_equal(f, l)


# -- dispatch-count regression -------------------------------------------------

def _step_once(params, trainer, seed=0):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    for p in params:
        dtype = p.data().asnumpy().dtype
        g = rng.standard_normal(p.shape).astype(dtype)
        p.list_grad()[0]._set_data(jnp.asarray(g))
    trainer.step(1, ignore_stale_grad=True)


def test_one_dispatch_per_group_per_step():
    os.environ["MXTPU_FUSED_STEP"] = "1"
    params = _make_params()
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                            kvstore=None)
    grouped.reset_dispatch_count()
    for step in range(3):
        _step_once(params, trainer, seed=step)
        # all params share (kernel, hyper-params, f32) -> ONE program
        assert grouped.dispatch_count() == step + 1


def test_two_dispatches_for_mixed_dtypes():
    import jax.numpy as jnp

    os.environ["MXTPU_FUSED_STEP"] = "1"
    params = _make_params(dtype="float32")
    params += _make_params(dtype="float16", seed=7)
    # re-wrap with unique names for the trainer's param2idx map
    named = []
    for i, p in enumerate(params):
        q = gluon.Parameter(f"q{i}_weight", shape=p.shape,
                            dtype=p.data().asnumpy().dtype)
        q.initialize(init=mx.init.Zero())
        q.data()._set_data(jnp.asarray(p.data().asnumpy()))
        named.append(q)
    trainer = gluon.Trainer(
        named, "sgd", {"learning_rate": 0.1, "momentum": 0.9,
                       "multi_precision": False}, kvstore=None)
    grouped.reset_dispatch_count()
    _step_once(named, trainer)
    assert grouped.dispatch_count() == 2  # one f32 group + one f16 group


def test_lars_two_groups():
    # 1-D params take the plain momentum kernel, >=2-D the LARS kernel
    os.environ["MXTPU_FUSED_STEP"] = "1"
    params = _make_params()
    trainer = gluon.Trainer(params, "lars",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore=None)
    grouped.reset_dispatch_count()
    _step_once(params, trainer)
    assert grouped.dispatch_count() == 2


def test_fallback_optimizer_zero_dispatches():
    os.environ["MXTPU_FUSED_STEP"] = "1"
    params = _make_params()
    before = [p.data().asnumpy().copy() for p in params]
    trainer = gluon.Trainer(params, "nadam", {"learning_rate": 1e-3},
                            kvstore=None)
    grouped.reset_dispatch_count()
    _step_once(params, trainer)
    assert grouped.dispatch_count() == 0  # no _PLANS entry -> legacy loop
    for b, p in zip(before, params):
        assert not np.array_equal(b, p.data().asnumpy())


def test_env_gate_restores_legacy():
    os.environ["MXTPU_FUSED_STEP"] = "0"
    params = _make_params()
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                            kvstore=None)
    grouped.reset_dispatch_count()
    _step_once(params, trainer)
    assert grouped.dispatch_count() == 0


def test_subclass_falls_back():
    # exact-type dispatch: a subclass may override update() arbitrarily
    class MySGD(mx.optimizer.SGD):
        pass

    os.environ["MXTPU_FUSED_STEP"] = "1"
    params = _make_params()
    trainer = gluon.Trainer(params, MySGD(learning_rate=0.1),
                            kvstore=None)
    grouped.reset_dispatch_count()
    _step_once(params, trainer)
    assert grouped.dispatch_count() == 0


# -- state sharing / save-load -------------------------------------------------

def test_states_shared_with_legacy_updater():
    os.environ["MXTPU_FUSED_STEP"] = "1"
    params = _make_params()
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                            kvstore=None)
    _step_once(params, trainer)
    upd = trainer._updaters[0]
    assert set(upd.states.keys()) == set(range(len(params)))
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "trainer.states")
        trainer.save_states(fname)
        trainer.load_states(fname)
    _step_once(params, trainer, seed=1)


def test_fused_then_legacy_continuation():
    """Switching the flag mid-run must keep stepping the SAME states."""
    import jax.numpy as jnp

    finals = []
    for flip_at in (None, 2):
        os.environ["MXTPU_FUSED_STEP"] = "0" if flip_at is None else "1"
        params = _make_params()
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                                kvstore=None)
        rng = np.random.RandomState(1)
        for step in range(4):
            if flip_at is not None and step == flip_at:
                os.environ["MXTPU_FUSED_STEP"] = "0"
            for p in params:
                g = rng.standard_normal(p.shape).astype("float32")
                p.list_grad()[0]._set_data(jnp.asarray(g))
            trainer.step(2, ignore_stale_grad=True)
        finals.append([p.data().asnumpy() for p in params])
    for a, b in zip(*finals):
        np.testing.assert_array_equal(a, b)


# -- Trainer.load_states validation (satellite #6) -----------------------------

def _trained_state_file(d, n_params=3, shape=(4, 3)):
    params = [gluon.Parameter(f"w{i}", shape=shape, dtype="float32")
              for i in range(n_params)]
    for p in params:
        p.initialize(init=mx.init.Uniform())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                            kvstore=None)
    _step_once(params, trainer)
    fname = os.path.join(d, "trainer.states")
    trainer.save_states(fname)
    return fname


def test_load_states_count_mismatch():
    with tempfile.TemporaryDirectory() as d:
        fname = _trained_state_file(d, n_params=3)
        params = [gluon.Parameter("w0", shape=(4, 3), dtype="float32")]
        params[0].initialize(init=mx.init.Uniform())
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                                kvstore=None)
        with pytest.raises(MXNetError, match="parameter list changed"):
            trainer.load_states(fname)


def test_load_states_shape_mismatch():
    with tempfile.TemporaryDirectory() as d:
        fname = _trained_state_file(d, n_params=2, shape=(4, 3))
        params = [gluon.Parameter(f"w{i}", shape=(5, 2), dtype="float32")
                  for i in range(2)]
        for p in params:
            p.initialize(init=mx.init.Uniform())
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                                kvstore=None)
        with pytest.raises(MXNetError, match="does not match the "
                                             "parameter shape"):
            trainer.load_states(fname)


def test_load_states_roundtrip_ok():
    with tempfile.TemporaryDirectory() as d:
        params = [gluon.Parameter(f"w{i}", shape=(4, 3), dtype="float32")
                  for i in range(3)]
        for p in params:
            p.initialize(init=mx.init.Uniform())
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                                kvstore=None)
        _step_once(params, trainer)
        fname = os.path.join(d, "trainer.states")
        trainer.save_states(fname)
        trainer.load_states(fname)  # same param list: no error


# -- bucketed all-reduce -------------------------------------------------------

def _kv_with_keys(n=6, seed=0, kv_type="local"):
    from mxnet_tpu import kvstore as kvs

    rng = np.random.RandomState(seed)
    kv = kvs.create(kv_type)
    vals = []
    for k in range(n):
        shape = SHAPES[k % len(SHAPES)]
        v = mx.nd.array(rng.standard_normal(shape).astype("float32"))
        kv.init(k, v)
        vals.append(v)
    return kv, vals


def test_bucketed_pushpull_matches_per_key():
    rng = np.random.RandomState(3)
    grads = [rng.standard_normal(SHAPES[k % len(SHAPES)])
             .astype("float32") for k in range(6)]

    kv1, _ = _kv_with_keys()
    outs1 = [mx.nd.array(g) for g in grads]
    for k, v in enumerate(outs1):
        kv1.pushpull(k, v, out=v)

    kv2, _ = _kv_with_keys()
    outs2 = [mx.nd.array(g) for g in grads]
    kv2.bucketed_pushpull(list(range(6)), outs2, outs=outs2)

    for a, b in zip(outs1, outs2):
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


def test_bucketed_pushpull_tiny_buckets():
    # a 100-byte budget forces one bucket per key; results must not change
    os.environ["MXTPU_ALLREDUCE_BUCKET_MB"] = "0.0001"
    rng = np.random.RandomState(4)
    grads = [rng.standard_normal(SHAPES[k % len(SHAPES)])
             .astype("float32") for k in range(6)]

    kv1, _ = _kv_with_keys()
    outs1 = [mx.nd.array(g) for g in grads]
    for k, v in enumerate(outs1):
        kv1.pushpull(k, v, out=v)

    kv2, _ = _kv_with_keys()
    outs2 = [mx.nd.array(g) for g in grads]
    kv2.bucketed_pushpull(list(range(6)), outs2, outs=outs2)

    for a, b in zip(outs1, outs2):
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


def test_bucketed_pushpull_device_lists():
    # multi-device value lists are summed per key, like pushpull
    kv, _ = _kv_with_keys(n=2)
    a = mx.nd.array(np.ones((5, 7), np.float32))
    b = mx.nd.array(np.full((5, 7), 2.0, np.float32))
    c = mx.nd.array(np.ones((3,), np.float32))
    out0 = mx.nd.array(np.zeros((5, 7), np.float32))
    out1 = mx.nd.array(np.zeros((3,), np.float32))
    kv.bucketed_pushpull([0, 1], [[a, b], [c]], outs=[[out0], [out1]])
    np.testing.assert_array_equal(out0.asnumpy(),
                                  np.full((5, 7), 3.0, np.float32))
    np.testing.assert_array_equal(out1.asnumpy(),
                                  np.ones((3,), np.float32))


def test_bucketed_pushpull_uninit_key():
    kv, _ = _kv_with_keys(n=2)
    v = mx.nd.array(np.zeros((5, 7), np.float32))
    with pytest.raises(MXNetError):
        kv.bucketed_pushpull([99], [v], outs=[v])


def test_bucketed_pushpull_compression_fallback():
    # active compression keeps per-key error-feedback residuals: the
    # bucketed entry point must give the same answer as per-key pushpull
    from mxnet_tpu import kvstore as kvs

    rng = np.random.RandomState(5)
    grads = [rng.standard_normal((5, 7)).astype("float32")
             for _ in range(3)]

    results = []
    for _ in range(2):
        kv = kvs.create("device")
        kv.set_gradient_compression({"type": "fp16"})
        for k in range(3):
            kv.init(k, mx.nd.array(np.zeros((5, 7), np.float32)))
        results.append(kv)
    kv1, kv2 = results

    outs1 = [mx.nd.array(g) for g in grads]
    for k, v in enumerate(outs1):
        kv1.pushpull(k, v, out=v)
    outs2 = [mx.nd.array(g) for g in grads]
    kv2.bucketed_pushpull([0, 1, 2], outs2, outs=outs2)
    for a, b in zip(outs1, outs2):
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


def test_trainer_uses_bucketed_allreduce(monkeypatch):
    """Trainer._allreduce_grads routes through bucketed_pushpull when the
    fused path is on and the kvstore supports it."""
    from mxnet_tpu import kvstore as kvs

    os.environ["MXTPU_FUSED_STEP"] = "1"
    params = _make_params()
    kv = kvs.create("local")
    calls = []
    orig = kv.bucketed_pushpull

    def spy(keys, values, outs=None, priority=0):
        calls.append(list(keys))
        return orig(keys, values, outs=outs, priority=priority)

    monkeypatch.setattr(kv, "bucketed_pushpull", spy)
    # force the trainer to keep the local store (it normally drops it
    # for a single worker)
    from mxnet_tpu.gluon import trainer as trainer_mod
    monkeypatch.setattr(trainer_mod, "kvstore_requires_store",
                        lambda _kv: True)
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore=kv, update_on_kvstore=False)
    _step_once(params, trainer)
    assert calls and calls[0] == list(range(len(params)))
