"""Tensor-parallel + FSDP training through the captured step
(parallel/sharding.py shard_model + gluon/captured.py).

Everything runs on the virtual 8-device CPU mesh (conftest forces
``--xla_force_host_platform_device_count=8``), so these sharding paths
execute on every tier-1 pass.  The load-bearing claims:

- `shard_model` places params, grads and optimizer state per the rules,
  in both TP and FSDP modes, and a model too big for one device's
  budget fits per-device once sharded;
- the sharded captured path stays ONE dispatch + ONE readback per
  healthy step (the PR 6 regression discipline, extended to tp>1);
- dp-only sharded runs are bitwise equal to the eager oracle
  (``MXTPU_CAPTURED_STEP=0``) on the same mesh.
"""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

import mxnet_tpu as mx
from mxnet_tpu import gluon, numerics, parallel, telemetry
from mxnet_tpu.gluon import captured, nn
from mxnet_tpu.gluon.model_zoo.bert import TransformerEncoder
from mxnet_tpu.optimizer import grouped


def _transformer(layers=2, units=32, hidden=64, seed=7):
    mx.random.seed(seed)
    net = TransformerEncoder(num_layers=layers, units=units,
                             num_heads=4, hidden_size=hidden,
                             dropout=0.0)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    return net


def _train(net, steps=3, n=8, t=6, units=32, seed=3):
    rng = np.random.RandomState(seed)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    losses = []
    for _ in range(steps):
        x = mx.nd.array(rng.normal(size=(n, t, units)).astype(np.float32))
        y = mx.nd.array(rng.randint(0, units, size=(n, t))
                        .astype(np.float32))
        losses.append(tr.train_step(net, loss_fn, x, y).asnumpy())
    return tr, losses


def _assert_state_sharded_like_weight(trainer, p, i):
    w = p.data()._data
    st = trainer._updaters[0].states[i]
    leaves = st if isinstance(st, (list, tuple)) else [st]
    for s in leaves:
        if hasattr(s, "_data") and s.shape == p.shape:
            assert s._data.sharding.is_equivalent_to(
                w.sharding, s._data.ndim), \
                f"state of param {i} not sharded like its weight"


def _per_device_param_bytes(net):
    """Bytes of parameter shards resident on ONE device (uniform across
    the mesh), plus the total across all params unsharded."""
    per_dev = total = 0
    for p in net.collect_params().values():
        w = p.data()._data
        itemsize = np.dtype(w.dtype).itemsize
        total += int(np.prod(w.shape)) * itemsize
        shard = w.sharding.shard_shape(w.shape)
        per_dev += int(np.prod(shard)) * itemsize
    return per_dev, total


# -- placement: TP and FSDP modes ----------------------------------------------

def test_shard_model_tp_places_params_grads_state(mesh8):
    mesh = mesh8(dp=2, tp=4)
    net = _transformer(layers=1)
    specs = parallel.shard_model(net, mesh, mode="tp")
    assert any("tp" in tuple(s) for s in specs.values())
    tr, losses = _train(net)
    assert all(np.isfinite(l).all() for l in losses)
    params = list(net.collect_params().items())
    tp_seen = 0
    for i, (name, p) in enumerate(params):
        w = p.data()._data
        assert isinstance(w.sharding, NamedSharding)
        assert tuple(w.sharding.spec) == tuple(specs[name])
        if "tp" in tuple(specs[name]):
            tp_seen += 1
        _assert_state_sharded_like_weight(tr, p, i)
    assert tp_seen >= 6  # qkv/proj/ffn1/ffn2 weights+biases per layer


def test_shard_model_fsdp_places_params_grads_state(mesh8):
    mesh = mesh8(dp=8)
    net = _transformer(layers=1)
    specs = parallel.shard_model(net, mesh, mode="fsdp", min_size=64)
    assert any("dp" in tuple(s) for s in specs.values())
    tr, losses = _train(net)
    assert all(np.isfinite(l).all() for l in losses)
    for i, (name, p) in enumerate(net.collect_params().items()):
        w = p.data()._data
        assert tuple(w.sharding.spec) == tuple(specs[name])
        _assert_state_sharded_like_weight(tr, p, i)


def test_shard_model_eager_grads_shard_with_weights(mesh8, monkeypatch):
    """Eager-oracle backward writes gradients whose shardings match the
    weights' — GSPMD inference from committed placements alone."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "0")
    mesh = mesh8(dp=2, tp=4)
    net = _transformer(layers=1)
    specs = parallel.shard_model(net, mesh, mode="tp")
    _train(net, steps=1)
    checked = 0
    for name, p in net.collect_params().items():
        if "tp" not in tuple(specs[name]) or p._grad is None:
            continue
        g, w = p._grad._data, p.data()._data
        assert g.sharding.is_equivalent_to(w.sharding, g.ndim), \
            f"grad of {name}: {g.sharding.spec} vs {w.sharding.spec}"
        checked += 1
    assert checked >= 6


def test_shard_model_aux_params_stay_replicated(mesh8):
    """FSDP's shape heuristic must not shard BatchNorm running stats:
    grad_req='null' params are forced replicated."""
    mesh = mesh8(dp=8)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu", in_units=32))
        net.add(nn.BatchNorm(axis=1))
        net.add(nn.Dense(8, in_units=64))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    net(mx.nd.array(np.random.randn(8, 32).astype(np.float32)))
    specs = parallel.shard_model(net, mesh, mode="fsdp", min_size=16)
    for name, p in net.collect_params().items():
        if p.grad_req == "null":
            assert tuple(specs[name]) == ()
            assert p.data()._data.sharding.is_fully_replicated


def test_shard_model_rejects_unknown_mode(mesh8):
    with pytest.raises(ValueError):
        parallel.shard_model(_transformer(), mesh8(dp=8), mode="zp")


# -- acceptance: over-budget model fits per-device sharded ---------------------

@pytest.mark.parametrize("mode,axes", [("tp", dict(dp=2, tp=4)),
                                       ("fsdp", dict(dp=8))])
def test_over_budget_transformer_trains_sharded(mesh8, mode, axes):
    """A transformer whose total parameter bytes EXCEED a one-device
    budget trains on the 8-device mesh with per-device shard bytes
    UNDER it — the whole point of model parallelism, checked with a
    budget set between per-device and total."""
    mesh = mesh8(**axes)
    net = _transformer(layers=2, units=64, hidden=256)
    parallel.shard_model(net, mesh, mode=mode)
    per_dev, total = _per_device_param_bytes(net)
    budget = total // 2
    assert total > budget          # does NOT fit unsharded
    assert per_dev <= budget       # fits sharded
    tr, losses = _train(net, units=64)
    assert all(np.isfinite(l).all() for l in losses)


# -- captured-path regression discipline at tp>1 -------------------------------

def test_one_dispatch_one_readback_per_step_tp(mesh8, monkeypatch):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    mesh = mesh8(dp=2, tp=4)
    net = _transformer(layers=1)
    parallel.shard_model(net, mesh, mode="tp")
    rng = np.random.RandomState(5)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    mk = lambda: (mx.nd.array(rng.normal(size=(8, 6, 32))
                              .astype(np.float32)),
                  mx.nd.array(rng.randint(0, 32, size=(8, 6))
                              .astype(np.float32)))
    for _ in range(2):  # warmup: trace + compile
        x, y = mk()
        tr.train_step(net, loss_fn, x, y)
    captured.reset_counters()
    grouped.reset_dispatch_count()
    numerics.reset_readback_count()
    for _ in range(4):
        x, y = mk()
        tr.train_step(net, loss_fn, x, y)
    assert captured.dispatch_count() == 4
    assert grouped.dispatch_count() == 0
    assert numerics.readback_count() == 4
    assert captured.trace_count() == 0
    assert captured.cache_stats() == {"hits": 4, "misses": 0}


def test_resharding_misses_capture_cache(mesh8, monkeypatch):
    """Moving a model onto a mesh (or a different layout) must MISS the
    capture cache: the old program's layouts are stale."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    net = _transformer(layers=1)
    tr, _ = _train(net, steps=1)
    captured.reset_counters()
    mesh = mesh8(dp=2, tp=4)
    parallel.shard_model(net, mesh, mode="tp", trainer=tr)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    rng = np.random.RandomState(9)
    x = mx.nd.array(rng.normal(size=(8, 6, 32)).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 32, size=(8, 6)).astype(np.float32))
    tr.train_step(net, loss_fn, x, y)
    assert captured.cache_stats()["misses"] == 1


# -- dp-only bitwise parity with the eager oracle ------------------------------

def _run_dp_sharded(monkeypatch, captured_on, steps=6):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP",
                       "1" if captured_on else "0")
    np.random.seed(0)
    mesh = parallel.make_mesh(dp=8)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(3, in_units=16))
    mx.random.seed(11)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    parallel.shard_model(net, mesh, mode="fsdp", min_size=8)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    rng = np.random.RandomState(42)
    losses, weights = [], None
    for _ in range(steps):
        x = mx.nd.array(rng.normal(size=(16, 8)).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 3, size=(16,)).astype(np.float32))
        losses.append(tr.train_step(net, loss_fn, x, y).asnumpy())
    weights = [p.data().asnumpy() for p in tr._params]
    parallel.set_default_mesh(None)
    return losses, weights


@pytest.mark.parametrize("guard", ["1", "0"])
def test_dp_sharded_bitwise_captured_vs_eager(mesh8, monkeypatch, guard):
    """dp-only sharded: captured program == eager oracle on the same
    mesh, bitwise, guard on and off (the guard-off eager oracle
    discipline extended to sharded placements)."""
    monkeypatch.setenv("MXTPU_GRAD_GUARD", guard)
    le, we = _run_dp_sharded(monkeypatch, False)
    lc, wc = _run_dp_sharded(monkeypatch, True)
    for s, (a, b) in enumerate(zip(le, lc)):
        np.testing.assert_array_equal(a, b, err_msg=f"loss step {s}")
    for i, (a, b) in enumerate(zip(we, wc)):
        np.testing.assert_array_equal(a, b, err_msg=f"weight {i}")


def test_dp_sharded_matches_single_device_allclose(mesh8, monkeypatch):
    """Sanity anchor: the sharded run computes the same math as the
    unsharded single-device run (allclose — reduction orders differ)."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    lc, wc = _run_dp_sharded(monkeypatch, True)

    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(3, in_units=16))
    mx.random.seed(11)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    rng = np.random.RandomState(42)
    ls = []
    for _ in range(6):
        x = mx.nd.array(rng.normal(size=(16, 8)).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 3, size=(16,)).astype(np.float32))
        ls.append(tr.train_step(net, loss_fn, x, y).asnumpy())
    for a, b in zip(ls, lc):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    for (a, b) in zip([p.data().asnumpy() for p in tr._params], wc):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


# -- activation annotations ----------------------------------------------------

def test_shard_activations_constrains_output(mesh8):
    mesh = mesh8(dp=2, tp=4)
    net = nn.Dense(16, in_units=8)
    net.initialize(mx.init.Xavier())
    net.shard_activations(("dp", "tp"), mesh)
    x = mx.nd.array(np.random.randn(4, 8).astype(np.float32))
    out = net(x)
    sh = out._data.sharding
    assert isinstance(sh, NamedSharding)
    assert tuple(sh.spec) == ("dp", "tp")


def test_shard_activations_noop_without_mesh():
    net = nn.Dense(16, in_units=8)
    net.initialize(mx.init.Xavier())
    net.shard_activations(("dp", "tp"))  # default mesh: None
    parallel.set_default_mesh(None)
    x = mx.nd.array(np.random.randn(4, 8).astype(np.float32))
    out = net(x)
    assert out.shape == (4, 16)


def test_annotate_activations_by_block_name(mesh8):
    mesh = mesh8(dp=8)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(3, in_units=16))
    rules = parallel.ShardingRules(rules=[(r"dense0", ("dp",))])
    parallel.annotate_activations(net, rules, mesh)
    assert net[0]._act_spec is not None
    assert net[1]._act_spec is None


# -- telemetry: per-axis collective bytes + memory high-water ------------------

def test_sharded_step_telemetry_fields(mesh8, monkeypatch):
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    mesh = mesh8(dp=2, tp=4)
    net = _transformer(layers=1)
    parallel.shard_model(net, mesh, mode="tp")
    _train(net, steps=3)
    recs = [r for r in telemetry.recent_steps()
            if r.get("path") == "captured"]
    assert recs
    rec = recs[-1]
    telemetry.validate_record(rec)
    assert rec.get("device_peak_bytes", 0) > 0
    coll = rec.get("collective_bytes_by_axis")
    assert isinstance(coll, dict) and coll
    # Megatron TP moves bytes over the tp axis inside the step
    assert coll.get("tp", 0) > 0
    for v in coll.values():
        assert isinstance(v, int) and v >= 0
