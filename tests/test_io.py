"""IO tests (reference: tests/python/unittest/test_io.py,
test_recordio.py, test_gluon_data.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, recordio


def test_ndarray_iter_basic():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    labels = np.arange(10).astype(np.float32)
    it = mx.io.NDArrayIter(data, labels, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 4)
    np.testing.assert_allclose(batches[0].label[0].asnumpy(),
                               labels[:5])


def test_ndarray_iter_pad_and_discard():
    data = np.zeros((7, 2), dtype=np.float32)
    it = mx.io.NDArrayIter(data, np.zeros(7), batch_size=3,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    assert batches[-1].data[0].shape == (3, 2)

    it = mx.io.NDArrayIter(data, np.zeros(7), batch_size=3,
                           last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_shuffle_covers_all():
    data = np.arange(12).reshape(12, 1).astype(np.float32)
    it = mx.io.NDArrayIter(data, np.arange(12), batch_size=4, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(12))


def test_ndarray_iter_dict_input():
    it = mx.io.NDArrayIter({"a": np.zeros((6, 2)), "b": np.ones((6, 3))},
                           np.zeros(6), batch_size=2)
    names = [d.name for d in it.provide_data]
    assert names == ["a", "b"]


def test_resize_iter():
    data = np.zeros((10, 2), dtype=np.float32)
    base = mx.io.NDArrayIter(data, np.zeros(10), batch_size=5)
    resized = mx.io.ResizeIter(base, 5)
    assert len(list(resized)) == 5


def test_prefetching_iter():
    data = np.random.rand(20, 3).astype(np.float32)
    base = mx.io.NDArrayIter(data, np.zeros(20), batch_size=5)
    pre = mx.io.PrefetchingIter(base)
    batches = list(pre)
    assert len(batches) == 4
    pre.reset()
    assert len(list(pre)) == 4


def test_csviter(tmp_path):
    data = np.random.rand(8, 3).astype(np.float32)
    labels = np.arange(8).astype(np.float32)
    data_csv = tmp_path / "data.csv"
    label_csv = tmp_path / "label.csv"
    np.savetxt(data_csv, data, delimiter=",")
    np.savetxt(label_csv, labels, delimiter=",")
    it = mx.io.CSVIter(data_csv=str(data_csv), data_shape=(3,),
                       label_csv=str(label_csv), batch_size=4)
    batch = next(iter(it))
    np.testing.assert_allclose(batch.data[0].asnumpy(), data[:4],
                               rtol=1e-5)


def _make_rec(tmp_path, n=8, size=(32, 48), fmt="jpeg"):
    """Synthesize a .rec of n images with labels 0..n-1."""
    import io as _io

    from PIL import Image

    path = str(tmp_path / f"imgs_{fmt}.rec")
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(path, "w")
    raws = []
    for i in range(n):
        arr = rng.randint(0, 255, size + (3,)).astype(np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format=fmt)
        payload = buf.getvalue()
        raws.append(payload)
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write(recordio.pack(header, payload))
    w.close()
    return path, raws


def test_image_record_iter_native_decode(tmp_path):
    """Native libjpeg batch path: bit-identical to the PIL fallback when
    no resize is involved (same libjpeg decode, same crop/normalize
    math); close under resize (native = OpenCV-convention bilinear like
    the reference, PIL = filtered bilinear).  Labels must pair up across
    multiple batches."""
    from mxnet_tpu import _native
    from mxnet_tpu.io import ImageRecordIter

    # exact path: images bigger than crop, no resize
    path, _ = _make_rec(tmp_path, n=6, size=(40, 56))
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=3,
              mean_r=0.3, std_r=1.1, scale=1 / 255.0)
    it = ImageRecordIter(**kw)
    batches = [it.next() for _ in range(2)]
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    np.testing.assert_array_equal(np.sort(labels), np.arange(6))
    if _native.has_jpeg():
        it2 = ImageRecordIter(**kw)
        got = it2.next().data[0].asnumpy()
        py = np.stack([
            it2._decode_one(p, False)
            for p in _collect_payloads(path)[:3]])
        np.testing.assert_allclose(got, py, atol=1e-6)
        # resize path: algorithms differ by design; catch gross errors
        it3 = ImageRecordIter(resize=36, **kw)
        got3 = it3.next().data[0].asnumpy()
        py3 = np.stack([
            it3._decode_one(p, False)
            for p in _collect_payloads(path)[:3]])
        # noise images are the worst case for filter differences;
        # this bounds gross errors (wrong crop/channel order would be
        # >0.2 mean), not codec agreement
        assert np.mean(np.abs(got3 - py3)) < 10 / 255


def test_native_resize_no_geometric_offset(tmp_path):
    """VERDICT r3 Weak #9: the noise-image tolerance (mean |Δ| < 10/255)
    could hide a half-pixel crop/offset.  A smooth linear ramp is nearly
    filter-invariant under bilinear resize, so native-vs-PIL must agree
    TIGHTLY in the interior — a half-pixel geometric offset on this ramp
    would show up as a uniform ~0.5·slope shift and fail."""
    import io as _io

    from PIL import Image

    from mxnet_tpu import _native, recordio
    from mxnet_tpu.io import ImageRecordIter

    if not _native.has_jpeg():
        pytest.skip("native decode lib not built")
    h, w_ = 48, 64
    ramp = np.tile(np.linspace(0, 255, w_, dtype=np.float32),
                   (h, 1)).astype(np.uint8)
    img = np.stack([ramp, ramp[:, ::-1], ramp], axis=-1)  # R→, G←, B→
    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="jpeg", quality=95)
    path = str(tmp_path / "ramp.rec")
    wrt = recordio.MXRecordIO(path, "w")
    wrt.write(recordio.pack(recordio.IRHeader(0, 0.0, 0, 0),
                            buf.getvalue()))
    wrt.close()

    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=1,
              resize=40, scale=1 / 255.0)
    it = ImageRecordIter(**kw)
    native = it.next().data[0].asnumpy()[0]            # (3, 32, 32)
    py = it._decode_one(_collect_payloads(path)[0], False)
    inner = (slice(None), slice(2, -2), slice(2, -2))
    diff = np.abs(native[inner] - py[inner])
    # slope after resize ≈ (255/64)·(64/40)/255 ≈ 0.016/px: a half-pixel
    # offset would shift the ramp by ~0.008 uniformly; demand ≤ 0.004
    assert diff.mean() < 0.004, diff.mean()
    assert diff.max() < 0.04, diff.max()
    # orientation: R increases left→right, G decreases (flip detector)
    assert native[0, 16, -3] > native[0, 16, 2] + 0.2
    assert native[1, 16, 2] > native[1, 16, -3] + 0.2


def _collect_payloads(path):
    r = recordio.MXRecordIO(path, "r")
    out = []
    while True:
        rec = r.read()
        if rec is None:
            break
        out.append(recordio.unpack(rec)[1])
    return out


def test_image_record_iter_png_fallback(tmp_path):
    """PNG payloads can't go through libjpeg — the per-image python
    fallback must kick in transparently."""
    from mxnet_tpu.io import ImageRecordIter

    path, _ = _make_rec(tmp_path, n=4, size=(32, 32), fmt="png")
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 32, 32),
                         batch_size=4)
    b = it.next()
    assert b.data[0].shape == (4, 3, 32, 32)
    assert np.isfinite(b.data[0].asnumpy()).all()


def test_native_jpeg_feature_flag():
    """runtime.Features JPEG_TURBO must reflect the built library."""
    import mxnet_tpu as mx
    from mxnet_tpu import _native

    feats = mx.runtime.Features()
    assert feats["JPEG_TURBO"].enabled == _native.has_jpeg()


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"world" * 3]
    for p in payloads:
        writer.write(p)
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for expected in payloads:
        assert reader.read() == expected
    assert reader.read() is None


def test_recordio_magic_embedded(tmp_path):
    """Payload containing the magic must survive (continuation framing)."""
    import struct

    path = str(tmp_path / "magic.rec")
    payload = b"abc" + struct.pack("<I", 0xced7230a) + b"def"
    w = recordio.MXRecordIO(path, "w")
    w.write(payload)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == payload


def test_recordio_magic_torture(tmp_path):
    """dmlc-core split semantics: aligned embedded magics are excised on
    write and re-inserted on read; unaligned ones pass through.  Python
    and C++ codecs must produce byte-identical files (reference:
    3rdparty/dmlc-core/src/recordio.cc WriteRecord/NextRecord)."""
    import struct

    magic = struct.pack("<I", 0xced7230a)
    recs = [
        b"hello world",
        magic,                      # record that IS a magic
        b"ab" + magic + b"cd",      # unaligned magic (kept inline)
        b"abcd" + magic + b"efgh",  # aligned magic (excised)
        magic + magic + b"tail",    # consecutive aligned magics
        b"xyz1" + magic,            # aligned magic at end
        b"",
        bytes(range(256)) * 4 + magic * 3,
    ]
    path = str(tmp_path / "torture.rec")
    w = recordio.MXRecordIO(path, "w")
    for rec in recs:
        w.write(rec)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for expected in recs:
        assert r.read() == expected
    assert r.read() is None

    from mxnet_tpu import _native

    if _native.available():
        nr = _native.NativeRecordReader(path)
        offs = nr.scan()
        assert [nr.read_at(o) for o in offs] == recs
        nr.close()
        cc_path = str(tmp_path / "torture_cc.rec")
        nw = _native.NativeRecordWriter(cc_path)
        for rec in recs:
            nw.write(rec)
        nw.close()
        with open(path, "rb") as f1, open(cc_path, "rb") as f2:
            assert f1.read() == f2.read()


def test_indexed_recordio(tmp_path):
    rec = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(5):
        writer.write_idx(i, f"record{i}".encode())
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert reader.read_idx(3) == b"record3"
    assert reader.read_idx(0) == b"record0"
    assert reader.keys == list(range(5))


def test_irheader_pack_unpack():
    header = recordio.IRHeader(0, 42.0, 7, 0)
    packed = recordio.pack(header, b"payload")
    h2, payload = recordio.unpack(packed)
    assert h2.label == 42.0
    assert h2.id == 7
    assert payload == b"payload"
    # multi-label
    header = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0]), 1, 0)
    h3, payload = recordio.unpack(recordio.pack(header, b"x"))
    np.testing.assert_allclose(h3.label, [1.0, 2.0, 3.0])


def test_pack_img_unpack_img():
    img = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
    s = recordio.pack_img((0, 5.0, 1, 0), img, quality=100, img_fmt=".png")
    header, decoded = recordio.unpack_img(s)
    assert header.label == 5.0
    np.testing.assert_array_equal(decoded, img)


def test_image_record_iter(tmp_path):
    from mxnet_tpu.io import ImageRecordIter

    rec = str(tmp_path / "imgs.rec")
    writer = recordio.MXRecordIO(rec, "w")
    for i in range(8):
        img = (np.random.rand(12, 12, 3) * 255).astype(np.uint8)
        writer.write(recordio.pack_img((0, float(i % 2), i, 0), img,
                                       img_fmt=".png"))
    writer.close()
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 8, 8),
                         batch_size=4)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 8, 8)
    assert batch.label[0].shape == (4,)


def test_native_recordio_interop(tmp_path):
    """C++ codec (src/recordio.cc) ↔ python codec byte compatibility."""
    from mxnet_tpu import _native

    if not _native.available():
        pytest.skip("native library not built (make -C src)")
    import struct

    path = str(tmp_path / "n.rec")
    payloads = [b"hello", b"x" * 999, b"",
                b"abc" + struct.pack("<I", 0xced7230a) + b"def"]
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = _native.NativeRecordReader(path)
    offsets = r.scan()
    assert len(offsets) == len(payloads)
    for off, exp in zip(offsets, payloads):
        assert r.read_at(off) == exp
    r.close()

    path2 = str(tmp_path / "n2.rec")
    w2 = _native.NativeRecordWriter(path2)
    for p in payloads:
        w2.write(p)
    w2.close()
    rd = recordio.MXRecordIO(path2, "r")
    for exp in payloads:
        assert rd.read() == exp
    assert rd.read() is None


def test_native_prefetcher(tmp_path):
    from mxnet_tpu import _native

    if not _native.available():
        pytest.skip("native library not built (make -C src)")
    path = str(tmp_path / "p.rec")
    payloads = [f"record{i}".encode() for i in range(20)]
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    pf = _native.NativePrefetcher(path, n_threads=3)
    assert len(pf) == 20
    got = []
    while True:
        rec = pf.next()
        if rec is None:
            break
        got.append(rec)
    assert got == payloads
    pf.reset(seed=1)
    got2 = [pf.next() for _ in range(20)]
    assert got2 == payloads
    pf.close()


def test_gluon_dataset_and_dataloader():
    data = np.random.rand(20, 5).astype(np.float32)
    labels = np.arange(20).astype(np.float32)
    ds = gluon.data.ArrayDataset(data, labels)
    assert len(ds) == 20
    x, y = ds[3]
    np.testing.assert_allclose(x, data[3])

    loader = gluon.data.DataLoader(ds, batch_size=6, shuffle=False,
                                   last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 5)
    assert batches[-1][0].shape == (2, 5)


def test_dataloader_workers():
    data = np.random.rand(16, 3).astype(np.float32)
    ds = gluon.data.ArrayDataset(data, np.zeros(16, dtype=np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=2)
    batches = list(loader)
    assert len(batches) == 4
    total = np.concatenate([b[0].asnumpy() for b in batches])
    np.testing.assert_allclose(np.sort(total.ravel()),
                               np.sort(data.ravel()), rtol=1e-6)


def test_dataset_transform():
    ds = gluon.data.SimpleDataset(list(range(10)))
    doubled = ds.transform(lambda x: x * 2)
    assert doubled[4] == 8
    ds2 = gluon.data.ArrayDataset(np.ones((4, 2)), np.zeros(4))
    t = ds2.transform_first(lambda x: x + 1)
    x, y = t[0]
    np.testing.assert_allclose(x, 2 * np.ones(2))


def test_sampler_batch():
    s = gluon.data.BatchSampler(gluon.data.SequentialSampler(10), 3,
                                "keep")
    assert list(s) == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    s = gluon.data.BatchSampler(gluon.data.SequentialSampler(10), 3,
                                "discard")
    assert len(list(s)) == 3
    s = gluon.data.BatchSampler(gluon.data.SequentialSampler(10), 3,
                                "rollover")
    assert len(list(s)) == 3
    assert list(s)[0] == [9, 0, 1]


def test_vision_transforms():
    from mxnet_tpu.gluon.data.vision import transforms

    img = mx.nd.array((np.random.rand(10, 12, 3) * 255).astype(np.uint8))
    t = transforms.ToTensor()
    out = t(img)
    assert out.shape == (3, 10, 12)
    assert out.asnumpy().max() <= 1.0

    norm = transforms.Normalize(mean=[0.5, 0.5, 0.5], std=[0.1, 0.1, 0.1])
    out2 = norm(out)
    assert out2.shape == (3, 10, 12)

    resize = transforms.Resize(6)
    assert resize(img).shape == (6, 6, 3)

    crop = transforms.CenterCrop(8)
    assert crop(img).shape == (8, 8, 3)

    comp = transforms.Compose([transforms.Resize(8),
                               transforms.ToTensor()])
    assert comp(img).shape == (3, 8, 8)

    cr = transforms.CropResize(2, 1, 6, 4)
    np.testing.assert_array_equal(cr(img).asnumpy(),
                                  img.asnumpy()[1:5, 2:8])
    assert transforms.CropResize(2, 1, 6, 4,
                                 size=(3, 2))(img).shape == (2, 3, 3)


# -- detection pipeline (reference: python/mxnet/image/detection.py) -----------

def _make_det_list(tmp_path, n=8):
    from PIL import Image

    rs = np.random.RandomState(0)
    lines = []
    for i in range(n):
        arr = (rs.rand(40, 50, 3) * 255).astype(np.uint8)
        Image.fromarray(arr).save(str(tmp_path / f"img{i}.jpg"))
        objs = [[1.0, 0.1, 0.2, 0.6, 0.7]]
        if i % 2:
            objs.append([0.0, 0.3, 0.3, 0.9, 0.9])
        flat = [2, 5] + [v for o in objs for v in o]
        lines.append(f"{i}\t" + "\t".join(str(v) for v in flat)
                     + f"\timg{i}.jpg")
    lst = tmp_path / "det.lst"
    lst.write_text("\n".join(lines) + "\n")
    return str(lst)


def test_image_det_iter_batches(tmp_path):
    from mxnet_tpu import image

    lst = _make_det_list(tmp_path)
    it = image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                            path_imglist=lst, path_root=str(tmp_path))
    batch = next(it)
    assert batch.data[0].shape == (4, 3, 32, 32)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (4, 2, 5)
    valid = lab[lab[:, :, 0] >= 0]
    assert len(valid) >= 4  # at least one object per image
    assert (valid[:, 1:5] >= 0).all() and (valid[:, 1:5] <= 1).all()
    # -1 padding rows where images have fewer objects
    assert (lab[:, :, 0] == -1).any()


def test_det_horizontal_flip_flips_boxes():
    from mxnet_tpu.image_detection import DetHorizontalFlipAug

    src = np.arange(2 * 4 * 3, dtype=np.uint8).reshape(2, 4, 3)
    label = np.array([[1.0, 0.1, 0.2, 0.4, 0.7]], np.float32)
    aug = DetHorizontalFlipAug(p=1.0)
    out, lab2 = aug(src, label)
    np.testing.assert_allclose(lab2[0, 1], 0.6, atol=1e-6)  # 1-0.4
    np.testing.assert_allclose(lab2[0, 3], 0.9, atol=1e-6)  # 1-0.1
    np.testing.assert_array_equal(np.asarray(out), src[:, ::-1])


def test_det_random_crop_keeps_covered_objects():
    from mxnet_tpu.image_detection import DetRandomCropAug

    np.random.seed(0)
    src = np.zeros((100, 100, 3), np.uint8)
    label = np.array([[0.0, 0.4, 0.4, 0.6, 0.6]], np.float32)
    aug = DetRandomCropAug(min_object_covered=0.9,
                           area_range=(0.5, 1.0),
                           min_eject_coverage=0.5, max_attempts=100)
    out, lab2 = aug(src, label)
    # surviving boxes stay normalized and inside the crop
    if lab2.size:
        assert (lab2[:, 1:5] >= 0).all() and (lab2[:, 1:5] <= 1).all()


def test_det_augmenter_pipeline_runs(tmp_path):
    from mxnet_tpu import image

    lst = _make_det_list(tmp_path)
    augs = image.CreateDetAugmenter(data_shape=(3, 32, 32),
                                    rand_crop=0.5, rand_pad=0.5,
                                    rand_mirror=True, brightness=0.2,
                                    contrast=0.2, saturation=0.2,
                                    hue=0.1,
                                    mean=np.array([123., 117., 104.]),
                                    std=np.array([58., 57., 57.]))
    it = image.ImageDetIter(batch_size=8, data_shape=(3, 32, 32),
                            path_imglist=lst, path_root=str(tmp_path),
                            aug_list=augs, shuffle=True)
    batch = next(it)
    assert batch.data[0].shape == (8, 3, 32, 32)
    # normalized pixel stats in a sane range
    d = batch.data[0].asnumpy()
    assert np.abs(d).max() < 10


def test_det_random_crop_rejects_truncating_crops():
    """Reference semantics (review finding): every INTERSECTING object
    must meet min_object_covered — a crop that truncates one box below
    the constraint is rejected even if another box is fully covered."""
    from mxnet_tpu.image_detection import DetRandomCropAug

    aug = DetRandomCropAug(min_object_covered=0.95,
                           min_eject_coverage=0.3)
    label = np.array([[0.0, 0.05, 0.05, 0.5, 0.5],
                      [1.0, 0.4, 0.4, 0.95, 0.95]], np.float32)
    # crop covering box0 fully, box1 ~31%: must NOT be accepted
    crop = (0.0, 0.0, 0.55, 0.55)
    from mxnet_tpu.image_detection import _box_iou_coverage

    cov = _box_iou_coverage(crop, label)
    inter = cov > 0
    assert not (inter.any()
                and cov[inter].min() >= aug.min_object_covered)


def test_det_parse_label_rejects_malformed():
    import pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.image_detection import ImageDetIter

    with pytest.raises(MXNetError):
        ImageDetIter._parse_label(
            np.array([2, 5, 1.0, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7],
                     np.float32)[: -1])  # 7-value body, ow=5


def test_native_decode_beats_pil():
    """IO-throughput guard (BASELINE.md round-4 table): the native
    libjpeg decode+augment path must not regress below the PIL path —
    a cheap in-CI version of tools/bench_io.py (small batch, one
    thread; the recorded numbers come from the tool)."""
    import importlib.util
    import time as _t

    spec = importlib.util.spec_from_file_location(
        "bench_io", os.path.join(os.path.dirname(__file__), "..",
                                 "tools", "bench_io.py"))
    bench_io = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_io)
    from mxnet_tpu import _native
    if not _native.has_jpeg():
        pytest.skip("native decode lib not built")
    with tempfile.TemporaryDirectory() as tmp:
        rec = os.path.join(tmp, "bench.rec")
        bench_io.synth_rec(rec, n=48, size=(240, 320))
        native = bench_io.run(rec, n=48, batch_size=16)
        pil = bench_io.run(rec, n=48, batch_size=16,
                           force_python=True)
    assert native >= 0.9 * pil, \
        f"native decode ({native:.0f}/s) slower than PIL ({pil:.0f}/s)"


# -- corruption hardening (mxnet_tpu/resilience.py integration) ----------------

def _write_rec(path, payloads):
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()


def _read_all(reader):
    out = []
    while True:
        rec = reader.read()
        if rec is None:
            return out
        out.append(rec)


def test_recordio_truncated_tail_strict(tmp_path):
    path = str(tmp_path / "trunc.rec")
    payloads = [bytes([i]) * 40 for i in range(5)]
    _write_rec(path, payloads)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:     # cut mid-way through the last record
        f.write(blob[:-25])
    r = recordio.MXRecordIO(path, "r")
    for i in range(4):
        assert r.read() == payloads[i]
    with pytest.raises(mx.MXNetError, match="truncated"):
        r.read()
    r.close()


def test_recordio_truncated_tail_skip(tmp_path):
    path = str(tmp_path / "trunc.rec")
    payloads = [bytes([i]) * 40 for i in range(5)]
    _write_rec(path, payloads)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:-25])
    r = recordio.MXRecordIO(path, "r", skip_corrupt=True)
    with pytest.warns(UserWarning, match="truncated"):
        got = _read_all(r)
    assert got == payloads[:4]      # every intact record, then clean EOF
    r.close()


def test_recordio_partial_header_tail(tmp_path):
    path = str(tmp_path / "hdr.rec")
    payloads = [b"x" * 16, b"y" * 16]
    _write_rec(path, payloads)
    with open(path, "ab") as f:     # 5 stray bytes: not even a header
        f.write(b"\x01\x02\x03\x04\x05")
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == payloads[0]
    assert r.read() == payloads[1]
    with pytest.raises(mx.MXNetError, match="trailing header"):
        r.read()
    r.close()
    r = recordio.MXRecordIO(path, "r", skip_corrupt=True)
    with pytest.warns(UserWarning):
        assert _read_all(r) == payloads
    r.close()


def test_recordio_bad_magic_strict(tmp_path):
    path = str(tmp_path / "magic.rec")
    payloads = [bytes([65 + i]) * 32 for i in range(6)]
    _write_rec(path, payloads)
    # stomp record 2's magic (each record: 8B header + 32B payload)
    off = 2 * (8 + 32)
    blob = bytearray(open(path, "rb").read())
    blob[off:off + 4] = b"\xff\xff\xff\xff"
    open(path, "wb").write(bytes(blob))
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == payloads[0]
    assert r.read() == payloads[1]
    with pytest.raises(mx.MXNetError, match="magic"):
        r.read()
    r.close()


def test_recordio_bad_magic_resyncs(tmp_path):
    path = str(tmp_path / "magic.rec")
    payloads = [bytes([65 + i]) * 32 for i in range(6)]
    _write_rec(path, payloads)
    off = 2 * (8 + 32)
    blob = bytearray(open(path, "rb").read())
    blob[off:off + 4] = b"\xff\xff\xff\xff"
    open(path, "wb").write(bytes(blob))
    r = recordio.MXRecordIO(path, "r", skip_corrupt=True)
    with pytest.warns(UserWarning, match="magic"):
        got = _read_all(r)
    # record 2 is lost (its header was stomped); 0,1 and 3.. survive
    assert got == payloads[:2] + payloads[3:]
    r.close()


@pytest.mark.faults
def test_recordio_injected_corrupt_record_strict(tmp_path, fault_inject):
    path = str(tmp_path / "inj.rec")
    payloads = [bytes([i]) * 24 for i in range(5)]
    _write_rec(path, payloads)
    fault_inject("corrupt_record:3")
    r = recordio.MXRecordIO(path, "r")
    for i in range(3):
        assert r.read() == payloads[i]
    with pytest.raises(mx.MXNetError, match="injected corrupt record"):
        r.read()
    r.close()


@pytest.mark.faults
def test_recordio_injected_corrupt_record_skip(tmp_path, fault_inject):
    path = str(tmp_path / "inj.rec")
    payloads = [bytes([i]) * 24 for i in range(5)]
    _write_rec(path, payloads)
    fault_inject("corrupt_record:3")
    r = recordio.MXRecordIO(path, "r", skip_corrupt=True)
    with pytest.warns(UserWarning, match="injected"):
        got = _read_all(r)
    assert got == payloads[:3] + payloads[4:]   # record 3 dropped
    r.close()


@pytest.mark.faults
def test_recordio_open_retries_flaky_fs(tmp_path, fault_inject,
                                        monkeypatch):
    monkeypatch.setenv("MXTPU_IO_RETRIES", "3")
    monkeypatch.setenv("MXTPU_IO_BACKOFF", "0.001")
    path = str(tmp_path / "flaky.rec")
    _write_rec(path, [b"payload" * 4])
    fault_inject("io_open:2")
    r = recordio.MXRecordIO(path, "r")   # survives 2 injected failures
    assert r.read() == b"payload" * 4
    r.close()


def test_recordio_missing_file_fails_fast(tmp_path):
    t0 = __import__("time").monotonic()
    with pytest.raises(FileNotFoundError):
        recordio.MXRecordIO(str(tmp_path / "nope.rec"), "r")
    assert __import__("time").monotonic() - t0 < 1.0  # ENOENT: no retry


def test_image_record_iter_skip_corrupt_kwarg(tmp_path):
    """ImageRecordIter(skip_corrupt=True) survives a truncated tail and
    still yields the intact images."""
    rec, _ = _make_rec(tmp_path, n=6, size=(8, 8))
    blob = open(rec, "rb").read()
    with open(rec, "wb") as f:
        f.write(blob[:-30])
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        it = mx.io.ImageRecordIter(path_imgrec=rec, batch_size=5,
                                   data_shape=(3, 8, 8),
                                   skip_corrupt=True)
        batch = next(iter(it))
    assert batch.data[0].shape == (5, 3, 8, 8)
