"""Native async multi-host checkpoint engine tests
(mxnet_tpu/checkpoint.AsyncCheckpointer).

CPU-hermetic throughout: multi-rank commits are faked by constructing
one checkpointer per rank in a single process (``rank=``/``world_size=``
— no barrier), crashes come from the MXTPU_FAULT_INJECT harness killing
a subprocess mid-save, and the real 2-process gang (rendezvous, shard
barrier, rank-0 manifest commit, watchdog abort, launch.py restart) runs
in the slow tier.
"""

import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, numerics, resilience
from mxnet_tpu.checkpoint import AsyncCheckpointer, make_checkpointer
from mxnet_tpu.resilience import CheckpointCorrupt

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    """Subprocess workers must run on the CPU backend, never the TPU
    tunnel (same recipe as tests/test_distributed.py)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_FAULT_INJECT", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _state():
    return {
        "params": [np.arange(12, dtype=np.float32).reshape(3, 4),
                   np.full((2, 2), 2.5, np.float64)],
        "opt": ({"m": np.zeros(3, np.float32)},
                np.arange(5, dtype=np.int32)),
        "meta": {"lr": 0.1, "name": "toy", "flag": True, "none": None},
        "steps": [1, 2, 3],
    }


def _assert_state_equal(a, b):
    assert sorted(a) == sorted(b)
    for i in range(2):
        got, want = a["params"][i], b["params"][i]
        assert got.dtype == want.dtype and np.array_equal(got, want)
    assert isinstance(a["opt"], tuple)
    assert np.array_equal(a["opt"][0]["m"], b["opt"][0]["m"])
    assert np.array_equal(a["opt"][1], b["opt"][1])
    assert a["opt"][1].dtype == b["opt"][1].dtype
    assert a["meta"] == b["meta"]
    assert a["steps"] == b["steps"]


# -- roundtrip + snapshot semantics --------------------------------------------

@pytest.mark.parametrize("async_save", [False, True])
def test_roundtrip(tmp_path, async_save):
    ck = AsyncCheckpointer(tmp_path, async_save=async_save,
                           rank=0, world_size=1)
    ck.save(3, _state())
    ck.wait()
    assert ck.all_steps() == [3]
    _assert_state_equal(ck.restore(3), _state())
    _assert_state_equal(ck.restore(), _state())   # latest


def test_copy_on_snapshot_survives_mutation(tmp_path):
    """save() must host-copy before returning: mutating the state pytree
    in place afterwards (what a training loop does) cannot leak into the
    bytes the background writer serializes."""
    w = np.arange(1024, dtype=np.float32)
    ck = AsyncCheckpointer(tmp_path, async_save=True, rank=0,
                           world_size=1)
    ck.save(1, {"w": w})
    w *= -1.0   # the very next "training step", racing the writer
    ck.wait()
    restored = ck.restore(1)
    assert np.array_equal(restored["w"],
                          np.arange(1024, dtype=np.float32))


def test_backpressure_exactly_one_outstanding(tmp_path, monkeypatch):
    """A second save() blocks until the in-flight commit lands — never
    two writers racing, never an unbounded snapshot queue."""
    gate = threading.Event()
    real = checkpoint._write_shard

    def gated(path, payload):
        gate.wait(timeout=30)
        return real(path, payload)

    monkeypatch.setattr(checkpoint, "_write_shard", gated)
    ck = AsyncCheckpointer(tmp_path, async_save=True, rank=0,
                           world_size=1)
    ck.save(1, {"w": np.zeros(4)})
    assert ck.in_flight() and ck.pending_step == 1

    done = threading.Event()

    def second():
        ck.save(2, {"w": np.ones(4)})
        done.set()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    assert not done.wait(timeout=0.3)   # blocked on save 1's commit
    assert ck.pending_step == 1
    gate.set()
    t.join(timeout=30)
    ck.wait()
    assert ck.all_steps() == [1, 2]


def test_writer_error_propagates(tmp_path, monkeypatch):
    """An error in the background writer surfaces at the NEXT
    save()/wait(), and the engine stays usable afterwards."""
    real = checkpoint._write_shard
    monkeypatch.setattr(
        checkpoint, "_write_shard",
        lambda *a: (_ for _ in ()).throw(OSError("disk gone")))
    ck = AsyncCheckpointer(tmp_path, async_save=True, rank=0,
                           world_size=1)
    ck.save(1, {"w": np.zeros(4)})   # returns fine; writer fails
    with pytest.raises(OSError, match="disk gone"):
        ck.wait()
    ck.save(2, {"w": np.zeros(4)})   # error was consumed: save starts
    with pytest.raises(OSError, match="disk gone"):
        ck.save(3, {"w": np.zeros(4)})   # save 2's failure lands here
    monkeypatch.setattr(checkpoint, "_write_shard", real)
    ck.save(3, {"w": np.ones(4)})    # disk "repaired": engine recovers
    ck.wait()
    assert ck.all_steps() == [3]
    assert np.array_equal(ck.restore(3)["w"], np.ones(4))


# -- crash consistency (1-process harness) -------------------------------------

_CRASH_WORKER = os.path.join(_REPO, "tests", "ckpt_crash_worker.py")


@pytest.mark.faults
@pytest.mark.parametrize("site,mode", [
    ("crash_during_save", "async"),
    ("crash_before_manifest", "async"),
    ("crash_during_save", "sync"),
])
def test_crash_leaves_previous_checkpoint(tmp_path, site, mode):
    """Kill the process mid-save (torn shard) or between the shard write
    and the manifest rename: restore must always yield the PREVIOUS
    fully-committed checkpoint, and the next save GCs the orphan."""
    proc = subprocess.run(
        [sys.executable, _CRASH_WORKER, str(tmp_path), site, mode],
        env=_clean_env(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == resilience.CRASH_EXIT_CODE, \
        (proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:])
    assert f"injected crash at {site}" in proc.stderr

    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    # the half-written step 20 is invisible; step 10 restores intact
    assert ck.all_steps() == [10]
    restored = []
    assert resilience.resume_latest(ck, restored.append) == 10
    assert np.array_equal(restored[0]["w"],
                          np.full((64, 64), 10.0, np.float32))
    orphan = os.path.join(str(tmp_path), "step_0000000020")
    assert os.path.isdir(orphan)   # crash leftovers linger until...
    ck.save(30, {"w": np.zeros(2)})
    ck.wait()
    assert not os.path.exists(orphan)   # ...the next save GCs them
    assert ck.all_steps() == [10, 30]


@pytest.mark.faults
def test_corrupt_shard_falls_back(tmp_path, fault_inject):
    """``corrupt_shard:K`` bit-rots a committed shard: restore fails
    closed on the CRC and resume_latest falls back a step."""
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    ck.save(10, {"w": np.full(8, 10.0)})
    fault_inject("corrupt_shard:0")
    ck.save(20, {"w": np.full(8, 20.0)})
    with pytest.raises(CheckpointCorrupt, match="checksum"):
        ck.restore(20)
    restored = []
    assert resilience.resume_latest(ck, restored.append) == 10
    assert np.array_equal(restored[0]["w"], np.full(8, 10.0))


def test_manifest_validation(tmp_path):
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    ck.save(5, {"w": np.zeros(4)})
    mpath = os.path.join(ck._step_dir(5), "MANIFEST.json")
    with open(mpath) as f:
        m = json.load(f)

    def rewrite(d):
        with open(mpath, "w") as f:
            json.dump(d, f)

    rewrite({**m, "magic": "NOPE"})
    with pytest.raises(CheckpointCorrupt, match="magic"):
        ck.restore(5)
    rewrite({**m, "version": 99})
    with pytest.raises(CheckpointCorrupt, match="version"):
        ck.restore(5)
    rewrite({**m, "shards": []})
    with pytest.raises(CheckpointCorrupt, match="shard entries"):
        ck.restore(5)
    rewrite(m)
    ck.restore(5)   # pristine manifest restores again

    # truncated shard: framing length check fails closed
    spath = os.path.join(ck._step_dir(5), "shard_00000.mxtckpt")
    blob = open(spath, "rb").read()
    with open(spath, "wb") as f:
        f.write(blob[:-3])
    with pytest.raises(CheckpointCorrupt, match="truncated"):
        ck.restore(5)


def test_uncommitted_step_is_invisible(tmp_path):
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    ck.save(7, {"w": np.zeros(2)})
    orphan = os.path.join(str(tmp_path), "step_0000000099")
    os.makedirs(orphan)
    open(os.path.join(orphan, "shard_00000.mxtckpt"), "wb").close()
    assert ck.all_steps() == [7]
    assert ck.latest_step() == 7
    with pytest.raises(CheckpointCorrupt, match="no manifest"):
        ck.restore(99)


# -- fake multi-rank commit + elastic restore ----------------------------------

def _save_two_rank(tmp_path, step, state):
    """Commit one checkpoint as TWO fake ranks sharing a directory.
    Rank 1 first: with barriers off, rank 0's manifest pass must find
    every rank entry already durable."""
    for rank in (1, 0):
        ck = AsyncCheckpointer(tmp_path, async_save=False, rank=rank,
                               world_size=2)
        ck.save(step, state)
    return ck


def test_two_rank_commit_restores_anywhere(tmp_path):
    """A 2-rank checkpoint reassembles under a different world size from
    the manifest alone (host pytree — no template needed off-cluster)."""
    _save_two_rank(tmp_path, 4, _state())
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    with open(os.path.join(ck._step_dir(4), "MANIFEST.json")) as f:
        m = json.load(f)
    assert m["world_size"] == 2 and len(m["shards"]) == 2
    # both shards carry a disjoint, non-empty slice of the leaves
    slices = [set(sh["leaves"]) for sh in m["shards"]]
    assert slices[0] and slices[1] and not (slices[0] & slices[1])
    _assert_state_equal(ck.restore(4), _state())


def test_rank0_aborts_commit_on_missing_entry(tmp_path):
    """Rank 0 alone (rank 1's entry missing) must abort the commit and
    leave no manifest — the previous checkpoint stays authoritative."""
    ck0 = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                            world_size=2)
    with pytest.raises(mx.MXNetError, match="commit aborted"):
        ck0.save(4, _state())
    assert ck0.all_steps() == []


def test_world_size_mismatch_is_hard_error(tmp_path):
    _save_two_rank(tmp_path, 4, _state())
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=3)
    ck._use_barrier = True   # pretend this is a REAL 3-host job
    with pytest.raises(mx.MXNetError, match="pass template"):
        ck.restore(4)


def test_template_validation_errors(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("dp",))
    repl = NamedSharding(mesh, PartitionSpec())
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    ck.save(1, {"w": np.zeros((4, 2), np.float32), "b": np.zeros(3)})
    with pytest.raises(mx.MXNetError, match="keys differ"):
        ck.restore(1, template={"w": repl, "EXTRA": repl, "b": repl})
    with pytest.raises(mx.MXNetError, match="shape"):
        ck.restore(1, template={
            "w": jax.ShapeDtypeStruct((4, 999), np.float32,
                                      sharding=repl),
            "b": repl})
    with pytest.raises(mx.MXNetError, match="dtype"):
        ck.restore(1, template={
            "w": jax.ShapeDtypeStruct((4, 2), np.int32, sharding=repl),
            "b": repl})
    out = ck.restore(1, template={
        "w": NamedSharding(mesh, PartitionSpec("dp")), "b": repl})
    assert isinstance(out["w"], jax.Array)
    assert out["w"].sharding.spec == PartitionSpec("dp")


def test_elastic_trainer_restore_bitwise(tmp_path):
    """The acceptance bar: a ShardedTrainer checkpoint written under one
    world size restores BITWISE-identically under another via the
    trainer's sharding template — and the snapshot is immune to the
    trainer training on after the save (satellite: snapshot-safe
    trainer_state)."""
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential(prefix="ck_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize(init=mx.init.Xavier())
    tr = parallel.ShardedTrainer(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 1e-2},
        mesh=parallel.make_mesh(dp=8))
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randn(16, 4).astype(np.float32)
    tr.step(x, y)
    tr.step(x, y)

    st = checkpoint.trainer_state(tr)
    frozen = [np.array(p, copy=True) for p in st["params"]]
    tr.step(x, y)   # mutate the trainer AFTER the snapshot
    tr.step(x, y)
    for before, after in zip(frozen, st["params"]):
        assert np.array_equal(before, after)   # snapshot never aliased

    _save_two_rank(tmp_path, 2, st)            # "written by 2 hosts"

    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)       # "restored by 1"
    restored = ck.restore(2, template=tr.state_template())
    checkpoint.load_trainer_state(tr, restored)
    for got, want in zip(tr._param_vals, frozen):
        got = np.asarray(got)
        assert got.dtype == want.dtype
        assert np.array_equal(got, want)       # bitwise, pre-mutation
    assert tr._num_update == int(st["num_update"])
    tr.step(x, y)   # restored trainer still trains


def test_elastic_restore_dp4_onto_dp2_tp2_bitwise(tmp_path):
    """PR 9 satellite: a checkpoint written under a pure ``dp=4`` mesh
    restores BITWISE onto a ``dp=2,tp=2`` mesh with Megatron TP rules,
    through `AsyncCheckpointer`'s template path — the PR 5 elastic
    mechanism aimed at the new shardings."""
    import jax
    from jax.sharding import PartitionSpec

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    def build(prefix):
        net = nn.HybridSequential(prefix=prefix)
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu", in_units=8),
                    nn.Dense(4, in_units=16))
        net.initialize(init=mx.init.Xavier())
        return net

    # writer: dp=4 over half the devices
    mx.random.seed(3)
    src = parallel.ShardedTrainer(
        build("ckel_"), gluon.loss.L2Loss(), "adam",
        {"learning_rate": 1e-2},
        mesh=parallel.make_mesh(dp=4, devices=jax.devices()[:4]))
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randn(16, 4).astype(np.float32)
    src.step(x, y)
    src.step(x, y)
    st = checkpoint.trainer_state(src)
    frozen = [np.array(p, copy=True) for p in st["params"]]
    _save_two_rank(tmp_path, 5, st)

    # reader: dp=2,tp=2 with TP rules over dense weights
    mx.random.seed(99)  # different init — restore must overwrite it
    rules = parallel.ShardingRules(rules=[
        (r"dense0_weight$", ("tp", None)),
        (r"dense1_weight$", (None, "tp")),
    ])
    dst = parallel.ShardedTrainer(
        build("ckel2_"), gluon.loss.L2Loss(), "adam",
        {"learning_rate": 1e-2},
        mesh=parallel.make_mesh(dp=2, tp=2), rules=rules)
    dst.step(x, y)  # stage + one step of divergent training
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    restored = ck.restore(5, template=dst.state_template())
    checkpoint.load_trainer_state(dst, restored)
    tp_specs = [sh.spec for sh in dst._param_shardings]
    assert PartitionSpec("tp", None) in tp_specs  # template was TP
    for got, want, sh in zip(dst._param_vals, frozen,
                             dst._param_shardings):
        assert got.sharding.is_equivalent_to(sh, got.ndim)
        assert np.array_equal(np.asarray(got), want)  # bitwise
    assert dst._num_update == int(st["num_update"])
    dst.step(x, y)  # restored trainer still trains on the new mesh


def test_elastic_restore_dp8_onto_tp2_pp2_dp2_bitwise(tmp_path):
    """PR 17 acceptance: a checkpoint written under a pure ``dp=8``
    mesh restores BITWISE onto the 3-axis ``tp=2×pp=2×dp=2`` layout —
    the scanned trunk's layer-stack dim lands on the pp axis
    (`pp_rules` composed over `TRANSFORMER_TP_RULES`), through the same
    PR 5/9 elastic template path."""
    import jax
    from jax.sharding import PartitionSpec

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo.bert import ScanTransformerEncoder

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (forced-host) devices")

    def build(seed):
        mx.random.seed(seed)
        net = ScanTransformerEncoder(num_layers=2, units=16,
                                     num_heads=2, hidden_size=32,
                                     dropout=0.0)
        net.initialize(init=mx.init.Xavier())
        net.hybridize()
        return net

    rng = np.random.RandomState(0)
    x = rng.randn(8, 4, 16).astype(np.float32)
    y = rng.randn(8, 4, 16).astype(np.float32)

    # writer: pure data parallel over all 8 devices
    src = parallel.ShardedTrainer(
        build(3), gluon.loss.L2Loss(), "adam", {"learning_rate": 1e-2},
        mesh=parallel.make_mesh(dp=8))
    src.step(x, y)
    src.step(x, y)
    st = checkpoint.trainer_state(src)
    frozen = [np.array(p, copy=True) for p in st["params"]]
    _save_two_rank(tmp_path, 17, st)

    # reader: the 3-axis pipeline layout — different init, must be
    # overwritten bitwise by the restore
    mesh = parallel.make_mesh(axes={"tp": 2, "pp": 2, "dp": 2})
    rules = parallel.combined_rules(parallel.pp_rules(mesh),
                                    parallel.TRANSFORMER_TP_RULES)
    dst = parallel.ShardedTrainer(
        build(99), gluon.loss.L2Loss(), "adam",
        {"learning_rate": 1e-2}, mesh=mesh, rules=rules)
    dst.step(x, y)  # stage + one step of divergent training
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    restored = ck.restore(17, template=dst.state_template())
    checkpoint.load_trainer_state(dst, restored)
    specs = [tuple(sh.spec) for sh in dst._param_shardings]
    assert any("pp" in s and "tp" in s for s in specs)  # 3-axis layout
    for got, want, sh in zip(dst._param_vals, frozen,
                             dst._param_shardings):
        assert got.sharding.is_equivalent_to(sh, got.ndim)
        assert np.array_equal(np.asarray(got), want)  # bitwise
    assert dst._num_update == int(st["num_update"])
    dst.step(x, y)  # restored trainer still trains on the new layout
    parallel.set_default_mesh(None)


def test_gluon_trainer_checkpoint_roundtrip_sharded(tmp_path):
    """The imperative gluon Trainer checkpoints through the SAME
    trainer_state/template/load surface (duck-typed): params + adam
    moments + update counters round-trip bitwise onto the captured
    path's sharded placements."""
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    def build():
        mx.random.seed(7)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu", in_units=8),
                    nn.Dense(3, in_units=16))
        net.initialize(init=mx.init.Xavier())
        net.hybridize()
        return net

    mesh = parallel.make_mesh(dp=2, tp=4)
    net = build()
    parallel.shard_model(net, mesh, mode="fsdp", min_size=8)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    rng = np.random.RandomState(1)
    batches = [(rng.randn(16, 8).astype(np.float32),
                rng.randint(0, 3, (16,)).astype(np.float32))
               for _ in range(4)]
    for x, y in batches[:2]:
        tr.train_step(net, loss_fn, mx.nd.array(x), mx.nd.array(y))
    st = checkpoint.trainer_state(tr)
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    ck.save(2, st)
    # train on, then restore: must rewind bitwise
    for x, y in batches[2:]:
        tr.train_step(net, loss_fn, mx.nd.array(x), mx.nd.array(y))
    restored = ck.restore(2, template=checkpoint.trainer_state_template(tr))
    checkpoint.load_trainer_state(tr, restored)
    for p, want in zip(tr._params, st["params"]):
        assert np.array_equal(p.data().asnumpy(), want)
    assert tr._optimizer.num_update == int(st["num_update"])
    # the restored trainer still trains on the sharded placements
    for x, y in batches[2:]:
        tr.train_step(net, loss_fn, mx.nd.array(x), mx.nd.array(y))
    parallel.set_default_mesh(None)


def test_elastic_restore_row_sharded_table_bitwise(tmp_path):
    """PR 18 acceptance: a row-sharded `ShardedEmbedding` table trained
    on a ``dp=8`` mesh (6-row shards) restores BITWISE onto a
    ``dp=2,tp=2`` layout (24-row shards, replicated over tp) through
    the elastic template path — shard sizes differ across the layouts,
    the bytes must not."""
    import jax
    from jax.sharding import PartitionSpec

    from mxnet_tpu import embedding, gluon, parallel
    from mxnet_tpu.gluon import nn

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (forced-host) devices")

    def build(seed, prefix):
        mx.random.seed(seed)
        net = nn.HybridSequential(prefix=prefix)
        with net.name_scope():
            net.add(embedding.ShardedEmbedding(48, 8),
                    nn.Dense(3, in_units=8, flatten=False))
        net.initialize(init=mx.init.Xavier())
        net.hybridize()
        return net

    def table_of(tr):
        (i, p), = [(i, p) for i, p in enumerate(tr._params)
                   if p.name.endswith("embed_table")]
        return i, p

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(5)
    batches = [(rng.randint(0, 48, (16,)).astype(np.float32),
                rng.randint(0, 3, (16,)).astype(np.float32))
               for _ in range(4)]

    # writer: table rows sharded 48/8 = 6 per device
    src_net = build(11, "ckemb_")
    src = gluon.Trainer(src_net.collect_params(), "adam",
                        {"learning_rate": 1e-2})
    parallel.shard_model(src_net, parallel.make_mesh(dp=8),
                         mode="fsdp", min_size=1, trainer=src)
    for x, y in batches[:2]:
        src.train_step(src_net, loss_fn, mx.nd.array(x), mx.nd.array(y))
    _, src_table = table_of(src)
    src_jax = src_table.data()._data
    assert src_jax.sharding.spec == PartitionSpec("dp", None)
    assert src_jax.sharding.shard_shape(src_jax.shape) == (6, 8)
    st = checkpoint.trainer_state(src)
    frozen = [np.array(p, copy=True) for p in st["params"]]
    _save_two_rank(tmp_path, 18, st)

    # reader: different init + layout — 24-row shards over dp=2
    dst_net = build(97, "ckemb2_")
    dst = gluon.Trainer(dst_net.collect_params(), "adam",
                        {"learning_rate": 1e-2})
    parallel.shard_model(dst_net, parallel.make_mesh(dp=2, tp=2),
                         mode="fsdp", min_size=1, trainer=dst)
    x, y = batches[2]
    dst.train_step(dst_net, loss_fn, mx.nd.array(x), mx.nd.array(y))
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    restored = ck.restore(
        18, template=checkpoint.trainer_state_template(dst))
    checkpoint.load_trainer_state(dst, restored)
    ti, dst_table = table_of(dst)
    dst_jax = dst_table.data()._data
    assert dst_jax.sharding.shard_shape(dst_jax.shape) == (24, 8)
    for p, want in zip(dst._params, frozen):
        assert np.array_equal(p.data().asnumpy(), want)  # bitwise
    assert dst._optimizer.num_update == int(st["num_update"])
    # the restored table still trains row-sparse on the new layout
    for x, y in batches[2:]:
        dst.train_step(dst_net, loss_fn, mx.nd.array(x), mx.nd.array(y))
    parallel.set_default_mesh(None)


# -- integration: rollback / preemption / run_resilient / factory --------------

def test_async_save_overlapped_with_rollback(tmp_path, monkeypatch):
    """DivergenceMonitor rollback while a save is STILL IN FLIGHT: the
    recovery path drains the commit first (flush_inflight inside
    resume_latest), so the rollback restores the just-committed step —
    never a half-observed one."""
    gate = threading.Event()
    real = checkpoint._write_shard

    def gated(path, payload):
        gate.wait(timeout=30)
        return real(path, payload)

    ck = AsyncCheckpointer(tmp_path, async_save=True, rank=0,
                           world_size=1)
    ck.save(10, {"w": np.full(8, 1.0)})
    ck.wait()
    monkeypatch.setattr(checkpoint, "_write_shard", gated)
    ck.save(20, {"w": np.full(8, 2.0)})
    assert ck.in_flight()

    restored = {}
    mon = numerics.DivergenceMonitor(
        checkpointer=ck, set_state=restored.update, max_bad_steps=2)
    threading.Timer(0.3, gate.set).start()
    assert mon.observe(step=21, loss=float("nan")) is False
    assert mon.observe(step=22, loss=float("nan")) is True
    assert mon.recoveries == 1
    assert ck.latest_step() == 20   # the in-flight save DID commit
    assert np.array_equal(restored["w"], np.full(8, 2.0))


def test_preemption_completes_pending_commit(tmp_path, monkeypatch):
    """SIGTERM with a save in flight: the grace window finishes THAT
    commit; no new save is started (get_state must never be called)."""
    gate = threading.Event()
    real = checkpoint._write_shard

    def gated(path, payload):
        gate.wait(timeout=30)
        return real(path, payload)

    monkeypatch.setattr(checkpoint, "_write_shard", gated)
    ck = AsyncCheckpointer(tmp_path, async_save=True, rank=0,
                           world_size=1)
    ck.save(7, {"w": np.full(4, 7.0)})
    assert ck.in_flight()

    def boom():
        raise AssertionError("a NEW save was started in the grace window")

    with checkpoint.PreemptionHandler(ck, get_state=boom,
                                      get_step=lambda: 99) as h:
        assert h.maybe_checkpoint() is False   # not preempted yet
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.preempted.is_set()
        threading.Timer(0.3, gate.set).start()
        assert h.maybe_checkpoint() is True
    assert ck.latest_step() == 7
    assert np.array_equal(ck.restore(7)["w"], np.full(4, 7.0))


@pytest.mark.faults
def test_run_resilient_with_async_engine(tmp_path, fault_inject):
    """run_resilient on the async engine end-to-end, including an
    injected SIGTERM preemption: drain-at-recovery + final wait() give
    the same trajectory as an uninterrupted synchronous run."""
    fault_inject("sigterm_at_step:7")
    state = {"w": np.full(4, 10.0)}

    def step_fn(step):
        w = state["w"]
        loss = float((w ** 2).sum())
        state["w"] = w - 0.1 * 2 * w
        return loss

    ck = AsyncCheckpointer(tmp_path, async_save=True, rank=0,
                           world_size=1)
    report = resilience.run_resilient(
        step_fn, ck, 20,
        get_state=lambda: {"w": state["w"].copy()},
        set_state=lambda s: state.update(w=np.asarray(s["w"]).copy()),
        checkpoint_every=5, max_restarts=3)
    assert report.preempted and report.restarts == 1
    assert report.final_step == 20
    # the grace window either commits the step-7 save or completes the
    # in-flight step-5 one — both are consistent resume points (the
    # trajectory is a pure function of the restored state)
    assert report.resumed_from[0] == 0 and report.resumed_from[1] in (5, 7)
    assert not ck.in_flight()
    assert ck.latest_step() == 20
    np.testing.assert_allclose(ck.restore(20)["w"],
                               np.full(4, 10.0) * 0.8 ** 20)


def test_make_checkpointer_backends(tmp_path, monkeypatch):
    msgs = []

    class Log:
        def info(self, m):
            msgs.append(m)

    ck = make_checkpointer(tmp_path / "a", logger=Log())
    assert isinstance(ck, AsyncCheckpointer)
    assert any("native" in m for m in msgs)

    ck = make_checkpointer(tmp_path / "b", backend="local", logger=Log())
    assert isinstance(ck, resilience.LocalCheckpointer)

    # orbax requested but unavailable: clean fallback, logged
    monkeypatch.setitem(sys.modules, "orbax", None)
    msgs.clear()
    ck = make_checkpointer(tmp_path / "c", backend="orbax", logger=Log())
    assert isinstance(ck, AsyncCheckpointer)
    assert any("falling back" in m for m in msgs)

    monkeypatch.setenv("MXTPU_CKPT_BACKEND", "local")
    ck = make_checkpointer(tmp_path / "d", logger=Log())
    assert isinstance(ck, resilience.LocalCheckpointer)

    with pytest.raises(mx.MXNetError, match="unknown backend"):
        make_checkpointer(tmp_path / "e", backend="nope", logger=Log())


def test_fsync_dir_helper(tmp_path):
    resilience.fsync_dir(str(tmp_path))           # real dir: no error
    resilience.fsync_dir(str(tmp_path / "gone"))  # missing: tolerated


def test_max_to_keep_prunes(tmp_path):
    ck = AsyncCheckpointer(tmp_path, max_to_keep=2, async_save=False,
                           rank=0, world_size=1)
    for s in (1, 2, 3, 4):
        ck.save(s, {"w": np.zeros(2)})
    assert ck.all_steps() == [3, 4]


# -- 2-process gang: real barriers, real crash, real restart -------------------

_DIST_WORKER = os.path.join(_REPO, "tests", "ckpt_dist_worker.py")


def _serial_replay(num_steps):
    sys.path.insert(0, os.path.join(_REPO, "tests"))
    try:
        import ckpt_dist_worker as w
    finally:
        sys.path.pop(0)
    state = w.initial_state()
    for _ in range(num_steps):
        w.apply_step(state)
    return state


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.parametrize("site", ["crash_during_save",
                                  "crash_before_manifest"])
def test_two_process_crash_consistency(tmp_path, site):
    """The acceptance bar, 2-process edition: rank 0 dies mid-commit
    (torn shard, or after the shard barrier but before the manifest
    rename), the survivor's barrier is aborted by the collective
    watchdog, launch.py relaunches the gang, both ranks resume from the
    last COMMITTED step, and the final state matches a serial replay."""
    num_steps = 20
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--max-restarts", "1",
         "--port", str(port), "--",
         sys.executable, _DIST_WORKER, str(tmp_path), str(num_steps)],
        env={**_clean_env(),
             "MXTPU_COLLECTIVE_TIMEOUT": "8",
             "MXTPU_WATCHDOG_ACTION": "abort",
             "CKPT_CRASH_SITE": site,
             "CKPT_CRASH_RANK": "0",
             "CKPT_CRASH_STEP": "10"},
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    assert f"injected crash at {site}" in proc.stderr
    assert "restarting gang" in proc.stderr
    expected = _serial_replay(num_steps)
    for rank in range(2):
        assert (f"worker {rank}: ckpt run done at step {num_steps} "
                f"w00={expected['w'][0, 0]:.9g}") in proc.stdout
        # the torn step-10 checkpoint is invisible: both ranks resume
        # from the last COMMITTED step
        assert f"worker {rank}: resumed from step 5" in proc.stdout

    # the final checkpoint: committed by 2 ranks, restorable by 1
    ck = AsyncCheckpointer(os.path.join(str(tmp_path), "ckpt"),
                           async_save=False, rank=0, world_size=1)
    assert ck.latest_step() == num_steps
    with open(os.path.join(ck._step_dir(num_steps),
                           "MANIFEST.json")) as f:
        assert json.load(f)["world_size"] == 2
    final = ck.restore(num_steps)
    assert np.array_equal(final["w"], expected["w"])
    assert np.array_equal(final["b"], expected["b"])


# -- epoch fencing on the durable commit (split-brain guard) -------------------

def test_manifest_carries_gang_epoch(tmp_path):
    """attach_gang stamps the gang epoch into every rank entry and into
    MANIFEST.json; manifests restore normally and verify() hands the
    stamp back (the serving reload gate reads it)."""
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    assert ck.attach_gang(lambda: 7, lambda: 7) is ck
    ck.save(3, _state())
    ck.wait()
    with open(os.path.join(ck._step_dir(3), "MANIFEST.json")) as f:
        assert json.load(f)["gang_epoch"] == 7
    assert ck.verify(3)["gang_epoch"] == 7
    _assert_state_equal(ck.restore(3), _state())


def test_stale_epoch_manifest_commit_aborted(tmp_path, monkeypatch):
    """The tentpole abort path: the fence moved on while this rank was
    out to lunch (paused rank 0, partition minority).  The manifest
    rename must NOT happen — MXNetError, one ckpt_fenced event, no
    orphan .tmp, and the PREVIOUS manifest stays the restore point."""
    from mxnet_tpu import telemetry

    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    telemetry.reset()
    try:
        ckdir = tmp_path / "ckpt"
        ck = AsyncCheckpointer(ckdir, async_save=False, rank=0,
                               world_size=1)
        ck.attach_gang(lambda: 1, lambda: 1)
        ck.save(1, _state())
        ck.wait()
        assert checkpoint.latest_manifest_step(ckdir) == 1
        # a quorum elsewhere committed epoch 3: we are now a zombie
        ck.attach_gang(lambda: 1, lambda: 3)
        with pytest.raises(resilience.MXNetError, match="FENCED"):
            ck.save(2, _state())
        # the previous manifest remains the restore point
        assert checkpoint.latest_manifest_step(ckdir) == 1
        _assert_state_equal(ck.restore(), _state())
        # no half-published manifest anywhere
        orphans = [f for root, _dirs, files in os.walk(ckdir)
                   for f in files if f.endswith(".tmp")]
        assert orphans == []
    finally:
        telemetry.reset()
    with open(ev_path) as f:
        ev = [json.loads(ln) for ln in f if ln.strip()]
    fenced = [e for e in ev if e.get("event") == "ckpt_fenced"]
    assert len(fenced) == 1
    assert fenced[0]["step"] == 2
    assert fenced[0]["epoch"] == 1
    assert fenced[0]["committed"] == 3


def test_manifest_commit_fails_closed_on_unreachable_fence(tmp_path):
    """No fence answer -> no rename: a rank that cannot read the fence
    might BE the fenced minority, so the commit aborts rather than
    gambling on a stale restore point."""
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)

    def down():
        raise OSError("gang kv unreachable")

    ck.attach_gang(lambda: 1, down)
    with pytest.raises(resilience.MXNetError, match="FENCED"):
        ck.save(1, _state())
    assert checkpoint.latest_manifest_step(tmp_path) is None


def test_unfenced_checkpointer_unchanged(tmp_path):
    """No attach_gang -> no stamp, no fence check: the pre-v8 surface
    is bitwise what it was."""
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    ck.save(1, _state())
    ck.wait()
    with open(os.path.join(ck._step_dir(1), "MANIFEST.json")) as f:
        assert "gang_epoch" not in json.load(f)
