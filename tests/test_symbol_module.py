"""Symbol + Module legacy API tests (reference:
tests/python/unittest/test_symbol.py, test_module.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp_symbol():
    data = mx.sym.Variable("data")
    w1 = mx.sym.Variable("fc1_weight")
    b1 = mx.sym.Variable("fc1_bias")
    h = mx.sym.FullyConnected(data, w1, b1, num_hidden=16,
                              name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    w2 = mx.sym.Variable("fc2_weight")
    b2 = mx.sym.Variable("fc2_bias")
    out = mx.sym.FullyConnected(h, w2, b2, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(out, mx.sym.Variable("softmax_label"),
                                name="softmax")


def test_symbol_compose_and_arguments():
    net = _mlp_symbol()
    args = net.list_arguments()
    assert args[0] == "data"
    assert "fc1_weight" in args and "softmax_label" in args
    assert net.list_outputs() == ["softmax_output"]


def test_symbol_arithmetic_eval():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = 2 * a + b / a
    out = c.eval(a=mx.nd.array([2.0]), b=mx.nd.array([6.0]))
    np.testing.assert_allclose(out.asnumpy(), [7.0])


def test_symbol_infer_shape():
    net = _mlp_symbol()
    arg_shapes, out_shapes, _ = net.infer_shape(
        data=(8, 10), fc1_weight=(16, 10), fc1_bias=(16,),
        fc2_weight=(4, 16), fc2_bias=(4,), softmax_label=(8,))
    assert out_shapes == [(8, 4)]


def test_symbol_json_roundtrip(tmp_path):
    net = _mlp_symbol()
    f = str(tmp_path / "net-symbol.json")
    net.save(f)
    net2 = mx.sym.load(f)
    assert net2.list_arguments() == net.list_arguments()
    # eval equivalence
    rng = np.random.RandomState(0)
    env = {"data": mx.nd.array(rng.randn(2, 10).astype(np.float32)),
           "fc1_weight": mx.nd.array(rng.randn(16, 10).astype(np.float32)),
           "fc1_bias": mx.nd.zeros((16,)),
           "fc2_weight": mx.nd.array(rng.randn(4, 16).astype(np.float32)),
           "fc2_bias": mx.nd.zeros((4,)),
           "softmax_label": mx.nd.zeros((2,))}
    np.testing.assert_allclose(net.eval(**env).asnumpy(),
                               net2.eval(**env).asnumpy(), rtol=1e-5)


def test_executor_forward_backward():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = mx.sym.broadcast_mul(a, b)
    exe = c.simple_bind(a=(3,), b=(3,))
    exe.arg_dict["a"]._set_data(mx.nd.array([1.0, 2.0, 3.0])._data)
    exe.arg_dict["b"]._set_data(mx.nd.array([4.0, 5.0, 6.0])._data)
    out = exe.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [4.0, 10.0, 18.0])
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["a"].asnumpy(),
                               [4.0, 5.0, 6.0])
    np.testing.assert_allclose(exe.grad_dict["b"].asnumpy(),
                               [1.0, 2.0, 3.0])


def test_module_fit_mnist_style():
    """Tiny Module.fit run (reference: tests/python/train/test_mlp.py via
    Module)."""
    rng = np.random.RandomState(0)
    centers = rng.uniform(-2, 2, size=(4, 10)).astype(np.float32)
    labels = rng.randint(0, 4, 256)
    data = centers[labels] + rng.normal(0, 0.4, (256, 10)) \
        .astype(np.float32)
    train_iter = mx.io.NDArrayIter(data, labels.astype(np.float32),
                                   batch_size=32, shuffle=True,
                                   label_name="softmax_label")
    net = _mlp_symbol()
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train_iter, num_epoch=6, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            eval_metric="acc",
            initializer=mx.init.Xavier())
    score = mod.score(train_iter, "acc")
    assert score[0][1] > 0.85, score


def test_module_predict_and_checkpoint(tmp_path):
    rng = np.random.RandomState(0)
    data = rng.randn(16, 10).astype(np.float32)
    it = mx.io.NDArrayIter(data, np.zeros(16, np.float32), batch_size=8,
                           label_name="softmax_label")
    net = _mlp_symbol()
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    preds = mod.predict(it)
    assert preds.shape == (16, 4)

    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1)
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 1)
    assert "fc1_weight" in arg2
    mod2 = mx.mod.Module(net)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params(arg_params=arg2, aux_params=aux2)
    preds2 = mod2.predict(it)
    np.testing.assert_allclose(preds.asnumpy(), preds2.asnumpy(),
                               rtol=1e-5)


def test_symbol_grouping():
    a = mx.sym.Variable("a")
    s1 = mx.sym.relu(a)
    s2 = mx.sym.sigmoid(a)
    g = mx.sym.Group([s1, s2])
    outs = g.eval_raw(a=np.array([-1.0, 1.0], np.float32))
    assert len(outs) == 2


# -- HybridBlock.export / SymbolBlock.imports (deploy format) ------------------
# Reference: tests/python/unittest/test_gluon.py::test_export/test_import

def test_export_import_roundtrip_mlp(tmp_path):
    from mxnet_tpu import autograd, gluon, nd

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Dropout(0.5),
            gluon.nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(0).randn(3, 8).astype("float32"))
    net(x)
    with autograd.predict_mode():
        ref = net(x)
    sym = net.export(str(tmp_path / "model"))
    # traced graph exposes params/aux under their global names (the
    # numeric suffix depends on gluon's process-wide name counter)
    assert any(a.endswith("_running_mean")
               for a in sym.list_auxiliary_states())
    assert any("dense" in a and a.endswith("_weight")
               for a in sym.list_arguments())
    sb = gluon.SymbolBlock.imports(
        str(tmp_path / "model-symbol.json"), ["data"],
        str(tmp_path / "model-0000.params"))
    out = sb(x)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), atol=1e-6)


def test_export_import_roundtrip_convnet(tmp_path):
    from mxnet_tpu import autograd, gluon, nd

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, use_bias=False),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(5))
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(1).randn(2, 3, 16, 16)
                 .astype("float32"))
    net(x)
    with autograd.predict_mode():
        ref = net(x)
    net.export(str(tmp_path / "conv"))
    sb = gluon.SymbolBlock.imports(
        str(tmp_path / "conv-symbol.json"), ["data"],
        str(tmp_path / "conv-0000.params"))
    np.testing.assert_allclose(sb(x).asnumpy(), ref.asnumpy(), atol=1e-6)


def test_export_import_resnet18(tmp_path):
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=10)
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(2).randn(2, 3, 32, 32)
                 .astype("float32"))
    net(x)
    with autograd.predict_mode():
        ref = net(x)
    net.export(str(tmp_path / "r18"))
    sb = gluon.SymbolBlock.imports(
        str(tmp_path / "r18-symbol.json"), ["data"],
        str(tmp_path / "r18-0000.params"))
    np.testing.assert_allclose(sb(x).asnumpy(), ref.asnumpy(), atol=1e-5)


def test_exported_json_scalar_positional_roundtrip(tmp_path):
    # relu6 (clip(x, 0, 6)) traces scalar positionals; they must survive
    # save/load as constants, not become loadable parameters
    from mxnet_tpu import gluon, nd

    class Relu6(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.clip(x, 0.0, 6.0)

    net = Relu6()
    net.initialize()
    net.hybridize()
    x = nd.array(np.array([[-1.0, 3.0, 9.0]], np.float32))
    net(x)
    net.export(str(tmp_path / "r6"))
    sb = gluon.SymbolBlock.imports(str(tmp_path / "r6-symbol.json"),
                                   ["data"])
    np.testing.assert_allclose(sb(x).asnumpy(), [[0.0, 3.0, 6.0]])


def test_symbol_contrib_namespace():
    """mx.sym.contrib mirrors the contrib op surface as graph builders
    (reference: python/mxnet/symbol/contrib.py)."""
    assert hasattr(mx.sym.contrib, "box_nms")
    d = mx.sym.Variable("dets")
    out = mx.sym.contrib.box_nms(d, overlap_thresh=0.5,
                                 valid_thresh=0.01)
    dets = np.array([[[0.9, 0.1, 0.1, 0.5, 0.5],
                      [0.8, 0.12, 0.12, 0.52, 0.52],
                      [0.7, 0.6, 0.6, 0.9, 0.9]]], np.float32)
    res = np.asarray(out.eval_raw(dets=dets))
    assert res.shape == (1, 3, 5)


def test_export_import_transformers(tmp_path):
    """Transformer models export (round 4): the trace now serves
    x.shape via recorded input shapes, lifts Symbol-valued op kwargs
    (packed-qkv MHA) into graph inputs, supports array indexing
    (pos_table[:T], seq[:, 0, :]) and multi-output Group round-trips —
    GPT (1 output) and full BERT (4 outputs) import bit-close."""
    import numpy as np

    from mxnet_tpu import nd
    from mxnet_tpu.gluon import SymbolBlock
    from mxnet_tpu.gluon.model_zoo import bert, gpt

    cases = [
        ("gpt", gpt.gpt_tiny(),
         nd.array(np.random.RandomState(0)
                  .randint(0, 128, (2, 10)).astype("float32"))),
        ("bert", bert.bert_tiny(use_decoder=True, use_pooler=True),
         nd.array(np.random.RandomState(0)
                  .randint(0, 100, (2, 12)).astype("float32"))),
    ]
    for name, net, inp in cases:
        net.initialize(init=mx.init.Xavier())
        out = net(inp)
        refs = list(out) if isinstance(out, tuple) else [out]
        net.hybridize()
        net(inp)
        p = str(tmp_path / name)
        net.export(p)
        sb = SymbolBlock.imports(f"{p}-symbol.json", ["data"],
                                 f"{p}-0000.params")
        got = sb(inp)
        gots = list(got) if isinstance(got, (tuple, list)) else [got]
        assert len(refs) == len(gots)
        for a, b in zip(refs, gots):
            np.testing.assert_allclose(b.asnumpy(), a.asnumpy(),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=name)


def test_attr_scope_applies_to_symbols():
    """mx.AttrScope attaches attrs to every node created in scope
    (reference: python/mxnet/attribute.py; the group2ctx /
    per-layer-lr_mult mechanism)."""
    import mxnet_tpu as mx

    with mx.AttrScope(ctx_group="stage1", lr_mult="0.1"):
        a = mx.sym.var("a")
        b = mx.sym.relu(a)
        # var()'s own (absent) lr_mult kwarg must NOT clobber the scope
        assert a.attr("lr_mult") == "0.1"
        with mx.AttrScope(ctx_group="stage2"):  # inner overrides
            c = mx.sym.exp(b)
    d = mx.sym.log(c)  # outside: no scope attrs
    assert a.attr("ctx_group") == "stage1"
    assert b.attr("ctx_group") == "stage1"
    assert b.attr("lr_mult") == "0.1"
    assert c.attr("ctx_group") == "stage2"
    assert c.attr("lr_mult") == "0.1"
    assert d.attr("ctx_group") is None


def test_module_level_random_and_bulk_size():
    """mx.random.uniform/normal (module level, reference random.py) and
    mx.engine.set_bulk_size exist and behave."""
    import numpy as np

    import mxnet_tpu as mx

    mx.random.seed(7)
    u = mx.random.uniform(0, 1, shape=(100,))
    n = mx.random.normal(0, 1, shape=(100,))
    assert u.shape == (100,) and n.shape == (100,)
    un = u.asnumpy()
    assert (un >= 0).all() and (un <= 1).all()
    assert abs(float(np.mean(n.asnumpy()))) < 0.5
    prev = mx.engine.set_bulk_size(30)
    assert isinstance(prev, int)
    assert mx.engine.set_bulk_size(prev) == 30


def test_attr_scope_lr_mult_freezes_layer_in_module():
    """End-to-end AttrScope -> Optimizer.sym_info: a variable created
    under AttrScope(lr_mult='0.0') must not move during Module training
    (reference: Optimizer.set_lr_mult reading __lr_mult__ symbol
    attrs)."""
    import numpy as np

    import mxnet_tpu as mx

    x = mx.sym.var("data")
    with mx.AttrScope(lr_mult="0.0"):
        frozen_w = mx.sym.var("frozen_weight")
    h = mx.sym.FullyConnected(x, frozen_w, None, num_hidden=4,
                              no_bias=True, name="fc1")
    out = mx.sym.FullyConnected(h, mx.sym.var("fc2_weight"), None,
                                num_hidden=1, no_bias=True, name="fc2")
    loss = mx.sym.MakeLoss(mx.sym.mean(mx.sym.square(out)))

    mod = mx.mod.Module(loss, data_names=("data",), label_names=())
    batch = mx.io.DataBatch(data=[mx.nd.array(
        np.random.RandomState(0).randn(8, 6).astype(np.float32))])
    mod.bind(data_shapes=[("data", (8, 6))], label_shapes=None)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),))
    assert mod._optimizer.lr_mult.get("frozen_weight") == 0.0
    w0 = mod._exec.arg_dict["frozen_weight"].asnumpy().copy()
    f0 = mod._exec.arg_dict["fc2_weight"].asnumpy().copy()
    for _ in range(3):
        mod.forward(batch)
        mod.backward()
        mod.update()
    np.testing.assert_array_equal(
        mod._exec.arg_dict["frozen_weight"].asnumpy(), w0)
    assert not np.allclose(mod._exec.arg_dict["fc2_weight"].asnumpy(), f0)


def test_node_attrs_survive_json_roundtrip(tmp_path):
    """AttrScope/lr_mult node attrs serialize into symbol.json and a
    load inside an ACTIVE AttrScope must not stamp the ambient scope
    onto loaded nodes (reference loader bypasses AttrScope)."""
    x = mx.sym.var("data")
    with mx.AttrScope(lr_mult="0.0", ctx_group="s1"):
        w = mx.sym.var("w")
    y = mx.sym.FullyConnected(x, w, None, num_hidden=2, no_bias=True,
                              name="fc")
    f = str(tmp_path / "net-symbol.json")
    y.save(f)
    y2 = mx.sym.load(f)
    ad = y2.attr_dict()
    assert ad["w"]["lr_mult"] == "0.0"
    assert ad["w"]["ctx_group"] == "s1"
    assert "lr_mult" not in ad.get("data", {})
    # ambient scope must not leak into deserialized nodes
    with mx.AttrScope(lr_mult="9.9"):
        y3 = mx.sym.load(f)
    assert y3.attr_dict()["w"]["lr_mult"] == "0.0"
    assert "lr_mult" not in y3.attr_dict().get("data", {})
    # upstream-MXNet format: dunder user attrs in a variable's "attrs"
    # dict must surface in attr_dict() (Optimizer.sym_info interop)
    import json as _json

    doc = _json.loads(y.tojson())
    wnode = next(n for n in doc["nodes"] if n["name"] == "w")
    assert wnode["attrs"]["lr_mult"] == "0.0"  # serialized in-format
    wnode["attrs"]["__lr_mult__"] = "0.25"
    del wnode["attrs"]["lr_mult"]
    y4 = mx.sym.fromjson(_json.dumps(doc))
    assert y4.attr_dict()["w"]["__lr_mult__"] == "0.25"


def test_monitor_collects_layer_stats():
    """mx.monitor.Monitor through Module.fit(monitor=...) (reference:
    python/mxnet/monitor.py): interval-gated collection of per-node
    output stats, pattern filtering."""
    import logging

    mon = mx.monitor.Monitor(interval=2, pattern=".*fc.*")
    rng = np.random.RandomState(0)
    data = rng.randn(32, 10).astype(np.float32)
    labels = rng.randint(0, 4, 32).astype(np.float32)
    it = mx.io.NDArrayIter(data, labels, batch_size=16,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier(), monitor=mon)
    # interval=2 over 2 batches -> one collection, fc nodes only
    assert mon.queue, "monitor collected nothing"
    names = {name for _, name, _ in mon.queue}
    assert any("fc1" in n for n in names), names
    assert all("softmax" not in n for n in names), names
    for _, _, stat in mon.queue:
        v = float(stat.asnumpy())
        assert np.isfinite(v) and v >= 0

    # manual tic/toc surface on a bare executor
    mon2 = mx.mon.Monitor(interval=1, sort=True)
    exe = _mlp_symbol().simple_bind(
        data=(4, 10), fc1_weight=(16, 10), fc1_bias=(16,),
        fc2_weight=(4, 16), fc2_bias=(4,), softmax_label=(4,))
    mon2.install(exe)
    mon2.tic()
    exe.forward()
    res = mon2.toc()
    assert res and [r[1] for r in res] == sorted(r[1] for r in res)


def test_monitor_mode_and_prng_isolation():
    """Review regressions: toc() re-evaluates in the mode the monitored
    forward used (train-mode dropout ACTIVE in stats), and must not
    advance the global PRNG stream (observer effect)."""
    import mxnet_tpu.random as mxrand

    x = mx.sym.var("data")
    d = mx.sym.Dropout(x, p=0.5, name="drop")
    out = mx.sym.MakeLoss(mx.sym.mean(d * d), name="loss")
    exe = out.simple_bind(data=(64, 64))
    exe.arg_dict["data"]._set_data(mx.nd.ones((64, 64))._data)
    mon = mx.monitor.Monitor(interval=1, pattern=".*drop.*")
    mon.install(exe)

    mon.tic()
    exe.forward(is_train=True)
    key_before = mxrand._STATE.key
    res = mon.toc()
    assert mxrand._STATE.key is key_before, \
        "toc() advanced the global PRNG stream"
    # train-mode dropout: mean |out| of kept/scaled ones is ~1, and the
    # zeros prove dropout actually ran (predict mode would give exactly 1)
    stats = {name: float(s.asnumpy()) for _, name, s in res}
    drop_stat = next(v for k, v in stats.items() if "drop" in k)
    assert 0.7 < drop_stat < 1.3, stats
    # re-eval the same node eagerly in predict mode: identity => 1.0
    mon2 = mx.monitor.Monitor(interval=1, pattern=".*drop.*")
    mon2.install(exe)
    mon2.tic()
    exe.forward(is_train=False)
    stats2 = {name: float(s.asnumpy())
              for _, name, s in mon2.toc()}
    drop2 = next(v for k, v in stats2.items() if "drop" in k)
    assert abs(drop2 - 1.0) < 1e-6, stats2

    # rebind eviction: a new executor over the same symbol replaces the
    # stale one
    exe2 = out.simple_bind(data=(8, 8))
    mon.install(exe2)
    assert len(mon._exes) == 1 and mon._exes[0] is exe2


def test_bucketing_module_variable_length_training():
    """BucketingModule (reference: bucketing_module.py) trains across
    two sequence buckets with SHARED parameters: per-bucket graphs are
    per-shape XLA programs, weights move together."""
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        w = mx.sym.var("fc_weight")
        b = mx.sym.var("fc_bias")
        # mean over the sequence then classify — same params any length
        pooled = mx.sym.mean(data, axis=1)
        out = mx.sym.FullyConnected(pooled, w, b, num_hidden=3,
                                    name="fc")
        return (mx.sym.SoftmaxOutput(out, label, name="softmax"),
                ("data",), ("softmax_label",))

    rng = np.random.RandomState(0)
    centers = rng.uniform(-2, 2, (3, 6)).astype(np.float32)

    def batch(seq_len, n=16):
        y = rng.randint(0, 3, n)
        x = centers[y][:, None, :] + rng.normal(
            0, 0.3, (n, seq_len, 6)).astype(np.float32)
        return mx.io.DataBatch(
            data=[mx.nd.array(x)], label=[mx.nd.array(y.astype("f"))],
            bucket_key=seq_len,
            provide_data=[("data", (n, seq_len, 6))],
            provide_label=[("softmax_label", (n,))])

    bm = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    bm.bind(data_shapes=[("data", (16, 8, 6))],
            label_shapes=[("softmax_label", (16,))])
    bm.init_params(initializer=mx.init.Xavier())
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params=(("learning_rate", 0.5),))
    metric = mx.metric.Accuracy()
    for step in range(30):
        b = batch(8 if step % 2 == 0 else 4)  # alternate buckets
        bm.forward(b, is_train=True)
        bm.backward()
        bm.update()
    # both buckets classify well with the shared weights
    metric.reset()
    for L in (8, 4):
        b = batch(L)
        bm.forward(b, is_train=False)
        bm.update_metric(metric, b.label)
    assert metric.get()[1] > 0.9, metric.get()
    # the two bucket modules literally share parameter values
    arg8, _ = bm._buckets[8].get_params()
    arg4, _ = bm._buckets[4].get_params()
    np.testing.assert_allclose(arg8["fc_weight"].asnumpy(),
                               arg4["fc_weight"].asnumpy())
