"""Optimizer tests.

Mirrors the reference's tests/python/unittest/test_optimizer.py strategy:
each fused update op is checked against an independent numpy reimplementation
of the reference kernel semantics (src/operator/optimizer_op-inl.h).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _nd(a):
    return mx.nd.array(np.asarray(a, dtype=np.float32))


def test_sgd_matches_numpy():
    w0 = np.random.uniform(-1, 1, (5, 4)).astype(np.float32)
    g0 = np.random.uniform(-1, 1, (5, 4)).astype(np.float32)
    w, g = _nd(w0), _nd(g0)
    o = opt.SGD(learning_rate=0.1, momentum=0.9, wd=0.01, rescale_grad=0.5)
    state = o.create_state(0, w)
    mom = np.zeros_like(w0)
    for _ in range(3):
        o.update(0, w, g, state)
        grad = 0.5 * g0 + 0.01 * w0
        mom = 0.9 * mom - 0.1 * grad
        w0 = w0 + mom
    np.testing.assert_allclose(w.asnumpy(), w0, rtol=1e-5, atol=1e-6)


def test_sgd_no_momentum_and_clip():
    w0 = np.ones((3,), np.float32)
    g0 = np.array([10.0, -10.0, 0.1], np.float32)
    w, g = _nd(w0), _nd(g0)
    o = opt.SGD(learning_rate=0.1, clip_gradient=1.0)
    o.update(0, w, g, o.create_state(0, w))
    exp = w0 - 0.1 * np.clip(g0, -1, 1)
    np.testing.assert_allclose(w.asnumpy(), exp, rtol=1e-6)


def test_adam_matches_numpy():
    w0 = np.random.uniform(-1, 1, (7,)).astype(np.float32)
    g0 = np.random.uniform(-1, 1, (7,)).astype(np.float32)
    w, g = _nd(w0), _nd(g0)
    o = opt.Adam(learning_rate=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8)
    state = o.create_state(0, w)
    m = np.zeros_like(w0)
    v = np.zeros_like(w0)
    for t in range(1, 4):
        o.update(0, w, g, state)
        lr_t = 0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        m = 0.9 * m + 0.1 * g0
        v = 0.999 * v + 0.001 * g0 ** 2
        w0 = w0 - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(w.asnumpy(), w0, rtol=1e-5, atol=1e-6)


def test_nag_matches_reference_fallback():
    w0 = np.random.uniform(-1, 1, (6,)).astype(np.float32)
    g0 = np.random.uniform(-1, 1, (6,)).astype(np.float32)
    w, g = _nd(w0), _nd(g0)
    o = opt.NAG(learning_rate=0.1, momentum=0.9)
    state = o.create_state(0, w)
    mom = np.zeros_like(w0)
    for _ in range(2):
        o.update(0, w, g, state)
        grad = g0
        mom = 0.9 * mom + grad
        w0 = w0 - 0.1 * (grad + 0.9 * mom)
    np.testing.assert_allclose(w.asnumpy(), w0, rtol=1e-5, atol=1e-6)


def test_rmsprop_and_centered():
    w = _nd(np.ones((4,)))
    g = _nd(np.full((4,), 0.5))
    o = opt.RMSProp(learning_rate=0.01)
    o.update(0, w, g, o.create_state(0, w))
    assert np.all(np.isfinite(w.asnumpy()))
    w2 = _nd(np.ones((4,)))
    o2 = opt.RMSProp(learning_rate=0.01, centered=True)
    o2.update(0, w2, g, o2.create_state(0, w2))
    assert np.all(np.isfinite(w2.asnumpy()))


def test_ftrl_sparsifies():
    w = _nd(np.ones((4,)))
    g = _nd(np.full((4,), 1e-4))
    o = opt.Ftrl(learning_rate=0.1, lamda1=1.0)
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    # tiny gradients + strong l1 → weights snap to zero
    np.testing.assert_allclose(w.asnumpy(), np.zeros(4), atol=1e-6)


def test_signum():
    w0 = np.zeros((3,), np.float32)
    w = _nd(w0)
    g = _nd(np.array([0.5, -2.0, 0.0]))
    o = opt.Signum(learning_rate=0.1, momentum=0.0)
    o.update(0, w, g, o.create_state(0, w))
    np.testing.assert_allclose(w.asnumpy(), [-0.1, 0.1, 0.0], atol=1e-7)


def test_lamb_runs():
    w = _nd(np.random.uniform(-1, 1, (8, 4)))
    g = _nd(np.random.uniform(-1, 1, (8, 4)))
    o = opt.LAMB(learning_rate=0.01)
    state = o.create_state(0, w)
    before = w.asnumpy().copy()
    o.update(0, w, g, state)
    assert np.all(np.isfinite(w.asnumpy()))
    assert not np.allclose(before, w.asnumpy())


def test_lars_oracle_and_bias_path():
    """lars_update against a numpy oracle (trust ratio × SGD-mom) on a
    2-D weight; 1-D params take the plain SGD-momentum step (the
    reference LBSGD skip list)."""
    rs = np.random.RandomState(0)
    w0 = rs.uniform(-1, 1, (6, 3)).astype(np.float32)
    g0 = rs.uniform(-1, 1, (6, 3)).astype(np.float32)
    lr, eta, mom_c, wd = 0.1, 0.01, 0.9, 0.001
    w, g = _nd(w0.copy()), _nd(g0)
    o = opt.LARS(learning_rate=lr, eta=eta, momentum=mom_c, wd=wd)
    st = o.create_state(0, w)
    o.update(0, w, g, st)
    ratio = eta * np.linalg.norm(w0) / (
        np.linalg.norm(g0) + wd * np.linalg.norm(w0) + 1e-9)
    mom = -lr * ratio * (g0 + wd * w0)
    np.testing.assert_allclose(w.asnumpy(), w0 + mom, rtol=1e-5,
                               atol=1e-6)
    # second step exercises momentum accumulation
    o.update(0, w, g, st)
    assert np.all(np.isfinite(w.asnumpy()))
    # 1-D bias: no trust ratio — exact SGD-momentum result
    b0 = rs.uniform(-1, 1, (4,)).astype(np.float32)
    b, gb = _nd(b0.copy()), _nd(np.full((4,), 0.5, np.float32))
    stb = o.create_state(1, b)
    o.update(1, b, gb, stb)
    np.testing.assert_allclose(
        b.asnumpy(), b0 - lr * (0.5 + wd * b0), rtol=1e-6)


def test_ftml_oracle():
    """ftml_update against a numpy oracle of the FTML recurrence
    (Zheng & Kwok 2017), two steps so d_{t-1}/z carry-over is checked."""
    rs = np.random.RandomState(1)
    w0 = rs.uniform(-1, 1, (5, 2)).astype(np.float32)
    lr, b1, b2, eps, wd, clip = 0.01, 0.6, 0.999, 1e-8, 0.001, 0.5
    w = _nd(w0.copy())
    o = opt.FTML(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps, wd=wd,
                 clip_gradient=clip)
    st = o.create_state(0, w)
    wn = w0.copy()
    d = v = z = np.zeros_like(w0)
    for t in (1, 2):
        g0 = rs.uniform(-1, 1, (5, 2)).astype(np.float32)
        o.update(0, w, _nd(g0), st)
        # ftml folds wd in BEFORE clipping (reference kernel order)
        grad = np.clip(g0 + wd * wn, -clip, clip)
        v = b2 * v + (1 - b2) * grad ** 2
        d_t = (1 - b1 ** t) / lr * (np.sqrt(v / (1 - b2 ** t)) + eps)
        sigma = d_t - b1 * d
        z = b1 * z + (1 - b1) * grad - sigma * wn
        wn = -z / d_t
        d = d_t
        np.testing.assert_allclose(w.asnumpy(), wn, rtol=1e-5, atol=1e-6)


def test_lbsgd_warmup_scales_lr():
    """LBSGD = LARS + warmup: the effective lr ramps linearly to
    lr*batch_scale over warmup_epochs*updates_per_epoch updates."""
    o = opt.LBSGD(learning_rate=0.1, momentum=0.9, batch_scale=4,
                  warmup_strategy="linear", warmup_epochs=1,
                  updates_per_epoch=10)
    assert isinstance(o, opt.LARS)
    w = _nd(np.ones((3, 2)))
    st = o.create_state(0, w)
    o.update(0, w, _nd(np.full((3, 2), 0.1, np.float32)), st)
    assert o._get_lr(0) == pytest.approx(0.1 * (1 + 0.1 * 3))  # t=1/10
    for _ in range(20):
        o.update(0, w, _nd(np.full((3, 2), 0.1, np.float32)), st)
    assert o._get_lr(0) == pytest.approx(0.4)  # fully warmed: lr*scale
    assert np.all(np.isfinite(w.asnumpy()))


def test_multi_precision_master_weights():
    w = _nd(np.ones((5,))).astype(np.float16)
    g = _nd(np.full((5,), 0.1)).astype(np.float16)
    o = opt.SGD(learning_rate=0.01, momentum=0.9, multi_precision=True)
    state = o.create_state_multi_precision(0, w)
    master, _ = state
    assert master.dtype == np.float32
    for _ in range(5):
        o.update_multi_precision(0, w, g, state)
    assert w.dtype == np.float16
    # master accumulates in fp32, fp16 copy tracks it
    np.testing.assert_allclose(w.asnumpy(), master.asnumpy(), rtol=1e-3)


def test_lr_scheduler_factor():
    s = opt.FactorScheduler(step=10, factor=0.1, base_lr=1.0)
    assert s(1) == 1.0
    assert abs(s(25) - 0.01) < 1e-9


def test_lr_scheduler_warmup_cosine():
    s = opt.CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0,
                            warmup_steps=10, warmup_begin_lr=0.0)
    assert s(5) == pytest.approx(0.5)
    assert s(100) == pytest.approx(0.0, abs=1e-9)


def test_optimizer_registry_and_updater_roundtrip():
    o = opt.create("sgd", learning_rate=0.5, momentum=0.9)
    assert isinstance(o, opt.SGD)
    u = opt.get_updater(o)
    w = _nd(np.ones((3,)))
    g = _nd(np.ones((3,)))
    u(0, g, w)
    blob = u.get_states()
    u2 = opt.get_updater(opt.create("sgd", learning_rate=0.5, momentum=0.9))
    u2.set_states(blob)
    np.testing.assert_allclose(u2.states[0].asnumpy(),
                               u.states[0].asnumpy())


def test_fused_op_reference_api():
    # reference call pattern: mx.nd.sgd_mom_update(w, g, mom, out=w, ...)
    w = _nd(np.ones((2, 2)))
    g = _nd(np.ones((2, 2)))
    mom = _nd(np.zeros((2, 2)))
    out = mx.nd.sgd_mom_update(w, g, mom, out=w, lr=0.1, momentum=0.9,
                               wd=0.0)
    assert out is w
    np.testing.assert_allclose(w.asnumpy(), 0.9 * np.ones((2, 2)),
                               rtol=1e-6)
    # momentum state mutated in place (reference contract)
    np.testing.assert_allclose(mom.asnumpy(), -0.1 * np.ones((2, 2)),
                               rtol=1e-6)


def test_lr_wd_mult():
    o = opt.SGD(learning_rate=1.0, param_idx2name={0: "w_weight",
                                                   1: "b_bias"}, wd=0.1)
    o.set_lr_mult({"w_weight": 0.5})
    assert o._get_lr(0) == 0.5
    assert o._get_lr(1) == 1.0
    # bias gets wd_mult 0 automatically (reference behavior)
    assert o._get_wd(1) == 0.0


# -- gradient compression (reference: src/kvstore/gradient_compression.cc) -----

def test_gradient_compression_2bit_error_feedback():
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", nd.zeros(4))
    kv.set_updater(lambda k, g, s: s._set_data((s + g)._data))

    g = nd.array([0.6, -0.7, 0.2, 0.3])
    kv.push("w", g)
    out = nd.zeros(4)
    kv.pull("w", out=out)
    # quantized: [0.5, -0.5, 0, 0]; residual [0.1, -0.2, 0.2, 0.3]
    np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0],
                               atol=1e-6)
    # second push: acc = g + r = [0.7, -0.9, 0.4, 0.6]
    #   -> q [0.5, -0.5, 0, 0.5]; store accumulates
    kv.push("w", g)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), [1.0, -1.0, 0.0, 0.5],
                               atol=1e-6)


def test_gradient_compression_fp16():
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "fp16"})
    kv.init("w", nd.zeros(3))
    kv.set_updater(lambda k, g, s: s._set_data((s + g)._data))
    vals = np.array([1.0 + 2 ** -12, -3.14159, 1e-8], np.float32)
    kv.push("w", nd.array(vals))
    out = nd.zeros(3)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(),
                               vals.astype(np.float16).astype(np.float32))


def test_gradient_compression_rejects_local():
    import pytest

    import mxnet_tpu as mx

    kv = mx.kv.create("local")
    with pytest.raises(mx.base.MXNetError):
        kv.set_gradient_compression({"type": "2bit"})


def test_gradient_compression_pack_decode_roundtrip():
    import jax.numpy as jnp

    from mxnet_tpu.gradient_compression import GradientCompression

    gc = GradientCompression({"type": "2bit", "threshold": 0.25})
    rs = np.random.RandomState(0)
    g = jnp.asarray(rs.randn(103).astype(np.float32))  # non-multiple of 4
    packed = gc.codes("k", g)
    assert packed.dtype == jnp.uint8 and packed.size == (103 + 1) // 4 * 1
    dec = GradientCompression.decode_sum(packed[None], 103, 0.25,
                                         jnp.float32)
    expect = np.where(np.asarray(g) >= 0.25, 0.25,
                      np.where(np.asarray(g) <= -0.25, -0.25, 0.0))
    np.testing.assert_allclose(np.asarray(dec), expect, atol=1e-7)
    # residual carries the quantization error
    r = np.asarray(gc._residual["k"])
    np.testing.assert_allclose(r, np.asarray(g) - expect, atol=1e-6)
