"""Flat C API (L5) round-trip tests.

Reference parity: the role of include/mxnet/c_api.h + src/c_api/ — the
ABI a second language frontend builds on.  Two proofs:

1. ctypes round-trip (attached mode): this Python process loads
   libmxtpu.so and drives NDArray/op/autograd/KVStore through the C
   surface only — exactly what a Java/Go binding would generate.
2. embedded mode: a pure C program is compiled with g++ against
   mxtpu_c_api.h, linked to libmxtpu.so, and run as its own process with
   NO Python code of its own — it boots the runtime via MXTPUInit().
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpu_free_env(**extra):
    """Env for subprocesses that must never claim the TPU tunnel."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_",
                                "LIBTPU"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env
LIB = os.path.join(ROOT, "src", "libmxtpu.so")


def _build_lib():
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "-C", os.path.join(ROOT, "src")],
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"cannot build libmxtpu.so: {r.stderr[-300:]}")
    return LIB


@pytest.fixture(scope="module")
def capi():
    _build_lib()
    lib = ctypes.CDLL(LIB)
    lib.MXGetLastError.restype = ctypes.c_char_p
    assert lib.MXTPUInit() == 0, lib.MXGetLastError().decode()
    return lib


def _err(lib):
    return lib.MXGetLastError().decode()


def _create(lib, arr):
    arr = np.ascontiguousarray(arr)
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    rc = lib.MXNDArrayCreate(
        arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, shape, arr.ndim,
        arr.dtype.name.encode(), ctypes.byref(h))
    assert rc == 0, _err(lib)
    return h


def _read(lib, h, shape, dtype=np.float32):
    out = np.empty(shape, dtype)
    rc = lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
    assert rc == 0, _err(lib)
    return out


def _invoke(lib, name, handles, params=None, n_out=4):
    params = params or {}
    keys = (ctypes.c_char_p * len(params))(
        *[k.encode() for k in params])
    vals = (ctypes.c_char_p * len(params))(
        *[str(v).encode() for v in params.values()])
    ins = (ctypes.c_void_p * len(handles))(
        *[h.value for h in handles])
    outs = (ctypes.c_void_p * n_out)()
    n = ctypes.c_int(n_out)
    rc = lib.MXImperativeInvoke(name.encode(), ins, len(handles), keys,
                                vals, len(params), outs,
                                ctypes.byref(n))
    assert rc == 0, _err(lib)
    return [ctypes.c_void_p(outs[i]) for i in range(n.value)]


def test_c_api_ndarray_roundtrip(capi):
    lib = capi
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    h = _create(lib, x)
    ndim = ctypes.c_int()
    shape = (ctypes.c_int64 * 8)()
    assert lib.MXNDArrayGetShape(h, ctypes.byref(ndim), shape) == 0
    assert (ndim.value, shape[0], shape[1]) == (2, 2, 3)
    dt = ctypes.create_string_buffer(16)
    assert lib.MXNDArrayGetDType(h, dt) == 0
    assert dt.value == b"float32"
    np.testing.assert_array_equal(_read(lib, h, (2, 3)), x)
    assert lib.MXNDArrayFree(h) == 0


def test_c_api_invoke_op(capi):
    lib = capi
    x = np.linspace(-1, 1, 6, dtype=np.float32).reshape(2, 3)
    h = _create(lib, x)
    (out,) = _invoke(lib, "sin", [h])
    np.testing.assert_allclose(_read(lib, out, (2, 3)), np.sin(x),
                               rtol=1e-6)
    # op with a string-encoded param
    (t,) = _invoke(lib, "transpose", [h], {"axes": "(1, 0)"})
    np.testing.assert_array_equal(_read(lib, t, (3, 2)), x.T)
    for hh in (h, out, t):
        lib.MXNDArrayFree(hh)


def test_c_api_list_ops(capi):
    lib = capi
    count = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXListAllOpNames(ctypes.byref(count),
                                ctypes.byref(names)) == 0
    got = {names[i].decode() for i in range(count.value)}
    assert {"sin", "FullyConnected", "Convolution"} <= got
    assert count.value > 300


def test_c_api_autograd(capi):
    lib = capi
    x = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    h = _create(lib, x)
    assert lib.MXAutogradAttachGrad(h) == 0, _err(lib)
    assert lib.MXAutogradRecordStart() == 0
    (sq,) = _invoke(lib, "square", [h])
    (loss,) = _invoke(lib, "sum", [sq])
    assert lib.MXAutogradRecordStop() == 0
    assert lib.MXAutogradBackward(loss) == 0, _err(lib)
    g = ctypes.c_void_p()
    assert lib.MXNDArrayGetGrad(h, ctypes.byref(g)) == 0, _err(lib)
    np.testing.assert_allclose(_read(lib, g, (3,)), 2 * x, rtol=1e-6)
    for hh in (h, sq, loss, g):
        lib.MXNDArrayFree(hh)


def test_c_api_kvstore(capi):
    lib = capi
    kv = ctypes.c_int()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0, _err(lib)
    v = np.ones(4, dtype=np.float32)
    h = _create(lib, v)
    assert lib.MXKVStoreInit(kv, 3, h) == 0, _err(lib)
    h2 = _create(lib, 2 * v)
    assert lib.MXKVStorePush(kv, 3, h2) == 0, _err(lib)
    out = ctypes.c_void_p()
    assert lib.MXKVStorePull(kv, 3, ctypes.byref(out)) == 0, _err(lib)
    np.testing.assert_allclose(_read(lib, out, (4,)), 2 * v)
    assert lib.MXKVStoreFree(kv) == 0
    for hh in (h, h2, out):
        lib.MXNDArrayFree(hh)


def test_c_api_error_reporting(capi):
    lib = capi
    x = _create(lib, np.ones(2, np.float32))
    outs = (ctypes.c_void_p * 1)()
    n = ctypes.c_int(1)
    rc = lib.MXImperativeInvoke(b"definitely_not_an_op",
                                (ctypes.c_void_p * 1)(x.value), 1,
                                None, None, 0, outs, ctypes.byref(n))
    assert rc == -1
    assert "definitely_not_an_op" in _err(lib)
    lib.MXNDArrayFree(x)


_C_SMOKE = r"""
#include <stdio.h>
#include <stdlib.h>
#include <math.h>
#include "mxtpu_c_api.h"

int main(void) {
  if (MXTPUInit() != 0) {
    fprintf(stderr, "init: %s\n", MXGetLastError());
    return 1;
  }
  float data[6] = {0.f, 1.f, 2.f, 3.f, 4.f, 5.f};
  int64_t shape[2] = {2, 3};
  NDArrayHandle x, y;
  if (MXNDArrayCreate(data, sizeof(data), shape, 2, "float32", &x) != 0) {
    fprintf(stderr, "create: %s\n", MXGetLastError());
    return 1;
  }
  NDArrayHandle outs[1];
  int n_out = 1;
  if (MXImperativeInvoke("sin", &x, 1, NULL, NULL, 0, outs, &n_out) != 0) {
    fprintf(stderr, "invoke: %s\n", MXGetLastError());
    return 1;
  }
  y = outs[0];
  float back[6];
  if (MXNDArraySyncCopyToCPU(y, back, sizeof(back)) != 0) {
    fprintf(stderr, "copy: %s\n", MXGetLastError());
    return 1;
  }
  for (int i = 0; i < 6; ++i) {
    if (fabsf(back[i] - sinf(data[i])) > 1e-5f) {
      fprintf(stderr, "value mismatch at %d: %f vs %f\n", i, back[i],
              sinf(data[i]));
      return 1;
    }
  }
  MXNDArrayFree(x);
  MXNDArrayFree(y);
  printf("C_SMOKE_OK\n");
  return 0;
}
"""


def test_c_frontend_smoke(tmp_path):
    """A second frontend exists: pure C, no Python source, drives the
    framework through libmxtpu.so alone."""
    _build_lib()
    src = tmp_path / "smoke.c"
    src.write_text(_C_SMOKE)
    exe = tmp_path / "smoke"
    build = subprocess.run(
        ["g++", "-x", "c", str(src), "-o", str(exe),
         f"-I{os.path.join(ROOT, 'src')}",
         f"-L{os.path.join(ROOT, 'src')}", "-lmxtpu",
         f"-Wl,-rpath,{os.path.join(ROOT, 'src')}"],
        capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"cannot compile C smoke: {build.stderr[-300:]}")
    env = _tpu_free_env(PYTHONPATH=ROOT)
    r = subprocess.run([str(exe)], env=env, capture_output=True,
                       text=True, timeout=240)
    assert r.returncode == 0, (r.stdout, r.stderr[-500:])
    assert "C_SMOKE_OK" in r.stdout


def test_cpp_package_linreg_example(capi):
    """The C++ binding (cpp-package/) trains linear regression through
    the C ABI only — the reference's cpp-package/example analog."""
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    src = os.path.join(ROOT, "cpp-package", "example", "linreg.cpp")
    inc = os.path.join(ROOT, "cpp-package", "include", "mxnet-tpu-cpp")
    binp = os.path.join(ROOT, "src", ".linreg_cpp_test")
    r = subprocess.run(
        ["g++", "-std=c++17", src, f"-I{inc}",
         f"-I{os.path.join(ROOT, 'src')}",
         f"-L{os.path.join(ROOT, 'src')}", "-lmxtpu",
         f"-Wl,-rpath,{os.path.join(ROOT, 'src')}", "-o", binp],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-500:]
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    try:
        run = subprocess.run([binp], capture_output=True, text=True,
                             env=env, timeout=240)
        assert run.returncode == 0, (run.stdout[-300:], run.stderr[-300:])
        assert "PASS" in run.stdout
    finally:
        if os.path.exists(binp):
            os.remove(binp)


def test_c_predict_api_roundtrip(capi, tmp_path):
    """MXPred* deploy surface (reference: include/mxnet/c_predict_api.h):
    export a trained net, run inference through the C predictor only,
    match the in-process output."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    lib = capi
    rs = np.random.RandomState(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    net.hybridize()
    x = nd.array(rs.randn(2, 5).astype("float32"))
    net(x)
    with autograd.predict_mode():
        ref = net(x).asnumpy()
    net.export(str(tmp_path / "pred"))
    sym_json = (tmp_path / "pred-symbol.json").read_text()
    param_bytes = (tmp_path / "pred-0000.params").read_bytes()

    import ctypes

    lib.MXPredCreate.restype = ctypes.c_int
    h = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"data")
    rc = lib.MXPredCreate(sym_json.encode(), param_bytes,
                          len(param_bytes), 1, 0, 1, keys,
                          ctypes.byref(h))
    assert rc == 0, _err(lib)
    data = np.ascontiguousarray(x.asnumpy(), np.float32)
    shape = (ctypes.c_int64 * 2)(2, 5)
    rc = lib.MXPredSetInput(h, b"data",
                            data.ctypes.data_as(
                                ctypes.POINTER(ctypes.c_float)),
                            shape, 2)
    assert rc == 0, _err(lib)
    assert lib.MXPredForward(h) == 0, _err(lib)
    ndim = ctypes.c_int()
    oshape = (ctypes.c_int64 * 8)()
    assert lib.MXPredGetOutputShape(h, 0, ctypes.byref(ndim),
                                    oshape) == 0, _err(lib)
    shp = tuple(oshape[i] for i in range(ndim.value))
    assert shp == (2, 3), shp
    out = np.empty(shp, np.float32)
    assert lib.MXPredGetOutput(
        h, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size) == 0, _err(lib)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    assert lib.MXPredFree(h) == 0, _err(lib)


def test_cpp_generated_op_wrappers(capi):
    """cpp-package/OpWrapperGenerator.py output compiles and the typed
    wrappers drive real ops (reference: generated mxnet-cpp op.h)."""
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    hpp = os.path.join(ROOT, "cpp-package", "include", "mxnet-tpu-cpp",
                       "ops.hpp")
    # regenerate to prove the generator tracks the live registry
    r = subprocess.run([sys.executable,
                        os.path.join(ROOT, "cpp-package",
                                     "OpWrapperGenerator.py")],
                       capture_output=True, text=True,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-400:]
    assert os.path.exists(hpp)
    src = os.path.join(ROOT, "src", ".ops_smoke.cpp")
    binp = os.path.join(ROOT, "src", ".ops_smoke_test")
    with open(src, "w") as f:
        f.write("""
#include <cstdio>
#include "ndarray.hpp"
#include "ops.hpp"
int main() {
  mxtpu::cpp::Init();
  mxtpu::cpp::NDArray x(std::vector<float>{-1.0f, 2.0f, -3.0f}, {3});
  auto v = mxtpu::cpp::op::abs(x)[0].ToVector();
  auto rv = mxtpu::cpp::op::activation(
      x, {{"act_type", "relu"}})[0].ToVector();
  if (v[0] == 1 && v[2] == 3 && rv[0] == 0 && rv[1] == 2) {
    std::printf("PASS\\n");
    return 0;
  }
  return 1;
}
""")
    try:
        rc = subprocess.run(
            ["g++", "-std=c++17", src,
             f"-I{os.path.join(ROOT, 'cpp-package', 'include', 'mxnet-tpu-cpp')}",
             f"-I{os.path.join(ROOT, 'src')}",
             f"-L{os.path.join(ROOT, 'src')}", "-lmxtpu",
             f"-Wl,-rpath,{os.path.join(ROOT, 'src')}", "-o", binp],
            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr[-500:]
        env = dict(os.environ)
        env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        run = subprocess.run([binp], capture_output=True, text=True,
                             env=env, timeout=240)
        assert run.returncode == 0 and "PASS" in run.stdout, \
            (run.stdout[-200:], run.stderr[-200:])
    finally:
        for p in (src, binp):
            if os.path.exists(p):
                os.remove(p)


def test_perl_package_linreg_example(capi):
    """The Perl binding (perl-package/, the reference's AI::MXNet
    analog) trains linear regression through the C ABI only: XS shim
    over libmxtpu.so + generated typed op wrappers
    (OpWrapperGenerator.py over the live registry)."""
    import shutil

    if shutil.which("perl") is None:
        pytest.skip("no perl")
    pp = os.path.join(ROOT, "perl-package")
    env = _tpu_free_env(PYTHONPATH=ROOT)
    mm = subprocess.run(["perl", "-MExtUtils::MakeMaker", "-e", "1"],
                        capture_output=True, text=True)
    if mm.returncode != 0:
        pytest.skip("no ExtUtils::MakeMaker")
    mk = subprocess.run(["perl", "Makefile.PL"], cwd=pp, env=env,
                        capture_output=True, text=True)
    assert mk.returncode == 0, mk.stderr[-500:]
    bld = subprocess.run(["make"], cwd=pp, env=env,
                         capture_output=True, text=True)
    assert bld.returncode == 0, bld.stderr[-500:]
    env["PERL5LIB"] = os.pathsep.join(
        [os.path.join(pp, "blib", "lib"),
         os.path.join(pp, "blib", "arch")])
    r = subprocess.run(["perl", os.path.join(pp, "example",
                                             "linreg.pl")],
                       cwd=ROOT, env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, (r.stdout[-300:], r.stderr[-300:])
    assert "PASS" in r.stdout


def test_perl_ops_pm_is_fresh():
    """The checked-in generated wrappers match the live registry (the
    same freshness guard the cpp-package generated header has)."""
    import tempfile

    gen = os.path.join(ROOT, "perl-package", "OpWrapperGenerator.py")
    committed = os.path.join(ROOT, "perl-package", "lib", "AI",
                             "MXNetTPU", "Ops.pm")
    env = _tpu_free_env(PYTHONPATH=ROOT)
    with tempfile.NamedTemporaryFile("r", suffix=".pm") as tmp:
        r = subprocess.run([sys.executable, gen, "-o", tmp.name],
                           env=env, capture_output=True, text=True,
                           timeout=240)
        assert r.returncode == 0, r.stderr[-400:]
        assert open(committed).read() == open(tmp.name).read(), \
            "Ops.pm is stale: re-run perl-package/OpWrapperGenerator.py"
