"""Model zoo tests (reference: tests/python/unittest/test_gluon_model_zoo.py).

Full-resolution ImageNet forwards are exercised on TPU by bench.py; here we
keep CPU-mesh costs sane: construct every family, forward the cheap ones.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision


def test_get_model_registry():
    with pytest.raises(ValueError):
        vision.get_model("no_such_model")
    net = vision.get_model("resnet18_v1", classes=10)
    assert net is not None


def test_resnet18_thumbnail_forward():
    net = vision.get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_resnet18_v2_thumbnail_forward():
    net = vision.get_model("resnet18_v2", classes=10, thumbnail=True)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_resnet50_structure():
    net = vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Xavier())
    # materialize deferred shapes with a tiny spatial input: conv stack
    # accepts any spatial size >= 32
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 1000)
    n_params = sum(int(np.prod(p.shape))
                   for p in net.collect_params().values())
    # ResNet-50 has ~25.6M parameters
    assert 24e6 < n_params < 27e6, n_params


def test_mobilenet_forward():
    net = vision.get_model("mobilenet0.25", classes=10)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_mobilenet_v2_forward():
    net = vision.get_model("mobilenetv2_0.25", classes=10)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_squeezenet_forward():
    net = vision.get_model("squeezenet1.1", classes=10)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_densenet_constructs():
    net = vision.densenet121(classes=10)
    assert net is not None


def test_vgg_alexnet_inception_construct():
    assert vision.vgg11(classes=10) is not None
    assert vision.alexnet(classes=10) is not None
    assert vision.inception_v3(classes=10) is not None


def test_model_zoo_save_load(tmp_path):
    net = vision.get_model("resnet18_v1", classes=4, thumbnail=True)
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.random_normal(shape=(1, 3, 32, 32))
    ref = net(x).asnumpy()
    f = str(tmp_path / "r18.params")
    net.save_parameters(f)
    net2 = vision.get_model("resnet18_v1", classes=4, thumbnail=True)
    net2.load_parameters(f)
    np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-5,
                               atol=1e-6)
