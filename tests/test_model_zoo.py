"""Model zoo tests (reference: tests/python/unittest/test_gluon_model_zoo.py).

Full-resolution ImageNet forwards are exercised on TPU by bench.py; here we
keep CPU-mesh costs sane: construct every family, forward the cheap ones.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision


def test_get_model_registry():
    with pytest.raises(ValueError):
        vision.get_model("no_such_model")
    net = vision.get_model("resnet18_v1", classes=10)
    assert net is not None


def test_resnet18_thumbnail_forward():
    net = vision.get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_resnet18_v2_thumbnail_forward():
    net = vision.get_model("resnet18_v2", classes=10, thumbnail=True)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_resnet50_structure():
    net = vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Xavier())
    # materialize deferred shapes with a tiny spatial input: conv stack
    # accepts any spatial size >= 32
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 1000)
    n_params = sum(int(np.prod(p.shape))
                   for p in net.collect_params().values())
    # ResNet-50 has ~25.6M parameters
    assert 24e6 < n_params < 27e6, n_params


def test_resnet_nhwc_matches_nchw(tmp_path):
    """layout='NHWC' ResNet (the BASELINE.md layout experiment) computes
    the SAME function as the NCHW model: parameters are layout-portable
    (weights stay OIHW), so an NCHW checkpoint loads into the NHWC
    variant and the outputs match on transposed input — fwd and grads."""
    from mxnet_tpu import autograd

    net = vision.get_model("resnet18_v1", classes=4, thumbnail=True)
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.random_normal(shape=(2, 3, 32, 32))
    x.attach_grad()
    with autograd.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    ref, gref = out.asnumpy(), x.grad.asnumpy()

    f = str(tmp_path / "r18.params")
    net.save_parameters(f)
    net2 = vision.get_model("resnet18_v1", classes=4, thumbnail=True,
                            layout="NHWC")
    net2.load_parameters(f)
    x2 = mx.nd.array(np.transpose(x.asnumpy(), (0, 2, 3, 1)))
    x2.attach_grad()
    with autograd.record():
        out2 = net2(x2)
        loss2 = (out2 * out2).sum()
    loss2.backward()
    np.testing.assert_allclose(out2.asnumpy(), ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.transpose(x2.grad.asnumpy(), (0, 3, 1, 2)), gref,
        rtol=1e-4, atol=1e-4)


def test_resnet50_nhwc_structure():
    """The bench NHWC config (resnet50_v1 layout='NHWC') builds, forwards
    and keeps the NCHW parameter count."""
    net = vision.resnet50_v1(classes=1000, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(1, 64, 64, 3)))
    assert out.shape == (1, 1000)
    n_params = sum(int(np.prod(p.shape))
                   for p in net.collect_params().values())
    assert 24e6 < n_params < 27e6, n_params


def test_mobilenet_forward():
    net = vision.get_model("mobilenet0.25", classes=10)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_mobilenet_v2_forward():
    net = vision.get_model("mobilenetv2_0.25", classes=10)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_squeezenet_forward():
    net = vision.get_model("squeezenet1.1", classes=10)
    net.initialize(init=mx.init.Xavier())
    out = net(mx.nd.random_normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_densenet_constructs():
    net = vision.densenet121(classes=10)
    assert net is not None


def test_vgg_alexnet_inception_construct():
    assert vision.vgg11(classes=10) is not None
    assert vision.alexnet(classes=10) is not None
    assert vision.inception_v3(classes=10) is not None


def test_model_zoo_save_load(tmp_path):
    net = vision.get_model("resnet18_v1", classes=4, thumbnail=True)
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.random_normal(shape=(1, 3, 32, 32))
    ref = net(x).asnumpy()
    f = str(tmp_path / "r18.params")
    net.save_parameters(f)
    net2 = vision.get_model("resnet18_v1", classes=4, thumbnail=True)
    net2.load_parameters(f)
    np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-5,
                               atol=1e-6)


def _copy_unstacked_to_scan(pa, pb, eprefix, sprefix, num_layers):
    """Copy an unstacked transformer trunk's per-layer params into a
    scan trunk's (L, ...) stacks — the one home of the *_stack_* naming
    convention both equivalence tests rely on."""
    from mxnet_tpu import nd

    def stack(name):
        return nd.array(np.stack(
            [pa[f"{eprefix}layer{i}_{name}"].data().asnumpy()
             for i in range(num_layers)]))

    for nm in ("qkv_weight", "qkv_bias", "proj_weight", "proj_bias",
               "ffn1_weight", "ffn1_bias", "ffn2_weight", "ffn2_bias"):
        pb[f"{sprefix}{nm.replace('_', '_stack_', 1)}"].set_data(
            stack(nm))
    for li, tag in ((0, "ln1"), (1, "ln2")):
        for wb in ("gamma", "beta"):
            pb[f"{sprefix}{tag}_stack_{wb}"].set_data(nd.array(np.stack(
                [pa[f"{eprefix}layer{i}_layernorm{li}_{wb}"]
                 .data().asnumpy() for i in range(num_layers)])))
    for wb in ("gamma", "beta"):
        final = [n for n in pa
                 if n.startswith(f"{eprefix}layernorm")
                 and n.endswith(wb)]
        pb[f"{sprefix}lnf_{wb}"].set_data(pa[final[0]].data())


def test_scan_transformer_encoder_matches_unstacked():
    """ScanTransformerEncoder (lax.scan trunk) must equal
    TransformerEncoder layer-by-layer math, fwd and grads."""
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon.model_zoo import bert as bz

    rs = np.random.RandomState(0)
    L, U, H = 3, 32, 4
    enc = bz.TransformerEncoder(L, U, H, dropout=0.0)
    enc.initialize(init=mx.init.Xavier())
    senc = bz.ScanTransformerEncoder(L, U, H, dropout=0.0)
    senc.initialize(init=mx.init.Xavier())

    ep = enc.collect_params()
    epre = [n for n in ep if n.endswith("layer0_qkv_weight")][0]
    eprefix = epre[:-len("layer0_qkv_weight")]
    sp = senc.collect_params()
    spre = [n for n in sp if n.endswith("qkv_stack_weight")][0]
    sprefix = spre[:-len("qkv_stack_weight")]
    _copy_unstacked_to_scan(ep, sp, eprefix, sprefix, L)

    x = nd.array(rs.randn(2, 5, U).astype("float32"))
    x2 = nd.array(x.asnumpy())
    x.attach_grad()
    x2.attach_grad()
    with autograd.record():
        y1 = enc(x)
        (y1 * y1).sum().backward()
    with autograd.record():
        y2 = senc(x2)
        (y2 * y2).sum().backward()
    np.testing.assert_allclose(y2.asnumpy(), y1.asnumpy(), atol=2e-5)
    np.testing.assert_allclose(x2.grad.asnumpy(), x.grad.asnumpy(),
                               atol=2e-4)


def test_bert_scan_layers_trains():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import bert as bz

    net = bz.bert_tiny(dropout=0.0, scan_layers=True, max_length=32)
    net.initialize(init=mx.init.Xavier())
    tr = parallel.ShardedTrainer(
        net, bz.BERTPretrainLoss(), "adamw", {"learning_rate": 1e-3},
        mesh=parallel.data_parallel_mesh(1))
    rs = np.random.RandomState(0)
    ids = mx.nd.array(rs.randint(0, 512, (4, 32)).astype("int32"))
    mlm = np.where(rs.rand(4, 32) < 0.2,
                   rs.randint(0, 512, (4, 32)), -1).astype("int32")
    nsp = rs.randint(0, 2, (4,)).astype("int32")
    losses = [float(np.asarray(
        tr.step(ids, (mx.nd.array(mlm), mx.nd.array(nsp)))._data,
        dtype=np.float32)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_transformer_seq2seq_overfits_copy_and_decodes():
    """NMT-family Transformer: causal decoder + cross-attention learn a
    fixed copy batch to ~zero loss; greedy decode reproduces it."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.model_zoo import transformer as tfm

    rs = np.random.RandomState(0)
    V, B, T = 20, 16, 8
    net = tfm.transformer_tiny(V, V, dropout=0.0, max_length=16)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    loss_fn = tfm.LabelSmoothedCELoss(smoothing=0.0)
    src_np = rs.randint(3, V, (B, T)).astype("float32")
    tgt_in_np = np.concatenate([np.full((B, 1), 1.0),
                                src_np[:, :-1]], axis=1)
    src = nd.array(src_np)
    tgt_in = nd.array(tgt_in_np)
    labels = nd.array(src_np)
    for _ in range(150):
        with autograd.record():
            loss = loss_fn(net(src, tgt_in), labels)
        loss.backward()
        trainer.step(B)
    final = float(nd.array(loss).asnumpy())
    assert final < 0.05, final
    out = net.greedy_decode(src, bos_id=1, eos_id=2, max_len=T + 1)
    acc = (out[:, 1:T + 1] == src_np.astype(np.int32)).mean()
    assert acc > 0.95, acc


def test_transformer_decoder_is_causal():
    """Changing a future target token must not change earlier logits."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon.model_zoo import transformer as tfm

    rs = np.random.RandomState(1)
    net = tfm.transformer_tiny(12, 12, dropout=0.0, max_length=8)
    net.initialize(init=mx.init.Xavier())
    src = nd.array(rs.randint(3, 12, (2, 6)).astype("float32"))
    tgt = rs.randint(3, 12, (2, 6)).astype("float32")
    with autograd.predict_mode():
        l1 = net(src, nd.array(tgt)).asnumpy()
        tgt2 = tgt.copy()
        tgt2[:, -1] = (tgt2[:, -1] % 9) + 3  # perturb the LAST token
        l2 = net(src, nd.array(tgt2)).asnumpy()
    np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], atol=1e-5)
    assert np.abs(l1[:, -1] - l2[:, -1]).max() > 1e-4


def test_transformer_beam_search_beats_or_matches_greedy():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.model_zoo import transformer as tfm

    rs = np.random.RandomState(0)
    V, B, T = 20, 8, 6
    net = tfm.transformer_tiny(V, V, dropout=0.0, max_length=16)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    loss_fn = tfm.LabelSmoothedCELoss(smoothing=0.0)
    src_np = rs.randint(3, V, (B, T)).astype("float32")
    tgt_in = np.concatenate([np.full((B, 1), 1.0),
                             src_np[:, :-1]], axis=1)
    src = nd.array(src_np)
    for _ in range(120):
        with autograd.record():
            loss = loss_fn(net(src, nd.array(tgt_in)), nd.array(src_np))
        loss.backward()
        trainer.step(B)
    out, sc = tfm.beam_search(net, src, bos_id=1, eos_id=2, beam_size=3,
                              max_len=T + 1)
    acc = (out[:, 1:T + 1] == src_np.astype(np.int32)).mean()
    assert acc > 0.9, acc
    assert np.isfinite(sc).all()


def test_scan_encoder_remat_identical_grads():
    """remat=True recomputes layer activations in the backward; grads
    must be bit-identical to the non-remat scan."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.attention import scan_transformer_encoder

    rs = np.random.RandomState(0)
    L, U, H = 3, 16, 2
    args = [jnp.asarray(a.astype(np.float32)) for a in (
        rs.randn(2, 4, U),
        rs.randn(L, 3 * U, U) * 0.1, rs.randn(L, 3 * U) * 0.1,
        rs.randn(L, U, U) * 0.1, rs.randn(L, U) * 0.1,
        rs.randn(L, 4 * U, U) * 0.1, rs.randn(L, 4 * U) * 0.1,
        rs.randn(L, U, 4 * U) * 0.1, rs.randn(L, U) * 0.1,
        np.ones((L, U)), np.zeros((L, U)),
        np.ones((L, U)), np.zeros((L, U)),
        np.ones(U), np.zeros(U))]

    def loss(remat):
        def f(x):
            out = scan_transformer_encoder(
                x, *args[1:], num_heads=H, dropout=0.0, remat=remat)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return jax.grad(f)(args[0])

    g0 = np.asarray(loss(False))
    g1 = np.asarray(loss(True))
    np.testing.assert_array_equal(g0, g1)


def test_gpt_trains_causal_and_generates():
    """Decoder-only LM family: gpt_tiny learns the next-token pattern,
    attention is provably causal (future-token edits cannot change past
    logits), and greedy generate() continues the learned sequence."""
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.gpt_tiny()
    net.initialize(init=mx.init.Xavier())
    loss_fn = gpt.GPTLMLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    rs = np.random.RandomState(0)
    seq = (np.cumsum(np.ones((8, 32)), axis=1)
           + rs.randint(0, 16, (8, 1))) % 16        # next = (t + 1) % 16
    ids = nd.array(seq.astype(np.float32))
    losses = []
    for _ in range(30):
        with autograd.record():
            loss = loss_fn(net(ids), ids)
        loss.backward()
        tr.step(8)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < 0.5 * losses[0], losses[::10]

    ids2 = seq.copy()
    ids2[:, 20] = (ids2[:, 20] + 7) % 16
    l1 = net(nd.array(seq.astype(np.float32))).asnumpy()
    l2 = net(nd.array(ids2.astype(np.float32))).asnumpy()
    np.testing.assert_allclose(l1[:, :20], l2[:, :20], atol=1e-5)
    assert not np.allclose(l1[:, 20:], l2[:, 20:], atol=1e-5)

    out = gpt.generate(net, ids[:2, :8], max_new_tokens=4).asnumpy()
    expect = [(seq[0, 7] + k + 1) % 16 for k in range(4)]
    np.testing.assert_array_equal(out[0, 8:12], expect)


def test_gpt_scan_matches_unstacked():
    """scan_layers=True GPT (one scanned causal layer) == the unstacked
    trunk given the same parameters — fwd logits match."""
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import gpt

    L = 2
    a = gpt.gpt_tiny(scan_layers=False)
    a.initialize(init=mx.init.Xavier())
    b = gpt.gpt_tiny(scan_layers=True)
    b.initialize(init=mx.init.Xavier())
    ids = nd.array(np.random.RandomState(1)
                   .randint(0, 128, (2, 16)).astype(np.float32))
    a(ids)
    b(ids)

    pa, pb = dict(a.collect_params()), dict(b.collect_params())
    epre = [n for n in pa if n.endswith("layer0_qkv_weight")][0]
    eprefix = epre[:-len("layer0_qkv_weight")]
    spre = [n for n in pb if n.endswith("qkv_stack_weight")][0]
    sprefix = spre[:-len("qkv_stack_weight")]
    _copy_unstacked_to_scan(pa, pb, eprefix, sprefix, L)
    for nm in ("tok_embed_weight", "pos_embed_weight"):
        src_key = [k for k in pa if k.endswith(nm)][0]
        dst_key = [k for k in pb if k.endswith(nm)][0]
        pb[dst_key].set_data(pa[src_key].data())

    np.testing.assert_allclose(b(ids).asnumpy(), a(ids).asnumpy(),
                               rtol=2e-4, atol=2e-5)


def test_gpt_cached_decoder_matches_recompute():
    """KV-cache incremental decoding (static cache +
    dynamic_update_slice, ONE jitted step) produces byte-identical
    tokens to the full-recompute generate() — both trunk variants."""
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import gpt

    for scan in (False, True):
        net = gpt.gpt_tiny(scan_layers=scan)
        net.initialize(init=mx.init.Xavier())
        ids = nd.array(np.random.RandomState(0)
                       .randint(0, 128, (2, 6)).astype(np.float32))
        net(ids)
        ref = gpt.generate(net, ids, max_new_tokens=5).asnumpy()
        dec = gpt.CachedDecoder(net).decode(
            ids, max_new_tokens=5).asnumpy()
        np.testing.assert_array_equal(ref, dec, err_msg=f"scan={scan}")


def test_gpt_cached_decoder_tensor_parallel():
    """tp-sharded serving: CachedDecoder(mesh=) shards heads, the KV
    cache, and the FFN hidden dim over the tp axis (Megatron rules,
    GSPMD collectives) and produces the same tokens as the
    single-device cached decoder."""
    import jax
    from jax.sharding import Mesh

    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.gpt_tiny(scan_layers=True)
    net.initialize(init=mx.init.Xavier())
    ids = nd.array(np.random.RandomState(1)
                   .randint(0, 128, (2, 6)).astype(np.float32))
    net(ids)
    ref_t, ref_lg = gpt.CachedDecoder(net).decode(
        ids, max_new_tokens=5, return_logits=True)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    tp_t, tp_lg = gpt.CachedDecoder(net, mesh=mesh).decode(
        ids, max_new_tokens=5, return_logits=True)
    _assert_decode_equiv(ref_t.asnumpy(), ref_lg, tp_t.asnumpy(), tp_lg,
                         T0=ids.shape[1])


def test_gpt_cached_decoder_bf16_serving():
    """dtype='bfloat16' puts the big tensors (weight stacks, embed
    tables, KV cache) in bf16 HBM while accumulating f32 — logits stay
    within bf16 tolerance of the f32 decoder, also combined with tp."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.gpt_tiny(scan_layers=True)
    net.initialize(init=mx.init.Xavier())
    ids = nd.array(np.random.RandomState(2)
                   .randint(0, 128, (2, 6)).astype(np.float32))
    net(ids)
    _, ref_lg = gpt.CachedDecoder(net).decode(
        ids, max_new_tokens=3, return_logits=True)
    dec = gpt.CachedDecoder(net, dtype="bfloat16")
    toks, lg = dec.decode(ids, max_new_tokens=3, return_logits=True)
    assert toks.shape == (2, 9)
    scale = np.abs(ref_lg[0]).max()
    np.testing.assert_allclose(lg[0], ref_lg[0], atol=0.05 * scale)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    _, lg_tp = gpt.CachedDecoder(net, mesh=mesh, dtype="bfloat16").decode(
        ids, max_new_tokens=3, return_logits=True)
    np.testing.assert_allclose(lg_tp[0], ref_lg[0], atol=0.05 * scale)
    # the cache really is bf16 (the HBM claim)
    dec._build()
    assert dec._tok.dtype == jnp.bfloat16


def test_gpt_speculative_decode_lossless():
    """Speculative decoding emits EXACTLY the target's greedy tokens —
    with a self-draft (all-accept fast path), an independent weaker
    draft (mixed accept/reject), and batch > 1 (uniform-min progress)."""
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import gpt

    tgt = gpt.gpt_tiny(scan_layers=True)
    tgt.initialize(init=mx.init.Xavier())
    ids = nd.array(np.random.RandomState(5)
                   .randint(0, 128, (3, 7)).astype(np.float32))
    tgt(ids)
    ref_nd, ref_lg = gpt.CachedDecoder(tgt).decode(
        ids, max_new_tokens=9, return_logits=True)
    ref = ref_nd.asnumpy()

    def assert_lossless(spec_np):
        """Token-exact, except a divergence whose reference top-2
        margin is inside rounding noise (S=1 vs S=k+1 reduction-order
        ties — see the speculative_decode docstring)."""
        if np.array_equal(spec_np, ref):
            return
        j = int(np.argwhere((spec_np != ref).any(axis=0))[0, 0]) \
            - ids.shape[1]
        top2 = np.sort(ref_lg[j], axis=-1)[:, -2:]
        margin = float((top2[:, 1] - top2[:, 0]).min())
        assert margin < 1e-3 * np.abs(ref_lg[j]).max(), \
            f"diverged at step {j} with a decisive margin {margin}"

    # self-draft: every (untrimmed) proposal must be accepted
    spec, st = gpt.speculative_decode(tgt, tgt, ids, max_new_tokens=9,
                                      k=3, return_stats=True)
    assert_lossless(spec.asnumpy())
    assert st["accepted_draft_tokens"] >= 6  # all-accept up to trim

    # independent draft: still lossless, some rejections expected
    drf = gpt.gpt_tiny(scan_layers=True)
    drf.initialize(init=mx.init.Xavier())
    drf(ids)
    spec2, st2 = gpt.speculative_decode(tgt, drf, ids, max_new_tokens=9,
                                        k=3, return_stats=True)
    assert_lossless(spec2.asnumpy())
    assert st2["rounds"] >= st["rounds"]


def _assert_decode_equiv(ref_t, ref_lg, tp_t, tp_lg, T0):
    """Greedy tokens should match; if argmax flips, it is legitimate
    ONLY inside float32 rounding noise — the sharded partial-sum
    all-reduce associates reductions differently, so the contract is
    logits-to-rounding, tokens-in-practice."""
    np.testing.assert_allclose(tp_lg[0], ref_lg[0], rtol=2e-4, atol=1e-5)
    if np.array_equal(ref_t, tp_t):
        return
    j = int(np.argwhere((ref_t != tp_t).any(axis=0))[0, 0]) - T0
    np.testing.assert_allclose(
        tp_lg[j], ref_lg[j], rtol=2e-4, atol=1e-5,
        err_msg=f"tokens diverged at step {j} with logits beyond "
                "rounding tolerance")


def test_gpt_flash_attention_trains():
    """The causal LM with attention_impl='flash' (interpret mode on
    CPU): the Pallas causal kernel inside the full training step."""
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.gpt_tiny(attention_impl="flash", scan_layers=True)
    net.initialize(init=mx.init.Xavier())
    loss_fn = gpt.GPTLMLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    rs = np.random.RandomState(0)
    seq = (np.cumsum(np.ones((4, 32)), axis=1)
           + rs.randint(0, 16, (4, 1))) % 16
    ids = nd.array(seq.astype(np.float32))
    losses = []
    for _ in range(10):
        with autograd.record():
            loss = loss_fn(net(ids), ids)
        loss.backward()
        tr.step(4)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses


def test_gpt_beam_generate():
    """Beam search for the decoder-only family (shared beam_loop core):
    on a trained deterministic next-token pattern, beam-1 equals greedy
    generate() and wider beams score at least as well."""
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.gpt_tiny()
    net.initialize(init=mx.init.Xavier())
    loss_fn = gpt.GPTLMLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    rs = np.random.RandomState(0)
    seq = (np.cumsum(np.ones((8, 32)), axis=1)
           + rs.randint(0, 16, (8, 1))) % 16
    ids = nd.array(seq.astype(np.float32))
    for _ in range(30):
        with autograd.record():
            loss = loss_fn(net(ids), ids)
        loss.backward()
        tr.step(8)

    seed = ids[:2, :8]
    greedy = gpt.generate(net, seed, max_new_tokens=4).asnumpy()
    b1, s1 = gpt.beam_generate(net, seed, max_new_tokens=4, beam_size=1)
    np.testing.assert_array_equal(b1.asnumpy(), greedy)
    b4, s4 = gpt.beam_generate(net, seed, max_new_tokens=4, beam_size=4)
    assert (s4 >= s1 - 1e-5).all(), (s1, s4)
    # on a learned deterministic pattern the wide beam agrees too
    np.testing.assert_array_equal(b4.asnumpy(), greedy)


def test_vit_forward_and_trains():
    """VisionTransformer: patchify + scanned pre-LN trunk + cls head;
    hybridized training drops loss; scan and per-layer trunks agree
    in architecture (forward shapes)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    np.random.seed(0)
    net = vision.get_model("vit_tiny")
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(2, 3, 32, 32)
                    .astype(np.float32))
    assert net(x).shape == (2, 10)
    unscanned = vision.vit_tiny(scan_layers=False)
    unscanned.initialize(init=mx.init.Xavier())
    assert unscanned(x).shape == (2, 10)
    # deploy path: shape-free hybrid_forward must trace symbolically
    import os
    import tempfile

    net.hybridize()
    net(x)
    with autograd.predict_mode():
        ref = net(x)
    d = tempfile.mkdtemp()
    net.export(os.path.join(d, "vit"))
    sb = gluon.SymbolBlock.imports(
        os.path.join(d, "vit-symbol.json"), ["data"],
        os.path.join(d, "vit-0000.params"))
    np.testing.assert_allclose(sb(x).asnumpy(), ref.asnumpy(),
                               atol=1e-5)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adamw",
                       {"learning_rate": 1e-3})
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    y = mx.nd.array(np.array([1.0, 7.0], np.float32))
    first = last = None
    for _ in range(10):
        with autograd.record():
            l = lf(net(x), y)
        l.backward()
        tr.step(2)
        v = float(l.mean().asnumpy())
        first = v if first is None else first
        last = v
    assert last < first, (first, last)


def test_gpt_trunk_lora_finetuning():
    """Built-in trunk LoRA (scan_transformer_encoder qkv adapters):
    rank-r model with copied base params starts EXACTLY equal (B=0),
    freeze_for_lora leaves only adapters trainable, loss drops, frozen
    stacks don't move."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.contrib import freeze_for_lora
    from mxnet_tpu.gluon.model_zoo import gpt

    mx.random.seed(0)
    np.random.seed(0)
    base = gpt.gpt_tiny(scan_layers=True, dropout=0.0)
    base.initialize(init=mx.init.Xavier())
    ids = mx.nd.array(np.random.RandomState(0)
                      .randint(0, 100, (2, 16)).astype(np.float32))
    ref = base(ids).asnumpy()

    lnet = gpt.gpt_tiny(scan_layers=True, dropout=0.0, lora_rank=4,
                        lora_alpha=8)
    lnet.initialize(init=mx.init.Xavier())
    bmap = {n.split("_", 1)[1]: p
            for n, p in base.collect_params().items()}
    for n, p in lnet.collect_params().items():
        key = n.split("_", 1)[1]
        if "lora" not in n and key in bmap:
            p.set_data(bmap[key].data())
    np.testing.assert_allclose(lnet(ids).asnumpy(), ref, rtol=2e-5,
                               atol=2e-5)

    n_train, n_total = freeze_for_lora(lnet)
    assert n_train < 0.1 * n_total, (n_train, n_total)
    tr = gluon.Trainer(lnet.collect_params(), "adam",
                       {"learning_rate": 5e-3})
    lf = gpt.GPTLMLoss()
    frozen = {n: p.data().asnumpy().copy()
              for n, p in lnet.collect_params().items()
              if p.grad_req == "null"}
    first = last = None
    for _ in range(8):
        with autograd.record():
            l = lf(lnet(ids), ids)
        l.backward()
        tr.step(2)
        v = float(l.asnumpy())
        first = v if first is None else first
        last = v
    assert last < first, (first, last)
    for n, p in lnet.collect_params().items():
        if p.grad_req == "null":
            np.testing.assert_array_equal(p.data().asnumpy(), frozen[n])
    # non-scan + lora must raise (adapters live in the scanned trunk)
    with pytest.raises(ValueError):
        gpt.GPTModel(vocab_size=100, units=32, num_layers=2,
                     num_heads=2, scan_layers=False, lora_rank=2)


def test_bert_trunk_lora_wires():
    """BERT family forwards lora_rank to the scanned trunk; non-scan
    raises; freeze leaves only adapter params trainable."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.contrib import freeze_for_lora
    from mxnet_tpu.gluon.model_zoo import bert

    net = bert.bert_tiny(scan_layers=True, dropout=0.0, lora_rank=2)
    net.initialize(init=mx.init.Xavier())
    ids = mx.nd.array(np.random.RandomState(0)
                      .randint(0, 200, (2, 16)).astype(np.float32))
    net(ids)
    n_train, n_total = freeze_for_lora(net)
    assert 0 < n_train < 0.05 * n_total
    with pytest.raises(ValueError):
        bert.bert_tiny(lora_rank=2)  # scan_layers=False default


def test_ssd_export_roundtrip(tmp_path):
    """SSD exports symbolically (shape-free head reshapes) and
    SymbolBlock round-trips all three outputs."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo import SSD

    mx.random.seed(0)
    np.random.seed(0)
    net = SSD(num_classes=2)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).randn(1, 3, 64, 64)
                    .astype(np.float32))
    net(x)
    with autograd.predict_mode():
        ref = net(x)
    net.export(str(tmp_path / "ssd"))
    sb = gluon.SymbolBlock.imports(
        str(tmp_path / "ssd-symbol.json"), ["data"],
        str(tmp_path / "ssd-0000.params"))
    out = sb(x)
    for o, r in zip(out, ref):
        np.testing.assert_allclose(o.asnumpy(), r.asnumpy(), atol=1e-5)
