"""SDC integrity plane (mxnet_tpu/integrity.py): the fingerprint math
(device/host bitwise parity, single-bit sensitivity), tier-1
cross-replica attestation (majority vote over the gang KV), tier-2
shadow-replay audits (memory vs compute classification), the tier-3
hash-chained lineage ledger + checkpoint provenance, the quarantine →
elastic-reshape → grow-back path, the SDC fault sites
(bit_flip_param / bit_flip_grad / bad_core), charge-consumption
semantics (`resilience.consume_charges` / `consume_rank_fault`), the
fault-site coverage sweep (parser ⊆ docs ⊆ tests), and the telemetry
torn-tail strike-out.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import (checkpoint, distributed, gluon, integrity,
                       resilience, telemetry)
from mxnet_tpu.gluon import captured, nn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")


def _clean_env(**extra):
    """Subprocess env: CPU backend, no inherited faults/telemetry (same
    recipe as tests/test_elastic.py)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU",
                                "MXTPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


# -- fingerprint math ----------------------------------------------------------


def test_fingerprint_device_host_parity():
    """The in-program fingerprint (jit-traceable uint32 math) and the
    host mirror must agree bitwise across dtypes — the attestation
    compares one against the other."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    arrs = [
        jnp.asarray(rng.normal(size=(17,)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(3, 5)).astype(np.float16)),
        jnp.asarray(rng.normal(size=(9,)).astype(np.float32),
                    dtype=jnp.bfloat16),
        jnp.asarray(rng.randint(-50, 50, size=(11,)).astype(np.int32)),
        jnp.asarray(rng.rand(8) > 0.5),
        jnp.asarray(rng.randint(0, 255, size=(6,)).astype(np.uint8)),
    ]
    dev = integrity.combine(np.asarray(integrity.fingerprint_arrays(arrs)))
    host = integrity.fingerprint_host([np.asarray(a) for a in arrs])
    assert dev == host
    assert integrity.fp_hex(host) == f"{host:016x}"


def test_fingerprint_single_bit_sensitivity():
    """Odd per-word weights: a single flipped bit — any bit position,
    any element — always changes the fingerprint."""
    base = np.linspace(-1.0, 1.0, 33, dtype=np.float32)
    fp0 = integrity.fingerprint_host([base])
    seen = {fp0}
    for bit in (0, 7, 20, 31):
        a = base.copy()
        integrity.bit_flip_host(a, bit=bit)
        fp = integrity.fingerprint_host([a])
        assert fp not in seen, f"bit {bit} collided"
        seen.add(fp)
    a = base.copy()
    a.view(np.uint32)[16] ^= 1          # element 16, not element 0
    assert integrity.fingerprint_host([a]) not in seen


def test_fingerprint_is_order_canonical():
    a = np.arange(4, dtype=np.float32)
    b = np.arange(4, 8, dtype=np.float32)
    assert integrity.fingerprint_host([a, b]) != \
        integrity.fingerprint_host([b, a])
    # pytree leaves are canonical (dict keys sort): same fp as the list
    assert integrity.fingerprint_host({"a": a, "b": b}) == \
        integrity.fingerprint_host([a, b])


def test_bit_flip_host_flips_exactly_one_bit():
    a = np.arange(16, dtype=np.float32)
    b = a.copy()
    integrity.bit_flip_host(b, bit=20)
    x = a.view(np.uint32) ^ b.view(np.uint32)
    assert np.unpackbits(x.view(np.uint8)).sum() == 1
    assert x[0] != 0 and not x[1:].any()


# -- captured-step attestation (tier 1, zero extra dispatches) -----------------


STEPS = 10


def _make_net(seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(3))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    return net


def _batches(steps=STEPS, n=8, d=6, seed=42):
    rng = np.random.RandomState(seed)
    xs = [rng.normal(size=(n, d)).astype(np.float32) for _ in range(steps)]
    ys = [rng.randint(0, 3, size=(n,)).astype(np.float32)
          for _ in range(steps)]
    return xs, ys


def _train_captured(monkeypatch, tmp_path, steps=STEPS, every=None,
                    tag=""):
    """Run `steps` captured train steps; with ``every`` set, attach an
    IntegrityPlane (solo gang over a FileKV, private ledger)."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    if every is not None:
        monkeypatch.setenv("MXTPU_INTEGRITY", "1")
    else:
        monkeypatch.delenv("MXTPU_INTEGRITY", raising=False)
    net = _make_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    plane = None
    if every is not None:
        plane = integrity.IntegrityPlane(
            rank=0, world=1,
            kv=distributed.FileKV(str(tmp_path / f"kv{tag}")),
            every=every,
            ledger=integrity.IntegrityLedger(
                str(tmp_path / f"led{tag}.jsonl")),
            run="test")
        trainer.attach_integrity(plane)
    xs, ys = _batches(steps)
    captured.reset_counters()
    losses = [trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                                 mx.nd.array(ys[s])).asnumpy()
              for s in range(steps)]
    dispatches = captured.dispatch_count()
    weights = [p.data().asnumpy() for p in trainer._params]
    return {"losses": losses, "weights": weights,
            "dispatches": dispatches, "trainer": trainer,
            "plane": plane, "net": net, "loss_fn": loss_fn,
            "xs": xs, "ys": ys}


def test_captured_attestation_is_a_pure_observer(monkeypatch, tmp_path):
    """Attestation must not perturb training: same losses and bitwise
    identical weights with integrity on vs off, ONE dispatch per step
    either way (the fingerprint rides the step program), rounds firing
    exactly every `every` steps, and the attested fingerprint equal to
    the host fingerprint of the LIVE post-step params + optimizer
    state."""
    off = _train_captured(monkeypatch, tmp_path, every=None, tag="off")
    on = _train_captured(monkeypatch, tmp_path, every=5, tag="on")
    for s, (a, b) in enumerate(zip(off["losses"], on["losses"])):
        np.testing.assert_array_equal(a, b, err_msg=f"loss step {s}")
    for i, (a, b) in enumerate(zip(off["weights"], on["weights"])):
        np.testing.assert_array_equal(a, b, err_msg=f"weight {i}")
    assert off["dispatches"] == STEPS
    assert on["dispatches"] == STEPS      # zero extra dispatches
    plane = on["plane"]
    assert plane.attestations == STEPS // 5
    v = plane.last_verdict
    assert v["ok"] and v["step"] == STEPS and not v["corrupt"]
    # tier 3: one ledger entry per round, chained
    entries = plane.ledger.entries()
    assert [e["step"] for e in entries] == [5, 10]
    ok, why = plane.ledger.verify_chain()
    assert ok, why
    # the attested fp IS the live state: host-recompute it from the
    # captured step's own leaf order (new_train + flattened states)
    tr = on["trainer"]
    step = captured.get_step(tr, on["net"], on["loss_fn"],
                             mx.nd.array(on["xs"][0]),
                             mx.nd.array(on["ys"][0]), 1)
    assert step is not None               # cache hit
    leaves = [p.data().asnumpy() for _i, p in step._trained]
    for _gkey, items in step._groups.items():
        for _i, _w, _g, st, _d in items:
            leaves.extend(s.asnumpy() for s in st)
    assert integrity.fp_hex(integrity.fingerprint_host(leaves)) == v["fp"]


def test_bit_flip_param_fires_after_captured_commit(monkeypatch,
                                                    fault_inject):
    """bit_flip_param corrupts the live state AFTER the program commits:
    the step's loss is untouched, exactly one parameter differs from an
    uninjected twin, and by exactly one bit; the charge is one-shot."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")

    def run_once():
        net = _make_net()
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        loss_fn.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        xs, ys = _batches(steps=1)
        loss = tr.train_step(net, loss_fn, mx.nd.array(xs[0]),
                             mx.nd.array(ys[0])).asnumpy()
        return loss, [p.data().asnumpy() for p in tr._params]

    clean_loss, clean_w = run_once()
    fault_inject("bit_flip_param:0")
    flip_loss, flip_w = run_once()
    assert not resilience.fault_armed("bit_flip_param")   # consumed
    np.testing.assert_array_equal(clean_loss, flip_loss)
    diffs = [i for i, (a, b) in enumerate(zip(clean_w, flip_w))
             if not np.array_equal(a, b)]
    assert len(diffs) == 1
    x = clean_w[diffs[0]].view(np.uint32) ^ \
        flip_w[diffs[0]].view(np.uint32)
    assert np.unpackbits(x.view(np.uint8)).sum() == 1


def test_bit_flip_grad_routes_step_to_eager_oracle(monkeypatch,
                                                   fault_inject):
    """The captured program's gradients never materialize, so an armed
    bit_flip_grad must route that step to the eager oracle (where a
    gradient buffer exists to flip) and re-capture once the charge is
    spent."""
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")

    def run(steps=2):
        net = _make_net()
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        loss_fn.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        xs, ys = _batches(steps=steps)
        captured.reset_counters()
        for s in range(steps):
            tr.train_step(net, loss_fn, mx.nd.array(xs[s]),
                          mx.nd.array(ys[s]))
        return captured.dispatch_count(), \
            [p.data().asnumpy() for p in tr._params]

    clean_disp, clean_w = run()
    assert clean_disp == 2
    fault_inject("bit_flip_grad:0")
    flip_disp, flip_w = run()
    assert flip_disp == 1          # step 1 went eager, step 2 captured
    assert not resilience.fault_armed("bit_flip_grad")
    assert any(not np.array_equal(a, b)
               for a, b in zip(clean_w, flip_w))


# -- tier 1: cross-replica majority vote ---------------------------------------


def _mk_planes(tmp_path, n, every=1, timeout=10.0):
    return [integrity.IntegrityPlane(
        rank=r, world=n,
        kv=distributed.FileKV(str(tmp_path / "kv")),
        every=every, timeout=timeout,
        ledger=integrity.IntegrityLedger(
            str(tmp_path / f"led_{r}.jsonl")),
        run="test") for r in range(n)]


def test_attest_majority_names_corrupt_minority(tmp_path):
    telemetry.reset()
    planes = _mk_planes(tmp_path, 3)
    w = np.arange(64, dtype=np.float32) / 3.0
    states = [{"w": w.copy()} for _ in range(3)]
    integrity.bit_flip_host(states[2]["w"])
    fps = [integrity.fingerprint_host(s) for s in states]
    assert fps[0] == fps[1] != fps[2]
    planes[1].publish(0, fps[1])
    planes[2].publish(0, fps[2])
    v0 = planes[0].attest(0, fps[0])
    assert v0["ok"] is False and not v0["tie"]
    assert v0["corrupt"] == [2] and not v0["self_corrupt"]
    assert v0["absent"] == []
    v2 = planes[2].attest(0, fps[2])
    assert v2["self_corrupt"] and v2["corrupt"] == [2]
    # one announcer per verdict: rank 0 (lowest healthy), exactly once
    counts = telemetry.event_counts()
    assert counts.get("integrity_mismatch") == 1
    assert counts.get("sdc_detected") == 1
    assert planes[0].mismatches == 1 and planes[2].mismatches == 1
    telemetry.reset()


def test_attest_two_way_tie_names_nobody(tmp_path):
    telemetry.reset()
    planes = _mk_planes(tmp_path, 2)
    a = np.arange(8, dtype=np.float32)
    b = a.copy()
    integrity.bit_flip_host(b)
    planes[1].publish(0, integrity.fingerprint_host([b]))
    v = planes[0].attest(0, integrity.fingerprint_host([a]))
    assert v["ok"] is False and v["tie"] is True
    assert v["corrupt"] == [] and not v["self_corrupt"]
    # a tie names nobody — no mismatch announcement, no sdc event
    assert telemetry.event_counts().get("sdc_detected") is None
    telemetry.reset()


def test_attest_absent_peer_times_out_without_blocking(tmp_path):
    planes = _mk_planes(tmp_path, 3, timeout=0.3)
    fp = integrity.fingerprint_host([np.ones(4, np.float32)])
    planes[1].publish(0, fp)
    t0 = time.monotonic()
    v = planes[0].attest(0, fp)       # rank 2 never publishes
    assert time.monotonic() - t0 < 5
    assert v["absent"] == [2]
    assert v["ok"] is True and v["corrupt"] == []


# -- tier 2: shadow replay classification --------------------------------------


def test_replay_audit_classifies_memory_compute_clean(tmp_path):
    telemetry.reset()
    plane = integrity.IntegrityPlane(
        rank=1, world=1,
        ledger=integrity.IntegrityLedger(str(tmp_path / "led.jsonl")),
        run="test")

    def step_fn(state, lr):
        return {"w": state["w"] * (1.0 - lr)}

    pre = {"w": np.arange(16, dtype=np.float64) / 7.0}
    live = step_fn({"w": pre["w"].copy()}, 0.01)
    plane.retain(3, {"w": pre["w"].copy()}, inputs=0.01)
    rep = plane.audit(step_fn, integrity.fingerprint_host(live),
                      step=3, peers_agree=True)
    assert rep["kind"] == "clean"
    assert rep["replay_fp"] == rep["live_fp"]
    # memory: live state mutated after the step committed
    bad = {"w": live["w"].copy()}
    integrity.bit_flip_host(bad["w"])
    rep = plane.audit(step_fn, integrity.fingerprint_host(bad),
                      step=3, peers_agree=False)
    assert rep["kind"] == "memory"
    # compute: the WRONG input was recorded, so the replay reproduces
    # the wrong answer — replay == live while peers disagree
    live2 = step_fn({"w": pre["w"].copy()}, 0.02)
    plane.retain(4, {"w": pre["w"].copy()}, inputs=0.02)
    rep = plane.audit(step_fn, integrity.fingerprint_host(live2),
                      step=4, peers_agree=False)
    assert rep["kind"] == "compute"
    assert plane.audit(step_fn, 0, step=99) is None   # nothing retained
    counts = telemetry.event_counts()
    assert counts.get("replay_audit") == 3
    assert counts.get("sdc_detected") == 2            # memory + compute
    assert plane.replays == 3
    telemetry.reset()


def test_bad_core_perturbs_the_input_once(fault_inject):
    fault_inject("bad_core:0")
    x = np.arange(6, dtype=np.float32)
    y = integrity.maybe_bad_core(rank=0, value=x)
    assert y is not x and y[0] != x[0]
    np.testing.assert_array_equal(y[1:], x[1:])
    z = integrity.maybe_bad_core(rank=0, value=x)     # charge spent
    np.testing.assert_array_equal(z, x)
    assert not resilience.fault_armed("bad_core")


# -- tier 3: lineage ledger + checkpoint provenance ----------------------------


def test_ledger_chain_append_verify_tamper(tmp_path):
    path = str(tmp_path / "led.jsonl")
    led = integrity.IntegrityLedger(path)
    assert led.head() is None
    for s in (0, 50, 100):
        led.append(s, 0xDEADBEEF + s, rank=0, epoch=0, run="t")
    ok, why = led.verify_chain()
    assert ok, why
    entries = led.entries()
    assert [e["step"] for e in entries] == [0, 50, 100]
    assert led.has_hash(led.head())
    assert not led.has_hash("f" * 64)
    # tamper entry 1's fp but keep its hash: the chain must fail closed
    lines = open(path).read().splitlines()
    rec = json.loads(lines[1])
    rec["fp"] = "0" * 16
    lines[1] = json.dumps(rec)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    led2 = integrity.IntegrityLedger(path)
    ok, why = led2.verify_chain()
    assert not ok and why


def test_checkpoint_provenance_stamp_and_fail_closed(tmp_path,
                                                     monkeypatch):
    """AsyncCheckpointer stamps the ledger head into MANIFEST.json;
    restore audits the stamp back to the chain — a tampered ledger
    fails closed, a missing ledger (fresh machine) stays lenient."""
    from mxnet_tpu.checkpoint import CheckpointCorrupt

    lpath = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("MXTPU_INTEGRITY_LEDGER", lpath)
    integrity.reset()
    led = integrity.get_ledger()
    led.append(100, 0xABCD, rank=0, run="t")
    state = {"w": np.arange(8, dtype=np.float32)}
    ck = checkpoint.AsyncCheckpointer(str(tmp_path / "ck"), rank=0,
                                      world_size=1)
    try:
        ck.save(1, state)
        ck.wait()
        m = ck.verify(1)
        assert m["integrity"]["ledger_head"] == led.head()
        np.testing.assert_array_equal(ck.restore(1)["w"], state["w"])
        # unstamped manifests (pre-integrity writers) stay readable
        ok, why = integrity.verify_provenance({"step": 1})
        assert ok
        # tamper the ledger → chain invalid → provenance fails closed
        lines = open(lpath).read().splitlines()
        rec = json.loads(lines[0])
        rec["fp"] = "0" * 16
        with open(lpath, "w") as f:
            f.write(json.dumps(rec) + "\n")
        integrity.reset()
        with pytest.raises(CheckpointCorrupt, match="provenance"):
            ck.restore(1)
        # ledger gone entirely (checkpoint shipped to a fresh machine):
        # nothing to audit against — lenient
        os.remove(lpath)
        integrity.reset()
        np.testing.assert_array_equal(ck.restore(1)["w"], state["w"])
    finally:
        ck.close()
        integrity.reset()


# -- end-to-end: 3-rank gang, bit flip detected / audited / repaired -----------


def _sim_losses(num_steps, phases, n=8):
    """Serial oracle of the thread-gang arithmetic (test_elastic.py)."""
    w = np.full(n, 1.0, dtype=np.float64)
    losses = {}
    for step in range(num_steps):
        members = None
        for start, m in sorted(phases):
            if step >= start:
                members = m
        total = sum(float((r + 1) * float(w.sum()))
                    for r in sorted(members))
        loss = total / len(members)
        losses[step] = loss
        w = w * 0.99 - 0.01 * (loss / w.size)
    return losses, w


def _kv_allreduce(gang, kv, step, contribution):
    epoch = gang.epoch
    kv.put_json(f"red/{epoch}/{step}/{gang.rank}",
                {"v": float(contribution)})
    gang.barrier(f"red{step}")
    total = 0.0
    for r in sorted(gang.members):
        total += float(kv.get_json(f"red/{epoch}/{step}/{r}")["v"])
    return total / len(gang.members)


def _apply(pre, loss):
    return {"w": pre["w"] * 0.99 - 0.01 * (loss / pre["w"].size),
            "opt": pre["opt"] + loss}


@pytest.fixture(params=["file", "tcp"])
def kv_backend(request, tmp_path):
    """(mode, make) over both gang control planes — the same surface
    tests/test_elastic.py exercises."""
    if request.param == "file":
        kvdir = str(tmp_path / "kv")

        def make(rank=None):
            return distributed.FileKV(kvdir)

        yield request.param, make
    else:
        server = distributed.GangKVServer(lease_ttl=5.0).start()
        clients = []

        def make(rank=None):
            c = distributed.TcpKV(server.addr, rank=rank)
            clients.append(c)
            return c

        yield request.param, make
        for c in clients:
            try:
                c.close()
            except Exception:           # noqa: BLE001 — teardown
                pass
        server.stop()


def _run_sdc_rank(rank, world, kv_make, root, num_steps, every,
                  flip_step, out):
    """Thread rank: lockstep KV allreduce + integrity plane.  A
    self-corrupt verdict triggers the shadow replay; kind "memory"
    means the replayed step IS the clean post-step state, so the rank
    repairs in place — zero lost steps, no reshape."""
    kv = kv_make(rank)
    gang = resilience.ElasticGang(rank, world, kv=kv, peer_snap_every=2,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=5.0)
    gang.start()
    plane = integrity.IntegrityPlane(
        rank=rank, world=world, kv=kv, every=every, timeout=30.0,
        ledger=integrity.IntegrityLedger(
            os.path.join(root, f"led_{rank}.jsonl")),
        run="sdc-test")
    state = {"w": np.full(8, 1.0, dtype=np.float64), "opt": 0.0}
    step, losses, audits, repaired, last_ok = 0, {}, [], 0, None
    try:
        while step < num_steps:
            gang.step_tick(step, state=state)
            pre = {"w": state["w"].copy(), "opt": state["opt"]}
            loss = _kv_allreduce(gang, kv, step,
                                 (rank + 1) * float(state["w"].sum()))
            losses[step] = loss
            state = _apply(pre, loss)
            if step == flip_step and \
                    resilience.consume_rank_fault("bit_flip_param",
                                                  rank):
                integrity.bit_flip_host(state["w"])
            if plane.due(step):
                plane.retain(step, pre, inputs=loss)
                v = plane.attest(step,
                                 integrity.fingerprint_host(state))
                last_ok = v["ok"]
                if not v["ok"] and v["self_corrupt"]:
                    rep = plane.audit(
                        _apply, integrity.fingerprint_host(state),
                        step=step)
                    audits.append(rep)
                    if rep["kind"] == "memory":
                        state = _apply(pre, loss)
                        repaired += 1
            step += 1
        out[rank] = {"status": "done", "losses": losses,
                     "w": state["w"], "gang": gang, "audits": audits,
                     "repaired": repaired, "last_ok": last_ok,
                     "attestations": plane.attestations}
    except Exception as e:                  # noqa: BLE001 — surfaced
        out[rank] = {"status": "error", "error": repr(e), "gang": gang}


def test_gang_detects_audits_and_repairs_bit_flip(kv_backend, tmp_path,
                                                  monkeypatch,
                                                  fault_inject):
    """The ISSUE's acceptance run: 3 ranks, bit_flip_param:1 lands at
    step 6 (post-commit).  The very next attestation round (same step:
    within one interval) names rank 1, the shadow replay classifies it
    "memory", the rank repairs from the retained snapshot, and every
    rank's losses and final weights are BITWISE equal to the uninjected
    serial oracle.  The event log must flow through trace_report."""
    _, kv_make = kv_backend
    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    telemetry.reset()
    fault_inject("bit_flip_param:1")
    num_steps, every, flip_step = 12, 3, 6
    out = {}
    threads = [threading.Thread(
        target=_run_sdc_rank,
        args=(r, 3, kv_make, str(tmp_path), num_steps, every,
              flip_step, out)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    try:
        assert not any(t.is_alive() for t in threads), "gang wedged"
        for r in range(3):
            assert out[r]["status"] == "done", out.get(r)
            assert out[r]["last_ok"] is True          # clean re-attest
            assert out[r]["attestations"] == 4        # steps 0,3,6,9
        # detection within the SAME round the flip landed in
        (audit,) = out[1]["audits"]
        assert audit["step"] == flip_step
        assert audit["kind"] == "memory"
        assert audit["replay_fp"] != audit["live_fp"]
        assert out[1]["repaired"] == 1
        assert out[0]["audits"] == [] and out[2]["audits"] == []
        # post-recovery: bitwise equal to the uninjected run — the
        # corruption never escaped the detection interval
        sim, sim_w = _sim_losses(num_steps, [(0, [0, 1, 2])])
        for r in range(3):
            assert out[r]["losses"] == sim
            np.testing.assert_array_equal(out[r]["w"], sim_w)
        counts = telemetry.event_counts()
        assert counts.get("integrity_mismatch") == 1
        assert counts.get("replay_audit") == 1
        assert counts.get("sdc_detected", 0) >= 1
        # the victim is NAMED: rank 1, refined kind "memory"
        events = [json.loads(l) for l in open(ev_path)]
        sdc = [e for e in events if e.get("event") == "sdc_detected"]
        assert all(e["rank"] == 1 and e["step"] == flip_step
                   for e in sdc)
        assert any(e["kind"] == "memory" for e in sdc)
    finally:
        for res in out.values():
            res["gang"].stop()
        telemetry.reset()                   # close the sink

    proc = subprocess.run(
        [sys.executable, _TRACE_REPORT, ev_path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "integrity:" in proc.stdout
    assert "attestations:" in proc.stdout
    assert f"mismatch: step {flip_step}" in proc.stdout
    assert "sdc: rank 1" in proc.stdout
    assert "-> memory" in proc.stdout


# -- quarantine: evict the corrupt rank, reshape, grow back --------------------


def _run_quarantine_rank(rank, world, kv_make, root, num_steps, every,
                         flip_step, out, step_s=0.03):
    """Thread rank where a mismatch verdict quarantines instead of
    repairing: survivors turn the verdict into a RankFailure and
    reshape around the corrupt rank; the victim gets evicted, restarts
    its gang membership and `join`s back with clean state."""
    kv = kv_make(rank)
    gang = resilience.ElasticGang(rank, world, kv=kv, peer_snap_every=2,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=5.0)
    gang.start()
    plane = integrity.IntegrityPlane(
        rank=rank, world=world, kv=kv, every=every, timeout=15.0,
        ledger=integrity.IntegrityLedger(
            os.path.join(root, f"qled_{rank}.jsonl")),
        run="q-test")
    state = {"w": np.full(8, 1.0, dtype=np.float64), "opt": 0.0}
    step, losses, infos, audits = 0, {}, [], []
    evicted_at = None

    def adopt(info):
        # fresh joiner: any replica's shard — ranks run in lockstep, so
        # EVERY field (opt included) is replica-identical; adopting a
        # partial state would fail the very next attestation
        st = info.shards.get(rank) or next(iter(info.shards.values()))
        return {"w": np.array(st["w"], dtype=np.float64),
                "opt": float(st["opt"])}

    def resync(info):
        infos.append(info)
        plane.peers = list(info.members)
        plane.epoch = info.epoch
        return adopt(info), info.snap_step

    def rejoin(at):
        # quarantined: come back as a fresh member with clean
        # (replica-restored) state, like a restarted process would
        nonlocal evicted_at, gang
        evicted_at = at
        gang.stop()
        gang = resilience.ElasticGang(
            rank, world, kv=kv_make(rank), peer_snap_every=2,
            heartbeat_interval=0.05, heartbeat_timeout=5.0)
        info = gang.join()
        assert info is not None
        return resync(info)

    try:
        while step < num_steps:
            try:
                gang.step_tick(step, state=state)
                pre = {"w": state["w"].copy(), "opt": state["opt"]}
                loss = _kv_allreduce(
                    gang, kv, step,
                    (rank + 1) * float(state["w"].sum()))
            except resilience.GangEvicted:
                state, step = rejoin(step)
                continue
            except resilience.RankFailure as rf:
                try:
                    info = gang.recover(rf)
                except resilience.GangEvicted:
                    state, step = rejoin(step)
                    continue
                state, step = resync(info)
                continue
            losses[step] = loss
            state = _apply(pre, loss)
            if step == flip_step and \
                    resilience.consume_rank_fault("bit_flip_param",
                                                  rank):
                integrity.bit_flip_host(state["w"])
            if plane.due(step) and gang.rank in gang.members:
                plane.retain(step, pre, inputs=loss)
                v = plane.attest(step,
                                 integrity.fingerprint_host(state))
                if not v["ok"] and not v["tie"] and v["corrupt"]:
                    if v["self_corrupt"]:
                        rep = plane.audit(
                            _apply,
                            integrity.fingerprint_host(state),
                            step=step)
                        audits.append(rep)
                        # no self-repair here: the gang evicts us
                    else:
                        rf = plane.quarantine(gang, v)
                        assert rf is not None
                        state, step = resync(gang.recover(rf))
                        continue
            step += 1
            if step_s:
                time.sleep(step_s)
        out[rank] = {"status": "done", "losses": losses,
                     "w": state["w"], "gang": gang, "infos": infos,
                     "audits": audits, "evicted_at": evicted_at}
    except Exception as e:                  # noqa: BLE001 — surfaced
        out[rank] = {"status": "error", "error": repr(e), "gang": gang}


def test_quarantine_evicts_corrupt_rank_and_grows_back(kv_backend,
                                                       tmp_path,
                                                       monkeypatch,
                                                       fault_inject):
    _, kv_make = kv_backend
    ev_path = str(tmp_path / "qev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    telemetry.reset()
    fault_inject("bit_flip_param:1")
    num_steps, every, flip_step = 26, 3, 6
    out = {}
    threads = [threading.Thread(
        target=_run_quarantine_rank,
        args=(r, 3, kv_make, str(tmp_path), num_steps, every,
              flip_step, out)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not any(t.is_alive() for t in threads), "gang wedged"
        for r in range(3):
            assert out[r]["status"] == "done", out.get(r)
        # the victim was evicted, audited itself ("memory"), and rejoined
        assert out[1]["evicted_at"] is not None
        assert any(a["kind"] == "memory" for a in out[1]["audits"])
        rejoin = out[1]["infos"][-1]
        assert 1 in rejoin.members
        # survivors' first reshape excluded exactly the corrupt rank
        for r in (0, 2):
            first = out[r]["infos"][0]
            assert first.members == [0, 2]
            assert first.dead == [1]
        counts = telemetry.event_counts()
        assert counts.get("rank_quarantined", 0) >= 1
        assert counts.get("sdc_detected", 0) >= 1
        # grown back and converged: same weights on every rank, and the
        # post-rejoin trajectory agrees step for step
        np.testing.assert_array_equal(out[0]["w"], out[1]["w"])
        np.testing.assert_array_equal(out[0]["w"], out[2]["w"])
        for s in range(rejoin.snap_step, num_steps):
            assert out[0]["losses"][s] == out[1]["losses"][s] \
                == out[2]["losses"][s], f"step {s}"
    finally:
        for res in out.values():
            res["gang"].stop()
        telemetry.reset()


# -- charge consumption (resilience.consume_charges / consume_rank_fault) ------


def test_consume_charges_fire_on_last(fault_inject):
    """kill_coordinator discipline: N charges absorb N-1 triggers and
    fire on the LAST one (the Nth mutation kills the daemon)."""
    fault_inject("kill_coordinator:3")
    assert resilience.consume_charges("kill_coordinator") is False
    assert resilience.consume_charges("kill_coordinator") is False
    assert resilience.consume_charges("kill_coordinator") is True
    assert resilience.consume_charges("kill_coordinator") is False


def test_consume_charges_fire_on_each(fault_inject):
    """corrupt_ckpt_write discipline: every charge fires (bit-rot the
    next N files), then the site disarms."""
    fault_inject("corrupt_ckpt_write:2")
    assert resilience.consume_charges("corrupt_ckpt_write",
                                      on_last=False) is True
    assert resilience.consume_charges("corrupt_ckpt_write",
                                      on_last=False) is True
    assert resilience.consume_charges("corrupt_ckpt_write",
                                      on_last=False) is False


def test_consume_rank_fault_is_one_shot_per_rank(fault_inject):
    fault_inject("bit_flip_param:1,bit_flip_param:2,bad_core:0")
    assert tuple(resilience.fault_args("bit_flip_param")) == (1, 2)
    assert resilience.fault_armed("bit_flip_param")
    assert not resilience.consume_rank_fault("bit_flip_param", 0)
    assert resilience.consume_rank_fault("bit_flip_param", 1)
    assert not resilience.consume_rank_fault("bit_flip_param", 1)
    assert resilience.fault_armed("bit_flip_param")   # rank 2 pending
    assert resilience.consume_rank_fault("bit_flip_param", 2)
    assert not resilience.fault_armed("bit_flip_param")
    assert resilience.consume_rank_fault("bad_core", 0)
    assert not resilience.consume_rank_fault("bad_core", 0)


# -- fault-site coverage sweep -------------------------------------------------


def _parser_sites():
    import inspect

    src = inspect.getsource(resilience._FaultPlan.__init__)
    groups = re.findall(r"site in \(([^)]*)\)", src)
    sites = {m for g in groups for m in re.findall(r'"([a-z_]+)"', g)}
    sites.discard("stall")              # alias of stall_collective
    return sites


def test_every_fault_site_is_documented_and_tested():
    """Grep-driven sweep: every site MXTPU_FAULT_INJECT's parser
    accepts must (a) have a row in docs/env_vars.md's fault-site table
    and (b) be exercised by at least one test under tests/ — and the
    docs table must not carry stale rows the parser rejects."""
    sites = _parser_sites()
    assert len(sites) >= 25, sorted(sites)

    docs = open(os.path.join(_REPO, "docs", "env_vars.md")).read()
    assert "### Fault sites" in docs
    table = docs.split("### Fault sites")[1].split("\n## ")[0]
    doc_sites = set(re.findall(r"^\| `([a-z_]+)`", table, re.M))
    undocumented = sites - doc_sites
    assert not undocumented, f"sites missing from docs: {undocumented}"
    stale = doc_sites - sites
    assert not stale, f"docs rows the parser rejects: {stale}"

    tests_dir = os.path.join(_REPO, "tests")
    blob = "".join(
        open(os.path.join(tests_dir, name)).read()
        for name in sorted(os.listdir(tests_dir))
        if name.endswith(".py"))
    untested = {s for s in sites if s not in blob}
    assert not untested, f"sites no test exercises: {untested}"


# -- telemetry: integrity records + torn-tail strike-out -----------------------


def test_integrity_record_schema_validates(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    telemetry.integrity_record(step=50, fp="00ab", ok=False, epoch=1,
                               peers=3, corrupt=[1], kind="memory",
                               rank=0)
    telemetry.reset()                   # close the sink
    (rec,) = [json.loads(l) for l in open(path)]
    telemetry.validate_record(rec)
    assert rec["type"] == "integrity" and rec["corrupt"] == [1]
    with pytest.raises(ValueError, match="step"):
        telemetry.validate_record(dict(rec, step=-1))
    with pytest.raises(ValueError, match="kind"):
        telemetry.validate_record(dict(rec, kind="banana"))


def test_torn_tail_strikes_out_after_three_polls(tmp_path):
    """A tail that stays torn for MXTPU_TELEMETRY_TAIL_STRIKES polls
    (default 3) is a dead write, not an in-flight flush: skip it, emit
    ONE telemetry_torn_line, and keep reading what comes after."""
    telemetry.reset()
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write('{"type": "event", "event": "resume", "step": 0}\n')
        f.write('{"type": "event", "ev')            # torn forever
    assert [r["step"] for r in telemetry.tail_records(path)] == [0]
    assert telemetry.tail_records(path) == []       # strike 2: held
    c0 = telemetry.event_counts().get("telemetry_torn_line", 0)
    assert telemetry.tail_records(path) == []       # strike 3: skipped
    assert telemetry.event_counts()["telemetry_torn_line"] == c0 + 1
    assert telemetry.tail_records(path) == []       # no repeat event
    assert telemetry.event_counts()["telemetry_torn_line"] == c0 + 1
    # the reader moved PAST the torn bytes: later complete lines flow
    with open(path, "a") as f:
        f.write('{"type": "event", "event": "resume", "step": 2}\n')
    assert [r["step"] for r in telemetry.tail_records(path)] == [2]
    telemetry.reset()


def test_torn_tail_growth_resets_the_strike_count(tmp_path):
    """A tail that GROWS between polls is an in-flight flush — the
    strike count restarts and the completed line is delivered intact."""
    telemetry.reset()
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write('{"type": "event", "event": "resume", "step": 0}\n')
        f.write('{"type": "event", "ev')
    assert [r["step"] for r in telemetry.tail_records(path)] == [0]
    assert telemetry.tail_records(path) == []       # 2 strikes held
    with open(path, "a") as f:
        f.write('ent": "resu')                      # still torn, grew
    assert telemetry.tail_records(path) == []       # back to strike 1
    assert telemetry.tail_records(path) == []       # strike 2
    assert telemetry.event_counts().get("telemetry_torn_line", 0) == 0
    with open(path, "a") as f:
        f.write('me", "step": 7}\n')                # flush completes
    assert [r["step"] for r in telemetry.tail_records(path)] == [7]
    assert telemetry.event_counts().get("telemetry_torn_line", 0) == 0
    telemetry.reset()


def test_tail_strikes_env_override(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_TELEMETRY_TAIL_STRIKES", "2")
    telemetry.reset()
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write('{"type": "event", "event": "resume", "step": 0}\n')
        f.write('{"type": "event", "ev')
    assert [r["step"] for r in telemetry.tail_records(path)] == [0]
    c0 = telemetry.event_counts().get("telemetry_torn_line", 0)
    assert telemetry.tail_records(path) == []       # strike 2: skipped
    assert telemetry.event_counts()["telemetry_torn_line"] == c0 + 1
    telemetry.reset()
