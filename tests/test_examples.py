"""CI-run the examples (VERDICT r2 coverage note: the reference treats
example/ as a de-facto integration zoo; these run each script small)."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EX = os.path.join(ROOT, "examples")


def _run(script, *args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    r = subprocess.run([sys.executable, os.path.join(EX, script),
                        *args],
                       capture_output=True, text=True, timeout=timeout,
                       env=env, cwd=ROOT)
    assert r.returncode == 0, (script, r.stdout[-400:], r.stderr[-400:])
    return r.stdout


def test_example_mnist_gluon():
    out = _run("train_mnist_gluon.py", "--epochs", "1",
               "--batch-size", "64")
    assert "accuracy" in out.lower() or "epoch" in out.lower()


def test_example_deploy_pipeline():
    out = _run("deploy_export_quantize.py", "--steps", "5")
    assert "deploy pipeline OK" in out


def test_example_moe_expert_parallel():
    out = _run("moe_expert_parallel.py", "--dp", "2", "--ep", "4",
               "--steps", "4")
    assert "training OK" in out


def test_example_imagenet_sharded():
    out = _run("train_imagenet_sharded.py", "--steps", "2",
               "--batch-size", "16", "--image-size", "32",
               "--network", "resnet18_v1", "--dtype", "float32")
    assert "samples/sec" in out or "step" in out.lower()


def test_example_bert_sharded():
    out = _run("bert_pretrain_sharded.py", "--model", "bert_tiny",
               "--steps", "2", "--batch-size", "8", "--seq-len", "32",
               "--dp", "2", "--dtype", "float32")
    assert "loss" in out.lower()


def test_example_lstm_language_model():
    out = _run("lstm_language_model.py", "--epochs", "3", "--tokens",
               "2000", "--vocab", "50")
    assert "lstm_language_model OK" in out


def test_example_sparse_linear_libsvm():
    out = _run("linear_classification_libsvm.py", "--dim", "2000",
               "--epochs", "10")
    assert "final accuracy" in out


def test_example_gpt_char_lm():
    out = _run("gpt_char_lm.py", "--steps", "120", timeout=500)
    assert "char-LM OK" in out


def test_example_serve_gpt():
    out = _run("serve_gpt.py", "--steps", "8", "--requests", "4",
               "--new-tokens", "4", timeout=500)
    assert "hot reloads applied" in out
    assert "retraces after warmup: 0" in out
    assert out.strip().endswith("ok")


def test_example_gpt_pretrain_sharded():
    out = _run("gpt_pretrain_sharded.py", "--model", "gpt_tiny",
               "--steps", "12", "--batch-size", "8", "--seq-len", "32",
               "--tp", "2", timeout=500)
    assert "GPT sharded pretrain OK" in out


def test_example_train_ssd():
    out = _run("train_ssd.py", "--steps", "12", "--batch-size", "4",
               "--size", "64", timeout=500)
    assert "ssd training OK" in out


def test_example_train_rcnn():
    out = _run("train_rcnn.py", "--steps", "10", "--batch-size", "2",
               timeout=500)
    assert "rcnn training OK" in out


def test_example_finetune_lora():
    out = _run("finetune_lora.py", "--steps", "120")
    assert "lora finetune OK" in out


def test_example_pipeline_parallel_bert():
    out = _run("pipeline_parallel_bert.py", "--steps", "5", "--pp", "4",
               "--batch-size", "8", timeout=500)
    assert "pipeline pretrain OK" in out
    assert "bubble=" in out


def test_example_dcgan():
    out = _run("dcgan.py", "--steps", "50", "--batch-size", "16",
               timeout=500)
    assert "dcgan OK" in out


def test_example_train_dlrm():
    out = _run("train_dlrm.py", "--steps", "40", "--batch-size", "64")
    assert "dlrm OK" in out
    assert "40 captured dispatches" in out  # sparse path stayed captured


def test_example_matrix_factorization():
    out = _run("matrix_factorization.py", "--steps", "150", timeout=500)
    assert "matrix factorization OK" in out
    assert "stype=row_sparse" in out


def test_example_neural_style():
    out = _run("neural_style.py", "--steps", "50", timeout=500)
    assert "neural style OK" in out


def test_example_train_resilient():
    out = _run("train_resilient.py", "--steps", "40",
               "--crash-step", "15")
    assert "recovery OK" in out
    assert "train_resilient: all checks passed" in out
