"""Real sparse storage tests (VERDICT r2 Missing #4 / task: row_sparse
with sparse gradient flow).

Reference parity: tests/python/unittest/test_sparse_ndarray.py +
test_sparse_operator.py — the invariant under test is the one that
matters: gradient/storage buffers are O(touched rows), never O(table).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ndarray.sparse import (CSRNDArray, RowSparseNDArray,
                                      csr_matrix, row_sparse_array)
from mxnet_tpu.ndarray import sparse as sp


def test_row_sparse_compact_storage():
    vals = np.arange(12, dtype=np.float32).reshape(3, 4)
    idx = np.array([1, 5, 98])
    a = row_sparse_array((vals, idx), shape=(100, 4))
    # storage is the compact parts, not a dense (100, 4) buffer
    assert a.num_stored_rows == 3
    assert a._rs_values.shape == (3, 4)
    assert a.shape == (100, 4)
    np.testing.assert_array_equal(a.indices.asnumpy(), idx)
    np.testing.assert_array_equal(a.data.asnumpy(), vals)
    dense = a.tostype("default")
    assert dense.shape == (100, 4)
    np.testing.assert_array_equal(dense.asnumpy()[idx], vals)
    assert dense.asnumpy().sum() == vals.sum()
    # dense -> sparse round trip
    back = dense.tostype("row_sparse")
    assert isinstance(back, RowSparseNDArray)
    assert back.num_stored_rows == 3
    np.testing.assert_array_equal(back.asnumpy(), a.asnumpy())


def test_row_sparse_dense_ops_work():
    a = row_sparse_array((np.ones((2, 3), np.float32), [0, 4]),
                         shape=(6, 3))
    s = (a * 2).asnumpy()
    assert s.sum() == 12.0


def test_csr_compact_storage():
    data = np.array([1.0, 2.0, 3.0], np.float32)
    indices = np.array([0, 2, 1])
    indptr = np.array([0, 2, 2, 3])
    a = csr_matrix((data, indices, indptr), shape=(3, 4))
    assert isinstance(a, CSRNDArray)
    assert a._csr_data.shape == (3,)
    expect = np.zeros((3, 4), np.float32)
    expect[0, 0], expect[0, 2], expect[2, 1] = 1, 2, 3
    np.testing.assert_array_equal(a.asnumpy(), expect)
    np.testing.assert_array_equal(a.indptr.asnumpy(), indptr)
    # dense -> csr
    b = nd.array(expect).tostype("csr")
    np.testing.assert_array_equal(b.data.asnumpy(), data)
    np.testing.assert_array_equal(b.asnumpy(), expect)


def test_sparse_zeros():
    z = sp.zeros("row_sparse", (50, 8))
    assert z.num_stored_rows == 0
    assert z.shape == (50, 8)
    assert z.asnumpy().sum() == 0


def test_embedding_sparse_grad_is_compact():
    """The headline invariant: a 10k-row table touched by 4 distinct ids
    yields a gradient holding exactly 4 rows."""
    from mxnet_tpu.gluon import nn

    vocab, dim = 10000, 8
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize(init=mx.init.Xavier())
    ids = nd.array(np.array([[3, 77, 3], [500, 9999, 77]], np.float32))
    with autograd.record():
        out = emb(ids)
        loss = (out * out).sum()
    loss.backward()
    g = emb.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    assert g.num_stored_rows == 4          # {3, 77, 500, 9999} coalesced
    assert g._rs_values.shape == (4, dim)  # O(touched), not O(vocab)
    # values match the dense autograd path
    emb_d = nn.Embedding(vocab, dim, sparse_grad=False)
    emb_d.initialize(init=mx.init.Xavier())
    emb_d.weight.set_data(emb.weight.data())
    with autograd.record():
        out = emb_d(ids)
        loss = (out * out).sum()
    loss.backward()
    gd = emb_d.weight.grad().asnumpy()
    np.testing.assert_allclose(g.asnumpy(), gd, rtol=1e-5, atol=1e-6)


def test_sparse_sgd_lazy_update_touches_only_rows():
    """Optimizer lazy path: untouched rows (weight AND momentum state)
    must be bit-identical after the update."""
    rng = np.random.RandomState(0)
    vocab, dim = 200, 4
    w0 = rng.randn(vocab, dim).astype(np.float32)
    weight = nd.array(w0.copy())
    idx = np.array([7, 42])
    gvals = rng.randn(2, dim).astype(np.float32)
    grad = row_sparse_array((gvals, idx), shape=(vocab, dim))

    opt = mx.optimizer.SGD(learning_rate=0.5, momentum=0.9, wd=0.0)
    state = opt.create_state(0, weight)
    opt.update(0, weight, grad, state)

    w1 = weight.asnumpy()
    untouched = np.setdiff1d(np.arange(vocab), idx)
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    np.testing.assert_allclose(w1[idx], w0[idx] - 0.5 * gvals,
                               rtol=1e-6)
    mom = state.asnumpy()
    assert np.all(mom[untouched] == 0)
    assert np.any(mom[idx] != 0)


def test_sparse_adam_matches_dense_on_touched_rows():
    rng = np.random.RandomState(1)
    vocab, dim = 50, 3
    w0 = rng.randn(vocab, dim).astype(np.float32)
    idx = np.array([2, 30])
    gvals = rng.randn(2, dim).astype(np.float32)

    w_sp = nd.array(w0.copy())
    opt_sp = mx.optimizer.Adam(learning_rate=0.01)
    st_sp = opt_sp.create_state(0, w_sp)
    opt_sp.update(0, w_sp, row_sparse_array((gvals, idx),
                                            shape=(vocab, dim)), st_sp)

    # dense reference on the same rows: adam on rows with zero grad
    # still moves them (dense semantics) — compare touched rows only
    w_d = nd.array(w0.copy())
    gd = np.zeros((vocab, dim), np.float32)
    gd[idx] = gvals
    opt_d = mx.optimizer.Adam(learning_rate=0.01)
    st_d = opt_d.create_state(0, w_d)
    opt_d.update(0, w_d, nd.array(gd), st_d)

    np.testing.assert_allclose(w_sp.asnumpy()[idx], w_d.asnumpy()[idx],
                               rtol=1e-5, atol=1e-7)
    untouched = np.setdiff1d(np.arange(vocab), idx)
    np.testing.assert_array_equal(w_sp.asnumpy()[untouched],
                                  w0[untouched])


def test_end_to_end_sparse_embedding_training():
    """Eager training loop: Embedding(sparse_grad=True) + Trainer-style
    updates move only touched rows and still learn."""
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(2)
    vocab, dim = 1000, 4
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize(init=mx.init.Xavier())
    w_before = emb.weight.data().asnumpy().copy()
    opt = mx.optimizer.SGD(learning_rate=0.2)
    state = opt.create_state(0, emb.weight.data())
    target = nd.array(rng.randn(2, 3, dim).astype(np.float32))
    ids = nd.array(np.array([[1, 2, 3], [4, 5, 6]], np.float32))
    losses = []
    for _ in range(5):
        with autograd.record():
            out = emb(ids)
            loss = ((out - target) ** 2).sum()
        loss.backward()
        opt.update(0, emb.weight.data(), emb.weight.grad(), state)
        losses.append(float(loss.asscalar()))
    assert losses[-1] < losses[0] * 0.5
    w_after = emb.weight.data().asnumpy()
    untouched = np.setdiff1d(np.arange(vocab), np.arange(1, 7))
    np.testing.assert_array_equal(w_after[untouched],
                                  w_before[untouched])


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    table = np.arange(40, dtype=np.float32).reshape(10, 4)
    kv.init(0, nd.array(table))
    out = sp.zeros("row_sparse", (10, 4))
    kv.row_sparse_pull(0, out=out, row_ids=nd.array([2.0, 7.0, 2.0]))
    assert isinstance(out, RowSparseNDArray)
    assert out.num_stored_rows == 2        # deduplicated
    np.testing.assert_array_equal(out.indices.asnumpy(), [2, 7])
    np.testing.assert_array_equal(out.data.asnumpy(), table[[2, 7]])


def test_sparse_embedding_clips_out_of_range_ids():
    """Backward must scatter at the same CLIPPED ids the forward read:
    id -1 reads row 0 so its gradient belongs to row 0, not the last
    row; id >= vocab belongs to the last row."""
    from mxnet_tpu.gluon import nn

    emb = nn.Embedding(10, 4, sparse_grad=True)
    emb.initialize(init=mx.init.Xavier())
    ids = nd.array(np.array([[-1.0, 12.0]], np.float32))
    with autograd.record():
        loss = emb(ids).sum()
    loss.backward()
    g = emb.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    np.testing.assert_array_equal(np.sort(g.indices.asnumpy()), [0, 9])
    dense = g.asnumpy()
    np.testing.assert_allclose(dense[0], np.ones(4))
    np.testing.assert_allclose(dense[9], np.ones(4))


def test_sparse_sgd_lazy_update_false_is_dense():
    """lazy_update=False must run the full dense update: weight decay
    applies to untouched rows too (reference semantics)."""
    rng = np.random.RandomState(3)
    vocab, dim = 20, 3
    w0 = rng.randn(vocab, dim).astype(np.float32)
    weight = nd.array(w0.copy())
    grad = row_sparse_array(
        (rng.randn(1, dim).astype(np.float32), [4]), shape=(vocab, dim))
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.1, lazy_update=False)
    opt.update(0, weight, grad, opt.create_state(0, weight))
    w1 = weight.asnumpy()
    # untouched row 0 still decayed: w1 = w0 - lr*wd*w0
    np.testing.assert_allclose(w1[0], w0[0] * (1 - 0.1 * 0.1),
                               rtol=1e-5)


def test_grad_req_add_accumulates_sparse():
    from mxnet_tpu.gluon import nn

    emb = nn.Embedding(100, 4, sparse_grad=True)
    emb.initialize(init=mx.init.Xavier())
    emb.weight.grad_req = "add"
    ids1 = nd.array(np.array([[1, 2]], np.float32))
    ids2 = nd.array(np.array([[2, 3]], np.float32))
    for ids in (ids1, ids2):
        with autograd.record():
            loss = emb(ids).sum()
        loss.backward()
    g = emb.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    assert g.num_stored_rows == 3          # {1, 2, 3}
    dense = g.asnumpy()
    np.testing.assert_allclose(dense[2], 2 * np.ones(4), rtol=1e-6)
    np.testing.assert_allclose(dense[1], np.ones(4), rtol=1e-6)


# -- sparse COMPUTE (VERDICT r3 task #5) ---------------------------------------

def test_csr_dot_dense_matches_oracle():
    """dot(csr, dense) and dot(csrᵀ, dense) against numpy, fwd + the
    compact rhs gradient."""
    rs = np.random.RandomState(0)
    a = (rs.rand(8, 12) < 0.3) * rs.standard_normal((8, 12))
    a = a.astype(np.float32)
    w = rs.standard_normal((12, 5)).astype(np.float32)
    a_csr = csr_matrix(a)
    w_nd = nd.array(w)
    w_nd.attach_grad()

    with autograd.record():
        y = nd.sparse.dot(a_csr, w_nd)
        loss = (y * y).sum()
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), a @ w, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(w_nd.grad.asnumpy(), a.T @ (2 * (a @ w)),
                               rtol=1e-4, atol=1e-4)

    # transpose_a: (8, 12)ᵀ @ (8, 5) -> (12, 5)
    x = rs.standard_normal((8, 5)).astype(np.float32)
    x_nd = nd.array(x)
    x_nd.attach_grad()
    with autograd.record():
        yt = nd.sparse.dot(a_csr, x_nd, transpose_a=True)
        loss = (yt * yt).sum()
    loss.backward()
    np.testing.assert_allclose(yt.asnumpy(), a.T @ x, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(x_nd.grad.asnumpy(),
                               a @ (2 * (a.T @ x)), rtol=1e-4,
                               atol=1e-4)


def test_csr_dot_dense_is_jittable():
    """The kernel itself is pure and static-shaped: jit compiles it and
    the jitted result matches (the reference's DotCsrDnsDns under jit —
    no dense (rows, cols) intermediate; the HLO has no such tensor)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ndarray.sparse import csr_dot_dense

    rs = np.random.RandomState(1)
    a = ((rs.rand(16, 300) < 0.1) *
         rs.standard_normal((16, 300))).astype(np.float32)
    w = rs.standard_normal((300, 7)).astype(np.float32)
    a_csr = csr_matrix(a)
    f = jax.jit(lambda d, i, p, r: csr_dot_dense(d, i, p, r, 16))
    out = f(a_csr._csr_data, a_csr._csr_indices, a_csr._csr_indptr,
            jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(out), a @ w, rtol=1e-4,
                               atol=1e-4)
    txt = jax.jit(
        lambda d, i, p, r: csr_dot_dense(d, i, p, r, 16)).lower(
        a_csr._csr_data, a_csr._csr_indices, a_csr._csr_indptr,
        jnp.asarray(w)).as_text()
    assert "16x300" not in txt  # never materializes the dense view


def test_cast_storage_real():
    rs = np.random.RandomState(2)
    dense = ((rs.rand(20, 6) < 0.2) *
             rs.standard_normal((20, 6))).astype(np.float32)
    d_nd = nd.array(dense)
    as_csr = nd.cast_storage(d_nd, "csr")
    assert isinstance(as_csr, CSRNDArray)
    np.testing.assert_allclose(as_csr.asnumpy(), dense)
    as_rs = nd.cast_storage(d_nd, "row_sparse")
    assert isinstance(as_rs, RowSparseNDArray)
    assert as_rs.num_stored_rows == int((dense != 0).any(1).sum())
    back = nd.cast_storage(as_csr, "default")
    assert not isinstance(back, CSRNDArray)
    np.testing.assert_allclose(back.asnumpy(), dense)


def test_libsvm_iter_yields_csr():
    import os
    import tempfile

    from mxnet_tpu import io

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "t.libsvm")
        with open(path, "w") as f:
            f.write("1 0:1.5 3:2.0\n0 1:-1.0\n1 2:0.5 3:1.0\n")
        it = io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                           batch_size=2)
        batch = next(it)
        x = batch.data[0]
        assert isinstance(x, CSRNDArray)
        np.testing.assert_allclose(
            x.asnumpy(), [[1.5, 0, 0, 2.0], [0, -1.0, 0, 0]])
        np.testing.assert_allclose(batch.label[0].asnumpy(), [1, 0])
        batch2 = next(it)  # round_batch wraps
        assert batch2.data[0].shape == (2, 4)
        # dense mode preserved for compat
        it_d = io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                             batch_size=2, stype="default")
        xd = next(it_d).data[0]
        assert not isinstance(xd, CSRNDArray)
        np.testing.assert_allclose(
            xd.asnumpy(), [[1.5, 0, 0, 2.0], [0, -1.0, 0, 0]])


def test_csr_dot_dispatch_covers_all_entry_points():
    """The stype dispatch lives at the invoke layer: nd.dot, the @
    operator, and invoke_registered all route a CSR lhs to the compact
    kernel (never the densify-at-unwrap path)."""
    rs = np.random.RandomState(4)
    a = ((rs.rand(6, 9) < 0.4) *
         rs.standard_normal((6, 9))).astype(np.float32)
    w = rs.standard_normal((9, 3)).astype(np.float32)
    a_csr = csr_matrix(a)
    w_nd = nd.array(w)
    expect = a @ w
    np.testing.assert_allclose(nd.dot(a_csr, w_nd).asnumpy(), expect,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose((a_csr @ w_nd).asnumpy(), expect,
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(Exception, match="shape mismatch"):
        nd.sparse.dot(a_csr, nd.array(w[:5]))


def test_csr_dot_positional_transpose_and_out():
    """ADVICE r4: positional transpose flags must reach the CSR kernel
    (nd.dot(csr, x, True) — valid reference API), and out= must either
    be honored (dense result) or raise (sparse result), never be
    silently left stale."""
    rs = np.random.RandomState(11)
    a = ((rs.rand(6, 9) < 0.4) *
         rs.standard_normal((6, 9))).astype(np.float32)
    x = rs.standard_normal((6, 3)).astype(np.float32)
    a_csr = csr_matrix(a)
    x_nd = nd.array(x)
    # positional transpose_a (third positional arg, dense-op order)
    np.testing.assert_allclose(nd.dot(a_csr, x_nd, True).asnumpy(),
                               a.T @ x, rtol=1e-5, atol=1e-5)
    # out= with a dense result is written through
    w_nd = nd.array(rs.standard_normal((9, 3)).astype(np.float32))
    buf = nd.zeros((6, 3))
    got = nd.dot(a_csr, w_nd, out=buf)
    assert got is buf
    np.testing.assert_allclose(buf.asnumpy(),
                               a @ w_nd.asnumpy(), rtol=1e-5, atol=1e-5)
    # out= with a sparse result raises instead of going stale
    d_nd = nd.array(a)
    with pytest.raises(Exception, match="sparse storage"):
        nd.cast_storage(d_nd, "csr", out=nd.zeros((6, 9)))


def test_libsvm_iter_rejects_multilabel_shape():
    """ADVICE r4: the parser reads one label per row, so a wider
    label_shape must be rejected up front rather than advertising a
    provide_label descriptor the batches never match."""
    import os
    import tempfile

    from mxnet_tpu import io

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "t.libsvm")
        with open(path, "w") as f:
            f.write("1 0:1.5\n")
        with pytest.raises(Exception, match="label_shape"):
            io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                          batch_size=1, label_shape=(3,))
        it = io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                           batch_size=1, label_shape=1)
        assert it.provide_label[0].shape == (1,)


def test_cast_storage_preserves_dtype():
    # int32 survives jnp.asarray (f64 would be downcast at nd.array
    # already, before cast_storage is involved); nd.array defaults to
    # f32 (reference semantics) so pass dtype explicitly
    x = nd.array(np.arange(6).reshape(2, 3), dtype="int32")
    back = nd.cast_storage(nd.cast_storage(x, "csr"), "default")
    assert back.asnumpy().dtype == np.int32
    np.testing.assert_array_equal(back.asnumpy(),
                                  np.arange(6).reshape(2, 3))


def test_sparse_elemwise_compact():
    """Compact row-sparse add / elemwise_mul / retain (reference:
    FComputeEx rsp kernels + mx.nd.sparse.retain): results stay
    compact — stored rows are union / intersection / selection, never
    a dense row-dim buffer."""
    a = row_sparse_array(
        (np.array([[1., 2.], [3., 4.]], np.float32), [1, 5]),
        shape=(100, 2))
    b = row_sparse_array(
        (np.array([[10., 20.], [30., 40.]], np.float32), [5, 7]),
        shape=(100, 2))

    s = sp.add(a, b)
    assert isinstance(s, RowSparseNDArray) and s.num_stored_rows == 3
    np.testing.assert_array_equal(s.indices.asnumpy(), [1, 5, 7])
    np.testing.assert_allclose(
        s.asnumpy()[[1, 5, 7]], [[1, 2], [13, 24], [30, 40]])

    m = sp.elemwise_mul(a, b)
    assert isinstance(m, RowSparseNDArray) and m.num_stored_rows == 1
    np.testing.assert_array_equal(m.indices.asnumpy(), [5])
    np.testing.assert_allclose(m.asnumpy()[5], [30., 80.])

    r = sp.retain(a, nd.array([5, 60]))
    assert isinstance(r, RowSparseNDArray) and r.num_stored_rows == 1
    np.testing.assert_array_equal(r.indices.asnumpy(), [5])
    np.testing.assert_allclose(r.data.asnumpy(), [[3., 4.]])

    # empty intersection
    c = row_sparse_array(
        (np.array([[9., 9.]], np.float32), [50]), shape=(100, 2))
    e = sp.elemwise_mul(a, c)
    assert e.num_stored_rows == 0
    # mixed sparse/dense falls back dense
    d = sp.add(a, nd.ones((100, 2)))
    assert not isinstance(d, RowSparseNDArray)
    np.testing.assert_allclose(d.asnumpy()[1], [2., 3.])


def test_sparse_elemwise_dispatch_and_tape_fallback():
    """rsp+rsp routes compact through EVERY entry point (nd.elemwise_add,
    the + operator) via the invoke-layer dispatch; operands on the
    autograd tape fall back to the dense recording path so gradients
    are never silently dropped."""
    a = row_sparse_array(
        (np.array([[1., 2.]], np.float32), [3]), shape=(50, 2))
    b = row_sparse_array(
        (np.array([[5., 6.]], np.float32), [3]), shape=(50, 2))
    s1 = nd.elemwise_add(a, b)
    assert isinstance(s1, RowSparseNDArray) and s1.num_stored_rows == 1
    s2 = a + b
    assert isinstance(s2, RowSparseNDArray)
    np.testing.assert_allclose(s2.asnumpy()[3], [6., 8.])
    m = nd.elemwise_mul(a, b)
    assert isinstance(m, RowSparseNDArray)
    np.testing.assert_allclose(m.asnumpy()[3], [5., 12.])

    # tape fallback: dense path records, gradients flow
    x = nd.ones((50, 2))
    x.attach_grad()
    with autograd.record():
        y = sp.add(a.tostype("default") * 0 + x, a)  # dense + sparse
        loss = (y * y).sum()
    loss.backward()
    assert float(np.abs(x.grad.asnumpy()).sum()) > 0


def test_kvstore_row_sparse_pull_compact_store():
    """row_sparse_pull on a row-sparse STORE gathers from the compact
    parts — the full dense table is never materialized (asserted by
    poisoning the dense view during the pull)."""
    import mxnet_tpu as mx

    kv = mx.kv.create("local")
    w = row_sparse_array(
        (np.array([[1., 1.], [2., 2.], [3., 3.]], np.float32),
         [2, 7, 11]), shape=(1000, 2))
    kv.init("emb", w)
    out = sp.zeros("row_sparse", (1000, 2))
    poisoned = {"hit": False}
    orig = RowSparseNDArray._data

    def boom(self):
        poisoned["hit"] = True
        return orig.fget(self)

    try:
        RowSparseNDArray._data = property(boom, orig.fset)
        kv.row_sparse_pull("emb", out=out, row_ids=nd.array([7, 500]))
    finally:
        RowSparseNDArray._data = orig
    assert not poisoned["hit"], "dense view materialized during pull"
    np.testing.assert_array_equal(out.indices.asnumpy(), [7, 500])
    np.testing.assert_allclose(out.data.asnumpy(),
                               [[2., 2.], [0., 0.]])


def test_sparse_save_load_roundtrip(tmp_path):
    """nd.save/load round-trips sparse arrays COMPACTLY with stype
    preserved (reference: sparse NDArray::Save) — a row-sparse record
    stores K rows, not the logical row count; dense records are
    byte-identical to before."""
    import os

    a = row_sparse_array(
        (np.array([[1., 2.], [3., 4.]], np.float32), [5, 9000]),
        shape=(10000, 2))
    c = csr_matrix(np.eye(4, dtype=np.float32))
    d = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    p = str(tmp_path / "s.params")
    nd.save(p, {"a": a, "c": c, "d": d})
    sz = os.path.getsize(p)
    assert sz < 4096, sz  # compact: dense-a alone would be 80 KB
    back = nd.load(p)
    assert isinstance(back["a"], RowSparseNDArray)
    assert back["a"].num_stored_rows == 2
    np.testing.assert_array_equal(back["a"].indices.asnumpy(),
                                  [5, 9000])
    np.testing.assert_allclose(back["a"].asnumpy(), a.asnumpy())
    assert isinstance(back["c"], CSRNDArray)
    np.testing.assert_allclose(back["c"].asnumpy(), np.eye(4))
    np.testing.assert_allclose(back["d"].asnumpy(), d.asnumpy())


# -- PR 18: duplicate-id pulls + compression on row-sparse grads ---------------

def test_kvstore_row_sparse_pull_duplicate_numpy_ids():
    """The pull coalesces duplicate row ids ON THE HOST before touching
    the device (one gather, no device-side unique dispatch), and plain
    numpy id arrays are accepted — the prefetcher's warm-pull path
    hands over exactly that."""
    kv = mx.kv.create("local")
    table = np.arange(40, dtype=np.float32).reshape(10, 4)
    kv.init(0, nd.array(table))
    out = sp.zeros("row_sparse", (10, 4))
    kv.row_sparse_pull(0, out=out,
                       row_ids=np.array([7, 2, 7, 7, 2], np.int64))
    assert out.num_stored_rows == 2
    np.testing.assert_array_equal(out.indices.asnumpy(), [2, 7])
    np.testing.assert_array_equal(out.data.asnumpy(), table[[2, 7]])
    # bitwise identical to the already-unique pull
    out2 = sp.zeros("row_sparse", (10, 4))
    kv.row_sparse_pull(0, out=out2, row_ids=nd.array([2.0, 7.0]))
    np.testing.assert_array_equal(out.data.asnumpy(),
                                  out2.data.asnumpy())


def _rs_grad(vals, ids, shape):
    return row_sparse_array(
        (np.asarray(vals, np.float32), list(ids)), shape=shape)


@pytest.mark.parametrize("gc_type,threshold", [("2bit", 0.5),
                                               ("fp16", 0.5)])
def test_compression_rowsparse_error_feedback_bitwise(gc_type,
                                                      threshold):
    """2bit/fp16 on RowSparseNDArray gradients: the quantized push is
    BITWISE equal to a numpy oracle of the error-feedback recurrence,
    the residual stays compact (touched rows only — cold rows never
    materialize error), and rows owing residual are re-emitted on later
    rounds even when the new batch misses them."""
    shape = (12, 2)
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": gc_type,
                                 "threshold": threshold})
    kv.init("emb", nd.zeros(shape))

    rounds = [
        ([[0.7, -0.1], [0.2, 0.9]], [1, 4]),
        ([[0.4, 0.4]], [4]),               # row 1 only owes residual
        ([[0.3, -0.8], [0.05, 0.0]], [1, 9]),
    ]
    residual = {}  # oracle: row id -> np residual row

    def oracle(vals, ids):
        acc = {i: np.array(r, np.float32)
               for i, r in residual.items()}
        for r, i in zip(np.asarray(vals, np.float32), ids):
            acc[i] = acc.get(i, np.zeros(shape[1], np.float32)) + r
        out = {}
        residual.clear()
        for i, a in acc.items():
            if gc_type == "fp16":
                q = a.astype(np.float16).astype(np.float32)
            else:
                q = np.where(a >= threshold, np.float32(threshold),
                             np.where(a <= -threshold,
                                      np.float32(-threshold),
                                      np.float32(0.0)))
            out[i] = q
            res = a - q
            if np.any(res != 0):
                residual[i] = res
        return out

    touched = set()
    for vals, ids in rounds:
        touched.update(ids)
        kv.push("emb", _rs_grad(vals, ids, shape))
        want_rows = oracle(vals, ids)
        got = nd.zeros(shape)
        kv.pull("emb", out=got)
        want = np.zeros(shape, np.float32)
        for i, q in want_rows.items():
            want[i] = q
        np.testing.assert_array_equal(got.asnumpy(), want)
        # the store-side residual mirrors the oracle's, compactly
        gc = kv._compression
        if residual:
            ids_kept, res_kept = gc._residual["emb"]
            np.testing.assert_array_equal(
                np.asarray(ids_kept), sorted(residual))
            for row, i in zip(np.asarray(res_kept),
                              sorted(residual)):
                np.testing.assert_array_equal(row, residual[i])
            assert set(int(i) for i in np.asarray(ids_kept)) <= touched
        else:
            assert "emb" not in gc._residual
