#!/usr/bin/env python
"""One rank of the fleet-observability acceptance run
(tests/test_obs.py::test_fleet_observability_end_to_end).

Each rank runs a bounded-staleness "training" loop: per step it emits a
real telemetry step record (step_begin / on_scope / step_end, so MFU
and the breakdown shares are the production code path), ticks its
`ElasticGang`, then waits until every live peer's heartbeat-published
step is within LAG steps — measuring that wait as the collective share
it feeds the StragglerMonitor.  A `slow_rank` fault on one rank makes
it fall >LAG behind, so the fast ranks genuinely stall in "collective"
while the slow rank's own interval lands in "other" — the exact
correlation `FleetView._stragglers` renders.

A `HostCollector` per rank tails the rank's own JSONL and publishes
rollups at ``obs/rollup/<rank>`` on the shared FileKV; one rank can be
told to die silently mid-run (MXTPU_OBS_EXIT_RANK/STEP) so the
survivors reshape and the fleet timeline gains mesh_reshape/rank_dead.

Protocol lines on stdout (flushed, parsed by the test):

    PID <rank> <pid>
    RESULT <json>   (rank, final_step, epoch, members, reshapes)

Usage:  obs_fleet_worker.py <work_dir> <num_steps> [work_ms]
Env:    MXTPU_WORKER_RANK, MXTPU_NUM_WORKERS, MXTPU_GANG_DIR,
        MXTPU_TELEMETRY_PATH (per rank), MXTPU_PEAK_FLOPS, plus the
        heartbeat/straggler knobs the test sets.
"""

import importlib
import json
import os
import sys
import time
import types

LAG = 2          # bounded staleness: how far a peer may trail


def _emit(line):
    sys.stdout.write(line + "\n")
    sys.stdout.flush()


def _import_modules():
    """Load the needed submodules without executing the package
    __init__ (keeps the worker jax-free and spawn cheap)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "mxnet_tpu" not in sys.modules:
        pkg = types.ModuleType("mxnet_tpu")
        pkg.__path__ = [os.path.join(root, "mxnet_tpu")]
        sys.modules["mxnet_tpu"] = pkg
    tel = importlib.import_module("mxnet_tpu.telemetry")
    res = importlib.import_module("mxnet_tpu.resilience")
    dist = importlib.import_module("mxnet_tpu.distributed")
    col = importlib.import_module("mxnet_tpu.obs.collector")
    return tel, res, dist, col


def _wait_peers(gang, res, step, timeout=15.0):
    """Block until every live peer has published step >= step - LAG;
    raises RankFailure when a peer is confirmed dead instead."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        steps = gang.detector.peer_steps()
        live = [p for p in gang.members if p != gang.rank]
        if all(steps.get(p) is not None and steps[p] >= step - LAG
               for p in live):
            return
        dead = gang.detector.poll() & set(gang.members)
        dead.discard(gang.rank)
        if dead:
            raise res.RankFailure(dead, gang.epoch)
        time.sleep(0.005)


def main():
    tel, res, dist, col = _import_modules()

    work_dir = sys.argv[1]
    num_steps = int(sys.argv[2])
    work_s = (float(sys.argv[3]) / 1e3) if len(sys.argv) > 3 else 0.02
    rank = int(os.environ["MXTPU_WORKER_RANK"])
    world = int(os.environ["MXTPU_NUM_WORKERS"])
    exit_rank = int(os.environ.get("MXTPU_OBS_EXIT_RANK", "-1"))
    exit_step = int(os.environ.get("MXTPU_OBS_EXIT_STEP", "-1"))

    _emit(f"PID {rank} {os.getpid()}")

    kv = dist.gang_kv()
    assert kv is not None, "worker needs MXTPU_GANG_DIR"
    gang = res.ElasticGang(rank, world, kv=kv, peer_snap_every=1)
    gang.start()
    collector = col.HostCollector(kv=kv, rank=rank, world=world,
                                  period_s=0.15).start()

    state = {"w": [float(rank)], "opt": 0.0}
    step = 0
    prev_share = None
    stats = {"reshapes": 0}

    try:
        while step < num_steps:
            if rank == exit_rank and step == exit_step:
                # silent death: heartbeats stop, survivors reshape
                os._exit(0)
            t_iter = time.perf_counter()
            try:
                gang.step_tick(step, state=state,
                               collective_share=prev_share)
                # slow_rank fault slept inside step_tick: that stall is
                # the gap BETWEEN this rank's step records ("other")
                acc = tel.step_begin(path="captured")
                time.sleep(work_s)                    # the "compute"
                tel.on_scope("captured_step", work_s)
                tel.note(flops=float(
                    os.environ.get("MXTPU_OBS_STEP_FLOPS", 1e9)))
                t_w = time.perf_counter()
                _wait_peers(gang, res, step)
                wait_s = time.perf_counter() - t_w
                tel.on_scope("allreduce", wait_s)     # stall bucket
                tel.step_end(acc, step=step)
                total = time.perf_counter() - t_iter
                prev_share = wait_s / total if total > 0 else 0.0
            except res.RankFailure as rf:
                tel.step_abort(tel._CURRENT)
                info = gang.recover(rf)
                step = info.snap_step
                stats["reshapes"] += 1
                continue
            step += 1
        collector.poll_once()          # final rollup with every step
        collector.close()
        gang.stop()
    except res.GangEvicted:
        _emit(f"EVICTED {rank}")
        return 0
    _emit("RESULT " + json.dumps(
        {"rank": rank, "final_step": step, "epoch": gang.epoch,
         "members": gang.members, "reshapes": stats["reshapes"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
