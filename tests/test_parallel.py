"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4:
the analog of the reference's fake-multi-node local tracker)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon import nn
from mxnet_tpu.ops.attention import scaled_dot_product_attention


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(2, 8, 64, 16).astype(np.float32))
    return mk(), mk(), mk()


def test_mesh_axes():
    mesh = parallel.make_mesh(dp=4, tp=2)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    with pytest.raises(Exception):
        parallel.make_mesh(dp=100)


@pytest.mark.parametrize("axis", ["dp", "tp", "pp", "sp", "ep"])
def test_make_mesh_overflow_message_per_axis(axis):
    """Mismatch raises OUR ValueError naming the axis product and the
    device count — not whatever jax raises from a bad reshape."""
    n = len(jax.devices())
    with pytest.raises(ValueError) as ei:
        parallel.make_mesh(**{axis: n + 1})
    msg = str(ei.value)
    assert f"{axis}={n + 1}" in msg
    assert str(n + 1) in msg and str(n) in msg
    assert "jax.devices()" in msg


def test_make_mesh_overflow_product_named():
    with pytest.raises(ValueError) as ei:
        parallel.make_mesh(dp=4, tp=4)
    msg = str(ei.value)
    assert "dp=4 * tp=4 = 16" in msg


def test_make_mesh_devices_override():
    devs = jax.devices()[:4]
    mesh = parallel.make_mesh(dp=2, tp=2, devices=devs)
    assert mesh.shape == {"dp": 2, "tp": 2}
    assert set(mesh.devices.flat) == set(devs)
    with pytest.raises(ValueError) as ei:
        parallel.make_mesh(dp=8, devices=devs)
    assert "devices= override" in str(ei.value)
    assert "only 4 available" in str(ei.value)


def test_make_mesh_rejects_bad_axis_values():
    for bad in (0, -1, 2.0, "2"):
        with pytest.raises(ValueError):
            parallel.make_mesh(dp=bad)


def test_make_mesh_axes_dict_form():
    """PR 17 ergonomics: axes={...} builds the same mesh as keywords,
    keeps the per-axis overflow ValueError naming the axis, and rejects
    ambiguous keyword+dict mixes / unknown axis names."""
    mesh = parallel.make_mesh(axes={"tp": 2, "pp": 2, "dp": 2})
    assert mesh.shape == {"pp": 2, "dp": 2, "tp": 2}  # canonical order
    kw = parallel.make_mesh(tp=2, pp=2, dp=2)
    assert mesh.shape == kw.shape
    assert [d.id for d in mesh.devices.flat] \
        == [d.id for d in kw.devices.flat]
    n = len(jax.devices())
    with pytest.raises(ValueError) as ei:
        parallel.make_mesh(axes={"pp": n + 1})
    assert f"pp={n + 1}" in str(ei.value)
    with pytest.raises(ValueError) as ei:
        parallel.make_mesh(tp=2, axes={"dp": 2})
    assert "not both" in str(ei.value)
    with pytest.raises(ValueError) as ei:
        parallel.make_mesh(axes={"zz": 2})
    assert "unknown axis 'zz'" in str(ei.value)
    with pytest.raises(ValueError):
        parallel.make_mesh(axes={"dp": 0})


# -- ShardingRules resolution order (pinned semantics) -------------------------

def test_sharding_rules_first_match_wins():
    """Resolution is FIRST match in insertion order, not most-specific:
    the broad rule inserted first shadows the narrower one after it."""
    rules = parallel.ShardingRules(rules=[
        (r"weight$", ("tp", None)),
        (r"special_weight$", (None, "tp")),
    ])
    assert tuple(rules.spec_for("special_weight")) == ("tp", None)
    # swapping the insertion order flips the winner
    rules2 = parallel.ShardingRules(rules=[
        (r"special_weight$", (None, "tp")),
        (r"weight$", ("tp", None)),
    ])
    assert tuple(rules2.spec_for("special_weight")) == (None, "tp")


def test_sharding_rules_spec_for_shape_none_and_default():
    rules = parallel.ShardingRules(rules=[(r"w$", ("tp",))],
                                   default=("dp",))
    # shape=None is always legal on regex rules
    assert tuple(rules.spec_for("layer_w", shape=None)) == ("tp",)
    # no match falls to the rule set's default
    assert tuple(rules.spec_for("unmatched_bias")) == ("dp",)
    assert tuple(parallel.ShardingRules().spec_for("anything")) == ()


def test_combined_rules_override_semantics():
    """Every rule of an earlier set outranks every rule of a later set;
    `add` on the combination appends at LOWEST precedence."""
    a = parallel.ShardingRules(rules=[(r"weight$", ("tp", None))])
    b = parallel.ShardingRules(rules=[(r"weight$", (None, "tp")),
                                      (r"bias$", ("tp",))])
    combo = parallel.combined_rules(a, b)
    assert tuple(combo.spec_for("x_weight")) == ("tp", None)   # a wins
    assert tuple(combo.spec_for("x_bias")) == ("tp",)          # b fills in
    combo.add(r"bias$", (None,))
    assert tuple(combo.spec_for("x_bias")) == ("tp",)  # b still outranks
    combo2 = parallel.combined_rules(a).add(r"gamma$", ("dp",))
    assert tuple(combo2.spec_for("bn_gamma")) == ("dp",)


def test_combined_rules_fsdp_shape_heuristic_ordering():
    """TP-in-front-of-FSDP: the regex rule claims matching names, the
    shape heuristic of the LATER set covers the rest."""
    tp = parallel.ShardingRules(rules=[(r"qkv_weight$", ("tp", None))])
    combo = parallel.combined_rules(
        tp, parallel.FSDPRules(axis_size=4, min_size=16))
    assert tuple(combo.spec_for("l0_qkv_weight", (12, 8))) == ("tp", None)
    assert tuple(combo.spec_for("l0_other_weight", (8, 4))) == ("dp", None)


def test_fsdp_rules_shape_heuristic():
    rules = parallel.FSDPRules(axis_size=4, min_size=16)
    assert tuple(rules.spec_for("w", (8, 4))) == ("dp", None)
    # first divisible dim wins; dim0=6 not divisible by 4, dim1=8 is
    assert tuple(rules.spec_for("w", (6, 8))) == (None, "dp")
    assert tuple(rules.spec_for("b", (3,))) == ()        # < min_size
    assert tuple(rules.spec_for("w", (6, 7))) == ()      # nothing divides
    assert tuple(rules.spec_for("w", None)) == ()        # unknown shape
    assert tuple(rules.spec_for("w", (4, 4, 4))) == ("dp", None, None)


def test_combined_rules_three_way_tp_pp_dp_earlier_set_wins():
    """Satellite (PR 17): earlier-set-wins holds for 3-way tp×pp×dp
    composition with OVERLAPPING ``*_stack_*`` patterns — the ordinary
    (non-composable) sets still compete whole-spec in order, while the
    PPRules overlay merges per-dim on top of whichever won."""
    tp = parallel.ShardingRules(rules=[
        (r"qkv_stack_weight$", (None, "tp", None))])
    # a later set with a BROADER overlapping stack pattern: must lose
    dp = parallel.ShardingRules(rules=[
        (r"_stack_weight$", (None, "dp", None)),
        (r"_stack_bias$", (None, "dp"))])
    combo = parallel.combined_rules(parallel.PPRules(), tp, dp)
    # tp (earlier) wins the overlap whole-spec; pp merges onto dim 0
    assert tuple(combo.spec_for("l_qkv_stack_weight", (4, 24, 8))) \
        == ("pp", "tp", None)
    # names only the later set matches fall through to it, pp on top
    assert tuple(combo.spec_for("l_ffn9_stack_weight", (4, 64, 8))) \
        == ("pp", "dp", None)
    assert tuple(combo.spec_for("l_qkv_stack_bias", (4, 24))) \
        == ("pp", "dp")
    # swapping tp/dp order flips the overlap winner (earlier-set-wins)
    combo2 = parallel.combined_rules(parallel.PPRules(), dp, tp)
    assert tuple(combo2.spec_for("l_qkv_stack_weight", (4, 24, 8))) \
        == ("pp", "dp", None)


def test_combined_rules_conflicting_dim_assignment_raises():
    """Two sets assigning DIFFERENT axes to the same dim of the same
    param is a hard error naming the param, the dim and both axes —
    not a silent override."""
    dp0 = parallel.ShardingRules(rules=[
        (r"_stack_weight$", ("dp", None, None))])
    combo = parallel.combined_rules(parallel.PPRules(), dp0)
    with pytest.raises(ValueError) as ei:
        combo.spec_for("l_qkv_stack_weight", (4, 24, 8))
    msg = str(ei.value)
    assert "l_qkv_stack_weight" in msg and "dim 0" in msg
    assert "'pp'" in msg and "'dp'" in msg
    # same axis on the same dim is idempotent, not a conflict
    pp0 = parallel.ShardingRules(rules=[
        (r"_stack_weight$", ("pp", None, None))])
    ok = parallel.combined_rules(parallel.PPRules(), pp0)
    assert tuple(ok.spec_for("l_qkv_stack_weight", (4, 24, 8))) \
        == ("pp", None, None)


def test_pp_rules_divisibility_and_fsdp_reroute():
    """A stack whose layer count the stage count does not divide stays
    unclaimed; the FSDP shape heuristic re-routes around the claimed
    stack dim instead of erroring (heuristic never outranks a claim)."""
    rules = parallel.pp_rules(axis_size=2)
    assert tuple(rules.spec_for("l_qkv_stack_weight", (4, 8, 8))) \
        == ("pp",)
    assert tuple(rules.spec_for("l_qkv_stack_weight", (3, 8, 8))) == ()
    combo = parallel.combined_rules(
        parallel.pp_rules(axis_size=2),
        parallel.FSDPRules(axis_size=4, min_size=16))
    # heuristic alone would take dim 0 (4 % 4 == 0); the pp claim moves
    # it to the next divisible dim
    assert tuple(combo.spec_for("l_ffn_stack_weight", (4, 8, 6))) \
        == ("pp", "dp", None)
    # non-stack params see the plain heuristic
    assert tuple(combo.spec_for("l_dense_weight", (8, 4))) \
        == ("dp", None)


def test_match_partition_rules_bulk():
    rules = parallel.ShardingRules(rules=[(r"weight$", ("tp", None))])
    specs = parallel.match_partition_rules(
        rules, {"a_weight": (8, 4), "a_bias": (8,)})
    assert tuple(specs["a_weight"]) == ("tp", None)
    assert tuple(specs["a_bias"]) == ()


def test_ring_attention_matches_dense(qkv):
    q, k, v = qkv
    mesh = parallel.make_mesh(sp=8)
    dense = scaled_dot_product_attention(q, k, v)
    ring = parallel.ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_causal(qkv):
    q, k, v = qkv
    mesh = parallel.make_mesh(sp=8)
    dense = scaled_dot_product_attention(q, k, v, causal=True)
    ring = parallel.ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_differentiable(qkv):
    q, k, v = qkv
    mesh = parallel.make_mesh(sp=8)

    def loss_ring(q):
        return jnp.sum(parallel.ring_attention(q, k, v, mesh=mesh) ** 2)

    def loss_dense(q):
        return jnp.sum(scaled_dot_product_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring)(q)
    g_dense = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(np.asarray(g_dense), np.asarray(g_ring),
                               rtol=2e-3, atol=2e-4)


def test_ring_attention_flash_no_dense_scores_in_hlo():
    """VERDICT r3 task #3 'done' criterion: with the Pallas path, the
    sharded program contains NO (Tq/P × Tk/P) score tensor — per-step
    memory is tile-bounded.  Small tile overrides (8×8) at Tloc=32 make
    a 32×32 intermediate the dense-path signature to assert against."""
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 256, 16).astype(np.float32))
               for _ in range(3))
    mesh = parallel.make_mesh(sp=8)

    def flash(q, k, v):
        return parallel.ring_attention(q, k, v, mesh=mesh, causal=True,
                                       block_q=8, block_k=8)

    def dense(q, k, v):
        return parallel.ring_attention(q, k, v, mesh=mesh, causal=True,
                                       impl="dense")

    txt_flash = jax.jit(flash).lower(q, k, v).as_text()
    txt_dense = jax.jit(dense).lower(q, k, v).as_text()
    assert "32x32xf32" in txt_dense      # the test can detect the tensor
    assert "32x32xf32" not in txt_flash  # ...and flash never builds it


def test_ring_attention_flash_long_seq_sharded():
    """T=32768 global causal over an 8-way sp ring (Tloc=4096, streamed
    2048-tile kernel): last 64 rows attend to the whole sequence, checked
    against a dense numpy oracle."""
    rng = np.random.RandomState(5)
    T, D = 32768, 8
    q, k, v = (rng.randn(1, 1, T, D).astype(np.float32) for _ in range(3))
    mesh = parallel.make_mesh(sp=8)
    out = parallel.ring_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh=mesh,
        causal=True, block_q=2048, block_k=2048)
    rows = slice(T - 64, T)
    s = q[0, 0, rows] @ k[0, 0].T * (D ** -0.5)   # (64, T)
    mask = np.arange(T)[None, :] <= np.arange(T - 64, T)[:, None]
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    ref = p @ v[0, 0]
    np.testing.assert_allclose(np.asarray(out)[0, 0, rows], ref,
                               rtol=2e-4, atol=2e-5)


def test_ulysses_matches_dense(qkv):
    q, k, v = qkv
    mesh = parallel.make_mesh(sp=8)
    dense = scaled_dot_product_attention(q, k, v, causal=True)
    uly = parallel.ulysses_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(uly),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_interpret(qkv):
    from mxnet_tpu.ops.pallas_attention import flash_attention

    q, k, v = qkv
    for causal in (False, True):
        dense = scaled_dot_product_attention(q, k, v, causal=causal)
        fl = flash_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(fl),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_grad(qkv):
    """FlashAttention-2 Pallas backward: dq, dk, dv vs the dense oracle,
    causal and bidirectional (interpret mode)."""
    from mxnet_tpu.ops.pallas_attention import flash_attention

    q, k, v = qkv
    for causal in (False, True):
        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(
                scaled_dot_product_attention(q, k, v, causal=causal) ** 2)

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, nm in zip(got, ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
                err_msg=f"d{nm} causal={causal}")


def test_flash_attention_multiblock_streaming():
    """K/V stream through the kernel in blocks: small block overrides at
    T=1024 force an 8x8 q/kv grid, so per-step VMEM is tile-sized and
    independent of T (the long-context property, VERDICT r2 Weak #3)."""
    from mxnet_tpu.ops.pallas_attention import flash_attention

    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 1024, 32).astype(np.float32))
               for _ in range(3))
    for causal in (False, True):
        dense = scaled_dot_product_attention(q, k, v, causal=causal)
        fl = flash_attention(q, k, v, causal=causal, block_q=128,
                             block_k=128)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(fl),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_odd_seq_len():
    """T not divisible by 128 still works off-TPU (single-block kernel);
    on TPU this shape dispatches to the dense path."""
    from mxnet_tpu.ops.pallas_attention import flash_attention

    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 192, 16).astype(np.float32))
               for _ in range(3))
    dense = scaled_dot_product_attention(q, k, v, causal=True)
    fl = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(fl),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_rejects_non_dividing_blocks():
    from mxnet_tpu.ops.pallas_attention import flash_attention

    q = jnp.zeros((1, 1, 128, 16))
    with pytest.raises(ValueError, match="must divide"):
        flash_attention(q, q, q, block_q=96)


def test_flash_attention_long_seq():
    """T=16384 causal with 2048-token tiles (64-step streamed grid).
    Attention rows are independent, so the oracle only needs a row
    subset: check the last 64 rows (they attend to the whole sequence)
    against a dense numpy reference."""
    from mxnet_tpu.ops.pallas_attention import flash_attention

    rng = np.random.RandomState(3)
    T, D = 16384, 8
    q, k, v = (rng.randn(1, 1, T, D).astype(np.float32) for _ in range(3))
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True, block_q=2048, block_k=2048)
    rows = slice(T - 64, T)
    s = q[0, 0, rows] @ k[0, 0].T * (D ** -0.5)   # (64, T)
    mask = np.arange(T)[None, :] <= np.arange(T - 64, T)[:, None]
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    ref = p @ v[0, 0]
    np.testing.assert_allclose(np.asarray(out)[0, 0, rows], ref,
                               rtol=2e-4, atol=2e-5)


def test_bert_flash_attention_trains():
    """BERT with attention_impl='flash' runs a full ShardedTrainer step —
    the Pallas fwd+bwd kernels inside a jitted, sharded training step."""
    from mxnet_tpu.gluon.model_zoo import bert

    mesh = parallel.data_parallel_mesh(8)
    net = bert.bert_tiny(attention_impl="flash")
    net.initialize(init=mx.init.Xavier())
    tr = parallel.ShardedTrainer(
        net, bert.BERTPretrainLoss(), "adam", {"learning_rate": 1e-3},
        mesh=mesh)
    rng = np.random.RandomState(0)
    B, T = 8, 32
    ids = rng.randint(0, 1024, (B, T)).astype(np.int32)
    mlm = np.where(rng.rand(B, T) < 0.15, ids, -1).astype(np.float32)
    nsp = rng.randint(0, 2, (B,)).astype(np.float32)
    l0 = float(tr.step(ids, (mx.nd.array(mlm), mx.nd.array(nsp)))
               .asscalar())
    l1 = float(tr.step(ids, (mx.nd.array(mlm), mx.nd.array(nsp)))
               .asscalar())
    assert np.isfinite(l0) and np.isfinite(l1)


def test_sharded_trainer_dp_matches_single_device():
    """DP training over 8 shards must match the same model trained
    locally (the CPU↔TPU consistency oracle, SURVEY §4)."""
    def build():
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.HybridSequential(prefix="m_")
        with net.name_scope():
            # in_units given → immediate (not deferred) init, so both
            # builds draw identical weights from the reseeded RNG
            net.add(nn.Dense(16, activation="relu", in_units=8),
                    nn.Dense(4, in_units=16))
        net.initialize(init=mx.init.Xavier())
        return net

    x = np.random.RandomState(1).randn(32, 8).astype(np.float32)
    y = (np.arange(32) % 4).astype(np.float32)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # sharded: dp=8 mesh
    net_a = build()
    tr_a = parallel.ShardedTrainer(net_a, loss_fn, "sgd",
                                   {"learning_rate": 0.1},
                                   mesh=parallel.make_mesh(dp=8))
    # local single-logical-device via gluon.Trainer
    net_b = build()
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1})
    for _ in range(3):
        tr_a.step(x, y)
        with mx.autograd.record():
            loss = loss_fn(net_b(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        tr_b.step(32)
    tr_a.sync_params()
    wa = net_a[0].weight.data().asnumpy()
    wb = net_b[0].weight.data().asnumpy()
    np.testing.assert_allclose(wa, wb, rtol=1e-4, atol=1e-5)


def test_sharded_trainer_tp_rules_shard_params():
    from mxnet_tpu.gluon.model_zoo import bert

    mesh = parallel.make_mesh(dp=4, tp=2)
    net = bert.bert_tiny()
    net.initialize(init=mx.init.Xavier())
    tr = parallel.ShardedTrainer(net, bert.BERTPretrainLoss(), "adam",
                                 {"learning_rate": 1e-3}, mesh=mesh,
                                 rules=parallel.TRANSFORMER_TP_RULES)
    rng = np.random.RandomState(0)
    B, T = 8, 32
    ids = rng.randint(0, 1024, (B, T)).astype(np.int32)
    mlm = np.where(rng.rand(B, T) < 0.15, ids, -1).astype(np.float32)
    nsp = rng.randint(0, 2, (B,)).astype(np.float32)
    l0 = tr.step(ids, (mx.nd.array(mlm), mx.nd.array(nsp)))
    l1 = tr.step(ids, (mx.nd.array(mlm), mx.nd.array(nsp)))
    assert np.isfinite(float(l1.asscalar()))
    specs = {n: v.sharding.spec for (n, _), v in
             zip(tr._trainable, tr._param_vals)}
    qkv = [s for n, s in specs.items() if "qkv_weight" in n]
    assert all(tuple(s) and s[0] == "tp" for s in qkv), qkv
    ffn2 = [s for n, s in specs.items() if "ffn2_weight" in n]
    assert all(len(tuple(s)) >= 2 and s[1] == "tp" for s in ffn2), ffn2


def test_pipeline_apply_matches_sequential():
    mesh = parallel.make_mesh(pp=8)
    feat = 8
    rng = np.random.RandomState(0)
    stages = [{"w": jnp.asarray(rng.randn(feat, feat).astype(np.float32)
                                * 0.3),
               "b": jnp.asarray(rng.randn(feat).astype(np.float32) * 0.1)}
              for _ in range(8)]

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    stacked = parallel.stack_stage_params(stages)
    stacked = jax.device_put(
        stacked, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("pp")))
    x_micro = jnp.asarray(rng.randn(16, 4, feat).astype(np.float32))
    out = parallel.pipeline_apply(stage_fn, stacked, x_micro, mesh=mesh)

    ref = x_micro
    for p in stages:
        ref = jnp.tanh(ref @ p["w"] + p["b"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_collectives_allreduce():
    mesh = parallel.make_mesh(dp=8)
    x = jax.device_put(
        jnp.arange(16.0),
        jax.sharding.NamedSharding(mesh,
                                   jax.sharding.PartitionSpec("dp")))
    out = parallel.collectives.allreduce(x, mesh)
    total = np.asarray(out)
    # psum over shards: every shard position holds the sum of its peers
    expected = np.arange(16.0).reshape(8, 2).sum(axis=0)
    np.testing.assert_allclose(total[:2], expected)


def test_bandwidth_tool_runs():
    mesh = parallel.make_mesh(dp=8)
    bw = parallel.collectives.measure_allreduce_bandwidth(
        mesh, size_mb=1, iters=2)
    assert bw > 0


def test_bert_ring_attention_model():
    """BERT with attention_impl='ring' trains on an sp mesh."""
    from mxnet_tpu.gluon.model_zoo import bert

    mesh = parallel.make_mesh(sp=4)
    parallel.set_default_mesh(mesh)
    net = bert.bert_tiny(attention_impl="ring", use_decoder=False,
                         use_pooler=False)
    net.initialize(init=mx.init.Xavier())
    ids = mx.nd.array(np.random.randint(0, 1024, (2, 32))
                      .astype(np.float32))
    out = net(ids)
    assert out.shape == (2, 32, 64)
    dense_net = bert.bert_tiny(attention_impl="dense", use_decoder=False,
                               use_pooler=False,
                               params=net.collect_params())
    out2 = dense_net(ids)
    np.testing.assert_allclose(out.asnumpy(), out2.asnumpy(), rtol=2e-3,
                               atol=2e-4)


def test_sharded_trainer_bf16_multi_step():
    """bf16 training: params must STAY bf16 across steps (the f32 lr
    scalar used to promote the update math, retracing the step and then
    failing in the conv transpose — the round-1 bench crash class)."""
    import jax.numpy as jnp

    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=10)
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mesh = parallel.data_parallel_mesh(8)
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}, mesh=mesh)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal((8, 3, 32, 32)),
                    dtype=jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 10, 8).astype("float32"))
    for _ in range(3):
        loss = tr.step(x, y)
    assert np.isfinite(float(loss.asscalar()))
    assert all(v.dtype == jnp.bfloat16 for v in tr._param_vals)


def test_pipeline_trainer_loss_decreases():
    """GPipe training: 4 stages on a pp mesh, one jitted step, loss falls."""
    mesh = parallel.make_mesh(pp=4)
    net = gluon.nn.HybridSequential()
    for _ in range(4):
        net.add(gluon.nn.Dense(16, activation="tanh"))
    net.initialize(init=mx.init.Xavier())
    pt = parallel.PipelineTrainer(net, gluon.loss.L2Loss(), "sgd",
                                  {"learning_rate": 0.1}, mesh=mesh,
                                  n_microbatches=8)
    rng = np.random.RandomState(0)
    xs = mx.nd.array(rng.standard_normal((16, 16)).astype("float32"))
    ys = mx.nd.array(rng.standard_normal((16, 16)).astype("float32") * 0.1)
    losses = [float(pt.step(xs, ys).asscalar()) for _ in range(6)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_trainer_matches_unpipelined(schedule):
    """Both schedules compute the SAME gradients as ordinary full-batch
    training: after 3 identical adam steps the pipelined and
    unpipelined parameters agree.  (GPipe backward is the AD transpose
    of the forward scan; 1F1B's is hand-rolled with recompute-vjp.)"""
    import jax.numpy as jnp

    def build():
        net = gluon.nn.HybridSequential(prefix="m_")
        for _ in range(2):
            net.add(gluon.nn.Dense(8, activation="tanh", in_units=8))
        net.initialize(init=mx.init.Xavier())
        return net

    mx.random.seed(7)
    net_pp = build()
    mx.random.seed(7)
    net_ref = build()

    rng = np.random.RandomState(1)
    xs = mx.nd.array(rng.standard_normal((8, 8)).astype("float32"))
    ys = mx.nd.array(rng.standard_normal((8, 8)).astype("float32"))

    mesh = parallel.make_mesh(pp=2)
    pt = parallel.PipelineTrainer(net_pp, gluon.loss.L2Loss(), "adam",
                                  {"learning_rate": 0.01}, mesh=mesh,
                                  n_microbatches=4, schedule=schedule)
    assert 0.0 < pt.bubble_fraction < 1.0
    ref = parallel.ShardedTrainer(net_ref, gluon.loss.L2Loss(), "adam",
                                  {"learning_rate": 0.01},
                                  mesh=parallel.data_parallel_mesh(1))
    for _ in range(3):
        lp = float(pt.step(xs, ys).asscalar())
        lr_ = float(ref.step(xs._data, ys._data).asscalar())
    np.testing.assert_allclose(lp, lr_, rtol=1e-5)
    pt.sync_params()
    ref.sync_params()
    # pair by STRUCTURAL order, not sorted names: global auto-name
    # counters depend on how many layers earlier tests created, and
    # two-digit names sort lexicographically (conv10 < conv9), which
    # would mis-pair the two identically-built networks
    for (n1, p1), (n2, p2) in zip(net_pp.collect_params().items(),
                                  net_ref.collect_params().items()):
        np.testing.assert_allclose(p1.data().asnumpy(),
                                   p2.data().asnumpy(), rtol=2e-5,
                                   atol=2e-6, err_msg=f"{n1} vs {n2}")


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_trainer_batchnorm_matches_microbatched(schedule):
    """VERDICT r3 task #4: BN-bearing stages pipeline.  Per-stage aux
    (running mean/var) is stacked on pp and updated per-microbatch tick;
    the oracle is unpipelined training with grad_accum = n_micro, which
    has the same per-microbatch BN semantics.  Params AND running stats
    must agree."""
    def build():
        net = gluon.nn.HybridSequential(prefix="bn_")
        for _ in range(2):
            blk = gluon.nn.HybridSequential(prefix="")
            blk.add(gluon.nn.Conv2D(4, 3, padding=1, in_channels=4,
                                    use_bias=False))
            blk.add(gluon.nn.BatchNorm(in_channels=4))
            blk.add(gluon.nn.Activation("relu"))
            net.add(blk)
        net.initialize(init=mx.init.Xavier())
        return net

    mx.random.seed(5)
    net_pp = build()
    mx.random.seed(5)
    net_ref = build()

    rng = np.random.RandomState(3)
    xs = mx.nd.array(rng.standard_normal((8, 4, 6, 6)).astype("float32"))
    ys = mx.nd.array(rng.standard_normal((8, 4, 6, 6)).astype("float32"))

    mesh = parallel.make_mesh(pp=2)
    pt = parallel.PipelineTrainer(net_pp, gluon.loss.L2Loss(), "sgd",
                                  {"learning_rate": 0.05, "momentum": 0.9},
                                  mesh=mesh, n_microbatches=4,
                                  schedule=schedule)
    ref = parallel.ShardedTrainer(net_ref, gluon.loss.L2Loss(), "sgd",
                                  {"learning_rate": 0.05, "momentum": 0.9},
                                  mesh=parallel.data_parallel_mesh(1),
                                  grad_accum=4)
    for _ in range(3):
        lp = float(pt.step(xs, ys).asscalar())
        lr_ = float(ref.step(xs._data, ys._data).asscalar())
    np.testing.assert_allclose(lp, lr_, rtol=1e-5)
    pt.sync_params()
    ref.sync_params()
    pairs = list(zip(net_pp.collect_params().items(),
                     net_ref.collect_params().items()))  # structural order
    assert any("running" in n1 for (n1, _), _ in pairs)  # aux compared
    for (n1, p1), (n2, p2) in pairs:
        np.testing.assert_allclose(p1.data().asnumpy(),
                                   p2.data().asnumpy(), rtol=2e-5,
                                   atol=2e-6, err_msg=f"{n1} vs {n2}")


def test_pipeline_bert_matches_unpipelined():
    """A REAL model through the pipe (VERDICT r2 Weak #4): BERT-tiny as
    embedding prologue + homogeneous encoder trunk + MLM-head epilogue.
    Pipelined training must match the unpipelined reference step for
    step."""
    import jax.numpy as jnp

    from mxnet_tpu.gluon.model_zoo import bert

    def build():
        mx.random.seed(11)
        np.random.seed(11)
        embed, layers, head = bert.bert_pipeline_parts(
            vocab_size=64, units=16, num_layers=2, num_heads=2,
            max_length=16, dropout=0.0)
        for b in [embed] + layers + [head]:
            b.initialize(init=mx.init.Xavier())
        return embed, layers, head

    # sgd+momentum, not adam: adam's m/sqrt(v) turns 1-ulp summation
    # -order differences on near-zero-gradient params into O(lr) steps,
    # which is optimizer amplification, not pipeline divergence
    opt, opt_kw = "sgd", {"learning_rate": 0.05, "momentum": 0.9}
    embed, layers, head = build()
    mesh = parallel.make_mesh(pp=2)
    pt = parallel.PipelineTrainer(
        layers, bert.BERTMLMLoss(), opt, opt_kw, mesh=mesh,
        n_microbatches=4, prologue=embed, epilogue=head)

    embed2, layers2, head2 = build()
    seq = gluon.nn.HybridSequential(prefix="ref_")
    seq.add(embed2)
    for l in layers2:
        seq.add(l)
    seq.add(head2)
    ref = parallel.ShardedTrainer(
        seq, bert.BERTMLMLoss(), opt, dict(opt_kw),
        mesh=parallel.data_parallel_mesh(1))

    rng = np.random.RandomState(2)
    B, T = 8, 16
    ids = rng.randint(0, 64, (B, T)).astype(np.int32)
    labels = np.where(rng.rand(B, T) < 0.2, ids, -1).astype(np.float32)

    for _ in range(3):
        lp = float(pt.step(mx.nd.array(ids),
                           mx.nd.array(labels)).asscalar())
        lr_ = float(ref.step(jnp.asarray(ids),
                             jnp.asarray(labels)).asscalar())
    np.testing.assert_allclose(lp, lr_, rtol=1e-5)
    pt.sync_params()
    ref.sync_params()
    pp_params = {}
    for block in [embed] + layers + [head]:
        pp_params.update(block.collect_params())
    ref_params = dict(seq.collect_params())
    assert len(pp_params) == len(ref_params)
    for (n1, p1), (n2, p2) in zip(pp_params.items(),
                                  ref_params.items()):  # structural order
        np.testing.assert_allclose(
            p1.data().asnumpy(), p2.data().asnumpy(), rtol=2e-5,
            atol=2e-6, err_msg=f"{n1} vs {n2}")


def test_remat_identical_grads():
    """remat ('full' and 'dots') must not change the math — params after
    identical steps match the no-remat run exactly (MXNET_BACKWARD_DO_MIRROR
    analog; mxnet_tpu/remat.py)."""
    rng = np.random.RandomState(3)
    x = rng.standard_normal((16, 12)).astype(np.float32)
    y = (np.arange(16) % 3).astype(np.float32)

    def run(remat):
        def build():
            mx.random.seed(5)
            np.random.seed(5)
            net = nn.HybridSequential(prefix="r_")
            with net.name_scope():
                net.add(nn.Dense(32, activation="relu", in_units=12),
                        nn.Dense(3, in_units=32))
            net.initialize(init=mx.init.Xavier())
            return net

        net = build()
        tr = parallel.ShardedTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 0.01}, mesh=parallel.data_parallel_mesh(8),
            remat=remat)
        for _ in range(2):
            loss = tr.step(x, y)
        return [np.asarray(v) for v in tr._param_vals], \
            float(loss.asscalar())

    base_p, base_l = run(None)
    for policy in ("full", "dots"):
        p, l = run(policy)
        assert l == base_l or abs(l - base_l) < 1e-6
        for a, b in zip(p, base_p):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_hybridize_remat_matches():
    """hybridize(remat='full'): same outputs and gradients as without."""
    def build(remat):
        mx.random.seed(9)
        np.random.seed(9)
        net = nn.HybridSequential(prefix="h_")
        with net.name_scope():
            net.add(nn.Dense(16, activation="tanh", in_units=8),
                    nn.Dense(4, in_units=16))
        net.initialize(init=mx.init.Xavier())
        net.hybridize(remat=remat) if remat else net.hybridize()
        return net

    x = mx.nd.array(np.random.RandomState(2).randn(4, 8)
                    .astype(np.float32))
    outs, grads = [], []
    for remat in (None, "full"):
        net = build(remat)
        with mx.autograd.record():
            out = net(x)
            loss = mx.nd.sum(out * out)
        loss.backward()
        outs.append(out.asnumpy())
        grads.append(net[0].weight.grad().asnumpy())
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-6)


# -- Mixture of Experts + expert parallelism -----------------------------------
# (SURVEY §2.5 ep slot; design follows public Switch/GShard recipe)

def test_moe_ffn_top1_matches_dense_oracle():
    """With capacity ≥ tokens, top-1 MoE == per-token expert FFN chosen
    by argmax of the router."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.moe import moe_ffn

    rs = np.random.RandomState(0)
    n, m, f, e = 12, 8, 16, 4
    x = jnp.asarray(rs.randn(n, m).astype(np.float32))
    gw = jnp.asarray(rs.randn(e, m).astype(np.float32))
    w1 = jnp.asarray(rs.randn(e, m, f).astype(np.float32) * 0.1)
    b1 = jnp.asarray(rs.randn(e, f).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rs.randn(e, f, m).astype(np.float32) * 0.1)
    b2 = jnp.asarray(rs.randn(e, m).astype(np.float32) * 0.1)

    y = np.asarray(moe_ffn(x, gw, w1, b1, w2, b2, num_experts=e, k=1,
                           capacity_factor=float(n)))  # no overflow
    # numpy oracle
    logits = np.asarray(x) @ np.asarray(gw).T
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    idx = probs.argmax(1)
    expect = np.zeros((n, m), np.float32)
    for t in range(n):
        ei = idx[t]
        h = np.maximum(np.asarray(x)[t] @ np.asarray(w1)[ei]
                       + np.asarray(b1)[ei], 0)
        expect[t] = probs[t, ei] * (h @ np.asarray(w2)[ei]
                                    + np.asarray(b2)[ei])
    np.testing.assert_allclose(y, expect, atol=1e-4)


def test_moe_ffn_top2_matches_dense_oracle():
    """With capacity ≥ tokens, GShard top-2 MoE == renormalized sum of
    the two argmax experts' FFNs (regression: round-2 capacity slots
    must not collide with round-1 slots)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.moe import moe_ffn

    rs = np.random.RandomState(3)
    n, m, f, e = 16, 8, 16, 4
    x = jnp.asarray(rs.randn(n, m).astype(np.float32))
    gw = jnp.asarray(rs.randn(e, m).astype(np.float32))
    w1 = jnp.asarray(rs.randn(e, m, f).astype(np.float32) * 0.1)
    b1 = jnp.asarray(rs.randn(e, f).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rs.randn(e, f, m).astype(np.float32) * 0.1)
    b2 = jnp.asarray(rs.randn(e, m).astype(np.float32) * 0.1)

    y = np.asarray(moe_ffn(x, gw, w1, b1, w2, b2, num_experts=e, k=2,
                           capacity_factor=float(n)))  # no overflow
    logits = np.asarray(x) @ np.asarray(gw).T
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    expect = np.zeros((n, m), np.float32)
    for t in range(n):
        order = np.argsort(-probs[t])
        e1, e2 = order[0], order[1]
        acc = np.zeros(m, np.float32)
        for ei, p in ((e1, probs[t, e1]), (e2, probs[t, e2])):
            h = np.maximum(np.asarray(x)[t] @ np.asarray(w1)[ei]
                           + np.asarray(b1)[ei], 0)
            acc += p * (h @ np.asarray(w2)[ei] + np.asarray(b2)[ei])
        expect[t] = acc / (probs[t, e1] + probs[t, e2])
    np.testing.assert_allclose(y, expect, atol=1e-4)


def test_moe_ffn_top2_slots_do_not_collide():
    """Force every token's 1st pick to expert 0 and 2nd to expert 1:
    expert 1's queue must start at slot len(kept-in-0) — with the
    pre-fix maximum-merge, slot 0 of expert 0 held two tokens' sum."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.moe import moe_ffn

    n, m, e = 4, 4, 2
    x = jnp.asarray(np.eye(n, m, dtype=np.float32))
    gw = jnp.asarray(np.array([[3.0] * m, [1.0] * m], np.float32))
    # identity-ish experts so the output is attributable per token
    w1 = jnp.stack([jnp.eye(m), 2 * jnp.eye(m)]).astype(jnp.float32)
    b1 = jnp.zeros((e, m), jnp.float32)
    w2 = jnp.stack([jnp.eye(m), jnp.eye(m)]).astype(jnp.float32)
    b2 = jnp.zeros((e, m), jnp.float32)
    # capacity_factor 2.0 with e=2, n=4 -> capacity 4: both rounds fit
    y = np.asarray(moe_ffn(x, gw, w1, b1, w2, b2, num_experts=e, k=2,
                           capacity_factor=2.0))
    # oracle: every token routes (p0, p1) to experts (id, 2·id);
    # renormalized combine -> y_t = (p0·x_t + p1·2·x_t)/(p0+p1)
    logits = np.asarray(x) @ np.asarray(gw).T
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    scale = (probs[:, 0] + 2 * probs[:, 1]) / (probs[:, 0] + probs[:, 1])
    expect = np.asarray(x) * scale[:, None]
    np.testing.assert_allclose(y, expect, atol=1e-5)


def test_moe_ffn_capacity_drops_overflow():
    """Tokens beyond an expert's capacity combine to zero (pass-through
    slot for the residual), Switch semantics."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.moe import moe_ffn

    n, m, e = 8, 4, 2
    # router forces every token onto expert 0
    x = jnp.ones((n, m), jnp.float32)
    gw = jnp.asarray(np.array([[5.0] * m, [-5.0] * m], np.float32))
    w1 = jnp.ones((e, m, 4), jnp.float32)
    b1 = jnp.zeros((e, 4), jnp.float32)
    w2 = jnp.ones((e, 4, m), jnp.float32)
    b2 = jnp.zeros((e, m), jnp.float32)
    # capacity_factor 1.0 -> capacity ceil(8/2)=4: only 4 tokens served
    y = np.asarray(moe_ffn(x, gw, w1, b1, w2, b2, num_experts=e, k=1,
                           capacity_factor=1.0))
    served = (np.abs(y).sum(axis=1) > 0).sum()
    assert served == 4, served


def test_moe_gluon_layer_trains_and_balances():
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.contrib import MoEFFN

    rs = np.random.RandomState(1)
    layer = MoEFFN(units=8, hidden=16, num_experts=4, k=2,
                   capacity_factor=2.0)
    layer.initialize(init=mx.init.Xavier())
    x = nd.array(rs.randn(16, 8).astype("float32"))
    x.attach_grad()
    with autograd.record():
        y = layer(x)
        loss = (y * y).sum()
    loss.backward()
    assert y.shape == x.shape
    assert float(np.abs(x.grad.asnumpy()).sum()) > 0
    g = layer.expert_w1.grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0
    # aux loss populated and >= 1 (1.0 == perfectly balanced)
    assert layer.aux_loss is not None
    assert float(nd.array(layer.aux_loss).asnumpy()) >= 0.99


def test_moe_expert_parallel_step_matches_single_device():
    """dp×ep sharded whole-step training == unsharded training (GSPMD
    collectives must not change the math)."""
    import jax

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.contrib import MoEFFN

    rs = np.random.RandomState(2)
    x = rs.randn(16, 8).astype("float32")
    y = rs.randn(16, 8).astype("float32")

    def build():
        mx.random.seed(7)
        net = gluon.nn.HybridSequential()
        net.add(MoEFFN(units=8, hidden=16, num_experts=4, k=1,
                       capacity_factor=4.0))
        net.initialize(init=mx.init.Xavier())
        net(mx.nd.array(x))  # materialize
        return net

    losses = {}
    for name, mesh, rules in [
            ("single", parallel.make_mesh(dp=1), None),
            ("dp2ep4", parallel.make_mesh(dp=2, ep=4),
             parallel.MOE_EP_RULES)]:
        net = build()
        tr = parallel.ShardedTrainer(
            net, gluon.loss.L2Loss(), "sgd", {"learning_rate": 0.1},
            mesh=mesh, rules=rules)
        ls = [float(np.asarray(tr.step(mx.nd.array(x),
                                       mx.nd.array(y))._data,
                               dtype=np.float32))
              for _ in range(3)]
        losses[name] = ls
    np.testing.assert_allclose(losses["single"], losses["dp2ep4"],
                               rtol=2e-4)


def test_pipeline_1f1b_bert_matches_grad_accum():
    """1F1B with prologue (embedding) + epilogue (MLM head): the oracle
    is unpipelined grad_accum=n_micro training, which has the SAME
    per-microbatch loss normalization (BERTMLMLoss normalizes by each
    microbatch's own masked count — full-batch mean differs, which is
    inherent to microbatching, not to the schedule)."""
    import jax.numpy as jnp

    from mxnet_tpu.gluon.model_zoo import bert

    def build():
        mx.random.seed(11)
        np.random.seed(11)
        embed, layers, head = bert.bert_pipeline_parts(
            vocab_size=64, units=16, num_layers=2, num_heads=2,
            max_length=16, dropout=0.0)
        for b in [embed] + layers + [head]:
            b.initialize(init=mx.init.Xavier())
        return embed, layers, head

    opt, opt_kw = "sgd", {"learning_rate": 0.05, "momentum": 0.9}
    embed, layers, head = build()
    mesh = parallel.make_mesh(pp=2)
    pt = parallel.PipelineTrainer(
        layers, bert.BERTMLMLoss(), opt, opt_kw, mesh=mesh,
        n_microbatches=4, prologue=embed, epilogue=head,
        schedule="1f1b")

    embed2, layers2, head2 = build()
    seq = gluon.nn.HybridSequential(prefix="ref_")
    seq.add(embed2)
    for l in layers2:
        seq.add(l)
    seq.add(head2)
    ref = parallel.ShardedTrainer(
        seq, bert.BERTMLMLoss(), opt, dict(opt_kw),
        mesh=parallel.data_parallel_mesh(1), grad_accum=4)

    rng = np.random.RandomState(2)
    ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
    labels = np.where(rng.rand(8, 16) < 0.2, ids, -1).astype(np.float32)
    for _ in range(3):
        lp = float(pt.step(mx.nd.array(ids),
                           mx.nd.array(labels)).asscalar())
        lr_ = float(ref.step(jnp.asarray(ids),
                             jnp.asarray(labels)).asscalar())
    np.testing.assert_allclose(lp, lr_, rtol=1e-5)
    pt.sync_params()
    ref.sync_params()
    pp_params = {}
    for block in [embed] + layers + [head]:
        pp_params.update(block.collect_params())
    for (n1, p1), (n2, p2) in zip(pp_params.items(),
                                  seq.collect_params().items()):
        np.testing.assert_allclose(p1.data().asnumpy(),
                                   p2.data().asnumpy(), rtol=2e-5,
                                   atol=2e-6, err_msg=f"{n1} vs {n2}")


def test_1f1b_schedule_properties():
    """The generated 1F1B tables respect dataflow ordering and the
    in-flight memory bound (<= S - s per stage, GPipe's is M), and the
    reported bubble matches the idle-slot count."""
    from mxnet_tpu.parallel.pipeline import (_schedule_1f1b,
                                             gpipe_bubble_fraction)

    for S, M in [(2, 4), (4, 8), (4, 4)]:
        rows_f, rows_b, T, bub = _schedule_1f1b(S, M)
        TF, TB = {}, {}
        for t, row in enumerate(rows_f):
            for s, m in enumerate(row):
                if m >= 0:
                    TF[(m, s)] = t
        for t, row in enumerate(rows_b):
            for s, m in enumerate(row):
                if m >= 0:
                    TB[(m, s)] = t
        assert len(TF) == S * M and len(TB) == S * M
        for m in range(M):
            for s in range(1, S):
                assert TF[(m, s)] > TF[(m, s - 1)]
            for s in range(S - 1):
                assert TB[(m, s)] > TB[(m, s + 1)]
            assert TB[(m, S - 1)] > TF[(m, S - 1)]
        for s in range(S):
            events = sorted([(TF[(m, s)], 1) for m in range(M)] +
                            [(TB[(m, s)], -1) for m in range(M)])
            cur = peak = 0
            for _, d in events:
                cur += d
                peak = max(peak, cur)
            assert peak <= S - s
        assert abs(bub - (1.0 - 2.0 * M / T)) < 1e-9
        # non-interleaved 1F1B matches GPipe's bubble; its win is memory
        assert abs(bub - gpipe_bubble_fraction(S, M)) < 0.12


def test_scan_bert_tensor_parallel_sharding():
    """Review regression: scan_layers=True stacks must shard under the
    TP rules (layer dim unsharded, Megatron split on dims 1+), and a
    dp×tp step must run and match dp-only losses."""
    import jax

    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import bert as bz

    mesh = parallel.make_mesh(dp=2, tp=4)
    rules = parallel.TRANSFORMER_TP_RULES
    from jax.sharding import PartitionSpec as P

    assert tuple(rules.spec_for("enc_qkv_stack_weight")) == \
        (None, "tp", None)
    assert tuple(rules.spec_for("enc_proj_stack_weight")) == \
        (None, None, "tp")
    assert tuple(rules.spec_for("enc_ffn2_stack_weight")) == \
        (None, None, "tp")

    def run(mesh, rules):
        mx.random.seed(3)
        net = bz.bert_tiny(dropout=0.0, scan_layers=True, max_length=16)
        net.initialize(init=mx.init.Xavier())
        tr = parallel.ShardedTrainer(
            net, bz.BERTPretrainLoss(), "adamw",
            {"learning_rate": 1e-3}, mesh=mesh, rules=rules)
        rs = np.random.RandomState(0)
        ids = mx.nd.array(rs.randint(0, 512, (8, 16)).astype("int32"))
        mlm = np.where(rs.rand(8, 16) < 0.2,
                       rs.randint(0, 512, (8, 16)), -1).astype("int32")
        nsp = rs.randint(0, 2, (8,)).astype("int32")
        return [float(np.asarray(
            tr.step(ids, (mx.nd.array(mlm), mx.nd.array(nsp)))._data,
            dtype=np.float32)) for _ in range(2)]

    l_tp = run(mesh, rules)
    l_dp = run(parallel.make_mesh(dp=2), None)
    np.testing.assert_allclose(l_tp, l_dp, rtol=2e-4)


def test_ulysses_flash_differentiable(qkv):
    """Ulysses now runs the streaming flash kernel after the all-to-all
    (round-4: same no-dense-scores property as ring); gradients must
    still match the dense oracle."""
    q, k, v = qkv
    mesh = parallel.make_mesh(sp=8)

    def loss_u(q):
        return jnp.sum(parallel.ulysses_attention(
            q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_d(q):
        return jnp.sum(scaled_dot_product_attention(
            q, k, v, causal=True) ** 2)

    g_u = jax.grad(loss_u)(q)
    g_d = jax.grad(loss_d)(q)
    np.testing.assert_allclose(np.asarray(g_d), np.asarray(g_u),
                               rtol=2e-3, atol=2e-4)


def test_bert_ring_attention_sharded_training():
    """The long-context FLAGSHIP config: BERT with ring attention inside
    the jitted ShardedTrainer whole-step over a dp×sp mesh — flash-ring
    blocks, GSPMD dp gradients and the sp ring compose in ONE compiled
    program and the loss decreases."""
    from mxnet_tpu.gluon.model_zoo import bert

    mesh = parallel.make_mesh(dp=2, sp=4)
    parallel.set_default_mesh(mesh)
    try:
        net = bert.bert_tiny(attention_impl="ring", use_decoder=False,
                             use_pooler=False)
        net.initialize(init=mx.init.Xavier())
        tr = parallel.ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                                     {"learning_rate": 1e-3}, mesh=mesh)
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 100, (4, 64)).astype(np.int32)
        tgt = rs.randn(4, 64, 64).astype(np.float32)
        losses = [float(np.asarray(
            tr.step(mx.nd.array(ids), mx.nd.array(tgt))._data,
            dtype=np.float32)) for _ in range(3)]
        assert losses[-1] < losses[0], losses
    finally:
        parallel.set_default_mesh(None)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_gpt_matches_grad_accum(schedule):
    """The decoder-only family pipelines under BOTH schedules: causal
    trunk stages + embedding prologue + LM head epilogue vs the
    unpipelined grad_accum oracle."""
    from mxnet_tpu.gluon.model_zoo import gpt

    def build():
        mx.random.seed(13)
        np.random.seed(13)
        embed, layers, head = gpt.gpt_pipeline_parts(
            vocab_size=64, units=16, num_layers=2, num_heads=2,
            max_length=16, dropout=0.0)
        for b in [embed] + layers + [head]:
            b.initialize(init=mx.init.Xavier())
        return embed, layers, head

    opt, opt_kw = "sgd", {"learning_rate": 0.05, "momentum": 0.9}
    embed, layers, head = build()
    mesh = parallel.make_mesh(pp=2)
    pt = parallel.PipelineTrainer(
        layers, gpt.GPTLMLoss(), opt, opt_kw, mesh=mesh,
        n_microbatches=4, prologue=embed, epilogue=head,
        schedule=schedule)

    embed2, layers2, head2 = build()
    seq = gluon.nn.HybridSequential(prefix="gptref_")
    seq.add(embed2)
    for l in layers2:
        seq.add(l)
    seq.add(head2)
    ref = parallel.ShardedTrainer(
        seq, gpt.GPTLMLoss(), opt, dict(opt_kw),
        mesh=parallel.data_parallel_mesh(1), grad_accum=4)

    rng = np.random.RandomState(4)
    ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
    for _ in range(3):
        lp = float(pt.step(mx.nd.array(ids),
                           mx.nd.array(ids)).asscalar())
        lr_ = float(ref.step(jnp.asarray(ids),
                             jnp.asarray(ids)).asscalar())
    np.testing.assert_allclose(lp, lr_, rtol=1e-5)
    pt.sync_params()
    ref.sync_params()
    pp_params = {}
    for block in [embed] + layers + [head]:
        pp_params.update(block.collect_params())
    for (n1, p1), (n2, p2) in zip(pp_params.items(),
                                  seq.collect_params().items()):
        np.testing.assert_allclose(p1.data().asnumpy(),
                                   p2.data().asnumpy(), rtol=2e-5,
                                   atol=2e-6, err_msg=f"{n1} vs {n2}")
