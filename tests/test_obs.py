"""Fleet observability plane (ISSUE 14): telemetry schema v3 identity
stamping, event-log rotation (size cap + torn-rotation crash safety),
O(new lines) incremental tailing, distributed request spans through the
serving path, the per-host collector + FleetView rollup aggregation,
the Prometheus exporter, on-demand profile capture, and the
tools/fleet_report.py consumer."""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from mxnet_tpu import distributed, obs, resilience, telemetry
from mxnet_tpu.obs.collector import (FleetView, HostCollector,
                                     request_profile)
from mxnet_tpu.obs.exporter import MetricsExporter, render_prometheus
from mxnet_tpu.obs.spans import Trace, render_tree

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "mxnet_tpu")
_FLEET_REPORT = os.path.join(_REPO, "tools", "fleet_report.py")
_OBS_WORKER = os.path.join(_REPO, "tests", "obs_fleet_worker.py")


def _clean_env(**extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_",
                                "LIBTPU", "MXTPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


@pytest.fixture(autouse=True)
def _obs_clean(monkeypatch):
    """Each test starts with no sink, no identity, no cached tails, and
    nothing the bootstrap may have started."""
    for var in ("MXTPU_TELEMETRY_PATH", "MXTPU_TELEMETRY",
                "MXTPU_TELEMETRY_MAX_MB", "MXTPU_WORKER_RANK",
                "MXTPU_NUM_WORKERS", "MXTPU_METRICS_PORT",
                "MXTPU_OBS_COLLECTOR", "MXTPU_GANG_DIR"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    telemetry.REGISTRY.reset()
    yield
    obs.shutdown()
    telemetry.reset()
    telemetry.REGISTRY.reset()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _emit_step(step=0, **over):
    """One synthetic-but-schema-valid step record via the real
    assembly path (step_begin/step_end)."""
    acc = telemetry.step_begin(path="captured")
    telemetry.on_scope("captured_step", 0.001)
    telemetry.note(flops=over.pop("flops", 1e9))
    return telemetry.step_end(acc, step=step, **over)


# -- schema v3: fleet identity -------------------------------------------------

def test_identity_from_env(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    monkeypatch.setenv("MXTPU_WORKER_RANK", "3")
    monkeypatch.setenv("MXTPU_NUM_WORKERS", "8")
    telemetry.reset()       # drop the cached (empty) identity
    telemetry.event("resume", step=1)
    _emit_step(step=1)
    for rec in _read_jsonl(path):
        assert rec["rank"] == 3 and rec["world"] == 8
        assert rec["v"] == telemetry.SCHEMA_VERSION == 8
        telemetry.validate_record(rec)


def test_set_identity_merges_and_explicit_fields_win(tmp_path,
                                                     monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.set_identity(rank=0, world=4)
    telemetry.set_identity(replica_id=2)            # merge, not replace
    assert telemetry.identity() == {"rank": 0, "world": 4,
                                    "replica_id": 2}
    # an event that NAMES a rank (straggler suspicion) must keep it:
    # identity is stamped with setdefault, never overwrites
    telemetry.event("straggler_suspected", rank=1, step=5,
                    mean_collective_share=0.8)
    rec = _read_jsonl(path)[-1]
    assert rec["rank"] == 1 and rec["world"] == 4
    assert rec["replica_id"] == 2
    telemetry.validate_record(rec)


def test_older_schema_versions_still_validate():
    base = {"type": "event", "event": "resume", "run": "r", "t": 1.0}
    for v in (1, 2, 3, 4, 5, 6, 7, 8):
        telemetry.validate_record(dict(base, v=v))
    with pytest.raises(ValueError, match="schema version"):
        telemetry.validate_record(dict(base, v=9))
    with pytest.raises(ValueError, match="rank"):
        telemetry.validate_record(dict(base, v=3, rank="zero"))
    with pytest.raises(ValueError, match="world"):
        telemetry.validate_record(dict(base, v=3, world=0))


def test_span_field_validation():
    req = {"type": "request", "v": 3, "run": "r", "t": 1.0,
           "queue_us": 1.0, "prefill_us": 2.0,
           "decode_us_per_token": 3.0, "bucket": [1, 8],
           "padded_fraction": 0.0}
    root = {"span_id": "a", "parent": None, "name": "frontdoor",
            "t0": 1.0, "dur_us": 10.0}
    kid = {"span_id": "b", "parent": "a", "name": "batcher",
           "t0": 1.0, "dur_us": 5.0}
    telemetry.validate_record(
        dict(req, trace_id="t1", spans=[root, kid]))
    for bad, msg in (
            ([kid], "root"),                          # no root
            ([root, dict(kid, parent=None)], "root"),  # two roots
            ([root, dict(kid, dur_us=None)], "dur_us"),
            ([root, dict(kid, parent="zz")], "parent"),
            ([root, dict(kid, span_id="a")], "duplicate"),
            ([], "empty")):
        with pytest.raises(ValueError, match=msg):
            telemetry.validate_record(
                dict(req, trace_id="t1", spans=bad))


# -- S1: size-capped rotation --------------------------------------------------

def test_rotation_size_cap_no_record_loss(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    monkeypatch.setenv("MXTPU_TELEMETRY_MAX_MB", "0.01")   # 10 kB
    telemetry.reset()
    n = 120                                  # ~110 B/line: one rotation
    for i in range(n):
        telemetry.event("resume", step=i)
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path + ".2")
    recs = _read_jsonl(path + ".1") + _read_jsonl(path)
    assert [r["step"] for r in recs] == list(range(n))
    assert os.path.getsize(path) <= 10000


def test_torn_rotation_crash_is_recoverable(tmp_path):
    """telemetry_rotate kills the process BETWEEN the rename and the
    reopen; the rotated file must hold every record emitted so far and
    the readers must see them all."""
    path = str(tmp_path / "t.jsonl")
    prog = ("import mxnet_tpu.telemetry as t\n"
            "for i in range(200):\n"
            "    t.event('resume', step=i)\n")
    proc = subprocess.run(
        [sys.executable, "-c", prog],
        env=_clean_env(MXTPU_TELEMETRY_PATH=path,
                       MXTPU_TELEMETRY_MAX_MB="0.003",
                       MXTPU_FAULT_INJECT="telemetry_rotate:1"),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == resilience.CRASH_EXIT_CODE, proc.stderr
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path)          # torn: died before reopen
    rotated = _read_jsonl(path + ".1")
    steps = [r["step"] for r in rotated]
    assert steps == list(range(len(steps))) and steps  # no loss, no tear
    # both readers recover across the torn boundary
    assert [r["step"] for r in telemetry.tail_records(path)] == steps
    out = subprocess.run(
        [sys.executable, _FLEET_REPORT, path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert f"{len(steps)} records" in out.stdout


# -- S2: incremental tail with a bytes-read pin --------------------------------

def test_tail_is_o_new_lines(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    for i in range(50):
        telemetry.event("resume", step=i)
    size0 = os.path.getsize(path)
    assert len(telemetry.tail_records(path)) == 50
    assert telemetry.tail_bytes_read() == size0
    # steady state: re-reading an unchanged log costs ZERO bytes
    assert telemetry.tail_records(path) == []
    assert telemetry.tail_bytes_read() == size0
    # two appended records cost exactly their own bytes
    telemetry.event("resume", step=50)
    telemetry.event("resume", step=51)
    new_bytes = os.path.getsize(path) - size0
    got = telemetry.tail_records(path)
    assert [r["step"] for r in got] == [50, 51]
    assert telemetry.tail_bytes_read() == size0 + new_bytes
    # recent_steps(jsonl=...) rides the same offset machinery
    _emit_step(step=52)
    steps = telemetry.recent_steps(jsonl=path)
    assert steps and steps[-1]["step"] == 52


def test_tail_survives_rotation(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    monkeypatch.setenv("MXTPU_TELEMETRY_MAX_MB", "0.002")  # ~13 lines
    telemetry.reset()
    seen = []
    for i in range(60):
        telemetry.event("resume", step=i)
        seen.extend(r["step"] for r in telemetry.tail_records(path))
    seen.extend(r["step"] for r in telemetry.tail_records(path))
    assert seen == list(range(60))           # nothing lost, nothing twice


def test_half_flushed_line_is_not_consumed(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write('{"type": "event", "event": "resume", "step": 0}\n')
        f.write('{"type": "event", "ev')            # torn tail
    recs = telemetry.tail_records(path)
    assert [r["step"] for r in recs] == [0]
    with open(path, "a") as f:                      # flush completes
        f.write('ent": "resume", "step": 1}\n')
    assert [r["step"] for r in telemetry.tail_records(path)] == [1]


# -- S3: every event emitter in the repo produces a valid record ---------------

def test_every_event_kind_in_repo_validates(tmp_path, monkeypatch):
    pat = re.compile(
        r"(?:telemetry\.event|_tel_event)\(\s*[\"']([a-z0-9_]+)[\"']")
    kinds = set()
    for root, _dirs, files in os.walk(_PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                kinds.update(pat.findall(f.read()))
    assert len(kinds) >= 15, f"emitter inventory shrank: {sorted(kinds)}"
    for probe in ("mesh_reshape", "straggler_suspected",
                  "profile_captured", "serving_reload", "resume"):
        assert probe in kinds
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    for kind in sorted(kinds):
        telemetry.event(kind, step=1, rank=0)
    recs = _read_jsonl(path)
    assert {r["event"] for r in recs} == kinds
    for rec in recs:
        telemetry.validate_record(rec)


# -- spans ---------------------------------------------------------------------

def test_trace_span_tree_lifecycle():
    tr = Trace()
    root = tr.begin("frontdoor", t0=100.0)
    child = tr.begin("batcher", parent=root, t0=100.1, replica_id=3)
    tr.begin("queue", parent=child, t0=100.1).close(dur_us=50.0)
    assert not tr.closed()                   # root + batcher still open
    child.close(dur_us=200.0)
    tr.close_open(t_end=100.2)
    assert tr.closed()
    fields = tr.to_fields()
    assert fields["trace_id"] == tr.trace_id
    assert len(fields["spans"]) == 3
    lines = render_tree(fields["spans"])
    assert lines[0].startswith("frontdoor")
    assert lines[1].strip().startswith("batcher")
    assert "replica_id=3" in lines[1]
    assert lines[2].strip().startswith("queue")
    # an abandoned open span (shed submit, never served) is dropped
    tr2 = Trace()
    tr2.begin("frontdoor", t0=1.0).close(dur_us=5.0)
    tr2.begin("batcher", parent=tr2.root(), t0=1.0)   # never closed
    assert [s["name"] for s in tr2.to_fields()["spans"]] == ["frontdoor"]


class _FakeEngine:
    """serve_group-compatible stand-in: real batcher/FrontDoor code
    path, no model, no compile — spans and records come out the same
    shape as the real engine's."""

    batch_buckets = (4,)

    def serve_group(self, prompts, max_new_tokens, temperature=None,
                    rng=None):
        now = time.time()
        outs = [list(range(int(m))) for m in max_new_tokens]
        timings = {"bucket": [len(prompts), 8], "generation": 0,
                   "prefill_us": 120.0, "decode_us_per_token": 30.0,
                   "padded_fraction": 0.25, "t_prefill0": now,
                   "t_decode0": now + 1e-4,
                   "decode_us": 30.0 * max(len(o) for o in outs)}
        return outs, timings


def test_request_spans_through_frontdoor(tmp_path, monkeypatch):
    from mxnet_tpu import serving

    path = str(tmp_path / "serve.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    replicas = [serving.ReplicaServer(_FakeEngine(), rank=r)
                for r in (0, 1)]
    door = serving.FrontDoor(replicas)
    try:
        futs = [door.submit(f"p{i}", max_new_tokens=4)
                for i in range(3)]
        for f in futs:
            assert f.result(timeout=30)["tokens"] == [0, 1, 2, 3]
    finally:
        door.close()
    recs = [r for r in _read_jsonl(path) if r["type"] == "request"]
    assert len(recs) == 3
    seen_replicas = set()
    for rec in recs:
        telemetry.validate_record(rec)
        spans = {s["name"]: s for s in rec["spans"]}
        assert set(spans) == {"frontdoor", "batcher", "queue",
                              "prefill", "decode"}
        assert spans["frontdoor"]["parent"] is None
        assert spans["batcher"]["parent"] == spans["frontdoor"]["span_id"]
        for leaf in ("queue", "prefill", "decode"):
            assert spans[leaf]["parent"] == spans["batcher"]["span_id"]
        assert all(s["dur_us"] >= 0 for s in rec["spans"])
        assert spans["decode"]["attrs"]["new_tokens"] == 4
        assert rec["replica_id"] == spans["batcher"]["attrs"]["replica_id"]
        seen_replicas.add(rec["replica_id"])
    assert seen_replicas <= {0, 1}


def test_direct_batcher_submit_roots_at_batcher(tmp_path, monkeypatch):
    from mxnet_tpu.serving.batcher import ContinuousBatcher

    path = str(tmp_path / "serve.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    b = ContinuousBatcher(_FakeEngine(), max_delay_ms=0.0)
    try:
        b.submit("p", max_new_tokens=2).result(timeout=30)
    finally:
        b.close()
    rec = [r for r in _read_jsonl(path) if r["type"] == "request"][0]
    telemetry.validate_record(rec)
    roots = [s for s in rec["spans"] if s["parent"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "batcher"


# -- collector + FleetView -----------------------------------------------------

def _seed_rank_log(tmp_path, rank, interval_us, mfu, shares, events=()):
    path = str(tmp_path / f"rank{rank}.jsonl")
    with open(path, "w") as f:
        for i in range(10):
            f.write(json.dumps({
                "type": "step", "step": i, "interval_us": interval_us,
                "wall_us": interval_us * 0.9, "mfu": mfu,
                "shares": shares}) + "\n")
        for e in events:
            f.write(json.dumps(dict(
                {"type": "event", "t": time.time()}, **e)) + "\n")
    return path


def test_collector_rollup_and_fleet_view(tmp_path):
    kv = distributed.FileKV(str(tmp_path / "kv"))
    fast = {"data": 0.05, "host_prep": 0.05, "dispatch": 0.1,
            "readback": 0.0, "collective": 0.7, "other": 0.1}
    slow = {"data": 0.05, "host_prep": 0.05, "dispatch": 0.1,
            "readback": 0.0, "collective": 0.05, "other": 0.75}
    logs = {
        0: _seed_rank_log(tmp_path, 0, 1000.0, 0.30, fast, events=[
            {"event": "straggler_suspected", "rank": 1,
             "mean_collective_share": 0.8, "step": 7},
            {"event": "mesh_reshape", "epoch": 1, "world": 3}]),
        1: _seed_rank_log(tmp_path, 1, 2000.0, 0.15, slow),
        2: _seed_rank_log(tmp_path, 2, 1000.0, 0.30, fast),
    }
    for rank, path in logs.items():
        c = HostCollector(path=path, kv=kv, rank=rank, world=3)
        c.poll_once()
        roll = c.rollup()
        assert roll["steps_total"] == roll["steps_window"] == 10
        assert roll["interval_us_mean"] == pytest.approx(
            1000.0 if rank != 1 else 2000.0)
    view = FleetView(kv)
    view.refresh()
    s = view.summary()
    assert s["ranks"] == [0, 1, 2] and s["world"] == 3
    assert s["steps_total"] == 30
    assert s["fleet_mfu"] == pytest.approx(0.25)      # (0.3+0.15+0.3)/3
    assert s["slowest_rank"] == 1
    assert s["interval_skew"] == pytest.approx(2.0)
    (straggler,) = s["stragglers"]
    assert straggler["rank"] == 1 and straggler["suspected_by"] == 0
    assert straggler["stall_bucket"] == "other"
    assert straggler["stall_share"] == pytest.approx(0.75)
    assert straggler["slowdown_vs_median"] == pytest.approx(2.0)
    kinds = [e["event"] for e in s["timeline"]]
    assert "mesh_reshape" in kinds and "straggler_suspected" in kinds


def test_collector_thread_stays_off_train_thread(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    kv = distributed.FileKV(str(tmp_path / "kv"))
    c = HostCollector(path=path, kv=kv, rank=0, world=1,
                      period_s=0.05).start()
    try:
        main_tid = threading.get_ident()
        assert c._thread.ident != main_tid
        for i in range(5):
            telemetry.event("resume", step=i)
        deadline = time.monotonic() + 10
        roll = None
        while time.monotonic() < deadline:
            roll = kv.get_json("obs/rollup/0")
            if roll is not None:
                break
            time.sleep(0.02)
        assert roll is not None and roll["rank"] == 0
    finally:
        c.close()


# -- on-demand profiling -------------------------------------------------------

def test_profile_request_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_PROFILE_BUDGET_S", "5.0")
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    with open(path, "w") as f:
        f.write(json.dumps({"type": "step", "step": 0}) + "\n")
    kv = distributed.FileKV(str(tmp_path / "kv"))
    c = HostCollector(path=path, kv=kv, rank=0, world=1,
                      hlo_provider=lambda: "HloModule step")
    c.poll_once()                            # folds step 0, no request
    logdir = str(tmp_path / "prof")
    req_id = request_profile(kv, 0, steps=1, logdir=logdir)
    # a step landing mid-capture releases the bounded wait
    t = threading.Timer(0.2, lambda: open(path, "a").write(
        json.dumps({"type": "step", "step": 1}) + "\n"))
    t.start()
    try:
        c.poll_once()
    finally:
        t.cancel()
    assert c.profiles_captured == 1
    done = kv.get_json("profile/done/0")
    assert done["id"] == req_id and done["steps"] >= 1
    assert kv.get_json("profile/req") is None          # consumed
    with open(os.path.join(logdir, "step_hlo.txt")) as f:
        assert "HloModule" in f.read()
    events = [r for r in _read_jsonl(path)
              if r.get("event") == "profile_captured"]
    assert len(events) == 1 and events[0]["rank"] == 0
    assert events[0]["hlo"] is True
    telemetry.validate_record(events[0])
    c.poll_once()                            # no re-trigger: req gone
    assert c.profiles_captured == 1


def test_profile_request_ignored_for_other_rank(tmp_path):
    kv = distributed.FileKV(str(tmp_path / "kv"))
    c = HostCollector(path=None, kv=kv, rank=0, world=2)
    request_profile(kv, 1, steps=1)
    c.poll_once()
    assert c.profiles_captured == 0
    assert kv.get_json("profile/req")["rank"] == 1     # left for rank 1


# -- S5: exporter scrape + fleet_report CLI ------------------------------------

_METRIC_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+="[^"]*"'
    r'(,[a-zA-Z0-9_]+="[^"]*")*\})? [-+]?[0-9.eE+-]+$')
_META_LINE = re.compile(r"^# (TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                        r"(counter|gauge)|HELP .+)$")


def test_exporter_scrapes_prometheus_text(tmp_path):
    telemetry.REGISTRY.counter("collective.bytes").inc(4096)
    telemetry.REGISTRY.gauge("input.queue_depth").set(3)
    h = telemetry.REGISTRY.histogram("serve.queue_us")
    for v in (10.0, 30.0):
        h.observe(v)
    kv = distributed.FileKV(str(tmp_path / "kv"))
    kv.put_json("obs/rollup/0", {
        "rank": 0, "world": 2, "t": time.time(), "run": "r",
        "steps_total": 10, "steps_window": 10, "skipped_total": 0,
        "last_step": 9, "interval_us_mean": 1000.0,
        "wall_us_mean": 900.0, "mfu_mean": 0.25, "shares": {},
        "requests_total": 0, "request_queue_us_mean": None,
        "events": []})
    exporter = MetricsExporter(port=0,
                               fleet=FleetView(kv))  # ephemeral port
    try:
        url = f"http://127.0.0.1:{exporter.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        lines = [ln for ln in body.splitlines() if ln]
        assert lines, body
        for ln in lines:                     # the full line grammar
            assert _METRIC_LINE.match(ln) or _META_LINE.match(ln), ln
        assert "mxtpu_collective_bytes 4096" in body
        assert "# TYPE mxtpu_collective_bytes counter" in body
        assert "mxtpu_input_queue_depth 3" in body
        assert "mxtpu_serve_queue_us_count 2" in body
        assert "mxtpu_serve_queue_us_sum 40" in body
        assert "mxtpu_fleet_mfu 0.25" in body
        assert 'mxtpu_fleet_rank_interval_us{rank="0"} 1000' in body
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/other", timeout=10)
        assert exc.value.code == 404
    finally:
        exporter.close()


def test_ensure_from_env_bootstrap(tmp_path, monkeypatch):
    # no env: a no-op
    assert obs.ensure_from_env() == (None, None)
    obs.shutdown()
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    monkeypatch.setenv("MXTPU_METRICS_PORT", "0")
    telemetry.reset()
    collector, exporter = obs.ensure_from_env()
    try:
        assert collector is not None and exporter is not None
        # idempotent: the Trainer may construct many times
        assert obs.ensure_from_env() == (collector, exporter)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics",
                timeout=10) as resp:
            assert resp.status == 200
    finally:
        obs.shutdown()


def _valid_rank_log(tmp_path, monkeypatch, rank, name=None,
                    interval_s=0.001, events=(), requests=0):
    """Write a fully schema-valid per-rank JSONL through the real
    telemetry pipeline."""
    path = str(tmp_path / (name or f"rank{rank}.jsonl"))
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    monkeypatch.setenv("MXTPU_PEAK_FLOPS", "1e12")
    telemetry.reset()
    telemetry.set_identity(rank=rank, world=3)
    for i in range(4):
        acc = telemetry.step_begin(path="captured")
        time.sleep(interval_s)
        telemetry.on_scope("captured_step", interval_s)
        telemetry.note(flops=1e9)
        telemetry.step_end(acc, step=i)
    for e in events:
        telemetry.event(e.pop("event"), **e)
    for _ in range(requests):
        tr = Trace()
        tr.begin("frontdoor", t0=time.time()).close(dur_us=500.0)
        tr.begin("batcher", parent=tr.root(), t0=time.time(),
                 replica_id=0).close(dur_us=400.0)
        telemetry.request_record(
            queue_us=100.0, prefill_us=200.0, decode_us_per_token=50.0,
            bucket=[1, 8], padded_fraction=0.0, new_tokens=4,
            generation=0, replica_id=0, **tr.to_fields())
    telemetry.reset()
    return path


def test_fleet_report_cli_on_three_rank_logs(tmp_path, monkeypatch):
    logdir = tmp_path / "logs"
    logdir.mkdir()
    _valid_rank_log(logdir, monkeypatch, 0, events=[
        {"event": "straggler_suspected", "rank": 1, "step": 3,
         "mean_collective_share": 0.8},
        {"event": "mesh_reshape", "epoch": 1, "world": 3,
         "members": [0, 1, 2]}])
    _valid_rank_log(logdir, monkeypatch, 1, interval_s=0.004)
    _valid_rank_log(logdir, monkeypatch, 2, requests=2)
    monkeypatch.delenv("MXTPU_TELEMETRY_PATH")
    proc = subprocess.run(
        [sys.executable, _FLEET_REPORT, str(logdir), "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    out = proc.stdout
    assert "validate (schema + span completeness)" in out
    assert "fleet: 3 rank(s), world 3" in out
    assert "fleet mfu (step-weighted):" in out
    assert "straggler: rank 1 suspected" in out
    assert "mesh_reshape" in out
    assert "frontdoor" in out and "batcher" in out
    assert re.search(r"step-time skew: \d+\.\d+x \(slowest rank 1",
                     out)


def test_fleet_report_validate_catches_broken_spans(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    rec = {"type": "request", "v": 3, "run": "r", "t": 1.0,
           "queue_us": 1.0, "prefill_us": 2.0,
           "decode_us_per_token": 3.0, "bucket": [1, 8],
           "padded_fraction": 0.0, "trace_id": "t1",
           "spans": [{"span_id": "a", "parent": "missing",
                      "name": "batcher", "t0": 1.0, "dur_us": None}]}
    with open(path, "w") as f:
        f.write(json.dumps(rec) + "\n")
    proc = subprocess.run(
        [sys.executable, _FLEET_REPORT, path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "violation" in proc.stderr


# -- the acceptance run: 3-rank elastic fleet + serving, one report ------------

@pytest.mark.slow
def test_fleet_observability_end_to_end(tmp_path, monkeypatch):
    """ISSUE 14 acceptance: a 3-rank elastic run with an injected
    slow_rank and a mid-run silent death, plus a 2-replica serving run,
    merge through tools/fleet_report.py into ONE fleet view: fleet MFU,
    the slow rank named with its stall share, the reshape timeline, and
    a complete FrontDoor→batcher→prefill/decode span tree."""
    work = tmp_path / "fleet"
    work.mkdir()
    gang_dir = work / "kv"
    gang_dir.mkdir()
    num_steps = 18
    base = dict(
        MXTPU_NUM_WORKERS="3",
        MXTPU_GANG_DIR=str(gang_dir),
        MXTPU_HEARTBEAT_INTERVAL="0.05",
        MXTPU_HEARTBEAT_TIMEOUT="1.5",
        MXTPU_STRAGGLER_WINDOW="4",
        MXTPU_STRAGGLER_SHARE="0.3",
        MXTPU_PEAK_FLOPS="1e12",
        MXTPU_OBS_ROLLUP_SECS="0.15",
        PYTHONUNBUFFERED="1",
    )
    per_rank = {
        0: {},
        1: {"MXTPU_FAULT_INJECT": "slow_rank:1",
            "MXTPU_SLOW_RANK_SECS": "0.25"},
        2: {"MXTPU_OBS_EXIT_RANK": "2", "MXTPU_OBS_EXIT_STEP": "12"},
    }
    procs = {}
    for rank in (0, 1, 2):
        env = _clean_env(**base, **per_rank[rank],
                         MXTPU_WORKER_RANK=str(rank),
                         MXTPU_TELEMETRY_PATH=str(
                             work / f"rank{rank}.jsonl"))
        procs[rank] = subprocess.Popen(
            [sys.executable, _OBS_WORKER, str(work), str(num_steps),
             "20"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
    outs = {r: p.communicate(timeout=300) for r, p in procs.items()}
    results = {}
    for rank in (0, 1):
        stdout, stderr = outs[rank]
        assert procs[rank].returncode == 0, (rank, stdout, stderr)
        for line in stdout.splitlines():
            if line.startswith("RESULT "):
                results[rank] = json.loads(line[len("RESULT "):])
    assert set(results) == {0, 1}
    for rank, res in results.items():
        assert res["final_step"] == num_steps
        assert res["members"] == [0, 1]      # rank 2's death adopted
        assert res["reshapes"] >= 1

    # serving half: two replicas behind one FrontDoor, real span path
    serve_log = str(work / "serving.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", serve_log)
    telemetry.reset()
    from mxnet_tpu import serving

    replicas = [serving.ReplicaServer(_FakeEngine(), rank=r)
                for r in (0, 1)]
    door = serving.FrontDoor(replicas)
    try:
        futs = [door.submit(f"prompt {i}", max_new_tokens=4)
                for i in range(4)]
        for f in futs:
            f.result(timeout=60)
    finally:
        door.close()
    monkeypatch.delenv("MXTPU_TELEMETRY_PATH")
    telemetry.reset()

    proc = subprocess.run(
        [sys.executable, _FLEET_REPORT, str(work), "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    out = proc.stdout
    assert "fleet mfu (step-weighted):" in out
    # the injected slow rank is named, with its own stall attribution
    assert re.search(r"straggler: rank 1 suspected.*its own time:",
                     out, re.S)
    # the reshape after rank 2's silent death is on the timeline
    assert "mesh_reshape" in out and "rank_dead" in out
    # at least one request renders as a complete causal tree
    assert "frontdoor" in out and "prefill" in out and "decode" in out
