"""Low-latency serving tier (mxnet_tpu/serving/).

The three claims that make the tier production-shaped, each pinned
here: the request path never retraces after warmup (AOT bucketed
programs), a coalesced batch is bitwise equal to the same requests
served one-by-one (padding can never leak into real rows), and hot
reload swaps weights mid-stream with zero dropped requests (weights are
program arguments, not constants).
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo import gpt
from mxnet_tpu.serving.replica import FrontDoor, ReplicaServer


def _model(seed=7, **kwargs):
    kwargs.setdefault("scan_layers", True)
    kwargs.setdefault("max_length", 16)
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gpt.gpt_tiny(**kwargs)
    net.initialize(init=mx.init.Xavier())
    net(mx.nd.array(np.random.randint(0, 128, (1, 4))
                    .astype(np.float32)))
    return net


def _prompts(n, rng, lo=2, hi=8):
    return [rng.randint(0, 128, rng.randint(lo, hi + 1)).tolist()
            for _ in range(n)]


# -- bitwise coalescing parity -------------------------------------------------

def test_coalesced_batch_bitwise_equals_one_by_one():
    net = _model()
    eng = serving.ServingEngine(net, batch_buckets=(4,))
    rng = np.random.RandomState(3)
    prompts = _prompts(3, rng)
    grouped, timings = eng.serve_group(prompts, 5)
    solo = [eng.serve_group([p], 5)[0][0] for p in prompts]
    for i, (a, b) in enumerate(zip(solo, grouped)):
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
    assert timings["bucket"] == [4, 8]
    assert 0 <= timings["padded_fraction"] < 1

    # ground truth: the engine agrees with CachedDecoder's greedy path
    dec = gpt.CachedDecoder(net)
    for p, got in zip(prompts, grouped):
        seed = mx.nd.array(np.asarray([p], np.float32))
        ref = dec.decode(seed, max_new_tokens=5).asnumpy()[0, len(p):]
        np.testing.assert_array_equal(ref.astype(np.int64),
                                      got.astype(np.int64))


def test_per_request_max_new_tokens_truncates():
    eng = serving.ServingEngine(_model(), batch_buckets=(2,))
    rng = np.random.RandomState(5)
    prompts = _prompts(2, rng)
    outs, _ = eng.serve_group(prompts, [2, 6])
    assert len(outs[0]) == 2 and len(outs[1]) == 6
    # the short request's tokens are a prefix of its solo 6-token run
    full = eng.serve_group([prompts[0]], 6)[0][0]
    np.testing.assert_array_equal(outs[0], full[:2])


# -- AOT warmup / retrace pin --------------------------------------------------

def test_zero_retraces_after_warmup_across_all_buckets():
    net = _model()
    eng = serving.ServingEngine(net, batch_buckets=(1, 2, 4))
    eng.warmup()
    # (prefill buckets 8, 16 for W=16) + decode, per batch bucket
    assert eng.program_count() == 3 * 3
    pinned = serving.trace_count()
    d0 = serving.dispatch_count()
    rng = np.random.RandomState(11)
    for n in (1, 2, 3, 4):
        eng.serve_group(_prompts(n, rng), 4)
    eng.serve_group(_prompts(2, rng, lo=9, hi=12), 3)  # 16-bucket
    assert serving.trace_count() == pinned, \
        "request path retraced after warmup"
    assert serving.compile_count() >= 9
    assert serving.dispatch_count() > d0


# -- continuous batcher --------------------------------------------------------

def test_batcher_coalesces_and_emits_request_records():
    telemetry.reset()
    eng = serving.ServingEngine(_model(), batch_buckets=(4,))
    eng.warmup()
    batcher = serving.ContinuousBatcher(eng, max_delay_ms=150,
                                        max_batch=4)
    try:
        rng = np.random.RandomState(2)
        futs = [batcher.submit(p, 3) for p in _prompts(4, rng)]
        recs = [f.result(timeout=120) for f in futs]
    finally:
        batcher.close()
    assert batcher.requests_served == 4
    # all 4 queued within the 150ms deadline → ONE coalesced group
    assert batcher.groups_served == 1
    for rec in recs:
        assert rec["queue_us"] >= 0
        assert len(rec["tokens"]) == 3
        assert rec["bucket"] == [4, 8]
    requests = telemetry.recent_requests()
    assert len(requests) == 4
    for r in requests:
        telemetry.validate_record(r)
        assert r["generation"] == 0


def test_batcher_propagates_engine_errors():
    eng = serving.ServingEngine(_model(), batch_buckets=(2,))
    batcher = serving.ContinuousBatcher(eng, max_delay_ms=1)
    try:
        fut = batcher.submit(list(range(30)), 10)  # exceeds W=16
        with pytest.raises(MXNetError, match="cache window"):
            fut.result(timeout=120)
    finally:
        batcher.close()


# -- admission control (no engine compile needed: stub engine) -----------------

class _StubEngine:
    """Engine-shaped stub: blockable, instant, jax-free — isolates the
    batcher's admission/deadline behavior from compile latency."""

    batch_buckets = (1, 2, 4)

    def __init__(self):
        import threading

        self.block = threading.Event()
        self.block.set()

    def serve_group(self, prompts, maxes, temperature=None, rng=None):
        self.block.wait()
        outs = [[1, 2, 3] for _ in prompts]
        timings = {"prefill_us": 10.0, "decode_us_per_token": 1.0,
                   "bucket": [max(len(prompts), 1), 8],
                   "padded_fraction": 0.0, "generation": 0}
        return outs, timings


def test_batcher_sheds_when_queue_full():
    telemetry.reset()
    eng = _StubEngine()
    eng.block.clear()               # engine wedged: queue can only grow
    b = serving.ContinuousBatcher(eng, max_delay_ms=0.0, max_queue=2)
    try:
        futs = [b.submit([1], 2)]
        time.sleep(0.2)             # loop takes it into the blocked serve
        futs += [b.submit([1], 2) for _ in range(2)]  # fills the queue
        with pytest.raises(serving.ServerOverloaded, match="queue full"):
            b.submit([1], 2)
        assert b.shed == 1
        assert telemetry.event_counts().get("queue_full", 0) == 1
        eng.block.set()             # back-pressure released: all served
        for f in futs:
            assert f.result(timeout=30)["tokens"] == [1, 2, 3]
    finally:
        eng.block.set()
        b.close(timeout=30)
    assert b.shed == 1              # shed request never cost a slot


def test_batcher_deadline_exceeded_before_dispatch():
    eng = _StubEngine()
    eng.block.clear()
    b = serving.ContinuousBatcher(eng, max_delay_ms=0.0, max_queue=16)
    try:
        blocker = b.submit([1], 2)          # occupies the engine
        time.sleep(0.05)
        doomed = b.submit([1], 2, deadline_ms=10.0)
        ok = b.submit([1], 2)               # no deadline: must survive
        time.sleep(0.2)                     # deadline passes while queued
        eng.block.set()
        with pytest.raises(serving.DeadlineExceeded):
            doomed.result(timeout=30)
        assert ok.result(timeout=30)["tokens"] == [1, 2, 3]
        assert blocker.result(timeout=30)["tokens"] == [1, 2, 3]
        assert b.deadline_exceeded == 1
    finally:
        eng.block.set()
        b.close(timeout=30)


def test_batcher_idle_blocks_instead_of_spinning():
    """The collector must sit in ONE blocking queue.get while idle —
    the PR 11 loop polled with timeout=0 and burned a core."""
    import queue as queue_mod

    from mxnet_tpu.serving import batcher as batcher_mod

    calls = {"n": 0}

    class CountingQueue(queue_mod.Queue):
        def get(self, block=True, timeout=None):
            calls["n"] += 1
            return super().get(block, timeout)

    orig = batcher_mod.queue.Queue
    batcher_mod.queue.Queue = CountingQueue
    try:
        b = serving.ContinuousBatcher(_StubEngine(), max_delay_ms=1.0)
    finally:
        batcher_mod.queue.Queue = orig
    try:
        time.sleep(0.5)
        assert calls["n"] == 1, \
            f"idle batcher polled the queue {calls['n']} times in 0.5s"
        assert b.submit([1], 2).result(timeout=30)["tokens"] == [1, 2, 3]
    finally:
        b.close(timeout=30)


def test_batcher_close_drains_queued_requests():
    import threading

    eng = _StubEngine()
    eng.block.clear()
    b = serving.ContinuousBatcher(eng, max_delay_ms=0.0, max_queue=16)
    futs = [b.submit([1], 2) for _ in range(4)]
    threading.Timer(0.2, eng.block.set).start()
    b.close(timeout=30)
    for f in futs:
        assert f.result(timeout=1)["tokens"] == [1, 2, 3]


# -- hot reload ----------------------------------------------------------------

def test_hot_reload_mid_stream_zero_dropped_requests(tmp_path):
    telemetry.reset()
    model_a, model_b = _model(seed=1), _model(seed=2)
    ck = checkpoint.AsyncCheckpointer(tmp_path, rank=0, world_size=1)
    ck.save(1, serving.state_for_serving(model_a))
    ck.wait()

    eng = serving.ServingEngine(model_a, batch_buckets=(1, 2))
    rs = ReplicaServer(eng, ckpt_dir=tmp_path, poll_ms=10,
                       max_delay_ms=1)
    rng = np.random.RandomState(9)
    prompts = _prompts(6, rng)
    try:
        pre = [rs.submit(p, 4).result(timeout=120) for p in prompts]
        # step 1 is model A's own weights, so whether the poller's first
        # swap landed yet (generation 0 vs 1) can't change outputs
        assert all(len(r["tokens"]) == 4 for r in pre)

        # commit new weights while the stream keeps flowing; the poller
        # stages them and the batcher swaps BETWEEN groups
        ck.save(2, serving.state_for_serving(model_b))
        ck.wait()
        ck.close()
        deadline = time.monotonic() + 30
        streamed = 0
        while rs.loaded_step != 2:
            assert time.monotonic() < deadline, "reload never landed"
            rs.submit(prompts[streamed % len(prompts)], 2)\
                .result(timeout=120)
            streamed += 1
        post = [rs.submit(p, 4).result(timeout=120) for p in prompts]
    finally:
        rs.close()
    # zero dropped/errored: every future above resolved with tokens
    assert all(len(r["tokens"]) == 4 for r in post)
    # all post-reload requests served by ONE weight generation (the
    # step-1 swap may or may not have landed first: 1 or 2 reloads)
    assert len({r["generation"] for r in post}) == 1
    assert 1 <= rs.reloads <= 2

    # post-reload outputs are REALLY model B's weights
    eng_b = serving.ServingEngine(_model(seed=2), batch_buckets=(1, 2))
    for p, r in zip(prompts, post):
        ref = eng_b.serve_group([p], 4)[0][0]
        np.testing.assert_array_equal(ref, r["tokens"])
    assert telemetry.event_counts().get("serving_reload", 0) >= 1


def test_reload_rejects_incompatible_state():
    eng = serving.ServingEngine(_model(), batch_buckets=(1,))
    gen0 = eng.generation
    with pytest.raises(MXNetError, match="scanned-trunk"):
        eng.reload_from_state({"dense0_weight": np.zeros((2, 2))})
    other = _model(units=16, max_length=16)
    with pytest.raises(MXNetError, match="mismatch"):
        eng.reload_from_state(serving.state_for_serving(other))
    assert eng.generation == gen0  # failed swaps leave weights alone


def test_latest_manifest_step_scans_committed_only(tmp_path):
    assert checkpoint.latest_manifest_step(tmp_path) is None
    for step, committed in ((3, True), (7, False), (5, True)):
        d = tmp_path / f"step_{step:010d}"
        d.mkdir()
        if committed:
            (d / "MANIFEST.json").write_text("{}")
    (tmp_path / "step_junk").mkdir()
    # 7 is a crash orphan (no manifest): invisible
    assert checkpoint.latest_manifest_step(tmp_path) == 5
    assert checkpoint.latest_manifest_step(tmp_path / "absent") is None


# -- front door ----------------------------------------------------------------

class _StubReplica:
    def __init__(self, rank, fail=False, shed=0):
        self.rank = rank
        self.fail = fail
        self.shed = shed        # raise ServerOverloaded this many times
        self.calls = 0

    def submit(self, prompt, max_new_tokens=16, deadline_ms=None,
               trace=None):
        self.calls += 1
        if self.fail:
            raise RuntimeError("replica down")
        if self.shed > 0:
            self.shed -= 1
            raise serving.ServerOverloaded("serving queue full")
        return ("ok", self.rank)

    def close(self, timeout=None):
        pass


def test_front_door_round_robin_and_failover():
    good1, bad, good2 = (_StubReplica(0), _StubReplica(1, fail=True),
                         _StubReplica(2))
    fd = FrontDoor([good1, bad, good2])
    results = [fd.submit([1, 2], 2) for _ in range(6)]
    assert all(r[0] == "ok" for r in results)
    # the failing replica was tried once, failed over, and quarantined
    assert bad.calls == 1
    assert {r.rank for r in fd.alive()} == {0, 2}
    assert good1.calls + good2.calls == 6
    fd2 = FrontDoor([_StubReplica(0, fail=True)])
    with pytest.raises(MXNetError, match="every replica"):
        fd2.submit([1], 1)


def test_front_door_retries_shed_once_without_quarantine():
    # first replica full, second takes it: client never sees the shed
    full, okr = _StubReplica(0, shed=1), _StubReplica(1)
    fd = FrontDoor([full, okr])
    assert fd.submit([1], 1) == ("ok", 1)
    assert {r.rank for r in fd.alive()} == {0, 1}, \
        "a shed is back-pressure, not a failure — no quarantine"
    assert fd.submit([1], 1) == ("ok", 1)   # round-robin unchanged
    assert fd.submit([1], 1) == ("ok", 0)   # ...and 0 drained its queue

    # EVERY replica full: one retry, then the shed reaches the client
    f0, f1, f2 = (_StubReplica(r, shed=9) for r in range(3))
    fd2 = FrontDoor([f0, f1, f2])
    with pytest.raises(serving.ServerOverloaded):
        fd2.submit([1], 1)
    assert f0.calls + f1.calls + f2.calls == 2, \
        "exactly one shed retry — no hammering a saturated fleet"
    assert len(fd2.alive()) == 3


def test_fleet_watcher_claims_freed_chips_and_spawns(tmp_path):
    from mxnet_tpu.distributed import FileKV
    from mxnet_tpu.resilience import announce_freed_chips

    telemetry.reset()
    kv = FileKV(str(tmp_path / "kv"))
    announce_freed_chips(kv, 1, step=12, count=4, addr="host1:0")
    spawned = []

    def spawn(rec):
        spawned.append(rec)
        return _StubReplica(rec["rank"])

    w = serving.FleetWatcher(kv, spawn)
    reps = w.poll_once()
    assert [r.rank for r in reps] == [1]
    assert w.claimed == 1 and len(spawned) == 1
    assert spawned[0]["count"] == 4 and spawned[0]["step"] == 12
    # announcement consumed, claim recorded: a second poll is a no-op
    assert kv.get_json("chips/freed/1") is None
    assert kv.get_json("chips/claimed/1")["rank"] == 1
    assert w.poll_once() == []
    assert telemetry.event_counts().get("serving_replica_spawned") == 1
    assert telemetry.event_counts().get("chips_freed") == 1


# -- tensor-parallel serving ---------------------------------------------------

def test_tp_serving_matches_unsharded(mesh8):
    """Sharded serving through TRANSFORMER_TP_RULES-style placements:
    prefill logits match the unsharded engine to float32 rounding (the
    tp all-reduce associates partial sums differently, so the contract
    is logits-to-rounding — same as _assert_decode_equiv in
    test_model_zoo), and the tp request path is retrace-free."""
    mesh = mesh8(tp=2, dp=4)
    net = _model()
    plain = serving.ServingEngine(net, batch_buckets=(2,))
    tp = serving.ServingEngine(net, batch_buckets=(2,), mesh=mesh)
    rng = np.random.RandomState(13)
    prompts = _prompts(2, rng, lo=4, hi=6)

    toks = np.zeros((2, 8), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    zero = np.zeros(2, np.int32)
    _, _, ref_lg = plain._call(2, 8, *plain.init_cache(2), zero, toks)
    _, _, tp_lg = tp._call(2, 8, *tp.init_cache(2), zero, toks)
    np.testing.assert_allclose(np.asarray(tp_lg), np.asarray(ref_lg),
                               rtol=2e-4, atol=1e-5)

    # the full request path runs end-to-end on the mesh, retrace-free
    outs, timings = tp.serve_group(prompts, 4)
    assert [len(o) for o in outs] == [4, 4]
    assert timings["bucket"] == [2, 8]
    pinned = serving.trace_count()
    tp.serve_group(prompts, 4)
    assert serving.trace_count() == pinned


# -- env knobs -----------------------------------------------------------------

def test_bucket_and_deadline_env_knobs(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_BUCKETS", "2,8,4")
    assert serving.batch_buckets_from_env() == (2, 4, 8)
    monkeypatch.setenv("MXTPU_SERVE_BUCKETS", "bogus")
    assert serving.batch_buckets_from_env() == (1, 2, 4, 8)
    assert serving.prefill_buckets_for(64) == (8, 16, 32, 64)
    assert serving.prefill_buckets_for(48) == (8, 16, 32, 48)
    monkeypatch.setenv("MXTPU_SERVE_MAX_DELAY_MS", "12.5")
    assert serving.max_delay_ms_from_env() == 12.5
    monkeypatch.delenv("MXTPU_SERVE_MAX_DELAY_MS")
    assert serving.max_delay_ms_from_env() == 5.0


def test_capture_cache_size_env(monkeypatch):
    from mxnet_tpu.gluon import captured

    assert captured.capture_cache_size() == 8
    monkeypatch.setenv("MXTPU_CAPTURE_CACHE", "3")
    assert captured.capture_cache_size() == 3
    monkeypatch.setenv("MXTPU_CAPTURE_CACHE", "0")
    assert captured.capture_cache_size() == 1  # floor: never cache-less


def test_capture_cache_eviction_emits_event(monkeypatch):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    telemetry.reset()
    monkeypatch.setenv("MXTPU_CAPTURED_STEP", "1")
    monkeypatch.setenv("MXTPU_CAPTURE_CACHE", "1")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    for n in (4, 6):   # two batch shapes, cache capacity 1 → eviction
        x = mx.nd.array(rng.normal(size=(n, 3)).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 4, n).astype(np.float32))
        trainer.train_step(net, loss_fn, x, y)
    assert telemetry.event_counts().get("capture_cache_evict", 0) >= 1


# -- telemetry schema ----------------------------------------------------------

def test_request_record_schema_validates():
    telemetry.reset()
    telemetry.request_record(queue_us=12.0, prefill_us=340.0,
                             decode_us_per_token=55.5, bucket=(4, 16),
                             padded_fraction=0.25, new_tokens=8,
                             generation=2)
    recs = telemetry.recent_requests()
    assert len(recs) == 1
    telemetry.validate_record(recs[0])
    bad = dict(recs[0], bucket=[0, 16])
    with pytest.raises(ValueError, match="bucket"):
        telemetry.validate_record(bad)
    bad = dict(recs[0], padded_fraction=1.5)
    with pytest.raises(ValueError, match="padded_fraction"):
        telemetry.validate_record(bad)


def test_trace_report_requests_section(tmp_path, monkeypatch):
    path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    for i in range(5):
        telemetry.request_record(queue_us=10.0 * i, prefill_us=200.0,
                                 decode_us_per_token=40.0,
                                 bucket=(2, 8), padded_fraction=0.1,
                                 new_tokens=4, generation=i % 2)
    telemetry.reset()  # close the sink so the file is flushed
    monkeypatch.delenv("MXTPU_TELEMETRY_PATH")

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    import io

    records, bad = trace_report.read_records(path)
    assert bad == 0 and len(records) == 5
    assert trace_report.validate_all(records) == []
    out = io.StringIO()
    trace_report.report_run("r", records, out)
    text = out.getvalue()
    assert "serving requests:" in text
    assert "decode/token" in text
    assert "2x8:5" in text
    assert "generations served: [0, 1]" in text


# -- CLI smoke -----------------------------------------------------------------

def test_serve_cli_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serve.py"),
         "--requests", "4", "--clients", "2", "--new-tokens", "3",
         "--buckets", "1,2"],
        cwd=root, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert "served 4 requests" in proc.stdout
    assert "retraces_after_warmup 0" in proc.stdout


# -- reload integrity gate (mxnet_tpu/integrity.py) ----------------------------

def test_reload_rejects_corrupt_checkpoint_and_keeps_serving(tmp_path):
    """A bit-rotted shard must never be swapped in: the poller's
    verify-before-stage gate (per-shard CRC + provenance audit) rejects
    the step ONCE (rejection dedups — a bad file will not un-corrupt),
    emits ``serving_reload_rejected``, and the replica keeps serving on
    its compiled-in weights."""
    telemetry.reset()
    model = _model(seed=1)
    ck = checkpoint.AsyncCheckpointer(tmp_path, rank=0, world_size=1)
    ck.save(1, serving.state_for_serving(model))
    ck.wait()
    ck.close()
    sdir = next(p for p in tmp_path.iterdir()
                if p.name.startswith("step_"))
    shard = next(p for p in sdir.iterdir()
                 if p.name.startswith("shard_"))
    raw = bytearray(shard.read_bytes())
    raw[len(raw) // 2] ^= 0x40
    shard.write_bytes(bytes(raw))

    eng = serving.ServingEngine(model, batch_buckets=(1, 2))
    rs = ReplicaServer(eng, ckpt_dir=tmp_path, poll_ms=10,
                       max_delay_ms=1)
    try:
        deadline = time.monotonic() + 30
        while not telemetry.event_counts().get("serving_reload_rejected"):
            assert time.monotonic() < deadline, "rejection never surfaced"
            time.sleep(0.01)
        time.sleep(0.2)                 # many more poll cycles
        assert rs.loaded_step is None and rs.reloads == 0
        assert telemetry.event_counts()["serving_reload_rejected"] == 1
        # the replica is still healthy on its original weights
        r = rs.submit(_prompts(1, np.random.RandomState(3))[0], 3)\
            .result(timeout=120)
        assert len(r["tokens"]) == 3
    finally:
        rs.close()
    telemetry.reset()


def test_reload_from_state_enforces_attested_fingerprint():
    """``expect_fp`` closes the loop past the per-shard CRCs: the
    restored state is re-fingerprinted and a mismatch with the
    training side's attested fingerprint refuses the swap."""
    from mxnet_tpu import integrity

    telemetry.reset()
    eng = serving.ServingEngine(_model(seed=1), batch_buckets=(1, 2))
    state = serving.state_for_serving(_model(seed=2))
    with pytest.raises(MXNetError, match="fingerprint"):
        eng.reload_from_state(state, step=2, expect_fp=12345)
    assert telemetry.event_counts().get("serving_reload_rejected") == 1
    # the attested fingerprint of the same state swaps cleanly
    eng.reload_from_state(state, step=2,
                          expect_fp=integrity.fingerprint_host(state))
    telemetry.reset()


def test_reload_skips_stale_epoch_manifest(tmp_path, monkeypatch):
    """Epoch fence on the serving side: once a manifest from gang epoch
    E has been served, a newer-STEP manifest stamped with an OLDER
    epoch (a fenced trainer's leftover commit) is rejected — the
    serving weights never roll backwards across a reshape — while a
    same-or-newer-epoch manifest reloads normally."""
    import json

    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    telemetry.reset()
    model = _model(seed=1)
    prompt = _prompts(1, np.random.RandomState(3))[0]

    def save(step, epoch):
        ck = checkpoint.AsyncCheckpointer(tmp_path, rank=0,
                                          world_size=1)
        ck.attach_gang(lambda: epoch)
        ck.save(step, serving.state_for_serving(model))
        ck.wait()
        ck.close()

    save(1, 2)
    eng = serving.ServingEngine(model, batch_buckets=(1, 2))
    rs = ReplicaServer(eng, ckpt_dir=tmp_path, poll_ms=10,
                       max_delay_ms=1)
    try:
        deadline = time.monotonic() + 30
        while rs.loaded_step != 1:
            assert time.monotonic() < deadline, "epoch-2 reload lost"
            rs.submit(prompt, 2).result(timeout=120)
        assert rs._served_epoch == 2

        save(2, 1)                      # newer step, OLDER epoch: stale
        deadline = time.monotonic() + 30
        while not telemetry.event_counts().get(
                "serving_reload_rejected"):
            assert time.monotonic() < deadline, \
                "stale-epoch rejection never surfaced"
            time.sleep(0.01)
        time.sleep(0.2)                 # many more poll cycles
        rs.submit(prompt, 2).result(timeout=120)
        assert rs.loaded_step == 1, "stale-epoch manifest was served"
        assert rs._served_epoch == 2

        save(3, 2)                      # same epoch again: reloads
        deadline = time.monotonic() + 30
        while rs.loaded_step != 3:
            assert time.monotonic() < deadline, "epoch-2 reload lost"
            rs.submit(prompt, 2).result(timeout=120)
    finally:
        rs.close()
    telemetry.reset()
    with open(ev_path) as f:
        ev = [json.loads(ln) for ln in f if ln.strip()]
    rejected = [e for e in ev
                if e.get("event") == "serving_reload_rejected"]
    assert rejected and all(
        e["reason"].startswith("stale_epoch") for e in rejected)
