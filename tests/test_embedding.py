"""Sharded embeddings on the captured step (PR 18:
mxnet_tpu/embedding/ + gluon/captured.py + optimizer/grouped.py).

The captured sparse step must be a pure performance transform: host
unique/inverse id prep, an in-program padded gather, and a segment-sum
scatter-add row update — ONE dispatch + ONE readback per step, BITWISE
equal to the eager row-sparse oracle (the op-by-op tape over
`ops.indexing.sparse_embedding` + the RowSparseNDArray lazy-row
updater), for sgd and adam, with and without grad accumulation,
including rows the batch never touched.  Retraces are bounded by the
power-of-2 unique-count bucket, and every routing of a
``sparse_grad=True`` model to the eager oracle emits a
``sparse_fallback{reason}`` telemetry event — never silent.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import embedding, gluon, numerics, telemetry
from mxnet_tpu.embedding import prep as emb_prep
from mxnet_tpu.gluon import captured, nn
from mxnet_tpu.optimizer import grouped

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")

VOCAB, DIM, STEPS = 50, 8, 6


def _make_net(hybridize, vocab=VOCAB, dim=DIM, seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(embedding.ShardedEmbedding(vocab, dim))
        net.add(nn.Dense(3))
    net.initialize(init=mx.init.Xavier())
    if hybridize:
        net.hybridize()
    return net


def _batches(steps=STEPS, n=8, t=4, vocab=VOCAB, seed=3):
    rng = np.random.RandomState(seed)
    xs = [rng.randint(0, vocab, size=(n, t)).astype(np.float32)
          for _ in range(steps)]
    ys = [rng.randint(0, 3, size=(n,)).astype(np.float32)
          for _ in range(steps)]
    return xs, ys


def _state_leaves(state):
    if state is None:
        return []
    if isinstance(state, (list, tuple)):
        return [a for s in state for a in _state_leaves(s)]
    return [state.asnumpy()] if hasattr(state, "asnumpy") else []


def _events(kind):
    with telemetry._LOCK:
        return [r for r in telemetry._RECENT
                if r.get("type") == "event" and r.get("event") == kind]


def _run(monkeypatch, captured_on, opt="sgd", opt_params=None, k=1,
         steps=STEPS, xs=None, ys=None):
    """One full training run; captured = hybridized net through the
    captured sparse step, eager = the NON-hybridized op-by-op oracle
    behind MXTPU_SPARSE_CAPTURED=0."""
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED",
                       "1" if captured_on else "0")
    net = _make_net(hybridize=captured_on)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), opt,
                            opt_params or {"learning_rate": 0.1})
    if xs is None:
        xs, ys = _batches(steps=steps)
    captured.reset_counters()
    losses = []
    for s in range(steps):
        loss = trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                                  mx.nd.array(ys[s]), grad_accum=k)
        losses.append(loss.asnumpy())
    weights = [p.data().asnumpy() for p in trainer._params]
    states = {i: _state_leaves(st)
              for i, st in trainer._updaters[0].states.items()}
    return (losses, weights, states, captured.dispatch_count(),
            captured.trace_count())


# -- bitwise parity with the eager row-sparse oracle ---------------------------

@pytest.mark.parametrize("opt,params", [
    ("sgd", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
])
@pytest.mark.parametrize("k", [1, 4])
def test_captured_sparse_bitwise_equals_eager_oracle(monkeypatch, opt,
                                                     params, k):
    """Losses, EVERY weight (embedding rows the batches never touched
    included — lazy-update must not decay them), and every optimizer
    state leaf: bitwise equal between the captured sparse step and the
    eager RowSparseNDArray oracle."""
    le, we, se, _, _ = _run(monkeypatch, False, opt, params, k)
    lc, wc, sc, disp, _ = _run(monkeypatch, True, opt, params, k)
    assert disp == STEPS  # every step stayed captured
    for s, (a, b) in enumerate(zip(le, lc)):
        np.testing.assert_array_equal(a, b, err_msg=f"loss step {s}")
    for i, (a, b) in enumerate(zip(we, wc)):
        np.testing.assert_array_equal(a, b, err_msg=f"weight {i}")
    assert set(se) == set(sc)
    for i in se:
        assert len(se[i]) == len(sc[i])
        for a, b in zip(se[i], sc[i]):
            np.testing.assert_array_equal(a, b, err_msg=f"state {i}")


def test_untouched_rows_never_move(monkeypatch):
    """Rows outside every batch's id set keep their init bytes: the
    scatter-add update touches only gathered rows (lazy update), in
    both modes."""
    rng = np.random.RandomState(11)
    # ids drawn from the first half of the vocab only
    xs = [rng.randint(0, VOCAB // 2, size=(8, 4)).astype(np.float32)
          for _ in range(STEPS)]
    ys = [rng.randint(0, 3, size=(8,)).astype(np.float32)
          for _ in range(STEPS)]
    init = _make_net(hybridize=False)
    table0 = init[0].weight.data().asnumpy().copy()
    for cap in (False, True):
        _, weights, _, _, _ = _run(monkeypatch, cap, "adam",
                                   {"learning_rate": 0.01}, 1,
                                   xs=xs, ys=ys)
        table = weights[0]
        np.testing.assert_array_equal(table[VOCAB // 2:],
                                      table0[VOCAB // 2:])
        assert not np.array_equal(table[:VOCAB // 2],
                                  table0[:VOCAB // 2])


# -- dispatch / readback / retrace accounting ----------------------------------

@pytest.mark.parametrize("k", [1, 4])
def test_one_dispatch_one_readback_per_sparse_step(monkeypatch, k):
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "1")
    monkeypatch.setenv("MXTPU_GRAD_GUARD", "1")
    net = _make_net(hybridize=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    rng = np.random.RandomState(5)
    # every batch uses the same id set -> one bucket, zero retrace
    # after warmup
    ids = rng.choice(VOCAB, size=24, replace=False)
    xs = [rng.choice(ids, size=(8, 4)).astype(np.float32)
          for _ in range(5)]
    ys = [rng.randint(0, 3, size=(8,)).astype(np.float32)
          for _ in range(5)]
    trainer.train_step(net, loss_fn, mx.nd.array(xs[0]),
                       mx.nd.array(ys[0]), grad_accum=k)
    captured.reset_counters()
    grouped.reset_dispatch_count()
    numerics.reset_readback_count()
    for s in range(1, 5):
        trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                           mx.nd.array(ys[s]), grad_accum=k)
    assert captured.dispatch_count() == 4
    assert grouped.dispatch_count() == 0
    assert numerics.readback_count() == 4
    assert captured.trace_count() == 0


def test_retrace_bounded_by_unique_buckets(monkeypatch):
    """Varying per-batch unique counts retrace at most once per
    DISTINCT power-of-2 bucket, not per batch."""
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "1")
    net = _make_net(hybridize=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    rng = np.random.RandomState(9)
    xs, ys, buckets = [], [], set()
    for s in range(10):
        # alternate small / large id sets -> two buckets at most
        n_ids = 5 if s % 2 == 0 else 20
        ids = rng.choice(VOCAB, size=n_ids, replace=False)
        xs.append(rng.choice(ids, size=(8, 4)).astype(np.float32))
        ys.append(rng.randint(0, 3, size=(8,)).astype(np.float32))
        buckets.add(emb_prep.bucket_for(
            len(np.unique(xs[-1].astype(np.int64)))))
    captured.reset_counters()
    for s in range(10):
        trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                           mx.nd.array(ys[s]))
    assert captured.dispatch_count() == 10
    assert captured.trace_count() <= len(buckets)
    assert len(buckets) <= 3


def test_step_records_carry_lookup_fields(monkeypatch):
    """Schema v6: captured sparse steps stamp ``lookup_us`` and
    ``unique_fraction`` into their StepStats records."""
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "1")
    telemetry.reset()
    _run(monkeypatch, True, "sgd", {"learning_rate": 0.1}, 1, steps=3)
    recs = telemetry.recent_steps(path="captured")
    assert recs
    for rec in recs[-2:]:
        assert rec.get("lookup_us") is not None and rec["lookup_us"] >= 0
        assert 0 < rec.get("unique_fraction") <= 1
        telemetry.validate_record(rec)


# -- sparse_fallback events: never silent --------------------------------------

def test_fallback_event_when_sparse_capture_disabled(monkeypatch):
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "0")
    telemetry.reset()
    net = _make_net(hybridize=True)  # otherwise capture-eligible
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    xs, ys = _batches(steps=2)
    captured.reset_counters()
    for s in range(2):
        trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                           mx.nd.array(ys[s]))
    assert captured.dispatch_count() == 0
    evs = _events("sparse_fallback")
    assert len(evs) == 2
    assert all("MXTPU_SPARSE_CAPTURED=0" in e["reason"] for e in evs)


def test_fallback_event_on_non_lazy_update(monkeypatch):
    """lazy_update=False densifies the row-sparse gradient — no fused
    row plan; the eager oracle still trains, loudly."""
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "1")
    telemetry.reset()
    net = _make_net(hybridize=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(
        net.collect_params(), "sgd",
        {"learning_rate": 0.1, "lazy_update": False})
    xs, ys = _batches(steps=2)
    captured.reset_counters()
    for s in range(2):
        loss = trainer.train_step(net, loss_fn, mx.nd.array(xs[s]),
                                  mx.nd.array(ys[s]))
        assert np.isfinite(loss.asnumpy()).all()
    assert captured.dispatch_count() == 0  # routed to the oracle
    evs = _events("sparse_fallback")
    assert len(evs) == 2
    assert all("lazy_update=False" in e["reason"] for e in evs)


def test_fallback_event_on_bucket_overflow(monkeypatch):
    """A fixed MXTPU_UNIQUE_BUCKET smaller than the batch's unique
    count falls back per-step with the overflow reason."""
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "1")
    monkeypatch.setenv("MXTPU_UNIQUE_BUCKET", "8")
    telemetry.reset()
    net = _make_net(hybridize=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    rng = np.random.RandomState(2)
    small = rng.choice(VOCAB, size=6, replace=False)  # fits bucket 8
    captured.reset_counters()
    trainer.train_step(
        net, loss_fn,
        mx.nd.array(rng.choice(small, (8, 4)).astype(np.float32)),
        mx.nd.array(rng.randint(0, 3, (8,)).astype(np.float32)))
    assert captured.dispatch_count() == 1
    trainer.train_step(  # 8 rows of 4 distinct ids each: > 8 unique
        net, loss_fn,
        mx.nd.array(np.arange(32, dtype=np.float32).reshape(8, 4)),
        mx.nd.array(rng.randint(0, 3, (8,)).astype(np.float32)))
    assert captured.dispatch_count() == 1  # overflow step went eager
    evs = _events("sparse_fallback")
    assert len(evs) == 1
    assert "unique count exceeds MXTPU_UNIQUE_BUCKET=8" in \
        evs[0]["reason"]


# -- sharding: EmbeddingRules + placement --------------------------------------

def test_embedding_rules_row_shard_and_user_merge():
    """EmbeddingRules claims the vocab dim for dp; a user rule on the
    output dim merges per-dim (PR 17) instead of colliding."""
    from mxnet_tpu import parallel

    rules = parallel.combined_rules(
        parallel.EmbeddingRules(),
        parallel.ShardingRules(rules=[(r"_embed_table$", (None, "tp"))]))
    spec = parallel.match_partition_rules(
        rules, {"net0_embed_table": (64, 16)})["net0_embed_table"]
    assert tuple(spec) == ("dp", "tp")
    # TRANSFORMER_TP_RULES' embedding\d*_weight rule must NOT claim it
    spec2 = parallel.match_partition_rules(
        parallel.combined_rules(parallel.EmbeddingRules(),
                                parallel.TRANSFORMER_TP_RULES),
        {"net0_embed_table": (64, 16)})["net0_embed_table"]
    assert tuple(spec2) == ("dp", None)


def test_uneven_vocab_degrades_to_replicated(mesh8):
    """jax.device_put rejects uneven placements: a vocab the dp axis
    does not divide must replicate at placement time, not fail."""
    from types import SimpleNamespace

    from jax.sharding import PartitionSpec

    from mxnet_tpu import parallel

    mesh = mesh8(dp=8)

    def fake(shape):
        return SimpleNamespace(partition_spec=PartitionSpec("dp", None),
                               shape=shape)

    even = parallel.param_sharding(fake((48, 8)), mesh)
    assert even.spec == PartitionSpec("dp", None)
    uneven = parallel.param_sharding(fake((51, 8)), mesh)
    assert uneven.spec == PartitionSpec(None, None)


def test_sharded_table_trains_captured(monkeypatch, mesh8):
    """A row-sharded table trains through the captured sparse step on
    an 8-device mesh: dispatch stays 1/step, the table keeps its
    ('dp', None) placement, and the loss is finite."""
    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "1")
    from jax.sharding import PartitionSpec

    from mxnet_tpu import parallel

    net = _make_net(hybridize=True, vocab=48)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    mesh = mesh8(dp=8)
    specs = parallel.shard_model(net, mesh, mode="fsdp", min_size=1,
                                 trainer=trainer)
    table_name = [n for n in specs if n.endswith("embed_table")][0]
    assert tuple(specs[table_name]) == ("dp", None)
    rng = np.random.RandomState(3)
    captured.reset_counters()
    for _ in range(4):
        x = rng.randint(0, 48, size=(16, 4)).astype(np.float32)
        y = rng.randint(0, 3, size=(16,)).astype(np.float32)
        loss = trainer.train_step(net, loss_fn, mx.nd.array(x),
                                  mx.nd.array(y), grad_accum=2)
        assert np.isfinite(loss.asnumpy()).all()
    assert captured.dispatch_count() == 4
    table = net[0].weight.data()._data
    assert table.sharding.spec == PartitionSpec("dp", None)


# -- prefetcher id-prep stage --------------------------------------------------

def test_prefetcher_stashes_and_captured_consumes(monkeypatch):
    """The producer-side id prep is stashed per batch tensor and
    consumed (one-shot) by the captured step's own prepare_step."""
    from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher

    monkeypatch.setenv("MXTPU_SPARSE_CAPTURED", "1")
    net = _make_net(hybridize=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    xs, ys = _batches(steps=4)
    batches = [(mx.nd.array(x), mx.nd.array(y)) for x, y in zip(xs, ys)]
    pf = DevicePrefetcher(batches, depth=2, sparse_tables=net)
    captured.reset_counters()
    n = 0
    for x, y in pf:
        # the producer thread stashed this batch's prep
        key = id(net[0].weight)
        trainer.train_step(net, loss_fn, x, y)
        n += 1
    pf.close()
    assert n == 4
    assert captured.dispatch_count() == 4
    # stash fully drained: nothing left for any batch
    for x, _ in batches:
        assert emb_prep.pop_prep(x) is None


def test_pop_prep_is_one_shot():
    data = mx.nd.array(np.array([[1.0, 2.0], [3.0, 1.0]]))
    blk = embedding.ShardedEmbedding(8, 4)
    blk.initialize()
    pr = emb_prep.prepare_one(data, blk)
    assert pr is not None
    emb_prep.stash_prep(data, {id(blk.weight): pr})
    got = emb_prep.pop_prep(data)
    assert got is not None and id(blk.weight) in got
    assert emb_prep.pop_prep(data) is None


# -- trace_report embeddings section -------------------------------------------

def test_trace_report_embeddings_section(tmp_path, monkeypatch):
    """A sparse run's event log flows through the trace_report CLI:
    lookup/unique aggregates plus the per-reason fallback census, and
    the v6 fields validate."""
    path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", path)
    telemetry.reset()
    for step in range(3):
        acc = telemetry.step_begin(path="captured")
        telemetry.note(lookup_us=100.0 + step, unique_fraction=0.5)
        telemetry.step_end(acc, step=step)
    telemetry.event("sparse_fallback",
                    reason="unique count exceeds MXTPU_UNIQUE_BUCKET=8")
    telemetry.reset()  # close the sink

    env = dict(os.environ)
    env.pop("MXTPU_TELEMETRY_PATH", None)
    proc = subprocess.run(
        [sys.executable, _TRACE_REPORT, path, "--validate"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    out = proc.stdout
    assert "embeddings:" in out
    assert "lookup_us: mean 101.0" in out
    assert "unique_fraction: mean 0.5000" in out
    assert "sparse fallbacks: 1 step(s)" in out
    assert "1x unique count exceeds MXTPU_UNIQUE_BUCKET=8" in out
    assert "validate against schema" in out
