"""RNN tests (reference: tests/python/unittest/test_gluon_rnn.py).

The fused op is validated against a plain numpy recursion with the same
gate orders (LSTM: i f g o; GRU: r z n — cuDNN layout, rnn.cc parity).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_lstm(x, h0, c0, wx, wh, bx, bh):
    """x: (T,B,I); returns outputs (T,B,H)."""
    T, B, _ = x.shape
    H = wh.shape[1]
    h, c = h0.copy(), c0.copy()
    outs = []
    for t in range(T):
        gates = x[t] @ wx.T + bx + h @ wh.T + bh
        i, f, g, o = np.split(gates, 4, axis=-1)
        i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
        g = np.tanh(g)
        c = f * c + i * g
        h = o * np.tanh(c)
        outs.append(h.copy())
    return np.stack(outs), h, c


def _np_gru(x, h0, wx, wh, bx, bh):
    T, B, _ = x.shape
    H = wh.shape[1]
    h = h0.copy()
    outs = []
    for t in range(T):
        xr, xz, xn = np.split(x[t] @ wx.T + bx, 3, axis=-1)
        hr, hz, hn = np.split(h @ wh.T + bh, 3, axis=-1)
        r = _sigmoid(xr + hr)
        z = _sigmoid(xz + hz)
        n = np.tanh(xn + r * hn)
        h = (1 - z) * n + z * h
        outs.append(h.copy())
    return np.stack(outs), h


def test_lstm_matches_numpy():
    T, B, I, H = 4, 2, 3, 5
    rng = np.random.RandomState(0)
    x = rng.randn(T, B, I).astype(np.float32)
    wx = rng.randn(4 * H, I).astype(np.float32) * 0.3
    wh = rng.randn(4 * H, H).astype(np.float32) * 0.3
    bx = rng.randn(4 * H).astype(np.float32) * 0.1
    bh = rng.randn(4 * H).astype(np.float32) * 0.1
    h0 = np.zeros((1, B, H), np.float32)
    c0 = np.zeros((1, B, H), np.float32)

    params = np.concatenate([wx.ravel(), wh.ravel(), bx, bh])
    out, h, c = mx.nd.RNN(
        mx.nd.array(x), mx.nd.array(params), mx.nd.array(h0),
        mx.nd.array(c0), state_size=H, num_layers=1, mode="lstm",
        state_outputs=True)
    ref_out, ref_h, ref_c = _np_lstm(x, h0[0], c0[0], wx, wh, bx, bh)
    np.testing.assert_allclose(out.asnumpy(), ref_out, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(h.asnumpy()[0], ref_h, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(c.asnumpy()[0], ref_c, rtol=1e-4,
                               atol=1e-5)


def test_gru_matches_numpy():
    T, B, I, H = 3, 2, 4, 3
    rng = np.random.RandomState(1)
    x = rng.randn(T, B, I).astype(np.float32)
    wx = rng.randn(3 * H, I).astype(np.float32) * 0.3
    wh = rng.randn(3 * H, H).astype(np.float32) * 0.3
    bx = rng.randn(3 * H).astype(np.float32) * 0.1
    bh = rng.randn(3 * H).astype(np.float32) * 0.1
    h0 = np.zeros((1, B, H), np.float32)

    params = np.concatenate([wx.ravel(), wh.ravel(), bx, bh])
    out, h = mx.nd.RNN(mx.nd.array(x), mx.nd.array(params),
                       mx.nd.array(h0), None, state_size=H, num_layers=1,
                       mode="gru", state_outputs=True)
    ref_out, ref_h = _np_gru(x, h0[0], wx, wh, bx, bh)
    np.testing.assert_allclose(out.asnumpy(), ref_out, rtol=1e-4,
                               atol=1e-5)


def test_lstm_layer_shapes_and_grad():
    lstm = gluon.rnn.LSTM(16, num_layers=2, bidirectional=True)
    lstm.initialize()
    x = mx.nd.random_normal(shape=(5, 3, 8))
    out = lstm(x)
    assert out.shape == (5, 3, 32)
    states = lstm.begin_state(batch_size=3)
    out, st = lstm(x, states)
    assert st[0].shape == (4, 3, 16) and st[1].shape == (4, 3, 16)
    with mx.autograd.record():
        loss = (lstm(x) ** 2).sum()
    loss.backward()
    g = lstm.l0_i2h_weight.grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_rnn_layer_ntc_layout():
    gru = gluon.rnn.GRU(8, layout="NTC")
    gru.initialize()
    out = gru(mx.nd.random_normal(shape=(3, 5, 4)))
    assert out.shape == (3, 5, 8)


def test_rnn_layer_hybridize_consistent():
    mx.random.seed(0)
    lstm = gluon.rnn.LSTM(8)
    lstm.initialize()
    x = mx.nd.random_normal(shape=(4, 2, 6))
    eager = lstm(x).asnumpy()
    lstm.hybridize()
    hybrid = lstm(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_cells_unroll_shapes():
    x = mx.nd.random_normal(shape=(2, 5, 4))  # NTC
    for cell, H in [(gluon.rnn.RNNCell(6), 6),
                    (gluon.rnn.LSTMCell(6), 6),
                    (gluon.rnn.GRUCell(6), 6)]:
        cell.initialize()
        outs, st = cell.unroll(5, x, layout="NTC", merge_outputs=True)
        assert outs.shape == (2, 5, H)


def test_cell_residual_and_dropout():
    base = gluon.rnn.GRUCell(4)
    cell = gluon.rnn.ResidualCell(base)
    cell.initialize()
    x = mx.nd.random_normal(shape=(2, 3, 4))
    outs, st = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 3, 4)

    d = gluon.rnn.DropoutCell(0.5)
    outs, _ = d.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 3, 4)


def test_sequential_cell_stack():
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(10))
    stack.add(gluon.rnn.GRUCell(6))
    stack.initialize()
    x = mx.nd.random_normal(shape=(3, 5, 8))
    outs, states = stack.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (3, 5, 6)
    assert len(states) == 3  # lstm h,c + gru h


def test_bidirectional_cell():
    bi = gluon.rnn.BidirectionalCell(gluon.rnn.LSTMCell(4),
                                     gluon.rnn.LSTMCell(4))
    bi.initialize()
    x = mx.nd.random_normal(shape=(2, 5, 3))
    outs, st = bi.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 8)


def test_rnn_dropout_between_layers():
    lstm = gluon.rnn.LSTM(8, num_layers=2, dropout=0.5)
    lstm.initialize()
    x = mx.nd.random_normal(shape=(4, 2, 6))
    with mx.autograd.train_mode():
        a = lstm(x).asnumpy()
        b = lstm(x).asnumpy()
    assert not np.allclose(a, b)  # dropout between layers is live
    # deterministic in inference
    c = lstm(x).asnumpy()
    d = lstm(x).asnumpy()
    np.testing.assert_allclose(c, d)


def test_lstmp_projection():
    """LSTMP (reference: rnn.cc projection_size): recurrent/output width
    P != cell width H; oracle-checked single step + trains."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    H, P, I, T, B = 8, 5, 4, 6, 3
    net = gluon.rnn.LSTM(H, num_layers=2, projection_size=P,
                         input_size=I)
    net.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(T, B, I)
                 .astype("float32"))
    out = net(x)
    assert out.shape == (T, B, P), out.shape
    states = net.begin_state(batch_size=B)
    assert states[0].shape == (2, B, P)   # h is projected
    assert states[1].shape == (2, B, H)   # c keeps cell width
    out2, new_states = net(x, states)
    assert new_states[0].shape == (2, B, P)
    assert new_states[1].shape == (2, B, H)

    # single-layer numeric oracle
    net1 = gluon.rnn.LSTM(H, num_layers=1, projection_size=P,
                          input_size=I)
    net1.initialize(init=mx.init.Xavier())
    wx = [v for n, v in net1.collect_params().items()
          if n.endswith("i2h_weight")][0].data().asnumpy()
    wh = [v for n, v in net1.collect_params().items()
          if n.endswith("h2h_weight")][0].data().asnumpy()
    wr = [v for n, v in net1.collect_params().items()
          if n.endswith("h2r_weight")][0].data().asnumpy()
    bx = [v for n, v in net1.collect_params().items()
          if n.endswith("i2h_bias")][0].data().asnumpy()
    bh = [v for n, v in net1.collect_params().items()
          if n.endswith("h2h_bias")][0].data().asnumpy()
    xs = np.random.RandomState(1).randn(2, 1, I).astype("float32")
    out = net1(nd.array(xs)).asnumpy()

    def sigmoid(a):
        return 1.0 / (1.0 + np.exp(-a))

    h = np.zeros((1, P), np.float32)
    c = np.zeros((1, H), np.float32)
    for t in range(2):
        gates = xs[t] @ wx.T + bx + h @ wh.T + bh
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sigmoid(f) * c + sigmoid(i) * np.tanh(g)
        h = (sigmoid(o) * np.tanh(c)) @ wr.T
        np.testing.assert_allclose(out[t], h, atol=1e-5)

    # gradient flows through the projection
    xg = nd.array(xs)
    xg.attach_grad()
    with autograd.record():
        loss = (net1(xg) ** 2).sum()
    loss.backward()
    assert float(np.abs(xg.grad.asnumpy()).sum()) > 0


def test_rnn_use_sequence_length_masks_correctly():
    """use_sequence_length (reference: rnn.cc masked RNN): padded steps
    must not advance state or emit output; per-sequence result equals
    running each unpadded sequence alone."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.rnn import rnn, rnn_param_size

    rs = np.random.RandomState(0)
    T, B, I, H = 6, 3, 4, 5
    lens = np.array([6, 3, 1], np.int32)
    x = rs.randn(T, B, I).astype(np.float32)
    x_pad = x.copy()
    for b, L in enumerate(lens):
        x_pad[L:, b] = 99.0  # garbage beyond length must not matter
    n = rnn_param_size("lstm", I, H)
    params = jnp.asarray(rs.randn(n).astype(np.float32) * 0.2)
    h0 = jnp.zeros((1, B, H), jnp.float32)
    c0 = jnp.zeros((1, B, H), jnp.float32)

    out, hT, cT = rnn(jnp.asarray(x_pad), params, h0, c0, state_size=H,
                      mode="lstm", state_outputs=True,
                      use_sequence_length=True,
                      sequence_length=jnp.asarray(lens))
    out = np.asarray(out)
    for b, L in enumerate(lens):
        # reference per-sequence run (unpadded, batch of 1)
        ob, hb, cb = rnn(jnp.asarray(x[:L, b:b + 1]), params,
                         h0[:, :1], c0[:, :1], state_size=H,
                         mode="lstm", state_outputs=True)
        np.testing.assert_allclose(out[:L, b], np.asarray(ob)[:, 0],
                                   atol=1e-5)
        assert np.abs(out[L:, b]).max() == 0 if L < 6 else True
        np.testing.assert_allclose(np.asarray(hT)[0, b],
                                   np.asarray(hb)[0, 0], atol=1e-5)
        np.testing.assert_allclose(np.asarray(cT)[0, b],
                                   np.asarray(cb)[0, 0], atol=1e-5)


def test_rnn_bidirectional_sequence_length():
    """Reverse direction with seq_len: each sequence reversed within
    its own valid region (global-flip + frozen invalid steps)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.rnn import rnn, rnn_param_size

    rs = np.random.RandomState(1)
    T, B, I, H = 5, 2, 3, 4
    lens = np.array([5, 3], np.int32)
    x = rs.randn(T, B, I).astype(np.float32)
    n = rnn_param_size("gru", I, H, bidirectional=True)
    params = jnp.asarray(rs.randn(n).astype(np.float32) * 0.2)
    h0 = jnp.zeros((2, B, H), jnp.float32)
    out, _ = rnn(jnp.asarray(x), params, h0, state_size=H, mode="gru",
                 bidirectional=True, state_outputs=True,
                 use_sequence_length=True,
                 sequence_length=jnp.asarray(lens))
    out = np.asarray(out)
    # sequence 1 (len 3): compare against the unpadded bidirectional run
    ob, _ = rnn(jnp.asarray(x[:3, 1:2]), params, h0[:, :1],
                state_size=H, mode="gru", bidirectional=True,
                state_outputs=True)
    np.testing.assert_allclose(out[:3, 1], np.asarray(ob)[:, 0],
                               atol=1e-5)


def test_lstmp_deferred_input_size():
    """Review regression: deferred init (input_size=0) must infer
    layer>0 input width from the PROJECTED size."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd

    net = gluon.rnn.LSTM(8, num_layers=2, projection_size=5)
    net.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(2).randn(4, 2, 3)
                 .astype("float32"))
    out = net(x)  # deferred shapes resolve here
    assert out.shape == (4, 2, 5)
    w = [p for n, p in net.collect_params().items()
         if n.endswith("l1_i2h_weight")][0]
    assert w.shape == (4 * 8, 5), w.shape
