"""Aux subsystem tests: profiler, runtime, amp, checkpoint, quantization,
gluon.contrib, visualization, symbol shape rules (SURVEY §5 parity)."""

import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon import nn


def test_profiler_chrome_trace(tmp_path):
    f = str(tmp_path / "prof.json")
    mx.profiler.set_config(profile_all=True, filename=f)
    mx.profiler.set_state("run")
    a = mx.nd.ones((8, 8))
    (a * a).sum().wait_to_read()
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(f) as fh:
        trace = json.load(fh)
    assert len(trace["traceEvents"]) >= 2
    names = {e["name"] for e in trace["traceEvents"]}
    assert "broadcast_mul" in names or "sum" in names
    summary = mx.profiler.get_summary(reset=True)
    assert "sum" in summary


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA")
    assert not feats.is_enabled("CUDA")
    assert len(mx.runtime.feature_list()) > 10
    with pytest.raises(RuntimeError):
        feats.is_enabled("NOT_A_FEATURE")


def test_amp_bf16_block():
    mx.amp.init("bfloat16")
    net = nn.Dense(4, in_units=3)
    net.initialize()
    mx.amp.convert_block(net)
    import jax.numpy as jnp

    assert net.weight.data()._data.dtype == jnp.bfloat16
    out = net(mx.nd.ones((2, 3)).astype("bfloat16" if hasattr(np, "bf16")
                                        else np.float32)
              .astype(jnp.bfloat16))
    assert out.shape == (2, 4)


def test_amp_loss_scaler():
    s = mx.amp.DynamicLossScaler(init_scale=1024.0, scale_window=2)
    assert s.update_scale(True) == 512.0
    s.update_scale(False)
    assert s.update_scale(False) == 1024.0
    assert s.has_overflow([mx.nd.array([np.inf])])
    assert not s.has_overflow([mx.nd.array([1.0])])


def test_quantize_dequantize_roundtrip():
    x = mx.nd.random_normal(shape=(6, 6))
    q, mn, mxr = mx.nd.quantize_v2(x)
    assert q.dtype == np.int8
    back = mx.nd.dequantize(q, mn, mxr)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=0.05)


def test_quantized_fc_matches_fp():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 8).astype(np.float32))
    w = mx.nd.array(rng.randn(5, 8).astype(np.float32))
    q, qmin, qmax = mx.nd.quantize_v2(x)
    qw, wmin, wmax = mx.nd.quantize_v2(w)
    out, omin, omax = mx.nd.quantized_fully_connected(
        q, qw, None, qmin, qmax, wmin, wmax, no_bias=True, num_hidden=5)
    assert out.dtype == np.int32
    deq = mx.nd.dequantize(out, omin, omax).asnumpy()
    ref = x.asnumpy() @ w.asnumpy().T
    rel = np.abs(deq - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_quantize_block_calibration():
    from mxnet_tpu.contrib import quantization

    net = nn.Dense(4, in_units=3)
    net.initialize()
    data = [mx.nd.random_normal(shape=(2, 3)) for _ in range(3)]
    net, ranges = quantization.quantize_block(net, calib_data=data,
                                              num_calib_batches=2)
    assert "__input__" in ranges and "__output__" in ranges


def test_contrib_layers():
    from mxnet_tpu.gluon import contrib

    ident = contrib.nn.Identity()
    x = mx.nd.ones((2, 3))
    np.testing.assert_allclose(ident(x).asnumpy(), x.asnumpy())

    ps = contrib.nn.PixelShuffle2D(2)
    out = ps(mx.nd.random_normal(shape=(1, 8, 3, 3)))
    assert out.shape == (1, 2, 6, 6)

    sbn = contrib.nn.SyncBatchNorm(in_channels=4, num_devices=8)
    sbn.initialize()
    assert sbn(mx.nd.random_normal(shape=(2, 4))).shape == (2, 4)


def test_contrib_conv_lstm():
    from mxnet_tpu.gluon import contrib

    cell = contrib.rnn.Conv2DLSTMCell((3, 6, 6), 4)
    cell.initialize()
    outs, st = cell.unroll(2, mx.nd.ones((2, 2, 3, 6, 6)), layout="NTC",
                           merge_outputs=False)
    assert outs[0].shape == (2, 4, 6, 6)
    assert st[0].shape == (2, 4, 6, 6)


def test_sharded_checkpoint_roundtrip(tmp_path):
    from mxnet_tpu import checkpoint

    net = nn.Dense(4, in_units=3)
    net.initialize()
    tr = parallel.ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                                 {"learning_rate": 0.01},
                                 mesh=parallel.make_mesh(dp=2))
    x = np.ones((4, 3), np.float32)
    y = np.zeros((4, 4), np.float32)
    tr.step(x, y)
    w_after_1 = None
    ck = checkpoint.ShardedCheckpointer(str(tmp_path / "ckpt"),
                                        async_save=False)
    state = checkpoint.trainer_state(tr)
    ck.save(1, state)
    w_after_1 = np.asarray(tr._param_vals[0])
    tr.step(x, y)  # move past the saved state
    restored = ck.restore(1, template=checkpoint.trainer_state(tr))
    checkpoint.load_trainer_state(tr, restored)
    np.testing.assert_allclose(np.asarray(tr._param_vals[0]), w_after_1)
    assert tr._num_update == 1
    ck.close()


def test_estimator_fit():
    from mxnet_tpu.gluon.contrib import Estimator

    rng = np.random.RandomState(0)
    x = rng.randn(64, 5).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    ds = gluon.data.ArrayDataset(x, y)
    loader = gluon.data.DataLoader(ds, batch_size=16)
    net = nn.Dense(2, in_units=5)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=["acc"], trainer=trainer)
    est.fit(loader, epochs=3)
    acc = est.evaluate(loader)[0]
    assert acc[1] > 0.7


def test_detection_ops():
    iou = mx.nd.box_iou(mx.nd.array([[0, 0, 2, 2]]),
                        mx.nd.array([[1, 1, 3, 3]]))
    np.testing.assert_allclose(iou.asnumpy(), [[1.0 / 7.0]], rtol=1e-5)

    det = mx.nd.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5],
                        [0, 0.8, 0.12, 0.12, 0.52, 0.52],
                        [1, 0.7, 0.6, 0.6, 0.9, 0.9]]])
    out = mx.nd.box_nms(det, overlap_thresh=0.5, coord_start=2,
                        score_index=1, id_index=0)
    scores = out.asnumpy()[0, :, 1]
    np.testing.assert_allclose(scores, [0.9, -1.0, 0.7], rtol=1e-5)

    anchors = mx.nd.MultiBoxPrior(mx.nd.zeros((1, 3, 4, 4)),
                                  sizes=(0.5, 0.25), ratios=(1, 2))
    assert anchors.shape == (1, 4 * 4 * 3, 4)


def test_multibox_target_matching():
    anchors = mx.nd.MultiBoxPrior(mx.nd.zeros((1, 3, 4, 4)),
                                  sizes=(0.5,), ratios=(1.0,))
    lab = mx.nd.array(np.array(
        [[[0, 0.1, 0.1, 0.4, 0.4], [-1, 0, 0, 0, 0]]], np.float32))
    pred = mx.nd.array(np.random.rand(1, 3, 16).astype(np.float32))
    lt, lm, ct = mx.nd.MultiBoxTarget(anchors, lab, pred,
                                      negative_mining_ratio=3)
    ctn = ct.asnumpy()
    assert (ctn > 0).sum() == 1     # force-matched anchor
    assert (ctn == 0).sum() == 3    # 3:1 mined negatives
    assert (ctn == -1).sum() == 12  # rest ignored


def test_roi_align_values():
    # constant image → every pooled cell is that constant
    img = mx.nd.ones((1, 2, 8, 8)) * 3.0
    rois = mx.nd.array([[0, 0, 0, 7, 7]])
    out = mx.nd.roi_align(img, rois, pooled_size=(2, 2))
    np.testing.assert_allclose(out.asnumpy(), 3.0 * np.ones((1, 2, 2, 2)),
                               rtol=1e-5)


def test_visualization_summary():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize()
    out = mx.visualization.print_summary(net)
    assert "Total params" in out and "16" in out


def test_storage_memory_knobs_and_info():
    """Storage surface (reference: MXNET_GPU_MEM_POOL_* +
    gpu_memory_info): env mapping + stats introspection."""
    import subprocess
    import sys

    import mxnet_tpu as mx
    from mxnet_tpu import storage

    # env knob mapping happens before jax init in a fresh process
    code = (
        "import os\n"
        "os.environ['MXNET_TPU_MEM_FRACTION'] = '0.5'\n"
        "import mxnet_tpu\n"
        "print(os.environ.get('XLA_PYTHON_CLIENT_MEM_FRACTION'))\n")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True,
                       env={**__import__('os').environ,
                            "JAX_PLATFORMS": "cpu"})
    assert r.stdout.strip().splitlines()[-1] == "0.5", r.stderr[-300:]

    free, total = storage.memory_info(mx.cpu())
    # CPU backend exposes no stats -> (None, None); a real TPU returns
    # positive numbers.  Either way the call must not raise.
    assert (free is None) == (total is None)
    assert isinstance(storage.memory_summary(), str)


def test_rtc_pallas_module():
    """mx.rtc parity: PallasModule compiles runtime kernel source
    (reference: rtc.CudaModule over NVRTC); CudaModule shim guides to
    the TPU path."""
    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    src = """
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

def _scale(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0

def scale2(x):
    return pl.pallas_call(
        _scale, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True)(x)
"""
    mod = mx.rtc.PallasModule(src, exports=["scale2"])
    k = mod.get_kernel("scale2")
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    out = k.launch([x])
    np.testing.assert_allclose(out.asnumpy(), [2.0, 4.0, 6.0])

    with pytest.raises(mx.base.MXNetError, match="Pallas"):
        mx.rtc.CudaModule("__global__ void k() {}")
    with pytest.raises(mx.base.MXNetError):
        mx.rtc.PallasModule("x = 1", exports=["missing"])


def test_contrib_deformable_convolution_layer():
    """gluon.contrib.cnn.DeformableConvolution (reference:
    gluon/contrib/cnn/conv_layers.py): zero-init offsets make it exactly
    a regular convolution; offsets train."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution

    mx.random.seed(0)
    np.random.seed(0)
    layer = DeformableConvolution(8, kernel_size=3, padding=1,
                                  num_deformable_group=2)
    layer.initialize(init=mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(2, 4, 10, 10)
                    .astype(np.float32))
    out = layer(x)
    assert out.shape == (2, 8, 10, 10)
    # zero offsets (the init) == plain convolution with the same weights
    ref = mx.nd.Convolution(
        x, layer.weight.data(), layer.bias.data(), kernel=(3, 3),
        stride=(1, 1), pad=(1, 1), dilate=(1, 1), num_filter=8,
        num_group=1, no_bias=False)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-4,
                               atol=1e-5)
    # gradients flow to the offset branch once offsets matter
    with autograd.record():
        loss = (layer(x) * mx.nd.array(
            np.random.RandomState(1).rand(2, 8, 10, 10)
            .astype(np.float32))).sum()
    loss.backward()
    gw = layer.offset_weight.grad().asnumpy()
    assert np.isfinite(gw).all() and np.abs(gw).sum() > 0


def test_contrib_data_sampler_and_text():
    """gluon.contrib.data: IntervalSampler index pattern and the local
    CharTokenDataset LM windows + DataLoader integration."""
    import numpy as np

    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.data import (CharTokenDataset,
                                              IntervalSampler)

    s = IntervalSampler(10, 3)
    idx = list(s)
    assert idx[:4] == [0, 3, 6, 9] and len(idx) == len(s) == 10
    assert sorted(idx) == list(range(10))
    assert list(IntervalSampler(10, 3, rollover=False)) == [0, 3, 6, 9]

    text = "hello tpu world, " * 40
    ds = CharTokenDataset(text, seq_len=16)
    x0, y0 = ds[0]
    assert x0.shape == (16,) and y0.shape == (16,)
    # target is input shifted by one token
    assert (x0[1:] == y0[:-1]).all()
    decoded = "".join(ds.inv_vocab[int(i)] for i in x0)
    assert decoded == text[:16]
    loader = gluon.data.DataLoader(ds, batch_size=4,
                                   sampler=IntervalSampler(len(ds), 2))
    xb, yb = next(iter(loader))
    assert xb.shape == (4, 16)


def test_parse_log_tool():
    """tools/parse_log.py parses the fit/Speedometer log formats into
    an epoch table (reference: tools/parse_log.py)."""
    import os
    import subprocess
    import sys
    import tempfile

    ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log = ("INFO Epoch[0] Batch [10-20]\tSpeed: 1000.00 samples/sec\n"
           "INFO Epoch[0] Train-accuracy=0.600000\n"
           "INFO Epoch[0] Time cost=12.300\n"
           "INFO Epoch[1] Train-accuracy=0.800000\n")
    with tempfile.NamedTemporaryFile("w", suffix=".log",
                                     delete=False) as f:
        f.write(log)
        path = f.name
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"),
         path, "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    os.unlink(path)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0] == "epoch,speed,time,train-accuracy"
    assert lines[1].startswith("0,1000.0,12.3,0.6")
    assert lines[2].startswith("1,,")
