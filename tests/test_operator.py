"""Registry-wide operator verification sweep.

Reference parity: tests/python/unittest/test_operator.py (SURVEY.md §4) —
the reference's op-level oracle is per-op forward checks plus
check_numeric_gradient.  Here the sweep is *registry-driven*: every
canonical registered op must either carry a spec in SPECS (forward smoke
+ optional numpy reference + optional finite-difference gradient check)
or a justified entry in SKIP.  A coverage test enforces the invariant, so
new ops cannot land untested.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.registry import all_ops
from mxnet_tpu.test_utils import check_numeric_gradient

_RNG = np.random.RandomState(7)


def N(*s):
    """Standard normal float32 array."""
    return _RNG.randn(*s).astype(np.float32)


def U(lo, hi, *s):
    return _RNG.uniform(lo, hi, s).astype(np.float32)


def I(hi, *s):
    return _RNG.randint(0, hi, s).astype(np.int32)


class Spec:
    def __init__(self, args, kwargs=None, fd=False, fd_argnums=None,
                 ref=None, rtol=2e-2, atol=5e-3):
        # FD tolerance floor: the numeric side runs in f32, where the
        # central difference carries ~|f|*eps_mach/eps ≈ 1e-3 absolute
        # noise — tighter atol would flag exact analytic gradients.
        self.args = args           # list of np arrays (or scalars)
        self.kwargs = kwargs or {}
        self.fd = fd               # finite-difference gradient check
        self.fd_argnums = fd_argnums
        self.ref = ref             # numpy forward oracle
        self.rtol, self.atol = rtol, atol


def _unary(dom=None, fd=True, ref=None):
    x = N(2, 3) if dom is None else U(dom[0], dom[1], 2, 3)
    return Spec([x], fd=fd, ref=ref)


def _binary(fd=True, positive=False, ref=None):
    a = U(0.5, 1.5, 2, 3) if positive else N(2, 3)
    b = U(0.5, 1.5, 1, 3) if positive else N(1, 3)
    return Spec([a, b], fd=fd, ref=ref)


def _reduce(fd=True, **kw):
    return Spec([N(2, 3, 4)], kw, fd=fd)


def _opt(n_states, mp=False, **kw):
    """Optimizer update op: weight, grad, states..., [weight32]."""
    args = [N(5), N(5)] + [np.zeros(5, np.float32)] * n_states
    if mp:
        args.append(args[0].astype(np.float32).copy())
    kw.setdefault("lr", 0.1)
    return Spec(args, kw)


def _rand(shape_kw=True, **kw):
    if shape_kw:
        kw.setdefault("shape", (3, 4))
    return Spec([], kw)


SPECS = {
    # -- elementwise unary ----------------------------------------------------
    "abs": _unary(ref=np.abs),
    "negative": _unary(ref=np.negative),
    "square": _unary(ref=np.square),
    "exp": _unary(ref=np.exp),
    "expm1": _unary(ref=np.expm1),
    "sin": _unary(ref=np.sin),
    "cos": _unary(ref=np.cos),
    "tan": _unary(dom=(-1.0, 1.0), ref=np.tan),
    "sinh": _unary(ref=np.sinh),
    "cosh": _unary(ref=np.cosh),
    "tanh": _unary(ref=np.tanh),
    "arcsin": _unary(dom=(-0.9, 0.9), ref=np.arcsin),
    "arccos": _unary(dom=(-0.9, 0.9), ref=np.arccos),
    "arctan": _unary(ref=np.arctan),
    "arcsinh": _unary(ref=np.arcsinh),
    "arccosh": _unary(dom=(1.1, 3.0), ref=np.arccosh),
    "arctanh": _unary(dom=(-0.9, 0.9), ref=np.arctanh),
    "sqrt": _unary(dom=(0.2, 2.0), ref=np.sqrt),
    "rsqrt": _unary(dom=(0.2, 2.0), ref=lambda x: 1 / np.sqrt(x)),
    "cbrt": _unary(dom=(0.2, 2.0), ref=np.cbrt),
    "rcbrt": _unary(dom=(0.2, 2.0), ref=lambda x: 1 / np.cbrt(x)),
    "log": _unary(dom=(0.2, 3.0), ref=np.log),
    "log2": _unary(dom=(0.2, 3.0), ref=np.log2),
    "log10": _unary(dom=(0.2, 3.0), ref=np.log10),
    "log1p": _unary(dom=(0.2, 3.0), ref=np.log1p),
    "reciprocal": _unary(dom=(0.5, 2.0), ref=lambda x: 1 / x),
    "erf": _unary(),
    "erfinv": _unary(dom=(-0.8, 0.8)),
    "gamma": _unary(dom=(1.0, 3.0)),
    "gammaln": _unary(dom=(1.0, 3.0)),
    "digamma": _unary(dom=(1.0, 3.0)),
    "degrees": _unary(ref=np.degrees),
    "radians": _unary(ref=np.radians),
    "sigmoid": _unary(),
    "relu": _unary(ref=lambda x: np.maximum(x, 0)),
    "gelu": _unary(),
    "silu": _unary(),
    "softrelu": _unary(),
    "softsign": _unary(ref=lambda x: x / (1 + np.abs(x))),
    "hard_sigmoid": _unary(),
    "smooth_l1": _unary(),
    "sign": _unary(fd=False, ref=np.sign),
    "ceil": _unary(fd=False, ref=np.ceil),
    "floor": _unary(fd=False, ref=np.floor),
    "rint": _unary(fd=False, ref=np.rint),
    "round": _unary(fd=False),
    "fix": _unary(fd=False, ref=np.trunc),
    "logical_not": _unary(fd=False),
    "isnan": _unary(fd=False, ref=np.isnan),
    "isinf": _unary(fd=False, ref=np.isinf),
    "isfinite": _unary(fd=False, ref=np.isfinite),
    "clip": Spec([N(2, 3)], {"a_min": -0.5, "a_max": 0.5}, fd=True,
                 ref=lambda x: np.clip(x, -0.5, 0.5)),
    "_copy": _unary(fd=True, ref=lambda x: x),
    "BlockGrad": _unary(fd=False, ref=lambda x: x),
    "Cast": Spec([N(2, 3)], {"dtype": "float64"}, fd=False),
    "amp_cast": Spec([N(2, 3)], {"dtype": "float32"}, fd=False),
    "Cast_storage": Spec([N(2, 3)], fd=False),
    # -- binary / broadcast ---------------------------------------------------
    "add": _binary(ref=np.add),
    "broadcast_minus": _binary(ref=np.subtract),
    "broadcast_mul": _binary(ref=np.multiply),
    "broadcast_div": _binary(positive=True, ref=np.divide),
    "broadcast_maximum": _binary(ref=np.maximum),
    "broadcast_minimum": _binary(ref=np.minimum),
    "broadcast_power": _binary(positive=True, ref=np.power),
    "broadcast_hypot": _binary(ref=np.hypot),
    "broadcast_mod": _binary(fd=False, positive=True, ref=np.fmod),
    "broadcast_equal": _binary(fd=False),
    "broadcast_not_equal": _binary(fd=False),
    "broadcast_greater": _binary(fd=False),
    "broadcast_greater_equal": _binary(fd=False),
    "broadcast_lesser": _binary(fd=False),
    "broadcast_lesser_equal": _binary(fd=False),
    "broadcast_logical_and": _binary(fd=False),
    "broadcast_logical_or": _binary(fd=False),
    "broadcast_logical_xor": _binary(fd=False),
    # -- reductions -----------------------------------------------------------
    "sum": _reduce(axis=1),
    "mean": _reduce(axis=1),
    "prod": Spec([U(0.5, 1.5, 2, 3, 4)], {"axis": 2}, fd=True),
    "nansum": _reduce(axis=1),
    "nanprod": Spec([U(0.5, 1.5, 2, 3, 4)], {"axis": 2}, fd=True),
    "max": _reduce(axis=1),
    "min": _reduce(axis=1),
    "norm": _reduce(axis=1),
    "cumsum": Spec([N(2, 4)], {"axis": 1}, fd=True,
                   ref=lambda x: np.cumsum(x, 1)),
    "cumprod": Spec([U(0.5, 1.5, 2, 4)], {"axis": 1}, fd=True,
                    ref=lambda x: np.cumprod(x, 1)),
    "argmax": Spec([N(2, 5)], {"axis": 1}, ref=lambda x: np.argmax(x, 1)),
    "argmin": Spec([N(2, 5)], {"axis": 1}, ref=lambda x: np.argmin(x, 1)),
    "argmax_channel": Spec([N(2, 5)], ref=lambda x: np.argmax(x, 1)),
    "argsort": Spec([N(2, 5)], ref=lambda x: np.argsort(x, 1)),
    "sort": Spec([N(2, 5)], ref=lambda x: np.sort(x, 1)),
    "topk": Spec([N(2, 5)], {"k": 2}),
    "L2Normalization": Spec([N(2, 4)], fd=True),
    "softmax": Spec([N(2, 5)], {"axis": -1}, fd=True),
    "log_softmax": Spec([N(2, 5)], {"axis": -1}, fd=True),
    "softmin": Spec([N(2, 5)], {"axis": -1}, fd=True),
    # -- shape manipulation ---------------------------------------------------
    "Reshape": Spec([N(2, 6)], {"shape": (3, 4)}, fd=True,
                    ref=lambda x: x.reshape(3, 4)),
    "reshape_like": Spec([N(2, 6), N(3, 4)], fd=True, fd_argnums=[0],
                         ref=lambda x, y: x.reshape(3, 4)),
    "Flatten": Spec([N(2, 3, 4)], fd=True,
                    ref=lambda x: x.reshape(2, 12)),
    "expand_dims": Spec([N(2, 3)], {"axis": 1},
                        ref=lambda x: x[:, None]),
    "squeeze": Spec([N(2, 1, 3)], {"axis": 1},
                    ref=lambda x: x[:, 0]),
    "transpose": Spec([N(2, 3, 4)], {"axes": (2, 0, 1)}, fd=True,
                      ref=lambda x: x.transpose(2, 0, 1)),
    "SwapAxis": Spec([N(2, 3, 4)], {"dim1": 0, "dim2": 2},
                     ref=lambda x: x.swapaxes(0, 2)),
    "flip": Spec([N(2, 3)], {"axis": 1}, ref=lambda x: x[:, ::-1]),
    "tile": Spec([N(2, 3)], {"reps": (2, 2)},
                 ref=lambda x: np.tile(x, (2, 2))),
    "repeat": Spec([N(2, 3)], {"repeats": 2, "axis": 1},
                   ref=lambda x: np.repeat(x, 2, 1)),
    "stack": Spec([N(2, 3), N(2, 3)], {"axis": 0}, fd=True,
                  ref=lambda a, b: np.stack([a, b])),
    "Concat": Spec([N(2, 3), N(2, 3)], {"dim": 1}, fd=True,
                   ref=lambda a, b: np.concatenate([a, b], 1)),
    "SliceChannel": Spec([N(2, 6)], {"num_outputs": 2, "axis": 1},
                         fd=True),
    "slice": Spec([N(4, 5)], {"begin": (1, 0), "end": (3, 4)}, fd=True,
                  ref=lambda x: x[1:3, 0:4]),
    "slice_axis": Spec([N(4, 5)], {"axis": 1, "begin": 1, "end": 4},
                       fd=True, ref=lambda x: x[:, 1:4]),
    "slice_like": Spec([N(4, 5), N(2, 3)], fd=True, fd_argnums=[0],
                       ref=lambda x, y: x[:2, :3]),
    "broadcast_to": Spec([N(1, 3)], {"shape": (4, 3)},
                         ref=lambda x: np.broadcast_to(x, (4, 3))),
    "broadcast_axes": Spec([N(1, 3)], {"axis": 0, "size": 4}),
    "broadcast_like": Spec([N(1, 3), N(4, 3)], fd_argnums=[0],
                           ref=lambda x, y: np.broadcast_to(x, (4, 3))),
    "Pad": Spec([N(1, 2, 3, 3)],
                {"mode": "constant",
                 "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}, fd=True),
    "depth_to_space": Spec([N(1, 4, 2, 2)], {"block_size": 2}),
    "space_to_depth": Spec([N(1, 1, 4, 4)], {"block_size": 2}),
    "Crop": Spec([N(1, 2, 6, 6)], {"offset": (1, 1), "h_w": (4, 4),
                                   "num_args": 1}),
    "shape_array": Spec([N(2, 3)], fd=False,
                        ref=lambda x: np.array([2, 3])),
    "size_array": Spec([N(2, 3)], fd=False, ref=lambda x: np.array([6])),
    "diag": Spec([N(4, 4)], ref=np.diag),
    "ones_like": Spec([N(2, 3)], fd=False, ref=np.ones_like),
    "zeros_like": Spec([N(2, 3)], fd=False, ref=np.zeros_like),
    "full_like": Spec([N(2, 3)], {"fill_value": 2.5}, fd=False,
                      ref=lambda x: np.full_like(x, 2.5)),
    "where": Spec([(N(2, 3) > 0).astype(np.float32), N(2, 3), N(2, 3)],
                  fd=True, fd_argnums=[1, 2]),
    # -- indexing -------------------------------------------------------------
    "take": Spec([N(5, 3), I(5, 4).astype(np.float32)], fd=True,
                 fd_argnums=[0]),
    "batch_take": Spec([N(3, 4), I(4, 3).astype(np.float32)], fd=False),
    "pick": Spec([N(3, 4), I(4, 3).astype(np.float32)], fd=True,
                 fd_argnums=[0]),
    "one_hot": Spec([I(4, 3).astype(np.float32)], {"depth": 4},
                    fd=False),
    "Embedding": Spec([I(5, 4).astype(np.float32), N(5, 3)], fd=True,
                      fd_argnums=[1]),
    "gather_nd": Spec([N(4, 3), np.array([[0, 2], [1, 0]],
                                         np.float32).T], fd=False),
    "scatter_nd": Spec([N(2), np.array([[0, 2]], np.float32),
                        ], {"shape": (4,)}, fd=False),
    "_contrib_index_copy": Spec(
        [N(5, 3), np.array([1, 3], np.float32), N(2, 3)], fd=False),
    "index_add": Spec([N(5, 3), np.array([1, 3], np.float32), N(2, 3)],
                      fd=True, fd_argnums=[0, 2]),
    "_contrib_boolean_mask": Spec(
        [N(4, 3), np.array([1, 0, 1, 1], np.float32)], fd=False),
    "_contrib_index_array": Spec([N(2, 3)], fd=False),
    "_contrib_allclose": Spec(
        [(_ac := N(2, 3)), _ac.copy()], fd=False,
        ref=lambda a, b: np.float32(np.allclose(a, b))),  # close -> 1.0
    "SequenceMask": Spec([N(4, 2, 3), np.array([2, 4], np.float32)],
                         {"use_sequence_length": True}, fd=True,
                         fd_argnums=[0]),
    "SequenceLast": Spec([N(4, 2, 3), np.array([2, 4], np.float32)],
                         {"use_sequence_length": True}, fd=True,
                         fd_argnums=[0]),
    "SequenceReverse": Spec([N(4, 2, 3), np.array([2, 4], np.float32)],
                            {"use_sequence_length": True}, fd=True,
                            fd_argnums=[0]),
    "ravel_multi_index": Spec([np.array([[1, 2], [0, 1]], np.float32)],
                              {"shape": (3, 4)}, fd=False),
    "unravel_index": Spec([np.array([5, 7], np.float32)],
                          {"shape": (3, 4)}, fd=False),
    "random_shuffle": Spec([N(6)], fd=False),
    # -- linear algebra -------------------------------------------------------
    "dot": Spec([N(3, 4), N(4, 2)], fd=True,
                ref=lambda a, b: a @ b, rtol=2e-2),
    "batch_dot": Spec([N(2, 3, 4), N(2, 4, 2)], fd=True,
                      ref=lambda a, b: a @ b, rtol=2e-2),
    "linalg_gemm": Spec([N(3, 4), N(4, 2), N(3, 2)], fd=True,
                        rtol=2e-2),
    "linalg_gemm2": Spec([N(3, 4), N(4, 2)], fd=True, rtol=2e-2),
    "linalg_syrk": Spec([N(3, 4)], fd=True, rtol=2e-2),
    "det": Spec([N(3, 3) + 3 * np.eye(3, dtype=np.float32)], fd=True,
                ref=np.linalg.det, rtol=5e-2, atol=5e-2),
    "inverse": Spec([N(3, 3) + 3 * np.eye(3, dtype=np.float32)],
                    fd=True, ref=np.linalg.inv, rtol=2e-2),
    "linalg_potrf": Spec([np.array(np.eye(3) * 2 + 0.5,
                                   np.float32)], fd=False),
    "linalg_potri": Spec([np.array(np.eye(3) * 2, np.float32)],
                         fd=False),
    "linalg_slogdet": Spec([N(3, 3) + 3 * np.eye(3, dtype=np.float32)],
                           fd=False),
    "linalg_sumlogdiag": Spec([np.abs(N(1, 3, 3)) + np.eye(
        3, dtype=np.float32)], fd=False),
    "linalg_extractdiag": Spec([N(1, 3, 3)], fd=False),
    "linalg_extracttrian": Spec([N(1, 3, 3)], fd=False),
    "linalg_makediag": Spec([N(1, 3)], fd=False),
    "linalg_maketrian": Spec([N(1, 6)], fd=False),
    "linalg_svd": Spec([N(3, 4)], fd=False),
    "linalg_syevd": Spec([np.array(np.eye(3) + 0.1, np.float32)],
                         fd=False),
    "linalg_gelqf": Spec([N(3, 4)], fd=False),
    "linalg_trmm": Spec([np.tril(N(3, 3)).astype(np.float32),
                         N(1, 3, 3)], fd=False),
    "linalg_trsm": Spec([np.tril(N(1, 3, 3) + 2 * np.eye(
        3, dtype=np.float32)).astype(np.float32), N(1, 3, 3)],
        fd=False),
    # -- neural ---------------------------------------------------------------
    "FullyConnected": Spec([N(2, 4), N(3, 4), N(3)],
                           {"num_hidden": 3}, fd=True, rtol=2e-2),
    "Activation": Spec([N(2, 3)], {"act_type": "tanh"}, fd=True),
    "LeakyReLU": Spec([N(2, 3)], {"act_type": "leaky", "slope": 0.1},
                      fd=True),
    "Convolution": Spec([N(1, 2, 5, 5), N(3, 2, 3, 3), N(3)],
                        {"kernel": (3, 3), "num_filter": 3}, fd=True,
                        rtol=3e-2, atol=2e-2),
    "Deconvolution": Spec([N(1, 3, 3, 3), N(3, 2, 3, 3), N(2)],
                          {"kernel": (3, 3), "num_filter": 2}, fd=True,
                          rtol=3e-2, atol=2e-2),
    "Pooling": Spec([N(1, 2, 4, 4)],
                    {"kernel": (2, 2), "stride": (2, 2),
                     "pool_type": "avg"}, fd=True),
    "BatchNorm": Spec([N(2, 3, 4, 4), np.ones(3, np.float32),
                       np.zeros(3, np.float32), np.zeros(3, np.float32),
                       np.ones(3, np.float32)], fd=False),
    "LayerNorm": Spec([N(2, 5), np.ones(5, np.float32),
                       np.zeros(5, np.float32)], fd=True),
    "RMSNorm": Spec([N(2, 5), np.ones(5, np.float32)], fd=True),
    "InstanceNorm": Spec([N(2, 3, 4, 4), np.ones(3, np.float32),
                          np.zeros(3, np.float32)], fd=True,
                         fd_argnums=[0], atol=2e-2),
    "GroupNorm": Spec([N(2, 4, 3, 3), np.ones(4, np.float32),
                       np.zeros(4, np.float32)], {"num_groups": 2},
                      fd=True, fd_argnums=[0], atol=2e-2),
    "LRN": Spec([N(1, 4, 3, 3)], {"nsize": 3}, fd=True),
    "Dropout": Spec([N(2, 3)], {"p": 0.5}, fd=False,
                    ref=lambda x: x),  # predict mode = identity
    "SoftmaxOutput": Spec([N(3, 4), I(4, 3).astype(np.float32)],
                          fd=False),
    "softmax_cross_entropy": Spec([N(3, 4), I(4, 3).astype(np.float32)],
                                  fd=False),
    "CTCLoss": Spec([N(2, 5, 6), np.array([[1, 2], [3, 0]],
                                          np.float32)], fd=False),
    "UpSampling": Spec([N(1, 2, 3, 3)],
                       {"scale": 2, "sample_type": "nearest"},
                       fd=False),
    "BilinearResize2D": Spec([N(1, 2, 4, 4)],
                             {"height": 6, "width": 6}, fd=True),
    # -- attention / interleaved ----------------------------------------------
    "_contrib_interleaved_matmul_selfatt_qk": Spec(
        [N(4, 2, 3 * 8)], {"heads": 2}, fd=False),
    "_contrib_interleaved_matmul_selfatt_valatt": Spec(
        [N(4, 2, 3 * 8), np.abs(N(2 * 2, 4, 4))], {"heads": 2},
        fd=False),
    # -- vision / detection ---------------------------------------------------
    "ROIPooling": Spec(
        [N(1, 2, 8, 8),
         np.array([[0, 0, 0, 4, 4], [0, 1, 1, 6, 6]], np.float32)],
        {"pooled_size": (2, 2), "spatial_scale": 1.0}, fd=False),
    "ROIAlign": Spec(
        [N(1, 2, 8, 8),
         np.array([[0, 0, 0, 4, 4], [0, 1, 1, 6, 6]], np.float32)],
        {"pooled_size": (2, 2), "spatial_scale": 1.0}, fd=False),
    "_contrib_PSROIPooling": Spec(
        [N(1, 8, 8, 8), np.array([[0, 1, 1, 6, 6]], np.float32)],
        {"output_dim": 2, "pooled_size": 2}, fd=False),
    "BilinearSampler": Spec([N(1, 2, 5, 5), U(-0.9, 0.9, 1, 2, 4, 4)],
                            fd=False),
    "GridGenerator": Spec(
        [np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
        {"transform_type": "affine", "target_shape": (4, 4)}, fd=False),
    "SpatialTransformer": Spec(
        [N(1, 2, 6, 6), np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
        {"target_shape": (4, 4), "transform_type": "affine",
         "sampler_type": "bilinear"}, fd=False),
    "Correlation": Spec([N(1, 2, 6, 6), N(1, 2, 6, 6)], fd=False),
    "DeformableConvolution": Spec(
        [N(1, 2, 5, 5), np.zeros((1, 18, 3, 3), np.float32),
         N(2, 2, 3, 3)],
        {"kernel": (3, 3), "num_filter": 2}, fd=False),
    "MultiBoxPrior": Spec([N(1, 2, 4, 4)],
                          {"sizes": (0.5, 0.25), "ratios": (1, 2)},
                          fd=False),
    "MultiBoxTarget": Spec(
        [np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]],
                  np.float32),
         np.array([[[0, 0.1, 0.1, 0.45, 0.45]]], np.float32),
         np.abs(N(1, 2, 2))], fd=False),
    "MultiBoxDetection": Spec(
        [np.abs(N(1, 2, 2)), N(1, 8),
         np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]],
                  np.float32)], fd=False),
    "_contrib_box_iou": Spec(
        [np.array([[0, 0, 2, 2]], np.float32),
         np.array([[1, 1, 3, 3]], np.float32)], fd=False),
    "_contrib_box_nms": Spec(
        [np.array([[[0, 0.9, 0, 0, 2, 2], [0, 0.8, 1, 1, 3, 3]]],
                  np.float32)], fd=False),
    "_contrib_bipartite_matching": Spec(
        [np.abs(N(1, 2, 3))], {"threshold": 0.1}, fd=False),
    "MultiProposal": Spec(
        [np.abs(N(1, 6, 4, 4)), N(1, 12, 4, 4),
         np.tile(np.array([[64, 64, 1.0]], np.float32), (1, 1))],
        {"rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4,
         "feature_stride": 16, "scales": (8,), "ratios": (0.5, 1, 2),
         "rpn_min_size": 1}, fd=False),
    # -- quantization ---------------------------------------------------------
    "_contrib_quantize": Spec(
        [N(2, 3), np.array([-1.0], np.float32),
         np.array([1.0], np.float32)], fd=False),
    "_contrib_quantize_v2": Spec([N(2, 3)], fd=False),
    "_contrib_dequantize": Spec(
        [I(127, 2, 3).astype(np.int8), np.array([-1.0], np.float32),
         np.array([1.0], np.float32)], fd=False),
    "_contrib_requantize": Spec(
        [(I(1000, 2, 3) - 500).astype(np.int32),
         np.array([-10.0], np.float32), np.array([10.0], np.float32)],
        fd=False),
    "_contrib_quantized_fully_connected": Spec(
        [I(127, 2, 4).astype(np.int8), I(127, 3, 4).astype(np.int8),
         I(127, 3).astype(np.int8),
         np.array([-1.0], np.float32), np.array([1.0], np.float32),
         np.array([-1.0], np.float32), np.array([1.0], np.float32),
         np.array([-1.0], np.float32), np.array([1.0], np.float32)],
        {"num_hidden": 3}, fd=False),
    "_contrib_quantized_conv": Spec(
        [I(127, 1, 2, 5, 5).astype(np.int8),
         I(127, 3, 2, 3, 3).astype(np.int8),
         I(127, 3).astype(np.int8),
         np.array([-1.0], np.float32), np.array([1.0], np.float32),
         np.array([-1.0], np.float32), np.array([1.0], np.float32),
         np.array([-1.0], np.float32), np.array([1.0], np.float32)],
        {"kernel": (3, 3), "num_filter": 3}, fd=False),
    # -- loss heads -----------------------------------------------------------
    "LinearRegressionOutput": Spec([N(3, 2), N(3, 2)], fd=False),
    "MAERegressionOutput": Spec([N(3, 2), N(3, 2)], fd=False),
    "LogisticRegressionOutput": Spec([N(3, 2),
                                      (N(3, 2) > 0).astype(np.float32)],
                                     fd=False),
    "SVMOutput": Spec([N(3, 4), I(4, 3).astype(np.float32)], fd=False),
    "MakeLoss": Spec([np.abs(N(3, 2))], fd=False),
    "all_finite": Spec([N(2, 3)], fd=False,
                       ref=lambda x: np.array(1.0, np.float32)),
    "multi_all_finite": Spec([N(2, 3), N(2, 3)], fd=False),
    # -- optimizer updates ----------------------------------------------------
    "sgd_update": _opt(0),
    "sgd_mom_update": _opt(1, momentum=0.9),
    "nag_mom_update": _opt(1, momentum=0.9),
    "adam_update": _opt(2),
    "adamw_update": _opt(2),
    "rmsprop_update": _opt(1),
    "rmspropalex_update": _opt(3),
    "ftrl_update": _opt(2),
    "signsgd_update": _opt(0),
    "signum_update": _opt(1, momentum=0.9),
    "adagrad_update": _opt(1),
    "lars_update": _opt(1, momentum=0.9, eta=0.01),
    "mp_lars_update": _opt(1, mp=True, momentum=0.9, eta=0.01),
    "ftml_update": _opt(3, t=1),
    "adadelta_update": Spec([N(5), N(5), np.zeros(5, np.float32),
                             np.zeros(5, np.float32)], {"rho": 0.9}),
    "lamb_update_phase1": Spec([N(5), N(5), np.zeros(5, np.float32),
                                np.zeros(5, np.float32)], {"t": 1}),
    "lamb_update_phase2": Spec([N(5), N(5),
                                np.array(1.0, np.float32),
                                np.array(1.0, np.float32)],
                               {"lr": 0.1}),
    "mp_sgd_update": _opt(0, mp=True),
    "mp_sgd_mom_update": _opt(1, mp=True, momentum=0.9),
    "mp_nag_mom_update": _opt(1, mp=True, momentum=0.9),
    "mp_adam_update": _opt(2, mp=True),
    "mp_lamb_update_phase1": Spec(
        [N(5), N(5), np.zeros(5, np.float32), np.zeros(5, np.float32),
         np.zeros(5, np.float32) + 1.0], {"t": 1}),
    # -- random ---------------------------------------------------------------
    "random_uniform": _rand(),
    "normal": _rand(),
    "randint": _rand(low=0, high=10),
    "bernoulli": _rand(p=0.3),
    "exponential": _rand(lam=2.0),
    "poisson": _rand(lam=3.0),
    "negative_binomial": _rand(k=3, p=0.4),
    "generalized_negative_binomial": _rand(mu=2.0, alpha=0.5),
    "gamma_sample": _rand(alpha=2.0, beta=1.0),
    "multinomial": Spec([np.array([[0.2, 0.3, 0.5]], np.float32)],
                        {"shape": (4,)}, fd=False),
    "sample_uniform": Spec([np.zeros(2, np.float32),
                            np.ones(2, np.float32)], {"shape": (3,)},
                           fd=False),
    "sample_normal": Spec([np.zeros(2, np.float32),
                           np.ones(2, np.float32)], {"shape": (3,)},
                          fd=False),
    # APPEND new specs at the END: Spec inputs draw from one shared
    # sequential RNG stream, so inserting mid-dict shifts every later
    # op's inputs (and FD checks are tolerance-marginal)
    "_sym_index": Spec(
        [N(4, 5)],
        {"index_spec": [["s", None, 3, None], ["i", 1]]}, fd=True,
        ref=lambda x: x[:3, 1]),
}

SKIP = {
    # covered by dedicated suites
    "RNN": "fused RNN op covered end-to-end in tests/test_rnn.py",
    "Custom": "opaque host op; covered in tests/test_autograd.py",
    "scaled_dot_product_attention":
        "covered in tests/test_parallel.py vs dense/ring/flash",
    "multi_head_attention": "covered in tests/test_parallel.py + BERT",
    "Embedding_like": "alias surface",
    "MoEFFN_op": "MoE dispatch/combine covered vs oracle + ep-sharded "
                 "step in tests/test_parallel.py (moe suite)",
    "scan_transformer_encoder":
        "lax.scan trunk equivalence-tested (fwd+grads) vs the "
        "unstacked TransformerEncoder in tests/test_model_zoo.py",
}


def _canonical_ops():
    # one entry per distinct op function, keyed by its primary name
    prim = {}
    for n, d in sorted(all_ops().items()):
        prim.setdefault(d.fn, d.name if d.name in all_ops() else n)
    return sorted(set(prim.values()))


def test_registry_fully_covered():
    """Every canonical op must have a spec or a justified skip — new ops
    cannot land untested (reference: the per-op sweep culture of
    tests/python/unittest/test_operator.py)."""
    missing = [n for n in _canonical_ops()
               if n not in SPECS and n not in SKIP]
    assert not missing, (
        f"ops registered without a test spec (add to SPECS or SKIP "
        f"with a reason): {missing}")


def _run_op(name, spec):
    fn = getattr(nd, name)
    args = [nd.array(a) if isinstance(a, np.ndarray) else a
            for a in spec.args]
    out = fn(*args, **spec.kwargs)
    return out


@pytest.mark.parametrize("name", sorted(SPECS))
def test_op_forward(name):
    spec = SPECS[name]
    out = _run_op(name, spec)
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        a = o.asnumpy()
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), f"{name}: non-finite output"
    if spec.ref is not None:
        expect = spec.ref(*[np.asarray(a) for a in spec.args])
        np.testing.assert_allclose(
            outs[0].asnumpy().astype(np.float64),
            np.asarray(expect).astype(np.float64),
            rtol=1e-4, atol=1e-5, err_msg=f"{name} vs numpy")


@pytest.mark.parametrize(
    "name", sorted(n for n, s in SPECS.items() if s.fd))
def test_op_gradient(name):
    """Finite-difference oracle over the registered op's autograd path
    (reference: check_numeric_gradient in test_operator.py)."""
    spec = SPECS[name]

    def fn(*arrs):
        return getattr(nd, name)(*arrs, **spec.kwargs)

    check_numeric_gradient(fn, [np.asarray(a) for a in spec.args],
                           rtol=spec.rtol, atol=spec.atol,
                           argnums=spec.fd_argnums)


# -- MakeLoss normalization semantics (reference: make_loss.cc) ---------------

def test_make_loss_normalization_modes():
    from mxnet_tpu import autograd

    data = np.array([[0.5, 0.0], [1.5, 2.0], [0.0, 0.25]], np.float32)

    def grad_of(**kw):
        x = nd.array(data.copy())
        x.attach_grad()
        with autograd.record():
            y = nd.MakeLoss(x, **kw)
        y.backward()
        return x.grad.asnumpy()

    np.testing.assert_allclose(grad_of(normalization="null", grad_scale=2.0),
                               np.full_like(data, 2.0))
    np.testing.assert_allclose(grad_of(normalization="batch", grad_scale=2.0),
                               np.full_like(data, 2.0 / 3.0), rtol=1e-6)
    # 4 elements above valid_thresh=0.1 -> scale / 4
    np.testing.assert_allclose(
        grad_of(normalization="valid", grad_scale=2.0, valid_thresh=0.1),
        np.full_like(data, 0.5), rtol=1e-6)
    with pytest.raises(ValueError):
        nd.MakeLoss(nd.array(data), normalization="bogus")


def test_make_loss_valid_f16_large_count():
    """f16 loss with >65504 valid elements: the normalizing division must
    run in f32 (an f16 denominator overflows to inf → zero gradient)."""
    from mxnet_tpu import autograd

    x = nd.array(np.ones((256, 512), np.float16), dtype="float16")
    x.attach_grad()
    with autograd.record():
        y = nd.MakeLoss(x, normalization="valid", valid_thresh=0.5)
    y.backward()
    g = x.grad.asnumpy()
    expect = np.float16(1.0 / (256 * 512))
    assert g.dtype == np.float16
    assert np.all(g > 0), "gradient flushed to zero"
    np.testing.assert_allclose(g, np.full_like(g, expect), rtol=1e-2)


def test_batchnorm_fused_vjp_matches_oracle():
    """The bandwidth-optimal BN custom_vjp (fwd sum/sumsq single pass,
    bwd two passes) must match the textbook gradients exactly."""
    from mxnet_tpu import autograd, nd

    rs = np.random.RandomState(7)
    x = rs.randn(4, 3, 5, 5).astype("float32")
    gamma = rs.rand(3).astype("float32") + 0.5
    beta = rs.randn(3).astype("float32")
    eps = 1e-5

    xn, gn, bn = nd.array(x), nd.array(gamma), nd.array(beta)
    mm, mv = nd.zeros(3), nd.ones(3)
    for p in (xn, gn, bn):
        p.attach_grad()
    with autograd.record():
        out = nd.BatchNorm(xn, gn, bn, mm, mv, fix_gamma=False, eps=eps)
        ((out * out).sum()).backward()

    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    xhat = (x - mean[None, :, None, None]) / \
        np.sqrt(var + eps)[None, :, None, None]
    o = gamma[None, :, None, None] * xhat + beta[None, :, None, None]
    dy = 2 * o
    n = x.shape[0] * x.shape[2] * x.shape[3]
    sum_dy = dy.sum(axis=(0, 2, 3))
    sum_dy_xhat = (dy * xhat).sum(axis=(0, 2, 3))
    dx = (gamma / np.sqrt(var + eps))[None, :, None, None] * (
        dy - sum_dy[None, :, None, None] / n
        - xhat * sum_dy_xhat[None, :, None, None] / n)
    np.testing.assert_allclose(out.asnumpy(), o, atol=1e-5)
    np.testing.assert_allclose(xn.grad.asnumpy(), dx, atol=1e-4)
    np.testing.assert_allclose(gn.grad.asnumpy(), sum_dy_xhat, rtol=1e-4)
    np.testing.assert_allclose(bn.grad.asnumpy(), sum_dy, rtol=1e-4)


def test_batchnorm_bf16_stats_are_f32_quality():
    """bf16 activations: stats must accumulate in f32 (reference keeps BN
    stats fp32) — a bf16-accumulated mean over 2^14 elements would be off
    by O(1e-2)."""
    import jax.numpy as jnp

    from mxnet_tpu import nd

    rs = np.random.RandomState(3)
    x = (rs.randn(64, 4, 16, 16) + 5.0).astype("float32")
    out, mean, var = nd.BatchNorm(
        nd.array(x).astype("bfloat16"), nd.ones(4), nd.zeros(4),
        nd.zeros(4), nd.ones(4), fix_gamma=False,
        output_mean_var=True, _is_training=True)
    ref_mean = x.astype(np.float32).mean(axis=(0, 2, 3))
    # bf16 inputs quantize the data itself (~2 decimal digits) but the
    # ACCUMULATION must not add sequential-rounding drift on top
    np.testing.assert_allclose(np.asarray(mean.asnumpy(), np.float32),
                               ref_mean, rtol=3e-3)


def test_batchnorm_stat_output_cotangents():
    """Gradients THROUGH the returned batch statistics (review
    regression: the fused VJP must not drop mean/var cotangents)."""
    from mxnet_tpu import autograd, nd

    rs = np.random.RandomState(11)
    x = rs.randn(2, 3, 4, 4).astype("float32")
    xn = nd.array(x)
    xn.attach_grad()
    n = 2 * 4 * 4
    with autograd.record():
        _, mean, var = nd.BatchNorm(
            xn, nd.ones(3), nd.zeros(3), nd.zeros(3), nd.ones(3),
            fix_gamma=False, output_mean_var=True)
        (mean.sum() + var.sum()).backward()
    # d mean_c/dx = 1/n; d var_c/dx = 2(x - mean_c)/n
    m = x.mean(axis=(0, 2, 3))
    expect = 1.0 / n + 2.0 * (x - m[None, :, None, None]) / n
    np.testing.assert_allclose(xn.grad.asnumpy(), expect, atol=1e-5)


def test_bf16_conv_backward_error_bounded_at_depth():
    """VERDICT r2 Weak #10: dgrad/wgrad run in native bf16 (cuDNN
    tensor-core parity) — bound the resulting gradient error against the
    f32 oracle through a ResNet-depth stack of convs."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rs = np.random.RandomState(0)
    depth = 8
    ws = [rs.randn(16, 16, 3, 3).astype(np.float32) * (1.0 / 12.0)
          for _ in range(depth)]
    x0 = rs.randn(2, 16, 8, 8).astype(np.float32)

    from mxnet_tpu.ops.nn import convolution

    def stack(x, ws_):
        for w in ws_:
            x = convolution(x, w, kernel=(3, 3), pad=(1, 1),
                            num_filter=16, no_bias=True)
            x = jnp.tanh(x)  # keep magnitudes bounded like BN would
        return jnp.sum(x.astype(jnp.float32) ** 2)

    g32 = jax.grad(lambda x: stack(x, [jnp.asarray(w) for w in ws]))(
        jnp.asarray(x0))
    gbf = jax.grad(lambda x: stack(
        x, [jnp.asarray(w, jnp.bfloat16) for w in ws]))(
        jnp.asarray(x0, jnp.bfloat16))

    a = np.asarray(g32, np.float32)
    b = np.asarray(gbf.astype(jnp.float32))
    denom = np.abs(a).max() + 1e-6
    rel = np.abs(a - b).max() / denom
    # bf16 has ~3 decimal digits; through 8 conv+tanh layers the
    # accumulated relative error must stay in the few-percent range —
    # this is the quantitative backing for the "native-dtype backward is
    # acceptable" design note in ops/nn.py
    assert rel < 0.08, rel


def test_nd_contrib_namespace_carries_detection_ops():
    """The reference exposes _contrib_* ops as mx.nd.contrib.<Name>
    (python/mxnet/ndarray/contrib.py); Proposal -> ROIAlign must chain
    through that namespace (the rcnn example path)."""
    import mxnet_tpu as mx

    for name in ("Proposal", "ROIAlign", "box_nms",
                 "DeformableConvolution"):
        assert hasattr(mx.nd.contrib, name), name
    rs = np.random.RandomState(0)
    cls = mx.nd.array(rs.rand(1, 6, 4, 4))
    bb = mx.nd.array(rs.randn(1, 12, 4, 4) * 0.1)
    info = mx.nd.array([[64, 64, 1.0]])
    rois = mx.nd.contrib.Proposal(
        cls, bb, info, rpn_pre_nms_top_n=16, rpn_post_nms_top_n=4,
        feature_stride=16, scales=(8,), rpn_min_size=1)
    assert rois.shape == (4, 5)
    pooled = mx.nd.contrib.ROIAlign(
        mx.nd.array(rs.randn(1, 8, 4, 4)), rois, pooled_size=(2, 2),
        spatial_scale=1.0 / 16)
    assert pooled.shape == (4, 8, 2, 2)


def test_proposal_channel_anchor_mismatch_raises():
    """scales x ratios defines the anchor count; a cls_prob whose channel
    dim disagrees must fail loudly, not with a reshape error deep in the
    kernel (found driving nd.contrib.Proposal with default scales)."""
    import pytest

    import mxnet_tpu as mx

    cls = mx.nd.zeros((1, 6, 4, 4))   # 3 anchors' worth of channels
    bb = mx.nd.zeros((1, 12, 4, 4))
    info = mx.nd.array([[64, 64, 1.0]])
    with pytest.raises(ValueError, match="channels"):
        # default scales=(4,8,16,32) x ratios=(0.5,1,2) = 12 anchors
        mx.nd.contrib.Proposal(cls, bb, info)
