"""Exactly-once resumable input pipeline (gluon/data/state.py).

Every test asserts the sample LEDGER, not just API plumbing: across a
checkpoint/restore, an elastic N→M reshape, or a quarantine replay, the
union of delivered sample sets must cover the epoch exactly once — zero
re-read, zero skipped.  Fault sites exercised here: ``worker_hang:K``
(receive watchdog) and ``data_skew:K`` (slow-but-alive workers must NOT
trip it).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, telemetry
from mxnet_tpu import resilience
from mxnet_tpu.checkpoint import (AsyncCheckpointer, PeerSnapshotStore,
                                  _peer_unwrap, _peer_wrap)
from mxnet_tpu.gluon.data import (DataLoader, DataLoaderWorkerError,
                                  DataPipelineState, DevicePrefetcher,
                                  epoch_order)
from mxnet_tpu.numerics import DivergenceMonitor
from mxnet_tpu.resilience import (CheckpointCorrupt, LocalCheckpointer,
                                  run_resilient)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")


@pytest.fixture(autouse=True)
def _telemetry_clean(monkeypatch):
    monkeypatch.delenv("MXTPU_TELEMETRY_PATH", raising=False)
    monkeypatch.delenv("MXTPU_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def _index_dataset(n):
    """Samples ARE their indices — a delivered batch names exactly which
    samples it carried, so tests can keep a ledger."""
    return gluon.data.SimpleDataset(np.arange(n, dtype=np.int64))


def _vals(batch):
    return [int(v) for v in np.asarray(batch.asnumpy()).ravel()]


def _drain(source):
    out = []
    for batch in source:
        out.extend(_vals(batch))
    return out


# -- epoch_order / DataPipelineState unit --------------------------------------

def test_epoch_order_pure_function_of_seed_and_epoch():
    a = epoch_order(7, 0, 100)
    assert np.array_equal(a, epoch_order(7, 0, 100))   # deterministic
    assert np.array_equal(np.sort(a), np.arange(100))  # a permutation
    assert not np.array_equal(a, epoch_order(7, 1, 100))
    assert not np.array_equal(a, epoch_order(8, 0, 100))
    assert np.array_equal(epoch_order(7, 0, 10, shuffle=False),
                          np.arange(10))


@pytest.mark.parametrize("world", [1, 2, 3, 5])
def test_shards_partition_the_remaining_epoch(world):
    """order[cursor:][r::w] over all ranks == the un-consumed sample
    set, exactly once, for any world size and any cursor."""
    n = 41   # deliberately ragged
    for cursor in (0, 7, 40):
        shards = []
        for r in range(world):
            st = DataPipelineState(n, seed=3, rank=r, world=world)
            st.cursor = cursor
            shards.extend(st.shard().tolist())
            assert st.shard_len() == len(st.shard())
        expect = epoch_order(3, 0, n)[cursor:]
        assert sorted(shards) == sorted(expect.tolist())


def test_state_dict_roundtrips_through_json_and_keeps_local_shard():
    st = DataPipelineState(100, seed=9, rank=1, world=3)
    st.advance(4)
    st.quarantine([(0, 7)])
    sd = json.loads(json.dumps(st.state_dict()))

    st2 = DataPipelineState(100, seed=0, rank=0, world=2)
    st2.load_state_dict(sd)
    assert (st2.rank, st2.world) == (0, 2)   # LOCAL: the N→M re-shard
    assert st2.seed == 9 and st2.cursor == st.cursor
    assert st2.samples_seen == st.samples_seen
    assert st2.is_quarantined(0, 7)

    with pytest.raises(ValueError):
        DataPipelineState(99, seed=0).load_state_dict(sd)   # length
    with pytest.raises(ValueError):
        DataPipelineState(100).load_state_dict(dict(sd, version=99))
    with pytest.raises(ValueError):
        DataPipelineState(100).load_state_dict(dict(sd, cursor=101))


def test_skip_moves_cursor_but_not_samples_seen():
    st = DataPipelineState(32, seed=0, shuffle=False)
    st.advance(4)
    st.skip(4)
    assert st.cursor == 8 and st.samples_seen == 4
    assert st.batch_idx == 2 and st.last_delivered == (0, 0)


# -- DataLoader: resume / reshape / quarantine ledgers -------------------------

@pytest.mark.parametrize("num_workers", [0, 2])
def test_loader_resume_is_exactly_once(num_workers):
    n, bs = 64, 8
    loader = DataLoader(_index_dataset(n), batch_size=bs, shuffle=True,
                        seed=5, num_workers=num_workers)
    it = iter(loader)
    first = []
    for _ in range(3):
        first.extend(_vals(next(it)))
    sd = loader.state_dict()
    assert sd["cursor"] == 24 and loader.samples_seen == 24
    close = getattr(it, "close", None)
    if close:
        close()

    fresh = DataLoader(_index_dataset(n), batch_size=bs, shuffle=True,
                       seed=0, num_workers=num_workers)
    fresh.load_state_dict(sd)
    rest = _drain(fresh)
    assert sorted(first + rest) == list(range(n))   # zero re-read/skip
    assert telemetry.event_counts().get("data_resume") == 1
    # next epoch reshuffles and covers the epoch again
    assert sorted(_drain(fresh)) == list(range(n))
    assert fresh.state_dict()["epoch"] == 2


def test_elastic_3_to_2_reshape_mid_epoch_is_exactly_once():
    n, bs = 96, 8
    mk = lambda r, w: DataLoader(_index_dataset(n), batch_size=bs,
                                 shuffle=True, seed=13, rank=r,
                                 world_size=w)
    old = [mk(r, 3) for r in range(3)]
    before = []
    for loader in old:   # 2 rounds each, then rank 2 "dies"
        it = iter(loader)
        for _ in range(2):
            before.extend(_vals(next(it)))
    states = [ld.state_dict() for ld in old]
    # the GLOBAL position is rank-agnostic (only rank/world are local)
    globals_ = [{k: v for k, v in s.items() if k not in ("rank", "world")}
                for s in states]
    assert globals_[0] == globals_[1] == globals_[2]

    survivors = [mk(r, 2) for r in range(2)]
    after = []
    for loader in survivors:
        loader.load_state_dict(states[0])
        after.extend(_drain(loader))
    assert sorted(before + after) == list(range(n))
    assert len(before) + len(after) == n


def test_quarantined_batch_skipped_loudly_with_one_event_each():
    n, bs = 40, 8
    loader = DataLoader(_index_dataset(n), batch_size=bs, shuffle=True,
                        seed=2)
    planned = _drain(DataLoader(_index_dataset(n), batch_size=bs,
                                shuffle=True, seed=2))
    loader.quarantine([(0, 1), (0, 3)])
    got = _drain(loader)
    poisoned = set(planned[bs:2 * bs]) | set(planned[3 * bs:4 * bs])
    assert sorted(got) == sorted(set(planned) - poisoned)
    assert telemetry.event_counts().get("batch_quarantined") == 2
    sd = loader.state_dict()
    assert sd["epoch"] == 1 and loader.samples_seen == n - 2 * bs


def test_loader_without_seed_rejects_state_api():
    loader = DataLoader(_index_dataset(8), batch_size=4)
    with pytest.raises(RuntimeError, match="seed="):
        loader.state_dict()
    with pytest.raises(ValueError, match="seed="):
        DataLoader(_index_dataset(8), batch_size=4, seed=1,
                   sampler=gluon.data.SequentialSampler(8))


# -- receive watchdog (worker_hang / data_skew fault sites) --------------------

@pytest.mark.faults
def test_worker_hang_trips_receive_watchdog(fault_inject, monkeypatch):
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "0.2")
    monkeypatch.setenv("MXTPU_DATA_HANG_SECS", "1.5")
    fault_inject("worker_hang:1")
    loader = DataLoader(_index_dataset(32), batch_size=8, seed=0,
                        num_workers=2)
    with pytest.raises(DataLoaderWorkerError, match="batch 1"):
        _drain(loader)
    assert telemetry.event_counts().get("data_worker_timeout") == 1


@pytest.mark.faults
def test_data_skew_is_slow_but_alive(fault_inject, monkeypatch):
    """Skewed (straggler) workers delay batches without killing them —
    the watchdog must NOT fire and the ledger must stay exact."""
    monkeypatch.setenv("MXTPU_DATA_TIMEOUT", "30")
    fault_inject("data_skew:2")
    loader = DataLoader(_index_dataset(32), batch_size=8, seed=0,
                        num_workers=2)
    assert sorted(_drain(loader)) == list(range(32))
    assert not telemetry.event_counts().get("data_worker_timeout")


# -- DevicePrefetcher: deferred accounting -------------------------------------

def test_prefetcher_accounting_is_delivery_exact():
    """The producer thread runs ahead; the cursor must reflect only what
    the CONSUMER took, so a state_dict mid-epoch restores without
    re-reading the batches the producer had prefetched."""
    n, bs = 64, 8
    loader = DataLoader(_index_dataset(n), batch_size=bs, shuffle=True,
                        seed=4)
    pf = DevicePrefetcher(loader, depth=3)
    it = iter(pf)
    first = []
    for _ in range(3):
        first.extend(_vals(next(it)))
    sd = pf.state_dict()
    assert sd["cursor"] == 24   # not 24 + prefetched
    pf.close()   # discards in-flight batches; their tokens never commit
    assert loader.state_dict()["cursor"] == 24

    fresh_loader = DataLoader(_index_dataset(n), batch_size=bs,
                              shuffle=True, seed=4)
    fresh = DevicePrefetcher(fresh_loader, depth=3)
    fresh.load_state_dict(sd)
    rest = _drain(fresh)
    assert sorted(first + rest) == list(range(n))
    assert fresh.samples_seen == n and fresh.last_batch_id() == (0, 7)


# -- checkpoint path: stamp, sidecar, manifest, peer frames --------------------

def test_data_state_stamp_crc_fails_closed():
    sd = {"version": 1, "cursor": 8}
    stamp = resilience.data_state_stamp(sd)
    assert resilience.data_state_unstamp(stamp) == sd
    assert resilience.data_state_unstamp(None) is None   # lenient
    with pytest.raises(CheckpointCorrupt):
        resilience.data_state_unstamp(
            dict(stamp, state={"version": 1, "cursor": 9}))
    with pytest.raises(CheckpointCorrupt):
        resilience.data_state_unstamp(dict(stamp, version=99))
    with pytest.raises(CheckpointCorrupt):
        resilience.data_state_unstamp("junk")


def test_local_checkpointer_sidecar_roundtrip(tmp_path):
    ck = LocalCheckpointer(tmp_path)
    ck.save(5, {"w": np.arange(4.0)})
    assert ck.data_state(5) is None          # pre-data-state checkpoint
    ck.save(6, {"w": np.arange(4.0)}, data_state={"version": 1,
                                                  "cursor": 16})
    assert ck.data_state(6) == {"version": 1, "cursor": 16}
    assert ck.data_state() == {"version": 1, "cursor": 16}   # latest


@pytest.mark.parametrize("async_save", [False, True])
def test_async_manifest_carries_data_state(tmp_path, async_save):
    loader = DataLoader(_index_dataset(32), batch_size=8, seed=1)
    it = iter(loader)
    next(it)
    ck = AsyncCheckpointer(tmp_path, async_save=async_save, rank=0,
                           world_size=1)
    ck.save(1, {"w": np.arange(8.0)})                 # no data state
    ck.save(2, {"w": np.arange(8.0)},
            data_state=loader.state_dict())
    ck.wait()
    assert ck.data_state(1) is None                   # lenient absence
    assert ck.data_state(2) == loader.state_dict()
    assert ck.data_state() == loader.state_dict()     # latest
    np.testing.assert_array_equal(ck.restore(1)["w"], np.arange(8.0))

    # a reader process that never heard of data state still restores
    reader = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                               world_size=1)
    np.testing.assert_array_equal(reader.restore(2)["w"], np.arange(8.0))


def test_manifest_data_state_crc_fails_closed(tmp_path):
    ck = AsyncCheckpointer(tmp_path, async_save=False, rank=0,
                           world_size=1)
    ck.save(3, {"w": np.zeros(4)}, data_state={"version": 1, "cursor": 8})
    mpath = os.path.join(ck._step_dir(3), "MANIFEST.json")
    with open(mpath) as f:
        m = json.load(f)
    m["data_state"]["state"]["cursor"] = 9   # bit-rot the position
    with open(mpath, "w") as f:
        json.dump(m, f)
    with pytest.raises(CheckpointCorrupt):
        ck.data_state(3)


def test_peer_wrap_roundtrip_and_bare_compat(tmp_path):
    state = {"w": np.arange(4.0)}
    ds = {"version": 1, "cursor": 24}
    s, d = _peer_unwrap(_peer_wrap(state, ds))
    assert d == ds and s is state
    s, d = _peer_unwrap(state)          # pre-wrap snapshot
    assert s is state and d is None

    from mxnet_tpu import distributed
    kv = distributed.FileKV(str(tmp_path))
    store = PeerSnapshotStore(0, kv=kv).start()
    try:
        store.hold_own(4, _peer_wrap(state, ds))
        np.testing.assert_array_equal(store.own_at(4)["w"], state["w"])
        assert store.data_state_at(0, 4) == ds
        store.hold_own(5, state)        # bare: old writer, new reader
        np.testing.assert_array_equal(store.own_at(5)["w"], state["w"])
        assert store.data_state_at(0, 5) is None
        assert store.data_state_at(0, 99) is None
    finally:
        store.close()


def test_peer_only_step_serves_data_state_without_manifest(tmp_path):
    """Elastic recovery can restore from a peer-RAM step that never got
    a disk manifest — data_state() must fall through to the held wrap
    instead of raising on the missing MANIFEST.json."""
    from mxnet_tpu import distributed
    kv = distributed.FileKV(str(tmp_path / "kv"))
    store = PeerSnapshotStore(0, kv=kv).start()
    try:
        ck = AsyncCheckpointer(tmp_path / "ck", async_save=False, rank=0,
                               world_size=1).attach_peers(store, every=1)
        ds = {"version": 1, "cursor": 40}
        ck.save(7, {"w": np.zeros(2)}, data_state=ds)
        import shutil
        shutil.rmtree(ck._step_dir(7))
        assert ck.data_state(7) == ds    # from the peer wrap
    finally:
        store.close()


# -- run_resilient: lockstep rewind of trainer + sample stream -----------------

def test_run_resilient_rewinds_sample_stream_in_lockstep(tmp_path):
    n, bs, steps = 64, 8, 8
    loader = DataLoader(_index_dataset(n), batch_size=bs, shuffle=True,
                        seed=3)
    box = {"it": None}
    seen = {}          # step -> sample tuple; replay must match bitwise
    armed = {"crash": True}

    def step_fn(step):
        if box["it"] is None:
            box["it"] = iter(loader)
        vals = tuple(_vals(next(box["it"])))
        if step in seen:
            assert seen[step] == vals   # replay trains on SAME batch
        seen[step] = vals
        if armed["crash"] and step == 5:
            armed["crash"] = False
            raise RuntimeError("injected step failure")
        return 0.0

    def set_data_state(sd):
        loader.load_state_dict(sd)
        box["it"] = None

    report = run_resilient(
        step_fn, LocalCheckpointer(tmp_path), steps,
        get_state=lambda: {"w": 0.0}, set_state=lambda s: None,
        checkpoint_every=2, get_data_state=loader.state_dict,
        set_data_state=set_data_state)
    assert report.restarts == 1 and report.resumed_from == [0, 4]
    assert sorted(v for t in seen.values() for v in t) == list(range(n))
    # the restore rewound samples_seen along with the cursor, so the
    # replayed steps 4-5 don't double-count
    assert loader.samples_seen == n


# -- divergence rollback → quarantine → replay (bitwise parity) ----------------

def test_rollback_quarantine_replay_matches_clean_run_bitwise(tmp_path):
    """The e2e loop: a poisoned batch NaNs the loss, DivergenceMonitor
    rolls back, the pipeline rewinds + quarantines it, and the replay —
    which skips it loudly — lands on weights BITWISE equal to a run
    that never saw the batch."""
    n, bs, lr = 48, 8, 0.1
    rng = np.random.RandomState(0)
    x = rng.rand(n, 4).astype(np.float32)
    y = rng.rand(n, 1).astype(np.float32)
    x[16:24] = np.nan          # batch ordinal 2 under shuffle=False
    ds = gluon.data.ArrayDataset(x, y)
    w0 = rng.rand(4, 1).astype(np.float32)

    def sgd(w, batch):
        bx = np.asarray(batch[0].asnumpy(), np.float32)
        by = np.asarray(batch[1].asnumpy(), np.float32)
        err = bx @ w - by
        loss = float(np.mean(err ** 2))
        return w - lr * (2.0 / len(bx)) * (bx.T @ err), loss

    # faulty run: checkpoint at step 0, train until the NaN trips
    loader = DataLoader(ds, batch_size=bs, seed=11, shuffle=False)
    ck = LocalCheckpointer(tmp_path)
    box = {"w": w0.copy()}
    ck.save(1, {"w": box["w"]}, data_state=loader.state_dict())
    mon = DivergenceMonitor(checkpointer=ck, set_state=box.update,
                            max_bad_steps=1)
    mon.data_pipeline = loader   # what Trainer.attach_data_pipeline does
    rolled = False
    it = iter(loader)
    for step in range(n // bs):
        batch = next(it)
        w_next, loss = sgd(box["w"], batch)
        if mon.observe(step=step, loss=loss,
                       batch_indices=[loader.last_batch_id()]):
            rolled = True
            break          # restored: box["w"] back to w0, loader rewound
        box["w"] = w_next
    assert rolled and mon.quarantined == [(0, 2)]
    replay_losses = []
    for batch in loader:   # quarantine-honoring replay
        box["w"], loss = sgd(box["w"], batch)
        replay_losses.append(loss)
    assert telemetry.event_counts().get("batch_quarantined") == 1
    assert telemetry.event_counts().get("data_resume") == 1

    # oracle: same seed, never computes on the poisoned batch
    w = w0.copy()
    oracle_losses = []
    clean = DataLoader(ds, batch_size=bs, seed=11, shuffle=False)
    for i, batch in enumerate(clean):
        if i == 2:
            continue
        w, loss = sgd(w, batch)
        oracle_losses.append(loss)
    assert replay_losses == oracle_losses        # bitwise float equality
    assert np.array_equal(box["w"], w)


def test_trainer_attach_data_pipeline_wires_monitor():
    p = gluon.Parameter("p_weight", shape=(3,), dtype="float32")
    p.initialize(init=mx.init.Zero())
    trainer = gluon.Trainer([p], "sgd", {"learning_rate": 0.1},
                            kvstore=None)
    trainer.divergence_monitor = DivergenceMonitor(max_bad_steps=50)
    loader = DataLoader(_index_dataset(8), batch_size=4, seed=0)
    assert trainer.attach_data_pipeline(loader) is trainer
    assert trainer.divergence_monitor.data_pipeline is loader
    assert trainer._batch_ids() is None          # nothing delivered yet
    next(iter(loader))
    assert trainer._batch_ids() == [(0, 0)]


# -- io iterators --------------------------------------------------------------

def test_ndarray_iter_state_roundtrip_mid_epoch():
    data = np.arange(48).reshape(12, 4).astype(np.float32)
    label = np.arange(12).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=3, shuffle=True)
    first = [it.next() for _ in range(2)]
    sd = it.state_dict()

    it2 = mx.io.NDArrayIter(data, label, batch_size=3, shuffle=True)
    it2.load_state_dict(sd)
    rest_a = [b.data[0].asnumpy() for b in it]
    rest_b = [b.data[0].asnumpy() for b in it2]
    assert len(rest_a) == len(rest_b) == 2
    for a, b in zip(rest_a, rest_b):
        np.testing.assert_array_equal(a, b)
    covered = np.concatenate([first[0].data[0].asnumpy(),
                              first[1].data[0].asnumpy()] + rest_b)
    np.testing.assert_array_equal(
        np.sort(covered.ravel()), np.sort(data.ravel()))
    with pytest.raises(ValueError):
        it2.load_state_dict(dict(sd, idx=list(range(5))))


def test_prefetching_iter_refetches_in_flight_batch():
    data = np.arange(40).reshape(10, 4).astype(np.float32)

    def mk():
        return mx.io.NDArrayIter(data, np.zeros(10), batch_size=2)

    pre = mx.io.PrefetchingIter(mk())
    got = [pre.next().data[0].asnumpy() for _ in range(2)]
    sd = pre.state_dict()   # one batch sits fetched-but-undelivered

    pre2 = mx.io.PrefetchingIter(mk()).load_state_dict(sd)
    rest = [b.data[0].asnumpy() for b in pre2]
    covered = np.concatenate(got + rest)
    np.testing.assert_array_equal(covered, data)   # nothing skipped


# -- telemetry v7 / trace_report ----------------------------------------------

def test_step_record_samples_seen_validation():
    rec = {"type": "step", "run": "r", "t": 0.0,
           "v": telemetry.SCHEMA_VERSION, "step": 0, "path": "eager",
           "skipped": False, "wall_us": 1.0, "interval_us": 1.0,
           "breakdown_us": {k: 0.0 for k in telemetry._BREAKDOWN_KEYS},
           "shares": {k: 1.0 / len(telemetry._BREAKDOWN_KEYS)
                      for k in telemetry._BREAKDOWN_KEYS},
           "collective_bytes": 0, "collective_buckets": 0}
    telemetry.validate_record(dict(rec, samples_seen=128))
    telemetry.validate_record(rec)                  # absent is fine
    for bad in (-1, True, 1.5, "128"):
        with pytest.raises(ValueError, match="samples_seen"):
            telemetry.validate_record(dict(rec, samples_seen=bad))


def test_trace_report_renders_data_pipeline_section(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    evs = [
        {"event": "data_resume", "epoch": 1, "cursor": 24,
         "samples_seen": 88, "reread_samples": 0, "skipped_samples": 0,
         "world": 2, "loader_rank": 0},
        {"event": "batch_quarantined", "epoch": 1, "batch": 3,
         "samples": 8},
        {"event": "data_worker_timeout", "batch": 5},
    ]
    with open(path, "w") as f:
        for e in evs:
            rec = {"type": "event", "run": "r", "t": 0.0,
                   "v": telemetry.SCHEMA_VERSION}
            rec.update(e)
            f.write(json.dumps(rec) + "\n")
    r = subprocess.run(
        [sys.executable, _TRACE_REPORT, path, "--validate"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "data pipeline:" in r.stdout
    assert "resumes: 1  re-read samples 0  skipped samples 0" in r.stdout
    assert "NOT exactly-once" not in r.stdout
    assert "quarantined batches skipped on replay: 1 (8 sample(s))" \
        in r.stdout
    assert "worker-hang timeouts: 1" in r.stdout

    with open(path, "a") as f:
        f.write(json.dumps({"type": "event", "run": "r", "t": 0.0,
                            "v": telemetry.SCHEMA_VERSION,
                            "event": "data_resume",
                            "reread_samples": 8,
                            "skipped_samples": 0}) + "\n")
    r = subprocess.run([sys.executable, _TRACE_REPORT, path],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    assert "** NOT exactly-once **" in r.stdout


# -- SIGKILL'd run resumes from the async manifest -----------------------------

_KILLED_CHILD = r"""
import json, os, signal, sys
import numpy as np
sys.path.insert(0, {repo!r})
from mxnet_tpu import gluon
from mxnet_tpu.checkpoint import AsyncCheckpointer

ckdir, outpath = sys.argv[1], sys.argv[2]
ds = gluon.data.SimpleDataset(np.arange(64, dtype=np.int64))
loader = gluon.data.DataLoader(ds, batch_size=8, seed=5, shuffle=True)
it = iter(loader)
delivered = []
for _ in range(3):
    delivered += [int(v) for v in np.asarray(next(it).asnumpy()).ravel()]
ck = AsyncCheckpointer(ckdir, async_save=True, rank=0, world_size=1)
ck.save(3, {{"w": np.arange(4.0)}}, data_state=loader.state_dict())
ck.wait()
with open(outpath, "w") as f:
    json.dump(delivered, f)
    f.flush(); os.fsync(f.fileno())
os.kill(os.getpid(), signal.SIGKILL)   # no cleanup, no atexit
"""


def _clean_env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_",
                                "LIBTPU"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_FAULT_INJECT", None)
    env.pop("MXTPU_TELEMETRY_PATH", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_sigkilled_run_resumes_exactly_once_from_async_manifest(tmp_path):
    script = str(tmp_path / "child.py")
    outpath = str(tmp_path / "delivered.json")
    ckdir = str(tmp_path / "ck")
    with open(script, "w") as f:
        f.write(_KILLED_CHILD.format(repo=_REPO))
    r = subprocess.run([sys.executable, script, ckdir, outpath],
                       env=_clean_env(), capture_output=True, text=True,
                       timeout=180)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    with open(outpath) as f:
        delivered = json.load(f)
    assert len(delivered) == 24

    ck = AsyncCheckpointer(ckdir, async_save=False, rank=0, world_size=1)
    sd = ck.data_state()
    assert sd is not None and sd["cursor"] == 24
    loader = gluon.data.DataLoader(
        _index_dataset(64), batch_size=8, seed=0, shuffle=True)
    loader.load_state_dict(sd)
    rest = _drain(loader)
    assert sorted(delivered + rest) == list(range(64))   # exactly once
