"""Elastic gang recovery (mxnet_tpu/resilience.ElasticGang): the health
plane (heartbeats, phi failure detector, straggler naming), the
peer-replicated RAM snapshot store, the epoch-consensus reshape
protocol, and the end-to-end surviving-a-SIGKILL paths — in-process
(threads over one FileKV) for tier-1, and real multi-process gangs
(tests/elastic_gang_worker.py, tools/launch.py --elastic) under
@slow."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import distributed, resilience, telemetry
from mxnet_tpu.checkpoint import PeerSnapshotStore

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "elastic_gang_worker.py")
_LAUNCH = os.path.join(_REPO, "tools", "launch.py")
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")


def _clean_env(**extra):
    """Subprocess gang env: CPU backend, no inherited faults/telemetry,
    no stale gang knobs (same recipe as tests/test_telemetry.py)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU",
                                "MXTPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


# -- the serial reference simulation -------------------------------------------

def _sim_losses(num_steps, phases, n=8):
    """Replicate elastic_gang_worker.py's arithmetic exactly.

    ``phases`` is [(start_step, members), ...]: the membership in force
    from that step on.  A reshape rolls the gang back to the common
    snapshot (= w at the TOP of the boundary step), so a straight serial
    run that switches membership at the boundary IS "the clean M-rank
    run from the same snapshot" the acceptance criterion names — the
    rolled-back executions only produced loss records the re-run
    overwrote.
    """
    w = np.full(n, 1.0, dtype=np.float64)
    losses = {}
    for step in range(num_steps):
        members = None
        for start, m in sorted(phases):
            if step >= start:
                members = m
        total = 0.0
        for r in sorted(members):
            total += float((r + 1) * float(w.sum()))
        loss = total / len(members)
        losses[step] = loss
        w = w * 0.99 - 0.01 * (loss / w.size)
    return losses, w


def _kv_allreduce(gang, kv, step, contribution):
    """The worker's lockstep KV mean (see elastic_gang_worker.py)."""
    epoch = gang.epoch
    kv.put_json(f"red/{epoch}/{step}/{gang.rank}",
                {"v": float(contribution)})
    gang.barrier(f"red{step}")
    total = 0.0
    for r in sorted(gang.members):
        total += float(kv.get_json(f"red/{epoch}/{step}/{r}")["v"])
    return total / len(gang.members)


# -- control plane units -------------------------------------------------------

def test_filekv_roundtrip(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    kv.put_json("epoch/current", {"epoch": 3, "members": [0, 2]})
    assert kv.get_json("epoch/current") == {"epoch": 3,
                                            "members": [0, 2]}
    for r in range(3):
        kv.put_json(f"hb/{r}", {"rank": r, "seq": 1})
    assert [k for k, _ in kv.scan("hb")] == ["hb/0", "hb/1", "hb/2"]
    kv.delete("hb/1")
    kv.delete("hb/1")                       # idempotent
    assert [k for k, _ in kv.scan("hb")] == ["hb/0", "hb/2"]
    assert kv.get_json("hb/1", default="gone") == "gone"
    with pytest.raises(ValueError):
        kv.put("../escape", b"nope")
    # float values must survive the JSON hop bitwise (the lockstep
    # allreduce in the elastic tests depends on it)
    v = 1.0 / 3.0 * 7.3
    kv.put_json("red/0/0/0", {"v": v})
    assert kv.get_json("red/0/0/0")["v"] == v


def test_failure_detector_confirms_silence(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    hb = resilience.HeartbeatPublisher(kv, 1, interval=0.02)
    det = resilience.FailureDetector(kv, 0, [0, 1], timeout=0.3,
                                     check_interval=0.01)
    hb.publish_once()
    assert det.poll(force=True) == set()
    time.sleep(0.35)                        # silence beyond the timeout
    assert det.poll(force=True) == {1}
    hb.publish_once()                       # resurrection: seq moves on
    assert det.poll(force=True) == set()


@pytest.mark.faults
def test_heartbeat_loss_fault_looks_like_death(fault_inject, tmp_path):
    """heartbeat_loss:K — wedged-but-alive must be indistinguishable
    from death: publishes are suppressed, the detector confirms."""
    kv = distributed.FileKV(str(tmp_path))
    hb = resilience.HeartbeatPublisher(kv, 1, interval=0.02)
    det = resilience.FailureDetector(kv, 0, [0, 1], timeout=0.25,
                                     check_interval=0.01)
    hb.publish_once()
    assert det.poll(force=True) == set()
    seq = kv.get_json("hb/1")["seq"]
    fault_inject("heartbeat_loss:1")
    for _ in range(5):
        hb.publish_once()                   # all suppressed
    assert kv.get_json("hb/1")["seq"] == seq
    time.sleep(0.3)
    assert det.poll(force=True) == {1}


def test_straggler_monitor_names_laggard(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    det = resilience.FailureDetector(kv, 0, [0, 1, 2], timeout=60.0,
                                     check_interval=0.0)
    kv.put_json("hb/1", {"rank": 1, "seq": 1, "step": 3})
    kv.put_json("hb/2", {"rank": 2, "seq": 1, "step": 19})
    det.poll(force=True)
    mon = resilience.StragglerMonitor(det, window=3,
                                      share_threshold=0.5)
    assert mon.observe(20, 0.9) is None     # window not yet full
    assert mon.observe(21, 0.9) is None
    assert mon.observe(22, 0.9) == 1        # rank 1 is furthest behind
    assert mon.observe(23, 0.9) is None     # rate-limited to one/window


def test_peer_snapshot_roundtrip(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    s0 = PeerSnapshotStore(0, kv=kv).start()
    s1 = PeerSnapshotStore(1, kv=kv).start()
    try:
        state = {"w": np.arange(4.0), "opt": 3.5}
        s0.hold_own(4, state, epoch=0)
        assert s0.own_at(4)["opt"] == 3.5
        assert s0.send_to(1, 4, state, epoch=0)
        assert s1.held_steps(0) == [4]
        got = s0.fetch(1, 0, 4)             # over the socket
        np.testing.assert_array_equal(got["w"], state["w"])
        assert got["opt"] == 3.5
        assert s0.fetch(1, 0, 99) is None   # holder doesn't have it
        assert kv.get_json("held/1/0")["steps"] == [4]
    finally:
        s0.close()
        s1.close()


def test_peer_snapshot_retention_and_epoch_filter(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    # retain_s=0: pure count-based pruning
    s = PeerSnapshotStore(1, kv=kv, keep=2, retain_s=0.0)
    for step in (2, 4, 6):
        s._store(0, step, 0, b"x")
    assert s.held_steps(0) == [4, 6]
    # a large time floor overrides the count cap: everything inside the
    # detection window survives (the reshape needs a COMMON step)
    s2 = PeerSnapshotStore(2, kv=kv, keep=2, retain_s=3600.0)
    for step in (2, 4, 6, 8):
        s2._store(0, step, 0, b"x")
    assert s2.held_steps(0) == [2, 4, 6, 8]
    # epoch filtering: pre-reshape snapshots are never advertised as
    # restore points for the reshaped gang
    s2._store(0, 10, 1, b"x")
    assert s2.held_steps(0, epoch=1) == [10]
    assert kv.get_json("held/2/0") == {"steps": [10], "epoch": 1}


def test_buddy_ring(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    gang = resilience.ElasticGang(0, 4, kv=kv)
    assert gang.buddy_of(0) == 1
    assert gang.buddy_of(3) == 0
    assert gang.buddy_of(0, [0, 2]) == 2
    assert gang.buddy_of(2, [0, 2]) == 0


def test_join_fresh_gang_writes_epoch_record(tmp_path):
    """join() on a fresh gang must leave the epoch-0 record behind
    (it routes through start()), so later joiners have a record to
    read."""
    kv = distributed.FileKV(str(tmp_path))
    gang = resilience.ElasticGang(0, 2, kv=kv,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=1.0)
    try:
        assert gang.join() is None
        cur = kv.get_json("epoch/current")
        assert cur is not None
        assert cur["epoch"] == 0 and cur["members"] == [0, 1]
    finally:
        gang.stop()


# -- in-process gang: reshape, loss parity, report CLI -------------------------

def _run_thread_rank(rank, world, kvdir, num_steps, snap_every, die_at,
                     out):
    kv = distributed.FileKV(kvdir)
    gang = resilience.ElasticGang(rank, world, kv=kv,
                                  peer_snap_every=snap_every,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=0.5)
    gang.start()
    state = {"w": np.full(8, 1.0, dtype=np.float64), "opt": 0.0}
    step, losses, infos = 0, {}, []
    try:
        while step < num_steps:
            if die_at is not None and step == die_at:
                gang.hb.stop()              # silent death: no heartbeat
                out[rank] = {"status": "died", "losses": losses,
                             "gang": gang}
                return
            try:
                gang.step_tick(step, state=state)
                loss = _kv_allreduce(
                    gang, kv, step,
                    (rank + 1) * float(state["w"].sum()))
            except resilience.RankFailure as rf:
                info = gang.recover(rf)
                st = info.shards[rank]
                state = {"w": np.array(st["w"], dtype=np.float64),
                         "opt": float(st["opt"])}
                step = info.snap_step
                infos.append(info)
                continue
            losses[step] = loss
            state["w"] = state["w"] * 0.99 - 0.01 * (loss /
                                                     state["w"].size)
            state["opt"] += loss
            step += 1
        out[rank] = {"status": "done", "losses": losses, "gang": gang,
                     "infos": infos, "w": state["w"]}
    except Exception as e:                  # noqa: BLE001 — surfaced
        out[rank] = {"status": "error", "error": repr(e), "gang": gang}


def test_thread_gang_survives_silent_death(tmp_path, monkeypatch):
    """3 ranks over one FileKV; rank 1 goes silent at step 6.  The
    survivors must reshape to world 2 from the newest COMMON peer
    snapshot (step 4: the buddy's copy of the dead rank lags one
    round), and the post-reshape loss trajectory must be bitwise equal
    to a clean 2-rank run from that snapshot.  The resulting event log
    must flow through the trace_report CLI."""
    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    telemetry.reset()
    kvdir = str(tmp_path / "kv")
    num_steps, snap_every, die_at = 10, 2, 6
    out = {}
    threads = [threading.Thread(
        target=_run_thread_rank,
        args=(r, 3, kvdir, num_steps, snap_every,
              die_at if r == 1 else None, out))
        for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not any(t.is_alive() for t in threads), "gang wedged"
        assert out[1]["status"] == "died"
        for r in (0, 2):
            assert out[r]["status"] == "done", out[r]
        for r in (0, 2):
            (info,) = out[r]["infos"]
            assert info.source == "peer"
            assert info.snap_step == 4
            assert info.members == [0, 2]
            assert info.epoch == 1
            assert info.dead == [1]
        # bitwise parity: pre-reshape with [0,1,2], post with [0,2]
        sim, sim_w = _sim_losses(num_steps, [(0, [0, 1, 2]),
                                             (4, [0, 2])])
        for r in (0, 2):
            assert out[r]["losses"] == sim
            np.testing.assert_array_equal(out[r]["w"], sim_w)
        # the dead rank's pre-death losses agree up to the rollback
        for s in range(4):
            assert out[1]["losses"][s] == sim[s]
    finally:
        for res in out.values():
            res["gang"].stop()
        telemetry.reset()                   # close the sink

    # injected-death log through the report CLI
    proc = subprocess.run(
        [sys.executable, _TRACE_REPORT, ev_path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "resilience:" in proc.stdout
    assert "dead: rank 1" in proc.stdout
    assert "reshape: epoch 1 world 2" in proc.stdout
    assert "from peer" in proc.stdout


def test_step_tick_steady_state_overhead(tmp_path):
    """The health plane must cost ≤1% of a training step: budget the
    per-tick mechanism (heartbeat note + throttled detector poll +
    epoch check + periodic RAM snapshot) against a 50 ms step."""
    kv = distributed.FileKV(str(tmp_path))
    gang = resilience.ElasticGang(0, 1, kv=kv, peer_snap_every=5,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=5.0)
    gang.start()
    try:
        state = {"w": np.zeros(256, dtype=np.float32)}
        for step in range(20):              # warm caches
            gang.step_tick(step, state=state)
        n = 200
        t0 = time.perf_counter()
        for step in range(20, 20 + n):
            gang.step_tick(step, state=state)
        per_tick = (time.perf_counter() - t0) / n
    finally:
        gang.stop()
    assert per_tick < 0.01 * 0.050, \
        f"step_tick costs {per_tick * 1e6:.0f}us — over 1% of a 50ms " \
        f"step"


# -- multi-process gangs (slow) ------------------------------------------------

def _spawn_rank(rank, world, env, args):
    e = dict(env)
    e["MXTPU_WORKER_RANK"] = str(rank)
    e["MXTPU_NUM_WORKERS"] = str(world)
    return subprocess.Popen([sys.executable, _WORKER] + args, env=e,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _parse_worker_output(text):
    results, losses, pids = {}, {}, []
    for ln in text.splitlines():
        if ln.startswith("RESULT "):
            rec = json.loads(ln[len("RESULT "):])
            results[rec["rank"]] = rec
        elif ln.startswith("LOSS "):
            _, r, _e, s, h = ln.split()
            losses[int(s)] = float.fromhex(h)
        elif ln.startswith("PID "):
            pids.append(int(ln.split()[2]))
    return results, losses, pids


@pytest.mark.slow
@pytest.mark.faults
def test_multiproc_kill_rank_elastic_reshape(tmp_path):
    """Hermetic 3-rank gang; rank 1 is SIGKILLed at step 9.  Survivors
    must keep their pids, reshape to world 2 within the heartbeat
    timeout, restore from buddy RAM (disk restores = 0), and produce a
    loss trajectory bitwise equal to the clean 2-rank continuation."""
    world, steps, snap_every, kill_step = 3, 14, 4, 9
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    env = _clean_env(
        MXTPU_GANG_DIR=str(gang_dir),
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_FAULT_INJECT="kill_rank:1",
        MXTPU_KILL_AT_STEP=str(kill_step),
    )
    args = [str(tmp_path), str(steps), str(snap_every)]
    procs = {r: _spawn_rank(r, world, env, args) for r in range(world)}
    outs = {r: p.communicate(timeout=120) for r, p in procs.items()}
    assert procs[1].returncode == -signal.SIGKILL, outs[1]
    sim, sim_w = _sim_losses(steps, [(0, [0, 1, 2]), (8, [0, 2])])
    w0 = {}
    for r in (0, 2):
        assert procs[r].returncode == 0, outs[r]
        results, losses, pids = _parse_worker_output(outs[r][0])
        assert len(pids) == 1, "survivor pid must be stable"
        rec = results[r]
        assert rec["pid"] == pids[0]
        assert rec["final_step"] == steps
        assert rec["epoch"] == 1
        assert rec["members"] == [0, 2]
        assert rec["source"] == "peer"
        assert rec["disk_restores"] == 0
        assert rec["reshapes"] == 1
        assert losses == sim, f"rank {r} loss trajectory diverged"
        w0[r] = rec["w0"]
    assert w0[0] == w0[2] == float(sim_w[0]).hex()


@pytest.mark.slow
@pytest.mark.faults
def test_multiproc_dual_kill_falls_back_to_disk(tmp_path):
    """Ranks 1 AND 2 die at step 9 — rank 1's buddy (2) is gone too, so
    no common RAM snapshot can exist and the survivor must complete the
    run from its disk manifest."""
    world, steps, snap_every, kill_step = 3, 14, 4, 9
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    env = _clean_env(
        MXTPU_GANG_DIR=str(gang_dir),
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_FAULT_INJECT="kill_rank:1,kill_rank:2",
        MXTPU_KILL_AT_STEP=str(kill_step),
    )
    args = [str(tmp_path), str(steps), str(snap_every)]
    procs = {r: _spawn_rank(r, world, env, args) for r in range(world)}
    outs = {r: p.communicate(timeout=120) for r, p in procs.items()}
    for r in (1, 2):
        assert procs[r].returncode == -signal.SIGKILL, outs[r]
    assert procs[0].returncode == 0, outs[0]
    results, losses, _ = _parse_worker_output(outs[0][0])
    rec = results[0]
    assert rec["final_step"] == steps
    assert rec["members"] == [0]
    assert rec["source"] == "disk"
    assert rec["disk_restores"] == 1
    sim, sim_w = _sim_losses(steps, [(0, [0, 1, 2]), (8, [0])])
    assert losses == sim
    assert rec["w0"] == float(sim_w[0]).hex()


@pytest.mark.slow
@pytest.mark.faults
def test_launch_elastic_respawns_dead_rank_and_rejoins(tmp_path):
    """tools/launch.py --elastic end to end: rank 1 dies, the gang
    absorbs it and keeps training; the launcher respawns ONLY rank 1
    (new pid, ranks 0/2 keep theirs), which disarms its kill via the
    marker file and rejoins through the join protocol.  Everyone
    finishes at epoch 2 with world 3 and bitwise-identical state."""
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    steps, snap_every, step_ms = 120, 4, 25
    env = _clean_env(
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_ELASTIC_RESPAWN_DELAY="2.0",
        MXTPU_FAULT_INJECT="kill_rank:1",
        MXTPU_KILL_AT_STEP="6",
    )
    proc = subprocess.run(
        [sys.executable, _LAUNCH, "-n", "3", "--elastic",
         "--gang-dir", str(gang_dir), "--max-restarts", "1", "--",
         sys.executable, _WORKER, str(tmp_path), str(steps),
         str(snap_every), str(step_ms)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-4000:],
                                  proc.stderr[-4000:])
    results, _, _ = _parse_worker_output(proc.stdout)
    assert sorted(results) == [0, 1, 2], proc.stdout[-4000:]
    pids_by_rank = {}
    for ln in proc.stdout.splitlines():
        if ln.startswith("PID "):
            _, r, p = ln.split()
            pids_by_rank.setdefault(int(r), []).append(int(p))
    assert len(pids_by_rank[0]) == 1      # survivors: stable pids
    assert len(pids_by_rank[2]) == 1
    assert len(pids_by_rank[1]) == 2      # victim: respawned once
    for r in range(3):
        rec = results[r]
        assert rec["final_step"] == steps
        assert rec["epoch"] == 2          # shrink + rejoin
        assert rec["members"] == [0, 1, 2]
    assert results[1]["pid"] == pids_by_rank[1][1]
    assert results[0]["w0"] == results[1]["w0"] == results[2]["w0"]
    assert "respawning rank 1" in proc.stderr
