"""Elastic gang recovery (mxnet_tpu/resilience.ElasticGang): the health
plane (heartbeats, phi failure detector, straggler naming), the
peer-replicated RAM snapshot store, the epoch-consensus reshape
protocol, and the end-to-end surviving-a-SIGKILL paths — in-process
(threads over one FileKV) for tier-1, and real multi-process gangs
(tests/elastic_gang_worker.py, tools/launch.py --elastic) under
@slow."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import distributed, resilience, telemetry
from mxnet_tpu.checkpoint import PeerSnapshotStore

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "elastic_gang_worker.py")
_LAUNCH = os.path.join(_REPO, "tools", "launch.py")
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")
_GANG_KV = os.path.join(_REPO, "tools", "gang_kv.py")


def _clean_env(**extra):
    """Subprocess gang env: CPU backend, no inherited faults/telemetry,
    no stale gang knobs (same recipe as tests/test_telemetry.py)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU",
                                "MXTPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


# -- the serial reference simulation -------------------------------------------

def _sim_losses(num_steps, phases, n=8):
    """Replicate elastic_gang_worker.py's arithmetic exactly.

    ``phases`` is [(start_step, members), ...]: the membership in force
    from that step on.  A reshape rolls the gang back to the common
    snapshot (= w at the TOP of the boundary step), so a straight serial
    run that switches membership at the boundary IS "the clean M-rank
    run from the same snapshot" the acceptance criterion names — the
    rolled-back executions only produced loss records the re-run
    overwrote.
    """
    w = np.full(n, 1.0, dtype=np.float64)
    losses = {}
    for step in range(num_steps):
        members = None
        for start, m in sorted(phases):
            if step >= start:
                members = m
        total = 0.0
        for r in sorted(members):
            total += float((r + 1) * float(w.sum()))
        loss = total / len(members)
        losses[step] = loss
        w = w * 0.99 - 0.01 * (loss / w.size)
    return losses, w


def _kv_allreduce(gang, kv, step, contribution):
    """The worker's lockstep KV mean (see elastic_gang_worker.py)."""
    epoch = gang.epoch
    kv.put_json(f"red/{epoch}/{step}/{gang.rank}",
                {"v": float(contribution)})
    gang.barrier(f"red{step}")
    total = 0.0
    for r in sorted(gang.members):
        total += float(kv.get_json(f"red/{epoch}/{step}/{r}")["v"])
    return total / len(gang.members)


# -- control plane units -------------------------------------------------------

@pytest.fixture(params=["file", "tcp"])
def kv_backend(request, tmp_path):
    """Both gang control planes behind the same get/put/scan/delete
    surface: FileKV on a tmp dir, TcpKV against an in-process
    GangKVServer (no filesystem at all).  Yields (mode, make) where
    ``make(rank)`` returns a fresh client — thread-gang tests give each
    rank its own connection, exactly like separate processes would."""
    if request.param == "file":
        kvdir = str(tmp_path / "kv")

        def make(rank=None):
            return distributed.FileKV(kvdir, rank=rank)

        yield request.param, make
    else:
        server = distributed.GangKVServer(lease_ttl=5.0).start()
        clients = []

        def make(rank=None):
            c = distributed.TcpKV(server.addr, rank=rank)
            clients.append(c)
            return c

        yield request.param, make
        for c in clients:
            try:
                c.close()
            except Exception:           # noqa: BLE001 — teardown
                pass
        server.stop()


def test_kv_roundtrip(kv_backend):
    _, make = kv_backend
    kv = make(rank=0)
    kv.put_json("epoch/current", {"epoch": 3, "members": [0, 2]})
    assert kv.get_json("epoch/current") == {"epoch": 3,
                                            "members": [0, 2]}
    for r in range(3):
        kv.put_json(f"hb/{r}", {"rank": r, "seq": 1})
    assert [k for k, _ in kv.scan("hb")] == ["hb/0", "hb/1", "hb/2"]
    kv.delete("hb/1")
    kv.delete("hb/1")                       # idempotent
    assert [k for k, _ in kv.scan("hb")] == ["hb/0", "hb/2"]
    assert kv.get_json("hb/1", default="gone") == "gone"
    with pytest.raises(ValueError):
        kv.put("../escape", b"nope")
    # float values must survive the JSON hop bitwise (the lockstep
    # allreduce in the elastic tests depends on it)
    v = 1.0 / 3.0 * 7.3
    kv.put_json("red/0/0/0", {"v": v})
    assert kv.get_json("red/0/0/0")["v"] == v


def test_kv_put_if_epoch_fencing(kv_backend):
    """The epoch fence (both planes): an epoch-stamped write at or
    above the highest committed epoch lands and advances the fence; a
    STALE one is rejected with FencedWrite and the stored value is
    untouched.  The fence is server-side state, visible to every
    client."""
    _, make = kv_backend
    kv = make(rank=0)
    assert kv.committed_epoch() == 0
    kv.put_if_epoch("a", b"one", 1)         # advances the fence
    assert kv.get("a") == b"one"
    assert kv.committed_epoch() == 1
    kv.put_if_epoch("a", b"two", 1)         # equal epoch: accepted
    kv.put_if_epoch("a", b"three", 3)       # newer: accepted + advances
    assert kv.committed_epoch() == 3
    with pytest.raises(distributed.FencedWrite):
        kv.put_if_epoch("a", b"stale", 2)
    assert kv.get("a") == b"three"          # rejected write left no trace
    kv.put("plain", b"ok")                  # un-stamped writes unaffected
    assert kv.get("plain") == b"ok"
    # a SECOND client sees the same fence — this is what stops a
    # resumed zombie that still believes in the old epoch
    kv2 = make(rank=1)
    assert kv2.committed_epoch() == 3
    with pytest.raises(distributed.FencedWrite):
        kv2.put_json_if_epoch("a", {"v": 1}, 0)
    assert kv2.get("a") == b"three"


@pytest.mark.faults
def test_tcpkv_fence_survives_coordinator_failover(fault_inject,
                                                   monkeypatch):
    """The fence is part of the coordinator's replicated state frame:
    after the daemon dies and a standby promotes itself, a stale-epoch
    write must STILL be rejected — a failover that forgot the fence
    would reopen the split-brain window at the worst possible
    moment."""
    monkeypatch.setenv("MXTPU_KV_FAILOVER_STAGGER", "0.1")
    server = distributed.GangKVServer(lease_ttl=2.0).start()
    c0 = c1 = None
    try:
        c0 = distributed.TcpKV(server.addr, rank=0, lease_ttl=2.0)
        c1 = distributed.TcpKV(server.addr, rank=1, lease_ttl=2.0)
        c0.put_if_epoch("epoch/marker", b"e3", 3)
        # committed_epoch doubles as a state-frame refresh: the fence
        # it reads is the fence a promotion will replay
        assert c0.committed_epoch() == 3
        assert c1.committed_epoch() == 3
        time.sleep(0.8)                 # a renewal refreshes the
        fault_inject("kill_coordinator")  # clients' state frames
        c0.put_json("arm", {"v": 0})    # mutation -> daemon dies mid-op
        assert server.died
        assert c0.failovers == 1
        # the promoted coordinator still enforces the fence
        assert c1.committed_epoch() == 3
        with pytest.raises(distributed.FencedWrite):
            c1.put_if_epoch("epoch/marker", b"stale", 2)
        assert c1.get("epoch/marker") == b"e3"
    finally:
        for c in (c1, c0):
            if c is not None:
                c.close()
        server.stop()


def test_failure_detector_confirms_silence(kv_backend):
    _, make = kv_backend
    kv = make(rank=0)
    hb = resilience.HeartbeatPublisher(kv, 1, interval=0.02)
    det = resilience.FailureDetector(kv, 0, [0, 1], timeout=0.3,
                                     check_interval=0.01)
    hb.publish_once()
    assert det.poll(force=True) == set()
    time.sleep(0.35)                        # silence beyond the timeout
    assert det.poll(force=True) == {1}
    hb.publish_once()                       # resurrection: seq moves on
    assert det.poll(force=True) == set()


@pytest.mark.faults
def test_heartbeat_loss_fault_looks_like_death(fault_inject, tmp_path):
    """heartbeat_loss:K — wedged-but-alive must be indistinguishable
    from death: publishes are suppressed, the detector confirms."""
    kv = distributed.FileKV(str(tmp_path))
    hb = resilience.HeartbeatPublisher(kv, 1, interval=0.02)
    det = resilience.FailureDetector(kv, 0, [0, 1], timeout=0.25,
                                     check_interval=0.01)
    hb.publish_once()
    assert det.poll(force=True) == set()
    seq = kv.get_json("hb/1")["seq"]
    fault_inject("heartbeat_loss:1")
    for _ in range(5):
        hb.publish_once()                   # all suppressed
    assert kv.get_json("hb/1")["seq"] == seq
    time.sleep(0.3)
    assert det.poll(force=True) == {1}


def test_straggler_monitor_names_laggard(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    det = resilience.FailureDetector(kv, 0, [0, 1, 2], timeout=60.0,
                                     check_interval=0.0)
    kv.put_json("hb/1", {"rank": 1, "seq": 1, "step": 3})
    kv.put_json("hb/2", {"rank": 2, "seq": 1, "step": 19})
    det.poll(force=True)
    mon = resilience.StragglerMonitor(det, window=3,
                                      share_threshold=0.5)
    assert mon.observe(20, 0.9) is None     # window not yet full
    assert mon.observe(21, 0.9) is None
    assert mon.observe(22, 0.9) == 1        # rank 1 is furthest behind
    assert mon.observe(23, 0.9) is None     # rate-limited to one/window


def test_peer_snapshot_roundtrip(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    s0 = PeerSnapshotStore(0, kv=kv).start()
    s1 = PeerSnapshotStore(1, kv=kv).start()
    try:
        state = {"w": np.arange(4.0), "opt": 3.5}
        s0.hold_own(4, state, epoch=0)
        assert s0.own_at(4)["opt"] == 3.5
        assert s0.send_to(1, 4, state, epoch=0)
        assert s1.held_steps(0) == [4]
        got = s0.fetch(1, 0, 4)             # over the socket
        np.testing.assert_array_equal(got["w"], state["w"])
        assert got["opt"] == 3.5
        assert s0.fetch(1, 0, 99) is None   # holder doesn't have it
        assert kv.get_json("held/1/0")["steps"] == [4]
    finally:
        s0.close()
        s1.close()


def test_peer_snapshot_retention_and_epoch_filter(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    # retain_s=0: pure count-based pruning
    s = PeerSnapshotStore(1, kv=kv, keep=2, retain_s=0.0)
    for step in (2, 4, 6):
        s._store(0, step, 0, b"x")
    assert s.held_steps(0) == [4, 6]
    # a large time floor overrides the count cap: everything inside the
    # detection window survives (the reshape needs a COMMON step)
    s2 = PeerSnapshotStore(2, kv=kv, keep=2, retain_s=3600.0)
    for step in (2, 4, 6, 8):
        s2._store(0, step, 0, b"x")
    assert s2.held_steps(0) == [2, 4, 6, 8]
    # epoch filtering: pre-reshape snapshots are never advertised as
    # restore points for the reshaped gang
    s2._store(0, 10, 1, b"x")
    assert s2.held_steps(0, epoch=1) == [10]
    assert kv.get_json("held/2/0") == {"steps": [10], "epoch": 1}


def test_peer_snapshot_fence_drops_stale_frames(tmp_path):
    """A receiver whose gang committed a newer epoch must DROP frames
    stamped with an older one — a fenced trainer's RAM replica must
    never survive as a restore point — while still ACKING the sender
    (containment, not a wedge: the zombie learns its fate from the
    epoch check, not from a hung socket)."""
    kv = distributed.FileKV(str(tmp_path))
    s0 = PeerSnapshotStore(0, kv=kv).start()
    s1 = PeerSnapshotStore(1, kv=kv).start()
    try:
        state = {"w": np.arange(4.0)}
        s1.fence(2)
        assert s0.send_to(1, 4, state, epoch=1)   # acked ...
        assert s1.held_steps(0, epoch=1) == []    # ... but NOT stored
        assert s0.send_to(1, 6, state, epoch=2)   # current epoch lands
        assert s1.held_steps(0, epoch=2) == [6]
        s1.fence(1)                               # the fence never moves
        assert s0.send_to(1, 8, state, epoch=1)   # backwards
        assert s1.held_steps(0, epoch=1) == []
    finally:
        s0.close()
        s1.close()


def test_buddy_ring(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    gang = resilience.ElasticGang(0, 4, kv=kv)
    assert gang.buddy_of(0) == 1
    assert gang.buddy_of(3) == 0
    assert gang.buddy_of(0, [0, 2]) == 2
    assert gang.buddy_of(2, [0, 2]) == 0


def test_join_fresh_gang_writes_epoch_record(kv_backend):
    """join() on a fresh gang must leave the epoch-0 record behind
    (it routes through start()), so later joiners have a record to
    read."""
    _, make = kv_backend
    kv = make(rank=0)
    gang = resilience.ElasticGang(0, 2, kv=kv,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=1.0)
    try:
        assert gang.join() is None
        cur = kv.get_json("epoch/current")
        assert cur is not None
        assert cur["epoch"] == 0 and cur["members"] == [0, 1]
    finally:
        gang.stop()


# -- TcpKV specifics: leases, watches, failover, partition ---------------------

def test_tcpkv_lease_expiry_replaces_mtime_freshness():
    """Keys under the ephemeral prefixes ride the client's lease: when
    the client stops renewing (process death), the server expires them;
    durable keys survive."""
    server = distributed.GangKVServer(lease_ttl=0.3).start()
    c1 = None
    try:
        c0 = distributed.TcpKV(server.addr, rank=0)
        c1 = distributed.TcpKV(server.addr, rank=1)
        c0.put_json("hb/0", {"rank": 0, "seq": 1})
        c0.put_json("epoch/current", {"epoch": 0})
        assert c1.get_json("hb/0")["seq"] == 1
        c0.close()                      # renewals stop; lease expires
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and c1.get_json("hb/0") is not None:
            time.sleep(0.05)
        assert c1.get_json("hb/0") is None
        # the client's own failover advertisement is leased too
        assert c1.get_json("failover/0") is None
        assert c1.get_json("epoch/current") == {"epoch": 0}
    finally:
        if c1 is not None:
            c1.close()
        server.stop()


def test_tcpkv_watch_wakes_on_prefix_change():
    """watch(prefix) long-polls: it must block while nothing under the
    prefix changes and wake promptly on a put."""
    server = distributed.GangKVServer(lease_ttl=5.0).start()
    c0 = c1 = None
    try:
        c0 = distributed.TcpKV(server.addr, rank=0)
        c1 = distributed.TcpKV(server.addr, rank=1)
        got = {}

        def waiter():
            got["keys"] = c1.watch("leave/", timeout=10.0)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.2)
        assert "keys" in got or t.is_alive()    # still blocked
        c0.put_json("leave/1", {"rank": 1, "at_step": 7})
        t.join(timeout=10)
        assert not t.is_alive(), "watch never woke"
        # an unrelated prefix does not satisfy a fresh watch
        t2 = threading.Thread(
            target=lambda: got.update(other=c1.watch("admit/",
                                                     timeout=0.3)),
            daemon=True)
        t2.start()
        c0.put_json("leave/2", {"rank": 2})
        t2.join(timeout=10)
        assert not t2.is_alive()
    finally:
        for c in (c0, c1):
            if c is not None:
                c.close()
        server.stop()


@pytest.mark.faults
def test_kill_coordinator_failover(fault_inject, monkeypatch):
    """kill_coordinator — the daemon drops dead mid-mutation, cutting
    every client off with no reply.  The lowest live rank must promote
    itself on its standby socket, replay the state frame, and the
    higher rank must adopt the new address and still see pre-death
    writes."""
    monkeypatch.setenv("MXTPU_KV_FAILOVER_STAGGER", "0.1")
    server = distributed.GangKVServer(lease_ttl=2.0).start()
    c0 = c1 = None
    try:
        c0 = distributed.TcpKV(server.addr, rank=0)
        c1 = distributed.TcpKV(server.addr, rank=1)
        c0.put_json("epoch/current", {"epoch": 0, "members": [0, 1]})
        c1.get_json("epoch/current")    # both have live connections
        time.sleep(0.8)                 # a renewal refreshes the
        fault_inject("kill_coordinator")  # clients' state frames
        c0.put_json("arm", {"v": 0})    # mutation -> daemon dies mid-op
        assert server.died
        # the very put that killed the server must have been retried
        # through the failover and landed
        assert c0.failovers == 1
        assert c0.get_json("arm") == {"v": 0}
        # pre-death state survived the replay, and the OTHER client
        # adopts the promoted coordinator transparently
        assert c1.get_json("epoch/current") == {"epoch": 0,
                                                "members": [0, 1]}
        c1.put_json("after/1", {"v": 1})
        assert c0.get_json("after/1") == {"v": 1}
    finally:
        for c in (c1, c0):
            if c is not None:
                c.close()
        server.stop()


@pytest.mark.faults
def test_net_partition_cuts_one_rank(fault_inject):
    """net_partition:K — rank K's client is cut off (every op raises
    GangKVError) while other ranks keep working."""
    server = distributed.GangKVServer(lease_ttl=5.0).start()
    c0 = c1 = None
    try:
        c0 = distributed.TcpKV(server.addr, rank=0)
        c1 = distributed.TcpKV(server.addr, rank=1)
        fault_inject("net_partition:1")
        with pytest.raises(distributed.GangKVError):
            c1.put_json("x", {"v": 1})
        with pytest.raises(distributed.GangKVError):
            c1.get_json("x")
        c0.put_json("y", {"v": 2})      # the un-partitioned rank
        assert c0.get_json("y") == {"v": 2}
    finally:
        for c in (c0, c1):
            if c is not None:
                try:
                    c.close()
                except Exception:       # noqa: BLE001 — teardown
                    pass
        server.stop()


# -- in-process gang: reshape, loss parity, report CLI -------------------------

def _run_thread_rank(rank, world, kv_make, num_steps, snap_every, die_at,
                     out):
    kv = kv_make(rank)
    gang = resilience.ElasticGang(rank, world, kv=kv,
                                  peer_snap_every=snap_every,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=0.5)
    gang.start()
    state = {"w": np.full(8, 1.0, dtype=np.float64), "opt": 0.0}
    step, losses, infos = 0, {}, []
    try:
        while step < num_steps:
            if die_at is not None and step == die_at:
                gang.hb.stop()              # silent death: no heartbeat
                out[rank] = {"status": "died", "losses": losses,
                             "gang": gang}
                return
            try:
                gang.step_tick(step, state=state)
                loss = _kv_allreduce(
                    gang, kv, step,
                    (rank + 1) * float(state["w"].sum()))
            except resilience.RankFailure as rf:
                info = gang.recover(rf)
                st = info.shards[rank]
                state = {"w": np.array(st["w"], dtype=np.float64),
                         "opt": float(st["opt"])}
                step = info.snap_step
                infos.append(info)
                continue
            losses[step] = loss
            state["w"] = state["w"] * 0.99 - 0.01 * (loss /
                                                     state["w"].size)
            state["opt"] += loss
            step += 1
        out[rank] = {"status": "done", "losses": losses, "gang": gang,
                     "infos": infos, "w": state["w"]}
    except Exception as e:                  # noqa: BLE001 — surfaced
        out[rank] = {"status": "error", "error": repr(e), "gang": gang}


def test_thread_gang_survives_silent_death(kv_backend, tmp_path,
                                           monkeypatch):
    """3 ranks over one control plane (both backends — over TcpKV there
    is NO shared filesystem); rank 1 goes silent at step 6.  The
    survivors must reshape to world 2 from the newest COMMON peer
    snapshot (step 4: the buddy's copy of the dead rank lags one
    round), and the post-reshape loss trajectory must be bitwise equal
    to a clean 2-rank run from that snapshot.  The resulting event log
    must flow through the trace_report CLI."""
    _, kv_make = kv_backend
    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    telemetry.reset()
    num_steps, snap_every, die_at = 10, 2, 6
    out = {}
    threads = [threading.Thread(
        target=_run_thread_rank,
        args=(r, 3, kv_make, num_steps, snap_every,
              die_at if r == 1 else None, out))
        for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not any(t.is_alive() for t in threads), "gang wedged"
        assert out[1]["status"] == "died"
        for r in (0, 2):
            assert out[r]["status"] == "done", out[r]
        for r in (0, 2):
            (info,) = out[r]["infos"]
            assert info.source == "peer"
            assert info.snap_step == 4
            assert info.members == [0, 2]
            assert info.epoch == 1
            assert info.dead == [1]
        # bitwise parity: pre-reshape with [0,1,2], post with [0,2]
        sim, sim_w = _sim_losses(num_steps, [(0, [0, 1, 2]),
                                             (4, [0, 2])])
        for r in (0, 2):
            assert out[r]["losses"] == sim
            np.testing.assert_array_equal(out[r]["w"], sim_w)
        # the dead rank's pre-death losses agree up to the rollback
        for s in range(4):
            assert out[1]["losses"][s] == sim[s]
    finally:
        for res in out.values():
            res["gang"].stop()
        telemetry.reset()                   # close the sink

    # injected-death log through the report CLI
    proc = subprocess.run(
        [sys.executable, _TRACE_REPORT, ev_path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "resilience:" in proc.stdout
    assert "dead: rank 1" in proc.stdout
    assert "reshape: epoch 1 world 2" in proc.stdout
    assert "from peer" in proc.stdout


# -- planned drain / scheduled admit / scale policy ----------------------------

def _run_elastic_rank(rank, world, kv_make, num_steps, snap_every, out,
                      *, join=False, leave_after=None, step_s=0.0):
    """Thread rank with the full traffic-elastic surface: optional
    late join (scheduled admit) and optional planned departure
    (plan_leave at ``leave_after`` + drain_margin)."""
    kv = kv_make(rank)
    gang = resilience.ElasticGang(rank, world, kv=kv,
                                  peer_snap_every=snap_every,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=2.0)
    state = {"w": np.full(8, 1.0, dtype=np.float64), "opt": 0.0}
    step, losses, infos = 0, {}, []
    planned_at = None

    def adopt(info):
        st = info.shards.get(rank)
        if st is None:                  # fresh joiner: any replica's w
            st = dict(next(iter(info.shards.values())))
            st["opt"] = 0.0
        return {"w": np.array(st["w"], dtype=np.float64),
                "opt": float(st["opt"])}

    try:
        if join:
            info = gang.join()
            assert info is not None
            state = adopt(info)
            step = info.snap_step
            infos.append(info)
        else:
            gang.start()
        while step < num_steps:
            if leave_after is not None and step == leave_after \
                    and planned_at is None:
                planned_at = gang.plan_leave(step + gang.drain_margin)
            try:
                gang.step_tick(step, state=state)
                loss = _kv_allreduce(
                    gang, kv, step,
                    (rank + 1) * float(state["w"].sum()))
            except resilience.RankFailure as rf:
                try:
                    info = gang.recover(rf)
                except resilience.GangEvicted:
                    out[rank] = {"status": "evicted", "losses": losses,
                                 "gang": gang, "at": step}
                    return
                state = adopt(info)
                step = info.snap_step
                infos.append(info)
                continue
            losses[step] = loss
            state["w"] = state["w"] * 0.99 - 0.01 * (loss /
                                                     state["w"].size)
            state["opt"] += loss
            step += 1
            if step_s:
                time.sleep(step_s)
        out[rank] = {"status": "done", "losses": losses, "gang": gang,
                     "infos": infos, "w": state["w"]}
    except Exception as e:                  # noqa: BLE001 — surfaced
        out[rank] = {"status": "error", "error": repr(e), "gang": gang}


def test_thread_gang_planned_drain_zero_lost_steps(kv_backend, tmp_path,
                                                   monkeypatch):
    """Preemption-aware drain: rank 1 announces at step 4 that it will
    leave at step 6 (drain_margin 2).  Every member snapshots at
    EXACTLY step 6 and reshapes with no detection window and no
    rollback — the leaver produced exactly 6 losses (zero lost steps)
    and the survivors' trajectory is bitwise equal to a clean run that
    switches membership at the boundary.  The event log must carry the
    planned markers through the trace_report fleet section."""
    _, kv_make = kv_backend
    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    telemetry.reset()
    num_steps, snap_every = 10, 2
    out = {}
    threads = [threading.Thread(
        target=_run_elastic_rank,
        args=(r, 3, kv_make, num_steps, snap_every, out),
        kwargs={"leave_after": 4 if r == 1 else None})
        for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not any(t.is_alive() for t in threads), "gang wedged"
        assert out[1]["status"] == "evicted", out[1]
        for r in (0, 2):
            assert out[r]["status"] == "done", out[r]
            (info,) = out[r]["infos"]
            assert info.planned is True
            assert info.snap_step == 6      # at_step = 4 + margin(2)
            assert info.members == [0, 2]
            assert info.source == "peer"
        sim, sim_w = _sim_losses(num_steps, [(0, [0, 1, 2]),
                                             (6, [0, 2])])
        for r in (0, 2):
            assert out[r]["losses"] == sim
            np.testing.assert_array_equal(out[r]["w"], sim_w)
        # the leaver computed every step up to the boundary and NONE
        # was rolled back: zero lost steps
        assert sorted(out[1]["losses"]) == list(range(6))
        for s in range(6):
            assert out[1]["losses"][s] == sim[s]
    finally:
        for res in out.values():
            res["gang"].stop()
        telemetry.reset()

    with open(ev_path) as f:
        ev = [json.loads(ln) for ln in f if ln.strip()]
    drained = [e for e in ev if e.get("event") == "rank_drained"]
    assert any(e.get("rank") == 1 for e in drained)
    recs = [e for e in ev if e.get("event") == "elastic_recover"]
    assert recs and all(e.get("planned") for e in recs)
    sched = [e for e in ev
             if e.get("event") == "gang_drain_scheduled"]
    assert any(e.get("at_step") == 6 for e in sched)

    proc = subprocess.run(
        [sys.executable, _TRACE_REPORT, ev_path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "fleet:" in proc.stdout
    assert "drained: rank 1" in proc.stdout
    assert "reshape latency: planned" in proc.stdout


def test_thread_gang_scheduled_admit_zero_lost_steps(kv_backend):
    """A joiner arriving mid-run is admitted at a SCHEDULED future step
    (join_req -> admit/plan), so the running ranks never roll back:
    they produce a loss for every step of the run, and all three ranks
    end bitwise identical."""
    _, kv_make = kv_backend
    num_steps, snap_every = 12, 2
    out = {}
    threads = [threading.Thread(
        target=_run_elastic_rank,
        args=(r, 2, kv_make, num_steps, snap_every, out),
        kwargs={"step_s": 0.08}) for r in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    tj = threading.Thread(
        target=_run_elastic_rank,
        args=(2, 2, kv_make, num_steps, snap_every, out),
        kwargs={"join": True, "step_s": 0.08})
    tj.start()
    threads.append(tj)
    for t in threads:
        t.join(timeout=60)
    try:
        assert not any(t.is_alive() for t in threads), "gang wedged"
        for r in range(3):
            assert out[r]["status"] == "done", out.get(r)
        info0 = out[0]["infos"][0]
        admit_step = info0.snap_step
        assert info0.members == [0, 1, 2]
        assert info0.planned is True
        sim, sim_w = _sim_losses(num_steps, [(0, [0, 1]),
                                             (admit_step, [0, 1, 2])])
        for r in range(3):
            for s, v in out[r]["losses"].items():
                assert v == sim[s], (r, s)
            np.testing.assert_array_equal(out[r]["w"], sim_w)
        # zero lost steps: the base ranks computed EVERY step once
        for r in (0, 1):
            assert sorted(out[r]["losses"]) == list(range(num_steps))
    finally:
        for res in out.values():
            res["gang"].stop()


# -- split-brain: partition fencing + zombie containment -----------------------

def _run_partition_rank(rank, world, kv_make, num_steps, snap_every, out,
                        *, step_s=0.05):
    """Thread rank for the partition matrix.  On a KV cut (GangKVError
    mid-allreduce, or GangFenced out of step_tick/recover) the rank
    waits for the heal, probes the fence with a STALE-epoch write —
    which must be REJECTED: the zero-durable-writes pin — and rejoins
    via park_fenced."""
    kv = kv_make(rank)
    gang = resilience.ElasticGang(rank, world, kv=kv,
                                  peer_snap_every=snap_every,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=0.5)
    gang.start()
    state = {"w": np.full(8, 1.0, dtype=np.float64), "opt": 0.0}
    step, losses, infos = 0, {}, []
    fenced = rejoined = False
    probe_rejected = probe_committed = 0

    def adopt(info):
        st = info.shards.get(rank)
        if st is None:                  # readmitted: any replica's w
            st = dict(next(iter(info.shards.values())))
            st["opt"] = 0.0
        return {"w": np.array(st["w"], dtype=np.float64),
                "opt": float(st["opt"])}

    try:
        while step < num_steps:
            try:
                gang.step_tick(step, state=state)
                loss = _kv_allreduce(
                    gang, kv, step,
                    (rank + 1) * float(state["w"].sum()))
            except (resilience.GangFenced, distributed.GangKVError):
                fenced = True
                stale = gang.epoch
                # wait until the cut heals AND the majority has
                # committed the next epoch — the fence the stale probe
                # below must bounce off
                t0 = time.monotonic()
                while time.monotonic() - t0 < 20:
                    try:
                        cur = kv.get_json("epoch/current")
                        if cur and int(cur.get("epoch", 0)) > stale:
                            break
                    except Exception:   # noqa: BLE001 — still cut
                        pass
                    time.sleep(0.05)
                try:
                    kv.put_if_epoch(f"zombie/{rank}", b"stale", stale)
                    probe_committed += 1
                except distributed.FencedWrite:
                    probe_rejected += 1
                info = gang.park_fenced(timeout=30.0)
                rejoined = True
                if info is not None:
                    state = adopt(info)
                    step = info.snap_step
                    infos.append(info)
                continue
            except resilience.RankFailure as rf:
                info = gang.recover(rf)
                state = adopt(info)
                step = info.snap_step
                infos.append(info)
                continue
            losses[step] = loss
            state["w"] = state["w"] * 0.99 - 0.01 * (loss /
                                                     state["w"].size)
            state["opt"] += loss
            if step_s:
                time.sleep(step_s)
            step += 1
        out[rank] = {"status": "done", "losses": losses, "gang": gang,
                     "infos": infos, "w": state["w"], "fenced": fenced,
                     "rejoined": rejoined,
                     "probe_rejected": probe_rejected,
                     "probe_committed": probe_committed}
    except Exception as e:                  # noqa: BLE001 — surfaced
        out[rank] = {"status": "error", "error": repr(e), "gang": gang}


@pytest.mark.faults
def test_thread_gang_partition_minority_fences_and_rejoins(
        kv_backend, fault_inject, tmp_path, monkeypatch):
    """The split-brain tentpole, end to end, over BOTH control planes:
    rank 2's side of an asymmetric partition is cut mid-run.  The
    majority (a strict quorum of the old epoch) commits the next epoch
    and continues BITWISE; the minority parks fenced with ZERO durable
    writes — its stale-epoch probe bounces off the fence — then
    rejoins after the heal and the world is restored to [0, 1, 2].
    The event log flows through the trace_report fencing section."""
    _, kv_make = kv_backend
    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY_PATH", ev_path)
    monkeypatch.setenv("MXTPU_PARTITION_SECS", "1.5")
    telemetry.reset()
    num_steps, snap_every = 70, 2
    out = {}
    threads = [threading.Thread(
        target=_run_partition_rank,
        args=(r, 3, kv_make, num_steps, snap_every, out))
        for r in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.8)                     # gang forms, snapshots exist
    fault_inject("partition_split:2")
    for t in threads:
        t.join(timeout=90)
    try:
        assert not any(t.is_alive() for t in threads), "gang wedged"
        for r in range(3):
            assert out.get(r, {}).get("status") == "done", out.get(r)
        # the minority: fenced, rejected, back in
        assert out[2]["fenced"], out[2]
        assert out[2]["rejoined"], out[2]
        assert out[2]["probe_committed"] == 0, \
            "a fenced rank's stale write LANDED — split-brain"
        assert out[2]["probe_rejected"] >= 1, out[2]
        # world restored after the heal
        for r in range(3):
            assert sorted(out[r]["gang"].members) == [0, 1, 2], out[r]
        # the majority continued BITWISE: replay the membership history
        # rank 0 actually lived (cut -> [0,1], readmit -> [0,1,2])
        # against the serial simulation
        infos0 = out[0]["infos"]
        assert len(infos0) >= 2, infos0
        assert infos0[0].members == [0, 1]
        phases = [(0, [0, 1, 2])]
        for info in infos0:
            phases.append((info.snap_step, list(info.members)))
        sim, sim_w = _sim_losses(num_steps, phases)
        for r in (0, 1):
            assert out[r]["losses"] == sim, f"rank {r} diverged"
            np.testing.assert_array_equal(out[r]["w"], sim_w)
        np.testing.assert_array_equal(out[2]["w"], sim_w)
    finally:
        for res in out.values():
            res["gang"].stop()
        telemetry.reset()

    with open(ev_path) as f:
        ev = [json.loads(ln) for ln in f if ln.strip()]
    kinds = {e.get("event") for e in ev}
    assert "gang_fenced" in kinds
    assert "fencing_rejected" in kinds
    assert "partition_healed" in kinds
    healed = [e for e in ev if e.get("event") == "partition_healed"]
    assert any(e.get("rank") == 2 and e.get("fenced_ms", 0) > 0
               for e in healed)

    proc = subprocess.run(
        [sys.executable, _TRACE_REPORT, ev_path, "--validate"],
        env=_clean_env(), capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "fencing:" in proc.stdout
    assert "rejected stale writes:" in proc.stdout
    assert "healed: rank 2" in proc.stdout
    assert "heal latency:" in proc.stdout


def test_zombie_rank_evicted_before_any_durable_write(kv_backend):
    """Zombie containment, distilled: while this rank was out to lunch
    a majority elsewhere committed an epoch that EXCLUDES it.  The very
    next step_tick must raise GangEvicted from the epoch check — which
    runs BEFORE the periodic snapshot — so no durable write of the
    zombie's ever lands."""
    _, make = kv_backend
    kv = make(rank=0)
    gang = resilience.ElasticGang(0, 1, kv=kv, peer_snap_every=1,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=5.0)
    gang.start()
    try:
        state = {"w": np.ones(4), "opt": 0.0}
        gang.step_tick(0, state=state)
        assert kv.get_json("snap/0")["step"] == 0
        # the rest of the gang moved on without us (epoch 5, fence up)
        other = make(rank=1)
        other.put_json_if_epoch(
            "epoch/current", {"epoch": 5, "members": [1], "dead": [0]},
            5)
        with pytest.raises(resilience.GangEvicted):
            gang.step_tick(1, state=state)
        # containment: the snapshot advert was never refreshed
        assert kv.get_json("snap/0")["step"] == 0
        # and even a direct snapshot attempt is fenced into eviction,
        # leaving the stored advert untouched
        with pytest.raises(resilience.GangEvicted):
            gang.snapshot(1, state)
        assert kv.get_json("snap/0")["step"] == 0
    finally:
        gang.stop()


class _FakeGang:
    """Just enough gang surface for ScalePolicy unit tests."""

    def __init__(self, kv, members=(0, 1)):
        self.kv = kv
        self.rank = 0
        self.members = list(members)
        self.drain_margin = 2
        self.planned = []

    def plan_leave(self, at_step):
        self.planned.append(at_step)
        return at_step


def test_scale_policy_grow_window_cooldown_and_caps(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    gang = _FakeGang(kv)
    pol = resilience.ScalePolicy(gang, window=3, cooldown=100.0,
                                 max_world=4)
    # a cold queue resets the saturation window
    assert pol.observe(0, queue_depth=5.0) is None
    assert pol.observe(1, queue_depth=0.0) is None
    assert pol.observe(2, queue_depth=5.0) is None
    assert pol.observe(3, queue_depth=5.0) is None
    assert pol.observe(4, queue_depth=5.0) == "grow"
    req = kv.get_json("scale/req")
    assert req["want_world"] == 3
    assert req["reason"] == "input_saturated"
    # cooldown suppresses a second request even though the launcher
    # hasn't consumed the first
    for s in range(5, 12):
        assert pol.observe(s, queue_depth=5.0) is None
    assert pol.grow_requests == 1
    # data-bound saturation (high data-wait share) never grows: more
    # chips would only starve faster
    pol2 = resilience.ScalePolicy(gang, window=1, cooldown=0.0,
                                  max_world=4)
    kv.delete("scale/req")
    assert pol2.observe(0, queue_depth=5.0, data_share=0.9) is None
    # max_world caps the fleet
    gang.members = [0, 1, 2, 3]
    assert pol2.observe(1, queue_depth=5.0) is None
    assert kv.get_json("scale/req") is None


def test_scale_policy_preemption_drain_and_min_world(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    gang = _FakeGang(kv, members=(0, 1, 2))
    pol = resilience.ScalePolicy(gang, min_world=2)
    assert pol.on_preemption(7) == 9        # step + drain_margin
    assert gang.planned == [9]
    assert pol.drains == 1
    # at min_world the drain is refused: losing the rank would stall
    # the fleet harder than the preemption
    gang.members = [0, 1]
    assert pol.on_preemption(11) is None
    assert gang.planned == [9]


def test_announce_freed_chips_record(tmp_path):
    kv = distributed.FileKV(str(tmp_path))
    rec = resilience.announce_freed_chips(kv, 2, step=9, count=4,
                                          addr="10.0.0.2:8476")
    got = kv.get_json("chips/freed/2")
    assert got["rank"] == 2 and got["count"] == 4
    assert got["step"] == 9 and got["addr"] == "10.0.0.2:8476"
    assert rec["rank"] == 2


def test_step_tick_steady_state_overhead(tmp_path):
    """The health plane must cost ≤1% of a training step: budget the
    per-tick mechanism (heartbeat note + throttled detector poll +
    epoch check + periodic RAM snapshot) against a 50 ms step."""
    kv = distributed.FileKV(str(tmp_path))
    gang = resilience.ElasticGang(0, 1, kv=kv, peer_snap_every=5,
                                  heartbeat_interval=0.05,
                                  heartbeat_timeout=5.0)
    gang.start()
    try:
        # the fence bookkeeping must be LIVE while the budget is
        # measured: start() wired the committed epoch into the v8
        # telemetry stamp, so every tick below pays the real epoch-check
        # + stamping cost, not a fencing-disabled fast path
        assert telemetry._GANG_EPOCH == gang.epoch
        state = {"w": np.zeros(256, dtype=np.float32)}
        for step in range(20):              # warm caches
            gang.step_tick(step, state=state)
        # best of 3: the budget gates the mechanism's cost, not a
        # transient CPU-contention spike on a loaded CI host
        n, step, per_tick = 200, 20, float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for s in range(step, step + n):
                gang.step_tick(s, state=state)
            step += n
            per_tick = min(per_tick,
                           (time.perf_counter() - t0) / n)
    finally:
        gang.stop()
    assert per_tick < 0.01 * 0.050, \
        f"step_tick costs {per_tick * 1e6:.0f}us — over 1% of a 50ms " \
        f"step"


# -- multi-process gangs (slow) ------------------------------------------------

def _spawn_rank(rank, world, env, args):
    e = dict(env)
    e["MXTPU_WORKER_RANK"] = str(rank)
    e["MXTPU_NUM_WORKERS"] = str(world)
    return subprocess.Popen([sys.executable, _WORKER] + args, env=e,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _parse_worker_output(text):
    results, losses, pids = {}, {}, []
    for ln in text.splitlines():
        if ln.startswith("RESULT "):
            rec = json.loads(ln[len("RESULT "):])
            results[rec["rank"]] = rec
        elif ln.startswith("LOSS "):
            _, r, _e, s, h = ln.split()
            losses[int(s)] = float.fromhex(h)
        elif ln.startswith("PID "):
            pids.append(int(ln.split()[2]))
    return results, losses, pids


def _start_kv_daemon(extra_env=None):
    """Spawn tools/gang_kv.py on an ephemeral port; returns (proc,
    addr) once LISTEN is printed."""
    env = _clean_env(**(extra_env or {}))
    proc = subprocess.Popen([sys.executable, _GANG_KV], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    assert line.startswith("LISTEN "), (line, proc.stderr.read())
    return proc, line.split()[1]


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.parametrize("backend", ["file", "tcp"])
def test_multiproc_kill_rank_elastic_reshape(tmp_path, backend):
    """Hermetic 3-rank gang; rank 1 is SIGKILLed at step 9.  Survivors
    must keep their pids, reshape to world 2 within the heartbeat
    timeout, restore from buddy RAM (disk restores = 0), and produce a
    loss trajectory bitwise equal to the clean 2-rank continuation.
    Over ``tcp`` the control plane is a gang_kv.py daemon — NO shared
    filesystem between the ranks' KV clients."""
    world, steps, snap_every, kill_step = 3, 14, 4, 9
    daemon = None
    if backend == "file":
        gang_dir = tmp_path / "gang"
        gang_dir.mkdir()
        plane = {"MXTPU_GANG_DIR": str(gang_dir)}
    else:
        daemon, addr = _start_kv_daemon()
        plane = {"MXTPU_GANG_KV": "tcp", "MXTPU_GANG_ADDR": addr}
    env = _clean_env(
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_FAULT_INJECT="kill_rank:1",
        MXTPU_KILL_AT_STEP=str(kill_step),
        **plane,
    )
    args = [str(tmp_path), str(steps), str(snap_every)]
    try:
        procs = {r: _spawn_rank(r, world, env, args)
                 for r in range(world)}
        outs = {r: p.communicate(timeout=120)
                for r, p in procs.items()}
    finally:
        if daemon is not None:
            daemon.terminate()
            daemon.communicate(timeout=30)
    assert procs[1].returncode == -signal.SIGKILL, outs[1]
    sim, sim_w = _sim_losses(steps, [(0, [0, 1, 2]), (8, [0, 2])])
    w0 = {}
    for r in (0, 2):
        assert procs[r].returncode == 0, outs[r]
        results, losses, pids = _parse_worker_output(outs[r][0])
        assert len(pids) == 1, "survivor pid must be stable"
        rec = results[r]
        assert rec["pid"] == pids[0]
        assert rec["final_step"] == steps
        assert rec["epoch"] == 1
        assert rec["members"] == [0, 2]
        assert rec["source"] == "peer"
        assert rec["disk_restores"] == 0
        assert rec["reshapes"] == 1
        assert losses == sim, f"rank {r} loss trajectory diverged"
        w0[r] = rec["w0"]
    assert w0[0] == w0[2] == float(sim_w[0]).hex()


@pytest.mark.slow
@pytest.mark.faults
def test_multiproc_dual_kill_falls_back_to_disk(tmp_path):
    """Ranks 1 AND 2 die at step 9 — rank 1's buddy (2) is gone too, so
    no common RAM snapshot can exist and the survivor must complete the
    run from its disk manifest.  MXTPU_QUORUM=0: one survivor of three
    can never form a strict majority of the old epoch, and this
    single-controller deployment explicitly opts out of the split-brain
    guard (the documented escape hatch for worlds that shrink below
    quorum)."""
    world, steps, snap_every, kill_step = 3, 14, 4, 9
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    env = _clean_env(
        MXTPU_GANG_DIR=str(gang_dir),
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_FAULT_INJECT="kill_rank:1,kill_rank:2",
        MXTPU_KILL_AT_STEP=str(kill_step),
        MXTPU_QUORUM="0",
    )
    args = [str(tmp_path), str(steps), str(snap_every)]
    procs = {r: _spawn_rank(r, world, env, args) for r in range(world)}
    outs = {r: p.communicate(timeout=120) for r, p in procs.items()}
    for r in (1, 2):
        assert procs[r].returncode == -signal.SIGKILL, outs[r]
    assert procs[0].returncode == 0, outs[0]
    results, losses, _ = _parse_worker_output(outs[0][0])
    rec = results[0]
    assert rec["final_step"] == steps
    assert rec["members"] == [0]
    assert rec["source"] == "disk"
    assert rec["disk_restores"] == 1
    sim, sim_w = _sim_losses(steps, [(0, [0, 1, 2]), (8, [0])])
    assert losses == sim
    assert rec["w0"] == float(sim_w[0]).hex()


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.parametrize("backend", ["file", "tcp"])
def test_multiproc_partition_minority_fences_and_rejoins(tmp_path,
                                                         backend):
    """Real processes, both control planes: rank 2's KV path is cut at
    its own step 6 (deferred arming — see elastic_gang_worker.py) and
    heals 2 s later.  The majority quorum-commits the next epoch and
    finishes; the minority prints FENCED, parks without stepping, and
    rejoins after the heal — every rank ends at the full world
    [0, 1, 2] with the same final step."""
    world, steps, snap_every = 3, 30, 4
    daemon = None
    if backend == "file":
        gang_dir = tmp_path / "gang"
        gang_dir.mkdir()
        plane = {"MXTPU_GANG_DIR": str(gang_dir)}
    else:
        daemon, addr = _start_kv_daemon()
        plane = {"MXTPU_GANG_KV": "tcp", "MXTPU_GANG_ADDR": addr}
    env = _clean_env(
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_FAULT_INJECT="partition_split:2",
        MXTPU_FAULT_AT_STEP="6",
        MXTPU_PARTITION_SECS="2.0",
        **plane,
    )
    args = [str(tmp_path), str(steps), str(snap_every), "100"]
    try:
        procs = {r: _spawn_rank(r, world, env, args)
                 for r in range(world)}
        outs = {r: p.communicate(timeout=180)
                for r, p in procs.items()}
    finally:
        if daemon is not None:
            daemon.terminate()
            daemon.communicate(timeout=30)
    for r in range(world):
        assert procs[r].returncode == 0, outs[r]
    for r in (0, 1):
        results, _losses, pids = _parse_worker_output(outs[r][0])
        rec = results[r]
        assert len(pids) == 1
        assert rec["final_step"] == steps
        assert rec["fenced"] == 0, "the MAJORITY must never fence"
        assert rec["members"] == [0, 1, 2]
        assert rec["reshapes"] >= 2        # cut out + readmit
    results, _losses, pids = _parse_worker_output(outs[2][0])
    rec = results[2]
    assert len(pids) == 1, "the fenced rank keeps its process"
    assert "FENCED 2" in outs[2][0]
    assert rec["fenced"] >= 1
    assert rec["rejoined"] >= 1
    assert rec["evictions"] == 0
    assert rec["final_step"] == steps
    assert rec["members"] == [0, 1, 2]


@pytest.mark.slow
@pytest.mark.faults
def test_multiproc_pause_rank_zombie_contained_and_readmitted(tmp_path):
    """pause_rank:2 — the rank is SIGSTOPped at step 6 for 3 s, long
    past the heartbeat timeout; the survivors declare it dead and
    commit the next epoch.  On SIGCONT the zombie's very next KV touch
    must learn the committed epoch and raise GangEvicted BEFORE any
    durable write; with MXTPU_REJOIN_ON_EVICT it then re-enters via a
    planned admission and the full world finishes together."""
    world, steps, snap_every = 3, 35, 4
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    env = _clean_env(
        MXTPU_GANG_DIR=str(gang_dir),
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_FAULT_INJECT="pause_rank:2",
        MXTPU_FAULT_AT_STEP="6",
        MXTPU_PAUSE_SECS="3.0",
        MXTPU_REJOIN_ON_EVICT="1",
    )
    args = [str(tmp_path), str(steps), str(snap_every), "100"]
    procs = {r: _spawn_rank(r, world, env, args) for r in range(world)}
    outs = {r: p.communicate(timeout=180) for r, p in procs.items()}
    for r in range(world):
        assert procs[r].returncode == 0, outs[r]
    for r in (0, 1):
        results, _losses, _pids = _parse_worker_output(outs[r][0])
        rec = results[r]
        assert rec["final_step"] == steps
        assert rec["members"] == [0, 1, 2]
        assert rec["evictions"] == 0
    results, _losses, pids = _parse_worker_output(outs[2][0])
    rec = results[2]
    assert len(pids) == 1, "the zombie keeps its process"
    assert "EVICTED 2" in outs[2][0]
    assert rec["evictions"] == 1
    assert rec["final_step"] == steps
    assert rec["members"] == [0, 1, 2]
    # containment: between SIGCONT and the eviction the zombie produced
    # no LOSS line — its step counter froze at the pause step until the
    # readmission rolled it to the majority's snapshot
    assert "[resilience] injected pause_rank" in outs[2][1]


@pytest.mark.slow
@pytest.mark.faults
def test_multiproc_kill_coordinator_failover(tmp_path):
    """The coordination daemon is fault-armed to drop dead mid-run.
    The gang must NOT reshape: rank 0's client promotes itself on its
    standby socket, replays the daemon's state, the other ranks adopt,
    and the run finishes at epoch 0 with bitwise loss parity — a
    coordinator death is an availability blip, never a training event.

    The kill is armed with a count normal traffic can't reach; once
    every rank has published step 6 (reads don't consume the counter)
    the test burns the remainder with its own puts, so the daemon dies
    at a point where all three failover candidacies are registered and
    every client's state frame is warm — deterministic, not a race
    against the heartbeat mutation rate."""
    world, steps, snap_every, burn_budget = 3, 30, 4, 5000
    daemon, addr = _start_kv_daemon(
        {"MXTPU_FAULT_INJECT": f"kill_coordinator:{burn_budget}"})
    env = _clean_env(
        MXTPU_GANG_KV="tcp",
        MXTPU_GANG_ADDR=addr,
        MXTPU_LEASE_TTL="1.0",          # state-frame refresh every ~0.3s
        MXTPU_HEARTBEAT_INTERVAL="0.25",
        MXTPU_HEARTBEAT_TIMEOUT="3.0",
        MXTPU_KV_FAILOVER_STAGGER="0.2",
    )
    host, _, port = addr.rpartition(":")
    args = [str(tmp_path), str(steps), str(snap_every), "60"]
    d_rc = None
    try:
        procs = {r: _spawn_rank(r, world, env, args)
                 for r in range(world)}
        conn = socket.create_connection((host, int(port)), timeout=5)
        try:
            # wait for every rank's step-6 contribution (gets are free)
            deadline = time.time() + 60
            want = [f"red/0/6/{r}" for r in range(world)]
            while want and time.time() < deadline:
                distributed._kv_send(conn, distributed._OP_GET,
                                     (want[0],))
                _code, val = distributed._kv_recv(conn)
                if val is not None:
                    want.pop(0)
                else:
                    time.sleep(0.05)
            assert not want, f"gang never reached step 6: {want}"
            # burn the fault counter: the daemon dies mid-put, now
            burned = 0
            try:
                while burned < 2 * burn_budget:
                    distributed._kv_send(
                        conn, distributed._OP_PUT,
                        (f"burn/{burned % 50}", b"x", None))
                    distributed._kv_recv(conn)
                    burned += 1
            except (ConnectionError, OSError, EOFError):
                pass
            assert burned < 2 * burn_budget, "daemon survived the burn"
        finally:
            conn.close()
        outs = {r: p.communicate(timeout=120)
                for r, p in procs.items()}
        d_out = daemon.communicate(timeout=30)
        d_rc = daemon.returncode
    finally:
        if d_rc is None:
            daemon.terminate()
            d_out = daemon.communicate(timeout=30)
    # the daemon really did die (clean exit after the injected kill)
    assert daemon.returncode == 0, d_out
    sim, sim_w = _sim_losses(steps, [(0, [0, 1, 2])])
    w0 = {}
    for r in range(world):
        assert procs[r].returncode == 0, outs[r]
        results, losses, pids = _parse_worker_output(outs[r][0])
        rec = results[r]
        assert len(pids) == 1, "no respawn on coordinator death"
        assert rec["final_step"] == steps
        assert rec["epoch"] == 0, "coordinator death must not reshape"
        assert rec["members"] == [0, 1, 2]
        assert rec["reshapes"] == 0
        assert rec["kv_failovers"] == 1, \
            f"rank {r} never failed over — the test proved nothing"
        assert losses == sim, f"rank {r} loss trajectory diverged"
        w0[r] = rec["w0"]
    assert w0[0] == w0[1] == w0[2] == float(sim_w[0]).hex()


@pytest.mark.slow
@pytest.mark.faults
def test_launch_elastic_respawns_dead_rank_and_rejoins(tmp_path):
    """tools/launch.py --elastic end to end: rank 1 dies, the gang
    absorbs it and keeps training; the launcher respawns ONLY rank 1
    (new pid, ranks 0/2 keep theirs), which disarms its kill via the
    marker file and rejoins through the join protocol.  Everyone
    finishes at epoch 2 with world 3 and bitwise-identical state."""
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    steps, snap_every, step_ms = 120, 4, 25
    env = _clean_env(
        MXTPU_HEARTBEAT_INTERVAL="0.1",
        MXTPU_HEARTBEAT_TIMEOUT="1.0",
        MXTPU_ELASTIC_RESPAWN_DELAY="2.0",
        MXTPU_FAULT_INJECT="kill_rank:1",
        MXTPU_KILL_AT_STEP="6",
    )
    proc = subprocess.run(
        [sys.executable, _LAUNCH, "-n", "3", "--elastic",
         "--gang-dir", str(gang_dir), "--max-restarts", "1", "--",
         sys.executable, _WORKER, str(tmp_path), str(steps),
         str(snap_every), str(step_ms)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-4000:],
                                  proc.stderr[-4000:])
    results, _, _ = _parse_worker_output(proc.stdout)
    assert sorted(results) == [0, 1, 2], proc.stdout[-4000:]
    pids_by_rank = {}
    for ln in proc.stdout.splitlines():
        if ln.startswith("PID "):
            _, r, p = ln.split()
            pids_by_rank.setdefault(int(r), []).append(int(p))
    assert len(pids_by_rank[0]) == 1      # survivors: stable pids
    assert len(pids_by_rank[2]) == 1
    assert len(pids_by_rank[1]) == 2      # victim: respawned once
    for r in range(3):
        rec = results[r]
        assert rec["final_step"] == steps
        assert rec["epoch"] == 2          # shrink + rejoin
        assert rec["members"] == [0, 1, 2]
    assert results[1]["pid"] == pids_by_rank[1][1]
    assert results[0]["w0"] == results[1]["w0"] == results[2]["w0"]
    assert "respawning rank 1" in proc.stderr
