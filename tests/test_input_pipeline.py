"""Input-pipeline tests: single-copy collation, shared-memory workers,
device prefetch (docs/perf.md "Input pipeline").

Parity contract: every transport (in-process, thread pool, spawn
shared-memory) and the DevicePrefetcher wrapper must deliver batches
element-wise IDENTICAL — values and order — to the legacy in-process
path, given the same sampler seed.
"""

import gc
import io as _io
import multiprocessing
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, recordio
from mxnet_tpu.gluon.data import (DataLoader, DataLoaderWorkerError,
                                  DevicePrefetcher)
from mxnet_tpu.gluon.data import _shm_worker
from mxnet_tpu.gluon.data.dataloader import default_batchify_fn


class FailingDataset:
    """Module-level (picklable for spawn) dataset that poisons one index."""

    def __init__(self, n=16, bad=13):
        self._n = n
        self._bad = bad

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if i == self._bad:
            raise ValueError(f"poisoned sample {i}")
        return np.full(3, i, np.float32)


def sum_batchify(samples):
    """Module-level custom batchify (picklable for spawn workers)."""
    return np.asarray([float(np.sum(s[0])) for s in samples], np.float32)


def _as_np(batch):
    if isinstance(batch, (list, tuple)):
        return [_as_np(b) for b in batch]
    return batch.asnumpy()


def _assert_batches_equal(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        g, w = _as_np(g), _as_np(w)
        assert len(g) == len(w)
        for gc_, wc in zip(g, w):
            np.testing.assert_array_equal(gc_, wc)


def _float_ds(n=37, dim=4):
    rng = np.random.RandomState(0)
    return gluon.data.ArrayDataset(
        rng.rand(n, dim).astype(np.float32),
        np.arange(n, dtype=np.float32))


# -- collation -----------------------------------------------------------------

def test_collate_column_single_copy_matches_stack():
    rng = np.random.RandomState(1)
    col = [rng.rand(3, 5).astype(np.float32) for _ in range(8)]
    out = _shm_worker.collate_column(col)
    np.testing.assert_array_equal(out, np.stack(col))
    assert out.flags["C_CONTIGUOUS"]
    # preallocated output is written in place
    buf = np.empty((8, 3, 5), np.float32)
    assert _shm_worker.collate_column(col, out=buf) is buf
    np.testing.assert_array_equal(buf, np.stack(col))


def test_collate_column_mixed_dtype_falls_back_to_legacy_promotion():
    mixed = [np.arange(2, dtype=np.float32), np.arange(2, dtype=np.int64)]
    got = _shm_worker.collate_column(mixed)
    legacy = np.asarray([np.asarray(m) for m in mixed])
    assert got.dtype == legacy.dtype
    np.testing.assert_array_equal(got, legacy)
    # truly ragged shapes are an error on the legacy path too
    ragged = [np.zeros((2,), np.float32), np.zeros((3,), np.float32)]
    with pytest.raises(ValueError):
        _shm_worker.collate_column(ragged)


def test_default_batchify_parity_with_legacy_stack():
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    samples = [(rng.rand(4).astype(np.float32), np.float32(i))
               for i in range(6)]
    got = default_batchify_fn(samples)
    # the pre-optimization path: one jnp.asarray per sample + stack
    want_x = jnp.stack([jnp.asarray(s[0]) for s in samples])
    want_y = jnp.stack([jnp.asarray(s[1]) for s in samples])
    np.testing.assert_array_equal(got[0].asnumpy(), np.asarray(want_x))
    np.testing.assert_array_equal(got[1].asnumpy(), np.asarray(want_y))


def test_default_batchify_device_resident_samples():
    samples = [mx.nd.array(np.full((2, 2), i, np.float32))
               for i in range(4)]
    out = default_batchify_fn(samples)
    assert out.shape == (4, 2, 2)
    np.testing.assert_array_equal(
        out.asnumpy(), np.stack([s.asnumpy() for s in samples]))


# -- transport parity ----------------------------------------------------------

def test_loader_thread_workers_parity():
    ds = _float_ds()
    kw = dict(batch_size=5, shuffle=False, last_batch="keep")
    want = list(DataLoader(ds, **kw))
    got = list(DataLoader(ds, num_workers=2, **kw))
    _assert_batches_equal(got, want)


def test_loader_thread_workers_parity_shuffled():
    ds = _float_ds()
    np.random.seed(42)
    want = list(DataLoader(ds, batch_size=5, shuffle=True))
    np.random.seed(42)
    got = list(DataLoader(ds, batch_size=5, shuffle=True, num_workers=2))
    _assert_batches_equal(got, want)


def test_loader_shm_workers_parity():
    """Spawn + shared-memory ring transport: same values, same order.
    More batches than ring slots exercises slot recycling."""
    ds = _float_ds(n=48)
    want = list(DataLoader(ds, batch_size=4))
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        thread_pool=False)
    with iter(loader) as it:
        got = list(it)
    _assert_batches_equal(got, want)
    assert not [p for p in multiprocessing.active_children()
                if p.is_alive()]


def test_loader_shm_oversize_batch_pickle_fallback(monkeypatch):
    """A batch too big for a ring slot transparently takes the pickle
    path — identical results, merely slower."""
    monkeypatch.setenv("MXTPU_SHM_SLOT_MB", "0.00005")  # ~52 bytes
    rng = np.random.RandomState(3)
    ds = gluon.data.ArrayDataset(rng.rand(12, 64).astype(np.float32),
                                 np.arange(12, dtype=np.float32))
    want = list(DataLoader(ds, batch_size=4))
    loader = DataLoader(ds, batch_size=4, num_workers=1,
                        thread_pool=False)
    with iter(loader) as it:
        got = list(it)
    _assert_batches_equal(got, want)


def test_loader_shm_custom_batchify():
    ds = _float_ds(n=8, dim=3)
    want = [sum_batchify([ds[i] for i in range(b * 4, b * 4 + 4)])
            for b in range(2)]
    loader = DataLoader(ds, batch_size=4, num_workers=1,
                        thread_pool=False, batchify_fn=sum_batchify)
    with iter(loader) as it:
        got = list(it)
    assert len(got) == 2
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.asnumpy(), w)


# -- worker failure context ----------------------------------------------------

def test_worker_error_context_threads():
    loader = DataLoader(FailingDataset(), batch_size=4, num_workers=2)
    it = iter(loader)
    got = [next(it), next(it), next(it)]  # batches 0..2 are fine
    assert len(got) == 3
    with pytest.raises(DataLoaderWorkerError) as exc:
        next(it)
    msg = str(exc.value)
    assert "batch 3" in msg and "13" in msg
    assert not [t for t in threading.enumerate()
                if t.name.startswith("ThreadPoolExecutor")
                and t.is_alive() and "loader" in repr(t)]


def test_worker_error_context_processes():
    loader = DataLoader(FailingDataset(), batch_size=4, num_workers=1,
                        thread_pool=False)
    it = iter(loader)
    for _ in range(3):
        next(it)
    with pytest.raises(DataLoaderWorkerError) as exc:
        next(it)
    msg = str(exc.value)
    assert "batch 3" in msg and "13" in msg
    assert "worker traceback" in msg and "poisoned sample 13" in msg
    assert not [p for p in multiprocessing.active_children()
                if p.is_alive()]


# -- resource cleanup ----------------------------------------------------------

def test_early_break_leaves_no_worker_threads():
    ds = _float_ds(n=64)
    before = set(threading.enumerate())
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    it = iter(loader)
    next(it)
    it.close()
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive()]
    assert not leaked
    # __del__ path: abandoning the iterator mid-epoch also cleans up
    it2 = iter(loader)
    next(it2)
    del it2
    gc.collect()
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive()]
    assert not leaked


def test_early_break_leaves_no_worker_processes():
    ds = _float_ds(n=32)
    loader = DataLoader(ds, batch_size=4, num_workers=1,
                        thread_pool=False)
    it = iter(loader)
    next(it)
    del it
    gc.collect()
    assert not [p for p in multiprocessing.active_children()
                if p.is_alive()]


# -- last_batch semantics across epochs ----------------------------------------

@pytest.mark.parametrize("num_workers", [0, 2])
def test_last_batch_rollover_two_epochs(num_workers):
    ds = gluon.data.SimpleDataset(list(range(10)))
    loader = DataLoader(ds, batch_size=4, last_batch="rollover",
                        num_workers=num_workers)
    assert len(loader) == 2  # no carry yet
    ep1 = [b.asnumpy().tolist() for b in loader]
    assert ep1 == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # the tail [8, 9] rolled over: it leads epoch 2, in order
    assert len(loader) == 3
    ep2 = [b.asnumpy().tolist() for b in loader]
    assert ep2 == [[8, 9, 0, 1], [2, 3, 4, 5], [6, 7, 8, 9]]
    assert len(loader) == 2  # nothing carried out of epoch 2


@pytest.mark.parametrize("num_workers", [0, 2])
def test_last_batch_discard_two_epochs(num_workers):
    ds = gluon.data.SimpleDataset(list(range(10)))
    loader = DataLoader(ds, batch_size=4, last_batch="discard",
                        num_workers=num_workers)
    for _ in range(2):  # identical epochs, ragged tail dropped
        assert len(loader) == 2
        ep = [b.asnumpy().tolist() for b in loader]
        assert ep == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_prefetch_defaulting():
    ds = _float_ds(n=16)
    assert DataLoader(ds, 4, num_workers=3)._prefetch == 6  # 2 * workers
    assert DataLoader(ds, 4, num_workers=3, prefetch=None)._prefetch == 6
    assert DataLoader(ds, 4, num_workers=2, prefetch=0)._prefetch == 0
    assert DataLoader(ds, 4, num_workers=2, prefetch=7)._prefetch == 7
    loader = DataLoader(ds, 4, num_workers=2, prefetch=0)
    it = iter(loader)
    assert it._depth == 1  # prefetch=0: at most one batch in flight
    it.close()


# -- DevicePrefetcher ----------------------------------------------------------

def test_device_prefetcher_parity_and_order():
    ds = _float_ds(n=20, dim=3)
    loader = DataLoader(ds, batch_size=5)
    want = list(loader)
    got = list(DevicePrefetcher(loader, depth=2))
    _assert_batches_equal(got, want)


def test_device_prefetcher_env_zero_is_synchronous(monkeypatch):
    monkeypatch.setenv("MXTPU_DEVICE_PREFETCH", "0")
    ds = _float_ds(n=12, dim=2)
    loader = DataLoader(ds, batch_size=4)
    pf = DevicePrefetcher(loader)
    assert pf._depth == 0
    want = list(loader)
    got = []
    for b in pf:
        got.append(b)
        assert not [t for t in threading.enumerate()
                    if t.name == "mxtpu-device-prefetch"]
    _assert_batches_equal(got, want)
    assert pf._thread is None  # no background thread was ever started


def test_device_prefetcher_databatch_and_reset():
    data = np.random.RandomState(0).rand(20, 3).astype(np.float32)
    it = mx.io.NDArrayIter(data, np.zeros(20, np.float32), batch_size=5)
    pf = DevicePrefetcher(it, depth=2)
    for _ in range(2):  # two epochs through reset()
        pf.reset()
        batches = list(pf)
        assert len(batches) == 4
        got = np.concatenate([b.data[0].asnumpy() for b in batches])
        np.testing.assert_array_equal(got, data)
        assert batches[0].pad == 0


def test_device_prefetcher_mesh_sharding():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from mxnet_tpu import parallel

    ndev = len(jax.devices())
    mesh = parallel.data_parallel_mesh(ndev)
    data = np.random.RandomState(1).rand(2 * ndev + 1, 3) \
        .astype(np.float32)
    it = DataLoader(gluon.data.ArrayDataset(data,
                                            np.zeros(len(data),
                                                     np.float32)),
                    batch_size=2 * ndev, last_batch="keep")
    batches = list(DevicePrefetcher(it, depth=2, mesh=mesh))
    full = batches[0][0]._data
    want = NamedSharding(mesh, PartitionSpec("dp"))
    assert full.sharding.is_equivalent_to(want, full.ndim)
    # ragged tail (1 row) can't shard the batch axis: replicated
    tail = batches[-1][0]._data
    repl = NamedSharding(mesh, PartitionSpec())
    assert tail.sharding.is_equivalent_to(repl, tail.ndim)
    # values survive placement
    got = np.concatenate([b[0].asnumpy() for b in batches])
    np.testing.assert_array_equal(got, data)


def test_device_prefetcher_early_break_stops_producer():
    def endless():
        i = 0
        while True:
            yield np.full((2, 2), i, np.float32)
            i += 1

    pf = DevicePrefetcher(endless(), depth=2)
    it = iter(pf)
    a = next(it)
    np.testing.assert_array_equal(a.asnumpy(), np.zeros((2, 2)))
    next(it)
    pf.close()
    assert not [t for t in threading.enumerate()
                if t.name == "mxtpu-device-prefetch" and t.is_alive()]


def test_device_prefetcher_forwards_source_exception():
    def boom():
        yield np.zeros((2,), np.float32)
        raise RuntimeError("source exploded")

    pf = DevicePrefetcher(boom(), depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="source exploded"):
        next(it)


# -- batch-vectorized normalize/flip -------------------------------------------

def test_normalize_flip_batch_np_bit_parity():
    from mxnet_tpu import image as image_mod

    rng = np.random.RandomState(4)
    u8 = rng.randint(0, 256, (6, 9, 7, 3)).astype(np.uint8)
    mirror = np.array([1, 0, 1, 1, 0, 0], bool)
    scale = 1 / 255.0
    mean = np.array([0.2, 0.3, 0.4], np.float32).reshape(3, 1, 1)
    std = np.array([1.1, 0.9, 1.3], np.float32).reshape(3, 1, 1)
    # the per-sample reference op sequence, exactly as _decode_one had it
    ref = np.stack([
        ((arr[:, ::-1, :] if m else arr).astype(np.float32)
         .transpose(2, 0, 1) * scale - mean) / std
        for arr, m in zip(u8, mirror)])
    got = image_mod.normalize_flip_batch_np(u8.copy(), mirror, scale,
                                            mean, std)
    np.testing.assert_array_equal(got, ref)
    # preallocated output is honored
    out = np.empty((6, 3, 9, 7), np.float32)
    assert image_mod.normalize_flip_batch_np(
        u8.copy(), mirror, scale, mean, std, out=out) is out
    np.testing.assert_array_equal(out, ref)


def _write_rec(tmp_path, n, size):
    from PIL import Image

    path = str(tmp_path / "pipe.rec")
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(path, "w")
    payloads = []
    for i in range(n):
        arr = rng.randint(0, 255, size + (3,)).astype(np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format="jpeg")
        payloads.append(buf.getvalue())
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              payloads[-1]))
    w.close()
    return path, payloads


def test_image_record_iter_python_batch_parity(tmp_path, monkeypatch):
    """The vectorized pure-python branch is bit-identical to the
    per-sample reference path, mirror flags included."""
    from mxnet_tpu import _native as native_mod
    from mxnet_tpu.io import io as io_mod

    path, payloads = _write_rec(tmp_path, 4, (40, 48))
    monkeypatch.setattr(native_mod, "has_jpeg", lambda: False)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=4,
              mean_r=0.5, std_g=1.2, scale=1 / 255.0, rand_mirror=True)
    it = io_mod.ImageRecordIter(**kw)
    np.random.seed(7)
    got = it.next().data[0].asnumpy()
    np.random.seed(7)
    mirror = np.random.rand(4) < 0.5
    ref = np.stack([it._decode_one(p, m)
                    for p, m in zip(payloads, mirror)])
    np.testing.assert_array_equal(got, ref)


def test_image_iter_vectorized_tail_parity(tmp_path):
    """ImageIter's hoisted flip/cast/normalize suffix matches running the
    full augmenter list per sample — same RNG stream, same pixels."""
    import random as _pyrandom

    from mxnet_tpu import image as image_mod

    path, payloads = _write_rec(tmp_path, 4, (36, 44))
    mean = np.array([100.0, 50.0, 25.0])
    std = np.array([2.0, 3.0, 4.0])

    def make_augs():
        return [image_mod.CenterCropAug((24, 24)),
                image_mod.HorizontalFlipAug(0.5),
                image_mod.CastAug(),
                image_mod.ColorNormalizeAug(mean, std)]

    it = image_mod.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                             path_imgrec=path, aug_list=make_augs())
    assert len(it._aug_tail) == 3  # flip + cast + normalize hoisted
    _pyrandom.seed(11)
    got = it.next().data[0].asnumpy()

    # reference: the full per-sample pipeline, same RNG seed
    _pyrandom.seed(11)
    ref = np.empty((4, 3, 24, 24), np.float32)
    for i, payload in enumerate(payloads):
        arr = image_mod.imdecode_np(payload)
        arr = image_mod.center_crop_np(arr, (24, 24))
        if _pyrandom.random() < 0.5:
            arr = arr[:, ::-1, :]
        a = arr.astype(np.float32)          # CastAug
        a = (a - mean) / std                # ColorNormalizeAug (f64)
        ref[i] = a.astype(np.float32).transpose(2, 0, 1)
    np.testing.assert_array_equal(got, ref)


def test_image_iter_jitter_keeps_tail_minimal(tmp_path):
    """A non-hoistable aug (brightness jitter) between cast and normalize
    limits the hoisted suffix to the normalize alone."""
    from mxnet_tpu import image as image_mod

    path, _ = _write_rec(tmp_path, 4, (36, 44))
    it = image_mod.ImageIter(
        batch_size=2, data_shape=(3, 24, 24), path_imgrec=path,
        aug_list=image_mod.CreateAugmenter(
            data_shape=(3, 24, 24), rand_mirror=True, brightness=0.1,
            mean=np.array([1.0, 2.0, 3.0]), std=np.ones(3)))
    assert len(it._aug_tail) == 1
    assert isinstance(it._aug_tail[0], image_mod.ColorNormalizeAug)
    batch = it.next()
    assert batch.data[0].shape == (2, 3, 24, 24)
