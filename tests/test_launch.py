"""tools/launch.py supervision semantics: exit-code handling (only
nonzero exits are failures; teardown-induced codes are never reported),
SIGTERM→SIGKILL grace escalation, full-gang restart, and --elastic
single-rank respawn."""

import importlib.util
import os
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "_mxtpu_launch", os.path.join(_REPO, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


launch = _load_launch()


def _cmd(script, *args):
    return [sys.executable, "-c", script] + list(args)


def test_supervise_all_clean_exits_zero():
    procs = launch._spawn_gang(_cmd("import sys; sys.exit(0)"), 2,
                               port=0)
    assert launch._supervise_gang(procs, poll_interval=0.05) == 0


def test_supervise_reports_first_failure_and_escalates():
    """The failing rank's code is THE failure; the survivor ignores
    SIGTERM so teardown must escalate to SIGKILL after the grace — and
    the survivor's -9 must NOT replace the real code."""
    sleeper = _cmd("import signal, time\n"
                   "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
                   "time.sleep(60)")
    failer = _cmd("import time, sys\ntime.sleep(0.7)\nsys.exit(3)")
    procs = [launch._spawn_worker(sleeper, 0, 2, port=0),
             launch._spawn_worker(failer, 1, 2, port=0)]
    t0 = time.monotonic()
    code = launch._supervise_gang(procs, grace=0.5, poll_interval=0.05)
    elapsed = time.monotonic() - t0
    assert code == 3
    assert procs[0].returncode == -9        # SIGKILL escalation landed
    assert elapsed < 20, "grace escalation did not bound the teardown"


def test_supervise_clean_finish_after_peer_exit_is_not_failure():
    """A worker that exits 0 after its peer already exited 0 is
    complete — the gang result is success, not an error."""
    fast = _cmd("import sys; sys.exit(0)")
    slow = _cmd("import time, sys\ntime.sleep(0.5)\nsys.exit(0)")
    procs = [launch._spawn_worker(fast, 0, 2, port=0),
             launch._spawn_worker(slow, 1, 2, port=0)]
    assert launch._supervise_gang(procs, poll_interval=0.05) == 0


def test_launch_local_restarts_full_gang(tmp_path):
    """Default mode: one nonzero exit tears the gang down and
    --max-restarts relaunches everyone; the second attempt (marker
    files exist) succeeds."""
    script = ("import os, sys\n"
              "m = os.path.join(sys.argv[1],"
              " 'm' + os.environ['MXTPU_WORKER_RANK'])\n"
              "if os.path.exists(m):\n"
              "    sys.exit(0)\n"
              "open(m, 'w').close()\n"
              "sys.exit(1)\n")
    rc = launch.main(["-n", "2", "--max-restarts", "1", "--grace", "5",
                      "--", sys.executable, "-c", script,
                      str(tmp_path)])
    assert rc == 0
    assert sorted(os.listdir(tmp_path)) == ["m0", "m1"]


def test_launch_local_exhausted_restarts_returns_failure(tmp_path):
    rc = launch.main(["-n", "1", "--max-restarts", "1", "--",
                      sys.executable, "-c", "import sys; sys.exit(9)"])
    assert rc == 9


def test_launch_elastic_respawns_only_dead_rank(tmp_path, monkeypatch):
    """--elastic: a dying rank is absorbed and respawned individually —
    the surviving rank's process is never touched — and every worker
    gets the gang control-plane env."""
    monkeypatch.setenv("MXTPU_ELASTIC_RESPAWN_DELAY", "0.01")
    gang_dir = tmp_path / "gang"
    script = ("import os, sys, time\n"
              "d = sys.argv[1]\n"
              "r = os.environ['MXTPU_WORKER_RANK']\n"
              "assert os.environ.get('MXTPU_ELASTIC') == '1'\n"
              "assert os.environ.get('MXTPU_GANG_DIR')\n"
              "open(os.path.join(d, 'pid%s_%d' % (r, os.getpid())),"
              " 'w').close()\n"
              "lives = len([f for f in os.listdir(d)"
              " if f.startswith('pid' + r + '_')])\n"
              "if r == '1' and lives == 1:\n"
              "    sys.exit(7)\n"
              "time.sleep(1.0)\n"
              "sys.exit(0)\n")
    rc = launch.main(["-n", "2", "--elastic", "--gang-dir",
                      str(gang_dir), "--max-restarts", "1", "--",
                      sys.executable, "-c", script, str(tmp_path)])
    assert rc == 0
    pids = sorted(f for f in os.listdir(tmp_path)
                  if f.startswith("pid"))
    assert len([f for f in pids if f.startswith("pid0_")]) == 1
    assert len([f for f in pids if f.startswith("pid1_")]) == 2


def test_launch_elastic_no_survivors_is_failure(tmp_path):
    rc = launch.main(["-n", "1", "--elastic", "--gang-dir",
                      str(tmp_path / "gang"), "--",
                      sys.executable, "-c", "import sys; sys.exit(5)"])
    assert rc == 5


def test_elastic_requires_local_launcher(tmp_path):
    hosts = tmp_path / "hosts"
    hosts.write_text("localhost\n")
    with pytest.raises(SystemExit):
        launch.main(["-n", "1", "--launcher", "ssh", "--hostfile",
                     str(hosts), "--elastic", "--", "true"])
