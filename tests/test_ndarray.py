"""NDArray core tests (reference: tests/python/unittest/test_ndarray.py)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), 0)
    b = nd.ones((4,), dtype="int32")
    assert b.asnumpy().tolist() == [1, 1, 1, 1]
    c = nd.full((2, 2), 7.5)
    assert np.allclose(c.asnumpy(), 7.5)
    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2) and d.dtype == np.float32
    e = nd.arange(0, 10, 2)
    assert e.asnumpy().tolist() == [0, 2, 4, 6, 8]


def test_arithmetic():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    assert np.allclose((a + b).asnumpy(), [5, 7, 9])
    assert np.allclose((a - b).asnumpy(), [-3, -3, -3])
    assert np.allclose((a * b).asnumpy(), [4, 10, 18])
    assert np.allclose((b / a).asnumpy(), [4, 2.5, 2])
    assert np.allclose((a ** 2).asnumpy(), [1, 4, 9])
    assert np.allclose((2 + a).asnumpy(), [3, 4, 5])
    assert np.allclose((1 - a).asnumpy(), [0, -1, -2])
    assert np.allclose((-a).asnumpy(), [-1, -2, -3])


def test_comparison_elementwise():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([3.0, 2.0, 1.0])
    assert (a == b).asnumpy().tolist() == [0, 1, 0]
    assert (a < b).asnumpy().tolist() == [1, 0, 0]
    assert (a >= b).asnumpy().tolist() == [0, 1, 1]


def test_inplace_version_bump():
    a = nd.zeros((3,))
    v0 = a.version
    a += 1
    assert a.version > v0
    assert np.allclose(a.asnumpy(), 1)
    a *= 3
    assert np.allclose(a.asnumpy(), 3)


def test_setitem_getitem():
    a = nd.zeros((3, 4))
    a[1] = 5.0
    assert np.allclose(a.asnumpy()[1], 5)
    a[0, 2] = 1.0
    assert a.asnumpy()[0, 2] == 1
    b = a[1]
    assert b.shape == (4,)
    c = a[0:2, 1:3]
    assert c.shape == (2, 2)
    idx = nd.array([0, 2], dtype="int32")
    d = a[idx]
    assert d.shape == (2, 4)


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 0)).shape == (6, 4)
    assert a.reshape(6, 4).shape == (6, 4)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)


def test_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    out = nd.dot(a, b)
    assert out.shape == (3, 5)
    assert np.allclose(out.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)


def test_reduce_ops():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    a = nd.array(x)
    assert np.allclose(nd.sum(a).asnumpy(), x.sum(), rtol=1e-5)
    assert np.allclose(nd.sum(a, axis=1).asnumpy(), x.sum(1), rtol=1e-5)
    assert np.allclose(nd.mean(a, axis=(0, 2)).asnumpy(), x.mean((0, 2)),
                       rtol=1e-5)
    assert np.allclose(nd.max(a, axis=1, keepdims=True).asnumpy(),
                       x.max(1, keepdims=True))
    assert np.allclose(
        nd.sum(a, axis=1, exclude=True).asnumpy(), x.sum((0, 2)), rtol=1e-5)


def test_broadcast_ops():
    a = nd.array(np.random.rand(2, 1, 4))
    b = nd.array(np.random.rand(1, 3, 4))
    out = nd.broadcast_add(a, b)
    assert out.shape == (2, 3, 4)
    assert np.allclose(out.asnumpy(), a.asnumpy() + b.asnumpy(), rtol=1e-6)
    c = nd.broadcast_to(nd.ones((1, 3)), shape=(4, 3))
    assert c.shape == (4, 3)


def test_concat_split_stack():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_unary_math():
    x = np.random.rand(5).astype(np.float32) + 0.5
    a = nd.array(x)
    assert np.allclose(nd.exp(a).asnumpy(), np.exp(x), rtol=1e-5)
    assert np.allclose(nd.log(a).asnumpy(), np.log(x), rtol=1e-5)
    assert np.allclose(nd.sqrt(a).asnumpy(), np.sqrt(x), rtol=1e-5)
    assert np.allclose(nd.rsqrt(a).asnumpy(), 1 / np.sqrt(x), rtol=1e-5)
    assert np.allclose(nd.sigmoid(a).asnumpy(), 1 / (1 + np.exp(-x)),
                       rtol=1e-5)
    assert np.allclose(nd.relu(nd.array([-1.0, 1.0])).asnumpy(), [0, 1])


def test_indexing_ops():
    w = nd.array(np.arange(12).reshape(4, 3).astype(np.float32))
    idx = nd.array([0, 2], dtype="int32")
    out = nd.take(w, idx)
    assert np.allclose(out.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    oh = nd.one_hot(idx, depth=4)
    assert oh.shape == (2, 4)
    assert oh.asnumpy()[0, 0] == 1 and oh.asnumpy()[1, 2] == 1
    picked = nd.pick(w, nd.array([1, 0, 2, 1]), axis=1)
    assert np.allclose(picked.asnumpy(), [1, 3, 8, 10])


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    idx = nd.topk(a, k=2)
    assert idx.shape == (2, 2)
    both = nd.topk(a, k=1, ret_typ="both")
    assert np.allclose(both[0].asnumpy().ravel(), [3, 5])
    s = nd.sort(a, is_ascend=False)
    assert np.allclose(s.asnumpy()[0], [3, 2, 1])


def test_astype_cast():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = nd.cast(a, dtype="float16")
    assert c.dtype == np.float16


def test_context_roundtrip():
    a = nd.ones((2, 2), ctx=mx.cpu(0))
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b is a


def test_copyto():
    a = nd.ones((2, 2))
    b = nd.zeros((2, 2))
    a.copyto(b)
    assert np.allclose(b.asnumpy(), 1)


def test_save_load(tmp_path):
    f = str(tmp_path / "x.params")
    d = {"a": nd.array([1.0, 2.0]), "b": nd.ones((2, 3), dtype="int32")}
    nd.save(f, d)
    back = nd.load(f)
    assert set(back) == {"a", "b"}
    assert np.allclose(back["a"].asnumpy(), [1, 2])
    assert back["b"].dtype == np.int32
    lst = [nd.zeros((2,)), nd.ones((3,))]
    nd.save(f, lst)
    back = nd.load(f)
    assert isinstance(back, list) and len(back) == 2


def test_wait_and_waitall():
    a = nd.ones((100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == pytest.approx(3.5)
    assert int(nd.array([7], dtype="int32")) == 7
    with pytest.raises(mx.MXNetError):
        nd.zeros((2, 2)).asscalar()


def test_where_clip():
    cond = nd.array([1.0, 0.0, 1.0])
    x, y = nd.ones((3,)), nd.zeros((3,))
    assert nd.where(cond, x, y).asnumpy().tolist() == [1, 0, 1]
    assert nd.clip(nd.array([-2.0, 0.5, 9.0]), 0.0, 1.0).asnumpy().tolist() \
        == [0, 0.5, 1]


def test_random_ops():
    a = nd.random.uniform(0, 1, shape=(100,))
    assert a.shape == (100,)
    assert 0 <= float(nd.min(a)) and float(nd.max(a)) <= 1
    b = nd.random.normal(0, 1, shape=(1000,))
    assert abs(float(nd.mean(b))) < 0.2
    c = nd.random.randint(0, 10, shape=(50,))
    assert c.dtype == np.int32
    mx.random.seed(42)
    x1 = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    x2 = nd.random.uniform(shape=(5,)).asnumpy()
    assert np.allclose(x1, x2)


def test_control_flow_foreach():
    data = nd.array(np.arange(6).reshape(3, 2).astype(np.float32))
    init = nd.zeros((2,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = nd.foreach(body, data, init)
    assert np.allclose(final.asnumpy(), [6, 9])
    assert outs.shape == (3, 2)


def test_linalg():
    a = np.random.rand(3, 3).astype(np.float32)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    L = nd.linalg.potrf(nd.array(spd))
    assert np.allclose(L.asnumpy() @ L.asnumpy().T, spd, atol=1e-4)
    g = nd.linalg.gemm2(nd.array(a), nd.array(a), transpose_b=True)
    assert np.allclose(g.asnumpy(), a @ a.T, atol=1e-5)


@pytest.mark.skipif(not os.environ.get("MXNET_TEST_LARGE"),
                    reason="nightly tier (reference: tests/nightly/"
                           "test_large_array.py) — set MXNET_TEST_LARGE=1; "
                           "allocates >2 GB")
def test_large_array_int64_indexing():
    """INT64_TENSOR_SIZE: element counts past 2^31 index correctly
    (reference nightly large-array tier).  Covers the three x32
    failure modes found building this: index-carry overflow, silent
    scatter drop on >2^31 dims, and int64-creation truncation."""
    n = 2_200_000_000  # > 2^31
    a = mx.nd.zeros((n,), dtype="int8")
    a[n - 1] = 7
    a[5] = 2  # small index on a HUGE dim: x32 scatter silently drops
    assert int(a[n - 1].asnumpy()) == 7
    assert int(a[5].asnumpy()) == 2
    assert int(a.sum().asnumpy()) == 9
    assert a.shape == (n,)
    idx = mx.nd.array(np.array([5, n - 1], np.int64), dtype="int64")
    assert idx.dtype == np.int64  # creation must honor int64
    assert list(mx.nd.take(a, idx).asnumpy()) == [2, 7]


def test_explicit_64bit_dtypes_roundtrip(tmp_path):
    """Explicit int64/float64 NDArrays must hold and save/load values
    past 32-bit range (jax's x32 default silently wrapped both — the
    creation and load paths route through x64)."""
    i64 = mx.nd.array(np.array([5, 2_199_999_999], np.int64),
                      dtype="int64")
    f64 = mx.nd.array(np.array([1.5, 1e300]), dtype="float64")
    assert i64.dtype == np.int64 and f64.dtype == np.float64
    assert int(i64.asnumpy()[1]) == 2_199_999_999
    assert np.isfinite(f64.asnumpy()[1])
    f = str(tmp_path / "big.params")
    mx.nd.save(f, {"i": i64, "f": f64})
    back = mx.nd.load(f)
    assert back["i"].dtype == np.int64
    np.testing.assert_array_equal(back["i"].asnumpy(), i64.asnumpy())
    assert back["f"].dtype == np.float64
    np.testing.assert_array_equal(back["f"].asnumpy(), f64.asnumpy())


def test_64bit_creators_and_casts():
    """zeros/ones/full/arange/astype/cast honor 64-bit dtypes with
    values past 32-bit range (each routed through x64_scope_if)."""
    assert nd.zeros((3,), dtype="int64").dtype == np.int64
    assert nd.ones((2,), dtype="float64").dtype == np.float64
    assert int(nd.full((2,), 2_199_999_999,
                       dtype="int64").asnumpy()[0]) == 2_199_999_999
    ar = nd.arange(2_199_999_998, 2_200_000_001, 1, dtype="int64")
    assert ar.dtype == np.int64
    assert int(ar.asnumpy()[-1]) == 2_200_000_000
    a = nd.array(np.array([2.2e9]), dtype="float64")
    assert int(a.astype("int64").asnumpy()[0]) == 2_200_000_000
    assert int(nd.cast(a, dtype="int64").asnumpy()[0]) == 2_200_000_000


def test_64bit_pickle_setitem_linspace_eye():
    """Review regressions: pickle round-trip, large scalar setitem into
    int64, linspace/eye 64-bit dtypes, and x64 getitem on the tape."""
    import pickle

    from mxnet_tpu import autograd

    a = nd.array(np.array([2_199_999_999], np.int64), dtype="int64")
    b = pickle.loads(pickle.dumps(a))
    assert b.dtype == np.int64
    assert int(b.asnumpy()[0]) == 2_199_999_999
    c = nd.zeros((4,), dtype="int64")
    c[0] = 2_200_000_000
    assert int(c.asnumpy()[0]) == 2_200_000_000
    lin = nd.linspace(0, 1e300, 3, dtype="float64")
    assert lin.dtype == np.float64 and np.isfinite(lin.asnumpy()[-1])
    assert nd.eye(3, dtype="int64").dtype == np.int64
    x = nd.array(np.arange(6, dtype=np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x[1:4] * 2).sum()
    y.backward()
    assert list(x.grad.asnumpy()) == [0, 2, 2, 2, 0, 0]
