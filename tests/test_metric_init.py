"""Metric + initializer tests (reference: tests/python/unittest/test_metric.py
and test_init.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import initializer as init
from mxnet_tpu import metric


def test_accuracy():
    m = metric.create("acc")
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, value = m.get()
    assert name == "accuracy"
    assert value == pytest.approx(2.0 / 3.0)


def test_topk():
    m = metric.create("top_k_accuracy", top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.8, 0.1, 0.1]])
    label = mx.nd.array([2, 1])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_f1():
    m = metric.F1()
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]])
    label = mx.nd.array([1, 0, 1, 1])
    m.update([label], [pred])
    # tp=2 fp=0 fn=1 → p=1, r=2/3 → f1=0.8
    assert m.get()[1] == pytest.approx(0.8)


def test_mse_mae_rmse():
    label = mx.nd.array([1.0, 2.0, 3.0])
    pred = mx.nd.array([1.0, 2.0, 5.0])
    for name, exp in [("mse", 4.0 / 3), ("mae", 2.0 / 3),
                      ("rmse", np.sqrt(4.0 / 3))]:
        m = metric.create(name)
        m.update([label], [pred])
        assert m.get()[1] == pytest.approx(exp)


def test_perplexity():
    m = metric.Perplexity(ignore_label=None)
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    m.update([label], [pred])
    exp = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert m.get()[1] == pytest.approx(exp)


def test_composite_and_custom():
    m = metric.create(["acc", "mse"])
    pred = mx.nd.array([[0.3, 0.7]])
    label = mx.nd.array([1])
    m.get_metric(0).update([label], [pred])
    names, values = m.get()
    assert "accuracy" in names

    def my_metric(label, pred):
        return float(np.sum(pred))
    cm = metric.create(my_metric)
    cm.update([label], [pred])
    assert cm.get()[1] == pytest.approx(1.0)


def test_loss_metric():
    m = metric.Loss()
    m.update(None, [mx.nd.array([1.0, 2.0, 3.0])])
    assert m.get()[1] == pytest.approx(2.0)


def test_initializers_shapes_and_stats():
    mx.random.seed(42)
    np.random.seed(42)
    arr = mx.nd.zeros((64, 32))
    init.Xavier(rnd_type="gaussian", factor_type="in", magnitude=2)(
        init.InitDesc("fc_weight"), arr)
    std = arr.asnumpy().std()
    assert std == pytest.approx(np.sqrt(2.0 / 32), rel=0.2)

    arr2 = mx.nd.zeros((10,))
    init.Uniform(0.5)(init.InitDesc("x_weight"), arr2)
    assert np.abs(arr2.asnumpy()).max() <= 0.5

    arr3 = mx.nd.zeros((8, 8))
    init.Orthogonal()(init.InitDesc("q_weight"), arr3)
    a = arr3.asnumpy() / 1.414
    np.testing.assert_allclose(a @ a.T, np.eye(8), atol=1e-5)


def test_initializer_name_dispatch():
    ini = init.Xavier()
    bias = mx.nd.ones((4,))
    ini(init.InitDesc("fc1_bias"), bias)
    np.testing.assert_allclose(bias.asnumpy(), np.zeros(4))
    gamma = mx.nd.zeros((4,))
    ini(init.InitDesc("bn_gamma"), gamma)
    np.testing.assert_allclose(gamma.asnumpy(), np.ones(4))


def test_constant_and_mixed():
    arr = mx.nd.zeros((3, 3))
    init.Constant(2.5)(init.InitDesc("c_weight"), arr)
    np.testing.assert_allclose(arr.asnumpy(), 2.5 * np.ones((3, 3)))
    mixed = init.Mixed([".*fc2.*", ".*"], [init.One(), init.Constant(3.0)])
    b = mx.nd.zeros((2,))
    mixed(init.InitDesc("fc2_weight"), b)
    np.testing.assert_allclose(b.asnumpy(), np.ones(2))
    c = mx.nd.zeros((2,))
    mixed(init.InitDesc("fc1_weight"), c)
    np.testing.assert_allclose(c.asnumpy(), 3.0 * np.ones(2))


def test_initializer_dumps_create_roundtrip():
    ini = init.Xavier(rnd_type="gaussian", magnitude=2)
    import json

    name, kwargs = json.loads(ini.dumps())
    ini2 = init.create(name, **kwargs)
    assert ini == ini2


def test_lstmbias():
    arr = mx.nd.ones((8,))
    init.LSTMBias(forget_bias=1.0)(init.InitDesc("lstm_bias"), arr)
    out = arr.asnumpy()
    np.testing.assert_allclose(out[2:4], np.ones(2))
    np.testing.assert_allclose(out[:2], np.zeros(2))


def test_accuracy_device_numpy_parity():
    """Device-side fused accuracy (NDArray inputs) must agree exactly
    with the host numpy path (plain array inputs)."""
    rng = np.random.RandomState(0)
    pred = rng.rand(64, 10).astype("float32")
    label = rng.randint(0, 10, size=(64,)).astype("float32")
    dev = metric.Accuracy()
    dev.update([mx.nd.array(label)], [mx.nd.array(pred)])
    host = metric.Accuracy()
    host.update([label], [pred])
    assert dev.get() == host.get()
    # same-shape (no argmax) comparison path
    dev2 = metric.Accuracy()
    dev2.update([mx.nd.array([0, 1, 1])], [mx.nd.array([0, 1, 0])])
    assert dev2.get()[1] == pytest.approx(2.0 / 3.0)


def test_topk_device_numpy_parity():
    rng = np.random.RandomState(1)
    pred = rng.rand(64, 10).astype("float32")
    label = rng.randint(0, 10, size=(64,)).astype("float32")
    for k in (2, 3, 5):
        dev = metric.TopKAccuracy(top_k=k)
        dev.update([mx.nd.array(label)], [mx.nd.array(pred)])
        host = metric.TopKAccuracy(top_k=k)
        host.update([label], [pred])
        assert dev.get() == host.get()


def test_accuracy_device_shape_mismatch_error():
    m = metric.Accuracy()
    with pytest.raises(ValueError, match="Shape of labels"):
        m.update([mx.nd.array([0, 1])], [mx.nd.array([[0.1, 0.9]])])
