/*
 * mxnet-tpu-cpp — header-only C++ frontend over the flat C ABI.
 *
 * Reference parity: cpp-package/include/mxnet-cpp/ (NDArray, Operator) —
 * the reference's C++ binding is a thin RAII/operator layer over
 * c_api.h; this is the same layer over mxtpu_c_api.h.  Proof-of-design
 * for SURVEY §2.4 "other-language bindings": nothing here knows about
 * Python or JAX, only the C handles.
 */
#ifndef MXNET_TPU_CPP_NDARRAY_HPP_
#define MXNET_TPU_CPP_NDARRAY_HPP_

#include <cstdint>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "mxtpu_c_api.h"

namespace mxtpu {
namespace cpp {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXGetLastError());
}

/* Boot (or attach to) the runtime once per process. */
inline void Init() { Check(MXTPUInit()); }

class NDArray {
 public:
  NDArray() : h_(nullptr) {}
  explicit NDArray(NDArrayHandle h) : h_(h) {}

  NDArray(const std::vector<float> &data,
          const std::vector<int64_t> &shape) {
    Check(MXNDArrayCreate(data.data(), data.size() * sizeof(float),
                          shape.data(), static_cast<int>(shape.size()),
                          "float32", &h_));
  }

  ~NDArray() {
    if (h_) MXNDArrayFree(h_);
  }

  NDArray(NDArray &&o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  NDArray &operator=(NDArray &&o) noexcept {
    if (this != &o) {
      if (h_) MXNDArrayFree(h_);
      h_ = o.h_;
      o.h_ = nullptr;
    }
    return *this;
  }
  NDArray(const NDArray &) = delete;
  NDArray &operator=(const NDArray &) = delete;

  NDArrayHandle handle() const { return h_; }
  bool is_none() const { return h_ == nullptr; }

  std::vector<int64_t> Shape() const {
    int ndim = 0;
    int64_t dims[8];
    Check(MXNDArrayGetShape(h_, &ndim, dims));
    return std::vector<int64_t>(dims, dims + ndim);
  }

  size_t Size() const {
    size_t n = 0;
    Check(MXNDArraySize(h_, &n));
    return n;
  }

  /* Blocking device->host copy (the reference's SyncCopyToCPU). */
  std::vector<float> ToVector() const {
    std::vector<float> out(Size() / sizeof(float));
    Check(MXNDArraySyncCopyToCPU(h_, out.data(),
                                 out.size() * sizeof(float)));
    return out;
  }

  void AttachGrad() { Check(MXAutogradAttachGrad(h_)); }

  NDArray Grad() const {
    NDArrayHandle g = nullptr;
    Check(MXNDArrayGetGrad(h_, &g));
    return NDArray(g);
  }

 private:
  NDArrayHandle h_;
};

/* Operator invocation builder (reference: mxnet-cpp Operator). */
class Operator {
 public:
  explicit Operator(std::string name) : name_(std::move(name)) {}

  Operator &AddInput(const NDArray &a) {
    inputs_.push_back(a.handle());
    return *this;
  }

  Operator &SetParam(const std::string &k, const std::string &v) {
    keys_.push_back(k);
    vals_.push_back(v);
    return *this;
  }

  std::vector<NDArray> Invoke() {
    std::vector<const char *> ks, vs;
    for (auto &k : keys_) ks.push_back(k.c_str());
    for (auto &v : vals_) vs.push_back(v.c_str());
    NDArrayHandle outs[8] = {nullptr};
    int n_out = 8;
    Check(MXImperativeInvoke(name_.c_str(), inputs_.data(),
                             static_cast<int>(inputs_.size()),
                             ks.data(), vs.data(),
                             static_cast<int>(ks.size()), outs, &n_out));
    std::vector<NDArray> result;
    result.reserve(n_out);
    for (int i = 0; i < n_out; ++i) result.emplace_back(outs[i]);
    return result;
  }

 private:
  std::string name_;
  std::vector<NDArrayHandle> inputs_;
  std::vector<std::string> keys_, vals_;
};

/* Autograd scope (reference: mxnet-cpp autograd record). */
class AutogradRecord {
 public:
  AutogradRecord() { Check(MXAutogradRecordStart()); }
  ~AutogradRecord() { MXAutogradRecordStop(); }
};

inline void Backward(const NDArray &loss) {
  Check(MXAutogradBackward(loss.handle()));
}

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_NDARRAY_HPP_
