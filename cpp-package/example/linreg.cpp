/*
 * Train 1D linear regression through the C++ binding ONLY — no Python
 * source in this program (reference analog: cpp-package/example/
 * mlp_cpu.cpp driving c_api.h).
 *
 * Build (from repo root; libmxtpu.so built by `make -C src`):
 *   g++ -std=c++17 cpp-package/example/linreg.cpp \
 *       -Icpp-package/include/mxnet-tpu-cpp -Isrc \
 *       -Lsrc -lmxtpu -Wl,-rpath,$PWD/src -o /tmp/linreg_cpp
 *   PYTHONPATH=$PWD /tmp/linreg_cpp
 */
#include <cstdio>
#include <cmath>
#include <vector>

#include "ndarray.hpp"

using mxtpu::cpp::AutogradRecord;
using mxtpu::cpp::Backward;
using mxtpu::cpp::NDArray;
using mxtpu::cpp::Operator;

int main() {
  mxtpu::cpp::Init();

  // y = 3x - 1
  std::vector<float> xs, ys;
  for (int i = 0; i < 32; ++i) {
    float x = static_cast<float>(i) / 8.0f - 2.0f;
    xs.push_back(x);
    ys.push_back(3.0f * x - 1.0f);
  }
  NDArray x(xs, {32, 1});
  NDArray y(ys, {32, 1});
  NDArray w(std::vector<float>{0.0f}, {1, 1});
  NDArray b(std::vector<float>{0.0f}, {1});
  w.AttachGrad();
  b.AttachGrad();

  float lr = 0.2f;
  for (int step = 0; step < 60; ++step) {
    NDArray loss;
    {
      AutogradRecord rec;
      auto wx = Operator("dot").AddInput(x).AddInput(w).Invoke();
      auto pred = Operator("broadcast_add")
                      .AddInput(wx[0])
                      .AddInput(b)
                      .Invoke();
      auto diff = Operator("broadcast_sub")
                      .AddInput(pred[0])
                      .AddInput(y)
                      .Invoke();
      auto sq = Operator("square").AddInput(diff[0]).Invoke();
      auto m = Operator("mean").AddInput(sq[0]).Invoke();
      loss = std::move(m[0]);
    }
    Backward(loss);
    // SGD via the fused optimizer op, still C-surface only; the op
    // returns the updated weight (reference semantics would write
    // through out=, which the flat invoke surface expresses as output 0)
    auto wg = w.Grad();
    auto bg = b.Grad();
    auto w2 = Operator("sgd_update")
                  .AddInput(w)
                  .AddInput(wg)
                  .SetParam("lr", std::to_string(lr))
                  .Invoke();
    auto b2 = Operator("sgd_update")
                  .AddInput(b)
                  .AddInput(bg)
                  .SetParam("lr", std::to_string(lr))
                  .Invoke();
    w = std::move(w2[0]);
    b = std::move(b2[0]);
    w.AttachGrad();
    b.AttachGrad();
  }

  float wf = w.ToVector()[0];
  float bf = b.ToVector()[0];
  std::printf("w=%.4f b=%.4f\n", wf, bf);
  if (std::fabs(wf - 3.0f) > 0.05f || std::fabs(bf + 1.0f) > 0.05f) {
    std::printf("FAIL\n");
    return 1;
  }
  std::printf("PASS\n");
  MXTPUShutdown();
  return 0;
}
