// Native JPEG decode + default augmenter pipeline.
//
// Reference parity: src/io/iter_image_recordio_2.cc's OMP decode loop +
// src/io/image_aug_default.cc (resize-short / crop / mirror /
// mean-std normalize), rebuilt as a flat C entry on a fork-join thread
// pool.  libjpeg-turbo does the codec work; augmentation is fused into
// the decode pass so each image is touched once and written straight
// into the caller's (N, 3, H, W) float batch — the layout the training
// step consumes.
//
// Randomness (crop origin, mirror) comes from the CALLER: python draws
// per-image seeds/flags so seed semantics live in one place and this
// kernel stays pure.

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jmp;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jmp, 1);
}

bool DecodeJpeg(const uint8_t* buf, size_t len, int min_short_side,
                std::vector<uint8_t>* out, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  if (min_short_side > 0) {
    // libjpeg's M/8 scaled decode: pick the smallest scale that still
    // covers the resize target — decode cost drops with pixel count
    // (the trick behind the reference pipeline's decode throughput)
    const int short_side = std::min(cinfo.image_width,
                                    cinfo.image_height);
    int num = 8;
    while (num > 1 && short_side * (num - 1) / 8 >= min_short_side)
      --num;
    cinfo.scale_num = num;
    cinfo.scale_denom = 8;
  }
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// bilinear resize HWC uint8, half-pixel-center sampling (the OpenCV
// INTER_LINEAR convention the reference's augmenter uses; PIL's
// filtered bilinear differs slightly on downscale — both are valid,
// the python fallback keeps PIL)
void ResizeBilinear(const std::vector<uint8_t>& src, int sh, int sw,
                    int dh, int dw, std::vector<uint8_t>* dst) {
  dst->resize(static_cast<size_t>(dh) * dw * 3);
  const float ry = static_cast<float>(sh) / dh;
  const float rx = static_cast<float>(sw) / dw;
  for (int y = 0; y < dh; ++y) {
    const float fy = std::max((y + 0.5f) * ry - 0.5f, 0.f);
    const int y0 = static_cast<int>(fy);
    const int y1 = std::min(y0 + 1, sh - 1);
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      const float fx = std::max((x + 0.5f) * rx - 0.5f, 0.f);
      const int x0 = static_cast<int>(fx);
      const int x1 = std::min(x0 + 1, sw - 1);
      const float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        const float v00 = src[(static_cast<size_t>(y0) * sw + x0) * 3 + c];
        const float v01 = src[(static_cast<size_t>(y0) * sw + x1) * 3 + c];
        const float v10 = src[(static_cast<size_t>(y1) * sw + x0) * 3 + c];
        const float v11 = src[(static_cast<size_t>(y1) * sw + x1) * 3 + c];
        const float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                        v10 * wy * (1 - wx) + v11 * wy * wx;
        (*dst)[(static_cast<size_t>(y) * dw + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// one image: decode -> resize-short -> crop -> mirror -> normalized CHW
bool ProcessOne(const uint8_t* payload, size_t size, int resize_short,
                int out_h, int out_w, int32_t crop_mode, uint64_t seed,
                bool mirror, float scale, const float* mean,
                const float* stdv, float* out) {
  std::vector<uint8_t> img;
  int h = 0, w = 0;
  // scaled decode ONLY when a resize follows (the resample blends away
  // the scale); without resize the crop must see the full-res image
  if (!DecodeJpeg(payload, size, resize_short, &img, &h, &w))
    return false;

  std::vector<uint8_t> tmp;
  if (resize_short > 0) {
    // floor division matches image.py resize_short_np exactly
    int dh, dw;
    if (h > w) {
      dh = static_cast<int>(
          static_cast<int64_t>(resize_short) * h / w);
      dw = resize_short;
    } else {
      dh = resize_short;
      dw = static_cast<int>(
          static_cast<int64_t>(resize_short) * w / h);
    }
    if (dh != h || dw != w) {
      ResizeBilinear(img, h, w, dh, dw, &tmp);
      img.swap(tmp);
      h = dh;
      w = dw;
    }
  }
  // crop semantics match image.py center_crop_np/random_crop_np: the
  // crop window is clamped per-dimension (min(target, dim)) and the
  // CROPPED PATCH is then resized to the target if any dim fell short —
  // an undersized dim stretches, an oversized dim still crops
  const int ch = std::min(h, out_h), cw = std::min(w, out_w);
  int cy, cx;
  if (crop_mode == -2) {  // random crop, caller-seeded
    std::mt19937_64 rng(seed);
    cy = h == ch ? 0 : static_cast<int>(rng() % (h - ch + 1));
    cx = w == cw ? 0 : static_cast<int>(rng() % (w - cw + 1));
  } else {  // center
    cy = (h - ch) / 2;
    cx = (w - cw) / 2;
  }
  if (ch != out_h || cw != out_w) {
    std::vector<uint8_t> patch(static_cast<size_t>(ch) * cw * 3);
    for (int y = 0; y < ch; ++y)
      std::memcpy(patch.data() + static_cast<size_t>(y) * cw * 3,
                  img.data() + (static_cast<size_t>(cy + y) * w + cx) * 3,
                  static_cast<size_t>(cw) * 3);
    ResizeBilinear(patch, ch, cw, out_h, out_w, &tmp);
    img.swap(tmp);
    h = out_h;
    w = out_w;
    cy = cx = 0;
  }
  const float inv_std[3] = {1.f / stdv[0], 1.f / stdv[1], 1.f / stdv[2]};
  for (int y = 0; y < out_h; ++y) {
    const uint8_t* row =
        img.data() + (static_cast<size_t>(cy + y) * w + cx) * 3;
    for (int x = 0; x < out_w; ++x) {
      const int sx = mirror ? (out_w - 1 - x) : x;
      for (int c = 0; c < 3; ++c) {
        out[(static_cast<size_t>(c) * out_h + y) * out_w + x] =
            (row[sx * 3 + c] * scale - mean[c]) * inv_std[c];
      }
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Probe: 1 when the build carries the libjpeg decode path.
int MXTPUHasJpeg() { return 1; }

// Decode+augment a batch into out (n, 3, out_h, out_w) float32.
// crop_mode per image: -1 center, -2 random (seeded by seeds[i]).
// status per image: 1 decoded, 0 failed (caller falls back).
// Returns the number of failures.
int MXTPUImageDecodeAugment(const uint8_t* const* payloads,
                            const size_t* sizes, int n, int resize_short,
                            int out_h, int out_w,
                            const int32_t* crop_modes,
                            const uint64_t* seeds, const uint8_t* mirror,
                            float scale, const float* mean,
                            const float* stdv, int nthreads, float* out,
                            int32_t* status) {
  const size_t img_elems = static_cast<size_t>(3) * out_h * out_w;
  nthreads = std::max(1, std::min(nthreads, n));
  std::vector<std::thread> workers;
  workers.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) {
    workers.emplace_back([&, t]() {
      for (int i = t; i < n; i += nthreads) {
        status[i] = ProcessOne(payloads[i], sizes[i], resize_short,
                               out_h, out_w, crop_modes[i], seeds[i],
                               mirror[i] != 0, scale, mean, stdv,
                               out + i * img_elems)
                        ? 1
                        : 0;
      }
    });
  }
  for (auto& th : workers) th.join();
  int failures = 0;
  for (int i = 0; i < n; ++i) failures += status[i] == 0;
  return failures;
}

}  // extern "C"
