/*
 * mxtpu_c_api.h — flat C ABI for the mxnet_tpu framework (L5).
 *
 * Reference parity: include/mxnet/c_api.h — the reference's C surface is
 * the contract every language frontend builds on; this is the same
 * contract over the JAX/XLA engine.  libmxtpu.so embeds CPython: a pure
 * C (or Java/Go/...) program links this library, calls MXTPUInit(), and
 * drives NDArrays, operators, autograd and KVStore with no Python code.
 *
 * Conventions (as in the reference):
 *   - every call returns 0 on success, -1 on failure;
 *   - MXGetLastError() returns the failure message for this thread's
 *     most recent failing call;
 *   - handles are opaque; free NDArrays with MXNDArrayFree.
 *
 * The embedded interpreter resolves the mxnet_tpu package through
 * PYTHONPATH (set it to the repo root when embedding).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;
typedef int KVStoreHandle;

/* Boot (or attach to) the Python runtime and import mxnet_tpu. */
int MXTPUInit(void);
/* Shut down the embedded interpreter (no-op when attached). */
int MXTPUShutdown(void);
const char *MXGetLastError(void);

/* -- NDArray ---------------------------------------------------------- */
/* dtype is a numpy dtype name: "float32", "int32", ... */
int MXNDArrayCreate(const void *data, size_t nbytes, const int64_t *shape,
                    int ndim, const char *dtype, NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle h);
int MXNDArrayGetShape(NDArrayHandle h, int *ndim, int64_t shape[8]);
int MXNDArrayGetDType(NDArrayHandle h, char dtype[16]);
/* Blocking copy device -> caller buffer (nbytes must match). */
int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *out, size_t nbytes);
int MXNDArraySize(NDArrayHandle h, size_t *nbytes);

/* -- Operators --------------------------------------------------------- */
/* Invoke a registered operator by name.  Params are string key/value
 * pairs (values parsed like the reference's typed param dict).  On entry
 * *n_out is the capacity of outputs[]; on return it is the actual count. */
int MXImperativeInvoke(const char *op_name, NDArrayHandle *inputs,
                       int n_inputs, const char **param_keys,
                       const char **param_vals, int n_params,
                       NDArrayHandle *outputs, int *n_out);
int MXListAllOpNames(int *count, const char ***names);

/* -- Autograd ---------------------------------------------------------- */
int MXAutogradAttachGrad(NDArrayHandle h);
int MXAutogradRecordStart(void);
int MXAutogradRecordStop(void);
int MXAutogradBackward(NDArrayHandle loss);
int MXNDArrayGetGrad(NDArrayHandle h, NDArrayHandle *out);

/* -- Predictor (reference: include/mxnet/c_predict_api.h) -------------- */
/* Deploy-format inference: symbol.json text + .params bytes in, float32
 * tensors in/out.  The amalgamation/mobile predict surface. */
typedef void *PredictorHandle;
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 size_t param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 PredictorHandle *out);
int MXPredSetInput(PredictorHandle h, const char *key, const float *data,
                   const int64_t *shape, int ndim);
int MXPredForward(PredictorHandle h);
int MXPredGetOutputShape(PredictorHandle h, uint32_t index, int *ndim,
                         int64_t shape[8]);
int MXPredGetOutput(PredictorHandle h, uint32_t index, float *data,
                    size_t n_floats);
int MXPredFree(PredictorHandle h);

/* -- KVStore ----------------------------------------------------------- */
int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreInit(KVStoreHandle kv, int key, NDArrayHandle v);
int MXKVStorePush(KVStoreHandle kv, int key, NDArrayHandle v);
int MXKVStorePull(KVStoreHandle kv, int key, NDArrayHandle *out);
int MXKVStoreFree(KVStoreHandle kv);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
