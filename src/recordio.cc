// Native RecordIO codec + threaded prefetcher.
//
// Reference parity: 3rdparty/dmlc-core RecordIO (include/dmlc/recordio.h,
// src/io/recordio_split.cc) and the threaded data pipeline
// (dmlc::ThreadedIter + src/io/iter_prefetcher.h) — the C++ side of the
// reference's input path, rebuilt for the TPU framework.
//
// Byte-compatible framing with mxnet_tpu/recordio.py:
//   [kMagic u32][cflag(3b)|len(29b) u32][payload][pad to 4]
// cflag: 0 whole, 1 start, 2 middle, 3 end.  dmlc-core split semantics:
// the writer scans only 4-byte-ALIGNED positions for embedded magics,
// EXCISES each from the payload (the chunk boundary stands in for it),
// and the reader re-inserts kMagic before every cflag-2/3 chunk.
//
// Exposed as a flat C API (ctypes-loadable; reference: the c_api layer
// design, include/mxnet/c_api.h).  Build: `make -C src` → libmxtpu_io.so.

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Reader {
  FILE* fp = nullptr;
  std::string err;
};

struct Writer {
  FILE* fp = nullptr;
  std::vector<int64_t> idx;  // record start offsets
  std::string err;
};

bool ReadRecordAt(FILE* fp, int64_t offset, std::string* out,
                  std::string* err) {
  if (offset >= 0 && std::fseek(fp, offset, SEEK_SET) != 0) {
    *err = "seek failed";
    return false;
  }
  out->clear();
  while (true) {
    uint32_t header[2];
    size_t n = std::fread(header, 1, sizeof(header), fp);
    if (n == 0 && out->empty()) return false;  // clean EOF
    if (n != sizeof(header)) {
      *err = "truncated record header";
      return false;
    }
    if (header[0] != kMagic) {
      *err = "bad magic";
      return false;
    }
    uint32_t cflag = header[1] >> 29;
    uint32_t len = header[1] & kLenMask;
    if (cflag == 2 || cflag == 3) {
      // re-insert the excised embedded magic (dmlc-core NextRecord)
      out->append(reinterpret_cast<const char*>(&kMagic), 4);
    }
    size_t cur = out->size();
    out->resize(cur + len);
    if (len && std::fread(&(*out)[cur], 1, len, fp) != len) {
      *err = "truncated payload";
      return false;
    }
    uint32_t pad = (4 - len % 4) % 4;
    if (pad) std::fseek(fp, pad, SEEK_CUR);
    if (cflag == 0 || cflag == 3) return true;
  }
}

void WriteChunk(FILE* fp, uint32_t cflag, const char* data, uint32_t len) {
  uint32_t header[2] = {kMagic, (cflag << 29) | len};
  std::fwrite(header, 1, sizeof(header), fp);
  std::fwrite(data, 1, len, fp);
  uint32_t pad = (4 - len % 4) % 4;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad) std::fwrite(zeros, 1, pad, fp);
}

}  // namespace

extern "C" {

// ---------- reader ----------

void* mxtpu_recio_open_read(const char* path) {
  auto* r = new Reader();
  r->fp = std::fopen(path, "rb");
  if (!r->fp) {
    delete r;
    return nullptr;
  }
  return r;
}

void mxtpu_recio_close_read(void* h) {
  auto* r = static_cast<Reader*>(h);
  if (r->fp) std::fclose(r->fp);
  delete r;
}

// Scan the whole file, returning record offsets.  Caller frees with
// mxtpu_free_i64.  Returns count, or -1 on error.
int64_t mxtpu_recio_scan(void* h, int64_t** offsets_out) {
  auto* r = static_cast<Reader*>(h);
  std::fseek(r->fp, 0, SEEK_SET);
  std::vector<int64_t> offsets;
  std::string buf;
  while (true) {
    int64_t pos = std::ftell(r->fp);
    std::string err;
    if (!ReadRecordAt(r->fp, -1, &buf, &err)) {
      if (!err.empty()) return -1;
      break;
    }
    offsets.push_back(pos);
  }
  auto* out = new int64_t[offsets.size()];
  std::memcpy(out, offsets.data(), offsets.size() * sizeof(int64_t));
  *offsets_out = out;
  return static_cast<int64_t>(offsets.size());
}

// Read one record at byte offset; caller frees with mxtpu_free.  Returns
// payload size or -1.
int64_t mxtpu_recio_read_at(void* h, int64_t offset, char** data_out) {
  auto* r = static_cast<Reader*>(h);
  std::string buf, err;
  if (!ReadRecordAt(r->fp, offset, &buf, &err)) return -1;
  char* out = new char[buf.size()];
  std::memcpy(out, buf.data(), buf.size());
  *data_out = out;
  return static_cast<int64_t>(buf.size());
}

void mxtpu_free(char* p) { delete[] p; }
void mxtpu_free_i64(int64_t* p) { delete[] p; }

// ---------- writer ----------

void* mxtpu_recio_open_write(const char* path, int append) {
  auto* w = new Writer();
  w->fp = std::fopen(path, append ? "ab" : "wb");
  if (!w->fp) {
    delete w;
    return nullptr;
  }
  return w;
}

int64_t mxtpu_recio_write(void* h, const char* data, int64_t len) {
  auto* w = static_cast<Writer*>(h);
  int64_t pos = std::ftell(w->fp);
  // dmlc-core WriteRecord: scan only 4-byte-aligned positions; each
  // aligned embedded magic is excised (chunk boundary stands in for it)
  const char* magic_bytes = reinterpret_cast<const char*>(&kMagic);
  std::vector<int64_t> splits;
  int64_t lower_align = len & ~static_cast<int64_t>(3);
  for (int64_t i = 0; i < lower_align; i += 4) {
    if (std::memcmp(data + i, magic_bytes, 4) == 0) splits.push_back(i);
  }
  if (splits.empty()) {
    WriteChunk(w->fp, 0, data, static_cast<uint32_t>(len));
  } else {
    int64_t begin = 0;
    for (size_t n = 0; n < splits.size(); ++n) {
      WriteChunk(w->fp, n == 0 ? 1u : 2u, data + begin,
                 static_cast<uint32_t>(splits[n] - begin));
      begin = splits[n] + 4;
    }
    WriteChunk(w->fp, 3, data + begin, static_cast<uint32_t>(len - begin));
  }
  w->idx.push_back(pos);
  return pos;
}

void mxtpu_recio_close_write(void* h) {
  auto* w = static_cast<Writer*>(h);
  if (w->fp) std::fclose(w->fp);
  delete w;
}

// ---------- threaded prefetcher ----------
// The dmlc::ThreadedIter analog: N reader threads pull record indices from
// an epoch queue, read payloads, and push them into a bounded buffer the
// python side drains batch by batch.

struct Prefetcher {
  std::string path;
  std::vector<int64_t> offsets;
  std::vector<uint32_t> order;
  size_t cursor = 0;            // next index to hand to workers
  size_t delivered = 0;         // records handed to python this epoch
  uint64_t epoch = 0;           // guards against stale worker pushes
  bool shuffle = false;
  uint64_t seed = 0;
  size_t capacity = 256;
  std::deque<std::pair<uint32_t, std::string>> buffer;  // (order-pos, rec)
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::vector<std::thread> workers;
  bool stop = false;

  void WorkerLoop() {
    FILE* fp = std::fopen(path.c_str(), "rb");
    if (!fp) return;
    while (true) {
      size_t my_pos;
      uint64_t my_epoch;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_full.wait(lk, [&] {
          return stop || (cursor < order.size() &&
                          buffer.size() < capacity);
        });
        if (stop) break;
        my_pos = cursor++;
        my_epoch = epoch;
      }
      std::string rec, err;
      int64_t off = offsets[order[my_pos]];
      bool ok = ReadRecordAt(fp, off, &rec, &err);
      {
        std::unique_lock<std::mutex> lk(mu);
        if (my_epoch == epoch) {  // drop stale reads from before a reset
          buffer.emplace_back(static_cast<uint32_t>(my_pos),
                              ok ? std::move(rec) : std::string());
          cv_empty.notify_all();
        }
      }
    }
    std::fclose(fp);
  }
};

void* mxtpu_prefetcher_create(const char* path, int n_threads, int shuffle,
                              uint64_t seed) {
  auto* p = new Prefetcher();
  p->path = path;
  p->shuffle = shuffle != 0;
  p->seed = seed;
  void* rh = mxtpu_recio_open_read(path);
  if (!rh) {
    delete p;
    return nullptr;
  }
  int64_t* offs = nullptr;
  int64_t n = mxtpu_recio_scan(rh, &offs);
  mxtpu_recio_close_read(rh);
  if (n < 0) {
    delete p;
    return nullptr;
  }
  p->offsets.assign(offs, offs + n);
  mxtpu_free_i64(offs);
  p->order.resize(n);
  for (int64_t i = 0; i < n; ++i) p->order[i] = static_cast<uint32_t>(i);
  if (p->shuffle) {
    std::mt19937_64 rng(seed);
    std::shuffle(p->order.begin(), p->order.end(), rng);
  }
  int nt = n_threads > 0 ? n_threads : 4;
  for (int i = 0; i < nt; ++i)
    p->workers.emplace_back(&Prefetcher::WorkerLoop, p);
  return p;
}

int64_t mxtpu_prefetcher_size(void* h) {
  return static_cast<Prefetcher*>(h)->offsets.size();
}

// Pop the next record (in epoch order); returns size, -1 at epoch end.
// Caller frees data with mxtpu_free.
int64_t mxtpu_prefetcher_next(void* h, char** data_out) {
  auto* p = static_cast<Prefetcher*>(h);
  std::unique_lock<std::mutex> lk(p->mu);
  if (p->delivered >= p->order.size()) return -1;
  uint32_t want = static_cast<uint32_t>(p->delivered);
  p->cv_empty.wait(lk, [&] {
    for (auto& kv : p->buffer)
      if (kv.first == want) return true;
    return false;
  });
  for (auto it = p->buffer.begin(); it != p->buffer.end(); ++it) {
    if (it->first == want) {
      int64_t size = static_cast<int64_t>(it->second.size());
      char* out = new char[size];
      std::memcpy(out, it->second.data(), size);
      *data_out = out;
      p->buffer.erase(it);
      p->delivered++;
      p->cv_full.notify_all();
      return size;
    }
  }
  return -1;  // unreachable
}

// Start a new epoch (reshuffles when shuffle is on).
void mxtpu_prefetcher_reset(void* h, uint64_t seed) {
  auto* p = static_cast<Prefetcher*>(h);
  std::unique_lock<std::mutex> lk(p->mu);
  p->buffer.clear();
  p->cursor = 0;
  p->delivered = 0;
  p->epoch++;
  if (p->shuffle) {
    std::mt19937_64 rng(seed);
    std::shuffle(p->order.begin(), p->order.end(), rng);
  }
  p->cv_full.notify_all();
}

void mxtpu_prefetcher_destroy(void* h) {
  auto* p = static_cast<Prefetcher*>(h);
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_full.notify_all();
  }
  for (auto& t : p->workers) t.join();
  delete p;
}

}  // extern "C"
