// Flat C API over the mxnet_tpu runtime (see mxtpu_c_api.h).
//
// Reference parity: src/c_api/c_api.cc + c_api_ndarray.cc.  The
// reference's C layer marshals into its C++ engine; this one marshals
// into the Python/JAX engine by embedding CPython.  All heavy lifting
// (dtype handling, op dispatch, autograd, kvstore) lives in
// mxnet_tpu/c_api_impl.py — this file is only the ABI boundary: GIL
// management, handle lifetimes (handles ARE PyObject*), and error
// capture into MXGetLastError().

#include "mxtpu_c_api.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;
PyObject *g_impl = nullptr;      // mxnet_tpu.c_api_impl module
PyThreadState *g_main_tstate = nullptr;
bool g_we_initialized = false;
std::mutex g_init_mutex;

// Safe to call WITHOUT the GIL: entry points must check this before
// constructing Gil — PyGILState_Ensure on an uninitialized interpreter
// is a fatal abort, not an error return.
bool runtime_ready() { return Py_IsInitialized() && g_impl != nullptr; }

bool require_ready() {
  if (!runtime_ready()) {
    g_last_error = "MXTPUInit() not called (or failed)";
    return false;
  }
  return true;
}

void capture_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *msg = PyUnicode_AsUTF8(s);
      g_last_error = msg ? msg : "<unprintable python error>";
      Py_DECREF(s);
    }
  } else {
    g_last_error = "unknown python error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// RAII GIL hold for every API entry point.
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// Call impl.<method>(args...); returns new reference or nullptr (error
// captured).  Caller must hold the GIL.
PyObject *call_impl(const char *method, PyObject *args) {
  if (g_impl == nullptr) {
    g_last_error = "MXTPUInit() not called";
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *fn = PyObject_GetAttrString(g_impl, method);
  if (fn == nullptr) {
    capture_py_error();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *res = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (res == nullptr) capture_py_error();
  return res;
}

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return g_last_error.c_str(); }

int MXTPUInit(void) {
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (runtime_ready()) return 0;  // idempotent (incl. re-init after
                                  // MXTPUShutdown released the module)
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    g_impl = PyImport_ImportModule("mxnet_tpu.c_api_impl");
    if (g_impl == nullptr) capture_py_error();
    // release the GIL so other threads (and Gil) can take it
    g_main_tstate = PyEval_SaveThread();
  } else {
    // attached mode: a Python process loaded us (e.g. via ctypes);
    // the interpreter is initialized so taking the GIL is safe even
    // though g_impl is not imported yet
    Gil gil;
    g_impl = PyImport_ImportModule("mxnet_tpu.c_api_impl");
    if (g_impl == nullptr) capture_py_error();
  }
  return g_impl != nullptr ? 0 : -1;
}

int MXTPUShutdown(void) {
  // Releases the framework module; the embedded interpreter stays alive.
  // CPython extension modules (numpy, jax's C deps) do not survive
  // Py_Finalize + re-init, so finalizing would make the documented
  // shutdown->init sequence crash; keeping the interpreter makes
  // MXTPUInit() after shutdown well-defined.
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (g_impl != nullptr && Py_IsInitialized()) {
    Gil gil;
    Py_DECREF(g_impl);
    g_impl = nullptr;
  }
  return 0;
}

int MXNDArrayCreate(const void *data, size_t nbytes, const int64_t *shape,
                    int ndim, const char *dtype, NDArrayHandle *out) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *buf = PyBytes_FromStringAndSize(
      static_cast<const char *>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject *shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject *res = call_impl(
      "create", Py_BuildValue("(NNs)", buf, shp, dtype));
  if (res == nullptr) return -1;
  *out = res;  // ownership moves to the handle
  return 0;
}

int MXNDArrayFree(NDArrayHandle h) {
  if (h == nullptr) return 0;
  if (!require_ready()) return -1;
  Gil gil;
  Py_DECREF(static_cast<PyObject *>(h));
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle h, int *ndim, int64_t shape[8]) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "shape_of", Py_BuildValue("(O)", static_cast<PyObject *>(h)));
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > 8) {
    g_last_error = "ndim > 8 unsupported by the C shape call";
    Py_DECREF(res);
    return -1;
  }
  *ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(res, i));
  Py_DECREF(res);
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle h, char dtype[16]) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "dtype_of", Py_BuildValue("(O)", static_cast<PyObject *>(h)));
  if (res == nullptr) return -1;
  const char *s = PyUnicode_AsUTF8(res);
  std::strncpy(dtype, s ? s : "", 15);
  dtype[15] = '\0';
  Py_DECREF(res);
  return 0;
}

int MXNDArraySize(NDArrayHandle h, size_t *nbytes) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "size_bytes", Py_BuildValue("(O)", static_cast<PyObject *>(h)));
  if (res == nullptr) return -1;
  *nbytes = static_cast<size_t>(PyLong_AsSize_t(res));
  Py_DECREF(res);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *out, size_t nbytes) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "to_bytes", Py_BuildValue("(O)", static_cast<PyObject *>(h)));
  if (res == nullptr) return -1;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    capture_py_error();
    Py_DECREF(res);
    return -1;
  }
  if (static_cast<size_t>(len) != nbytes) {
    g_last_error = "MXNDArraySyncCopyToCPU: size mismatch (" +
                   std::to_string(len) + " vs " + std::to_string(nbytes) +
                   " bytes)";
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(out, buf, nbytes);
  Py_DECREF(res);
  return 0;
}

int MXImperativeInvoke(const char *op_name, NDArrayHandle *inputs,
                       int n_inputs, const char **param_keys,
                       const char **param_vals, int n_params,
                       NDArrayHandle *outputs, int *n_out) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *ins = PyList_New(n_inputs);
  for (int i = 0; i < n_inputs; ++i) {
    PyObject *o = static_cast<PyObject *>(inputs[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(ins, i, o);
  }
  PyObject *keys = PyList_New(n_params);
  PyObject *vals = PyList_New(n_params);
  for (int i = 0; i < n_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject *res = call_impl(
      "invoke", Py_BuildValue("(sNNN)", op_name, ins, keys, vals));
  if (res == nullptr) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (n > *n_out) {
    g_last_error = "MXImperativeInvoke: output capacity " +
                   std::to_string(*n_out) + " < " + std::to_string(n);
    Py_DECREF(res);
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  *n_out = static_cast<int>(n);
  Py_DECREF(res);
  return 0;
}

int MXListAllOpNames(int *count, const char ***names) {
  if (!require_ready()) return -1;
  Gil gil;
  // leak-once static storage, same convention as the reference's
  // MXListAllOpNames (the strings live for the process lifetime)
  static std::vector<std::string> storage;
  static std::vector<const char *> ptrs;
  if (storage.empty()) {
    PyObject *res = call_impl("list_op_names", PyTuple_New(0));
    if (res == nullptr) return -1;
    Py_ssize_t n = PyList_Size(res);
    storage.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char *s = PyUnicode_AsUTF8(PyList_GET_ITEM(res, i));
      storage.emplace_back(s ? s : "");
    }
    Py_DECREF(res);
    ptrs.reserve(storage.size());
    for (const auto &s : storage) ptrs.push_back(s.c_str());
  }
  *count = static_cast<int>(ptrs.size());
  *names = ptrs.data();
  return 0;
}

// -- autograd -----------------------------------------------------------

static int simple_call(const char *method, NDArrayHandle h) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      method, Py_BuildValue("(O)", static_cast<PyObject *>(h)));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXAutogradAttachGrad(NDArrayHandle h) {
  return simple_call("attach_grad", h);
}

int MXAutogradRecordStart(void) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl("record_start", PyTuple_New(0));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXAutogradRecordStop(void) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl("record_stop", PyTuple_New(0));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXAutogradBackward(NDArrayHandle loss) {
  return simple_call("backward", loss);
}

int MXNDArrayGetGrad(NDArrayHandle h, NDArrayHandle *out) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "grad_of", Py_BuildValue("(O)", static_cast<PyObject *>(h)));
  if (res == nullptr) return -1;
  *out = res;
  return 0;
}

// -- predictor (reference: c_predict_api.cc) ----------------------------
// Predictor handles are PyLong ids into c_api_impl._PREDICTORS, boxed
// as PyObject* so PredictorHandle stays an opaque pointer.

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 size_t param_size, int /*dev_type*/, int /*dev_id*/,
                 uint32_t num_input_nodes, const char **input_keys,
                 PredictorHandle *out) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *names = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyObject *s = PyUnicode_FromString(input_keys[i]);
    if (s == nullptr) {  // e.g. invalid UTF-8 key: error, never crash
      capture_py_error();
      Py_DECREF(names);
      return -1;
    }
    PyList_SET_ITEM(names, i, s);
  }
  PyObject *res = call_impl(
      "pred_create",
      Py_BuildValue("(sy#N)", symbol_json_str,
                    static_cast<const char *>(param_bytes),
                    static_cast<Py_ssize_t>(param_size), names));
  if (res == nullptr) return -1;
  *out = res;  // PyLong id, owned by the handle
  return 0;
}

int MXPredSetInput(PredictorHandle h, const char *key, const float *data,
                   const int64_t *shape, int ndim) {
  if (!require_ready()) return -1;
  Gil gil;
  size_t n = 1;
  PyObject *shp = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    n *= static_cast<size_t>(shape[i]);
    PyList_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject *res = call_impl(
      "pred_set_input",
      Py_BuildValue("(Osy#N)", static_cast<PyObject *>(h), key,
                    reinterpret_cast<const char *>(data),
                    static_cast<Py_ssize_t>(n * sizeof(float)), shp));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXPredForward(PredictorHandle h) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "pred_forward", Py_BuildValue("(O)", static_cast<PyObject *>(h)));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle h, uint32_t index, int *ndim,
                         int64_t shape[8]) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "pred_output_shape",
      Py_BuildValue("(OI)", static_cast<PyObject *>(h), index));
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > 8) {
    g_last_error = "MXPredGetOutputShape: ndim > 8";
    Py_DECREF(res);
    return -1;
  }
  *ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(res, i));
  }
  Py_DECREF(res);
  return 0;
}

int MXPredGetOutput(PredictorHandle h, uint32_t index, float *data,
                    size_t n_floats) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      "pred_get_output",
      Py_BuildValue("(OI)", static_cast<PyObject *>(h), index));
  if (res == nullptr) return -1;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    capture_py_error();
    Py_DECREF(res);
    return -1;
  }
  if (static_cast<size_t>(len) != n_floats * sizeof(float)) {
    g_last_error = "MXPredGetOutput: buffer size mismatch (want " +
                   std::to_string(len) + " bytes)";
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(res);
  return 0;
}

int MXPredFree(PredictorHandle h) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *id = static_cast<PyObject *>(h);
  PyObject *res = call_impl("pred_free", Py_BuildValue("(O)", id));
  Py_DECREF(id);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

// -- kvstore ------------------------------------------------------------

int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl("kv_create", Py_BuildValue("(s)", type));
  if (res == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

static int kv_call(const char *method, KVStoreHandle kv, int key,
                   NDArrayHandle v) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl(
      method, Py_BuildValue("(iiO)", kv, key, static_cast<PyObject *>(v)));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXKVStoreInit(KVStoreHandle kv, int key, NDArrayHandle v) {
  return kv_call("kv_init", kv, key, v);
}

int MXKVStorePush(KVStoreHandle kv, int key, NDArrayHandle v) {
  return kv_call("kv_push", kv, key, v);
}

int MXKVStorePull(KVStoreHandle kv, int key, NDArrayHandle *out) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl("kv_pull", Py_BuildValue("(ii)", kv, key));
  if (res == nullptr) return -1;
  *out = res;
  return 0;
}

int MXKVStoreFree(KVStoreHandle kv) {
  if (!require_ready()) return -1;
  Gil gil;
  PyObject *res = call_impl("kv_free", Py_BuildValue("(i)", kv));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

}  // extern "C"
