#!/usr/bin/env python
"""All-reduce bandwidth measurement.

Reference parity: tools/bandwidth/measure.py (the 'KVStore all-reduce BW'
BASELINE metric) — measures achieved all-reduce GB/s over the device mesh
(ICI on real TPU; the virtual CPU mesh for dry runs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size-mb", type=float, default=64.0,
                        help="tensor size per all-reduce")
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--devices", type=int, default=0,
                        help="0 = all visible devices")
    args = parser.parse_args()

    import jax

    from mxnet_tpu import parallel

    n = args.devices or len(jax.devices())
    mesh = parallel.make_mesh(dp=n)
    bw = parallel.collectives.measure_allreduce_bandwidth(
        mesh, size_mb=args.size_mb, dtype=args.dtype, iters=args.iters)
    print(json.dumps({
        "metric": "allreduce_bandwidth",
        "value": round(bw, 3),
        "unit": "GB/s",
        "devices": n,
        "size_mb": args.size_mb,
    }))


if __name__ == "__main__":
    main()
