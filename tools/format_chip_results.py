#!/usr/bin/env python
"""Format chip_results.jsonl (tools/chip_session.sh output) into the
BASELINE.md measurement table."""

import json
import sys


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "chip_results.jsonl"
    rows = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                rows.append(json.loads(ln))
    if not rows:
        sys.exit("no results")
    print("| step | rc | secs | metric | value | mfu | detail |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        res = r.get("result") or {}
        detail = ", ".join(
            f"{k}={res[k]}" for k in ("batch", "seq", "image", "layout",
                                      "attn", "calib_tflops",
                                      "device_kind")
            if k in res and res[k] is not None)
        print(f"| {r['step']} | {r['rc']} | {r['secs']} "
              f"| {res.get('metric', '—')} | {res.get('value', '—')} "
              f"| {res.get('mfu', '—')} | {detail} |")


if __name__ == "__main__":
    main()
