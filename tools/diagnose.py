#!/usr/bin/env python
"""Diagnose the runtime environment (reference: tools/diagnose.py —
the script users attach to bug reports: platform, versions, hardware,
feature flags, and a tiny timed op)."""

import os
import platform
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    print("----------Python Info----------")
    print(f"Version      : {platform.python_version()}")
    print(f"Compiler     : {platform.python_compiler()}")
    print(f"Platform     : {platform.platform()}")

    print("----------System Info----------")
    print(f"machine      : {platform.machine()}")
    print(f"processor    : {platform.processor() or 'n/a'}")
    try:
        print(f"cpu count    : {os.cpu_count()}")
    except Exception:
        pass

    print("----------MXNet-TPU Info----------")
    t0 = time.time()
    import mxnet_tpu as mx
    print(f"Version      : {mx.__version__}")
    print(f"Import time  : {time.time() - t0:.2f}s")
    import jax
    print(f"jax          : {jax.__version__}")
    try:
        devs = jax.devices()
        print(f"Devices      : {[str(d) for d in devs]}")
        print(f"Backend      : {devs[0].platform}")
    except Exception as e:
        print(f"Devices      : unavailable ({type(e).__name__}: {e})")
    print(f"num_tpus     : {mx.num_tpus()}")

    print("----------Features----------")
    for feat in mx.runtime.Features().values():
        print(f"  {feat!r}")

    print("----------Timed op----------")
    a = mx.nd.ones((256, 256))
    t0 = time.time()
    b = (a @ a).sum()
    val = float(b.asnumpy())
    print(f"(256,256) matmul+sum: {time.time() - t0 :.3f}s "
          f"(= {val:.0f})")

    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXNET_", "JAX_", "XLA_")):
            print(f"{k}={v}")


if __name__ == "__main__":
    main()
