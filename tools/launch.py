#!/usr/bin/env python
"""Distributed job launcher.

Reference parity: tools/launch.py + dmlc-core tracker — spawns the
scheduler/server/worker processes for dist kvstore.

TPU-native redesign (SURVEY.md §2.6): there is no parameter server; a
"distributed job" is N identical processes joining one
``jax.distributed.initialize`` rendezvous (coordinator address replaces the
dmlc tracker).  Supported launchers: ``local`` (N processes on this host —
the analog of the reference's fake-multi-node nightly tests) and ``ssh``.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def _spawn_gang(cmd, num_workers, port):
    """Spawn one full gang of workers sharing a rendezvous on ``port``."""
    procs = []
    coord = f"127.0.0.1:{port}"
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update({
            "MXTPU_COORDINATOR": coord,
            "MXTPU_NUM_WORKERS": str(num_workers),
            "MXTPU_WORKER_RANK": str(rank),
        })
        procs.append(subprocess.Popen(cmd, env=env))
    return procs


def _terminate_gang(procs, grace=10.0):
    """SIGTERM every live worker, then SIGKILL stragglers after grace."""
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace
    for p in procs:
        if p.poll() is None:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def launch_local(args, cmd):
    """Spawn n worker processes on localhost, each with the env
    jax.distributed expects (reference: dmlc tracker 'local' mode env
    DMLC_ROLE/DMLC_PS_ROOT_URI → MXTPU_COORDINATOR/RANK/WORLD).

    Gang supervision: a distributed job is all-or-nothing — one dead
    worker wedges every surviving collective.  When any worker exits
    nonzero the whole gang is torn down, and with ``--max-restarts N``
    the full gang is relaunched (workers are expected to resume from
    their latest checkpoint; see mxnet_tpu/resilience.py).  Each attempt
    uses ``port + attempt`` so a lingering coordinator socket from the
    dead gang can't poison the new rendezvous.
    """
    for attempt in range(args.max_restarts + 1):
        procs = _spawn_gang(cmd, args.num_workers, args.port + attempt)
        live = {p.pid: p for p in procs}
        failed = 0
        while live:
            time.sleep(0.2)
            for pid, p in list(live.items()):
                code = p.poll()
                if code is None:
                    continue
                del live[pid]
                if code != 0:
                    failed = code
            if failed:
                # gang fate-sharing: survivors are wedged in collectives
                # waiting on the dead rank — tear them down now
                _terminate_gang(list(live.values()))
                live.clear()
        if not failed:
            return 0
        if attempt < args.max_restarts:
            sys.stderr.write(
                f"[launch] worker exited rc={failed}; restarting gang "
                f"(attempt {attempt + 2}/{args.max_restarts + 1}, "
                f"port {args.port + attempt + 1})\n")
    return failed


def launch_ssh(args, cmd):
    assert args.hostfile, "--hostfile required for ssh launcher"
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    assert len(hosts) >= args.num_workers
    coord = f"{hosts[0]}:{args.port}"
    procs = []
    for rank in range(args.num_workers):
        envs = (f"MXTPU_COORDINATOR={coord} "
                f"MXTPU_NUM_WORKERS={args.num_workers} "
                f"MXTPU_WORKER_RANK={rank}")
        remote = f"cd {os.getcwd()} && {envs} {' '.join(cmd)}"
        procs.append(subprocess.Popen(["ssh", hosts[rank], remote]))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("--hostfile", default=None)
    parser.add_argument("--port", type=int, default=9927)
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="relaunch the full gang up to N times after "
                             "a nonzero worker exit (local launcher); "
                             "workers resume from their checkpoints")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args, cmd))
    sys.exit(launch_ssh(args, cmd))


if __name__ == "__main__":
    main()
