#!/usr/bin/env python
"""Distributed job launcher.

Reference parity: tools/launch.py + dmlc-core tracker — spawns the
scheduler/server/worker processes for dist kvstore.

TPU-native redesign (SURVEY.md §2.6): there is no parameter server; a
"distributed job" is N identical processes joining one
``jax.distributed.initialize`` rendezvous (coordinator address replaces the
dmlc tracker).  Supported launchers: ``local`` (N processes on this host —
the analog of the reference's fake-multi-node nightly tests) and ``ssh``.

Two supervision modes for ``local``:

- default (gang fate-sharing): one nonzero worker exit tears down the
  whole gang; ``--max-restarts`` relaunches the FULL gang on a fresh
  port and workers resume from their checkpoints.
- ``--elastic``: workers share a gang control-plane directory
  (``MXTPU_GANG_DIR``) and survive peer death in-job
  (mxnet_tpu/resilience.ElasticGang).  A dead rank does NOT take the
  gang down; the launcher respawns ONLY that rank (after a delay that
  lets the survivors agree the shrink epoch first), and the respawn
  rejoins through the gang's join protocol.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import time


def _spawn_worker(cmd, rank, num_workers, port, extra_env=None):
    """Spawn ONE worker with the gang env contract."""
    env = dict(os.environ)
    env.update({
        "MXTPU_COORDINATOR": f"127.0.0.1:{port}",
        "MXTPU_NUM_WORKERS": str(num_workers),
        "MXTPU_WORKER_RANK": str(rank),
    })
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(cmd, env=env)


def _spawn_gang(cmd, num_workers, port, extra_env=None):
    """Spawn one full gang of workers sharing a rendezvous on ``port``."""
    return [_spawn_worker(cmd, rank, num_workers, port, extra_env)
            for rank in range(num_workers)]


def _terminate_gang(procs, grace=10.0):
    """SIGTERM every live worker, then SIGKILL stragglers after grace."""
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace
    for p in procs:
        if p.poll() is None:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def _supervise_gang(procs, grace=10.0, poll_interval=0.2):
    """Wait out one gang attempt; returns the attempt's failure code.

    Exit-code semantics, explicitly: ONLY a nonzero exit counts as a
    failure — a worker that finishes cleanly (exit 0) after its peers
    die is complete, not failed.  The first nonzero exit triggers gang
    fate-sharing teardown of the survivors; the codes those survivors
    then die with (-SIGTERM/-SIGKILL) are artifacts of OUR teardown and
    are never reported as the failure.  Returns 0 when every worker
    exited 0.
    """
    live = dict(enumerate(procs))      # rank -> proc
    failed = 0
    while live:
        time.sleep(poll_interval)
        for rank, p in list(live.items()):
            code = p.poll()
            if code is None:
                continue
            del live[rank]
            if code != 0 and not failed:
                failed = code
                sys.stderr.write(
                    f"[launch] rank {rank} exited rc={code}\n")
        if failed and live:
            # gang fate-sharing: survivors are wedged in collectives
            # waiting on the dead rank — tear them down now (their
            # teardown exit codes are not failures, see above)
            _terminate_gang(list(live.values()), grace=grace)
            live.clear()
    return failed


def launch_local(args, cmd):
    """Spawn n worker processes on localhost, each with the env
    jax.distributed expects (reference: dmlc tracker 'local' mode env
    DMLC_ROLE/DMLC_PS_ROOT_URI → MXTPU_COORDINATOR/RANK/WORLD).

    Gang supervision: a distributed job is all-or-nothing — one dead
    worker wedges every surviving collective.  When any worker exits
    nonzero the whole gang is torn down, and with ``--max-restarts N``
    the full gang is relaunched (workers are expected to resume from
    their latest checkpoint; see mxnet_tpu/resilience.py).  Each attempt
    uses ``port + attempt`` so a lingering coordinator socket from the
    dead gang can't poison the new rendezvous.
    """
    for attempt in range(args.max_restarts + 1):
        procs = _spawn_gang(cmd, args.num_workers, args.port + attempt)
        failed = _supervise_gang(procs, grace=args.grace)
        if not failed:
            return 0
        if attempt < args.max_restarts:
            sys.stderr.write(
                f"[launch] worker exited rc={failed}; restarting gang "
                f"(attempt {attempt + 2}/{args.max_restarts + 1}, "
                f"port {args.port + attempt + 1})\n")
    return failed


def _import_distributed():
    """Load mxnet_tpu.distributed without the package __init__ (the
    launcher host needs no jax)."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "mxnet_tpu" not in sys.modules:
        pkg = types.ModuleType("mxnet_tpu")
        pkg.__path__ = [os.path.join(root, "mxnet_tpu")]
        sys.modules["mxnet_tpu"] = pkg
    return importlib.import_module("mxnet_tpu.distributed")


def _start_kv_daemon(addr):
    """Spawn the embedded gang-KV daemon (tools/gang_kv.py); returns
    (proc, bound_addr) once it prints its LISTEN line."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "gang_kv.py")
    proc = subprocess.Popen(
        [sys.executable, script, "--addr", addr or "127.0.0.1:0"],
        stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    if not line.startswith("LISTEN "):
        proc.kill()
        raise RuntimeError(f"gang KV daemon failed to start: {line!r}")
    return proc, line.split()[1]


def launch_elastic(args, cmd):
    """Elastic supervision: peer death shrinks the gang instead of
    killing it; the launcher's job is only to (a) provision the control
    plane, (b) respawn dead ranks so the gang can grow back, and (c)
    act on ScalePolicy grow requests.

    - Control plane: ``--kv file`` (default) exports ``MXTPU_GANG_DIR``
      (created if ``--gang-dir`` is not given); ``--kv tcp`` embeds the
      tools/gang_kv.py daemon and exports ``MXTPU_GANG_KV=tcp`` +
      ``MXTPU_GANG_ADDR`` — no shared filesystem.  The daemon is NOT
      restarted if it dies: the ranks' deterministic coordinator
      failover (distributed.TcpKV) is the recovery story.
    - ``MXTPU_ELASTIC=1`` is exported to every worker.
    - A rank that exits 0 is COMPLETE (including a rank the gang evicted
      — GangEvicted exits cleanly); it is never respawned.
    - A rank that dies (nonzero / signal) while peers are still running
      is absorbed by the gang; up to ``--max-restarts`` such ranks are
      respawned — same rank id, same port — after
      ``MXTPU_ELASTIC_RESPAWN_DELAY`` seconds (default 1.5x the
      heartbeat timeout) so the survivors commit the shrink epoch before
      the rejoin request lands.
    - A ``scale/req`` record in the KV (resilience.ScalePolicy) spawns a
      NEW rank id, which enters through the gang's join protocol.
    - The launcher fails (returns the exit code) only when a rank dies
      with NO surviving peers to absorb it, or a death exceeds the
      respawn budget and the remaining gang also fails.
    """
    kv_daemon = None
    gang_dir = None
    if args.kv == "tcp":
        kv_daemon, addr = _start_kv_daemon(args.gang_addr)
        extra = {"MXTPU_GANG_KV": "tcp", "MXTPU_GANG_ADDR": addr,
                 "MXTPU_ELASTIC": "1"}
        sys.stderr.write(f"[launch] elastic gang KV daemon at {addr} "
                         f"(pid {kv_daemon.pid})\n")
    else:
        gang_dir = args.gang_dir or tempfile.mkdtemp(prefix="mxtpu_gang_")
        extra = {"MXTPU_GANG_DIR": gang_dir, "MXTPU_ELASTIC": "1"}
        sys.stderr.write(f"[launch] elastic gang dir: {gang_dir}\n")
    hb_timeout = float(os.environ.get("MXTPU_HEARTBEAT_TIMEOUT", 5.0))
    delay = float(os.environ.get("MXTPU_ELASTIC_RESPAWN_DELAY",
                                 1.5 * hb_timeout))
    kv_client = None
    try:
        dist = _import_distributed()
        kv_client = (dist.FileKV(gang_dir) if gang_dir is not None
                     else dist.TcpKV(addr))
    except Exception as exc:            # noqa: BLE001 — scale polling
        sys.stderr.write(f"[launch] no scale polling ({exc})\n")
    procs = {rank: _spawn_worker(cmd, rank, args.num_workers, args.port,
                                 extra)
             for rank in range(args.num_workers)}
    next_rank = args.num_workers
    respawns = 0
    failed = 0
    last_scale_poll = 0.0
    try:
        while procs:
            time.sleep(0.2)
            for rank, p in list(procs.items()):
                code = p.poll()
                if code is None:
                    continue
                del procs[rank]
                if code == 0:
                    continue                  # complete, not failed
                if not procs:
                    # nobody left to absorb the death: a real failure
                    sys.stderr.write(f"[launch] rank {rank} exited "
                                     f"rc={code} with no survivors\n")
                    failed = failed or code
                    continue
                sys.stderr.write(f"[launch] rank {rank} died rc={code}; "
                                 f"gang absorbs it "
                                 f"({len(procs)} survivors)\n")
                if respawns < args.max_restarts:
                    respawns += 1
                    time.sleep(delay)         # let the shrink commit
                    sys.stderr.write(
                        f"[launch] respawning rank {rank} "
                        f"(respawn {respawns}/{args.max_restarts})\n")
                    procs[rank] = _spawn_worker(
                        cmd, rank, args.num_workers, args.port, extra)
            now = time.monotonic()
            if kv_client is not None and procs \
                    and now - last_scale_poll >= 1.0:
                last_scale_poll = now
                try:
                    req = kv_client.get_json("scale/req")
                    if isinstance(req, dict) and \
                            int(req.get("want_world", 0)) > len(procs):
                        kv_client.delete("scale/req")
                        r = next_rank
                        next_rank += 1
                        sys.stderr.write(
                            f"[launch] scale/req want_world="
                            f"{req['want_world']}: spawning rank {r}\n")
                        procs[r] = _spawn_worker(
                            cmd, r, args.num_workers, args.port, extra)
                except Exception:   # noqa: BLE001 — KV may be failing over
                    pass
    finally:
        if kv_client is not None:
            try:
                close = getattr(kv_client, "close", None)
                if close is not None:
                    close()
            except Exception:       # noqa: BLE001
                pass
        if kv_daemon is not None and kv_daemon.poll() is None:
            kv_daemon.terminate()
            try:
                kv_daemon.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                kv_daemon.kill()
    return failed


def launch_ssh(args, cmd):
    assert args.hostfile, "--hostfile required for ssh launcher"
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    assert len(hosts) >= args.num_workers
    coord = f"{hosts[0]}:{args.port}"
    procs = []
    for rank in range(args.num_workers):
        envs = (f"MXTPU_COORDINATOR={coord} "
                f"MXTPU_NUM_WORKERS={args.num_workers} "
                f"MXTPU_WORKER_RANK={rank}")
        remote = f"cd {os.getcwd()} && {envs} {' '.join(cmd)}"
        procs.append(subprocess.Popen(["ssh", hosts[rank], remote]))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("--hostfile", default=None)
    parser.add_argument("--port", type=int, default=9927)
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="default mode: relaunch the full gang up to "
                             "N times after a nonzero worker exit; "
                             "--elastic: respawn up to N dead ranks")
    parser.add_argument("--grace", type=float, default=10.0,
                        help="seconds between SIGTERM and SIGKILL when "
                             "tearing down a failed gang")
    parser.add_argument("--elastic", action="store_true",
                        help="elastic supervision (local launcher): a "
                             "dead rank is absorbed by the surviving "
                             "gang and respawned individually instead "
                             "of restarting everyone")
    parser.add_argument("--gang-dir", default=None,
                        help="shared control-plane dir for --elastic "
                             "(default: a fresh temp dir)")
    parser.add_argument("--kv", choices=["file", "tcp"], default="file",
                        help="--elastic control plane: 'file' shares "
                             "--gang-dir; 'tcp' embeds the gang_kv.py "
                             "daemon (no shared filesystem)")
    parser.add_argument("--gang-addr", default=None,
                        help="HOST:PORT for --kv tcp (default "
                             "127.0.0.1:0 — a free port)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given")
    if args.launcher != "local":
        if args.elastic:
            parser.error("--elastic requires the local launcher")
        return launch_ssh(args, cmd)
    if args.elastic:
        return launch_elastic(args, cmd)
    return launch_local(args, cmd)


if __name__ == "__main__":
    sys.exit(main())
