#!/usr/bin/env python
"""Distributed job launcher.

Reference parity: tools/launch.py + dmlc-core tracker — spawns the
scheduler/server/worker processes for dist kvstore.

TPU-native redesign (SURVEY.md §2.6): there is no parameter server; a
"distributed job" is N identical processes joining one
``jax.distributed.initialize`` rendezvous (coordinator address replaces the
dmlc tracker).  Supported launchers: ``local`` (N processes on this host —
the analog of the reference's fake-multi-node nightly tests) and ``ssh``.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def launch_local(args, cmd):
    """Spawn n worker processes on localhost, each with the env
    jax.distributed expects (reference: dmlc tracker 'local' mode env
    DMLC_ROLE/DMLC_PS_ROOT_URI → MXTPU_COORDINATOR/RANK/WORLD)."""
    procs = []
    coord = f"127.0.0.1:{args.port}"
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "MXTPU_COORDINATOR": coord,
            "MXTPU_NUM_WORKERS": str(args.num_workers),
            "MXTPU_WORKER_RANK": str(rank),
        })
        procs.append(subprocess.Popen(cmd, env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def launch_ssh(args, cmd):
    assert args.hostfile, "--hostfile required for ssh launcher"
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    assert len(hosts) >= args.num_workers
    coord = f"{hosts[0]}:{args.port}"
    procs = []
    for rank in range(args.num_workers):
        envs = (f"MXTPU_COORDINATOR={coord} "
                f"MXTPU_NUM_WORKERS={args.num_workers} "
                f"MXTPU_WORKER_RANK={rank}")
        remote = f"cd {os.getcwd()} && {envs} {' '.join(cmd)}"
        procs.append(subprocess.Popen(["ssh", hosts[rank], remote]))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("--hostfile", default=None)
    parser.add_argument("--port", type=int, default=9927)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args, cmd))
    sys.exit(launch_ssh(args, cmd))


if __name__ == "__main__":
    main()
