"""Autoregressive decode throughput: KV-cache step vs full recompute.

Prints one JSON line per config; run on TPU when the tunnel permits
(numbers land in BASELINE.md), any backend otherwise.  The cached path
is the inference story for the GPT family: O(W) per token at one
compiled shape vs the recompute path's O(W²) trunk per token.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main(batch=8, seed_len=16, new_tokens=48, units=256, layers=4,
         heads=8, window=256, vocab=32000):
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.GPTModel(vocab_size=vocab, units=units,
                       num_layers=layers, num_heads=heads,
                       max_length=window, dropout=0.0)
    net.initialize(init=mx.init.Xavier())
    ids = nd.array(np.random.RandomState(0)
                   .randint(0, vocab, (batch, seed_len))
                   .astype(np.float32))
    net(ids)

    dec = gpt.CachedDecoder(net)
    dec_bf16 = gpt.CachedDecoder(net, dtype="bfloat16")
    # warm all paths (compiles)
    dec.decode(ids, max_new_tokens=2)
    dec_bf16.decode(ids, max_new_tokens=2)
    gpt.generate(net, ids, max_new_tokens=2)

    t0 = time.perf_counter()
    out = dec.decode(ids, max_new_tokens=new_tokens)
    np.asarray(out._data)
    dt_cache = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = dec_bf16.decode(ids, max_new_tokens=new_tokens)
    np.asarray(out._data)
    dt_bf16 = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = gpt.generate(net, ids, max_new_tokens=new_tokens)
    np.asarray(out._data)
    dt_full = time.perf_counter() - t0

    tps_cache = batch * new_tokens / dt_cache
    tps_bf16 = batch * new_tokens / dt_bf16
    tps_full = batch * new_tokens / dt_full
    print(json.dumps({
        "bench": "gpt_decode",
        "config": {"batch": batch, "units": units, "layers": layers,
                   "window": window, "vocab": vocab,
                   "new_tokens": new_tokens},
        "kv_cache_tokens_per_sec": round(tps_cache, 1),
        "kv_cache_bf16_tokens_per_sec": round(tps_bf16, 1),
        "recompute_tokens_per_sec": round(tps_full, 1),
        "speedup": round(tps_cache / tps_full, 2),
        "bf16_speedup_over_f32_cache": round(tps_bf16 / tps_cache, 2),
    }))


if __name__ == "__main__":
    main()
