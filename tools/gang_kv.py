#!/usr/bin/env python
"""Standalone gang coordination-service daemon (mxnet_tpu.distributed's
GangKVServer behind a CLI).

Runs the TCP control plane the elastic gang uses when there is no
shared filesystem (``MXTPU_GANG_KV=tcp`` / ``MXTPU_GANG_ADDR``): the
FileKV key namespace over length-prefixed CRC'd frames, plus leases and
prefix watches.  tools/launch.py embeds the same server; this entry
point is for running it on its own host (or under a supervisor).

Prints ``LISTEN <host>:<port>`` on stdout once bound — launchers that
asked for port 0 read the chosen port from there.

Usage:
    python tools/gang_kv.py [--addr HOST:PORT] [--lease-ttl SECONDS]
"""

import argparse
import os
import signal
import sys
import threading


def _import_distributed():
    """Load mxnet_tpu.distributed without executing the package
    __init__ (no jax on a coordinator host)."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "mxnet_tpu" not in sys.modules:
        pkg = types.ModuleType("mxnet_tpu")
        pkg.__path__ = [os.path.join(root, "mxnet_tpu")]
        sys.modules["mxnet_tpu"] = pkg
    return importlib.import_module("mxnet_tpu.distributed")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="mxnet_tpu elastic-gang TCP KV daemon")
    ap.add_argument("--addr", default=None,
                    help="HOST:PORT to bind (default "
                         "$MXTPU_GANG_ADDR or 127.0.0.1:0)")
    ap.add_argument("--lease-ttl", type=float, default=None,
                    help="lease TTL seconds (default $MXTPU_LEASE_TTL "
                         "or 10)")
    args = ap.parse_args(argv)

    dist = _import_distributed()
    addr = args.addr or os.environ.get("MXTPU_GANG_ADDR", "127.0.0.1:0")
    host, _, port = addr.rpartition(":")
    srv = dist.GangKVServer(host or "127.0.0.1", int(port),
                            lease_ttl=args.lease_ttl)
    srv.start()
    sys.stdout.write(f"LISTEN {srv.addr}\n")
    sys.stdout.flush()

    done = threading.Event()

    def _term(_sig, _frm):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not done.is_set() and not srv._stop.is_set():
        done.wait(0.5)
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
