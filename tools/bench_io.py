#!/usr/bin/env python
"""Input-pipeline throughput microbench.

Reference parity: the role of tools/bandwidth + the perf tables for
iter_image_recordio_2.cc — proves the decode+augment path can feed the
chip faster than the training step consumes (BASELINE: the honest
ResNet-50 samples/sec/chip number).

Synthesizes a .rec of ImageNet-sized JPEGs, then measures images/sec
through ImageRecordIter for the native libjpeg path and the PIL
fallback.  Prints one JSON line.
"""

import io
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def synth_rec(path, n=256, size=(360, 480)):
    from PIL import Image

    from mxnet_tpu import recordio

    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(path, "w")
    # smooth-ish synthetic images compress/decode like photos
    base = rng.randint(0, 255, (size[0] // 8, size[1] // 8, 3))
    img = np.kron(base, np.ones((8, 8, 1))).astype(np.uint8)
    for i in range(n):
        buf = io.BytesIO()
        Image.fromarray(np.roll(img, i, axis=1)).save(
            buf, format="jpeg", quality=90)
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              buf.getvalue()))
    w.close()


def run(path, n, batch_size, force_python=False):
    from mxnet_tpu import _native
    from mxnet_tpu.io import ImageRecordIter

    it = ImageRecordIter(
        path_imgrec=path, data_shape=(3, 224, 224), batch_size=batch_size,
        resize=256, rand_crop=True, rand_mirror=True, scale=1 / 255.0,
        preprocess_threads=int(os.environ.get("BENCH_IO_THREADS",
                                              os.cpu_count() or 4)))
    if force_python:
        has = _native.has_jpeg
        _native.has_jpeg = lambda: False
    try:
        it.next()  # warm
        it.reset()
        t0 = time.perf_counter()
        count = 0
        for _ in range(n // batch_size):
            try:
                b = it.next()
            except StopIteration:
                it.reset()
                b = it.next()
            count += b.data[0].shape[0]
        dt = time.perf_counter() - t0
    finally:
        if force_python:
            _native.has_jpeg = has
    return count / dt


def main():
    n = int(os.environ.get("BENCH_IO_N", 512))
    batch = int(os.environ.get("BENCH_IO_BATCH", 64))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bench.rec")
        synth_rec(path, n=min(n, 256))
        native = run(path, n=min(n, 256) * 2, batch_size=batch)
        python = run(path, n=min(n, 128), batch_size=batch,
                     force_python=True)
    from mxnet_tpu import _native

    print(json.dumps({
        "metric": "image_decode_augment_images_per_sec",
        "native_images_per_sec": round(native, 1),
        "python_images_per_sec": round(python, 1),
        "speedup": round(native / python, 2),
        "native_jpeg": _native.has_jpeg(),
        "threads": int(os.environ.get("BENCH_IO_THREADS",
                                      os.cpu_count() or 4)),
    }))


if __name__ == "__main__":
    main()
