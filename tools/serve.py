#!/usr/bin/env python
"""Serve N requests through the low-latency serving tier, from the CLI.

The smallest end-to-end exercise of mxnet_tpu/serving/: build a model
zoo decoder, AOT-warm the bucketed programs, optionally hot-load the
newest committed AsyncCheckpointer manifest, push N random requests
through the continuous batcher from C concurrent clients, and print a
latency summary (p50/p99 per stage, tokens/sec, bucket usage).

Stdlib argparse only — the jax-facing imports happen after parsing, so
``--help`` works anywhere.

Usage:
    python tools/serve.py [--ckpt DIR] [--requests 16] [--clients 4]
                          [--new-tokens 8] [--buckets 1,2,4]
                          [--max-delay-ms 2.0] [--seed 0]
"""

import argparse
import os
import sys
import threading
import time


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serve N requests through mxnet_tpu/serving/")
    ap.add_argument("--ckpt", default=None,
                    help="AsyncCheckpointer directory; the newest "
                         "committed manifest is hot-loaded before "
                         "serving (default: fresh random weights)")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--buckets", default="1,2,4",
                    help="comma-separated batch buckets")
    ap.add_argument("--max-delay-ms", type=float, default=2.0,
                    help="batcher coalescing deadline")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint, serving
    from mxnet_tpu.gluon.model_zoo import gpt

    np.random.seed(args.seed)
    mx.random.seed(args.seed)
    net = gpt.gpt_tiny(scan_layers=True)
    net.initialize(init=mx.init.Xavier())
    net(mx.nd.array(np.random.randint(0, 128, (1, 8))
                    .astype(np.float32)))

    buckets = tuple(sorted({int(b) for b in args.buckets.split(",")}))
    engine = serving.ServingEngine(net, batch_buckets=buckets)
    t0 = time.perf_counter()
    engine.warmup()
    warm_ms = (time.perf_counter() - t0) * 1e3
    print(f"warmup: {engine.program_count()} AOT programs in "
          f"{warm_ms:.0f} ms (buckets {buckets} x prefill "
          f"{engine.prefill_buckets} + decode)")

    if args.ckpt:
        step = checkpoint.latest_manifest_step(args.ckpt)
        if step is None:
            sys.stderr.write(
                f"error: no committed manifest under {args.ckpt}\n")
            return 2
        ck = checkpoint.AsyncCheckpointer(args.ckpt, rank=0,
                                          world_size=1)
        engine.reload_from_state(ck.restore(step=step), step=step)
        print(f"loaded checkpoint step {step} "
              f"(generation {engine.generation})")

    rng = np.random.RandomState(args.seed + 1)
    window = engine.prefill_buckets[-1]
    max_prompt = max(2, min(16, window - args.new_tokens))
    prompts = [rng.randint(0, 128, rng.randint(2, max_prompt + 1))
               .tolist() for _ in range(args.requests)]

    batcher = serving.ContinuousBatcher(
        engine, max_delay_ms=args.max_delay_ms, max_batch=buckets[-1])
    results = [None] * args.requests
    lock = threading.Lock()

    def client(idx):
        for j in range(idx, args.requests, args.clients):
            t1 = time.perf_counter()
            rec = batcher.submit(prompts[j], args.new_tokens).result(
                timeout=300)
            rec["total_us"] = (time.perf_counter() - t1) * 1e6
            with lock:
                results[j] = rec

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(max(1, args.clients))]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    batcher.close()

    done = [r for r in results if r is not None]
    if len(done) != args.requests:
        sys.stderr.write(f"error: {args.requests - len(done)} of "
                         f"{args.requests} requests never resolved\n")
        return 1

    def pctl(key, q):
        vals = sorted(r[key] for r in done)
        return vals[min(len(vals) - 1,
                        max(0, int(round(q / 100 * len(vals))) - 1))]

    print(f"served {len(done)} requests from {args.clients} clients "
          f"in {wall * 1e3:.0f} ms "
          f"({len(done) * args.new_tokens / wall:.0f} tokens/sec)")
    print(f"  {'stage':<16}{'p50 us':>12}{'p99 us':>12}")
    for key, label in (("queue_us", "queue"), ("prefill_us", "prefill"),
                       ("decode_us_per_token", "decode/token"),
                       ("total_us", "total")):
        print(f"  {label:<16}{pctl(key, 50):>12.1f}"
              f"{pctl(key, 99):>12.1f}")
    hist = {}
    for r in done:
        key = f"{r['bucket'][0]}x{r['bucket'][1]}"
        hist[key] = hist.get(key, 0) + 1
    print("  buckets (batch x seq): " +
          "  ".join(f"{k}:{hist[k]}" for k in sorted(hist)))
    print(f"  mean padded_fraction "
          f"{sum(r['padded_fraction'] for r in done) / len(done):.4f}"
          f"  retraces_after_warmup "
          f"{serving.trace_count() - engine.program_count()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
