"""Benchmark the compact sparse paths against their dense equivalents
(VERDICT r3 task #5's 'record the win or retire the claim').

1. Embedding gradient at big vocab: eager compact row-sparse cotangent
   (O(touched rows)) vs the dense scatter path (O(vocab)).
2. dot(csr, dense): compact gather/segment-sum vs densify-then-matmul.

Prints one JSON line per comparison; run on TPU when the tunnel is up
(numbers land in BASELINE.md), falls back to whatever backend jax has.
"""

import json
import time

import numpy as np


def _sync(x):
    return np.asarray(x)


def bench_embedding_grad(vocab=1_000_000, dim=64, batch=4096, iters=5):
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.ops.indexing import sparse_embedding

    rs = np.random.RandomState(0)
    ids = nd.array(rs.randint(0, vocab, (batch,)).astype("float32"))
    weight = nd.random_normal(shape=(vocab, dim))

    def run(sparse):
        weight.attach_grad(stype="row_sparse" if sparse else "default")
        # warmup
        with autograd.record():
            out = (sparse_embedding(ids, weight) if sparse
                   else nd.Embedding(ids, weight, input_dim=vocab,
                                     output_dim=dim))
            loss = (out * out).sum()
        loss.backward()
        g = weight.grad
        _sync(g._rs_values if sparse else g._data)
        t0 = time.perf_counter()
        for _ in range(iters):
            with autograd.record():
                out = (sparse_embedding(ids, weight) if sparse
                       else nd.Embedding(ids, weight, input_dim=vocab,
                                         output_dim=dim))
                loss = (out * out).sum()
            loss.backward()
            g = weight.grad
            _sync(g._rs_values if sparse else g._data)
        dt = (time.perf_counter() - t0) / iters
        rows = (int(g._rs_values.shape[0]) if sparse else vocab)
        return dt, rows

    dt_sparse, rows_sparse = run(True)
    dt_dense, rows_dense = run(False)
    print(json.dumps({
        "bench": "embedding_grad",
        "vocab": vocab, "dim": dim, "batch": batch,
        "sparse_ms": round(dt_sparse * 1e3, 2),
        "dense_ms": round(dt_dense * 1e3, 2),
        "speedup": round(dt_dense / dt_sparse, 2),
        "sparse_grad_rows": rows_sparse,
        "dense_grad_rows": rows_dense,
        "grad_mem_ratio": round(rows_dense / max(rows_sparse, 1), 1),
    }))


def bench_csr_dot(n_rows=4096, dim=100_000, nnz_per_row=32, out_dim=64,
                  iters=5):
    import jax.numpy as jnp

    from mxnet_tpu.ndarray.sparse import csr_dot_dense

    rs = np.random.RandomState(1)
    nnz = n_rows * nnz_per_row
    data = jnp.asarray(rs.standard_normal(nnz).astype(np.float32))
    indices = jnp.asarray(
        rs.randint(0, dim, nnz).astype(np.int32))
    indptr = jnp.asarray(
        np.arange(0, nnz + 1, nnz_per_row).astype(np.int32))
    rhs = jnp.asarray(
        rs.standard_normal((dim, out_dim)).astype(np.float32))

    import jax

    f_sparse = jax.jit(lambda d, i, p, r: csr_dot_dense(
        d, i, p, r, n_rows))

    def dense_form(d, i, p, r):
        rows = (jnp.searchsorted(p, jnp.arange(nnz), side="right") - 1)
        dense = jnp.zeros((n_rows, dim), d.dtype).at[rows, i].add(d)
        return dense @ r

    f_dense = jax.jit(dense_form)

    _sync(f_sparse(data, indices, indptr, rhs))
    _sync(f_dense(data, indices, indptr, rhs))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f_sparse(data, indices, indptr, rhs)
    _sync(out)
    dt_s = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f_dense(data, indices, indptr, rhs)
    _sync(out)
    dt_d = (time.perf_counter() - t0) / iters
    print(json.dumps({
        "bench": "csr_dot",
        "shape": [n_rows, dim], "nnz_per_row": nnz_per_row,
        "out_dim": out_dim,
        "sparse_ms": round(dt_s * 1e3, 2),
        "densify_matmul_ms": round(dt_d * 1e3, 2),
        "speedup": round(dt_d / dt_s, 2),
    }))


if __name__ == "__main__":
    bench_embedding_grad()
    bench_csr_dot()
