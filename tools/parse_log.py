#!/usr/bin/env python
"""Parse training logs into an epoch table (reference: tools/parse_log.py
— turns Module.fit / Speedometer logging into markdown/csv rows).

Input lines it understands (the formats this framework's fit loop and
Speedometer emit, same shapes as the reference):

    Epoch[3] Train-accuracy=0.912
    Epoch[3] Validation-accuracy=0.874
    Epoch[3] Time cost=123.456
    Epoch[3] Batch [40]  Speed: 1234.56 samples/sec  accuracy=0.91

Usage: python tools/parse_log.py train.log [--format markdown|csv]
"""

import argparse
import re
import sys
from collections import defaultdict

EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([0-9.eE+-]+)")
EPOCH_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([0-9.eE+-]+)")
SPEED = re.compile(
    r"Epoch\[(\d+)\].*Speed:\s*([0-9.eE+-]+)\s*samples/sec")


def parse(lines):
    rows = defaultdict(dict)
    speeds = defaultdict(list)
    for ln in lines:
        m = EPOCH_METRIC.search(ln)
        if m:
            ep, kind, name, val = m.groups()
            rows[int(ep)][f"{kind.lower()}-{name}"] = float(val)
            continue
        m = EPOCH_TIME.search(ln)
        if m:
            rows[int(m.group(1))]["time"] = float(m.group(2))
            continue
        m = SPEED.search(ln)
        if m:
            speeds[int(m.group(1))].append(float(m.group(2)))
    for ep, ss in speeds.items():
        rows[ep]["speed"] = sum(ss) / len(ss)
    return dict(rows)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logfile")
    ap.add_argument("--format", choices=("markdown", "csv"),
                    default="markdown")
    args = ap.parse_args()
    with open(args.logfile) as f:
        rows = parse(f)
    if not rows:
        sys.exit("no epoch lines recognized")
    cols = sorted({k for r in rows.values() for k in r})
    if args.format == "csv":
        print(",".join(["epoch"] + cols))
        for ep in sorted(rows):
            print(",".join([str(ep)] + [str(rows[ep].get(c, ""))
                                        for c in cols]))
    else:
        print("| epoch | " + " | ".join(cols) + " |")
        print("|" + "---|" * (len(cols) + 1))
        for ep in sorted(rows):
            print(f"| {ep} | " + " | ".join(
                f"{rows[ep][c]:.4g}" if c in rows[ep] else ""
                for c in cols) + " |")


if __name__ == "__main__":
    main()
