#!/usr/bin/env python
"""Fleet-wide view over per-rank telemetry logs (ISSUE 14).

Where tools/trace_report.py narrates ONE process's JSONL, this merges
the logs of a whole fleet — every training rank plus every serving
replica — into a single picture:

- fleet summary: ranks seen, total steps, step-weighted fleet MFU;
- per-rank timeline table: steps, mean interval, MFU, and the
  breakdown-share columns side by side, so a straggler's signature
  (everyone else's ``collective`` share up, the laggard's own time in
  ``other``/compute) is visible at a glance;
- skew + straggler attribution: `StragglerMonitor` suspicions
  correlated with the named rank's own breakdown and its slowdown
  against the fleet-median step interval;
- reshape/drain timeline: elastic events from all ranks merged in
  time order (epochs, deaths, drains, rejoins, scale decisions);
- request span trees: each served request's FrontDoor → batcher →
  prefill/decode waterfall rendered from the ``spans`` field the
  serving path embeds in request records (obs/spans.py).

Stdlib-only, like trace_report: pull the JSONLs off the pods, read
them anywhere.  Rotated predecessors (``<path>.1``) are read
automatically.  ``--validate`` loads mxnet_tpu/telemetry.py standalone
and checks every record against the schema PLUS span-tree completeness
(every request carrying a trace renders exactly one closed tree) —
exit 1 on any violation.

Usage:
    python tools/fleet_report.py rank0.jsonl rank1.jsonl ... [--validate]
    python tools/fleet_report.py logdir/            # all *.jsonl inside
"""

import argparse
import glob
import json
import os
import sys

BREAKDOWN_KEYS = ("data", "host_prep", "dispatch", "readback",
                  "collective", "other")

TIMELINE_KINDS = ("mesh_reshape", "rank_drained", "rank_dead",
                  "rank_rejoin", "elastic_recover", "scale_up",
                  "scale_down", "gang_drain_scheduled", "chips_freed",
                  "straggler_suspected", "resume", "restart",
                  "serving_reload", "serving_replica_failover",
                  "serving_replica_spawned", "profile_captured",
                  "sdc_detected", "integrity_mismatch",
                  "rank_quarantined", "replay_audit",
                  "serving_reload_rejected")


def expand_paths(args_paths):
    """Files as given; directories expand to their *.jsonl members
    (rotated ``.1`` files are folded into their live log, not listed)."""
    paths = []
    for p in args_paths:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        else:
            paths.append(p)
    return [p for p in paths if not p.endswith(".1")]


def read_records(path):
    """One log, rotation-aware: ``<path>.1`` first (if present), then
    the live file; torn lines are skipped, never fatal."""
    records, bad = [], 0
    for candidate in (path + ".1", path):
        if not os.path.exists(candidate):
            continue
        with open(candidate, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records, bad


def _mean(vals):
    vals = [v for v in vals if v is not None]
    return sum(vals) / len(vals) if vals else None


def _median(vals):
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    n = len(vals)
    return vals[n // 2] if n % 2 else \
        (vals[n // 2 - 1] + vals[n // 2]) / 2.0


def _fmt(v, nd=1):
    return "-" if v is None else f"{v:.{nd}f}"


def group_by_rank(records):
    """{rank: [records]} — records without a rank field (pre-v3 logs)
    land under None."""
    ranks = {}
    for rec in records:
        key = rec.get("rank")
        if key is None and rec.get("replica_id") is not None:
            key = f"replica{rec['replica_id']}"
        ranks.setdefault(key, []).append(rec)
    return ranks


def rank_stats(records):
    steps = [r for r in records if r.get("type") == "step"
             and not r.get("tuning_trial")]
    shares = {}
    for k in BREAKDOWN_KEYS:
        shares[k] = _mean([s.get("shares", {}).get(k) for s in steps])
    integ = [r for r in records if r.get("type") == "integrity"]
    return {
        "steps": len(steps),
        "interval_us": _mean([s.get("interval_us") for s in steps]),
        "mfu": _mean([s.get("mfu") for s in steps]),
        "shares": shares,
        "requests": sum(1 for r in records if r.get("type") == "request"),
        "attestations": len(integ),
        "integrity_mismatches": sum(1 for r in integ if not r.get("ok")),
    }


def report_fleet_summary(ranks, out):
    stats = {r: rank_stats(recs) for r, recs in ranks.items()}
    train = {r: s for r, s in stats.items() if s["steps"]}
    total_steps = sum(s["steps"] for s in stats.values())
    worlds = {rec.get("world") for recs in ranks.values()
              for rec in recs if rec.get("world") is not None}
    out.write(f"fleet: {len(ranks)} rank(s)"
              + (f", world {max(worlds)}" if worlds else "")
              + f", {total_steps} steps, "
              f"{sum(s['requests'] for s in stats.values())} "
              f"request(s)\n")
    num = den = 0.0
    for s in train.values():
        if s["mfu"] is not None:
            num += s["mfu"] * s["steps"]
            den += s["steps"]
    if den:
        out.write(f"fleet mfu (step-weighted): {num / den:.5f}\n")
    attest = sum(s["attestations"] for s in stats.values())
    if attest:
        mism = sum(s["integrity_mismatches"] for s in stats.values())
        out.write(f"integrity: {attest} attestation(s), "
                  f"{mism} mismatch(es)\n")
    if train:
        out.write("per-rank breakdown (mean share of step interval):\n")
        hdr = (f"  {'rank':>6}{'steps':>7}{'interval_us':>13}"
               f"{'mfu':>9}")
        for k in BREAKDOWN_KEYS:
            hdr += f"{k:>11}"
        out.write(hdr + "\n")
        for r in sorted(train, key=lambda x: (str(type(x)), str(x))):
            s = train[r]
            row = (f"  {str(r):>6}{s['steps']:>7}"
                   f"{_fmt(s['interval_us']):>13}"
                   f"{_fmt(s['mfu'], 5) if s['mfu'] is not None else '-':>9}")
            for k in BREAKDOWN_KEYS:
                row += f"{_fmt(s['shares'][k], 3):>11}"
            out.write(row + "\n")
    return stats


def report_skew_and_stragglers(ranks, stats, out):
    train = {r: s for r, s in stats.items()
             if s["steps"] and s["interval_us"]}
    if len(train) > 1:
        slow = max(train, key=lambda r: train[r]["interval_us"])
        lo = min(s["interval_us"] for s in train.values())
        hi = train[slow]["interval_us"]
        if lo > 0:
            out.write(f"step-time skew: {hi / lo:.2f}x "
                      f"(slowest rank {slow} at {_fmt(hi)} us)\n")
    med = _median([s["interval_us"] for s in train.values()])
    seen = set()
    for r, recs in sorted(ranks.items(), key=lambda kv: str(kv[0])):
        for e in recs:
            if e.get("type") != "event" \
                    or e.get("event") != "straggler_suspected":
                continue
            named = e.get("rank")
            if named in seen:
                continue
            seen.add(named)
            line = (f"straggler: rank {named} suspected "
                    f"(mean collective share "
                    f"{_fmt(e.get('mean_collective_share'), 3)} "
                    f"across peers)")
            target = stats.get(named)
            if target and target["steps"]:
                shares = {k: v for k, v in target["shares"].items()
                          if v is not None}
                if shares:
                    bucket = max(shares, key=shares.get)
                    line += (f"; its own time: {bucket} "
                             f"{shares[bucket]:.3f}")
                if target["interval_us"] and med:
                    line += (f"; {target['interval_us'] / med:.2f}x "
                             f"the fleet-median step interval")
            out.write(line + "\n")


def report_timeline(records, out):
    events = [r for r in records if r.get("type") == "event"
              and r.get("event") in TIMELINE_KINDS
              and r.get("t") is not None]
    if not events:
        return
    events.sort(key=lambda e: e["t"])
    t0 = events[0]["t"]
    out.write("timeline:\n")
    for e in events:
        who = f" [rank {e['rank']}]" if e.get("rank") is not None else ""
        detail = []
        for k in ("epoch", "world", "members", "step", "planned",
                  "generation", "path", "steps", "kind", "corrupt"):
            if e.get(k) is not None:
                detail.append(f"{k}={e[k]}")
        out.write(f"  +{e['t'] - t0:8.2f}s  {e['event']}{who}"
                  f"{('  ' + ' '.join(detail)) if detail else ''}\n")


def render_span_tree(spans):
    """ASCII waterfall of one request's span list (same shape as
    obs/spans.render_tree, duplicated here so this tool stays
    standalone-importable without the package)."""
    by_parent = {}
    for sp in spans:
        by_parent.setdefault(sp.get("parent"), []).append(sp)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s.get("t0", 0.0))
    lines = []

    def walk(sp, depth):
        attrs = sp.get("attrs") or {}
        extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        dur = sp.get("dur_us")
        dur_txt = f"{dur / 1000.0:8.2f} ms" if dur is not None \
            else "    open  "
        lines.append(f"  {'  ' * depth}{sp['name']:<12} {dur_txt}"
                     f"{('  ' + extra) if extra else ''}")
        for kid in by_parent.get(sp.get("span_id"), []):
            walk(kid, depth + 1)

    for root in by_parent.get(None, []):
        walk(root, 0)
    return lines


def report_spans(records, out, limit=8):
    traced = [r for r in records if r.get("type") == "request"
              and r.get("spans")]
    if not traced:
        return
    out.write(f"request span trees ({len(traced)} traced request(s), "
              f"showing up to {limit}):\n")
    for r in traced[:limit]:
        who = f" replica {r['replica_id']}" \
            if r.get("replica_id") is not None else ""
        out.write(f"trace {r.get('trace_id', '?')}{who}:\n")
        for line in render_span_tree(r["spans"]):
            out.write(line + "\n")


def check_spans(records):
    """Span-completeness check: every request carrying a trace renders
    exactly ONE closed tree (one root, every parent resolvable, every
    span closed).  Returns a list of violation strings."""
    errors = []
    for i, r in enumerate(records):
        if r.get("type") != "request" or "trace_id" not in r:
            continue
        spans = r.get("spans")
        if not spans:
            errors.append(f"record {i}: trace_id without spans")
            continue
        ids = {sp.get("span_id") for sp in spans}
        roots = [sp for sp in spans if sp.get("parent") is None]
        if len(roots) != 1:
            errors.append(f"record {i}: {len(roots)} roots "
                          f"(want exactly 1)")
        for sp in spans:
            if sp.get("dur_us") is None:
                errors.append(f"record {i}: open span "
                              f"{sp.get('name')!r}")
            p = sp.get("parent")
            if p is not None and p not in ids:
                errors.append(f"record {i}: dangling parent {p!r}")
    return errors


def validate_all(records):
    """Schema-validate every record via mxnet_tpu/telemetry.py loaded
    standalone (no package import, no jax needed)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "telemetry.py")
    spec = importlib.util.spec_from_file_location("_mxtpu_telemetry",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    errors = []
    for i, rec in enumerate(records):
        try:
            mod.validate_record(rec)
        except ValueError as e:
            errors.append(f"record {i}: {e}")
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Merge per-rank telemetry JSONLs into one fleet "
                    "view")
    ap.add_argument("paths", nargs="+",
                    help="JSONL log(s) or directory of *.jsonl")
    ap.add_argument("--validate", action="store_true",
                    help="schema-validate every record and check span "
                         "tree completeness; exit 1 on violations")
    ap.add_argument("--spans", type=int, default=8,
                    help="max span trees to render (default 8)")
    args = ap.parse_args(argv)
    paths = expand_paths(args.paths)
    if not paths:
        sys.stderr.write("error: no logs found\n")
        return 2
    records, bad = [], 0
    for p in paths:
        if not os.path.exists(p) and not os.path.exists(p + ".1"):
            sys.stderr.write(f"error: no such file: {p}\n")
            return 2
        recs, b = read_records(p)
        records.extend(recs)
        bad += b
    if not records:
        sys.stderr.write("error: no parseable records\n")
        return 2
    if args.validate:
        errors = validate_all(records) + check_spans(records)
        if errors:
            for err in errors:
                sys.stderr.write(f"violation: {err}\n")
            return 1
        print(f"{len(records)} records from {len(paths)} log(s) "
              f"validate (schema + span completeness)")
    ranks = group_by_rank(records)
    stats = report_fleet_summary(ranks, sys.stdout)
    report_skew_and_stragglers(ranks, stats, sys.stdout)
    report_timeline(records, sys.stdout)
    report_spans(records, sys.stdout, limit=args.spans)
    if bad:
        print(f"({bad} unparseable line(s) skipped)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
