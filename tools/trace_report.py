#!/usr/bin/env python
"""Human consumer of the telemetry event log (mxnet_tpu/telemetry.py).

Reads a ``train_events.jsonl`` and prints, per run id: the step-time
breakdown table (mean microseconds + share of the step interval), MFU
statistics, and a summary of the discrete resilience events — skipped
steps (with step ids), restarts, divergence rollbacks, watchdog
expiries, checkpoint commits.  Elastic gang events (rank_dead /
mesh_reshape / rank_rejoin / elastic_recover, see
mxnet_tpu/resilience.py) get their own narrative section: who died at
which step, what each new mesh epoch looks like, and how long each
recovery took and from which source (peer RAM vs disk).

Stdlib-only on purpose: it must run on a machine with neither jax nor
the package installed (pull the JSONL off a pod, read it anywhere).
``--validate`` additionally loads ``mxnet_tpu/telemetry.py`` standalone
(importlib, no package import) and runs every record through
``validate_record`` — the schema's executable spec.

Usage:
    python tools/trace_report.py train_events.jsonl [--validate]
"""

import argparse
import json
import os
import sys

BREAKDOWN_KEYS = ("data", "host_prep", "dispatch", "readback",
                  "collective", "other")


def read_records(path):
    """Parse one JSONL log; when a rotated predecessor ``<path>.1``
    exists (MXTPU_TELEMETRY_MAX_MB size cap) it is read FIRST, so the
    report spans the rotation boundary.  A truncated tail (crash
    mid-append) is skipped with a warning, never a crash."""
    records, bad = [], 0
    rotated = path + ".1"
    if os.path.exists(rotated):
        recs, b = _read_one(rotated)
        records.extend(recs)
        bad += b
    if os.path.exists(path):
        recs, b = _read_one(path)
        records.extend(recs)
        bad += b
    return records, bad


def _read_one(path):
    records, bad = [], 0
    with open(path, "r") as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                sys.stderr.write(
                    f"warning: skipping unparseable line {ln} "
                    f"(truncated append?)\n")
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records, bad


def _mean(vals):
    vals = [v for v in vals if v is not None]
    return sum(vals) / len(vals) if vals else None


def _pctl(vals, q):
    """Nearest-rank percentile over a non-empty list (stdlib-only)."""
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    idx = min(len(vals) - 1, max(0, int(round(q / 100.0 * len(vals))) - 1))
    return vals[idx]


def _fmt(v, nd=1):
    return "-" if v is None else f"{v:.{nd}f}"


def report_run(run, records, out):
    all_steps = [r for r in records if r.get("type") == "step"]
    # autotune trial steps time candidate configs, not the run: every
    # steady-state aggregate below excludes them (they get their own
    # section)
    trials = [s for s in all_steps if s.get("tuning_trial")]
    steps = [s for s in all_steps if not s.get("tuning_trial")]
    events = [r for r in records if r.get("type") == "event"]
    requests = [r for r in records if r.get("type") == "request"]
    attestations = [r for r in records if r.get("type") == "integrity"]
    out.write(f"run {run}: {len(steps)} step records"
              + (f" (+{len(trials)} tuning trials)" if trials else "")
              + f", {len(events)} events, {len(requests)} requests"
              + (f", {len(attestations)} attestations"
                 if attestations else "")
              + "\n")
    if requests:
        report_requests(requests, out)
    if steps:
        wall = _mean([s.get("wall_us") for s in steps])
        interval = _mean([s.get("interval_us") for s in steps])
        out.write(f"  steps/s {1e6 / interval:.1f}  "
                  f"wall {_fmt(wall)} us  interval {_fmt(interval)} us\n")
        out.write("  breakdown (mean):\n")
        out.write(f"    {'stage':<12}{'us':>12}{'share':>9}\n")
        for key in BREAKDOWN_KEYS:
            us = _mean([s.get("breakdown_us", {}).get(key)
                        for s in steps])
            share = _mean([s.get("shares", {}).get(key) for s in steps])
            out.write(f"    {key:<12}{_fmt(us):>12}"
                      f"{_fmt(share, 3):>9}\n")
        mfus = [s.get("mfu") for s in steps if s.get("mfu") is not None]
        if mfus:
            out.write(f"  mfu: mean {sum(mfus) / len(mfus):.5f}  "
                      f"min {min(mfus):.5f}  max {max(mfus):.5f}\n")
        else:
            out.write("  mfu: unavailable (no cost analysis / unknown "
                      "device peak)\n")
        cbytes = sum(s.get("collective_bytes") or 0 for s in steps)
        cbuckets = sum(s.get("collective_buckets") or 0 for s in steps)
        if cbuckets:
            out.write(f"  collectives: {cbytes} bytes in {cbuckets} "
                      f"buckets\n")
        skipped = [s for s in steps if s.get("skipped")]
        if skipped:
            ids = [s.get("step") for s in skipped]
            out.write(f"  skipped steps: {len(skipped)} "
                      f"(ids {ids})\n")
        report_pipeline(steps, out)
    kinds = {}
    for e in events:
        kinds.setdefault(e.get("event", "?"), []).append(e)
    report_embeddings(steps, kinds, out)
    if events:
        out.write("  events:\n")
        for kind in sorted(kinds):
            group = kinds[kind]
            ids = [e["step"] for e in group if "step" in e]
            at = f" at steps {ids}" if ids else ""
            out.write(f"    {kind}: {len(group)}{at}\n")
        report_resilience(kinds, out)
        report_fencing(kinds, out)
        report_data(kinds, out)
        report_integrity(kinds, attestations, out)
        report_fleet(kinds, requests, out)
        report_autotune(kinds, trials, out)
    else:
        if attestations:
            report_integrity({}, attestations, out)
        if trials:
            report_autotune({}, trials, out)


def report_pipeline(steps, out):
    """Pipeline-schedule section (docs/parallel.md "Pipeline
    parallelism on the captured step"): the bubble share the 1F1B
    microbatch schedule paid, aggregated over the run's steps, plus
    the per-device bytes the stage grad hand-off moved on the ``pp``
    mesh axis.  Prints nothing for unpipelined runs — no step record
    carries ``bubble_fraction`` (schema v5)."""
    bubbles = [s.get("bubble_fraction") for s in steps
               if s.get("bubble_fraction") is not None]
    if not bubbles:
        return
    out.write("  pipeline:\n")
    out.write(f"    bubble_fraction: mean {_mean(bubbles):.4f}  "
              f"min {min(bubbles):.4f}  max {max(bubbles):.4f} "
              f"over {len(bubbles)} step(s)\n")
    pp_bytes = [s["collective_bytes_by_axis"]["pp"] for s in steps
                if isinstance(s.get("collective_bytes_by_axis"), dict)
                and s["collective_bytes_by_axis"].get("pp")]
    if pp_bytes:
        out.write(f"    pp hand-off: mean {_mean(pp_bytes):.0f} "
                  f"bytes/step/device\n")


def report_embeddings(steps, kinds, out):
    """Sparse-embedding section (docs/perf.md "Sharded embeddings"):
    host id-prep time and unique-id fraction of the captured sparse
    steps (schema v6 ``lookup_us``/``unique_fraction`` fields), plus
    every ``sparse_fallback`` event with its reason — a sparse model
    landing on the eager oracle is a performance cliff and never
    silent.  Prints nothing for dense runs."""
    lookups = [s.get("lookup_us") for s in steps
               if s.get("lookup_us") is not None]
    fallbacks = kinds.get("sparse_fallback", ())
    if not lookups and not fallbacks:
        return
    out.write("  embeddings:\n")
    if lookups:
        out.write(f"    lookup_us: mean {_mean(lookups):.1f}  "
                  f"p50 {_pctl(lookups, 50):.1f}  "
                  f"p99 {_pctl(lookups, 99):.1f} "
                  f"over {len(lookups)} step(s)\n")
        shares = [s["lookup_us"] / s["wall_us"] for s in steps
                  if s.get("lookup_us") is not None
                  and s.get("wall_us")]
        if shares:
            out.write(f"    lookup stall share: mean "
                      f"{_mean(shares):.4f} of step wall time\n")
        fracs = [s.get("unique_fraction") for s in steps
                 if s.get("unique_fraction") is not None]
        if fracs:
            out.write(f"    unique_fraction: mean {_mean(fracs):.4f}  "
                      f"min {min(fracs):.4f}  max {max(fracs):.4f}\n")
    if fallbacks:
        reasons = {}
        for e in fallbacks:
            reasons[e.get("reason", "?")] = \
                reasons.get(e.get("reason", "?"), 0) + 1
        out.write(f"    sparse fallbacks: {len(fallbacks)} step(s) ran "
                  f"the eager oracle\n")
        for reason in sorted(reasons):
            out.write(f"      {reasons[reason]}x {reason}\n")


def report_integrity(kinds, attestations, out):
    """Integrity-plane section: attestation rounds, cross-replica
    mismatches, replay-audit verdicts, and quarantines.  Prints
    nothing when the run never attested and saw no SDC events."""
    integ_kinds = ("sdc_detected", "integrity_mismatch", "replay_audit",
                   "rank_quarantined", "serving_reload_rejected")
    if not attestations and not any(k in kinds for k in integ_kinds):
        return
    out.write("  integrity:\n")
    if attestations:
        bad = [a for a in attestations if not a.get("ok")]
        out.write(f"    attestations: {len(attestations)} "
                  f"({len(bad)} mismatched)\n")
    for e in kinds.get("integrity_mismatch", ()):
        out.write(f"    mismatch: step {e.get('step', '?')} corrupt "
                  f"rank(s) {e.get('corrupt', '?')} "
                  f"({e.get('votes', '?')} votes)\n")
    for e in kinds.get("sdc_detected", ()):
        out.write(f"    sdc: rank {e.get('rank', '?')} at step "
                  f"{e.get('step', '?')} kind "
                  f"{e.get('kind', '?')}\n")
    for e in kinds.get("replay_audit", ()):
        out.write(f"    replay audit: rank {e.get('rank', '?')} step "
                  f"{e.get('step', '?')} -> {e.get('kind', '?')}\n")
    for e in kinds.get("rank_quarantined", ()):
        out.write(f"    quarantined: rank {e.get('rank', '?')} "
                  f"(epoch {e.get('epoch', '?')}, step "
                  f"{e.get('step', '?')})\n")
    for e in kinds.get("serving_reload_rejected", ()):
        out.write(f"    serving reload rejected: step "
                  f"{e.get('step', '?')} ({e.get('reason', '?')})\n")


def report_autotune(kinds, trials, out):
    """Autotune section: trials run (with infeasible count), the
    winning config and its measured improvement over defaults, and DB
    activity — hits on restart (the zero-trial replay path), writes,
    and corrupt-entry fallbacks.  Prints nothing when the run never
    tuned."""
    tune_kinds = ("tune_search_start", "tune_trial", "tune_infeasible",
                  "tune_winner", "tune_db_hit", "tune_db_write",
                  "tune_db_fallback")
    if not any(k in kinds for k in tune_kinds) and not trials:
        return
    out.write("  autotune:\n")
    n_trials = len(kinds.get("tune_trial", ())) or len(trials)
    n_infeasible = len(kinds.get("tune_infeasible", ()))
    if n_trials or n_infeasible:
        out.write(f"    trials: {n_trials} scored, "
                  f"{n_infeasible} infeasible (OOM)\n")
    for e in kinds.get("tune_winner", ()):
        imp = e.get("improvement")
        vs = "" if imp is None else \
            f"  ({imp:.3f}x vs default {_fmt(e.get('default_score_us'))}" \
            f" us)"
        out.write(f"    winner: {e.get('fingerprint', '?')} at "
                  f"{_fmt(e.get('score_us'))} us/step{vs}\n")
    hits = kinds.get("tune_db_hit", ())
    if hits:
        fps = sorted({e.get("fingerprint", "?") for e in hits})
        out.write(f"    db hits (replayed with zero trials): "
                  f"{len(hits)} ({', '.join(fps)})\n")
    writes = len(kinds.get("tune_db_write", ()))
    if writes:
        out.write(f"    db writes: {writes}\n")
    for e in kinds.get("tune_db_fallback", ()):
        why = e.get("reason") or (
            f"{e.get('corrupt_entries', 0)} corrupt, "
            f"{e.get('stale_entries', 0)} stale entries")
        out.write(f"    db fallback: {why} -> defaults kept\n")


def report_requests(requests, out):
    """Per-request serving section: latency percentiles for each stage
    of the request path plus the padding overhead the bucket policy
    cost (schema: the 'request' record in docs/observability.md)."""
    out.write("  serving requests:\n")
    out.write(f"    {'stage':<22}{'p50 us':>12}{'p99 us':>12}\n")
    for key, label in (("queue_us", "queue"),
                       ("prefill_us", "prefill"),
                       ("decode_us_per_token", "decode/token")):
        vals = [r.get(key) for r in requests]
        out.write(f"    {label:<22}{_fmt(_pctl(vals, 50)):>12}"
                  f"{_fmt(_pctl(vals, 99)):>12}\n")
    pf = _mean([r.get("padded_fraction") for r in requests])
    out.write(f"    mean padded_fraction {_fmt(pf, 4)}\n")
    buckets = {}
    for r in requests:
        b = r.get("bucket")
        if isinstance(b, list) and len(b) == 2:
            key = f"{b[0]}x{b[1]}"
            buckets[key] = buckets.get(key, 0) + 1
    if buckets:
        hist = "  ".join(f"{k}:{buckets[k]}" for k in sorted(buckets))
        out.write(f"    buckets (batch x seq): {hist}\n")
    gens = sorted({r["generation"] for r in requests
                   if r.get("generation") is not None})
    if len(gens) > 1:
        out.write(f"    weight generations served: {gens} "
                  f"(hot reload mid-run)\n")


def report_resilience(kinds, out):
    """Narrative summary of the elastic-gang events in one run.

    ``kinds`` is the {event kind: [records]} map built by report_run.
    Prints nothing when the run had no elastic activity.
    """
    elastic_kinds = ("rank_suspected", "straggler_suspected", "rank_dead",
                     "rank_rejoin", "mesh_reshape", "elastic_recover",
                     "ckpt_fallback", "inflight_save_dropped")
    if not any(k in kinds for k in elastic_kinds):
        return
    out.write("  resilience:\n")
    for e in kinds.get("rank_suspected", ()):
        out.write(f"    suspected: rank {e.get('rank', '?')} silent "
                  f"{_fmt(e.get('silence_s'), 2)} s "
                  f"(phi {_fmt(e.get('phi'), 1)})\n")
    for e in kinds.get("straggler_suspected", ()):
        out.write(f"    straggler: rank {e.get('rank', '?')} at step "
                  f"{e.get('step', '?')} (mean collective share "
                  f"{_fmt(e.get('mean_collective_share'), 3)})\n")
    for e in kinds.get("rank_dead", ()):
        out.write(f"    dead: rank {e.get('rank', '?')} "
                  f"(epoch {e.get('epoch', '?')}, "
                  f"detected at step {e.get('step', '?')})\n")
    for e in kinds.get("rank_rejoin", ()):
        out.write(f"    rejoin: rank {e.get('rank', '?')} "
                  f"(epoch {e.get('epoch', '?')})\n")
    for e in kinds.get("mesh_reshape", ()):
        out.write(f"    reshape: epoch {e.get('epoch', '?')} world "
                  f"{e.get('world', '?')} members "
                  f"{e.get('members', '?')} at step "
                  f"{e.get('step', '?')}\n")
    recovers = kinds.get("elastic_recover", ())
    for e in recovers:
        out.write(f"    recover: epoch {e.get('epoch', '?')} from "
                  f"{e.get('source', '?')} at step {e.get('step', '?')} "
                  f"in {_fmt(e.get('recovery_ms'))} ms\n")
    lat = [e.get("recovery_ms") for e in recovers
           if e.get("recovery_ms") is not None]
    if lat:
        out.write(f"    recovery latency: mean "
                  f"{sum(lat) / len(lat):.1f} ms  max {max(lat):.1f} ms "
                  f"over {len(lat)} recover(ies)\n")
    for e in kinds.get("ckpt_fallback", ()):
        out.write(f"    ckpt fallback: step {e.get('step', '?')} "
                  f"unreadable ({e.get('reason', '?')})\n")
    for e in kinds.get("inflight_save_dropped", ()):
        out.write(f"    inflight save dropped: step "
                  f"{e.get('step', '?')} ({e.get('reason', '?')})\n")


def report_fencing(kinds, out):
    """Split-brain fencing section (schema v8): which ranks fenced and
    why, every rejected stale write by kind (kv / peer_frame /
    checkpoint manifest), and partition heal latency.  Prints nothing
    for runs with no fencing activity."""
    fence_kinds = ("gang_fenced", "fencing_rejected", "ckpt_fenced",
                   "partition_healed")
    if not any(k in kinds for k in fence_kinds):
        return
    out.write("  fencing:\n")
    fenced = kinds.get("gang_fenced", ())
    for e in fenced:
        out.write(f"    fenced: rank {e.get('rank', '?')} at epoch "
                  f"{e.get('epoch', '?')} ({e.get('reason', '?')})\n")
    rejected = kinds.get("fencing_rejected", ())
    if rejected:
        by_kind = {}
        for e in rejected:
            by_kind.setdefault(e.get("kind", "?"), []).append(e)
        parts = ", ".join(f"{k}: {len(v)}"
                          for k, v in sorted(by_kind.items()))
        out.write(f"    rejected stale writes: {len(rejected)} "
                  f"({parts})\n")
        for e in rejected:
            out.write(f"      {e.get('kind', '?')}: rank "
                      f"{e.get('rank', '?')} epoch "
                      f"{e.get('epoch', '?')} < committed "
                      f"{e.get('committed', '?')}\n")
    for e in kinds.get("ckpt_fenced", ()):
        out.write(f"    ckpt commit aborted: rank {e.get('rank', '?')} "
                  f"step {e.get('step', '?')} epoch "
                  f"{e.get('epoch', '?')} ({e.get('reason', '?')})\n")
    healed = kinds.get("partition_healed", ())
    for e in healed:
        out.write(f"    healed: rank {e.get('rank', '?')} fenced for "
                  f"{_fmt(e.get('fenced_ms'))} ms before rejoin\n")
    lat = [e.get("fenced_ms") for e in healed
           if e.get("fenced_ms") is not None]
    if lat:
        out.write(f"    heal latency: mean {sum(lat) / len(lat):.1f} ms"
                  f"  max {max(lat):.1f} ms over {len(lat)} "
                  f"partition(s)\n")


def report_data(kinds, out):
    """Input-pipeline section (docs/resilience.md "Data-pipeline
    state"): every exactly-once resume with its sample ledger — the
    re-read and skipped counts MUST both be 0, anything else is
    flagged — plus the quarantine census (which poisoned batches the
    post-rollback replay refused, one ``batch_quarantined`` event
    each) and hung-worker timeouts.  Prints nothing for runs without
    a resumable pipeline."""
    data_kinds = ("data_resume", "batch_quarantined",
                  "data_worker_timeout")
    if not any(k in kinds for k in data_kinds):
        return
    out.write("  data pipeline:\n")
    resumes = kinds.get("data_resume", ())
    if resumes:
        reread = sum(e.get("reread_samples") or 0 for e in resumes)
        skipped = sum(e.get("skipped_samples") or 0 for e in resumes)
        flag = "" if reread == 0 and skipped == 0 else \
            "  ** NOT exactly-once **"
        out.write(f"    resumes: {len(resumes)}  re-read samples "
                  f"{reread}  skipped samples {skipped}{flag}\n")
        for e in resumes:
            out.write(f"      epoch {e.get('epoch', '?')} cursor "
                      f"{e.get('cursor', '?')} (samples_seen "
                      f"{e.get('samples_seen', '?')}, world "
                      f"{e.get('world', '?')})\n")
    quarantined = kinds.get("batch_quarantined", ())
    if quarantined:
        ids = [(e.get("epoch", "?"), e.get("batch", "?"))
               for e in quarantined]
        samples = sum(e.get("samples") or 0 for e in quarantined)
        out.write(f"    quarantined batches skipped on replay: "
                  f"{len(quarantined)} ({samples} sample(s)): "
                  f"{ids}\n")
    timeouts = kinds.get("data_worker_timeout", ())
    if timeouts:
        batches = [e.get("batch", "?") for e in timeouts]
        out.write(f"    worker-hang timeouts: {len(timeouts)} "
                  f"(batches {batches})\n")


def report_fleet(kinds, requests, out):
    """Traffic-elastic fleet section: scale events, planned-vs-detected
    reshape latency, coordinator failovers, and the serving admission
    counters (shed / deadline-exceeded requests).  Prints nothing when
    the run had no fleet activity."""
    fleet_kinds = ("scale_up", "scale_down", "gang_drain_scheduled",
                   "rank_drained", "chips_freed",
                   "serving_replica_spawned", "coordinator_failover",
                   "coordinator_reconnect", "queue_full",
                   "serving_request_shed")
    deadline = sum(1 for r in requests if r.get("deadline_exceeded"))
    if not any(k in kinds for k in fleet_kinds) and not deadline:
        return
    out.write("  fleet:\n")
    for e in kinds.get("scale_up", ()):
        out.write(f"    scale up: rank {e.get('rank', '?')} requested "
                  f"world {e.get('world', '?')} -> "
                  f"{e.get('want_world', '?')} at step "
                  f"{e.get('step', '?')} (queue depth "
                  f"{_fmt(e.get('queue_depth'))})\n")
    for e in kinds.get("scale_down", ()):
        out.write(f"    scale down: rank {e.get('rank', '?')} drains at "
                  f"step {e.get('at_step', '?')} (world "
                  f"{e.get('world', '?')}, planned)\n")
    for e in kinds.get("rank_drained", ()):
        out.write(f"    drained: rank {e.get('rank', '?')} left cleanly "
                  f"(epoch {e.get('epoch', '?')})\n")
    for e in kinds.get("chips_freed", ()):
        out.write(f"    chips freed: rank {e.get('rank', '?')} "
                  f"({e.get('count', '?')} chip(s))\n")
    for e in kinds.get("serving_replica_spawned", ()):
        out.write(f"    replica spawned on freed chips of rank "
                  f"{e.get('rank', '?')}\n")
    recovers = kinds.get("elastic_recover", ())
    planned = [e.get("recovery_ms") for e in recovers
               if e.get("planned") and e.get("recovery_ms") is not None]
    detected = [e.get("recovery_ms") for e in recovers
                if not e.get("planned")
                and e.get("recovery_ms") is not None]
    if planned or detected:
        def _stats(vals):
            return (f"mean {sum(vals) / len(vals):.1f} ms over "
                    f"{len(vals)}") if vals else "none"
        out.write(f"    reshape latency: planned {_stats(planned)}  "
                  f"detected {_stats(detected)}\n")
    failovers = kinds.get("coordinator_failover", ())
    if failovers:
        by = [f"rank {e.get('rank', '?')}" for e in failovers]
        out.write(f"    coordinator failovers: {len(failovers)} "
                  f"(promoted: {', '.join(by)})\n")
    reconnects = len(kinds.get("coordinator_reconnect", ()))
    if reconnects:
        out.write(f"    coordinator reconnects: {reconnects}\n")
    shed_batcher = len(kinds.get("queue_full", ()))
    shed_front = len(kinds.get("serving_request_shed", ()))
    if shed_batcher or shed_front:
        out.write(f"    shed requests: {shed_batcher} queue-full "
                  f"(front door retried {shed_front})\n")
    if deadline:
        out.write(f"    deadline-exceeded requests: {deadline}\n")


def validate_all(records):
    """Run every record through the package's validate_record without
    importing the package (and without needing jax installed)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "telemetry.py")
    spec = importlib.util.spec_from_file_location("_mxtpu_telemetry",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    errors = []
    for i, rec in enumerate(records):
        try:
            mod.validate_record(rec)
        except ValueError as e:
            errors.append(f"record {i}: {e}")
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Summarize a mxnet_tpu train_events.jsonl")
    ap.add_argument("path", help="path to the JSONL event log")
    ap.add_argument("--validate", action="store_true",
                    help="validate every record against the schema")
    args = ap.parse_args(argv)
    if not os.path.exists(args.path) \
            and not os.path.exists(args.path + ".1"):
        sys.stderr.write(f"error: no such file: {args.path}\n")
        return 2
    records, bad = read_records(args.path)
    if not records:
        sys.stderr.write("error: no parseable records\n")
        return 2
    if args.validate:
        errors = validate_all(records)
        if errors:
            for err in errors:
                sys.stderr.write(f"schema violation: {err}\n")
            return 1
        print(f"{len(records)} records validate against schema "
              f"v{records[0].get('v', '?')}")
    runs = {}
    for rec in records:
        runs.setdefault(rec.get("run", "?"), []).append(rec)
    for run in runs:
        report_run(run, runs[run], sys.stdout)
    if bad:
        print(f"({bad} unparseable line(s) skipped)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
