#!/bin/bash
# TPU measurement session (docs/perf.md runbook, automated).
#
# Polls the axon tunnel with a bounded probe; when it comes up, runs the
# measurement sequence SERIALLY with generous budgets (a budget kill
# mid-remote-compile can wedge the tunnel with a stale claim — so the
# per-step timeouts here are long enough that they should never fire on
# a healthy tunnel).  Results append to chip_results.jsonl; the warmed
# .jax_cache makes the driver's subsequent `python bench.py` fast.
#
# Usage: nohup bash tools/chip_session.sh &   (from the repo root)

cd "$(dirname "$0")/.." || exit 1
OUT=chip_results.jsonl
LOG=chip_session.log
PROBE_EVERY=${PROBE_EVERY:-600}
MAX_POLLS=${MAX_POLLS:-60}

log() { echo "[$(date +%T)] $*" >> "$LOG"; }

probe() {
    timeout 90 python -c "import jax; d=jax.devices(); \
print(d[0].platform, getattr(d[0],'device_kind',''))" 2>/dev/null
}

run_step() {  # $1 = label, $2 = timeout, rest = command
    local label=$1 budget=$2; shift 2
    log "start $label (budget ${budget}s)"
    local t0=$SECONDS
    local out
    out=$(mktemp) || return 1
    timeout "$budget" "$@" > "$out" 2>> "$LOG"
    local rc=$?
    local line
    line=$(grep -E '^\{' "$out" | tail -1)
    rm -f "$out"
    # only embed verified JSON (a budget kill can truncate mid-write)
    if [ -n "$line" ] && ! python -c 'import json,sys; json.loads(sys.argv[1])' "$line" 2>/dev/null; then
        line=""
    fi
    if [ -n "$line" ]; then
        echo "{\"step\": \"$label\", \"rc\": $rc, \"secs\": $((SECONDS-t0)), \"result\": $line}" >> "$OUT"
    else
        echo "{\"step\": \"$label\", \"rc\": $rc, \"secs\": $((SECONDS-t0)), \"result\": null}" >> "$OUT"
    fi
    log "done $label rc=$rc in $((SECONDS-t0))s"
    return $rc
}

log "watcher started"
for i in $(seq 1 "$MAX_POLLS"); do
    p=$(probe)
    if echo "$p" | grep -qv cpu && [ -n "$p" ]; then
        log "tunnel UP ($p) after $i polls — starting sequence"
        run_step resnet50_b256_nchw 2700 python bench.py --worker \
            '{"model": "resnet50", "batch": 256, "image": 224, "steps": 20, "backend": "tpu", "layout": "NCHW"}'
        run_step bert_b32_t512_flash 2700 python bench.py --worker \
            '{"model": "bert", "batch": 32, "seq": 512, "steps": 12, "backend": "tpu", "attn": "flash"}'
        run_step resnet50_b256_nhwc 2700 python bench.py --worker \
            '{"model": "resnet50", "batch": 256, "image": 224, "steps": 20, "backend": "tpu", "layout": "NHWC"}'
        run_step full_bench 2400 python bench.py
        # cheap extras once the cache is warm: on-chip decode + sparse
        run_step bench_decode 1200 python tools/bench_decode.py
        run_step bench_sparse 1200 python tools/bench_sparse.py
        log "sequence complete"
        exit 0
    fi
    log "probe $i/$MAX_POLLS: tunnel down"
    sleep "$PROBE_EVERY"
done
log "gave up after $MAX_POLLS polls"
exit 2
