#!/usr/bin/env python
"""Profile the compiled ResNet-50 / BERT training step on the attached chip.

The instrument behind BASELINE.md's MFU notes (VERDICT r2 item #2): times the
whole-step program honestly (host-readback terminated — block_until_ready does
not synchronize on this backend until a readback happens), then dissects the
optimized HLO: op-category histogram from XLA's cost analysis, transpose/copy
counts (layout pressure), conv shapes, and the biggest fusions.

Usage:
    python tools/profile_step.py resnet50 --batch 256 --steps 10
    python tools/profile_step.py bert --batch 32 --seq 512
    python tools/profile_step.py resnet50 --xplane /tmp/trace  # full trace
"""

import argparse
import collections
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_resnet(args):
    import numpy as np
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    net = getattr(vision, args.model)(classes=1000)
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mesh = parallel.data_parallel_mesh(1)
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}, mesh=mesh)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal(
        (args.batch, 3, args.image, args.image)), jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, args.batch).astype("float32"))
    flops = 3.0 * 2 * 4.089e9 * (args.image / 224.0) ** 2 * args.batch
    return trainer, (x, y), flops


def build_bert(args):
    import numpy as np
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon.model_zoo import bert as bert_zoo

    net = bert_zoo.bert_base(dropout=0.0, max_length=args.seq,
                             scan_layers=not args.no_scan,
                             attention_impl=args.attn)
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mesh = parallel.data_parallel_mesh(1)
    trainer = parallel.ShardedTrainer(
        net, bert_zoo.BERTPretrainLoss(), "adamw",
        {"learning_rate": 1e-4, "wd": 0.01}, mesh=mesh)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 30000, (args.batch, args.seq)),
                         jnp.int32)
    mlm = np.full((args.batch, args.seq), -1, np.int32)
    pos = rng.rand(args.batch, args.seq) < 0.15
    mlm[pos] = rng.randint(0, 30000, int(pos.sum()))
    nsp = jnp.asarray(rng.randint(0, 2, (args.batch,)), jnp.int32)
    y = (jnp.asarray(mlm), nsp)
    attn = 12 * 2 * 2 * args.seq * 768
    flops = 3.0 * (2 * 110e6 + attn) * args.batch * args.seq
    return trainer, (tokens, y), flops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", nargs="?", default="resnet50_v1")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--attn", default="flash")
    ap.add_argument("--no-scan", action="store_true",
                    help="unstacked per-layer blocks (slow compile)")
    ap.add_argument("--xplane", default=None,
                    help="directory to dump a jax.profiler trace into")
    ap.add_argument("--hlo-out", default=None,
                    help="write full optimized HLO text here")
    args = ap.parse_args()
    if args.model == "resnet50":
        args.model = "resnet50_v1"

    import numpy as np
    import jax

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})", file=sys.stderr)
    if dev.platform != "cpu":
        cache_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache")
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass

    if args.model.startswith("bert"):
        trainer, (x, y), flops = build_bert(args)
    else:
        trainer, (x, y), flops = build_resnet(args)

    # compile + drain (readback = the only real sync on this backend)
    t0 = time.perf_counter()
    np.asarray(trainer.step(x, y)._data)
    print(f"first step (compile): {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    np.asarray(trainer.step(x, y)._data)

    if args.xplane:
        with jax.profiler.trace(args.xplane):
            for _ in range(3):
                out = trainer.step(x, y)
            np.asarray(out._data)
        print(f"xplane trace in {args.xplane}", file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = trainer.step(x, y)
    np.asarray(out._data)
    dt = time.perf_counter() - t0
    step_ms = dt / args.steps * 1e3
    peak = {"v5e": 197e12, "v5 lite": 197e12, "v5p": 459e12,
            "v6": 918e12, "v4": 275e12}
    pk = next((v for k, v in peak.items()
               if k in dev.device_kind.lower()), None)
    mfu = flops / (dt / args.steps) / pk if pk else None

    # -- HLO dissection --------------------------------------------------------
    lowered = trainer._step_fn.lower(
        trainer._param_vals, trainer._opt_state, trainer._aux_vals,
        x, y, jax.random.PRNGKey(0),
        np.float32(0.1), np.float32(1.0))
    compiled = lowered.compile()
    hlo = compiled.as_text()
    if args.hlo_out:
        with open(args.hlo_out, "w") as f:
            f.write(hlo)

    ops = collections.Counter()
    conv_lines = []
    for m in re.finditer(r"^\s*(?:ROOT )?%?[\w.\-]+ = \S+ (\w+)\(", hlo,
                         re.M):
        ops[m.group(1)] += 1
    for ln in hlo.splitlines():
        if " convolution(" in ln and "fusion" not in ln:
            shape = re.search(r"= (\S+) convolution", ln)
            win = re.search(r"window={([^}]*)}", ln)
            dnums = re.search(r"dim_labels=(\S+?)[,}]", ln)
            conv_lines.append((shape and shape.group(1),
                               dnums and dnums.group(1),
                               win and win.group(1)[:40]))

    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
    except Exception:
        cost = {}

    result = {
        "model": args.model,
        "batch": args.batch,
        "step_ms": round(step_ms, 2),
        "mfu": round(mfu, 4) if mfu else None,
        "samples_per_sec": round(args.batch / (dt / args.steps), 1),
        "hlo_op_histogram": dict(ops.most_common(20)),
        "transposes": ops.get("transpose", 0),
        "copies": ops.get("copy", 0),
        "convs": len(conv_lines),
        "flops_analytic": flops,
        "flops_xla": cost.get("flops"),
        "bytes_accessed": cost.get("bytes accessed"),
    }
    print(json.dumps(result, indent=2))
    print("\nconv dim_labels (first 30):", file=sys.stderr)
    for c in conv_lines[:30]:
        print("  ", c, file=sys.stderr)


if __name__ == "__main__":
    main()
