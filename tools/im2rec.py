#!/usr/bin/env python
"""im2rec: pack image datasets into RecordIO (.rec + .idx).

Reference parity: tools/im2rec.py — builds .lst files from image folders
and encodes them into the RecordIO container the data pipeline consumes.
PIL does codec work (the reference uses OpenCV).
"""

from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def list_image(root, recursive, exts):
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and (suffix in exts):
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
        for k, v in sorted(cat.items(), key=lambda x: x[1]):
            print(os.path.relpath(k, root), v)
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and (suffix in exts):
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def read_list(path_in):
    with open(path_in) as fin:
        while True:
            line = fin.readline()
            if not line:
                break
            line = [i.strip() for i in line.strip().split("\t")]
            line_len = len(line)
            if line_len < 3:
                print(f"lst should have at least has three parts, but only "
                      f"has {line_len} parts for {line}")
                continue
            try:
                item = [int(line[0])] + [line[-1]] + \
                    [float(i) for i in line[1:-1]]
            except Exception as e:
                print(f"Parsing lst met error for {line}, detail: {e}")
                continue
            yield item


def image_encode(args, i, item, q_out):
    import numpy as np

    from mxnet_tpu import image as img_mod
    from mxnet_tpu import recordio

    fullpath = os.path.join(args.root, item[1])
    header = recordio.IRHeader(0, item[2] if len(item) == 3 else item[2:],
                               item[0], 0)
    try:
        with open(fullpath, "rb") as fin:
            img_bytes = fin.read()
        if args.pass_through:
            s = recordio.pack(header, img_bytes)
            q_out.append((i, s, item))
            return
        arr = img_mod.imdecode_np(img_bytes)
        if args.center_crop and arr.shape[0] != arr.shape[1]:
            size = min(arr.shape[:2])
            arr = img_mod.center_crop_np(arr, (size, size))
        if args.resize and (arr.shape[0] > args.resize
                            or arr.shape[1] > args.resize):
            arr = img_mod.resize_short_np(arr, args.resize)
        s = recordio.pack_img(header, arr, quality=args.quality,
                              img_fmt=args.encoding)
        q_out.append((i, s, item))
    except Exception as e:
        print(f"imread error trying to load file: {fullpath}: {e}")
        q_out.append((i, None, item))


def make_rec(args, image_list):
    from mxnet_tpu import recordio

    fname = os.path.basename(args.prefix)
    working_dir = os.path.dirname(os.path.abspath(args.prefix)) or "."
    record = recordio.MXIndexedRecordIO(
        os.path.join(working_dir, fname + ".idx"),
        os.path.join(working_dir, fname + ".rec"), "w")
    count = 0
    for i, item in enumerate(image_list):
        out = []
        image_encode(args, i, item, out)
        _, s, it = out[0]
        if s is None:
            continue
        record.write_idx(it[0], s)
        count += 1
        if count % 1000 == 0:
            print(f"{count} images packed")
    record.close()
    print(f"total {count} images packed into {args.prefix}.rec")


def parse_args():
    parser = argparse.ArgumentParser(
        description="Create an image list / RecordIO file")
    parser.add_argument("prefix", help="prefix of .lst/.rec files")
    parser.add_argument("root", help="image root folder")
    parser.add_argument("--list", action="store_true",
                        help="make a .lst file instead of a .rec")
    parser.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    parser.add_argument("--recursive", action="store_true")
    parser.add_argument("--shuffle", action="store_true", default=True)
    parser.add_argument("--no-shuffle", dest="shuffle",
                        action="store_false")
    parser.add_argument("--train-ratio", type=float, default=1.0)
    parser.add_argument("--test-ratio", type=float, default=0.0)
    parser.add_argument("--pass-through", action="store_true",
                        help="skip decode/encode, pack raw bytes")
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--center-crop", action="store_true")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", choices=[".jpg", ".png"],
                        default=".jpg")
    return parser.parse_args()


def main():
    args = parse_args()
    if args.list:
        image_list = list(list_image(args.root, args.recursive,
                                     args.exts))
        if args.shuffle:
            random.seed(100)
            random.shuffle(image_list)
        n = len(image_list)
        n_train = int(n * args.train_ratio)
        n_test = int(n * args.test_ratio)
        if args.train_ratio < 1.0:
            write_list(args.prefix + "_train.lst", image_list[:n_train])
            if n_test:
                write_list(args.prefix + "_test.lst",
                           image_list[n_train:n_train + n_test])
            write_list(args.prefix + "_val.lst",
                       image_list[n_train + n_test:])
        else:
            write_list(args.prefix + ".lst", image_list)
    else:
        lst = args.prefix + ".lst" if not args.prefix.endswith(".lst") \
            else args.prefix
        if os.path.exists(lst):
            image_list = list(read_list(lst))
        else:
            image_list = [(i, p, l) for i, p, l in
                          list_image(args.root, args.recursive, args.exts)]
            if args.shuffle:
                random.seed(100)
                random.shuffle(image_list)
        make_rec(args, image_list)


if __name__ == "__main__":
    main()
