"""Runtime feature detection.

Reference parity: src/libinfo.cc + python/mxnet/runtime.py —
``feature_list()`` / ``Features`` reporting what this build supports
(``mx.runtime.Features()['TPU'].enabled``).
"""

from __future__ import annotations


class Feature:
    __slots__ = ("name", "enabled")

    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _has_native_jpeg():
    try:
        from . import _native

        return _native.has_jpeg()
    except Exception:
        return False


def _detect():
    import jax

    backends = set()
    try:
        backends = {d.platform for d in jax.devices()}
    except Exception:
        pass
    tpu = bool(backends & {"tpu", "axon"})
    feats = {
        # accelerator backends (reference: CUDA/CUDNN/TENSORRT slots)
        "TPU": tpu,
        "XLA": True,
        "PALLAS": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "TENSORRT": False,
        "MKLDNN": False,
        # numeric
        "F16C": True,          # fp16 supported via XLA
        "BF16": True,
        "INT64_TENSOR_SIZE": True,
        # IO / formats
        "OPENCV": False,       # PIL-based codecs instead
        # native threaded libjpeg decode+augment (src/image_decode.cc);
        # honest: probed from the built library, False when unbuilt
        "JPEG_TURBO": _has_native_jpeg(),
        "RECORDIO": True,
        # distributed
        "DIST_KVSTORE": True,  # jax.distributed + collectives
        "PS_LITE": False,      # parameter server dropped on TPU (SURVEY §2.6)
        "ICI_COLLECTIVES": True,
        # language/runtime
        "SIGNAL_HANDLER": False,
        "DEBUG": False,
        "PROFILER": True,
    }
    return {name: Feature(name, on) for name, on in feats.items()}


class Features(dict):
    """Dict of Feature (reference: mx.runtime.Features)."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            dict.__init__(cls.instance, _detect())
        return cls.instance

    def __init__(self):
        pass

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature '{feature_name}' is unknown, "
                               "known features are: "
                               f"{list(self.keys())}")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
