"""Evaluation metrics.

Reference parity: python/mxnet/metric.py — the EvalMetric registry
(Accuracy, TopKAccuracy, F1, MCC, Perplexity, CrossEntropy, NLL, MAE, MSE,
RMSE, PearsonCorrelation, Loss, Torch→dropped, CompositeEvalMetric,
CustomMetric + ``mx.metric.np``), ``create`` factory, and
``check_label_shapes``.

Metric math runs on host numpy, EXCEPT the hot classification metrics
(Accuracy, TopKAccuracy): when both label and prediction live on device,
argmax/argsort + compare + count run as ONE cached jitted program and only
the scalar correct-count is read back per update() — pulling the full
(batch, num_classes) logits to host every batch costs more transfer than
the whole optimizer step.  Everything else stays on host: those updates are
tiny reductions at batch cadence and matching reference (CPU) numpy
semantics exactly matters more than transfer time.
"""

from __future__ import annotations

import math

import numpy

from .base import MXNetError

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def alias(*aliases):
    def reg(klass):
        for a in aliases:
            _METRIC_REGISTRY[a.lower()] = klass
        return klass
    return reg


def create(metric, *args, **kwargs):
    """mx.metric.create — from name, callable, or list."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, str):
        if metric.lower() not in _METRIC_REGISTRY:
            raise ValueError(f"Cannot find metric {metric}")
        return _METRIC_REGISTRY[metric.lower()](*args, **kwargs)
    raise TypeError("metric should be a str, callable, list or EvalMetric")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval(label, pred) into a metric (reference:
    mx.metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = name if name is not None else numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(f"Shape of labels {label_shape} does not match "
                         f"shape of predictions {pred_shape}")
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    return labels, preds


def _to_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else numpy.asarray(x)


_DEVICE_METRIC_CACHE = {}


def _device_correct_count(kind, pred, label, **static):
    """Correct-prediction count as one jitted program on device.

    ``pred``/``label`` are raw jax arrays; the returned device scalar is
    the caller's single host readback.  Programs are cached per
    (kind, static config) — jax.jit handles per-shape retracing.
    """
    import jax
    import jax.numpy as jnp

    key = (kind,) + tuple(sorted(static.items()))
    fn = _DEVICE_METRIC_CACHE.get(key)
    if fn is None:
        if kind == "acc":
            axis = static["axis"]
            need_argmax = static["need_argmax"]

            def fn(pred, label):
                p = jnp.argmax(pred, axis=axis) if need_argmax else pred
                return (p.astype(jnp.int32).reshape(-1)
                        == label.astype(jnp.int32).reshape(-1)).sum()
        else:  # topk
            top_k = static["top_k"]

            def fn(pred, label):
                # jnp.argsort is stable; on ties it yields the same order
                # as the host numpy path for the shapes tested here.
                # lax.top_k breaks ties by highest index — wrong answers.
                order = jnp.argsort(pred.astype(jnp.float32), axis=-1)
                lab = label.astype(jnp.int32).reshape(-1)
                if order.ndim == 1:
                    return (order == lab).sum()
                num_classes = order.shape[1]
                hits = jnp.zeros((), jnp.int32)
                for j in range(min(num_classes, top_k)):
                    hits = hits + (order[:, num_classes - 1 - j]
                                   == lab).sum()
                return hits
        fn = jax.jit(fn)
        _DEVICE_METRIC_CACHE[key] = fn
    return fn(pred, label)


class EvalMetric:
    """Base class (reference: mx.metric.EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._has_global_stats = kwargs.pop("has_global_stats", False)
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            "metric": self.__class__.__name__,
            "name": self.name,
            "output_names": self.output_names,
            "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names
                     if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self._has_global_stats:
            if self.global_num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.global_sum_metric / self.global_num_inst)
        return self.get()

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        if self._has_global_stats:
            name, value = self.get_global()
            if not isinstance(name, list):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            return list(zip(name, value))
        return self.get_name_value()

    def _update(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst


@register
@alias("acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        counts = []  # (device scalar, n) pairs; one readback after the loop
        for label, pred_label in zip(labels, preds):
            if hasattr(pred_label, "_data") and hasattr(label, "_data"):
                pshape = tuple(pred_label.shape)
                lshape = tuple(label.shape)
                need_argmax = pshape != lshape
                if need_argmax:
                    ax = self.axis % len(pshape)
                    out_size = math.prod(
                        s for d, s in enumerate(pshape) if d != ax)
                else:
                    out_size = math.prod(pshape)
                check_label_shapes(range(math.prod(lshape)), range(out_size))
                dev = _device_correct_count(
                    "acc", pred_label._data, label._data,
                    axis=self.axis, need_argmax=need_argmax)
                counts.append((dev, out_size))
                continue
            pred = _to_numpy(pred_label)
            label_np = _to_numpy(label).astype("int32")
            if pred.shape != label_np.shape:
                pred = numpy.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").flatten()
            label_np = label_np.flatten()
            check_label_shapes(label_np, pred)
            correct = (pred == label_np).sum()
            self._update(float(correct), len(pred))
        if counts:
            total = counts[0][0] if len(counts) == 1 \
                else sum(c for c, _ in counts)
            self._update(float(total), sum(n for _, n in counts))


@register
@alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        counts = []  # (device scalar, n) pairs; one readback after the loop
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, \
                "Predictions should be no more than 2 dims"
            if hasattr(pred_label, "_data") and hasattr(label, "_data"):
                check_label_shapes(range(label.shape[0]),
                                   range(pred_label.shape[0]))
                dev = _device_correct_count(
                    "topk", pred_label._data, label._data, top_k=self.top_k)
                counts.append((dev, pred_label.shape[0]))
                continue
            pred = numpy.argsort(_to_numpy(pred_label).astype("float32"),
                                 axis=-1)
            label_np = _to_numpy(label).astype("int32")
            check_label_shapes(label_np, pred)
            num_samples = pred.shape[0]
            num_dims = len(pred.shape)
            if num_dims == 1:
                self._update(float((pred.flatten() == label_np.flatten())
                                   .sum()), num_samples)
            elif num_dims == 2:
                num_classes = pred.shape[1]
                top_k = min(num_classes, self.top_k)
                correct = 0.0
                for j in range(top_k):
                    correct += float((pred[:, num_classes - 1 - j].flatten()
                                      == label_np.flatten()).sum())
                self._update(correct, num_samples)
        if counts:
            total = counts[0][0] if len(counts) == 1 \
                else sum(c for c, _ in counts)
            self._update(float(total), sum(n for _, n in counts))


class _BinaryClassificationMetrics:
    """Running TP/FP/TN/FN counters (reference: metric.py helper)."""

    def __init__(self):
        self.reset_stats()

    def update_binary_stats(self, label, pred):
        pred_np = _to_numpy(pred)
        label_np = _to_numpy(label).astype("int32")
        pred_label = numpy.argmax(pred_np, axis=1) if pred_np.ndim > 1 \
            else (pred_np > 0.5).astype("int32")
        check_label_shapes(label_np.flatten(), pred_label.flatten())
        if len(numpy.unique(label_np)) > 2:
            raise ValueError("%s currently only supports binary "
                             "classification." % type(self).__name__)
        pred_label = pred_label.flatten()
        label_np = label_np.flatten()
        pred_true = (pred_label == 1)
        pred_false = ~pred_true
        label_true = (label_np == 1)
        label_false = ~label_true
        self.true_positives += (pred_true & label_true).sum()
        self.false_positives += (pred_true & label_false).sum()
        self.false_negatives += (pred_false & label_true).sum()
        self.true_negatives += (pred_false & label_false).sum()

    @property
    def precision(self):
        tp_fp = self.true_positives + self.false_positives
        return self.true_positives / tp_fp if tp_fp > 0 else 0.0

    @property
    def recall(self):
        tp_fn = self.true_positives + self.false_negatives
        return self.true_positives / tp_fn if tp_fn > 0 else 0.0

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return (2 * self.precision * self.recall
                    / (self.precision + self.recall))
        return 0.0

    @property
    def matthewscc(self):
        if not self.total_examples:
            return 0.0
        true_pos = float(self.true_positives)
        false_pos = float(self.false_positives)
        false_neg = float(self.false_negatives)
        true_neg = float(self.true_negatives)
        terms = [(true_pos + false_pos), (true_pos + false_neg),
                 (true_neg + false_pos), (true_neg + false_neg)]
        denom = 1.0
        for t in filter(lambda t: t != 0.0, terms):
            denom *= t
        return ((true_pos * true_neg - false_pos * false_neg)
                / math.sqrt(denom))

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives
                + self.true_negatives + self.true_positives)

    def reset_stats(self):
        self.false_positives = 0
        self.false_negatives = 0
        self.true_positives = 0
        self.true_negatives = 0


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(label, pred)
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.global_sum_metric += self.metrics.fscore
            self.num_inst += 1
            self.global_num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore \
                * self.metrics.total_examples
            self.global_sum_metric = self.sum_metric
            self.num_inst = self.metrics.total_examples
            self.global_num_inst = self.num_inst

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        self.global_sum_metric = 0.0
        self.global_num_inst = 0
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self._average = average
        self._metrics = _BinaryClassificationMetrics()
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self._metrics.update_binary_stats(label, pred)
        if self._average == "macro":
            self.sum_metric += self._metrics.matthewscc
            self.global_sum_metric += self._metrics.matthewscc
            self.num_inst += 1
            self.global_num_inst += 1
            self._metrics.reset_stats()
        else:
            self.sum_metric = (self._metrics.matthewscc
                               * self._metrics.total_examples)
            self.global_sum_metric = self.sum_metric
            self.num_inst = self._metrics.total_examples
            self.global_num_inst = self.num_inst

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self.global_sum_metric = 0.0
        self.global_num_inst = 0.0
        if hasattr(self, "_metrics"):
            self._metrics.reset_stats()


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label_np = _to_numpy(label).astype("int32").flatten()
            pred_np = _to_numpy(pred)
            pred_np = pred_np.reshape(-1, pred_np.shape[-1])
            assert label_np.shape[0] == pred_np.shape[0], \
                "shape mismatch between label and prediction"
            probs = pred_np[numpy.arange(label_np.shape[0]), label_np]
            if self.ignore_label is not None:
                ignore = (label_np == self.ignore_label)
                probs = numpy.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label_np.shape[0]
        self._update(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def get_global(self):
        if self._has_global_stats:
            if self.global_num_inst == 0:
                return (self.name, float("nan"))
            return (self.name,
                    math.exp(self.global_sum_metric / self.global_num_inst))
        return self.get()


@register
@alias("ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _to_numpy(label)
            pred_np = _to_numpy(pred)
            label_np = label_np.ravel()
            assert label_np.shape[0] == pred_np.shape[0]
            prob = pred_np[numpy.arange(label_np.shape[0]),
                           numpy.int64(label_np)]
            cross_entropy = (-numpy.log(prob + self.eps)).sum()
            self._update(cross_entropy, label_np.shape[0])


@register
@alias("nll_loss")
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _to_numpy(label)
            pred_np = _to_numpy(pred)
            label_np = label_np.ravel()
            num_examples = pred_np.shape[0]
            assert label_np.shape[0] == num_examples
            prob = pred_np[numpy.arange(num_examples),
                           numpy.int64(label_np)]
            nll = (-numpy.log(prob + self.eps)).sum()
            self._update(nll, num_examples)


@register
@alias("mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _to_numpy(label)
            pred_np = _to_numpy(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            self._update(numpy.abs(label_np - pred_np).mean(), 1)


@register
@alias("mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _to_numpy(label)
            pred_np = _to_numpy(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            self._update(((label_np - pred_np) ** 2.0).mean(), 1)


@register
@alias("rmse")
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _to_numpy(label)
            pred_np = _to_numpy(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            self._update(numpy.sqrt(((label_np - pred_np) ** 2.0).mean()), 1)


@register
@alias("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _to_numpy(label).ravel()
            pred_np = _to_numpy(pred).ravel()
            check_label_shapes(label_np, pred_np, False, True)
            self._update(numpy.corrcoef(pred_np, label_np)[0, 1], 1)


@register
class Loss(EvalMetric):
    """Dummy metric for directly printing loss outputs (reference:
    mx.metric.Loss).

    Non-finite loss values are EXCLUDED from the running sum — a single
    NaN would otherwise poison the average for the rest of the epoch
    (``sum_metric`` can never recover from ``nan + x``).  The excluded
    count is tracked in ``num_nonfinite`` and warned about once per
    reset, so divergence stays visible without wrecking the report.
    """

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        self.num_nonfinite = 0

    def update(self, _, preds):
        if isinstance(preds, (list, tuple)) is False:
            preds = [preds]
        for pred in preds:
            arr = _to_numpy(pred)
            finite = numpy.isfinite(arr)
            if finite.all():
                self._update(float(arr.sum()), int(arr.size))
                continue
            n_bad = int(arr.size - finite.sum())
            if self.num_nonfinite == 0:
                import warnings

                warnings.warn(
                    f"Loss metric '{self.name}': {n_bad} non-finite "
                    "value(s) excluded from the running sum (see "
                    "num_nonfinite)", RuntimeWarning, stacklevel=2)
            self.num_nonfinite += n_bad
            self._update(float(arr[finite].sum()), int(finite.sum()))

    def reset(self):
        super().reset()
        self.num_nonfinite = 0


@register
class Caffe(Loss):
    pass


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names, has_global_stats=True)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(f"Metric index {index} is out of range 0 and "
                              f"{len(self.metrics)}")

    def update_dict(self, labels, preds):
        if self.label_names is not None:
            labels = {name: label for name, label in labels.items()
                      if name in self.label_names}
        if self.output_names is not None:
            preds = {name: pred for name, pred in preds.items()
                     if name in self.output_names}
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def reset_local(self):
        try:
            for metric in self.metrics:
                metric.reset_local()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, complex)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_global(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get_global()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, complex)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_config(self):
        config = super().get_config()
        config.update({"metrics": [i.get_config() for i in self.metrics]})
        return config


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = f"custom({name})"
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names,
                         has_global_stats=True)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            label_np = _to_numpy(label)
            pred_np = _to_numpy(pred)
            reval = self._feval(label_np, pred_np)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self._update(sum_metric, num_inst)
            else:
                self._update(reval, 1)

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")
