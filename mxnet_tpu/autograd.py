"""Autograd: imperative tape over JAX VJPs.

Reference parity: src/imperative/imperative.cc (Imperative::Backward, AGInfo
per-NDArray tape entries) and python/mxnet/autograd.py (record/pause/
train_mode/predict_mode/backward/grad/Function).

TPU-first design: instead of building an nnvm gradient graph, each recorded
op stores the ``jax.vjp`` pullback of its pure function.  For hybridized
blocks the recorded function is ``jax.jit``-wrapped, so both the forward call
and — because pjit transposes to pjit — the pullback execute as single
compiled XLA programs: the CachedOp forward/backward pair of the reference,
compiled by XLA instead of planned by nnvm.
"""

from __future__ import annotations

import threading

import numpy as _np

from .base import MXNetError


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _AGState()


# -- scope management ----------------------------------------------------------

class _RecordingScope:
    def __init__(self, recording, training):
        self._rec = recording
        self._train = training

    def __enter__(self):
        self._prev = (_STATE.recording, _STATE.training)
        if self._rec is not None:
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        _STATE.recording, _STATE.training = self._prev


def record(train_mode=True):
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, flag
    return prev


def set_training(flag: bool) -> bool:
    prev, _STATE.training = _STATE.training, flag
    return prev


# -- tape ----------------------------------------------------------------------

class TapeNode:
    """One recorded op: pullback + input links + produced outputs.

    The reference's AGInfo (src/imperative/imperative.cc) keeps op + saved
    inputs/outputs; here the vjp closure owns the residuals.

    Input links snapshot (array, producer_node, producer_slot) AT RECORD
    TIME: in-place ops later *adopt* another node's output handle
    (NDArray._adopt), so chasing ``arr._tape_node`` at backward time would
    follow the post-mutation producer and mis-route cotangents.
    """

    __slots__ = ("vjp_fn", "inputs", "outputs", "n_outputs", "name",
                 "pure_fn")

    def __init__(self, vjp_fn, inputs, outputs, name="", pure_fn=None):
        self.vjp_fn = vjp_fn
        self.outputs = outputs    # list[NDArray]
        self.n_outputs = len(outputs)
        self.name = name
        # pure (raw-array) re-execution of this op over its diff inputs;
        # lets create_graph=True replay the subgraph functionally so the
        # returned grads are themselves differentiable (higher order)
        self.pure_fn = pure_fn
        links = []
        for arr in inputs:        # diff positions only
            parent = arr._tape_node
            slot = None
            if parent is not None:
                slot = next((i for i, o in enumerate(parent.outputs)
                             if o is arr), None)
                if slot is None:
                    parent = None  # stale link (mutated handle): treat leaf
            links.append((arr, parent, slot))
        self.inputs = links


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference: MXAutogradMarkVariables."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._tape_node = None


def _toposort(head_nodes):
    # iterative post-order DFS: tapes can be arbitrarily deep (long unrolled
    # RNNs), so no recursion
    order, seen = [], set()
    stack = [(n, False) for n in reversed(head_nodes)]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for _arr, parent, _slot in node.inputs:
            if parent is not None and id(parent) not in seen:
                stack.append((parent, False))
    return order


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Walk the tape from `heads`, accumulating gradients into every variable
    with grad_req != 'null' (reference: Imperative::Backward)."""
    import jax.numpy as jnp

    from .ndarray import NDArray, _from_jax

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, list):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    # seed cotangents
    cotangents: dict[int, list] = {}  # id(node) -> per-output cotangent
    node_of: dict[int, TapeNode] = {}
    # per-variable accumulation across the whole pass; grad_req applied once
    # at the end (reference: Imperative::Backward writes grad buffers after
    # the full grad graph executes)
    var_accum: dict[int, list] = {}  # id(arr) -> [arr, ct_sum]
    head_nodes = []
    for h, hg in zip(heads, head_grads):
        node = h._tape_node
        if node is None:
            if h._grad_req != "null":
                g = jnp.ones_like(h._data) if hg is None else hg._data
                _accum_var(var_accum, h, g)
                continue
            raise MXNetError(
                "cannot differentiate a head that is not on the tape; "
                "call backward inside/after autograd.record()")
        head_nodes.append(node)
        node_of[id(node)] = node
        cots = cotangents.setdefault(
            id(node), [None] * node.n_outputs)
        idx = next((i for i, o in enumerate(node.outputs) if o is h), None)
        if idx is None:
            raise MXNetError(
                "head array is no longer an output of its producing tape "
                "node (was it mutated after recording?)")
        seed = jnp.ones_like(h._data) if hg is None else hg._data
        cots[idx] = seed if cots[idx] is None else cots[idx] + seed

    order = _toposort(head_nodes)
    for node in reversed(order):
        cots = cotangents.get(id(node))
        if cots is None:
            continue
        full = [c if c is not None else jnp.zeros_like(o._data)
                for c, o in zip(cots, node.outputs)]
        out_ct = tuple(full) if node.n_outputs > 1 else full[0]
        in_cts = node.vjp_fn(out_ct)
        import jax.dtypes

        for (arr, parent, slot), ct in zip(node.inputs, in_cts):
            if ct is None or (hasattr(ct, "dtype")
                              and ct.dtype == jax.dtypes.float0):
                continue
            if arr._grad_req != "null" and arr._grad is not None:
                _accum_var(var_accum, arr, ct)
            if parent is not None:
                pcots = cotangents.setdefault(
                    id(parent), [None] * parent.n_outputs)
                pcots[slot] = ct if pcots[slot] is None else pcots[slot] + ct
        if not retain_graph:
            cotangents.pop(id(node), None)

    for arr, ct in var_accum.values():
        _apply_grad(arr, ct)

    if not retain_graph:
        for node in order:
            for out in node.outputs:
                out._tape_node = None


def _accum_var(var_accum, arr, ct):
    entry = var_accum.get(id(arr))
    if entry is None:
        var_accum[id(arr)] = [arr, ct]
    else:
        entry[1] = entry[1] + ct


def _apply_grad(arr, ct):
    import jax.numpy as jnp

    from .ndarray.sparse import RowSparseNDArray, _RowSparseCt

    grad = arr._grad
    if isinstance(ct, _RowSparseCt):
        ct = ct.astype(grad._rs_values.dtype
                       if isinstance(grad, RowSparseNDArray)
                       else grad._data.dtype)
        if isinstance(grad, RowSparseNDArray):
            # compact write: O(touched rows), never O(table rows)
            if arr._grad_req == "add" and grad.num_stored_rows:
                ct = ct + _RowSparseCt(grad._rs_indices,
                                       grad._rs_values, ct.shape)
            ct = ct.coalesce()
            grad._set_sparse(ct.indices, ct.values)
            return
        # dense grad buffer: scatter the compact rows in
        if arr._grad_req == "add":
            grad._data = grad._data.at[ct.indices].add(ct.values)
        else:
            grad._data = ct.to_dense()
        grad._version += 1
        return
    ct = ct.astype(grad._data.dtype) if hasattr(ct, "astype") else ct
    if arr._grad_req == "add":
        grad._data = grad._data + ct
    else:  # write
        grad._data = jnp.asarray(ct)
    grad._version += 1


def _grad_create_graph(heads, variables, head_grads):
    """create_graph=True: replay the recorded subgraph as a pure jax
    function of `variables`, take its vjp, and put the resulting grads
    BACK on the tape (node whose pure_fn is the grad function itself), so
    grad-of-grad — to any order — composes.

    TPU-first take on the reference's Imperative::Backward(create_graph)
    (src/imperative/imperative.cc): instead of recording the backward's
    kernel-by-kernel execution on the tape, rebuild the functional
    expression and let jax.vjp transpose it; XLA compiles the whole
    higher-order program when the caller is under jit/hybridize.
    """
    import jax
    import jax.numpy as jnp

    from .ndarray import NDArray, _from_jax

    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, list):
            head_grads = [head_grads]
    if head_grads is None:
        seeds = [jnp.ones_like(h._data) for h in heads]
    else:
        seeds = [jnp.ones_like(h._data) if g is None else g._data
                 for h, g in zip(heads, head_grads)]

    var_list = list(variables)
    var_ids = {id(v) for v in var_list}

    head_nodes = []
    for h in heads:
        if h._tape_node is None and id(h) not in var_ids:
            raise MXNetError(
                "cannot differentiate a head that is not on the tape; "
                "call grad inside autograd.record()")
        if h._tape_node is not None:
            head_nodes.append(h._tape_node)

    # forward-topo order of nodes reachable from heads, stopping at the
    # variables (they are the leaves of the replayed expression)
    order, seen = [], set()
    stack = [(n, False) for n in reversed(head_nodes)]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for arr, parent, _slot in node.inputs:
            if parent is not None and id(arr) not in var_ids \
                    and id(parent) not in seen:
                stack.append((parent, False))
    for node in order:
        if node.pure_fn is None:
            raise MXNetError(
                f"create_graph=True cannot differentiate through "
                f"'{node.name}': its backward is opaque to higher-order "
                f"gradients (custom autograd.Function)")

    head_list = list(heads)

    def _replay(vs):
        val = {id(v): x for v, x in zip(var_list, vs)}
        for node in order:
            ins = [val.get(id(arr), arr._data)
                   for arr, _p, _s in node.inputs]
            out = node.pure_fn(*ins)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for oh, ov in zip(node.outputs, outs):
                if id(oh) in var_ids:
                    continue  # a variable is an independent leaf here
                val[id(oh)] = ov
        return tuple(val[id(h)] if id(h) in val else h._data
                     for h in head_list)

    def _gradfn(*vs):
        _outs, vjp = jax.vjp(_replay, list(vs))
        (gvs,) = vjp(tuple(seeds))
        return tuple(
            g if g is not None and not (
                hasattr(g, "dtype") and g.dtype == jax.dtypes.float0)
            else jnp.zeros_like(v)
            for g, v in zip(gvs, vs))

    vs0 = [v._data for v in var_list]
    grads_raw, vjp2 = jax.vjp(_gradfn, *vs0)
    out_nds = [_from_jax(g) for g in grads_raw]

    def node_vjp(out_ct):
        # backward() hands a bare leaf for single-output nodes; _gradfn
        # always returns a tuple
        cts = (out_ct,) if len(var_list) == 1 else tuple(out_ct)
        return vjp2(cts)

    node = TapeNode(node_vjp, var_list, out_nds, name="higher_order_grad",
                    pure_fn=_gradfn)
    for o in out_nds:
        o._tape_node = node
    return out_nds[0] if single else out_nds


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Reference: mx.autograd.grad — return grads w.r.t. `variables` without
    touching their .grad buffers."""
    from .ndarray import NDArray, _from_jax

    if create_graph:
        return _grad_create_graph(heads, variables, head_grads)
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    saved = [(v._grad, v._grad_req) for v in variables]
    import jax.numpy as jnp

    for v in variables:
        v._grad = _from_jax(jnp.zeros_like(v._data))
        v._grad_req = "write"
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph),
                 train_mode=train_mode)
        outs = [v._grad for v in variables]
    finally:
        for v, (g, req) in zip(variables, saved):
            v._grad, v._grad_req = g, req
    return outs[0] if single else outs


import contextlib


@contextlib.contextmanager
def accumulate_grads(params):
    """Accumulate gradients across several ``backward()`` calls — the
    eager side of gradient accumulation (`Trainer.train_step`'s oracle
    for ``grad_accum > 1``; the captured program folds the same
    accumulation into its `lax.scan` carry).

    Zeroes each trainable parameter's grad buffer, switches grad_req to
    'add' for the scope, and restores the original req on exit WITHOUT
    re-attaching the buffer (``Parameter.grad_req``'s setter would zero
    it, losing the accumulated sum the optimizer step is about to
    consume).  The first microbatch therefore computes ``0 + ct`` —
    exactly what the captured scan's zero-initialized carry computes.
    """
    params = [p for p in params if p._grad_req != "null"]
    saved = [(p, p._grad_req) for p in params]
    for p in params:
        p.zero_grad()
        p._grad_req = "add"
        if p._data is not None:
            p._data._grad_req = "add"
    try:
        yield
    finally:
        for p, req in saved:
            p._grad_req = req
            if p._data is not None:
                p._data._grad_req = req


def get_symbol(x):
    raise NotImplementedError(
        "symbol extraction from the imperative tape is not supported; "
        "use HybridBlock.export for a serialized graph")


class Function:
    """Custom differentiable function (reference: mx.autograd.Function).

    Subclass and implement forward(self, *inputs) and
    backward(self, *output_grads); call via instance(*inputs).
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray, _from_jax

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording() and any(
                isinstance(i, NDArray) and i._on_tape() for i in inputs):
            nd_inputs = [i for i in inputs if isinstance(i, NDArray)]

            def vjp_fn(out_ct):
                cts = (out_ct,) if single else tuple(out_ct)
                with pause():
                    in_grads = self.backward(
                        *[_from_jax(c) for c in cts])
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return [g._data if isinstance(g, NDArray) else g
                        for g in in_grads]

            node = TapeNode(vjp_fn, nd_inputs, outs,
                            name=type(self).__name__)
            for i, o in enumerate(outs):
                o._tape_node = node
        return outs[0] if single else outs
